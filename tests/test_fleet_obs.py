"""Fleet observability plane (ISSUE 19, mxtpu/fleet_obs.py):

* per-host blob publication: bounded content, atomic write, fake-clock
  cadence, riding the telemetry flush hook (incl. the final flush);
* FleetObservatory merge: per-host rows, FLOPs-weighted ``fleet.mfu``,
  cross-host step quantiles, heartbeat ages, host-labeled Prometheus
  exposition through ``register_prometheus_extra``, graceful
  degradation to surviving hosts on a torn blob;
* straggler matrix on fake payloads: uniform fleet → no trip; one slow
  rank → trip names the rank AND its dominant stage (latched); a
  recovered rank keeps the trip counter flat and re-arms;
* same-host regression sentinel: rolling-baseline drift trip, re-arm;
* step_barrier obs payloads: dict round-trip, fingerprint extraction
  for the divergence gate, compatibility with legacy list peers;
* trainer stage capture with the plane on: breakdown present, d2h == 0;
* telemetry_report: directory/glob multi-sink merge (per-file counter
  banking, trace-id dedup) and the ``--fleet`` board rendering;
* JSONL sink final-flush bugfix: a SIGTERM'd child (ResilientLoop
  installed) and a clean-exit counters-only child both land their last
  window of metrics — real subprocesses, bounded;
* ONE bounded 2-process board-merge acceptance run (fleet_worker with
  the plane on): both hosts' blobs on the board, observatory merges
  both, stitched stage payloads behind every step barrier.

Everything except the subprocess tests is sleep- and subprocess-free on
fake clocks.
"""
import glob
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from mxtpu import fleet, fleet_obs, resilience, telemetry
from mxtpu.fleet import Fleet, FleetMembership, FleetSupervisor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_FLEET_DIR", "MXTPU_FLEET_OBS_S",
                "MXTPU_STRAGGLER_X", "MXTPU_PROFILE_ON_TRIP",
                "MXTPU_FLIGHT_DIR", "MXTPU_FLIGHT_MAX",
                "MXTPU_FAULT_INJECT", "MXTPU_TELEMETRY",
                "MXTPU_TELEMETRY_FLUSH_S",
                "MXTPU_FLEET_BRINGUP_TIMEOUT_S",
                "MXTPU_FLEET_HEARTBEAT_S",
                "MXTPU_FLEET_COLLECTIVE_TIMEOUT_S"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    fleet_obs._PROFILE_DONE.clear()
    yield
    telemetry.reset()
    resilience.reset_faults()
    fleet_obs._PROFILE_DONE.clear()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _counter(name):
    v = telemetry.snapshot()["counters"].get(name, 0)
    return sum(v.values()) if isinstance(v, dict) else v


def _payloads(fast, slow=None, slow_rank=1, t0=100.0):
    """A 2-host barrier payload map: per-rank stage dicts."""
    out = {}
    for r in (0, 1):
        s = slow if (slow is not None and r == slow_rank) else fast
        out[r] = {"fp": [1.0], "trace": "aaaa-%d" % r,
                  "t": t0 + (s if r == slow_rank and slow else 0.0),
                  "stages": {"trainer.step.allreduce": s * 0.25,
                             "trainer.step.update": s * 0.25,
                             "data.wait": s * 0.5}}
    return out


# --------------------------------------------------- per-host publication
def test_publish_obs_blob_bounded(tmp_path):
    telemetry.inc("train.batches", 5)
    telemetry.gauge("perf.mfu", 0.42)
    for _ in range(10):
        telemetry.observe("trainer.step", 0.01)
    path = fleet_obs.publish_obs(str(tmp_path), 3, step=7, t=123.0)
    blob = json.load(open(path))
    assert os.path.basename(path) == "obs_3.json"
    assert blob["rank"] == 3 and blob["step"] == 7 and blob["t"] == 123.0
    assert blob["counters"]["train.batches"] == 5
    assert blob["gauges"]["perf.mfu"] == 0.42
    assert blob["histograms"]["trainer.step"]["count"] == 10
    assert len(blob["trace_tail"]) <= fleet_obs.TRACE_TAIL
    assert _counter("fleet.obs.publishes") == 1
    # no leftover tmp file: the write is tmp+rename
    assert not glob.glob(str(tmp_path / "*.tmp"))


def test_publisher_cadence_fake_clock(tmp_path):
    clk = FakeClock()
    pub = fleet_obs.HostObsPublisher(str(tmp_path), 0, interval_s=5.0,
                                     clock=clk)
    assert pub.maybe_publish(step=0)  # first call publishes
    assert pub.maybe_publish(step=1) is None  # inside the window
    clk.advance(5.1)
    assert pub.maybe_publish(step=2)
    assert json.load(open(pub.path))["step"] == 2
    assert _counter("fleet.obs.publishes") == 2
    # forced publish ignores the cadence
    assert pub.publish(step=3)
    assert json.load(open(pub.path))["step"] == 3


def test_publisher_disabled_without_interval(tmp_path):
    pub = fleet_obs.HostObsPublisher(str(tmp_path), 0, interval_s=0)
    assert pub.maybe_publish(step=0) is None
    assert not os.path.exists(pub.path)


def test_publisher_rides_final_flush(tmp_path):
    """install() hooks telemetry.flush — the path the atexit/SIGTERM
    final flush takes, so a dying host's blob reflects its last window."""
    pub = fleet_obs.HostObsPublisher(str(tmp_path), 1,
                                     interval_s=1e9).install()
    telemetry.inc("late.counter", 9)
    telemetry.flush()
    blob = json.load(open(pub.path))
    assert blob["counters"]["late.counter"] == 9


# ------------------------------------------------------ coordinator merge
def _write_host_blob(board, rank, mfu, flops, step_p50, t=1000.0,
                     step=5):
    os.makedirs(str(board), exist_ok=True)
    fleet._atomic_write(
        os.path.join(str(board), "obs_%d.json" % rank),
        json.dumps({"rank": rank, "pid": 100 + rank, "step": step, "t": t,
                    "counters": {"train.batches": 10 * (rank + 1),
                                 "faults.injected": {"oom": rank + 1}},
                    "gauges": {"perf.mfu": mfu},
                    "histograms": {"trainer.step": {
                        "count": 5, "sum": step_p50 * 5,
                        "mean": step_p50, "min": step_p50,
                        "max": step_p50 * 2, "p50": step_p50,
                        "p99": step_p50 * 2}},
                    "ledger": {"executed_flops": flops},
                    "trace_tail": []}))


def test_observatory_merges_hosts_and_aggregates(tmp_path):
    clk = FakeClock(1010.0)
    _write_host_blob(tmp_path, 0, mfu=0.5, flops=100.0, step_p50=0.1)
    _write_host_blob(tmp_path, 1, mfu=0.3, flops=300.0, step_p50=0.3)
    FleetMembership(tmp_path, 0, 2, clock=lambda: 1008.0).write("up")
    FleetMembership(tmp_path, 1, 2, clock=lambda: 1004.0).write("up")
    m = fleet_obs.FleetObservatory(str(tmp_path), 2, clock=clk).merged()
    assert sorted(m["hosts"]) == [0, 1]
    assert m["hosts"][1]["mfu"] == 0.3
    assert m["hosts"][0]["heartbeat_age_s"] == pytest.approx(2.0)
    assert m["hosts"][1]["heartbeat_age_s"] == pytest.approx(6.0)
    # fleet.mfu is FLOPs-weighted: (0.5*100 + 0.3*300) / 400
    assert m["fleet"]["mfu"] == pytest.approx(0.35)
    assert m["fleet"]["step_s"]["p50"] == pytest.approx(0.2)
    assert m["fleet"]["hosts_up"] == 2
    assert m["fleet"]["executed_flops"] == pytest.approx(400.0)


def test_observatory_refresh_lands_registry_gauges(tmp_path):
    _write_host_blob(tmp_path, 0, mfu=0.4, flops=100.0, step_p50=0.1)
    FleetMembership(tmp_path, 0, 1, clock=lambda: 999.0).write("up")
    obs = fleet_obs.FleetObservatory(str(tmp_path), 1,
                                     clock=FakeClock(1000.0))
    obs.refresh()
    g = telemetry.snapshot()["gauges"]
    assert g["fleet.mfu"] == pytest.approx(0.4)
    assert g["fleet.step_s"]["p50"] == pytest.approx(0.1)
    assert g["fleet.heartbeat_age_s"]["host0"] == pytest.approx(1.0)
    assert g["fleet.hosts_up"] == 1


def test_observatory_prometheus_host_labels(tmp_path):
    _write_host_blob(tmp_path, 0, mfu=0.5, flops=100.0, step_p50=0.1)
    _write_host_blob(tmp_path, 1, mfu=0.3, flops=300.0, step_p50=0.3)
    fleet_obs.FleetObservatory(str(tmp_path), 2,
                               clock=FakeClock()).install()
    out = telemetry.prometheus()
    # per-host families with the host label, tags preserved alongside
    assert 'mxtpu_train_batches{host="0"} 10' in out
    assert 'mxtpu_train_batches{host="1"} 20' in out
    assert 'mxtpu_faults_injected{host="1",tag="oom"} 2' in out
    assert 'mxtpu_trainer_step{host="0",quantile="50"} 0.1' in out
    # the refresh()'s fleet aggregates land in the SAME scrape
    assert "mxtpu_fleet_mfu 0.35" in out


def test_observatory_degrades_to_surviving_hosts(tmp_path):
    """A torn/garbage blob (host died mid-life) degrades the merge to
    the surviving hosts — it never raises (resilience.md matrix row)."""
    _write_host_blob(tmp_path, 0, mfu=0.5, flops=100.0, step_p50=0.1)
    with open(os.path.join(str(tmp_path), "obs_1.json"), "w") as f:
        f.write("{torn")
    m = fleet_obs.FleetObservatory(str(tmp_path), 2,
                                   clock=FakeClock()).merged()
    assert sorted(m["hosts"]) == [0]
    assert m["fleet"]["mfu"] == pytest.approx(0.5)


# ------------------------------------------------------- straggler matrix
def test_straggler_uniform_fleet_no_trip(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    s = fleet_obs.StragglerSentinel(factor=1.5, streak=3)
    for step in range(8):
        assert s.observe(step, _payloads(0.1)) is None
    assert _counter("fleet.straggler_trips") == 0
    assert not glob.glob(str(tmp_path / "flight_straggler_*"))


def test_straggler_slow_rank_named_with_dominant_stage(tmp_path,
                                                       monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    s = fleet_obs.StragglerSentinel(factor=1.5, streak=3)
    trips = [s.observe(step, _payloads(0.1, slow=0.5)) for step in range(4)]
    # streak=3: trips exactly at the 3rd consecutive slow observation,
    # then latches (no re-trip while still slow)
    assert trips[0] is None and trips[1] is None
    assert trips[2] is not None and trips[3] is None
    trip = trips[2]
    assert trip["rank"] == 1 and trip["step"] == 2
    assert trip["dominant_stage"] == "data.wait"
    assert trip["ratio"] > 1.5
    assert _counter("fleet.straggler_trips") == 1
    assert telemetry.tagged("fleet.straggler_trips") == {"host1": 1}
    arts = glob.glob(str(tmp_path / "flight_straggler_*"))
    assert len(arts) == 1
    extra = json.load(open(arts[0]))["extra"]
    assert extra["rank"] == 1
    assert extra["stages"]["data.wait"] == pytest.approx(0.25)
    # arrival-skew gauges rode the same observations
    skew = telemetry.snapshot()["gauges"]["fleet.arrival_skew_s"]
    assert skew["host1"] > skew["host0"] == 0.0


def test_straggler_recovered_rank_counter_flat():
    s = fleet_obs.StragglerSentinel(factor=1.5, streak=2)
    for step in range(2):
        s.observe(step, _payloads(0.1, slow=0.5))
    assert _counter("fleet.straggler_trips") == 1
    # recovery: uniform again — counter stays flat...
    for step in range(2, 8):
        assert s.observe(step, _payloads(0.1)) is None
    assert _counter("fleet.straggler_trips") == 1
    # ...and the sentinel re-armed: a NEW degradation trips again
    for step in range(8, 10):
        s.observe(step, _payloads(0.1, slow=0.5))
    assert _counter("fleet.straggler_trips") == 2


def test_straggler_disabled_without_factor():
    s = fleet_obs.StragglerSentinel(factor=0)
    for step in range(6):
        assert s.observe(step, _payloads(0.1, slow=9.0)) is None
    assert _counter("fleet.straggler_trips") == 0


def test_straggler_ignores_legacy_list_payloads():
    s = fleet_obs.StragglerSentinel(factor=1.5, streak=1)
    assert s.observe(0, {0: [1.0, 2.0], 1: [1.0, 2.0]}) is None


def test_regression_sentinel_trips_on_drift(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    r = fleet_obs.RegressionSentinel(factor=1.5, baseline_n=4, recent_n=2)
    for step in range(6):
        assert r.observe(step, 0.1) is None  # steady: baseline fills
    trip = None
    for step in range(6, 9):
        trip = r.observe(step, 0.3) or trip
    assert trip is not None and trip["ratio"] > 1.5
    assert _counter("fleet.step_regressions") == 1
    assert len(glob.glob(str(tmp_path / "flight_step_regression_*"))) == 1
    # recovery re-arms; a second drift trips again
    for step in range(9, 30):
        r.observe(step, 0.1)
    for step in range(30, 40):
        r.observe(step, 0.4)
    assert _counter("fleet.step_regressions") == 2


def test_profile_on_trip_one_capture_per_reason(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_PROFILE_ON_TRIP", "1")
    monkeypatch.setattr(fleet_obs, "PROFILE_WINDOW_S", 0.05)
    s = fleet_obs.StragglerSentinel(factor=1.5, streak=1)
    s.observe(0, _payloads(0.1, slow=0.5))
    caps = glob.glob(str(tmp_path / "profile_straggler_*"))
    assert len(caps) == 1
    assert _counter("fleet.profile_captures") == 1
    # recovery + second trip: SAME reason, no second capture window
    s.observe(1, _payloads(0.1))
    s.observe(2, _payloads(0.1, slow=0.5))
    assert _counter("fleet.straggler_trips") == 2
    assert _counter("fleet.profile_captures") == 1
    time.sleep(0.2)  # let the bounded stop-timer fire before teardown


# ------------------------------------------------- step_barrier stitching
def _peer_barrier_file(board, name, rank, payload):
    bdir = os.path.join(str(board), "barrier_%s" % name)
    os.makedirs(bdir, exist_ok=True)
    fleet._atomic_write(os.path.join(bdir, "host_%d" % rank),
                        json.dumps({"rank": rank, "payload": payload}))


def test_step_barrier_obs_payload_round_trip(tmp_path):
    clk = FakeClock()
    board = tmp_path / "b"
    m0 = FleetMembership(board, 0, 2, clock=clk)
    FleetMembership(board, 1, 2, clock=clk).write("up")
    f = Fleet(0, 2, membership=m0, fleet_dir=str(board))
    peer = {"fp": [1.5, 2.0], "trace": "beef-7",
            "stages": {"trainer.step.update": 0.2}, "t": 999.0}
    _peer_barrier_file(board, "step_3", 1, peer)
    fps = f.step_barrier(3, fingerprint=[1.5, 2.0],
                         obs={"trace": "cafe-3",
                              "stages": {"trainer.step.update": 0.1}})
    assert fps[1] == peer
    assert fps[0]["fp"] == [1.5, 2.0]
    assert fps[0]["trace"] == "cafe-3"
    assert fps[0]["t"] == clk.t  # barrier-arrival timestamp stamped
    assert _counter("resilience.divergence_checks") == 1


def test_step_barrier_obs_divergence_still_trips(tmp_path, monkeypatch):
    art = tmp_path / "flight"
    art.mkdir()
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(art))
    clk = FakeClock()
    board = tmp_path / "b"
    m0 = FleetMembership(board, 0, 2, clock=clk)
    FleetMembership(board, 1, 2, clock=clk).write("up")
    f = Fleet(0, 2, membership=m0, fleet_dir=str(board))
    _peer_barrier_file(board, "step_4", 1,
                       {"fp": [1.5, 999.0], "stages": {}})
    with pytest.raises(resilience.DivergenceError, match="step 4"):
        f.step_barrier(4, fingerprint=[1.5, 2.0], obs={"stages": {}})
    arts = glob.glob(str(art / "flight_fleet_divergence_*"))
    assert len(arts) == 1


def test_step_barrier_obs_interops_with_legacy_list_peer(tmp_path):
    """An ISSUE-18 peer that still ships bare fingerprint lists agrees
    with an obs-carrying host when the fingerprints match."""
    clk = FakeClock()
    board = tmp_path / "b"
    m0 = FleetMembership(board, 0, 2, clock=clk)
    FleetMembership(board, 1, 2, clock=clk).write("up")
    f = Fleet(0, 2, membership=m0, fleet_dir=str(board))
    _peer_barrier_file(board, "step_5", 1, [1.5, 2.0])
    fps = f.step_barrier(5, fingerprint=[1.5, 2.0], obs={"stages": {}})
    assert fps[1] == [1.5, 2.0]
    assert fps[0]["fp"] == [1.5, 2.0]


def test_step_traces_names_last_rank_and_stage(tmp_path):
    for step, (t0, t1) in enumerate([(10.0, 10.3), (20.4, 20.0)]):
        _peer_barrier_file(tmp_path, "step_%d" % step, 0,
                           {"fp": None, "t": t0, "trace": "aa-%d" % step,
                            "stages": {"trainer.step.update": 0.01,
                                       "data.wait": 0.35 if step == 1
                                       else 0.0}})
        _peer_barrier_file(tmp_path, "step_%d" % step, 1,
                           {"fp": None, "t": t1, "trace": "bb-%d" % step,
                            "stages": {"trainer.step.update": 0.4
                                       if step == 1 else 0.01}})
    rows = fleet_obs.step_traces(str(tmp_path))
    assert [r["step"] for r in rows] == [0, 1]
    assert rows[0]["last_rank"] == 1  # arrived at 10.3 vs 10.0
    assert rows[0]["skew_s"] == pytest.approx(0.3)
    assert rows[1]["last_rank"] == 0
    assert rows[1]["dominant_stage"] == "data.wait"
    assert rows[1]["trace"] == "aa-1"


# -------------------------------------------- trainer stage wiring + pins
def test_trainer_stage_capture_plane_on_d2h_zero(monkeypatch):
    """One real training step with every plane lever ON: the stage
    breakdown and trace id land on the trainer, and the step stays
    device-sync-free (d2h == 0) — the ISSUE-19 zero-device-work pin."""
    monkeypatch.setenv("MXTPU_TELEMETRY", "1")
    monkeypatch.setenv("MXTPU_TRACE", "1")
    monkeypatch.setenv("MXTPU_STRAGGLER_X", "2.0")
    import numpy as np

    import mxtpu as mx
    from mxtpu.gluon.parameter import Parameter
    from mxtpu.gluon.trainer import Trainer
    rng = np.random.RandomState(0)
    params = []
    for j in range(3):
        p = Parameter("sp%d" % j, shape=(5,), dtype="float32")
        p.initialize()
        params.append(p)
    trainer = Trainer(params, "sgd", {"learning_rate": 0.05,
                                      "momentum": 0.9}, kvstore=None)
    assert trainer.last_step_trace is None
    for _ in range(3):
        for p in params:
            p.grad()[:] = mx.nd.array(
                rng.randn(*p.shape).astype(np.float32))
        trainer.step(1)
    stages = trainer.last_step_stages
    assert set(stages) == {"trainer.step.allreduce", "trainer.step.update"}
    assert all(v >= 0 for v in stages.values())
    assert trainer.last_step_trace is not None
    assert telemetry.value("trainer.step.d2h") == 0


# ----------------------------------------- telemetry_report multi-sink
def _write_jsonl(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def test_report_merges_directory_of_sinks(tmp_path):
    import telemetry_report as rep
    # two hosts' cumulative counter streams + one duplicated trace-
    # linked obs line (same process prefix => same (trace, span))
    _write_jsonl(str(tmp_path / "h0.jsonl"), [
        {"t": 1.0, "kind": "counter", "metric": "train.batches",
         "value": 50},
        {"t": 2.0, "kind": "counter", "metric": "train.batches",
         "value": 100},
        {"t": 2.0, "kind": "gauge", "metric": "perf.mfu", "value": 0.5},
        {"t": 1.5, "kind": "obs", "metric": "trainer.step", "value": 0.1,
         "trace": "00aa-1", "span": 7},
    ])
    _write_jsonl(str(tmp_path / "h1.jsonl"), [
        {"t": 2.5, "kind": "counter", "metric": "train.batches",
         "value": 40},
        {"t": 3.0, "kind": "gauge", "metric": "perf.mfu", "value": 0.7},
        {"t": 1.5, "kind": "obs", "metric": "trainer.step", "value": 0.1,
         "trace": "00aa-1", "span": 7},              # the duplicate
        {"t": 1.6, "kind": "obs", "metric": "trainer.step", "value": 0.3,
         "trace": "00bb-1", "span": 9},
    ])
    recs = rep.load_many([str(tmp_path)])
    summary = rep.aggregate(recs)
    # per-file banking then sum: 100 (host 0 final) + 40 (host 1 final)
    assert summary["train.batches"]["value"] == 140
    # freshest gauge write wins regardless of file order
    assert summary["perf.mfu"]["value"] == 0.7
    # the duplicated trace-linked line folded once: 2 obs, not 3
    assert summary["trainer.step"]["count"] == 2


def test_report_single_file_behavior_unchanged(tmp_path):
    import telemetry_report as rep
    p = str(tmp_path / "one.jsonl")
    _write_jsonl(p, [
        {"t": 1.0, "kind": "counter", "metric": "c", "value": 10},
        {"t": 2.0, "kind": "counter", "metric": "c", "value": 3},  # restart
    ])
    assert rep.aggregate(rep.load(p))["c"]["value"] == 13
    assert rep.aggregate(rep.load_many([p]))["c"]["value"] == 13


def test_report_fleet_cli_renders_board(tmp_path, capsys):
    import telemetry_report as rep
    board = tmp_path / "board"
    _write_host_blob(board, 0, mfu=0.5, flops=100.0, step_p50=0.1)
    _write_host_blob(board, 1, mfu=0.3, flops=300.0, step_p50=0.3)
    _peer_barrier_file(board, "step_0", 0,
                       {"fp": None, "t": 10.0,
                        "stages": {"trainer.step.update": 0.01}})
    _peer_barrier_file(board, "step_0", 1,
                       {"fp": None, "t": 10.2,
                        "stages": {"data.wait": 0.2}})
    assert rep.main(["--fleet", str(board)]) == 0
    out = capsys.readouterr().out
    assert "Fleet:" in out and "critical path" in out
    assert "data.wait" in out
    # and the JSON spelling carries the merged view for machines
    assert rep.main(["--fleet", str(board), "--json"]) == 0
    js = json.loads(capsys.readouterr().out)
    assert js["_fleet"]["merged"]["fleet"]["mfu"] == pytest.approx(0.35)
    assert js["_fleet"]["steps"][0]["last_rank"] == 1


# ------------------------------------------------- sink final-flush fixes
_CLEAN_CHILD = """
import os, sys
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MXTPU_TELEMETRY"] = %(sink)r
os.environ["MXTPU_TELEMETRY_FLUSH_S"] = "3600"
from mxtpu import telemetry
telemetry.inc("child.counter", 7)
# counters-only: nothing ever queued an obs line, so nothing but the
# import-time atexit registration can flush this
"""

_SIGTERM_CHILD = """
import os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MXTPU_TELEMETRY"] = %(sink)r
os.environ["MXTPU_TELEMETRY_FLUSH_S"] = "3600"
from mxtpu import resilience, telemetry
loop = resilience.ResilientLoop(None, None).install()
telemetry.inc("child.counter", 7)
print("READY", flush=True)
deadline = time.time() + 60
while not loop.preempted and time.time() < deadline:
    time.sleep(0.02)
# handler path only: exit without reaching any explicit flush. The
# SIGTERM postmortem thread (flight + flush) must have landed the
# counter lines; give the daemon a beat, then die hard like a real
# preemption would.
time.sleep(1.0)
os._exit(0)
"""


def _counter_lines(sink, metric):
    if not os.path.exists(sink):
        return []
    out = []
    with open(sink) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == "counter" and rec.get("metric") == metric:
                out.append(rec)
    return out


def test_sink_clean_exit_counters_only_flushes(tmp_path):
    """ISSUE-19 satellite bugfix: a process that only bumped counters
    (never queued an obs line) used to lose them even on a CLEAN exit —
    the atexit hook was registered lazily inside _queue_line. The
    import-time registration must land the cumulative lines."""
    sink = str(tmp_path / "clean.jsonl")
    code = _CLEAN_CHILD % {"repo": REPO, "sink": sink}
    proc = subprocess.run([sys.executable, "-c", code], timeout=120,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = _counter_lines(sink, "child.counter")
    assert lines and lines[-1]["value"] == 7


def test_sink_sigterm_flushes_final_window(tmp_path):
    """SIGTERM between off-thread flushes: the signal path's postmortem
    (flight + flush on a daemon thread) lands the last buffered window
    even though the process dies via os._exit (no atexit)."""
    sink = str(tmp_path / "killed.jsonl")
    code = _SIGTERM_CHILD % {"repo": REPO, "sink": sink}
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    lines = _counter_lines(sink, "child.counter")
    assert lines and lines[-1]["value"] == 7


# ------------------------------------------- 2-process board-merge run
@pytest.mark.multidevice
def test_fleet_obs_two_host_board_merge_acceptance(tmp_path):
    """ISSUE-19 acceptance, the bounded tier-1 spelling: a real 2-host
    fleet runs with the obs plane ON — both hosts publish blobs onto
    the board, every step barrier carries the stitched stage payload,
    and the observatory merges the fleet into one snapshot."""
    worker = os.path.join(REPO, "tools", "fleet_worker.py")
    ckpt = str(tmp_path / "ckpt")
    steps = 2

    def command_for(rank, world, generation):
        return [sys.executable, worker, "--ckpt-dir", ckpt,
                "--steps", str(steps), "--devices", "1"]

    def env_for(rank, world, generation):
        return {"XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                "MXTPU_FLEET_COLLECTIVE_TIMEOUT_S": "30",
                "MXTPU_FLEET_OBS_S": "0.05",
                "MXTPU_STRAGGLER_X": "1.5"}

    sup = FleetSupervisor(
        command_for=command_for, num_hosts=2, fleet_dir=str(tmp_path / "b"),
        timeout_s=240.0, env_for=env_for)
    results = sup.launch_round(2, 0)
    for rank in (0, 1):
        rc, tail = results[rank]
        assert rc == 0, tail[-2000:]
    board = str(tmp_path / "b" / "gen_0")
    blobs = sorted(os.path.basename(p)
                   for p in glob.glob(os.path.join(board, "obs_*.json")))
    assert blobs == ["obs_0.json", "obs_1.json"]
    m = fleet_obs.FleetObservatory(board, 2).merged()
    assert sorted(m["hosts"]) == [0, 1]
    for rank in (0, 1):
        assert m["hosts"][rank]["step_s"]["count"] == steps
    # every step barrier carried the stitched payload on both hosts
    rows = fleet_obs.step_traces(board)
    assert [r["step"] for r in rows] == list(range(steps))
    assert all(r["ranks"] == 2 and r["stages"] for r in rows)
