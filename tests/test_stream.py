"""Device-resident input pipeline (ISSUE 9): sharded streaming readers +
double-buffered prefetch-to-device (mxtpu/io/stream.py).

Pins:

* shard determinism — same seed => identical per-replica batch streams
  across runs; epoch boundaries reshuffle; ``num_shards`` not dividing
  the index drops/duplicates nothing (remainder-balanced);
* ``_PyReader.read_at`` positioned reads are byte-identical to the
  sequential reader (incl. multi-chunk records) and leave the shared
  seek offset untouched, so concurrent shard readers share one handle;
* the prefetcher survives an injected ``prefetch_death`` and a mid-epoch
  close without hanging, and errors surface at the consumer;
* ``PrefetchingIter`` (now delegating to DevicePrefetcher) no longer
  deadlocks on reset over an exhausted underlying iter;
* ACCEPTANCE (ISSUE 9): per-replica batches land pre-sharded on the
  mesh — the device buffers' sharding equals ``Trainer.shard_batch``'s
  NamedSharding, with no host-side gather.
"""
import os
import struct
import threading
import time

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import recordio, resilience, telemetry
from mxtpu.base import MXNetError
from mxtpu.io import NDArrayIter, PrefetchingIter
from mxtpu.io.stream import (DevicePrefetcher, ShardedRecordReader,
                             StreamRecordIter, shard_keys)


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_FAULT_INJECT", "MXTPU_PREFETCH_DEPTH",
                "MXTPU_STREAM_THREADS", "MXTPU_DL_WORKER_RESTARTS"):
        monkeypatch.delenv(var, raising=False)
    resilience.reset_faults()
    telemetry.reset()
    yield
    resilience.reset_faults()
    telemetry.reset()


def _write_rec(tmp_path, n=23, shape=(3, 4, 4), name="s"):
    rec = str(tmp_path / (name + ".rec"))
    idx = str(tmp_path / (name + ".idx"))
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        payload = rng.randint(0, 255, shape).astype(np.uint8)
        hdr = recordio.IRHeader(0, float(i), i, 0)
        w.write_idx(i, recordio.pack(hdr, payload.tobytes()))
    w.close()
    return rec, idx


def _decode(shape):
    def fn(raw):
        hdr, payload = recordio.unpack(raw)
        data = np.frombuffer(payload, np.uint8).reshape(shape) \
            .astype(np.float32)
        return data, np.float32(hdr.label)
    return fn


# -------------------------------------------------------------- shard_keys
def test_shard_keys_deterministic_and_balanced():
    keys = list(range(23))
    shards = [shard_keys(keys, 5, i, epoch=3, seed=11) for i in range(5)]
    again = [shard_keys(keys, 5, i, epoch=3, seed=11) for i in range(5)]
    assert shards == again                       # same (seed, epoch) => same
    assert sorted(sum(shards, [])) == keys       # nothing dropped/duplicated
    sizes = sorted(len(s) for s in shards)
    assert sizes == [4, 4, 5, 5, 5]              # remainder-balanced


def test_shard_keys_epoch_reshuffles_seed_separates():
    keys = list(range(40))
    e0 = shard_keys(keys, 1, 0, epoch=0, seed=2)
    e1 = shard_keys(keys, 1, 0, epoch=1, seed=2)
    other = shard_keys(keys, 1, 0, epoch=0, seed=3)
    assert e0 != e1 and e0 != other
    assert sorted(e0) == sorted(e1) == keys
    # seed sequence, not seed+epoch arithmetic: (2,1) must not collide (3,0)
    assert e1 != other


def test_shard_keys_no_shuffle_and_validation():
    keys = list(range(10))
    assert shard_keys(keys, 3, 0, shuffle=False) == [0, 1, 2, 3]
    assert shard_keys(keys, 3, 1, shuffle=False) == [4, 5, 6]
    assert shard_keys(keys, 3, 2, shuffle=False) == [7, 8, 9]
    with pytest.raises(MXNetError):
        shard_keys(keys, 0, 0)
    with pytest.raises(MXNetError):
        shard_keys(keys, 2, 2)


# ------------------------------------------------------------------ read_at
def test_read_at_matches_sequential_and_keeps_offset(tmp_path):
    """Positioned reads are byte-identical to the sequential walk — incl.
    multi-chunk records (payloads containing the magic word) — and do not
    move the shared cursor (the pread contract)."""
    path = str(tmp_path / "chunks.rec")
    records = [b"hello", b"x" * 1000, b"",
               struct.pack("<I", 0xced7230a) * 3,
               b"abcd" + struct.pack("<I", 0xced7230a) + b"efgh"]
    w = recordio._PyWriter(path, "wb")
    positions = []
    for r in records:
        positions.append(w.tell())
        w.write(r)
    w.close()
    r = recordio._PyReader(path)
    first = r.read()                       # cursor now mid-file
    assert first == records[0]
    for pos, want in zip(positions, records):
        assert r.read_at(pos) == want
    # the sequential path is untouched by the preads above
    rest = []
    while True:
        rec = r.read()
        if rec is None:
            break
        rest.append(rec)
    assert rest == records[1:]
    r.close()


def test_pread_idx_concurrent_shared_handle(tmp_path):
    """Many threads pread the same open MXIndexedRecordIO with no seek
    races — every thread sees every record intact."""
    rec, idx = _write_rec(tmp_path, n=40)
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    expected = {k: r.read_idx(k) for k in r.keys}
    errors = []

    def hammer(seed):
        rng = np.random.RandomState(seed)
        for _ in range(120):
            k = int(rng.randint(0, 40))
            if r.pread_idx(k) != expected[k]:
                errors.append(k)

    threads = [threading.Thread(target=hammer, args=(s,)) for s in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    r.close()
    assert not errors


# ------------------------------------------------------ ShardedRecordReader
def test_reader_same_seed_identical_streams(tmp_path):
    rec, _ = _write_rec(tmp_path)
    a = list(ShardedRecordReader(rec, batch_size=4, decode_fn=_decode(
        (3, 4, 4)), seed=5))
    b = list(ShardedRecordReader(rec, batch_size=4, decode_fn=_decode(
        (3, 4, 4)), seed=5))
    assert len(a) == len(b) == 6                # 23 records, keep tail
    for (d1, l1), (d2, l2) in zip(a, b):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)


def test_reader_epoch_reshuffles_and_inline_matches_pool(tmp_path):
    rec, _ = _write_rec(tmp_path)
    rd = ShardedRecordReader(rec, batch_size=4,
                             decode_fn=_decode((3, 4, 4)), seed=5)
    e0 = list(rd)
    assert rd.epoch == 1                         # full consumption advances
    e1 = list(rd)
    labels0 = np.concatenate([b[1] for b in e0])
    labels1 = np.concatenate([b[1] for b in e1])
    assert not np.array_equal(labels0, labels1)  # epoch boundary reshuffled
    np.testing.assert_array_equal(np.sort(labels0), np.sort(labels1))
    # inline (num_threads=0) is the same stream as the pool
    inline = ShardedRecordReader(rec, batch_size=4,
                                 decode_fn=_decode((3, 4, 4)), seed=5,
                                 num_threads=0)
    for (d1, l1), (d2, l2) in zip(e0, inline):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)


def test_reader_set_epoch_resume_replays(tmp_path):
    """Resume contract: a fresh reader pinned at epoch e replays the run's
    epoch-e stream exactly (what a restored loop needs)."""
    rec, _ = _write_rec(tmp_path)
    rd = ShardedRecordReader(rec, batch_size=4,
                             decode_fn=_decode((3, 4, 4)), seed=9)
    list(rd)                                     # epoch 0 consumed
    second = list(rd)                            # epoch 1
    fresh = ShardedRecordReader(rec, batch_size=4,
                                decode_fn=_decode((3, 4, 4)), seed=9)
    fresh.set_epoch(1)
    for (d1, l1), (d2, l2) in zip(second, fresh):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)


def test_reader_shards_cover_exactly_non_dividing(tmp_path):
    """num_shards=3 over 23 records: per-epoch union across shards is
    every record exactly once, shard sizes differ by <= 1."""
    rec, _ = _write_rec(tmp_path)
    seen = []
    sizes = []
    for s in range(3):
        rd = ShardedRecordReader(rec, batch_size=4,
                                 decode_fn=_decode((3, 4, 4)),
                                 num_shards=3, shard_index=s, seed=4)
        labels = np.concatenate([b[1] for b in rd])
        sizes.append(len(labels))
        seen.append(labels)
    assert max(sizes) - min(sizes) <= 1
    allseen = np.sort(np.concatenate(seen))
    np.testing.assert_array_equal(allseen, np.arange(23, dtype=np.float32))


def test_reader_last_batch_discard(tmp_path):
    rec, _ = _write_rec(tmp_path)
    rd = ShardedRecordReader(rec, batch_size=4,
                             decode_fn=_decode((3, 4, 4)), seed=1,
                             last_batch="discard")
    batches = list(rd)
    assert len(batches) == len(rd) == 5          # 23 // 4
    assert all(b[0].shape[0] == 4 for b in batches)


def test_reader_worker_death_recovers_identically(tmp_path, monkeypatch):
    """An injected silent worker death restarts the pool worker under the
    budget and the delivered stream is identical to an undisturbed run."""
    rec, _ = _write_rec(tmp_path)
    clean = list(ShardedRecordReader(rec, batch_size=4,
                                     decode_fn=_decode((3, 4, 4)), seed=2))
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "worker_death@2")
    injected = list(ShardedRecordReader(rec, batch_size=4,
                                        decode_fn=_decode((3, 4, 4)),
                                        seed=2))
    assert resilience.FAULT_STATS["fired"] == [("worker_death", 2)]
    assert telemetry.value("stream.worker_restarts") >= 1
    for (d1, l1), (d2, l2) in zip(clean, injected):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)


def test_reader_worker_death_budget_exhausted(tmp_path, monkeypatch):
    rec, _ = _write_rec(tmp_path)
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "worker_death@0")
    monkeypatch.setenv("MXTPU_DL_WORKER_RESTARTS", "0")
    rd = ShardedRecordReader(rec, batch_size=4,
                             decode_fn=_decode((3, 4, 4)), seed=2)
    with pytest.raises(RuntimeError, match="giving up after"):
        list(rd)


def test_reader_decode_error_surfaces_with_batch_index(tmp_path):
    rec, _ = _write_rec(tmp_path)

    def bad(raw):
        raise ValueError("boom")

    rd = ShardedRecordReader(rec, batch_size=4, decode_fn=bad, seed=2)
    with pytest.raises(RuntimeError, match="failed at batch 0"):
        list(rd)


# --------------------------------------------------------- DevicePrefetcher
def test_prefetcher_parity_and_telemetry():
    src = [(np.full((4, 3), float(i)), np.full((4,), float(i)))
           for i in range(7)]
    pf = DevicePrefetcher(iter(src), depth=2)
    got = list(pf)
    pf.close()
    assert len(got) == 7
    for i, (d, l) in enumerate(got):
        assert isinstance(d, mx.nd.NDArray) and isinstance(l, mx.nd.NDArray)
        np.testing.assert_array_equal(d.asnumpy(), src[i][0])
        np.testing.assert_array_equal(l.asnumpy(), src[i][1])
    snap = telemetry.snapshot()
    assert snap["histograms"]["data.h2d"]["count"] == 7
    assert snap["gauges"]["data.prefetch_depth"] == 2


def test_prefetcher_starvation_is_counted_and_waited():
    """data.wait measures TRUE starvation: a consumer blocked on an empty
    buffer counts (and only then does data.starved move)."""
    gate = threading.Event()

    def slow():
        for i in range(2):
            gate.wait(timeout=10)
            gate.clear()
            yield np.full((2,), float(i))

    pf = DevicePrefetcher(slow())
    out = []
    t = threading.Thread(target=lambda: out.append(next(pf)))
    t.start()
    deadline = time.perf_counter() + 10
    while telemetry.value("data.starved") < 1:   # consumer provably blocked
        assert time.perf_counter() < deadline
        time.sleep(0.005)
    gate.set()                                   # now let the producer feed
    t.join(timeout=10)
    assert out and float(out[0].asnumpy()[0]) == 0.0
    assert telemetry.snapshot()["histograms"]["data.wait"]["count"] >= 1
    gate.set()   # release the producer's NEXT pull so close joins instantly
    pf.close()
    assert not pf._thread.is_alive()


def test_prefetcher_worker_death_restart_loses_nothing(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "prefetch_death@1")
    src = [np.full((2,), float(i)) for i in range(5)]
    pf = DevicePrefetcher(iter(src))
    vals = [float(v.asnumpy()[0]) for v in pf]
    pf.close()
    assert vals == [0.0, 1.0, 2.0, 3.0, 4.0]
    assert telemetry.value("data.prefetch_restarts") == 1


def test_prefetcher_worker_death_budget_exhausted(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "prefetch_death@0")
    monkeypatch.setenv("MXTPU_DL_WORKER_RESTARTS", "0")
    pf = DevicePrefetcher(iter([np.zeros(2)]))
    with pytest.raises(RuntimeError, match="giving up after"):
        list(pf)
    pf.close()


def test_prefetcher_source_error_raises_at_consumer():
    def src():
        yield np.zeros(2)
        raise ValueError("decode exploded")

    pf = DevicePrefetcher(src())
    next(pf)
    with pytest.raises(ValueError, match="decode exploded"):
        next(pf)
    pf.close()


def test_prefetcher_concurrent_close_unblocks_consumer_cleanly():
    """close() from another thread while a consumer is blocked on a slow
    source ends the stream as StopIteration — never a spurious
    worker-death restart or a fake 'worker died' RuntimeError."""
    gate = threading.Event()

    def slow():
        gate.wait(timeout=10)
        yield np.zeros(2)

    pf = DevicePrefetcher(slow())
    result = {}

    def consume():
        try:
            next(pf)
            result["out"] = "item"
        except StopIteration:
            result["out"] = "stop"
        except RuntimeError as e:
            result["out"] = e

    t = threading.Thread(target=consume)
    t.start()
    deadline = time.perf_counter() + 10
    while telemetry.value("data.starved") < 1:   # consumer provably blocked
        assert time.perf_counter() < deadline
        time.sleep(0.005)
    closer = threading.Thread(target=pf.close)
    closer.start()           # producer still parked inside the source
    t.join(timeout=10)       # consumer must unblock WITHOUT the producer
    assert result["out"] == "stop"
    gate.set()               # now release the producer so close joins fast
    closer.join(timeout=10)
    assert not closer.is_alive()
    assert telemetry.value("data.prefetch_restarts") == 0


def test_prefetcher_depth_zero_clamps_instead_of_hanging():
    """An explicit depth=0 must clamp to 1: a zero-capacity buffer makes
    the producer's backpressure check permanently true — it never
    produces, never dies, and the consumer would hang forever."""
    pf = DevicePrefetcher(iter([np.zeros(2), np.ones(2)]), depth=0)
    got = list(pf)
    pf.close()
    assert len(got) == 2
    assert pf._depth == 1


def test_prefetcher_mid_epoch_close_is_bounded_and_cleans_source():
    """close() mid-epoch: wakes a producer blocked on a full buffer,
    joins within the timeout, and runs a generator source's finally."""
    cleaned = []

    def src():
        try:
            for i in range(1000):
                yield np.full((2,), float(i))
        finally:
            cleaned.append(True)

    pf = DevicePrefetcher(src(), depth=2)
    next(pf)                                     # pipeline is flowing
    t0 = time.perf_counter()
    pf.close()
    assert time.perf_counter() - t0 < 5.0
    assert cleaned == [True]
    assert not pf._thread.is_alive()


# ----------------------------------------------------------- PrefetchingIter
def _collect_batches(it):
    out = []
    for b in it:
        out.append((b.data[0].asnumpy().copy(),
                    b.label[0].asnumpy().copy() if b.label else None))
    return out


def test_prefetching_iter_equivalence_and_exhausted_reset():
    """The old implementation could deadlock in reset() once the
    underlying iter was exhausted (worker parked on an event never set
    again); the DevicePrefetcher delegation joins with a timeout."""
    x = np.arange(80, dtype=np.float32).reshape(20, 4)
    y = np.arange(20, dtype=np.float32)
    base_it = NDArrayIter(x, y, batch_size=5)
    base = _collect_batches(base_it)
    base_it.reset()
    p = PrefetchingIter(base_it)
    assert [d.name for d in p.provide_data] == ["data"]
    got = _collect_batches(p)
    assert len(got) == len(base)
    for (d1, l1), (d2, l2) in zip(base, got):
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(l1, l2)
    for _ in range(2):                           # reset over EXHAUSTED iter
        p.reset()
        assert len(_collect_batches(p)) == len(base)
    p.reset()                                    # mid-epoch reset too
    p.next()
    p.reset()
    assert len(_collect_batches(p)) == len(base)
    p.close()


def test_prefetching_iter_multi_iter_merge_and_renames():
    x1 = np.arange(12, dtype=np.float32).reshape(12, 1)
    x2 = np.arange(12, 24, dtype=np.float32).reshape(12, 1)
    p = PrefetchingIter(
        [NDArrayIter(x1, batch_size=4), NDArrayIter(x2, batch_size=4)],
        rename_data=[{"data": "a"}, {"data": "b"}])
    assert [d.name for d in p.provide_data] == ["a", "b"]
    n = 0
    for b in p:
        assert len(b.data) == 2
        np.testing.assert_array_equal(b.data[1].asnumpy(),
                                      b.data[0].asnumpy() + 12)
        n += 1
    assert n == 3
    p.close()


def test_prefetching_iter_multi_iter_single_h2d_and_error_cleanup():
    """Multi-iter sub stages buffer on the HOST (the one H2D belongs to
    the outer stage — no double transfer), and a failing sub-iterator
    must not leak the OTHER iterator's sub producer through reset()."""
    x1 = np.arange(12, dtype=np.float32).reshape(12, 1)
    telemetry.reset()
    p = PrefetchingIter([NDArrayIter(x1, batch_size=4),
                         NDArrayIter(x1 + 12, batch_size=4)])
    n = sum(1 for _ in p)
    assert n == 3
    snap = telemetry.snapshot()["histograms"]
    # outer stage transferred each merged batch once; subs stayed host
    assert snap["data.h2d"]["count"] == 3
    assert "data.sub.h2d" not in snap
    p.close()

    class Exploding(NDArrayIter):
        def next(self):
            raise ValueError("sub iter exploded")

    p2 = PrefetchingIter([Exploding(x1, batch_size=4),
                          NDArrayIter(x1, batch_size=4)])
    deadline = time.perf_counter() + 10   # outer producer dies on the error
    while p2._prefetcher._thread.is_alive():
        assert time.perf_counter() < deadline
        time.sleep(0.005)
    with pytest.raises(ValueError, match="sub iter exploded"):
        p2.reset()   # pending producer error surfaces; subs closed anyway
    for sub in p2._sub:
        assert not sub._thread.is_alive()
    p2.close()


def test_prefetching_iter_worker_error_reraised():
    class Exploding(NDArrayIter):
        def next(self):
            b = super().next()
            if self._cursor == 1:
                raise ValueError("iterator exploded")
            return b

    it = Exploding(np.zeros((12, 2), np.float32), batch_size=4)
    p = PrefetchingIter(it)
    with pytest.raises(ValueError, match="iterator exploded"):
        _collect_batches(p)
    p.close()


# ------------------------------------------------------- DataLoader wiring
def test_dataloader_prefetch_to_device_parity():
    from mxtpu.gluon.data import ArrayDataset, DataLoader
    x = np.arange(80, dtype=np.float32).reshape(20, 4)
    y = np.arange(20, dtype=np.float32)
    ds = ArrayDataset(x, y)
    serial = [b[0].asnumpy() for b in DataLoader(ds, batch_size=4)]
    telemetry.reset()
    dl = DataLoader(ds, batch_size=4, prefetch_to_device=True)
    for epoch in range(2):                       # re-iteration works
        got = list(dl)
        assert len(got) == len(serial)
        for s, g in zip(serial, got):
            assert isinstance(g[0], mx.nd.NDArray)
            np.testing.assert_array_equal(s, g[0].asnumpy())
    snap = telemetry.snapshot()
    # the prefetcher owns the telemetry: one h2d per batch, and data.wait
    # now measures only starvation (present, but not decode-sized)
    assert snap["histograms"]["data.h2d"]["count"] == 2 * len(serial)
    assert "data.wait" in snap["histograms"]


def test_dataloader_prefetch_accepts_ndarray_samples():
    """A dataset yielding NDArray samples must keep working on the
    in-process paths with prefetch ON (the numpy-only batchify belongs
    to the mp worker pool alone)."""
    from mxtpu.gluon.data import DataLoader, SimpleDataset
    ds = SimpleDataset([mx.nd.array(np.full((3,), float(i)))
                        for i in range(8)])
    serial = [b.asnumpy() for b in DataLoader(ds, batch_size=4)]
    for kwargs in ({}, {"num_workers": 2, "thread_pool": True}):
        dl = DataLoader(ds, batch_size=4, prefetch_to_device=True, **kwargs)
        got = [b for b in dl]
        assert all(isinstance(g, mx.nd.NDArray) for g in got)
        for s, g in zip(serial, got):
            np.testing.assert_array_equal(s, g.asnumpy())


def test_dataloader_prefetch_with_worker_pool():
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from _mp_light_datasets import PlainArrayPairDataset

    from mxtpu.gluon.data import DataLoader
    ds = PlainArrayPairDataset(n=24)
    serial = [b[0].asnumpy() for b in DataLoader(ds, batch_size=4)]
    dl = DataLoader(ds, batch_size=4, num_workers=2,
                    prefetch_to_device=True)
    got = [b[0] for b in dl]
    dl.close()
    assert all(isinstance(g, mx.nd.NDArray) for g in got)
    for s, g in zip(serial, got):
        np.testing.assert_array_equal(s, g.asnumpy())


# ------------------------------------------------------------ StreamRecordIter
def test_stream_record_iter_protocol_and_epochs(tmp_path):
    rec, _ = _write_rec(tmp_path)
    it = StreamRecordIter(rec, batch_size=4, decode_fn=_decode((3, 4, 4)),
                          seed=3)
    assert it.provide_data[0].shape == (4, 3, 4, 4)
    assert it.provide_label[0].shape == (4,)
    e0 = []
    for b in it:
        assert isinstance(b.data[0], mx.nd.NDArray)
        e0.append(b.label[0].asnumpy().copy())
    assert len(e0) == 6 and e0[-1].shape == (3,)  # keep tail, pad reported
    it.reset()
    e1 = [b.label[0].asnumpy().copy() for b in it]
    assert not all(np.array_equal(a, b) for a, b in zip(e0, e1))
    np.testing.assert_array_equal(np.sort(np.concatenate(e0)),
                                  np.sort(np.concatenate(e1)))
    it.close()


@pytest.mark.parametrize("consume", [1, 5])
def test_stream_record_iter_mid_epoch_reset_replays(tmp_path, consume):
    """reset() after a mid-epoch abandon replays the SAME epoch — even
    one batch from the end, where the prefetcher's read-ahead has already
    exhausted the reader generator producer-side (the replay contract is
    consumer-driven, not depth-dependent)."""
    rec, _ = _write_rec(tmp_path)
    it = StreamRecordIter(rec, batch_size=4, decode_fn=_decode((3, 4, 4)),
                          seed=3)
    full = [b.label[0].asnumpy().copy() for b in it]
    it.close()
    it2 = StreamRecordIter(rec, batch_size=4, decode_fn=_decode((3, 4, 4)),
                           seed=3)
    for _ in range(consume):                     # abandon mid-epoch
        it2.next()
    it2.reset()                                  # replays the SAME epoch
    replay = [b.label[0].asnumpy().copy() for b in it2]
    assert len(replay) == len(full)
    for a, b in zip(full, replay):
        np.testing.assert_array_equal(a, b)
    it2.close()


@pytest.mark.parametrize("kind,reader_hits", [("worker_death", True),
                                              ("prefetch_death", False)])
def test_composed_pipeline_fault_routing_is_deterministic(
        tmp_path, monkeypatch, kind, reader_hits):
    """In the composed pipeline (reader pool UNDER a prefetcher) each
    fault kind fires in exactly its own stage — never scheduling-
    dependent — and the stream still completes identically."""
    rec, _ = _write_rec(tmp_path)
    clean = [b.label[0].asnumpy().copy()
             for b in StreamRecordIter(rec, batch_size=4,
                                       decode_fn=_decode((3, 4, 4)),
                                       seed=2)]
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "%s@1" % kind)
    it = StreamRecordIter(rec, batch_size=4, decode_fn=_decode((3, 4, 4)),
                          seed=2)
    got = [b.label[0].asnumpy().copy() for b in it]
    it.close()
    for a, b in zip(clean, got):
        np.testing.assert_array_equal(a, b)
    assert resilience.FAULT_STATS["fired"] == [(kind, 1)]
    if reader_hits:
        assert telemetry.value("stream.worker_restarts") >= 1
        assert telemetry.value("data.prefetch_restarts") == 0
    else:
        assert telemetry.value("data.prefetch_restarts") == 1
        assert telemetry.value("stream.worker_restarts") == 0


def test_stream_record_iter_requires_decode_fn(tmp_path):
    """No decode_fn AND no batchify_fn = raw bytes with no shape to form
    a DataBatch from — refused loudly at construction, not an
    AttributeError from the producer thread later."""
    rec, _ = _write_rec(tmp_path)
    with pytest.raises(MXNetError, match="decode_fn"):
        StreamRecordIter(rec, batch_size=4)


def test_stream_threads_env_zero_selects_inline(tmp_path, monkeypatch):
    """MXTPU_STREAM_THREADS=0 honors the inline synchronous path, same
    as the num_threads=0 argument (the A/B baseline contract)."""
    monkeypatch.setenv("MXTPU_STREAM_THREADS", "0")
    rec, _ = _write_rec(tmp_path)
    rd = ShardedRecordReader(rec, batch_size=4,
                             decode_fn=_decode((3, 4, 4)), seed=5)
    assert rd.num_threads == 0
    assert len(list(rd)) == 6


@pytest.mark.parametrize("prefetch", [True, False])
def test_stream_record_iter_step_counted_epochs_progress(tmp_path, prefetch):
    """A step-counted loop (`for _ in range(len(it)): it.next()`) never
    observes StopIteration, but it consumed the whole epoch — reset()
    must PROGRESS the shuffle (full consumption is judged by delivered
    batches), not replay the same order forever."""
    rec, _ = _write_rec(tmp_path)
    it = StreamRecordIter(rec, batch_size=4, decode_fn=_decode((3, 4, 4)),
                          seed=3, prefetch_to_device=prefetch)
    def epoch_labels():
        out = []
        for _ in range(len(it._reader)):
            b = it.next()
            l = b.label[0]
            out.append(l.asnumpy().copy() if hasattr(l, "asnumpy") else
                       np.array(l))
        it.reset()
        return np.concatenate(out)
    e0, e1 = epoch_labels(), epoch_labels()
    assert not np.array_equal(e0, e1)            # reshuffled, not replayed
    np.testing.assert_array_equal(np.sort(e0), np.sort(e1))
    it.close()


def test_stream_record_iter_host_mode(tmp_path):
    """prefetch_to_device=False means HOST batches: numpy leaves, no
    producer thread, no device placement."""
    rec, _ = _write_rec(tmp_path)
    it = StreamRecordIter(rec, batch_size=4, decode_fn=_decode((3, 4, 4)),
                          seed=3, prefetch_to_device=False)
    dev = StreamRecordIter(rec, batch_size=4, decode_fn=_decode((3, 4, 4)),
                           seed=3)
    n = 0
    for hb, db in zip(it, dev):
        assert isinstance(hb.data[0], np.ndarray)      # host numpy
        assert isinstance(db.data[0], mx.nd.NDArray)   # device twin
        np.testing.assert_array_equal(hb.data[0], db.data[0].asnumpy())
        n += 1
    assert n == 6
    it.reset()                                   # host path resets too
    assert sum(1 for _ in it) == 6
    it.close()
    dev.close()


# -------------------------------------------------- mesh acceptance pins
@pytest.mark.multidevice
def test_prefetched_batches_land_on_trainer_sharding(tmp_path):
    """ISSUE 9 acceptance: per-replica batches land PRE-SHARDED on the
    mesh — the prefetched device buffers' sharding equals
    Trainer.shard_batch's NamedSharding (no host-side gather), down to
    identical per-device shards."""
    import jax

    from mxtpu import gluon
    from mxtpu.gluon import nn
    from mxtpu.parallel import make_mesh
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device mesh")
    net = nn.Dense(4)
    net.initialize()
    net(mx.nd.array(np.ones((8, 6), np.float32)))
    mesh = make_mesh({"data": len(jax.devices())})
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, mesh=mesh)
    ref_sh = tr.batch_sharding
    assert ref_sh is not None

    n = len(jax.devices())
    src = [(np.arange(8 * 6, dtype=np.float32).reshape(8, 6) + i,
            np.arange(8, dtype=np.float32)) for i in range(3)]
    pf = DevicePrefetcher(iter(src), sharding=tr)
    got = list(pf)
    pf.close()
    for i, (d, l) in enumerate(got):
        ref = tr.shard_batch(mx.nd.array(src[i][0]))
        assert d._data.sharding == ref._data.sharding == ref_sh
        assert l._data.sharding == ref_sh
        shard_shapes = {s.data.shape for s in d._data.addressable_shards}
        assert shard_shapes == {(8 // n, 6)}     # pre-sharded, no gather
        np.testing.assert_array_equal(d.asnumpy(), src[i][0])


@pytest.mark.multidevice
def test_dataloader_and_stream_iter_mesh_path(tmp_path):
    """Both front doors — DataLoader(prefetch_to_device=trainer) and
    StreamRecordIter(sharding=trainer) — deliver mesh-sharded batches."""
    import jax

    from mxtpu import gluon
    from mxtpu.gluon import nn
    from mxtpu.gluon.data import ArrayDataset, DataLoader
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device mesh")
    from mxtpu.parallel import make_mesh
    net = nn.Dense(2)
    net.initialize()
    net(mx.nd.array(np.ones((8, 3), np.float32)))
    mesh = make_mesh({"data": len(jax.devices())})
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, mesh=mesh)
    sh = tr.batch_sharding

    x = np.arange(48, dtype=np.float32).reshape(16, 3)
    y = np.arange(16, dtype=np.float32)
    dl = DataLoader(ArrayDataset(x, y), batch_size=8,
                    prefetch_to_device=tr)
    for d, l in dl:
        assert d._data.sharding == sh and l._data.sharding == sh

    rec, _ = _write_rec(tmp_path, n=24, shape=(3,), name="mesh")
    it = StreamRecordIter(rec, batch_size=8, decode_fn=_decode((3,)),
                          seed=0, sharding=tr, last_batch="discard")
    count = 0
    for b in it:
        assert b.data[0]._data.sharding == sh
        count += 1
    assert count == 3
    it.close()
