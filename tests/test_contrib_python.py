"""Contrib python remainder (VERDICT r2 missing #8): text embeddings,
tensorboard logger, SVRG module, KL-entropy quantization calibration.

Reference: python/mxnet/contrib/{text/, tensorboard.py,
svrg_optimization/, quantization.py _get_optimal_thresholds}.
"""
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import gluon


# ------------------------------------------------------------------- text
def test_vocabulary_indexing():
    from mxtpu.contrib.text import Vocabulary
    from mxtpu.contrib.text.utils import count_tokens_from_str

    counter = count_tokens_from_str("a b b c c c\nd d d d")
    v = Vocabulary(counter, most_freq_count=3, min_freq=2,
                   reserved_tokens=["<pad>"])
    # layout: <unk>, <pad>, then frequency-major tokens
    assert v.idx_to_token[:2] == ["<unk>", "<pad>"]
    assert v.to_indices("d") == 2
    assert v.to_indices(["c", "b", "zzz"]) == [3, 4, 0]
    assert v.to_tokens(2) == "d"
    assert len(v) == 5  # unk + pad + d,c,b ('a' fails min_freq)


def test_custom_embedding_from_file(tmp_path):
    from mxtpu.contrib.text.embedding import (CompositeEmbedding,
                                              CustomEmbedding)
    from mxtpu.contrib.text import Vocabulary

    path = tmp_path / "vecs.txt"
    path.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
    emb = CustomEmbedding(str(path))
    assert emb.vec_len == 3
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("world").asnumpy(), [4, 5, 6])
    # unknown token -> zero vector
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("nope").asnumpy(), [0, 0, 0])
    got = emb.get_vecs_by_tokens(["hello", "world"]).asnumpy()
    np.testing.assert_allclose(got, [[1, 2, 3], [4, 5, 6]])
    emb.update_token_vectors("hello", mx.nd.array([9.0, 9.0, 9.0]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [9, 9, 9])

    # fastText .vec header is skipped
    path2 = tmp_path / "vecs.vec"
    path2.write_text("2 3\nfoo 1 1 1\nbar 2 2 2\n")
    emb2 = CustomEmbedding(str(path2))
    assert emb2.vec_len == 3 and len(emb2) == 3

    vocab = Vocabulary({"hello": 2, "foo": 1})
    comp = CompositeEmbedding(vocab, [emb, emb2])
    assert comp.vec_len == 6
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("hello").asnumpy(), [9, 9, 9, 0, 0, 0])
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("foo").asnumpy(), [0, 0, 0, 1, 1, 1])


# ------------------------------------------------------------ tensorboard
def test_log_metrics_callback(tmp_path):
    from mxtpu.contrib.tensorboard import LogMetricsCallback
    from mxtpu import metric as metric_mod
    from mxtpu.model import BatchEndParam

    logdir = str(tmp_path / "tb")
    cb = LogMetricsCallback(logdir)
    m = metric_mod.create("acc")
    m.update([mx.nd.array([1.0, 0.0])],
             [mx.nd.array([[0.1, 0.9], [0.8, 0.2]])])
    cb(BatchEndParam(epoch=0, nbatch=1, eval_metric=m, locals=None))
    cb.flush()
    cb.close()
    files = os.listdir(logdir)
    assert files, "no event/jsonl file written"


# ------------------------------------------------------------------ SVRG
def test_svrg_module_converges_linear_regression():
    from mxtpu.contrib.svrg_optimization import SVRGModule
    from mxtpu.io import NDArrayIter

    r = np.random.RandomState(0)
    true_w = np.array([[2.0], [-3.0], [1.5]], np.float32)
    X = r.uniform(-1, 1, (200, 3)).astype(np.float32)
    Y = (X @ true_w).ravel() + r.normal(0, 0.01, 200).astype(np.float32)

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("lin_label")
    pred = mx.sym.FullyConnected(data, weight=mx.sym.Variable("w"),
                                 bias=mx.sym.Variable("b"), num_hidden=1,
                                 name="fc")
    out = mx.sym.LinearRegressionOutput(pred, label, name="lro")

    it = NDArrayIter(X, Y, batch_size=20, label_name="lin_label")
    mod = SVRGModule(out, data_names=("data",), label_names=("lin_label",),
                     update_freq=2)
    mod.fit(it, num_epoch=8, optimizer="sgd",
            optimizer_params=(("learning_rate", 0.2),), eval_metric="mse")
    w = mod.get_params()[0]["w"].asnumpy().ravel()
    np.testing.assert_allclose(w, true_w.ravel(), atol=0.15)


def test_svrg_variance_reduction_math():
    """After update_full_grads, update() applies g - g_snapshot + mu: with
    weights == snapshot, the applied gradient equals mu exactly."""
    from mxtpu.contrib.svrg_optimization import SVRGModule
    from mxtpu.io import NDArrayIter

    r = np.random.RandomState(1)
    X = r.uniform(-1, 1, (40, 3)).astype(np.float32)
    Y = r.uniform(-1, 1, 40).astype(np.float32)
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("lin_label")
    pred = mx.sym.FullyConnected(data, weight=mx.sym.Variable("w"),
                                 bias=mx.sym.Variable("b"), num_hidden=1)
    out = mx.sym.LinearRegressionOutput(pred, label)
    it = NDArrayIter(X, Y, batch_size=10, label_name="lin_label")
    mod = SVRGModule(out, data_names=("data",), label_names=("lin_label",),
                     update_freq=1)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.0),))
    mod.update_full_grads(it)
    mu = {k: v.asnumpy() for k, v in mod._full_grads.items()}
    it.reset()
    batch = next(iter(it))
    mod.forward_backward(batch)
    # weights == snapshot -> g and g_snapshot cancel
    mod.update()
    for name in ("w", "b"):
        got = mod._exec.grad_dict[name].asnumpy()
        np.testing.assert_allclose(got, mu[name], rtol=1e-4, atol=1e-5)


# ---------------------------------------------------- entropy calibration
def test_entropy_calibration_clips_outliers():
    from mxtpu.contrib.quantization import (_optimal_threshold, calibrate,
                                            quantize_net, freeze)

    r = np.random.RandomState(0)
    # heavy-tailed: bulk in [-1, 1], a few extreme outliers at +-50
    bulk = r.normal(0, 0.3, 100000).astype(np.float32)
    outliers = np.array([50.0, -50.0, 45.0], np.float32)
    arr = np.concatenate([bulk, outliers])
    th = _optimal_threshold(arr)
    assert th < 10.0, "entropy threshold should clip the +-50 outliers"

    # end to end: entropy calibration quantizes better than naive when the
    # calibration data has a spike
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16))
    net.initialize()
    x = r.normal(0, 0.3, (64, 8)).astype(np.float32)
    x[0, 0] = 60.0  # one wild outlier
    xs = mx.nd.array(x)
    net(xs)
    ref = net(xs).asnumpy()

    def accuracy(mode):
        q = gluon.nn.HybridSequential()
        with q.name_scope():
            q.add(gluon.nn.Dense(16))
        q.initialize()
        q(xs)
        q[0].weight.set_data(net[0].weight.data())
        q[0].bias.set_data(net[0].bias.data())
        quantize_net(q, quiet=True)
        calibrate(q, [xs], mode=mode)
        freeze(q)
        got = q(xs).asnumpy()
        return np.abs(got[1:] - ref[1:]).mean()  # error off the outlier row

    assert accuracy("entropy") < accuracy("naive")


# ------------------------------------------------------------------- rtc
def test_rtc_pallas_module():
    """Runtime-compiled Pallas kernel launched on NDArrays
    (ref: python/mxnet/rtc.py CudaModule; test_rtc.py pattern)."""
    from mxtpu.rtc import PallasModule

    src = """
def axpy(x_ref, y_ref, out_ref):
    out_ref[...] = 2.5 * x_ref[...] + y_ref[...]

def square(x_ref, out_ref):
    out_ref[...] = x_ref[...] * x_ref[...]
"""
    mod = PallasModule(src)
    x = mx.nd.array(np.arange(8, dtype=np.float32).reshape(2, 4))
    y = mx.nd.array(np.ones((2, 4), np.float32))
    k = mod.get_kernel("axpy")
    out = k.launch([x, y], out_shapes=(2, 4))
    np.testing.assert_allclose(out.asnumpy(),
                               2.5 * x.asnumpy() + 1.0, rtol=1e-6)
    sq = mod.get_kernel("square").launch([x], out_shapes=(2, 4))
    np.testing.assert_allclose(sq.asnumpy(), x.asnumpy() ** 2)

    import pytest as _pytest
    with _pytest.raises(Exception, match="not in module"):
        mod.get_kernel("nope")


def test_contrib_namespace_aliases():
    """mx.contrib.{ndarray,nd,symbol,sym,quant} (ref:
    python/mxnet/contrib/__init__.py:21-35)."""
    import mxtpu as mx
    assert mx.contrib.nd is mx.contrib.ndarray
    assert mx.contrib.sym is mx.contrib.symbol
    assert mx.contrib.nd.box_nms is not None
    assert mx.contrib.sym.quadratic is not None
    assert mx.contrib.quant is mx.contrib.quantization


def test_contrib_autograd_legacy_api():
    """Old experimental autograd spellings (ref: contrib/autograd.py)."""
    import numpy as np
    import mxtpu as mx
    from mxtpu.contrib import autograd as cag

    x = mx.nd.array(np.array([3.0, -1.0], np.float32))
    grads, loss = cag.grad_and_loss(lambda a: (a * a).sum())(x)
    np.testing.assert_allclose(grads[0].asnumpy(), [6.0, -2.0])
    assert float(loss.asnumpy()) == 10.0
    g = cag.grad(lambda a: (2 * a).sum())(x)
    np.testing.assert_allclose(g[0].asnumpy(), [2.0, 2.0])
    # mark_variables + train_section + backward
    y = mx.nd.array(np.ones(2, np.float32))
    cag.mark_variables(y, mx.nd.zeros(2))
    with cag.train_section():
        out = (y * 3).sum()
    cag.backward(out)
    np.testing.assert_allclose(y.grad.asnumpy(), [3.0, 3.0])


def test_contrib_dataloader_iter_bridge():
    """gluon DataLoader -> Module-style DataIter (ref: contrib/io.py:25):
    shapes learned from the first batch, short tail zero-padded with
    honest pad count, reset replays."""
    import numpy as np
    import mxtpu as mx
    from mxtpu.gluon.data import ArrayDataset, DataLoader

    ds = ArrayDataset(np.arange(14, dtype=np.float32).reshape(7, 2),
                      np.arange(7, dtype=np.float32))
    it = mx.contrib.io.DataLoaderIter(
        DataLoader(ds, batch_size=3, last_batch="keep"))
    assert it.provide_data[0].shape == (3, 2)
    batches = list(it)
    assert [b.pad for b in batches] == [0, 0, 2]
    # padded tail: real rows then zeros
    tail = batches[-1].data[0].asnumpy()
    np.testing.assert_allclose(tail[0], [12.0, 13.0])
    np.testing.assert_allclose(tail[1:], 0.0)
    it.reset()
    assert len(list(it)) == 3


def test_contrib_fix_regressions():
    import numpy as np
    import pytest as _pt
    import mxtpu as mx
    from mxtpu.gluon.data import ArrayDataset, DataLoader
    # empty loader is a clear error, not a stray StopIteration
    with _pt.raises(ValueError, match="non-empty"):
        mx.contrib.io.DataLoaderIter(
            DataLoader(ArrayDataset(np.zeros((0, 2), np.float32),
                                    np.zeros(0, np.float32)), batch_size=2))
    # sym.random.randn parity with nd.random.randn
    s = mx.sym.random.randn(2, 3)
    assert s is not None


def test_rtc_cudamodule_reference_name():
    """mx.rtc.CudaModule (reference spelling): CUDA C++ source raises
    with migration guidance; Python/Pallas source routes to
    PallasModule."""
    import pytest
    import mxtpu as mx
    with pytest.raises(mx.base.MXNetError, match="Pallas"):
        mx.rtc.CudaModule("__global__ void k(float* x) { x[0] = 1.f; }")
    mod = mx.rtc.CudaModule(
        "def double(x_ref, o_ref):\n    o_ref[...] = 2.0 * x_ref[...]\n")
    k = mod.get_kernel("double")
    import numpy as np
    out = k.launch([mx.nd.array(np.arange(4, dtype=np.float32))], (4,))
    np.testing.assert_allclose(out.asnumpy(), [0, 2, 4, 6])
