"""Systematic operator sweep: every registered op is exercised.

The reference's test_operator.py (7,213 LoC) checks each op family against a
NumPy implementation with finite-difference gradient checks. This file is the
table-driven TPU-native equivalent:

* ``CASES``        — name -> forward spec (inputs, attrs, NumPy oracle) with
                     optional bf16-parity and numeric-gradient flags,
* ``COVERED_ELSEWHERE`` — ops with dedicated deeper tests in another file
                     (the coverage test verifies the claim by grepping it),
* ``test_registry_fully_covered`` — FAILS when someone registers a new op
                     without adding a case (VERDICT r2 item 3).

Forward parity runs in f32 against the oracle; ops flagged ``bf16`` re-run
with bfloat16 inputs at loose tolerance (TPU's native dtype — the reference
had no bf16 story at all). Ops flagged ``grad`` get a central-finite-
difference gradient check on tiny shapes.
"""
import os

import numpy as np
import pytest
import scipy.special
import scipy.linalg

import mxtpu as mx
from mxtpu.ops.registry import REGISTRY
from mxtpu.test_utils import assert_almost_equal, check_numeric_gradient

RNG = np.random.RandomState  # fresh, seeded per case


def C(inputs, oracle=None, kwargs=None, grad=False, bf16=None, rtol=1e-4,
      atol=1e-5, grad_rtol=1e-2, grad_atol=1e-3, run_only=False):
    """A sweep case. ``inputs`` is a callable -> list of np arrays."""
    if bf16 is None:
        bf16 = oracle is not None
    return dict(inputs=inputs, oracle=oracle, kwargs=kwargs or {}, grad=grad,
                bf16=bf16, rtol=rtol, atol=atol, grad_rtol=grad_rtol,
                grad_atol=grad_atol, run_only=run_only)


def _x(lo, hi, shape=(2, 3), seed=0):
    return lambda: [RNG(seed).uniform(lo, hi, shape).astype(np.float32)]


def _xy(lo, hi, sa=(2, 3, 1), sb=(1, 3, 4), seed=0):
    def gen():
        r = RNG(seed)
        return [r.uniform(lo, hi, sa).astype(np.float32),
                r.uniform(lo, hi, sb).astype(np.float32)]
    return gen


def _spd(n=3, batch=False, seed=0):
    """Symmetric positive-definite matrix (for potrf/potri/inverse/det)."""
    def gen():
        a = RNG(seed).uniform(-1, 1, (n, n)).astype(np.float32)
        m = a @ a.T + n * np.eye(n, dtype=np.float32)
        return [m[None] if batch else m]
    return gen


def _np_conv(x, w, b):
    import scipy.signal
    n, ci, hh, ww = x.shape
    co = w.shape[0]
    out = np.zeros((n, co, hh - 2, ww - 2), np.float32)
    for i in range(n):
        for o in range(co):
            acc = np.zeros((hh - 2, ww - 2), np.float32)
            for c in range(ci):
                acc += scipy.signal.correlate2d(x[i, c], w[o, c], mode="valid")
            out[i, o] = acc + b[o]
    return out


def _np_avgpool2(x):
    n, c, h, w = x.shape
    return x.reshape(n, c, h // 2, 2, w // 2, 2).mean((3, 5))


CASES = {}

# --------------------------------------------------------------- unary math
# name -> (np oracle, low, high, differentiable)
_UNARY = {
    "abs": (np.abs, 0.3, 2.0, True),
    "arccos": (np.arccos, -0.8, 0.8, True),
    "arccosh": (np.arccosh, 1.2, 3.0, True),
    "arcsin": (np.arcsin, -0.8, 0.8, True),
    "arcsinh": (np.arcsinh, -2.0, 2.0, True),
    "arctan": (np.arctan, -2.0, 2.0, True),
    "arctanh": (np.arctanh, -0.8, 0.8, True),
    "cbrt": (np.cbrt, 0.3, 2.0, True),
    "ceil": (np.ceil, -2.0, 2.0, False),
    "cos": (np.cos, -2.0, 2.0, True),
    "cosh": (np.cosh, -2.0, 2.0, True),
    "degrees": (np.degrees, -2.0, 2.0, True),
    "erf": (scipy.special.erf, -1.5, 1.5, True),
    "erfinv": (scipy.special.erfinv, -0.7, 0.7, True),
    "exp": (np.exp, -2.0, 2.0, True),
    "expm1": (np.expm1, -2.0, 2.0, True),
    "fix": (np.fix, -2.0, 2.0, False),
    "floor": (np.floor, -2.0, 2.0, False),
    "gammaln": (scipy.special.gammaln, 0.5, 3.0, True),
    "identity": (lambda x: x, -2.0, 2.0, True),
    "log": (np.log, 0.3, 3.0, True),
    "log10": (np.log10, 0.3, 3.0, True),
    "log1p": (np.log1p, -0.5, 2.0, True),
    "log2": (np.log2, 0.3, 3.0, True),
    "logical_not": (lambda x: (x == 0).astype(np.float32), -1.0, 1.0, False),
    "negative": (np.negative, -2.0, 2.0, True),
    "radians": (np.radians, -2.0, 2.0, True),
    "rcbrt": (lambda x: 1 / np.cbrt(x), 0.3, 2.0, True),
    "reciprocal": (np.reciprocal, 0.3, 2.0, True),
    "relu": (lambda x: np.maximum(x, 0), 0.2, 2.0, True),
    "rint": (np.rint, -2.0, 2.0, False),
    "round": (np.round, -2.0, 2.0, False),
    "rsqrt": (lambda x: 1 / np.sqrt(x), 0.3, 2.0, True),
    "sigmoid": (scipy.special.expit, -2.0, 2.0, True),
    "sign": (np.sign, 0.3, 2.0, False),
    "sin": (np.sin, -2.0, 2.0, True),
    "sinh": (np.sinh, -2.0, 2.0, True),
    "softsign": (lambda x: x / (1 + np.abs(x)), -2.0, 2.0, True),
    "sqrt": (np.sqrt, 0.3, 2.0, True),
    "square": (np.square, -2.0, 2.0, True),
    "tan": (np.tan, -1.0, 1.0, True),
    "tanh": (np.tanh, -2.0, 2.0, True),
    "trunc": (np.trunc, -2.0, 2.0, False),
}
for _name, (_fn, _lo, _hi, _diff) in _UNARY.items():
    CASES[_name] = C(_x(_lo, _hi), _fn, grad=_diff, rtol=1e-3, atol=1e-5)
CASES["gamma"] = C(_x(0.5, 3.0), scipy.special.gamma, grad=True, rtol=1e-3)
CASES["_random_gamma"] = C(lambda: [], None, run_only=True)  # statistical:
# sampler moments checked in test_random_ops_statistics below (was registered
# OVER the tgamma above until round 4 — see ops/random_ops.py gamma_sample)

# --------------------------------------------------------- binary broadcast
_BINARY = {
    "broadcast_add": (np.add, True),
    "broadcast_sub": (np.subtract, True),
    "broadcast_mul": (np.multiply, True),
    "broadcast_div": (np.divide, True),
    "broadcast_mod": (np.mod, False),
    "broadcast_power": (np.power, True),
    "broadcast_maximum": (np.maximum, True),
    "broadcast_minimum": (np.minimum, True),
    "broadcast_hypot": (np.hypot, True),
    "broadcast_equal": (lambda a, b: (a == b).astype(np.float32), False),
    "broadcast_not_equal": (lambda a, b: (a != b).astype(np.float32), False),
    "broadcast_greater": (lambda a, b: (a > b).astype(np.float32), False),
    "broadcast_greater_equal": (lambda a, b: (a >= b).astype(np.float32), False),
    "broadcast_lesser": (lambda a, b: (a < b).astype(np.float32), False),
    "broadcast_lesser_equal": (lambda a, b: (a <= b).astype(np.float32), False),
    "broadcast_logical_and": (lambda a, b: ((a != 0) & (b != 0)).astype(np.float32), False),
    "broadcast_logical_or": (lambda a, b: ((a != 0) | (b != 0)).astype(np.float32), False),
    "broadcast_logical_xor": (lambda a, b: ((a != 0) ^ (b != 0)).astype(np.float32), False),
    "arctan2": (np.arctan2, True),
    "ldexp": (lambda a, b: a * 2.0 ** b, True),
}
for _name, (_fn, _diff) in _BINARY.items():
    CASES[_name] = C(_xy(0.4, 2.0), _fn, grad=_diff, rtol=1e-3, atol=1e-5)

CASES["_rdiv_scalar"] = C(_x(0.4, 2.0), lambda x: 3.0 / x,
                          kwargs={"b": 3.0}, grad=True)
CASES["_rminus_scalar"] = C(_x(-2, 2), lambda x: 3.0 - x,
                            kwargs={"b": 3.0}, grad=True)
CASES["_rpower_scalar"] = C(_x(-1, 1), lambda x: 3.0 ** x,
                            kwargs={"b": 3.0}, grad=True, rtol=1e-3)

# -------------------------------------------------------------- reductions
def _red(np_fn, diff, kwargs=None, **kw):
    return C(_x(0.4, 2.0, (2, 3, 4)),
             lambda x, **k: np_fn(x), kwargs=kwargs or {}, grad=diff, **kw)


CASES["sum"] = _red(np.sum, True, rtol=1e-3)
CASES["mean"] = _red(np.mean, True, rtol=1e-3)
CASES["prod"] = _red(np.prod, True, rtol=1e-3)
CASES["nansum"] = _red(np.nansum, False, rtol=1e-3)
CASES["nanprod"] = _red(np.nanprod, False, rtol=1e-3)
CASES["max"] = _red(np.max, True)
CASES["min"] = _red(np.min, True)
CASES["norm"] = C(_x(0.4, 2.0, (3, 4)),
                  lambda x: np.sqrt((x ** 2).sum()), grad=True, rtol=1e-3)
CASES["argmax"] = C(_x(-2, 2, (3, 4)),
                    lambda x: x.argmax(1).astype(np.float32),
                    kwargs={"axis": 1}, bf16=False)
CASES["argmin"] = C(_x(-2, 2, (3, 4)),
                    lambda x: x.argmin(1).astype(np.float32),
                    kwargs={"axis": 1}, bf16=False)
CASES["argmax_channel"] = C(_x(-2, 2, (3, 4)),
                            lambda x: x.argmax(1).astype(np.float32),
                            bf16=False)
CASES["argsort"] = C(_x(-2, 2, (3, 4)),
                     lambda x: np.argsort(x, 1).astype(np.float32),
                     kwargs={"axis": 1}, bf16=False)
CASES["sort"] = C(_x(-2, 2, (3, 4)), lambda x: np.sort(x, 1),
                  kwargs={"axis": 1})
CASES["topk"] = C(_x(-2, 2, (3, 4)),
                  lambda x: np.argsort(-x, 1)[:, :2].astype(np.float32),
                  kwargs={"axis": 1, "k": 2}, bf16=False)
CASES["pick"] = C(lambda: [RNG(0).uniform(-1, 1, (3, 4)).astype(np.float32),
                           np.array([0, 3, 1], np.float32)],
                  lambda x, i: x[np.arange(3), i.astype(int)],
                  kwargs={"axis": 1})
CASES["softmax_cross_entropy"] = C(
    lambda: [RNG(0).uniform(-1, 1, (3, 4)).astype(np.float32),
             np.array([0, 3, 1], np.float32)],
    lambda x, l: -np.log(scipy.special.softmax(x, 1)[np.arange(3),
                                                     l.astype(int)]).sum(),
    rtol=1e-3)

# ---------------------------------------------------------- shape & layout
CASES["Reshape"] = C(_x(-2, 2, (2, 6)), lambda x: x.reshape(3, 4),
                     kwargs={"shape": (3, 4)}, grad=True)
CASES["Flatten"] = C(_x(-2, 2, (2, 3, 4)), lambda x: x.reshape(2, 12),
                     grad=True)
CASES["expand_dims"] = C(_x(-2, 2), lambda x: x[:, None, :],
                         kwargs={"axis": 1}, grad=True)
CASES["squeeze"] = C(_x(-2, 2, (2, 1, 3)), lambda x: x.squeeze(1),
                     kwargs={"axis": 1}, grad=True)
CASES["transpose"] = C(_x(-2, 2, (2, 3, 4)), lambda x: x.transpose(2, 0, 1),
                       kwargs={"axes": (2, 0, 1)}, grad=True)
CASES["swapaxes"] = C(_x(-2, 2, (2, 3, 4)), lambda x: x.swapaxes(0, 2),
                      kwargs={"dim1": 0, "dim2": 2}, grad=True)
CASES["tile"] = C(_x(-2, 2), lambda x: np.tile(x, (2, 2)),
                  kwargs={"reps": (2, 2)}, grad=True)
CASES["repeat"] = C(_x(-2, 2), lambda x: np.repeat(x, 2, 1),
                    kwargs={"repeats": 2, "axis": 1}, grad=True)
CASES["reverse"] = C(_x(-2, 2), lambda x: x[:, ::-1],
                     kwargs={"axis": 1}, grad=True)
CASES["pad"] = C(_x(-2, 2, (1, 2, 3, 3)),
                 lambda x: np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1))),
                 kwargs={"mode": "constant",
                         "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)}, grad=True)
CASES["slice"] = C(_x(-2, 2, (3, 4)), lambda x: x[1:3, 0:2],
                   kwargs={"begin": (1, 0), "end": (3, 2)}, grad=True)
CASES["slice_axis"] = C(_x(-2, 2, (3, 4)), lambda x: x[:, 1:3],
                        kwargs={"axis": 1, "begin": 1, "end": 3}, grad=True)
CASES["slice_like"] = C(_xy(-2, 2, (4, 5), (2, 3)), lambda a, b: a[:2, :3],
                        grad=True)
CASES["broadcast_to"] = C(_x(-2, 2, (1, 3)),
                          lambda x: np.broadcast_to(x, (2, 3)),
                          kwargs={"shape": (2, 3)}, grad=True)
CASES["broadcast_axis"] = C(_x(-2, 2, (1, 3)),
                            lambda x: np.broadcast_to(x, (4, 3)),
                            kwargs={"axis": 0, "size": 4}, grad=True)
CASES["broadcast_like"] = C(_xy(-2, 2, (1, 3), (2, 3)),
                            lambda a, b: np.broadcast_to(a, (2, 3)), grad=True)
def _np_depth_to_space(x, b=2):
    """Explicit index-formula oracle (ref matrix_op.cc depth_to_space, DCR):
    out[n, c, h*b+i, w*b+j] = in[n, (i*b + j)*C_out + c, h, w]."""
    n, c, h, w = x.shape
    co = c // (b * b)
    out = np.zeros((n, co, h * b, w * b), x.dtype)
    for i in range(b):
        for j in range(b):
            for cc in range(co):
                out[:, cc, i::b, j::b] = x[:, (i * b + j) * co + cc]
    return out


def _np_space_to_depth(x, b=2):
    n, c, h, w = x.shape
    out = np.zeros((n, c * b * b, h // b, w // b), x.dtype)
    for i in range(b):
        for j in range(b):
            for cc in range(c):
                out[:, (i * b + j) * c + cc] = x[:, cc, i::b, j::b]
    return out


CASES["depth_to_space"] = C(
    _x(-2, 2, (1, 8, 2, 2)), _np_depth_to_space,
    kwargs={"block_size": 2}, grad=True)
CASES["space_to_depth"] = C(
    _x(-2, 2, (1, 2, 4, 4)), _np_space_to_depth,
    kwargs={"block_size": 2}, grad=True)
CASES["diag"] = C(_x(-2, 2, (3, 3)), np.diag, grad=True)
CASES["clip"] = C(_x(-2, 2), lambda x: np.clip(x, -1, 1),
                  kwargs={"a_min": -1.0, "a_max": 1.0}, grad=False)
CASES["where"] = C(
    lambda: [np.array([[1, 0, 1]], np.float32),
             RNG(0).uniform(-1, 1, (2, 3)).astype(np.float32),
             RNG(1).uniform(-1, 1, (2, 3)).astype(np.float32)],
    lambda c, x, y: np.where(np.broadcast_to(c != 0, x.shape), x, y),
    grad=True)
CASES["one_hot"] = C(lambda: [np.array([0, 2, 1], np.float32)],
                     lambda i: np.eye(3, dtype=np.float32)[i.astype(int)],
                     kwargs={"depth": 3}, bf16=False)
CASES["shape_array"] = C(_x(-2, 2, (2, 3)),
                         lambda x: np.array([2, 3], np.int64), bf16=False)
CASES["size_array"] = C(_x(-2, 2, (2, 3)),
                        lambda x: np.array([6], np.int64), bf16=False)
CASES["cast"] = C(_x(-2, 2), lambda x: x.astype(np.float16),
                  kwargs={"dtype": "float16"}, bf16=False, rtol=1e-2,
                  atol=1e-3)
CASES["stack"] = C(_xy(-2, 2, (2, 3), (2, 3)),
                   lambda a, b: np.stack([a, b], 1), kwargs={"axis": 1},
                   grad=True)
CASES["Concat"] = C(_xy(-2, 2, (2, 3), (2, 3)),
                    lambda a, b: np.concatenate([a, b], 1),
                    kwargs={"dim": 1}, grad=True)
CASES["SliceChannel"] = C(
    _x(-2, 2, (2, 4)),
    lambda x: (x[:, :2], x[:, 2:]),
    kwargs={"num_outputs": 2, "axis": 1})
CASES["elemwise_sum"] = C(_xy(-2, 2, (2, 3), (2, 3)), lambda a, b: a + b,
                          grad=True)
CASES["BlockGrad"] = C(_x(-2, 2), lambda x: x)
CASES["make_loss"] = C(_x(-2, 2), lambda x: x)
CASES["smooth_l1"] = C(
    _x(-2, 2), lambda x: np.where(np.abs(x) < 1, 0.5 * x ** 2,
                                  np.abs(x) - 0.5),
    grad=True)
CASES["quadratic"] = C(_x(-2, 2), lambda x: 2 * x ** 2 + 3 * x + 1,
                       kwargs={"a": 2.0, "b": 3.0, "c": 1.0}, grad=True)

# ------------------------------------------------------------------ init
CASES["zeros"] = C(lambda: [], lambda: np.zeros((2, 3), np.float32),
                   kwargs={"shape": (2, 3)})
CASES["ones"] = C(lambda: [], lambda: np.ones((2, 3), np.float32),
                  kwargs={"shape": (2, 3)})
CASES["full"] = C(lambda: [], lambda: np.full((2, 3), 2.5, np.float32),
                  kwargs={"shape": (2, 3), "val": 2.5})
CASES["empty"] = C(lambda: [], None, kwargs={"shape": (2, 3)}, run_only=True)
# ^ run-only by definition: empty's CONTENTS are unspecified (ref: ndarray
#   empty docs); only shape/dtype/finiteness are checkable
CASES["eye"] = C(lambda: [], lambda: np.eye(3, 4, 1, dtype=np.float32),
                 kwargs={"N": 3, "M": 4, "k": 1})
CASES["arange"] = C(lambda: [], lambda: np.arange(1, 7, 2, dtype=np.float32),
                    kwargs={"start": 1, "stop": 7, "step": 2})
CASES["linspace"] = C(lambda: [],
                      lambda: np.linspace(0, 1, 5, dtype=np.float32),
                      kwargs={"start": 0.0, "stop": 1.0, "num": 5})
CASES["zeros_like"] = C(_x(-2, 2), np.zeros_like)
CASES["ones_like"] = C(_x(-2, 2), np.ones_like)
CASES["full_like"] = C(_x(-2, 2), lambda x: np.full_like(x, 1.5),
                       kwargs={"fill_value": 1.5})
CASES["arange_like"] = C(_x(-2, 2, (2, 3)),
                         lambda x: np.arange(6, dtype=np.float32).reshape(2, 3))
CASES["_contrib_arange_like"] = C(
    _x(-2, 2, (2, 3)),
    lambda x: np.arange(6, dtype=np.float32).reshape(2, 3))

# ------------------------------------------------------------- indexing
CASES["take"] = C(lambda: [RNG(0).uniform(-1, 1, (4, 3)).astype(np.float32),
                           np.array([0, 2], np.float32)],
                  lambda a, i: a[i.astype(int)])
CASES["batch_take"] = C(
    lambda: [RNG(0).uniform(-1, 1, (3, 4)).astype(np.float32),
             np.array([0, 3, 1], np.float32)],
    lambda a, i: a[np.arange(3), i.astype(int)])
CASES["gather_nd"] = C(
    lambda: [RNG(0).uniform(-1, 1, (3, 4)).astype(np.float32),
             np.array([[0, 2], [1, 3]], np.float32)],
    lambda a, i: a[i[0].astype(int), i[1].astype(int)])
def _np_scatter_nd(vals, idx, shape=(3, 4)):
    out = np.zeros(shape, vals.dtype)
    out[tuple(idx.astype(int))] = vals
    return out


CASES["scatter_nd"] = C(
    lambda: [np.array([9.0, 8.0], np.float32),
             np.array([[0, 2], [1, 3]], np.float32)],
    _np_scatter_nd, kwargs={"shape": (3, 4)})
def _np_scatter_set_nd(lhs, idx, rhs):
    out = lhs.copy()
    out[tuple(idx.astype(int))] = rhs
    return out


def _np_index_copy(old, index, new):
    out = old.copy()
    out[index.astype(int)] = new
    return out


CASES["_scatter_set_nd"] = C(
    lambda: [np.arange(12, dtype=np.float32).reshape(3, 4),
             np.array([[0, 2], [1, 3]], np.float32),
             np.array([9.0, 8.0], np.float32)],
    _np_scatter_set_nd, kwargs={"shape": (3, 4)})
CASES["_contrib_index_copy"] = C(
    lambda: [np.zeros((4, 3), np.float32), np.array([1, 3], np.float32),
             RNG(0).uniform(-1, 1, (2, 3)).astype(np.float32)],
    _np_index_copy)
CASES["Embedding"] = C(
    lambda: [np.array([1, 0, 3], np.float32),
             RNG(0).uniform(-1, 1, (5, 2)).astype(np.float32)],
    lambda i, w: w[i.astype(int)],
    kwargs={"input_dim": 5, "output_dim": 2})
CASES["dot"] = C(_xy(-1, 1, (3, 4), (4, 5)), lambda a, b: a @ b, grad=True,
                 rtol=1e-3)
CASES["batch_dot"] = C(_xy(-1, 1, (2, 3, 4), (2, 4, 5)),
                       lambda a, b: a @ b, grad=True, rtol=1e-3)
CASES["khatri_rao"] = C(
    _xy(-1, 1, (2, 3), (4, 3)),
    lambda a, b: scipy.linalg.khatri_rao(a, b), rtol=1e-3)

# --------------------------------------------------------------- linalg
CASES["linalg_gemm"] = C(
    lambda: [RNG(0).uniform(-1, 1, (2, 3)).astype(np.float32),
             RNG(1).uniform(-1, 1, (3, 4)).astype(np.float32),
             RNG(2).uniform(-1, 1, (2, 4)).astype(np.float32)],
    lambda a, b, c: a @ b + c, grad=True, rtol=1e-3)
CASES["linalg_gemm2"] = C(_xy(-1, 1, (2, 3), (3, 4)), lambda a, b: a @ b,
                          grad=True, rtol=1e-3)
CASES["linalg_potrf"] = C(_spd(), lambda m: np.linalg.cholesky(m),
                          rtol=1e-3, bf16=False)
CASES["linalg_potri"] = C(
    # input is the Cholesky factor L; potri(L) = inv(L L^T) (ref: la_op.h)
    lambda: [np.linalg.cholesky(_spd()()[0])],
    lambda l: np.linalg.inv(l @ l.T), rtol=2e-3, atol=1e-4, bf16=False)
CASES["linalg_inverse"] = C(_spd(), np.linalg.inv, rtol=2e-3, atol=1e-4,
                            bf16=False)
CASES["linalg_det"] = C(_spd(), lambda m: np.linalg.det(m).astype(np.float32),
                        rtol=1e-3, bf16=False)
CASES["linalg_slogdet"] = C(
    _spd(), lambda m: tuple(np.asarray(v, np.float32)
                            for v in np.linalg.slogdet(m)),
    rtol=1e-3, bf16=False)
CASES["linalg_sumlogdiag"] = C(
    _spd(), lambda m: np.log(np.diag(m)).sum().astype(np.float32),
    rtol=1e-3, bf16=False)
CASES["linalg_extractdiag"] = C(_x(-1, 1, (3, 3)), np.diag)
CASES["linalg_makediag"] = C(_x(-1, 1, (3,)), np.diag)
CASES["linalg_syrk"] = C(_x(-1, 1, (2, 3)), lambda a: a @ a.T, rtol=1e-3)
CASES["linalg_trmm"] = C(
    lambda: [np.tril(RNG(0).uniform(0.5, 1.5, (3, 3))).astype(np.float32),
             RNG(1).uniform(-1, 1, (3, 4)).astype(np.float32)],
    lambda a, b: a @ b, rtol=1e-3)
CASES["linalg_trsm"] = C(
    lambda: [(np.tril(RNG(0).uniform(0.5, 1.5, (3, 3)))
              + 2 * np.eye(3)).astype(np.float32),
             RNG(1).uniform(-1, 1, (3, 4)).astype(np.float32)],
    lambda a, b: scipy.linalg.solve_triangular(a, b, lower=True),
    rtol=1e-3, bf16=False)
CASES["linalg_gelqf"] = C(_x(-1, 1, (2, 4)), None, run_only=True)
# ^ LQ factors are unique only up to row signs, so a direct scipy compare
#   is convention-fragile; test_linalg_gelqf_properties below checks the
#   defining properties (A = L Q, Q orthonormal, L lower-triangular)
CASES["linalg_syevd"] = C(
    lambda: [(lambda a: a + a.T)(RNG(0).uniform(-1, 1, (3, 3))
                                 .astype(np.float32))],
    None, run_only=True)
# ^ eigenvectors are sign/order-ambiguous; test_linalg_syevd_properties
#   below checks A = U^T diag(L) U, orthonormality, and the eigenvalues
#   against numpy

# -------------------------------------------------------------------- nn
CASES["Activation"] = C(_x(-2, 2), np.tanh, kwargs={"act_type": "tanh"},
                        grad=True, rtol=1e-3)
CASES["SoftmaxActivation"] = C(
    _x(-2, 2, (2, 4)), lambda x: scipy.special.softmax(x, 1), rtol=1e-3)
CASES["softmax"] = C(_x(-2, 2, (2, 4)),
                     lambda x: scipy.special.softmax(x, 1),
                     kwargs={"axis": 1}, grad=True, rtol=1e-3)
CASES["softmin"] = C(_x(-2, 2, (2, 4)),
                     lambda x: scipy.special.softmax(-x, 1),
                     kwargs={"axis": 1}, grad=True, rtol=1e-3)
CASES["log_softmax"] = C(_x(-2, 2, (2, 4)),
                         lambda x: np.log(scipy.special.softmax(x, 1)),
                         kwargs={"axis": 1}, grad=True, rtol=1e-3,
                         atol=1e-4)
CASES["FullyConnected"] = C(
    lambda: [RNG(0).uniform(-1, 1, (2, 3)).astype(np.float32),
             RNG(1).uniform(-1, 1, (4, 3)).astype(np.float32),
             RNG(2).uniform(-1, 1, (4,)).astype(np.float32)],
    lambda x, w, b: x @ w.T + b, kwargs={"num_hidden": 4}, grad=True,
    rtol=1e-3)
CASES["Convolution"] = C(
    lambda: [RNG(0).uniform(-1, 1, (1, 2, 5, 5)).astype(np.float32),
             RNG(1).uniform(-1, 1, (3, 2, 3, 3)).astype(np.float32),
             RNG(2).uniform(-1, 1, (3,)).astype(np.float32)],
    _np_conv,
    kwargs={"kernel": (3, 3), "num_filter": 3}, grad=True, rtol=1e-3,
    atol=1e-4)
def _np_deconv(x, w):
    """Transposed conv, stride 1, no pad: out[n,o] = sum_i full-conv of
    x[n,i] with w[i,o] (ref: deconvolution.cc = gradient of Convolution)."""
    import scipy.signal
    n, ci, h, ww_ = x.shape
    co, kh = w.shape[1], w.shape[2]
    out = np.zeros((n, co, h + kh - 1, ww_ + kh - 1), np.float32)
    for b in range(n):
        for o in range(co):
            for i in range(ci):
                out[b, o] += scipy.signal.convolve2d(x[b, i], w[i, o],
                                                     mode="full")
    return out


CASES["Deconvolution"] = C(
    lambda: [RNG(0).uniform(-1, 1, (1, 3, 4, 4)).astype(np.float32),
             RNG(1).uniform(-1, 1, (3, 2, 3, 3)).astype(np.float32)],
    _np_deconv, kwargs={"kernel": (3, 3), "num_filter": 2, "no_bias": True},
    grad=True, rtol=1e-3, atol=1e-4)
CASES["Pooling"] = C(
    _x(-2, 2, (1, 2, 4, 4)), _np_avgpool2,
    kwargs={"kernel": (2, 2), "pool_type": "avg", "stride": (2, 2)},
    grad=True, rtol=1e-3)
def _np_lrn(x, nsize=3, alpha=1e-4, beta=0.75, knorm=2.0):
    """x / (k + alpha/n * sum_{window over C} x^2)^beta (ref: lrn.cc)."""
    n, c, h, w = x.shape
    half = nsize // 2
    out = np.zeros_like(x)
    for ci in range(c):
        lo, hi = max(0, ci - half), min(c, ci + half + 1)
        s = (x[:, lo:hi] ** 2).sum(axis=1)
        out[:, ci] = x[:, ci] / (knorm + alpha / nsize * s) ** beta
    return out


CASES["LRN"] = C(_x(0.1, 1, (1, 4, 3, 3)), _np_lrn, kwargs={"nsize": 3},
                 grad=True, rtol=1e-3)
CASES["LayerNorm"] = C(
    lambda: [RNG(0).uniform(-1, 1, (2, 4)).astype(np.float32),
             np.ones(4, np.float32), np.zeros(4, np.float32)],
    lambda x, g, b: (x - x.mean(-1, keepdims=True))
    / np.sqrt(x.var(-1, keepdims=True) + 1e-5),
    rtol=1e-3, atol=1e-4, grad=True)
CASES["InstanceNorm"] = C(
    lambda: [RNG(0).uniform(-1, 1, (2, 3, 4)).astype(np.float32),
             np.ones(3, np.float32), np.zeros(3, np.float32)],
    lambda x, g, b: (x - x.mean(-1, keepdims=True))
    / np.sqrt(x.var(-1, keepdims=True) + 1e-3),
    rtol=1e-3, atol=1e-4, grad=True)
CASES["L2Normalization"] = C(
    _x(-2, 2, (2, 4)),
    lambda x: x / np.sqrt((x ** 2).sum(1, keepdims=True) + 1e-10),
    rtol=1e-3, grad=True)
CASES["BatchNorm"] = C(
    lambda: [RNG(0).uniform(-1, 1, (2, 3, 4)).astype(np.float32),
             np.ones(3, np.float32), np.zeros(3, np.float32),
             np.zeros(3, np.float32), np.ones(3, np.float32)],
    lambda x, g, b, mm, mv: (x - mm[None, :, None])
    / np.sqrt(mv[None, :, None] + 1e-3),
    rtol=1e-3, atol=1e-4)  # eval mode: uses moving stats
CASES["LeakyReLU"] = C(
    _x(-2, 2), lambda x: np.where(x > 0, x, 0.25 * x),
    kwargs={"act_type": "leaky", "slope": 0.25}, grad=True, rtol=1e-3)
CASES["Dropout"] = C(_x(-2, 2), lambda x: x, kwargs={"p": 0.0})
CASES["_rrelu_train"] = C(
    # outside autograd.record the op takes its EVAL branch: deterministic
    # midpoint slope (lower+upper)/2 on negatives (ref: leaky_relu-inl.h)
    _x(-2, 2), lambda x: np.where(x > 0, x, (0.125 + 0.334) / 2 * x),
    kwargs={"lower_bound": 0.125, "upper_bound": 0.334}, rtol=1e-3)
CASES["SoftmaxOutput"] = C(
    lambda: [RNG(0).uniform(-1, 1, (3, 4)).astype(np.float32),
             np.array([0, 3, 1], np.float32)],
    lambda x, l: scipy.special.softmax(x, 1), rtol=1e-3)
CASES["LinearRegressionOutput"] = C(
    _xy(-1, 1, (2, 3), (2, 3)), lambda x, l: x)
CASES["LogisticRegressionOutput"] = C(
    _xy(-1, 1, (2, 3), (2, 3)), lambda x, l: scipy.special.expit(x),
    rtol=1e-3)
CASES["MAERegressionOutput"] = C(
    _xy(-1, 1, (2, 3), (2, 3)), lambda x, l: x)
CASES["_contrib_div_sqrt_dim"] = C(
    _x(-2, 2, (2, 4)), lambda x: x / np.sqrt(4.0), grad=True)
CASES["UpSampling"] = C(
    _x(-1, 1, (1, 2, 3, 3)), lambda x: x.repeat(2, 2).repeat(2, 3),
    kwargs={"scale": 2, "sample_type": "nearest"}, grad=True)
CASES["SequenceMask"] = C(
    _x(-1, 1, (3, 2, 4)), lambda x: x, kwargs={})  # no lengths = identity
CASES["SequenceLast"] = C(_x(-1, 1, (3, 2, 4)), lambda x: x[-1],
                          grad=True)
CASES["SequenceReverse"] = C(_x(-1, 1, (3, 2, 4)), lambda x: x[::-1],
                             grad=True)

# --------------------------------------------------------- vision / contrib
def _np_bilinear_at(img, y, x):
    """Sample img[c, y, x] bilinearly with edge clamping (one point)."""
    c, h, w = img.shape
    y0 = int(np.clip(np.floor(y), 0, h - 1))
    x0 = int(np.clip(np.floor(x), 0, w - 1))
    y1 = min(y0 + 1, h - 1)
    x1 = min(x0 + 1, w - 1)
    wy = np.clip(y, 0, h - 1) - y0
    wx = np.clip(x, 0, w - 1) - x0
    return (img[:, y0, x0] * (1 - wy) * (1 - wx)
            + img[:, y1, x0] * wy * (1 - wx)
            + img[:, y0, x1] * (1 - wy) * wx
            + img[:, y1, x1] * wy * wx)


def _np_roi_pool(data, rois, pooled=(2, 2)):
    """Brute-force max ROI pooling over a 2x-per-bin integer sample grid
    (this impl's documented ROIAlign-style discretization of
    roi_pooling.cc; see ops/contrib_ops.py ROIPooling)."""
    ph, pw = pooled
    outs = []
    for roi in rois:
        b = int(roi[0])
        x1, y1, x2, y2 = (int(round(v)) for v in roi[1:])
        rw, rh = max(x2 - x1 + 1, 1), max(y2 - y1 + 1, 1)
        img = data[b]
        c, h, w = img.shape
        ys = [min(max(y1 + (i * rh) // (ph * 2), 0), h - 1)
              for i in range(ph * 2)]
        xs = [min(max(x1 + (j * rw) // (pw * 2), 0), w - 1)
              for j in range(pw * 2)]
        v = img[:, ys][:, :, xs].reshape(c, ph, 2, pw, 2)
        outs.append(v.max(axis=(2, 4)))
    return np.stack(outs)


def _np_roi_align(data, rois, pooled=(2, 2), sr=2):
    """Brute-force ROIAlign (ref: roi_align.cc): sr x sr bilinear samples
    per bin, averaged."""
    ph, pw = pooled
    outs = []
    for roi in rois:
        b = int(roi[0])
        x1, y1, x2, y2 = roi[1:]
        rw, rh = max(x2 - x1, 1.0), max(y2 - y1, 1.0)
        bw, bh = rw / pw, rh / ph
        c = data.shape[1]
        out = np.zeros((c, ph, pw), np.float32)
        for i in range(ph):
            for j in range(pw):
                acc = np.zeros(c, np.float32)
                for si in range(sr):
                    for sj in range(sr):
                        y = y1 + i * bh + (si + 0.5) * bh / sr
                        x = x1 + j * bw + (sj + 0.5) * bw / sr
                        acc += _np_bilinear_at(data[b], y, x)
                out[:, i, j] = acc / (sr * sr)
        outs.append(out)
    return np.stack(outs)


CASES["ROIPooling"] = C(
    lambda: [RNG(0).uniform(0, 1, (1, 2, 8, 8)).astype(np.float32),
             np.array([[0, 0, 0, 4, 4]], np.float32)],
    _np_roi_pool, kwargs={"pooled_size": (2, 2)}, rtol=1e-4)
CASES["_contrib_ROIAlign"] = C(
    lambda: [RNG(0).uniform(0, 1, (1, 2, 8, 8)).astype(np.float32),
             np.array([[0, 0, 0, 4, 4]], np.float32)],
    _np_roi_align, kwargs={"pooled_size": (2, 2)}, rtol=1e-4)
CASES["_contrib_AdaptiveAvgPooling2D"] = C(
    _x(-1, 1, (1, 2, 4, 4)), lambda x: x.mean((2, 3), keepdims=True),
    kwargs={"output_size": 1}, rtol=1e-3)
def _np_bilinear_resize(x, oh=8, ow=8):
    """Half-pixel-center bilinear resize (jax.image.resize convention:
    in = (out + 0.5) * scale - 0.5, edges clamped)."""
    n, c, h, w = x.shape
    out = np.zeros((n, c, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            y = (i + 0.5) * h / oh - 0.5
            xx = (j + 0.5) * w / ow - 0.5
            for b in range(n):
                out[b, :, i, j] = _np_bilinear_at(x[b], max(y, 0.0),
                                                  max(xx, 0.0))
    return out


CASES["_contrib_BilinearResize2D"] = C(
    _x(-1, 1, (1, 2, 4, 4)), _np_bilinear_resize,
    kwargs={"height": 8, "width": 8}, rtol=1e-3, atol=1e-4)
CASES["_contrib_box_iou"] = C(
    lambda: [np.array([[0, 0, 2, 2]], np.float32),
             np.array([[1, 1, 3, 3]], np.float32)],
    lambda a, b: np.array([[1.0 / 7.0]], np.float32), rtol=1e-3)
CASES["_contrib_box_nms"] = C(
    lambda: [np.array([[[0, 0.9, 0, 0, 2, 2], [0, 0.8, 0, 0, 2, 2],
                        [1, 0.7, 5, 5, 7, 7]]], np.float32)],
    # hand-worked greedy NMS (ref bounding_box.cc output convention):
    # score order .9/.8/.7; box2 is a duplicate of box1 (IoU 1 > 0.5) so
    # its score -> -1; box3 doesn't overlap and survives
    lambda d: np.array([[[0, 0.9, 0, 0, 2, 2], [0, -1.0, 0, 0, 2, 2],
                         [1, 0.7, 5, 5, 7, 7]]], np.float32), bf16=False)


def _np_count_sketch(x, h, s, out_dim=4):
    n, d = x.shape
    out = np.zeros((n, out_dim), np.float32)
    for j in range(d):
        out[:, int(h[0, j])] += s[0, j] * x[:, j]
    return out


CASES["_contrib_count_sketch"] = C(
    lambda: [RNG(0).uniform(-1, 1, (2, 8)).astype(np.float32),
             RNG(1).randint(0, 4, (1, 8)).astype(np.float32),
             np.sign(RNG(2).uniform(-1, 1, (1, 8))).astype(np.float32)],
    _np_count_sketch, kwargs={"out_dim": 4}, rtol=1e-4)


def _np_fft_interleaved(x):
    f = np.fft.fft(x, axis=-1)
    return np.stack([f.real, f.imag], -1).reshape(
        x.shape[:-1] + (-1,)).astype(np.float32)


def _np_ifft_interleaved(x):
    z = x.reshape(x.shape[:-1] + (-1, 2))
    z = z[..., 0] + 1j * z[..., 1]
    return (np.real(np.fft.ifft(z, axis=-1)) * z.shape[-1]).astype(
        np.float32)


CASES["_contrib_fft"] = C(_x(-1, 1, (2, 8)), _np_fft_interleaved,
                          rtol=1e-3, atol=1e-4, bf16=False)
CASES["_contrib_ifft"] = C(_x(-1, 1, (2, 16)), _np_ifft_interleaved,
                           rtol=1e-3, atol=1e-4, bf16=False)
def _np_affine_grid(theta, h=4, w=4):
    """(ref: grid_generator.cc) target coords in [-1,1], row0 = x, row1 = y."""
    th = theta.reshape(-1, 2, 3)
    ys, xs = np.linspace(-1, 1, h), np.linspace(-1, 1, w)
    yy, xx = np.meshgrid(ys, xs, indexing="ij")
    src = np.stack([xx, yy, np.ones_like(xx)], 0).reshape(3, -1)
    return (th @ src).reshape(-1, 2, h, w).astype(np.float32)


def _np_bilinear_sample(data, grid):
    """(ref: bilinear_sampler.cc) normalized grid; out-of-bounds -> 0."""
    n, c, h, w = data.shape
    _, _, gh, gw = grid.shape
    out = np.zeros((n, c, gh, gw), np.float32)
    for b in range(n):
        for i in range(gh):
            for j in range(gw):
                x = (grid[b, 0, i, j] + 1) * (w - 1) / 2
                y = (grid[b, 1, i, j] + 1) * (h - 1) / 2
                if 0 <= x <= w - 1 and 0 <= y <= h - 1:
                    out[b, :, i, j] = _np_bilinear_at(data[b], y, x)
    return out


CASES["GridGenerator"] = C(
    lambda: [np.array([[1, 0, 0.25, 0, 1, -0.25]], np.float32)],
    lambda t: _np_affine_grid(t),
    kwargs={"transform_type": "affine", "target_shape": (4, 4)}, rtol=1e-4)
CASES["BilinearSampler"] = C(
    lambda: [RNG(0).uniform(-1, 1, (1, 1, 4, 4)).astype(np.float32),
             RNG(1).uniform(-0.9, 0.9, (1, 2, 3, 3)).astype(np.float32)],
    _np_bilinear_sample, rtol=1e-3, atol=1e-4)
CASES["SpatialTransformer"] = C(
    lambda: [RNG(0).uniform(-1, 1, (1, 1, 4, 4)).astype(np.float32),
             np.array([[1, 0, 0, 0, 1, 0]], np.float32)],
    # identity affine over a same-size target grid samples every pixel
    # exactly: the transform is the identity
    lambda d, loc: d, kwargs={"target_shape": (4, 4)}, rtol=1e-4)

CASES["_contrib_requantize"] = C(
    # int32 accumulators whose real range is +-100; recalibrate to +-4
    lambda: [np.array([[int(2.0 / 100 * (2 ** 31 - 1)),
                        int(-3.5 / 100 * (2 ** 31 - 1))]], np.int32)],
    lambda d: np.array([[int(2.0 / 4 * 127 + 0.5),
                         -int(3.5 / 4 * 127 + 0.5)]], np.int8),
    kwargs={"min_range": -100.0, "max_range": 100.0,
            "min_calib_range": -4.0, "max_calib_range": 4.0},
    bf16=False, rtol=0, atol=1.01)  # +-1 ulp rounding slack

# ------------------------------------------- legacy vision + SSD multibox
CASES["Crop"] = C(
    _x(-1, 1, (1, 2, 6, 6)), lambda x: x[:, :, 1:4, 2:6],
    kwargs={"offset": (1, 2), "h_w": (3, 4)}, grad=True)
CASES["SVMOutput"] = C(
    lambda: [RNG(0).uniform(-1, 1, (3, 4)).astype(np.float32),
             np.array([0, 3, 1], np.float32)],
    lambda x, l: x)  # identity forward; hinge grad tested separately
CASES["histogram"] = C(
    lambda: [np.array([0.1, 0.4, 0.6, 0.9, 2.5], np.float32)],
    lambda x: (np.histogram(x, bins=4, range=(0.0, 1.0))[0].astype(
        np.int32),
        np.linspace(0, 1, 5, dtype=np.float32)),
    kwargs={"bin_cnt": 4, "range": (0.0, 1.0)}, bf16=False)
def _np_correlation(a, b, k=1, bd=1, pad=1):
    """Brute-force FlowNet correlation (ref: correlation.cc), kernel 1,
    stride 1: out[d, y, x] = mean_c a[c, y, x] * b[c, y+dy, x+dx] over the
    padded inputs, displacement grid (2bd+1)^2."""
    n, c, h, w = a.shape
    pa = np.pad(a, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    pb = np.pad(b, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    border = bd  # + kernel radius 0
    oh = (h + 2 * pad) - 2 * border
    ow = (w + 2 * pad) - 2 * border
    grid = 2 * bd + 1
    out = np.zeros((n, grid * grid, oh, ow), np.float32)
    d = 0
    for dy in range(-bd, bd + 1):
        for dx in range(-bd, bd + 1):
            for y in range(oh):
                for x in range(ow):
                    ya, xa = y + border, x + border
                    out[:, d, y, x] = (pa[:, :, ya, xa]
                                       * pb[:, :, ya + dy, xa + dx]
                                       ).sum(1) / c
            d += 1
    return out


CASES["Correlation"] = C(
    _xy(-1, 1, (1, 2, 6, 6), (1, 2, 6, 6)), _np_correlation,
    kwargs={"kernel_size": 1, "max_displacement": 1, "pad_size": 1},
    rtol=1e-3, atol=1e-4)


def _np_multibox_prior(data, sizes=(0.5, 0.25), ratios=(1.0, 2.0)):
    """(ref: multibox_prior-inl.h) centers (i+0.5)/dim; anchor list = every
    size at ratio 1, then sizes[0] at each remaining ratio."""
    h, w = data.shape[2], data.shape[3]
    hw = [(s / 2 * h / w, s / 2) for s in sizes]
    hw += [(sizes[0] / 2 * np.sqrt(r) * h / w, sizes[0] / 2 / np.sqrt(r))
           for r in ratios[1:]]
    rows = []
    for i in range(h):
        cy = (i + 0.5) / h
        for j in range(w):
            cx = (j + 0.5) / w
            for hwidth, hheight in hw:
                rows.append([cx - hwidth, cy - hheight,
                             cx + hwidth, cy + hheight])
    return np.asarray(rows, np.float32).reshape(1, -1, 4)


CASES["_contrib_MultiBoxPrior"] = C(
    _x(-1, 1, (1, 3, 4, 4)), _np_multibox_prior,
    kwargs={"sizes": (0.5, 0.25), "ratios": (1.0, 2.0)}, rtol=1e-4)


def _mbt_expect(*_inputs):
    """Hand-worked SSD targets for the fixed case below (ref semantics,
    multibox_target.cc): gt [.12,.12,.38,.38] cls 0 vs anchors
    a0 [.1,.1,.4,.4], a1 [.5,.5,.9,.9]. IoU(a0,gt) = .0676/.09 ≈ .751 →
    a0 matched (cls target 1 = cls 0 + background shift), a1 background.
    Encode vs a0 (cx=cy=.25, w=h=.3) with variances (.1,.1,.2,.2):
    t_xy = 0, t_wh = log(.26/.3)/.2 ≈ -0.715394."""
    twh = float(np.log(0.26 / 0.3) / 0.2)
    loc_t = np.array([[0, 0, twh, twh, 0, 0, 0, 0]], np.float32)
    loc_m = np.array([[1, 1, 1, 1, 0, 0, 0, 0]], np.float32)
    cls_t = np.array([[1.0, 0.0]], np.float32)
    return loc_t, loc_m, cls_t


CASES["_contrib_MultiBoxTarget"] = C(
    lambda: [np.array([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]]],
                      np.float32),
             np.array([[[0.0, 0.12, 0.12, 0.38, 0.38]]], np.float32),
             RNG(0).uniform(0, 1, (1, 3, 2)).astype(np.float32)],
    _mbt_expect, rtol=1e-4, bf16=False)


def _mbd_expect(*_inputs):
    """Hand-worked detections for the fixed case below (ref semantics,
    multibox_detection.cc): anchor0 argmax class = 2 (p=.7) → id 1;
    anchor1 argmax = background → dropped. Zero loc deltas decode to the
    anchor box itself."""
    return np.array([[[1.0, 0.7, 0.1, 0.1, 0.4, 0.4],
                      [-1, -1, -1, -1, -1, -1]]], np.float32)


CASES["_contrib_MultiBoxDetection"] = C(
    # cls_prob [1, C=3, A=2]
    lambda: [np.array([[[0.1, 0.8], [0.2, 0.1], [0.7, 0.1]]], np.float32),
             np.zeros((1, 8), np.float32),
             np.array([[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]]],
                      np.float32)],
    _mbd_expect, rtol=1e-4, bf16=False)

# ------------------------------------------------------------- image ops
def _img(seed=0):
    return lambda: [RNG(seed).uniform(0, 255, (4, 5, 3)).astype(np.float32)]


CASES["_image_to_tensor"] = C(
    _img(), lambda x: x.transpose(2, 0, 1) / 255.0, rtol=1e-3)
CASES["_image_normalize"] = C(
    lambda: [RNG(0).uniform(0, 1, (3, 4, 5)).astype(np.float32)],
    lambda x: (x - 0.5) / 0.25,
    kwargs={"mean": 0.5, "std": 0.25}, rtol=1e-3)
CASES["_image_flip_left_right"] = C(_img(), lambda x: x[:, ::-1])
CASES["_image_flip_top_bottom"] = C(_img(), lambda x: x[::-1])
# random flips: output must be exactly x or its flip, and both outcomes
# must occur over repeated draws — property-tested in
# test_random_flips_are_flips below (no pointwise oracle exists)
CASES["_image_random_flip_left_right"] = C(_img(), None, run_only=True)
CASES["_image_random_flip_top_bottom"] = C(_img(), None, run_only=True)
CASES["_image_brightness"] = C(_img(), lambda x: x * 0.5,
                               kwargs={"alpha": 0.5}, rtol=1e-3)
_LUMA = np.array([0.299, 0.587, 0.114], np.float32)  # ITU-R BT.601


def _np_contrast(x, alpha=0.5):
    """alpha-blend toward the mean luma (ref: image_random-inl.h
    RandomContrast)."""
    gray = (x * _LUMA).sum(-1, keepdims=True)
    return x * alpha + gray.mean((-3, -2), keepdims=True) * (1 - alpha)


def _np_saturation(x, alpha=0.5):
    gray = (x * _LUMA).sum(-1, keepdims=True)
    return x * alpha + gray * (1 - alpha)


def _np_hue(x, alpha=0.1):
    """YIQ-rotation hue shift (ref: image_random-inl.h RandomHue)."""
    u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
    t_yiq = np.array([[0.299, 0.587, 0.114],
                      [0.596, -0.274, -0.321],
                      [0.211, -0.523, 0.311]], np.float32)
    t_rgb = np.array([[1.0, 0.956, 0.621],
                      [1.0, -0.272, -0.647],
                      [1.0, -1.107, 1.705]], np.float32)
    rot = np.array([[1, 0, 0], [0, u, -w], [0, w, u]], np.float32)
    m = t_rgb @ rot @ t_yiq
    return x @ m.T


CASES["_image_contrast"] = C(_img(), _np_contrast, kwargs={"alpha": 0.5},
                             rtol=1e-3, atol=1e-3)
CASES["_image_saturation"] = C(_img(), _np_saturation,
                               kwargs={"alpha": 0.5}, rtol=1e-3, atol=1e-3)
CASES["_image_hue"] = C(_img(), _np_hue, kwargs={"alpha": 0.1},
                        rtol=1e-3, atol=1e-2, bf16=False)
CASES["_image_crop"] = C(
    _img(), lambda x: x[1:3, 1:4],
    kwargs={"x": 1, "y": 1, "width": 3, "height": 2})
CASES["_image_center_crop"] = C(
    # 4x5 HWC image, crop size (w=2, h=2): y0 = (4-2)//2 = 1, x0 = (5-2)//2
    _img(), lambda x: x[1:3, 1:3], kwargs={"size": (2, 2)})


def _np_image_resize_bilinear(x, oh=8, ow=8):
    """HWC half-pixel bilinear = the NCHW oracle above on a transposed view.
    UPSAMPLE only: on downscale jax.image.resize anti-aliases with a
    widened triangle kernel, which point-sampling does not model."""
    return _np_bilinear_resize(x.transpose(2, 0, 1)[None], oh, ow)[0] \
        .transpose(1, 2, 0)


CASES["_image_resize"] = C(_img(), _np_image_resize_bilinear,
                           kwargs={"size": (8, 8)}, rtol=1e-3, atol=1e-2)

# -------------------------------------------------------- optimizer updates
CASES["sgd_update"] = C(
    _xy(-1, 1, (2, 3), (2, 3)), lambda w, g: w - 0.1 * g,
    kwargs={"lr": 0.1}, rtol=1e-3)
CASES["sgd_mom_update"] = C(
    lambda: [RNG(0).uniform(-1, 1, (2, 3)).astype(np.float32),
             RNG(1).uniform(-1, 1, (2, 3)).astype(np.float32),
             RNG(2).uniform(-1, 1, (2, 3)).astype(np.float32)],
    # mom' = momentum*mom - lr*grad; w' = w + mom' (ref: optimizer_op.cc
    # SGDMom; the op returns the updated weight, state mutates in place)
    lambda w, g, m: w + 0.9 * m - 0.1 * g,
    kwargs={"lr": 0.1, "momentum": 0.9}, rtol=1e-4, bf16=False)
CASES["signsgd_update"] = C(
    _xy(-1, 1, (2, 3), (2, 3)), lambda w, g: w - 0.1 * np.sign(g),
    kwargs={"lr": 0.1}, rtol=1e-3)
for _name in ("adam_update", "rmsprop_update", "rmspropalex_update",
              "ftrl_update", "adagrad_update", "nag_mom_update",
              "signum_update"):
    CASES[_name] = C(lambda: [], None, run_only=True)  # driven via Optimizer:
    # see test_optimizer_updates below (state layouts differ per op)

# ------------------------------------------------------------------ random
for _name in ("normal", "uniform", "exponential", "poisson",
              "negative_binomial", "generalized_negative_binomial",
              "randint", "normal_like", "uniform_like", "shuffle",
              "multinomial"):
    CASES[_name] = C(lambda: [], None, run_only=True)  # statistical tests below


# ops with dedicated deeper tests elsewhere; the coverage test greps the file
COVERED_ELSEWHERE = {
    # round-5 straggler ops: oracle tests incl. sparse storage semantics
    "hard_sigmoid": "test_straggler_ops.py",
    "_rmod_scalar": "test_straggler_ops.py",
    "_square_sum": "test_straggler_ops.py",
    "_scatter_plus_scalar": "test_straggler_ops.py",
    "_scatter_minus_scalar": "test_straggler_ops.py",
    "_scatter_elemwise_div": "test_straggler_ops.py",
    "_sample_unique_zipfian": "test_straggler_ops.py",
    "CTCLoss": "test_ctc.py",
    "Custom": "test_custom_op.py",
    "RNN": "test_operator.py",
    "foreach": "test_operator.py",
    "while_loop": "test_operator.py",
    "cond": "test_operator.py",
    "_contrib_quantize": "test_quantization.py",
    "_contrib_dequantize": "test_quantization.py",
    "_contrib_quantized_conv": "test_quantization.py",
    "_contrib_quantized_fully_connected": "test_quantization.py",
    "_contrib_ring_attention": "test_parallel.py",
    "_subgraph_exec": "test_subgraph.py",
    "_sg_flash_attention": "test_subgraph.py",
    "linalg_gelqf": "test_operator_sweep.py",  # run-only above
    # round-3 parity ops, oracle-tested in test_new_ops.py
    "BatchNorm_v1": "test_new_ops.py",
    "Convolution_v1": "test_new_ops.py",
    "Pooling_v1": "test_new_ops.py",
    "IdentityAttachKLSparseReg": "test_new_ops.py",
    "_contrib_DeformableConvolution": "test_new_ops.py",
    "_contrib_DeformablePSROIPooling": "test_new_ops.py",
    "_contrib_PSROIPooling": "test_new_ops.py",
    "_contrib_Proposal": "test_new_ops.py",
    "_contrib_MultiProposal": "test_new_ops.py",
    "_contrib_SparseEmbedding": "test_new_ops.py",
    "_contrib_bipartite_matching": "test_new_ops.py",
    "_contrib_getnnz": "test_new_ops.py",
    "_contrib_quantized_flatten": "test_new_ops.py",
    "_contrib_quantized_pooling": "test_new_ops.py",
    "_ravel_multi_index": "test_new_ops.py",
    "_unravel_index": "test_new_ops.py",
    "reshape_like": "test_new_ops.py",
    "_contrib_switch_moe": "test_contrib.py",
}


def _unique_ops():
    return sorted({op.name for op in REGISTRY.values()})


def _invoke(name, case):
    nds = [mx.nd.array(a) for a in case["inputs"]()]
    return mx.ops.invoke(name, *nds, **case["kwargs"]), nds


# ------------------------------------------------------------------- tests
def test_registry_fully_covered():
    missing = [n for n in _unique_ops()
               if n not in CASES and n not in COVERED_ELSEWHERE]
    assert not missing, (
        "ops registered without a sweep case (add to CASES or "
        "COVERED_ELSEWHERE): %s" % missing)
    here = os.path.dirname(__file__)
    for name, fname in COVERED_ELSEWHERE.items():
        with open(os.path.join(here, fname)) as f:
            text = f.read()
        candidates = ({name, name.lstrip("_"),
                       name.replace("_contrib_", "")}
                      | set(REGISTRY[name].aliases))
        assert any(c in text for c in candidates), (
            "%s claims coverage in %s but is not mentioned there"
            % (name, fname))


_FWD = sorted(n for n, c in CASES.items() if not c["run_only"])


@pytest.mark.parametrize("name", _FWD)
def test_forward_parity(name):
    case = CASES[name]
    out, _ = _invoke(name, case)
    expect = case["oracle"](*case["inputs"]())
    if isinstance(expect, tuple):
        for o, e in zip(out, expect):
            assert_almost_equal(o, e, rtol=case["rtol"], atol=case["atol"])
    else:
        if isinstance(out, list):
            out = out[0]
        assert_almost_equal(out, expect, rtol=case["rtol"], atol=case["atol"])


_RUN_ONLY = sorted(n for n, c in CASES.items()
                   if c["run_only"] and (c["inputs"]() or c["kwargs"]))


@pytest.mark.parametrize("name", _RUN_ONLY)
def test_forward_runs(name):
    """No oracle: the op must still run and produce finite values."""
    case = CASES[name]
    out, _ = _invoke(name, case)
    for o in (out if isinstance(out, (list, tuple)) else [out]):
        a = o.asnumpy()
        assert np.isfinite(a.astype(np.float64)).all() or a.dtype.kind in "iu"


_BF16 = sorted(n for n, c in CASES.items()
               if c["bf16"] and not c["run_only"])


@pytest.mark.parametrize("name", _BF16)
def test_bf16_forward(name):
    """bf16 in, output close to the f32 oracle at bf16 tolerance (~3 decimal
    digits). TPU native dtype — the entire bench path runs in bf16."""
    case = CASES[name]
    nds = [mx.nd.array(a) for a in case["inputs"]()]
    cast = [d.astype("bfloat16") if d.dtype == np.float32 else d
            for d in nds]
    out = mx.ops.invoke(name, *cast, **case["kwargs"])
    if isinstance(out, list):
        out = out[0]
    expect = case["oracle"](*case["inputs"]())
    if isinstance(expect, tuple):
        expect = expect[0]
    assert_almost_equal(out.astype("float32"), expect.astype(np.float32),
                        rtol=5e-2, atol=5e-2)


_GRAD = sorted(n for n, c in CASES.items() if c["grad"])


@pytest.mark.parametrize("name", _GRAD)
def test_numeric_gradient(name):
    case = CASES[name]
    kwargs = case["kwargs"]
    inputs = case["inputs"]()

    def fn(*nds):
        out = mx.ops.invoke(name, *nds, **kwargs)
        return out[0] if isinstance(out, list) else out

    check_numeric_gradient(fn, inputs, rtol=case["grad_rtol"],
                           atol=case["grad_atol"])


# --------------------------------------------------- optimizer update ops
def test_optimizer_updates():
    """adam/rmsprop/ftrl/adagrad/nag/signum update kernels vs NumPy oracles
    (ref: src/operator/optimizer_op.cc)."""
    r = RNG(0)
    w = r.uniform(-1, 1, (3, 4)).astype(np.float32)
    g = r.uniform(-1, 1, (3, 4)).astype(np.float32)

    # adam
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    out = mx.nd.adam_update(mx.nd.array(w), mx.nd.array(g),
                            mx.nd.array(m), mx.nd.array(v), lr=0.1)
    m2 = 0.1 * g
    v2 = 0.001 * g * g
    expect = w - 0.1 * m2 / (np.sqrt(v2) + 1e-8)
    assert_almost_equal(out, expect, rtol=1e-4, atol=1e-5)

    # signum
    mom = np.zeros_like(w)
    out = mx.nd.signum_update(mx.nd.array(w), mx.nd.array(g),
                              mx.nd.array(mom), lr=0.1, momentum=0.9)
    expect = w - 0.1 * np.sign(0.1 * g)
    assert_almost_equal(out, expect, rtol=1e-4, atol=1e-5)

    # nag
    mom = r.uniform(-1, 1, (3, 4)).astype(np.float32)
    out = mx.nd.nag_mom_update(mx.nd.array(w), mx.nd.array(g),
                               mx.nd.array(mom), lr=0.1, momentum=0.9)
    new_mom = 0.9 * mom + g
    expect = w - 0.1 * (g + 0.9 * new_mom)
    assert_almost_equal(out, expect, rtol=1e-4, atol=1e-5)

    # adagrad
    hist = np.zeros_like(w)
    out = mx.nd.adagrad_update(mx.nd.array(w), mx.nd.array(g),
                               mx.nd.array(hist), lr=0.1, epsilon=1e-7)
    hist2 = g * g
    expect = w - 0.1 * g / (np.sqrt(hist2) + 1e-7)
    assert_almost_equal(out, expect, rtol=1e-4, atol=1e-5)

    # rmsprop
    n = np.zeros_like(w)
    out = mx.nd.rmsprop_update(mx.nd.array(w), mx.nd.array(g),
                               mx.nd.array(n), lr=0.1, gamma1=0.95)
    n2 = 0.05 * g * g
    expect = w - 0.1 * g / np.sqrt(n2 + 1e-8)
    assert_almost_equal(out, expect, rtol=1e-4, atol=1e-4)

    # rmspropalex + ftrl: run and check finiteness + movement
    n = np.zeros_like(w)
    gbuf = np.zeros_like(w)
    delta = np.zeros_like(w)
    out = mx.nd.rmspropalex_update(mx.nd.array(w), mx.nd.array(g),
                                   mx.nd.array(n), mx.nd.array(gbuf),
                                   mx.nd.array(delta), lr=0.1)
    a = out.asnumpy()
    assert np.isfinite(a).all() and not np.allclose(a, w)

    z = np.zeros_like(w)
    nacc = np.zeros_like(w)
    out = mx.nd.ftrl_update(mx.nd.array(w), mx.nd.array(g),
                            mx.nd.array(z), mx.nd.array(nacc), lr=0.1)
    a = out.asnumpy()
    assert np.isfinite(a).all()


# ------------------------------------------------------------ random ops
def test_random_ops_statistics():
    n = 4000
    x = mx.nd.normal(loc=1.0, scale=2.0, shape=(n,)).asnumpy()
    assert abs(x.mean() - 1.0) < 0.15 and abs(x.std() - 2.0) < 0.15
    x = mx.nd.uniform(low=-1, high=3, shape=(n,)).asnumpy()
    assert x.min() >= -1 and x.max() <= 3 and abs(x.mean() - 1.0) < 0.15
    x = mx.nd.exponential(lam=2.0, shape=(n,)).asnumpy()
    assert abs(x.mean() - 0.5) < 0.1
    x = mx.nd.poisson(lam=3.0, shape=(n,)).asnumpy()
    assert abs(x.mean() - 3.0) < 0.2
    # mx.nd.gamma is the ELEMENTWISE gamma function (as in the reference);
    # the sampler lives at mx.nd.random.gamma / random_gamma
    x = mx.nd.random.gamma(alpha=2.0, beta=1.5, shape=(n,)).asnumpy()
    assert abs(x.mean() - 3.0) < 0.3  # mean = alpha*beta
    x2 = mx.nd.random_gamma(alpha=2.0, beta=1.5, shape=(n,)).asnumpy()
    assert abs(x2.mean() - 3.0) < 0.3
    x = mx.nd.negative_binomial(k=3, p=0.5, shape=(n,)).asnumpy()
    assert abs(x.mean() - 3.0) < 0.4  # mean = k(1-p)/p
    x = mx.nd.generalized_negative_binomial(mu=2.0, alpha=0.3,
                                            shape=(n,)).asnumpy()
    assert abs(x.mean() - 2.0) < 0.4
    x = mx.nd.randint(low=0, high=10, shape=(n,)).asnumpy()
    assert x.min() >= 0 and x.max() <= 9
    base = np.arange(20, dtype=np.float32)
    x = mx.nd.shuffle(mx.nd.array(base)).asnumpy()
    assert sorted(x.tolist()) == base.tolist()
    like = mx.nd.normal_like(mx.nd.zeros((7, 2)))
    assert like.shape == (7, 2)
    like = mx.nd.uniform_like(mx.nd.zeros((7, 2)))
    assert like.shape == (7, 2)
    probs = mx.nd.array(np.array([[0.0, 1.0, 0.0]], np.float32))
    draws = mx.nd.multinomial(probs, shape=(8,)).asnumpy()
    assert (draws == 1).all()


def test_deferred_exception_surfaces_at_sync():
    """Async-dispatch semantics: an invalid op surfaces its error at the
    sync point (ref: docs/architecture/exception_handling.md,
    threaded_engine.cc:472)."""
    a = mx.nd.array(np.ones((2, 2), np.float32))
    with pytest.raises(Exception):
        b = mx.nd.dot(a, mx.nd.array(np.ones((3, 3), np.float32)))
        b.asnumpy()


def test_regression_output_grad_shapes():
    """Regression-output backward must match the data shape exactly — a
    (N,) label vs (N,1) pred once silently broadcast the grad to (N,N)
    (caught by the SVRG convergence test; ref regression_output-inl.h
    reshapes the label)."""
    from mxtpu import autograd as ag
    for name in ("LinearRegressionOutput", "LogisticRegressionOutput",
                 "MAERegressionOutput"):
        d = mx.nd.array(np.array([[1.0], [2.0]], np.float32))
        lab = mx.nd.array(np.array([0.5, 0.25], np.float32))
        d.attach_grad()
        with ag.record():
            out = mx.ops.invoke(name, d, lab)
        out.backward()
        assert d.grad.shape == d.shape, (name, d.grad.shape)


def test_op_describe_reflection():
    """Op parameter reflection (the dmlc::Parameter analog, SURVEY §5):
    declared arguments/attributes with defaults are introspectable for
    every registered op."""
    from mxtpu.ops.registry import describe

    d = describe("Convolution")
    assert d["name"] == "Convolution"
    arg_names = [a["name"] for a in d["arguments"]]
    assert "data" in arg_names and "weight" in arg_names
    attrs = {a["name"]: a.get("default") for a in d["attributes"]}
    assert attrs["num_group"] == 1 and attrs["no_bias"] is False
    assert "convolution" in d["aliases"]
    # every unique op must be describable
    for name in _unique_ops():
        info = describe(name)
        assert info["name"] == name


# ------------------------------------------------- decomposition properties
def test_linalg_gelqf_properties():
    """LQ factors are sign-ambiguous, so check the DEFINING properties
    instead of a fixed oracle: A = L Q, Q Q^T = I, L lower-triangular
    (ref: la_op.cc gelqf semantics)."""
    a = RNG(0).uniform(-1, 1, (2, 4)).astype(np.float32)
    out = mx.ops.invoke("linalg_gelqf", mx.nd.array(a))
    L, Q = out[0].asnumpy(), out[1].asnumpy()
    assert L.shape == (2, 2) and Q.shape == (2, 4)
    assert_almost_equal(L @ Q, a, rtol=1e-4, atol=1e-5)
    assert_almost_equal(Q @ Q.T, np.eye(2, dtype=np.float32),
                        rtol=1e-4, atol=1e-5)
    assert np.allclose(np.triu(L, 1), 0, atol=1e-6), "L not lower-triangular"


def test_linalg_syevd_properties():
    """U rows are eigenvectors up to sign/order: check A = U^T diag(L) U,
    orthonormality, and eigenvalues against numpy (ref: la_op.cc syevd)."""
    a = RNG(0).uniform(-1, 1, (3, 3)).astype(np.float32)
    a = a + a.T
    out = mx.ops.invoke("linalg_syevd", mx.nd.array(a))
    U, lam = out[0].asnumpy(), out[1].asnumpy()
    assert_almost_equal(U.T @ np.diag(lam) @ U, a, rtol=1e-3, atol=1e-4)
    assert_almost_equal(U @ U.T, np.eye(3, dtype=np.float32),
                        rtol=1e-4, atol=1e-5)
    assert_almost_equal(np.sort(lam), np.linalg.eigvalsh(a),
                        rtol=1e-4, atol=1e-5)


# ------------------------------------------------------ random flip property
@pytest.mark.parametrize("op,axis", [("_image_random_flip_left_right", 1),
                                     ("_image_random_flip_top_bottom", 0)])
def test_random_flips_are_flips(op, axis):
    """Every draw must be exactly the input or its flip, and both outcomes
    must occur across draws (p=0.5, 40 draws: P[one-sided] = 2^-40)."""
    x = RNG(0).uniform(0, 255, (4, 5, 3)).astype(np.float32)
    flipped = np.flip(x, axis=axis)
    seen = set()
    for _ in range(40):
        out = mx.ops.invoke(op, mx.nd.array(x)).asnumpy()
        if np.array_equal(out, x):
            seen.add("id")
        elif np.array_equal(out, flipped):
            seen.add("flip")
        else:
            raise AssertionError("output is neither input nor its flip")
    assert seen == {"id", "flip"}, seen
