"""Executable observatory (mxtpu/xprof.py + mxtpu/perf_model.py) —
ISSUE 12:

* per-jit-site ledger: every compile recorded with cost-model
  FLOPs/bytes, HBM footprint, donated-bytes savings, and compile
  wall-time; the runtime ledger covers EVERY jit cache graftlint's
  static ``--inventory`` lists (the runtime/static cross-check);
* wrapped jits stay cache-stable: steady-state calls add zero compiles
  (fused-retrace-flat with ``MXTPU_XPROF=1``) and the per-call counting
  feeds ``executed_flops``;
* live HBM accounting: ``device_memory`` is the ONE normalizer
  (``util.get_gpu_memory`` / C-ABI parity), ``poll_memory`` gauges,
  the ``MXTPU_MEMWATCH_S`` monitor thread, and the warmup will-it-fit
  pre-flight (``memory.overcommit``);
* the OOM flight path: fault kind ``oom`` through Trainer.step, the
  Predictor dispatch, and the decode loop produces a
  ``flight_record("oom")`` artifact carrying the ledger + per-device
  memory stats (+ the KVCacheAccountant view in decode), and every
  loop fails LOUD, never hangs;
* runtime MFU: the ``perf.mfu`` gauge from ledger FLOPs x step rate
  over the shared datasheet-peak table;
* perf_model accessors: list-of-dicts vs dict vs None cost_analysis
  normalization, the roofline verdict, and the
  ``telemetry_report --ledger`` table.
"""
import json
import os
import sys
import time
from pathlib import Path

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import perf_model, resilience, telemetry, xprof
from mxtpu.base import MXNetError
from mxtpu.gluon import nn
from mxtpu.gluon.parameter import Parameter
from mxtpu.gluon.trainer import Trainer

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # pytest rootdir variants
    sys.path.insert(0, str(REPO))
if str(REPO / "tools") not in sys.path:  # serve_bench's DecodeModel
    sys.path.insert(0, str(REPO / "tools"))


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_TELEMETRY", "MXTPU_TRACE", "MXTPU_XPROF",
                "MXTPU_FAULT_INJECT", "MXTPU_FLIGHT_DIR",
                "MXTPU_MEMWATCH_S", "MXTPU_PEAK_TFLOPS",
                "MXTPU_PEAK_GBPS", "MXTPU_RETRACE_BUDGET"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    yield
    telemetry.reset()
    resilience.reset_faults()


def _make_trainer(n_params=2, shape=(6,), optimizer="sgd"):
    rng = np.random.RandomState(0)
    params = []
    for j in range(n_params):
        p = Parameter("xp%d" % j, shape=shape, dtype="float32")
        p.initialize()
        p.data()._set_data(mx.nd.array(
            rng.uniform(-1, 1, shape).astype(np.float32))._data)
        params.append(p)
    tr = Trainer(params, optimizer, {"learning_rate": 0.05},
                 kvstore=None)
    return tr, params, rng


def _set_grads(params, rng):
    for p in params:
        p.grad()[:] = mx.nd.array(rng.randn(*p.shape).astype(np.float32))


def _sites_of(entries):
    return {e["site"] for e in entries}


# ------------------------------------------------------------------ ledger
def test_record_retrace_compiled_returns_wrapped_and_ledgers():
    import jax
    import jax.numpy as jnp

    fn = telemetry.record_retrace(
        "demo.site", {"k": 1}, compiled=jax.jit(lambda a: (a @ a).sum()))
    a = jnp.ones((16, 16), jnp.float32)
    for _ in range(3):
        fn(a)
    led = xprof.ledger("demo.site")
    assert len(led) == 1
    e = led[0]
    assert e["calls"] == 3
    assert e["compile_s"] is not None and e["compile_s"] > 0
    assert e["error"] is None
    assert e["flops"] and e["flops"] > 0
    assert e["bytes_accessed"] and e["bytes_accessed"] > 0
    # memory_analysis footprint keys present on the CPU backend too
    assert e["argument_bytes"] > 0 and e["output_bytes"] >= 0
    assert "temp_bytes" in e and "donated_bytes" in e
    # executed FLOPs = flops x calls (the MFU numerator)
    assert xprof.executed_flops(("demo.site",)) == \
        pytest.approx(e["flops"] * 3)
    # compile wall-time reached the registry histogram
    assert telemetry.snapshot()["histograms"]["compile.wall_s"]["count"] == 1
    # the resolve-free view is exported in snapshot() (-> /metrics)
    assert _sites_of(telemetry.snapshot()["ledger"]) == {"demo.site"}


def test_xprof_off_returns_unwrapped(monkeypatch):
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("MXTPU_XPROF", "0")
    jfn = jax.jit(lambda a: a + 1)
    out = telemetry.record_retrace("demo.site", None, compiled=jfn)
    assert out is jfn  # zero added dispatch layers
    out(jnp.ones((2,)))
    assert xprof.ledger() == []
    assert "ledger" not in telemetry.snapshot()
    # the retrace count itself is unchanged by the lever
    assert telemetry.value("retrace.demo.site") == 1


def test_wrapped_jit_forwards_attributes():
    import jax
    import jax.numpy as jnp

    fn = telemetry.record_retrace(
        "demo.site", None, compiled=jax.jit(lambda a: a * 2))
    a = jnp.ones((4,), jnp.float32)
    fn(a)
    # .lower() keeps working through the wrapper (compiled_step_flops path)
    c = fn.lower(jax.ShapeDtypeStruct((4,), jnp.float32)).compile()
    assert perf_model.flops_of(c) is not None or True  # no raise is the pin


def test_ledger_bounded_per_site():
    import jax
    import jax.numpy as jnp

    a = jnp.ones((2,))
    for i in range(20):
        fn = telemetry.record_retrace(
            "demo.bounded", {"i": i}, compiled=jax.jit(lambda x: x + i))
        fn(a)
    led = xprof.ledger("demo.bounded", resolve=False)
    assert len(led) == 16  # newest kept, oldest evicted
    assert led[-1]["provenance"] == {"i": 19}


def test_fused_retrace_flat_and_mfu_with_xprof_on(monkeypatch):
    """Steady-state Trainer.steps through the WRAPPED fused jit add zero
    compiles (the fused-retrace-flat pin with MXTPU_XPROF=1), and the
    MFU meter turns ledger FLOPs x step rate into the perf.mfu gauge
    under an MXTPU_PEAK_TFLOPS override (CPU tier has no datasheet
    peak)."""
    monkeypatch.setenv("MXTPU_XPROF", "1")
    monkeypatch.setenv("MXTPU_PEAK_TFLOPS", "0.001")
    tr, params, rng = _make_trainer()
    tr._mfu = xprof.MFUMeter(every=2)  # test-tempo window
    for _ in range(6):
        _set_grads(params, rng)
        tr.step(1)
    assert telemetry.value("retrace.fused_optimizer") == 1  # flat
    led = xprof.ledger("fused_optimizer")
    assert len(led) == 1 and led[0]["calls"] == 6
    mfu = telemetry.snapshot()["gauges"].get("perf.mfu")
    assert mfu is not None and mfu > 0
    assert tr._mfu.last == pytest.approx(mfu)


# ---------------------------------------------- runtime/static cross-check
def test_ledger_covers_graftlint_inventory():
    """THE acceptance cross-check: after exercising every jit-cache
    owner, xprof.ledger() has an entry for every cache in graftlint's
    static ``--inventory`` — the runtime inventory matches the static
    scouting report site for site (per-instance families like
    ``serving.predict.r<i>`` match by dotted prefix)."""
    from tools.graftlint import LintConfig, run

    import jax.numpy as jnp

    static_sites = {e["retrace_site"]
                    for e in run(LintConfig(root=REPO),
                                 ["mxtpu"]).jit_inventory}
    assert None not in static_sites and "<dynamic>" not in static_sites

    # the ledger records COMPILES: the two process-global caches must be
    # cold or an earlier test's warm executable would skip record_retrace
    from mxtpu import optimizer_fused
    from mxtpu.ops import subgraph_ops
    optimizer_fused._JIT_CACHE.clear()
    subgraph_ops._SUBGRAPH_CACHE.clear()

    rng = np.random.RandomState(0)

    # fused_optimizer: one guarded-free Trainer step
    tr, params, trng = _make_trainer()
    _set_grads(params, trng)
    tr.step(1)

    # cached_op: hybridized gluon forward (first call settles deferred
    # shapes eagerly; the second compiles)
    net = nn.HybridSequential()
    net.add(nn.Dense(4))
    net.initialize()
    net.hybridize()
    x = mx.nd.array(rng.randn(2, 3).astype(np.float32))
    net(x)
    net(x)

    # executor + executor.backward: a plain symbol bound and run fwd/bwd
    import mxtpu.symbol as sym_mod
    from mxtpu.symbol import partition

    data = sym_mod.Variable("data")
    out = sym_mod.FullyConnected(data, num_hidden=4, name="xfc")
    exe = out.simple_bind(grad_req="write", data=(2, 3))
    for arr in exe.arg_dict.values():
        arr._set_data(mx.nd.array(
            rng.normal(size=arr.shape).astype(np.float32))._data)
    exe.forward(is_train=True, data=mx.nd.ones((2, 3)))
    exe.backward(out_grads=mx.nd.ones((2, 4)))

    # subgraph_exec: the partitioned twin, inference mode (the region
    # executes as its own compiled executable there)
    part = partition(out, "default")
    args = {n: mx.nd.array(rng.normal(size=tuple(s)).astype(np.float32))
            for n, s in zip(out.list_arguments(),
                            out.infer_shape(data=(2, 3))[0])}
    part.bind(args=args, grad_req="null").forward(is_train=False)

    # parallel.train_step: the mesh step
    from mxtpu import gluon
    from mxtpu.parallel import ShardedTrainStep, data_parallel_mesh

    pnet = nn.Dense(2)
    pnet.initialize()
    pnet(mx.nd.ones((8, 3)))  # settle deferred shapes before the step
    step = ShardedTrainStep(pnet, gluon.loss.L2Loss(),
                            data_parallel_mesh(), optimizer="sgd",
                            optimizer_params={"learning_rate": 0.01})
    step(mx.nd.ones((8, 3)), mx.nd.ones((8, 2)))

    # rtc: a runtime-compiled Pallas kernel launch
    from mxtpu.rtc import PallasModule
    mod = PallasModule(
        "def scale(x_ref, out_ref):\n"
        "    out_ref[...] = 2.0 * x_ref[...]\n")
    mod.get_kernel("scale").launch([mx.nd.ones((2, 4))],
                                   out_shapes=(2, 4))

    # serving.predict: a warmed single-bucket Predictor
    from mxtpu.serving import BucketSpec, DecodeEngine, Predictor
    snet = nn.Dense(3)
    snet.initialize()
    Predictor(snet, BucketSpec([2]),
              example=np.zeros((1, 5), np.float32), warmup=True)

    # serving.decode + serving.draft: a warmed tiny SPECULATIVE paged
    # engine compiles the whole six-caches inventory's serving tail —
    # the draft site only exists when a draft model is attached
    import serve_bench as sb
    model = sb.build_decode_model(vocab=16, dim=8, max_len=16, seed=3)
    DecodeEngine(model, BucketSpec([1], seq_lens=[4]),
                 BucketSpec(decode_slots=[2]), max_len=8,
                 page_tokens=4, draft_model=model, spec_k=2,
                 warmup=True, start=False)

    # autotune.search: one ephemeral candidate probe (ISSUE 17) — the
    # measured search's throwaway jits report to the same ledger site
    # (a single-candidate class keeps it to exactly one compile)
    from mxtpu.ops.pallas import autotune as ptune
    from mxtpu.ops.pallas import conv as pconv
    acfg = pconv._Cfg((1, 1), ((1, 1), (1, 1)), False, False, False, False)
    asc = pconv.shape_class_of(jnp.zeros((1, 8, 8, 4), jnp.float32),
                               jnp.zeros((3, 3, 4, 8), jnp.float32), acfg)
    ptune.search("pallas_conv", asc, rounds=1, install=False,
                 persist=False)

    runtime_sites = _sites_of(xprof.ledger(resolve=False))
    missing = {s for s in static_sites
               if not any(r == s or r.startswith(s + ".")
                          for r in runtime_sites)}
    assert not missing, \
        "jit caches with no runtime ledger entry: %s (runtime saw %s)" \
        % (sorted(missing), sorted(runtime_sites))
    # and the executor entries resolve to real cost/memory analyses
    exe_entries = xprof.ledger("executor")
    assert exe_entries and all(e["error"] is None and e["flops"]
                               for e in exe_entries)


# --------------------------------------------------------- HBM accounting
class _FakeDev:
    platform = "tpu"
    device_kind = "TPU v5 lite"

    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_device_memory_normalizes_and_unifies():
    d = _FakeDev({"bytes_in_use": 30, "bytes_limit": 100,
                  "peak_bytes_in_use": 60})
    m = xprof.device_memory(d)
    assert m == {"bytes_in_use": 30, "bytes_limit": 100,
                 "peak_bytes_in_use": 60, "bytes_free": 70}
    # key fallbacks: a backend with only the reservable spelling
    m2 = xprof.device_memory(_FakeDev({"bytes_reservable_limit": 50,
                                       "bytes_in_use": 10}))
    assert m2["bytes_limit"] == 50 and m2["bytes_free"] == 40
    assert m2["peak_bytes_in_use"] == 10  # falls back to in-use
    # stats-less backend (CPU): all zeros, never a guess
    assert xprof.device_memory(_FakeDev(None))["bytes_limit"] == 0


def test_util_and_c_api_agree_with_device_memory(monkeypatch):
    import jax

    from mxtpu import c_api_impl, util

    d = _FakeDev({"bytes_in_use": 25, "bytes_limit": 100})
    monkeypatch.setattr(jax, "devices", lambda *a: [d])
    assert util.get_gpu_memory(0) == (75, 100)
    assert c_api_impl.get_memory_information(0) == (75, 100)
    # CPU tier: util degrades to (0, 0), the C ABI refuses loudly
    monkeypatch.setattr(jax, "devices", lambda *a: [_FakeDev(None)])
    assert util.get_gpu_memory(0) == (0, 0)
    with pytest.raises(MXNetError, match="no memory stats"):
        c_api_impl.get_memory_information(0)


def test_poll_memory_gauges_and_prometheus():
    xprof.poll_memory({"d0": {"bytes_in_use": 30, "bytes_limit": 100,
                              "peak_bytes_in_use": 60},
                       "d1": {"bytes_in_use": 10, "bytes_limit": 100,
                              "peak_bytes_in_use": 20}})
    g = telemetry.snapshot()["gauges"]
    assert g["memory.hbm_used_bytes"] == {"d0": 30.0, "d1": 10.0}
    assert g["memory.hbm_headroom_bytes"]["d0"] == 70.0
    assert g["memory.hbm_limit_bytes"]["d1"] == 100.0
    assert g["memory.hbm_peak_bytes"]["d0"] == 60.0
    text = telemetry.prometheus()
    assert 'mxtpu_memory_hbm_used_bytes{tag="d0"} 30' in text


def test_memwatch_thread_lifecycle(monkeypatch):
    monkeypatch.setenv("MXTPU_MEMWATCH_S", "0.01")
    polled = []
    monkeypatch.setattr(xprof, "poll_memory",
                        lambda stats=None: polled.append(1))
    assert xprof.ensure_memwatch() is True
    assert xprof.ensure_memwatch() is True  # idempotent
    deadline = time.time() + 2.0
    while not polled and time.time() < deadline:
        time.sleep(0.01)
    xprof.stop_memwatch()
    assert polled, "monitor thread never polled"
    # off by default: no interval, no thread
    monkeypatch.setenv("MXTPU_MEMWATCH_S", "0")
    assert xprof.ensure_memwatch() is False


def test_preflight_overcommit_warning():
    import jax
    import jax.numpy as jnp

    fn = telemetry.record_retrace(
        "demo.preflight", None,
        compiled=jax.jit(lambda a: (a @ a).sum()))
    fn(jnp.ones((32, 32), jnp.float32))
    # no limit known and none supplied -> skipped entirely (CPU tier)
    assert xprof.preflight("demo.preflight") is None
    # a generous budget: no overcommit
    need, limit = xprof.preflight("demo.preflight", limit=1 << 40)
    assert need > 0 and limit == 1 << 40
    assert telemetry.value("memory.overcommit") == 0
    # a tiny budget: overcommit counted + preflight gauge set
    xprof.preflight("demo.preflight", limit=16)
    assert telemetry.tagged("memory.overcommit") == {"demo.preflight": 1}
    g = telemetry.snapshot()["gauges"]["memory.preflight_bytes"]
    assert g["demo.preflight"] == need


# ------------------------------------------------------------- OOM flight
def _flight_files(d):
    return sorted(Path(d).glob("flight_oom_*.json"))


def test_trainer_oom_flight_artifact(monkeypatch, tmp_path):
    """Fault kind ``oom`` in Trainer.step: the step raises LOUD
    (ResourceExhausted reaches the caller) and the flight artifact
    carries the ledger snapshot + per-device memory stats."""
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "oom@0")
    tr, params, rng = _make_trainer()
    _set_grads(params, rng)
    with pytest.raises(resilience.ResourceExhausted,
                       match="RESOURCE_EXHAUSTED"):
        tr.step(1)
    files = _flight_files(tmp_path)
    assert len(files) == 1
    art = json.loads(files[0].read_text())
    assert art["reason"] == "oom"
    assert art["extra"]["where"] == "trainer.step"
    assert "RESOURCE_EXHAUSTED" in art["extra"]["error"]
    assert "ledger" in art["extra"] and "memory" in art["extra"]
    assert telemetry.tagged("memory.oom") == {"trainer.step": 1}
    # inject() itself dumps a "fault" artifact; the OOM path adds ITS own
    assert telemetry.tagged("flight.dumps")["oom"] == 1
    # the NEXT step (fault consumed) trains normally — fail loud, not dead
    _set_grads(params, rng)
    tr.step(1)


def test_predictor_oom_fails_cohort_loud(monkeypatch, tmp_path):
    """Fault kind ``oom`` on the Predictor dispatch: the batcher's
    error path completes the request future with the error (no hang)
    and the artifact is written."""
    from mxtpu.serving import BucketSpec, MicroBatcher, Predictor

    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    net = nn.Dense(3)
    net.initialize()
    pred = Predictor(net, BucketSpec([2]),
                     example=np.zeros((1, 5), np.float32), warmup=True)
    mb = MicroBatcher(pred, max_batch_size=1, start=False)
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "oom@0")
    fut = mb.submit(np.zeros((1, 5), np.float32))
    mb.poll()
    with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
        fut.result(timeout=2.0)
    art = json.loads(_flight_files(tmp_path)[0].read_text())
    assert art["extra"]["where"] == "serving.predict"
    # the predict-site ledger entries ride the artifact's registry view
    assert any(e["site"] == "serving.predict"
               for e in art["extra"]["ledger"])


def test_decode_oom_flight_with_accountant_view(monkeypatch, tmp_path):
    """Fault kind ``oom`` in the decode loop (poll drive): the artifact
    carries the KVCacheAccountant residency view and the engine's
    failure is LOUD."""
    import serve_bench as sb

    from mxtpu.serving import BucketSpec, DecodeEngine, KVCacheAccountant

    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    model = sb.build_decode_model(vocab=16, dim=8, max_len=16, seed=3)
    acct = KVCacheAccountant()
    eng = DecodeEngine(model, BucketSpec([1], seq_lens=[4]),
                       BucketSpec(decode_slots=[2]), max_len=8,
                       accountant=acct, warmup=True, start=False)
    fut = eng.submit([1, 2, 3], max_new=4)
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "oom@0")
    with pytest.raises(resilience.ResourceExhausted):
        eng.poll()
    art = json.loads(_flight_files(tmp_path)[0].read_text())
    assert art["extra"]["where"] == "serving.decode"
    assert art["extra"]["kv"]  # the accountant snapshot rode along
    assert any(e["site"] == "serving.decode"
               for e in art["extra"]["ledger"])
    assert not fut.done()  # poll drive: the raise went to the caller
    eng.close()


def test_decode_oom_threaded_crash_barrier(monkeypatch, tmp_path):
    """Threaded decode loop + injected OOM: the crash barrier fails the
    pending future LOUD (never hangs) after the artifact is dumped."""
    import serve_bench as sb

    from mxtpu.serving import BucketSpec, DecodeEngine

    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    model = sb.build_decode_model(vocab=16, dim=8, max_len=16, seed=3)
    eng = DecodeEngine(model, BucketSpec([1], seq_lens=[4]),
                       BucketSpec(decode_slots=[2]), max_len=8,
                       warmup=True, start=False)
    fut = eng.submit([1, 2, 3], max_new=4)
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "oom@0")
    eng.start()
    # the injected RESOURCE_EXHAUSTED surfaces on the loop thread's
    # prefill dispatch; the future completes LOUD with it either way
    with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
        fut.result(timeout=10.0)
    assert _flight_files(tmp_path)
    # ...and the re-raise reaches the crash barrier (poll: the future is
    # failed loud BEFORE the barrier runs, so wait for the counter)
    deadline = time.time() + 5.0
    while telemetry.value("serving.worker_crashes") < 1 \
            and time.time() < deadline:
        time.sleep(0.01)
    assert telemetry.value("serving.worker_crashes") == 1
    assert telemetry.tagged("memory.oom")  # at least one OOM site tagged
    eng.close()


# -------------------------------------------------------------- perf_model
def test_cost_dict_normalizes_every_shape():
    assert perf_model.cost_dict(None) == {}
    assert perf_model.cost_dict([]) == {}
    assert perf_model.cost_dict([None]) == {}
    assert perf_model.cost_dict({"flops": 5.0}) == {"flops": 5.0}
    assert perf_model.cost_dict([{"flops": 5.0}]) == {"flops": 5.0}

    class _C:
        def cost_analysis(self):
            return [{"flops": -1.0}]  # XLA's "unknown" spelling

    assert perf_model.flops_of(_C()) is None


def test_peak_tables_and_roofline():
    assert perf_model.nominal_tflops("TPU v5 lite") == 197.0
    assert perf_model.nominal_tflops("TPU v4") == 275.0
    os.environ["MXTPU_PEAK_TFLOPS"] = "2"
    os.environ["MXTPU_PEAK_GBPS"] = "1"
    try:
        assert perf_model.peak_flops() == 2e12
        ridge = perf_model.critical_intensity()
        assert ridge == pytest.approx(2000.0)  # 2 TFLOP/s over 1 GB/s
        assert perf_model.roofline_verdict(1e7, 1.0, ridge) == "compute"
        assert perf_model.roofline_verdict(100.0, 1.0, 0.01) == "compute"
        assert perf_model.roofline_verdict(100.0, 1.0, ridge) == "memory"
        assert perf_model.roofline_verdict(None, 1.0, ridge) is None
    finally:
        os.environ.pop("MXTPU_PEAK_TFLOPS")
        os.environ.pop("MXTPU_PEAK_GBPS")
    # off-TPU with no override: no peak, no MFU
    assert perf_model.peak_flops() is None
    assert perf_model.mfu(1e12) is None


def test_bench_peak_delegates_to_perf_model(monkeypatch):
    import bench
    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "3")
    assert bench._peak_flops() == 3e12
    monkeypatch.delenv("BENCH_PEAK_TFLOPS")
    assert bench._peak_flops() is None  # CPU tier -> table says no peak


# --------------------------------------------------- report + sink plumbing
def test_ledger_jsonl_roundtrip_and_report(monkeypatch, tmp_path):
    """Resolved ledger entries reach the JSONL sink at flush and
    ``telemetry_report --ledger`` folds them into the roofline table
    (last line per (site, seq) wins), including the ranked memory-bound
    Pallas-candidate shortlist."""
    import subprocess

    import jax
    import jax.numpy as jnp

    sink = tmp_path / "t.jsonl"
    monkeypatch.setenv("MXTPU_TELEMETRY", str(sink))
    monkeypatch.setenv("MXTPU_PEAK_TFLOPS", "1")
    monkeypatch.setenv("MXTPU_PEAK_GBPS", "1000")  # ridge = 1.0 FLOP/B
    fn = telemetry.record_retrace(
        "demo.sink", None, compiled=jax.jit(lambda a: a + 1.0))
    fn(jnp.ones((64,), jnp.float32))  # intensity << 1 -> memory-bound
    xprof.resolve()
    telemetry.flush()
    out = subprocess.run(
        [sys.executable, "tools/telemetry_report.py", str(sink),
         "--ledger", "--json"],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    rows = json.loads(out.stdout)["_ledger"]["rows"]
    row = [r for r in rows if r["site"] == "demo.sink"][0]
    assert row["verdict"] == "memory"
    assert "demo.sink#%s" % row["seq"] in \
        json.loads(out.stdout)["_ledger"]["candidates"]
    # the human table renders without error too
    from tools.telemetry_report import (format_ledger_table, ledger_summary,
                                        load)
    rows2, cands = ledger_summary(load(str(sink)))
    table = format_ledger_table(rows2, cands)
    assert "demo.sink" in table and "Pallas candidates" in table


def test_bench_stamp_carries_ledger_summary():
    import bench

    import jax
    import jax.numpy as jnp

    fn = telemetry.record_retrace(
        "demo.stamp", None, compiled=jax.jit(lambda a: a * 3))
    fn(jnp.ones((4,)))
    rec = bench._stamp({"metric": "x"})
    assert rec["ledger"]["compiles"] >= 1
    assert rec["ledger"]["compile_s_total"] > 0
    assert "peak_hbm_bytes" in rec["ledger"]
    json.dumps(rec)  # the stamp stays JSON-serializable
