"""Unified compile service (mxtpu/compile_service.py, ISSUE 15): canonical
keying, LRU bounding, concurrent AOT warmup with shared lowerings, and the
persistent on-disk executable cache's full failure matrix — every
degradation lands on a silent recompile with a counted reason, never a
crash, never a stale-policy executable."""
import json
import os
import pickle
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxtpu as mx
from mxtpu import compile_service as csvc
from mxtpu import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_service():
    csvc.reset()
    yield
    csvc.reset()


def _counter(name, tag=None):
    return telemetry.value(name, tag=tag)


def _key(site="executor", sig=((4,), "f32"), policy=("p0",), nonce=None,
         fn_id="svc-test", sharding=None, donation=None):
    return csvc.canonical_key(site=site, fn_id=fn_id, signature=sig,
                              policy=policy, sharding=sharding,
                              donation=donation,
                              device=csvc.device_token(), nonce=nonce)


def _build_mul(c=3.0, calls=None):
    def build():
        if calls is not None:
            calls.append(1)

        def f(x):
            return x * c

        return jax.jit(f)

    return build


# ---------------------------------------------------------------- basics
def test_miss_builds_and_reports_then_hits():
    k = _key()
    r0 = _counter("retrace.executor")
    calls = []
    e1 = csvc.get_or_build(k, _build_mul(calls=calls),
                           provenance={"t": 1})
    assert e1.origin == "built" and calls == [1]
    assert _counter("retrace.executor") == r0 + 1
    out = e1.fn(jnp.ones((4,)))
    assert float(out[0]) == 3.0
    e2 = csvc.get_or_build(k, _build_mul(calls=calls))
    assert e2.fn is e1.fn and calls == [1]          # pure hit: no rebuild
    assert _counter("retrace.executor") == r0 + 1   # and no re-report


def test_distinct_key_components_are_distinct_entries():
    base = dict(site="executor", sig=((4,), "f32"))
    ks = [_key(**base),
          _key(**dict(base, policy=("p1",))),
          _key(**dict(base, sharding=("mesh", 8))),
          _key(**dict(base, donation=(0,))),
          _key(**dict(base, nonce="iface2"))]
    for k in ks:
        csvc.get_or_build(k, _build_mul())
    assert csvc.stats()["entries"] == len(ks)


def test_meta_rides_the_entry():
    def build():
        cell = {"in_fmt": [1, 0]}

        def f(x):
            return x + 1

        return jax.jit(f), cell

    e = csvc.get_or_build(_key(), build)
    assert e.meta == {"in_fmt": [1, 0]}


def test_concurrent_misses_build_once():
    k = _key()
    calls, results = [], []
    gate = threading.Barrier(4)

    def slow_build():
        calls.append(1)

        def f(x):
            return x * 2

        return jax.jit(f)

    def worker():
        gate.wait()
        results.append(csvc.get_or_build(k, slow_build))

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(calls) == 1
    assert all(r.fn is results[0].fn for r in results)


# ------------------------------------------------------------------- LRU
def test_lru_bound_evicts_and_counts(monkeypatch):
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_ENTRIES", "3")
    ev0 = _counter("compile.evictions", tag="executor")
    keys = [_key(sig=((i + 1,), "f32")) for i in range(5)]
    for k in keys:
        csvc.get_or_build(k, _build_mul())
    assert csvc.stats()["entries"] == 3
    assert _counter("compile.evictions", tag="executor") == ev0 + 2
    # oldest evicted: a re-request is a real (re-counted) compile
    r0 = _counter("retrace.executor")
    again = csvc.get_or_build(keys[0], _build_mul())
    assert again.origin == "built"
    assert _counter("retrace.executor") == r0 + 1
    # the refreshed entry displaced the then-oldest survivor
    assert csvc.stats()["entries"] == 3


def test_lru_hit_refreshes_position(monkeypatch):
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_ENTRIES", "2")
    ka, kb, kc = (_key(sig=((i + 1,), "f32")) for i in range(3))
    csvc.get_or_build(ka, _build_mul())
    csvc.get_or_build(kb, _build_mul())
    csvc.get_or_build(ka, _build_mul())    # refresh a
    csvc.get_or_build(kc, _build_mul())    # evicts b, not a
    assert csvc.get(ka) is not None
    assert csvc.get(kb) is None


# ------------------------------------------------------------ disk cache
def test_disk_roundtrip_zero_compiles_bit_parity(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    k = _key()
    x = jnp.asarray(np.random.RandomState(0).randn(4).astype(np.float32))
    cold = csvc.get_or_build(k, _build_mul(), example_args=(x,))
    assert cold.origin == "built"
    ref = np.asarray(cold.fn(x))
    assert _counter("compile.disk.writes", tag="executor") >= 1
    assert os.path.exists(csvc.disk_path_of(k))
    # "fresh process": drop all in-memory state, same dir
    csvc.reset()
    r0 = _counter("retrace.executor")
    h0 = _counter("compile.disk.hits", tag="executor")
    warm = csvc.get_or_build(k, _build_mul(), example_args=(x,))
    assert warm.origin == "disk"
    assert _counter("retrace.executor") == r0        # a load is NOT a compile
    assert _counter("compile.disk.hits", tag="executor") == h0 + 1
    np.testing.assert_array_equal(np.asarray(warm.fn(x)), ref)


def test_disk_meta_persists(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))

    def build():
        def f(x):
            return x - 1

        return jax.jit(f), {"out_fmt": [0], "out_specs": [((4,), "f32")]}

    k = _key()
    csvc.get_or_build(k, build, example_args=(jnp.ones((4,)),))
    csvc.reset()
    warm = csvc.get_or_build(k, build, example_args=(jnp.ones((4,)),))
    assert warm.origin == "disk"
    assert warm.meta == {"out_fmt": [0], "out_specs": [[(4,), "f32"]]} \
        or warm.meta == {"out_fmt": [0], "out_specs": [((4,), "f32")]}


def test_truncated_blob_recompiles(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    k = _key()
    x = jnp.ones((4,))
    csvc.get_or_build(k, _build_mul(), example_args=(x,))
    path = csvc.disk_path_of(k)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:max(4, len(blob) // 3)])
    csvc.reset()
    d0 = _counter("compile.disk.drops", tag="corrupt")
    r0 = _counter("retrace.executor")
    e = csvc.get_or_build(k, _build_mul(), example_args=(x,))
    assert e.origin == "built"                       # degraded, not crashed
    assert float(e.fn(x)[0]) == 3.0
    assert _counter("compile.disk.drops", tag="corrupt") == d0 + 1
    assert _counter("retrace.executor") == r0 + 1
    # the recompile re-spilled a GOOD blob: next probe loads again
    csvc.reset()
    assert csvc.get_or_build(k, _build_mul(),
                             example_args=(x,)).origin == "disk"


def test_garbage_blob_recompiles(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    k = _key()
    with open(csvc.disk_path_of(k), "wb") as f:
        f.write(b"not a pickle at all")
    d0 = _counter("compile.disk.drops", tag="corrupt")
    e = csvc.get_or_build(k, _build_mul(), example_args=(jnp.ones((4,)),))
    assert e.origin == "built"
    assert _counter("compile.disk.drops", tag="corrupt") == d0 + 1


def test_version_mismatch_recompiles(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    k = _key()
    x = jnp.ones((4,))
    csvc.get_or_build(k, _build_mul(), example_args=(x,))
    path = csvc.disk_path_of(k)
    rec = pickle.load(open(path, "rb"))
    rec["env"] = dict(rec["env"], jax="0.0.1-older")
    with open(path, "wb") as f:
        pickle.dump(rec, f)
    csvc.reset()
    d0 = _counter("compile.disk.drops", tag="version_mismatch")
    e = csvc.get_or_build(k, _build_mul(), example_args=(x,))
    assert e.origin == "built"
    assert _counter("compile.disk.drops",
                    tag="version_mismatch") == d0 + 1


def test_unrestorable_blob_marked_and_skipped(tmp_path, monkeypatch):
    """A blob whose executable cannot deserialize in this environment
    (XLA CPU fusion-symbol limitation) recompiles once (load_error),
    gets tombstoned, and every later restart skips straight to the
    recompile — no repeated failed loads, no re-spill churn."""
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    k = _key()
    x = jnp.ones((4,))
    csvc.get_or_build(k, _build_mul(), example_args=(x,))
    path = csvc.disk_path_of(k)
    rec = pickle.load(open(path, "rb"))
    rec["payload"] = b"\x00not an executable"
    with open(path, "wb") as f:
        pickle.dump(rec, f)
    csvc.reset()
    d0 = _counter("compile.disk.drops", tag="load_error")
    w0 = _counter("compile.disk.writes", tag="executor")
    e = csvc.get_or_build(k, _build_mul(), example_args=(x,))
    assert e.origin == "built"
    assert _counter("compile.disk.drops", tag="load_error") == d0 + 1
    # the recompile did NOT re-spill (the digest is marked unloadable)
    assert _counter("compile.disk.writes", tag="executor") == w0
    assert os.path.exists(path + ".unloadable")
    csvc.reset()
    u0 = _counter("compile.disk.drops", tag="unloadable")
    e2 = csvc.get_or_build(k, _build_mul(), example_args=(x,))
    assert e2.origin == "built"
    assert _counter("compile.disk.drops", tag="unloadable") == u0 + 1
    assert _counter("compile.disk.drops", tag="load_error") == d0 + 1


def test_forged_key_blob_never_served(tmp_path, monkeypatch):
    """A blob renamed onto another key's digest (or a digest collision)
    is refused by the in-blob canonical-key check — the cache can never
    serve an executable built for a different policy/sharding/donation."""
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    ka = _key(policy=("pA",))
    kb = _key(policy=("pB",))
    x = jnp.ones((4,))
    csvc.get_or_build(ka, _build_mul(7.0), example_args=(x,))
    os.replace(csvc.disk_path_of(ka), csvc.disk_path_of(kb))
    csvc.reset()
    d0 = _counter("compile.disk.drops", tag="key_mismatch")
    e = csvc.get_or_build(kb, _build_mul(3.0), example_args=(x,))
    assert e.origin == "built"
    assert float(e.fn(x)[0]) == 3.0                  # kb's OWN function
    assert _counter("compile.disk.drops", tag="key_mismatch") == d0 + 1


def test_policy_sharding_donation_flips_change_digest():
    """The stale-policy safety is structural: every canonical-key
    component that changes the traced program changes the DIGEST, so
    flipped configurations can never even find each other's blobs."""
    base = _key(policy=("a",))
    assert csvc.digest_of(base) != csvc.digest_of(_key(policy=("b",)))
    assert csvc.digest_of(base) != csvc.digest_of(
        _key(policy=("a",), sharding=("zero1", 8)))
    assert csvc.digest_of(base) != csvc.digest_of(
        _key(policy=("a",), donation=(0, 2)))
    assert csvc.digest_of(base) != csvc.digest_of(
        _key(policy=("a",), sig=((8,), "f32")))
    # site and instance nonce deliberately do NOT move the digest: a
    # replacement replica r9 on the same device reuses retired r2's blob
    assert csvc.digest_of(base) == csvc.digest_of(
        _key(policy=("a",), site="serving.predict.r9", nonce="iXYZ"))


def test_concurrent_writers_one_dir(tmp_path):
    """Two processes racing the same key into one cache dir: both
    succeed, the committed blob stays loadable (tmp+rename — a torn
    write can never land under the final name)."""
    script = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MXTPU_COMPILE_CACHE_DIR"] = sys.argv[1]
import jax, jax.numpy as jnp
from mxtpu import compile_service as csvc
k = csvc.canonical_key(site="executor", fn_id="race", signature=((64, 64), "f32"),
                       policy=("p",), device=csvc.device_token())
e = csvc.get_or_build(k, lambda: jax.jit(lambda x: x @ x + 1.0),
                      example_args=(jnp.ones((64, 64)),))
print("OK", e.origin, float(e.fn(jnp.ones((64, 64)))[0][0]))
"""
    env = dict(os.environ, PYTHONPATH=REPO)
    procs = [subprocess.Popen([sys.executable, "-c", script,
                               str(tmp_path)],
                              env=env, cwd=REPO, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for _ in range(2)]
    outs = [p.communicate(timeout=240) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err[-1500:]
        assert "OK" in out and "65.0" in out, (out, err[-800:])
    # a third process loads what the racers committed — zero compiles
    p3 = subprocess.run([sys.executable, "-c", script, str(tmp_path)],
                        env=env, cwd=REPO, capture_output=True, text=True,
                        timeout=240)
    assert p3.returncode == 0, p3.stderr[-1500:]
    assert "OK disk" in p3.stdout, p3.stdout


def test_manifest_written(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    k = _key()
    csvc.get_or_build(k, _build_mul(), example_args=(jnp.ones((4,)),))
    man = csvc.manifest(str(tmp_path))
    assert man["format"] == csvc.FORMAT_VERSION
    assert csvc.digest_of(k) in man["entries"]
    row = man["entries"][csvc.digest_of(k)]
    assert row["site"] == "executor"
    assert row["key"] == k.digest_material()


def test_no_dir_means_plain_jit_path():
    """Without MXTPU_COMPILE_CACHE_DIR (and outside warmup) the service
    returns the freshly-built plain jit exactly as the per-site caches
    did — no AOT, no disk traffic."""
    os.environ.pop("MXTPU_COMPILE_CACHE_DIR", None)
    w0 = _counter("compile.disk.writes", tag="executor")
    e = csvc.get_or_build(_key(), _build_mul(),
                          example_args=(jnp.ones((4,)),))
    assert e.origin == "built"
    assert _counter("compile.disk.writes", tag="executor") == w0
    # a plain jit retraces on new shapes (an AOT Compiled would refuse)
    assert float(e.fn(jnp.ones((9,)))[0]) == 3.0


# ------------------------------------------------------------------ warmup
def test_warmup_concurrent_and_grouped():
    builds = []

    def build():
        builds.append(1)

        def f(x):
            return x + 5

        return jax.jit(f)

    s0 = _counter("compile.lowering_shares", tag="serving.predict.r1")
    entries = [csvc.WarmupEntry(
        key=_key(site="serving.predict.r%d" % i, nonce="i%d" % i),
        build=build, example_args=(jnp.ones((4,)),),
        provenance={"r": i}, group=("g", "sig")) for i in range(3)]
    summary = csvc.warmup(entries, threads=3)
    assert summary["entries"] == 3 and summary["built"] == 3
    assert summary["errors"] == 0
    assert len(builds) == 1                          # ONE trace, N compiles
    assert _counter("compile.lowering_shares",
                    tag="serving.predict.r1") == s0 + 1
    for i in range(3):
        e = csvc.get(_key(site="serving.predict.r%d" % i,
                          nonce="i%d" % i))
        assert e is not None
        assert float(e.fn(jnp.ones((4,)))[0]) == 6.0


def test_warmup_reraises_first_error():
    def bad_build():
        raise RuntimeError("broken bucket")

    entries = [
        csvc.WarmupEntry(key=_key(sig=((1,), "f32")),
                         build=_build_mul(), example_args=(jnp.ones((1,)),),
                         provenance=None),
        csvc.WarmupEntry(key=_key(sig=((2,), "f32")), build=bad_build,
                         example_args=(jnp.ones((2,)),), provenance=None),
    ]
    with pytest.raises(RuntimeError, match="broken bucket"):
        csvc.warmup(entries)
    # the good entry still landed
    assert csvc.get(_key(sig=((1,), "f32"))) is not None


def test_warmup_aot_even_without_dir():
    """warmup forces the AOT path (explicit lower+compile) with or
    without a disk dir — the executable is ready before first
    dispatch."""
    os.environ.pop("MXTPU_COMPILE_CACHE_DIR", None)
    entries = [csvc.WarmupEntry(key=_key(), build=_build_mul(),
                                example_args=(jnp.ones((4,)),),
                                provenance=None)]
    csvc.warmup(entries)
    e = csvc.get(_key())
    assert hasattr(e.fn, "cost_analysis")            # AOT executable


# ------------------------------------------------- end-to-end warm starts
def _run_startup_child(scenario, cache_dir, extra_env=None):
    env = dict(os.environ, PYTHONPATH=REPO,
               BENCH_STARTUP_HIDDEN="8", BENCH_STARTUP_LAYERS="1")
    env.update(extra_env or {})
    env["MXTPU_COMPILE_CACHE_DIR"] = str(cache_dir)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "startup_bench.py"),
         "--child", scenario],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("STARTUP_BENCH ")][0]
    return json.loads(line[len("STARTUP_BENCH "):])


def test_trainer_warm_start_zero_compiles(tmp_path):
    """ISSUE-15 acceptance (a): a restarted trainer reaches its first
    step from a warm MXTPU_COMPILE_CACHE_DIR with ZERO compiles
    (watchdog-pinned across every retrace site) and the identical
    loss."""
    cold = _run_startup_child("trainer", tmp_path)
    warm = _run_startup_child("trainer", tmp_path)
    assert cold["compiles"] > 0 and cold["disk_writes"] > 0
    assert warm["compiles"] == 0, warm
    assert warm["disk_hits"] > 0
    assert warm["loss"] == cold["loss"]              # bit parity


def test_predictor_warm_start_zero_compiles(tmp_path):
    """ISSUE-15 acceptance (b): a fresh Predictor replica finishes
    warmup from a warm dir with ZERO compiles."""
    cold = _run_startup_child("predictor", tmp_path)
    warm = _run_startup_child("predictor", tmp_path)
    assert cold["compiles"] > 0
    assert warm["compiles"] == 0, warm
    assert warm["disk_hits"] > 0


# ---------------------------------------------- site integration details
def test_cached_op_policy_flip_with_disk_never_stale(tmp_path,
                                                     monkeypatch):
    """A policy flip under a live disk cache recompiles; flipping BACK
    disk-hits the original executable with zero new compiles — and both
    directions stay bit-identical to their first runs."""
    from mxtpu.gluon import nn
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")  # policy_key member
    net = nn.HybridSequential()
    net.add(nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(2, 3).astype(np.float32))
    net(x)
    net.hybridize()
    y_a = net(x).asnumpy()
    n0 = len(net._cached_op._jits)
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "0")
    y_b = net(x).asnumpy()
    assert len(net._cached_op._jits) == n0 + 1       # flip: one new entry
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    r0 = telemetry.value("retrace.cached_op")
    y_a2 = net(x).asnumpy()
    assert telemetry.value("retrace.cached_op") == r0   # L1 hit, no compile
    np.testing.assert_array_equal(y_a, y_a2)
    np.testing.assert_allclose(y_a, y_b, rtol=1e-6)


def test_rtc_kernel_cache_bounded(monkeypatch):
    """The rtc per-kernel dict was unbounded under launch-signature
    churn; in the service it rides the LRU bound."""
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_ENTRIES", "4")
    from mxtpu import rtc

    mod = rtc.PallasModule("""
def scale_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0
""", exports=["scale_kernel"])
    kern = mod.get_kernel("scale_kernel")
    ev0 = _counter("compile.evictions", tag="rtc")
    for n in range(2, 9):
        out = kern.launch([mx.nd.ones((n,))], out_shapes=(n,))
        assert float(out.asnumpy()[0]) == 2.0
    st = csvc.stats()["per_site"]
    assert st.get("rtc", 0) <= 4
    assert _counter("compile.evictions", tag="rtc") > ev0


def test_executor_entries_live_in_service():
    """Executor signatures are service entries now (bounded, shared
    reporting) — the module path's old private dict is gone."""
    import mxtpu.symbol as sym_mod

    data = sym_mod.var("data")
    out = sym_mod.FullyConnected(data=data, num_hidden=4, name="fc")
    exe = out.simple_bind(data=(2, 3))
    exe.forward(is_train=False, data=mx.nd.ones((2, 3)))
    assert csvc.stats()["per_site"].get("executor", 0) >= 1
    assert not hasattr(exe, "_jits")
