"""Space-to-depth stem transform (mxtpu/contrib/s2d_stem.py — the MLPerf
ResNet trick): exact functional equivalence and gradient flow to the
original 7x7 parameter."""
import numpy as np
import jax.numpy as jnp
import pytest
from jax import lax

import mxtpu as mx
from mxtpu import gluon
from mxtpu.contrib.s2d_stem import (apply_to_resnet, embed_stem_weight,
                                    space_to_depth_nhwc)


def test_weight_embedding_exact():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
    w = jnp.asarray(rng.randn(7, 7, 3, 8) * 0.1, jnp.float32)
    ref = lax.conv_general_dilated(x, w, (2, 2), [(3, 3), (3, 3)],
                                   dimension_numbers=("NHWC", "HWIO",
                                                      "NHWC"))
    out = lax.conv_general_dilated(
        space_to_depth_nhwc(x), embed_stem_weight(w), (1, 1),
        [(2, 1), (2, 1)], dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-4)


def test_zoo_resnet_transform_preserves_function_and_trains():
    from mxtpu.gluon.model_zoo import vision
    mx.random.seed(0)
    with mx.layout("NHWC"):
        net = vision.resnet18_v1(classes=10)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0)
                    .uniform(-1, 1, (2, 224, 224, 3)).astype(np.float32))
    y = mx.nd.array(np.array([1.0, 2.0], np.float32))
    ref = net(x).asnumpy()
    apply_to_resnet(net, mode=1)
    np.testing.assert_allclose(net(x).asnumpy(), ref, rtol=2e-4, atol=2e-4)
    # training still updates the ORIGINAL 7x7 stem weight
    w = [p for n, p in net.collect_params().items()
         if p.shape[:2] == (7, 7)][0]
    before = w.data().asnumpy().copy()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with mx.autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    tr.step(2)
    assert np.abs(w.data().asnumpy() - before).sum() > 0


def test_double_s2d_weight_embedding_exact():
    """Mode 2 (4x4 s2d -> 3x3 conv on 48->256ch -> 2x2 depth-to-space)
    must equal the plain 7x7s2 stem exactly, incl. weight gradients
    through the embedding (round 5: mode 1 measured no faster than the
    plain stem in isolation; this is the MXU-shaped answer)."""
    import jax
    from mxtpu.contrib.s2d_stem import (_StemFn, depth_to_space2_nhwc,
                                        embed_stem_weight4,
                                        space_to_depth4_nhwc)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
    w = jnp.asarray(rng.randn(7, 7, 3, 8) * 0.1, jnp.float32)
    ref = lax.conv_general_dilated(x, w, (2, 2), [(3, 3), (3, 3)],
                                   dimension_numbers=("NHWC", "HWIO",
                                                      "NHWC"))
    got = _StemFn(w, None, mode=2)(x)
    assert got.shape == ref.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    # shapes of the MXU-shaped intermediate
    assert space_to_depth4_nhwc(x).shape == (2, 8, 8, 48)
    assert embed_stem_weight4(w).shape == (3, 3, 48, 32)
    # gradient to the ORIGINAL weight matches plain autodiff
    g = jax.grad(lambda w_: jnp.sum(_StemFn(w_, None, mode=2)(x) ** 2))(w)
    gref = jax.grad(lambda w_: jnp.sum(lax.conv_general_dilated(
        x, w_, (2, 2), [(3, 3), (3, 3)],
        dimension_numbers=("NHWC", "HWIO", "NHWC")) ** 2))(w)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=5e-3, atol=5e-3)


def test_zoo_resnet_mode2_preserves_function():
    from mxtpu.gluon.model_zoo import vision
    mx.random.seed(0)
    with mx.layout("NHWC"):
        net = vision.resnet18_v1(classes=10)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(1)
                    .uniform(-1, 1, (2, 224, 224, 3)).astype(np.float32))
    ref = net(x).asnumpy()
    apply_to_resnet(net, mode=2)
    np.testing.assert_allclose(net(x).asnumpy(), ref, rtol=2e-4, atol=2e-4)


def test_policy_mode_lever_selects_stem_per_trace(monkeypatch):
    """The round-7 promotion: apply_to_resnet() with no mode defers to
    MXTPU_S2D_STEM at trace time — one wrapped net serves plain / s2d /
    double-s2d, each mode preserving the function, with the flip
    recompiling through registry.policy_key (not reusing a stale trace)."""
    from mxtpu.contrib.s2d_stem import stem_mode
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.ops.registry import policy_key

    monkeypatch.delenv("MXTPU_S2D_STEM", raising=False)
    assert stem_mode() == 0                      # default: plain stem
    keys = set()
    for mode in ("0", "1", "2"):
        monkeypatch.setenv("MXTPU_S2D_STEM", mode)
        keys.add(policy_key())
    assert len(keys) == 3                        # each mode its own cache key
    monkeypatch.setenv("MXTPU_S2D_STEM", "bogus")
    with pytest.raises(Exception, match="MXTPU_S2D_STEM"):
        stem_mode()
    monkeypatch.delenv("MXTPU_S2D_STEM", raising=False)

    mx.random.seed(0)
    with mx.layout("NHWC"):
        net = vision.resnet18_v1(classes=10)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(2)
                    .uniform(-1, 1, (2, 224, 224, 3)).astype(np.float32))
    ref = net(x).asnumpy()
    apply_to_resnet(net)                         # policy mode (mode=None)
    for mode in ("0", "1", "2"):
        monkeypatch.setenv("MXTPU_S2D_STEM", mode)
        np.testing.assert_allclose(net(x).asnumpy(), ref, rtol=2e-4,
                                   atol=2e-4, err_msg="mode %s" % mode)
