"""Space-to-depth stem transform (mxtpu/contrib/s2d_stem.py — the MLPerf
ResNet trick): exact functional equivalence and gradient flow to the
original 7x7 parameter."""
import numpy as np
import jax.numpy as jnp
from jax import lax

import mxtpu as mx
from mxtpu import gluon
from mxtpu.contrib.s2d_stem import (apply_to_resnet, embed_stem_weight,
                                    space_to_depth_nhwc)


def test_weight_embedding_exact():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
    w = jnp.asarray(rng.randn(7, 7, 3, 8) * 0.1, jnp.float32)
    ref = lax.conv_general_dilated(x, w, (2, 2), [(3, 3), (3, 3)],
                                   dimension_numbers=("NHWC", "HWIO",
                                                      "NHWC"))
    out = lax.conv_general_dilated(
        space_to_depth_nhwc(x), embed_stem_weight(w), (1, 1),
        [(2, 1), (2, 1)], dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-4)


def test_zoo_resnet_transform_preserves_function_and_trains():
    from mxtpu.gluon.model_zoo import vision
    mx.random.seed(0)
    with mx.layout("NHWC"):
        net = vision.resnet18_v1(classes=10)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0)
                    .uniform(-1, 1, (2, 224, 224, 3)).astype(np.float32))
    y = mx.nd.array(np.array([1.0, 2.0], np.float32))
    ref = net(x).asnumpy()
    apply_to_resnet(net)
    np.testing.assert_allclose(net(x).asnumpy(), ref, rtol=2e-4, atol=2e-4)
    # training still updates the ORIGINAL 7x7 stem weight
    w = [p for n, p in net.collect_params().items()
         if p.shape[:2] == (7, 7)][0]
    before = w.data().asnumpy().copy()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    with mx.autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    tr.step(2)
    assert np.abs(w.data().asnumpy() - before).sum() > 0
