"""External-kernel hook (ref analog: the TVM bridge,
src/nnvm/tvm_bridge.cc:54-178 — externally-built kernels joining the
execution graph as first-class ops). Here: device kernels inline into the
jitted program; host kernels ride jax.pure_callback."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.base import MXNetError
from mxtpu.contrib.external_kernel import (register_external_kernel,
                                           register_host_kernel)


@pytest.fixture(autouse=True, scope="module")
def _registry_cleanup():
    """Unregister this module's `_ext_*` ops afterwards: the sweep's
    registry-coverage gate (test_operator_sweep.py) audits every
    registered op, and test-scoped kernels are not framework surface."""
    from mxtpu.ops.registry import REGISTRY
    import mxtpu.ndarray as nd_mod
    import mxtpu.symbol as sym_mod
    before = set(REGISTRY)
    yield
    for name in set(REGISTRY) - before:
        del REGISTRY[name]
        short = name[len("_contrib_"):] if name.startswith("_contrib_") \
            else None
        for mod in (nd_mod, sym_mod):
            if name in vars(mod):
                delattr(mod, name)
        for sub in (nd_mod.contrib, nd_mod._internal, sym_mod.contrib):
            for attr in (name, short):
                if attr and attr in vars(sub):
                    delattr(sub, attr)


def test_device_kernel_nd_sym_hybridize_and_grad():
    import jax.numpy as jnp

    def scaled_gelu(x, scale=1.0):
        return scale * 0.5 * x * (1.0 + jnp.tanh(
            0.7978845608 * (x + 0.044715 * x ** 3)))

    fn = register_external_kernel("_ext_scaled_gelu", scaled_gelu)
    x = np.linspace(-2, 2, 7).astype(np.float32)

    # imperative, via the returned callable AND the nd namespace
    a = mx.nd.array(x)
    got = fn(a, scale=2.0).asnumpy()
    ref = 2.0 * 0.5 * x * (1.0 + np.tanh(0.7978845608 * (x + 0.044715 * x**3)))
    np.testing.assert_allclose(got, ref, rtol=1e-6)

    # autograd flows through jax's own differentiation of the kernel
    a.attach_grad()
    with mx.autograd.record():
        y = fn(a, scale=2.0)
    y.backward(mx.nd.ones_like(y))
    eps = 1e-3
    num = (2.0 * 0.5 * (x + eps) * (1 + np.tanh(0.7978845608 * ((x + eps) + 0.044715 * (x + eps)**3)))
           - 2.0 * 0.5 * (x - eps) * (1 + np.tanh(0.7978845608 * ((x - eps) + 0.044715 * (x - eps)**3)))) / (2 * eps)
    np.testing.assert_allclose(a.grad.asnumpy(), num, rtol=1e-2, atol=1e-3)

    # symbolic composition + executor (the graph path the TVM bridge fed);
    # the namespace resolves late-registered ops via module __getattr__
    from mxtpu import symbol as sym
    data = sym.var("data")
    out = sym._ext_scaled_gelu(data, scale=2.0)
    ex = out.bind(args={"data": mx.nd.array(x)})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), ref, rtol=1e-6)


def test_duplicate_name_rejected():
    register_external_kernel("_ext_dup_probe", lambda x: x)
    with pytest.raises(MXNetError, match="already registered"):
        register_external_kernel("_ext_dup_probe", lambda x: x)
    # aliases must not silently shadow builtins either
    with pytest.raises(MXNetError, match="already registered"):
        register_external_kernel("_ext_other_probe", lambda x: x,
                                 aliases=("dot",))


def test_vjp_kernel_accepts_attr_kwargs():
    """custom_vjp kernels must still take attr kwargs (attrs bind before
    the custom_vjp boundary — regression: jax rejected them)."""
    def scaled(x, alpha=1.0):
        return alpha * x

    def vjp(g, x, alpha=1.0):
        return (alpha * g,)

    fn = register_external_kernel("_ext_scaled_id", scaled, vjp=vjp)
    a = mx.nd.array(np.array([1.0, 2.0], np.float32))
    a.attach_grad()
    with mx.autograd.record():
        y = fn(a, alpha=3.0)
    y.backward(mx.nd.ones_like(y))
    np.testing.assert_allclose(y.asnumpy(), [3.0, 6.0])
    np.testing.assert_allclose(a.grad.asnumpy(), [3.0, 3.0])


def test_late_contrib_registration_reaches_subnamespaces():
    register_external_kernel("_contrib_ext_probe_op", lambda x: x + 1.0)
    a = mx.nd.array(np.zeros(2, np.float32))
    np.testing.assert_allclose(mx.nd.contrib.ext_probe_op(a).asnumpy(), 1.0)
    np.testing.assert_allclose(
        mx.nd._internal._contrib_ext_probe_op(a).asnumpy(), 1.0)
    from mxtpu import symbol as sym
    s = sym.contrib.ext_probe_op(sym.var("data"))
    ex = s.bind(args={"data": a})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), 1.0)


def test_host_kernel_with_custom_vjp_trains():
    """A numpy host function with a hand-written vjp participates in a
    jitted training step (the bridge's async external call, with grads)."""
    import jax.numpy as jnp

    calls = []

    def host_square(x):
        calls.append(1)
        return np.square(np.asarray(x))

    def vjp(g, x):
        return (2.0 * x * g,)

    fn = register_host_kernel("_ext_host_square", host_square, vjp=vjp)
    a = mx.nd.array(np.array([1.0, -3.0, 0.5], np.float32))
    np.testing.assert_allclose(fn(a).asnumpy(), [1.0, 9.0, 0.25], rtol=1e-6)
    assert calls  # really ran on the host

    a.attach_grad()
    with mx.autograd.record():
        y = fn(a)
    y.backward(mx.nd.ones_like(y))
    np.testing.assert_allclose(a.grad.asnumpy(), 2.0 * a.asnumpy(),
                               rtol=1e-6)


def test_host_kernel_out_shape_fn():
    import jax

    def row_sums(x):
        return np.asarray(x).sum(axis=1)

    fn = register_host_kernel(
        "_ext_row_sums", row_sums,
        out_shape_fn=lambda x: jax.ShapeDtypeStruct((x.shape[0],), x.dtype))
    a = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose(fn(a).asnumpy(), [3.0, 12.0])


def test_device_kernel_usable_in_hybridized_block():
    import jax.numpy as jnp
    register_external_kernel("_ext_double", lambda x: x * 2.0)
    from mxtpu import gluon

    class Net(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F._ext_double(x) + 1.0

    net = Net()
    net.hybridize()
    x = mx.nd.array(np.ones((2, 2), np.float32))
    out1 = net(x).asnumpy()
    out2 = net(x).asnumpy()  # second call: cached jit executable
    np.testing.assert_allclose(out1, 3.0)
    np.testing.assert_allclose(out2, 3.0)
