"""Sparse NDArray DEPTH tests: arithmetic storage dispatch, cast_storage
round-trips, dot variants, retain/indexing, and lazy optimizer updates —
the combinatorial tier mirroring the reference's 2,311-LoC
tests/python/unittest/test_sparse_operator.py + test_sparse_ndarray.py.

Regression anchor: sparse arithmetic used to inherit the dense NDArray
dunders, which operate on the raw VALUES buffer — ``rsp + rsp`` on a 4x3
returned a wrong 2x3 dense. These tests pin the reference semantics:
zero-preserving scalar ops stay sparse, same-format +/- merges sparsely,
everything else densifies BOTH operands (storage fallback).
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.base import MXNetError
from mxtpu.test_utils import assert_almost_equal

RNG = np.random.RandomState


def _rand_sparse(shape, density, seed=0):
    rng = RNG(seed)
    d = rng.uniform(-2, 2, shape).astype(np.float32)
    d[rng.uniform(size=shape) > density] = 0.0
    if d.ndim == 2:  # keep at least one structurally-zero row
        d[shape[0] // 2] = 0.0
    return d


# ----------------------------------------------------- arithmetic dispatch
def test_rsp_scalar_ops_stay_sparse():
    d = _rand_sparse((6, 4), 0.4, 1)
    r = mx.nd.array(d).tostype("row_sparse")
    for out, ref in [(r * 3, d * 3), (3 * r, d * 3), (r / 2, d / 2),
                     (-r, -d), (abs(r), np.abs(d)), (r ** 2, d ** 2)]:
        assert out.stype == "row_sparse", "zero-preserving op must stay rsp"
        assert_almost_equal(out.todense(), ref, rtol=1e-6)


def test_csr_scalar_ops_stay_sparse():
    d = _rand_sparse((5, 7), 0.3, 2)
    c = mx.nd.array(d).tostype("csr")
    for out, ref in [(c * 2, d * 2), (c / 4, d / 4), (-c, -d)]:
        assert out.stype == "csr"
        assert_almost_equal(out.todense(), ref, rtol=1e-6)


def test_rsp_add_sub_merges_sparsely():
    da = _rand_sparse((8, 3), 0.3, 3)
    db = _rand_sparse((8, 3), 0.3, 4)
    ra = mx.nd.array(da).tostype("row_sparse")
    rb = mx.nd.array(db).tostype("row_sparse")
    s = ra + rb
    assert s.stype == "row_sparse", "rsp+rsp must not densify"
    assert_almost_equal(s.todense(), da + db, rtol=1e-6)
    s = ra - rb
    assert s.stype == "row_sparse"
    assert_almost_equal(s.todense(), da - db, rtol=1e-6)
    # merged row ids = union, each appearing once
    idx = s.indices.asnumpy()
    assert len(np.unique(idx)) == len(idx)


def test_csr_add_keeps_csr():
    da = _rand_sparse((5, 6), 0.3, 5)
    db = _rand_sparse((5, 6), 0.3, 6)
    s = mx.nd.array(da).tostype("csr") + mx.nd.array(db).tostype("csr")
    assert s.stype == "csr"
    assert_almost_equal(s.todense(), da + db, rtol=1e-6)


def test_mixed_operands_densify_correctly():
    """sparse op dense / sparse op scalar-add: storage fallback must use
    the DENSE VIEW of the sparse operand, never its values buffer."""
    d = _rand_sparse((6, 4), 0.4, 7)
    e = RNG(8).uniform(-1, 1, (6, 4)).astype(np.float32)
    r = mx.nd.array(d).tostype("row_sparse")
    c = mx.nd.array(d).tostype("csr")
    for out, ref in [(r + mx.nd.array(e), d + e),
                     (mx.nd.array(e) + r, d + e),
                     (mx.nd.array(e) - r, e - d),
                     (r * mx.nd.array(e), d * e),
                     (c + mx.nd.array(e), d + e),
                     (r + 1.0, d + 1.0),       # +scalar not zero-preserving
                     (1.0 - r, 1.0 - d),
                     (r + c, d + d)]:          # rsp+csr: both densify
        assert out.stype == "default"
        assert out.shape == (6, 4)
        assert_almost_equal(out, ref, rtol=1e-6)


def test_sparse_comparisons_use_dense_view():
    d = _rand_sparse((4, 3), 0.5, 9)
    r = mx.nd.array(d).tostype("row_sparse")
    assert_almost_equal(r == r, np.ones_like(d))
    assert_almost_equal(r > 0, (d > 0).astype(np.float32))
    assert_almost_equal(r <= 0, (d <= 0).astype(np.float32))


def test_sparse_inplace_rules():
    d = _rand_sparse((6, 4), 0.4, 10)
    r = mx.nd.array(d).tostype("row_sparse")
    r *= 2
    assert r.stype == "row_sparse"
    assert_almost_equal(r.todense(), d * 2, rtol=1e-6)
    r /= 2
    assert_almost_equal(r.todense(), d, rtol=1e-6)
    r += mx.nd.array(d).tostype("row_sparse")
    assert r.stype == "row_sparse"
    assert_almost_equal(r.todense(), d * 2, rtol=1e-6)
    with pytest.raises(MXNetError):
        r += mx.nd.array(d)       # would silently densify
    with pytest.raises(MXNetError):
        r *= mx.nd.array(d)


# ------------------------------------------------------------ cast_storage
@pytest.mark.parametrize("src,dst", [
    ("default", "row_sparse"), ("default", "csr"),
    ("row_sparse", "default"), ("csr", "default"),
    ("row_sparse", "csr"), ("csr", "row_sparse"),
])
def test_cast_storage_round_trips(src, dst):
    d = _rand_sparse((7, 5), 0.35, 11)
    a = mx.nd.array(d)
    if src != "default":
        a = a.tostype(src)
    b = a.tostype(dst)
    assert b.stype == dst
    back = b.tostype("default") if dst != "default" else b
    assert_almost_equal(back, d, rtol=1e-6)


def test_rsp_structural_zero_rows_not_stored():
    d = np.zeros((6, 3), np.float32)
    d[1] = 1.5
    d[4] = -2.0
    r = mx.nd.array(d).tostype("row_sparse")
    assert sorted(r.indices.asnumpy().astype(int).tolist()) == [1, 4]
    assert r.data.shape == (2, 3)


# --------------------------------------------------------------------- dot
def test_csr_dot_dense_variants():
    scipy_sparse = pytest.importorskip("scipy.sparse")
    d = _rand_sparse((6, 40), 0.15, 12)
    rhs = RNG(13).uniform(-1, 1, (40, 3)).astype(np.float32)
    c = mx.nd.array(d).tostype("csr")
    sp = scipy_sparse.csr_matrix(d)
    out = mx.nd.sparse.dot(c, mx.nd.array(rhs))
    assert_almost_equal(out, np.asarray(sp @ rhs), rtol=1e-4, atol=1e-5)
    # transpose_b
    out = mx.nd.sparse.dot(c, mx.nd.array(rhs.T), transpose_b=True)
    assert_almost_equal(out, np.asarray(sp @ rhs), rtol=1e-4, atol=1e-5)
    # transpose_a falls back to dense math but must still be right
    lhs2 = RNG(14).uniform(-1, 1, (6, 3)).astype(np.float32)
    out = mx.nd.sparse.dot(c, mx.nd.array(lhs2), transpose_a=True)
    assert_almost_equal(out, d.T @ lhs2, rtol=1e-4, atol=1e-5)


def test_rsp_dot_falls_back_dense():
    d = _rand_sparse((5, 8), 0.3, 15)
    rhs = RNG(16).uniform(-1, 1, (8, 2)).astype(np.float32)
    r = mx.nd.array(d).tostype("row_sparse")
    out = mx.nd.sparse.dot(r, mx.nd.array(rhs))
    assert_almost_equal(out, d @ rhs, rtol=1e-4, atol=1e-5)


# ------------------------------------------------------- retain / indexing
def test_retain_subset_and_missing_rows():
    d = _rand_sparse((8, 3), 0.6, 17)
    r = mx.nd.array(d).tostype("row_sparse")
    kept = r.retain(mx.nd.array(np.array([0, 3, 7], np.float32)))
    ref = np.zeros_like(d)
    for i in (0, 3, 7):
        ref[i] = d[i]
    assert kept.stype == "row_sparse"
    assert_almost_equal(kept.todense(), ref, rtol=1e-6)
    assert set(kept.indices.asnumpy().astype(int)) <= {0, 3, 7}


def test_csr_getitem_rows():
    d = _rand_sparse((6, 5), 0.4, 18)
    c = mx.nd.array(d).tostype("csr")
    assert_almost_equal(c[2:5], d[2:5], rtol=1e-6)
    assert_almost_equal(c[1], d[1], rtol=1e-6)


# --------------------------------------------------- lazy optimizer update
def test_sgd_lazy_update_touches_only_grad_rows():
    """With a row_sparse grad and lazy_update, rows absent from the grad
    must NOT move even under weight decay (ref: sgd lazy row_sparse path,
    src/operator/optimizer_op.cc)."""
    from mxtpu.ndarray.sparse import RowSparseNDArray
    w0 = RNG(19).uniform(-1, 1, (6, 4)).astype(np.float32)
    w = mx.nd.array(w0.copy())
    grad_rows = np.array([1, 4], np.int32)
    gvals = RNG(20).uniform(-1, 1, (2, 4)).astype(np.float32)
    g = RowSparseNDArray(mx.nd.array(gvals), mx.nd.array(grad_rows), (6, 4))
    opt = mx.optimizer.SGD(learning_rate=0.5, wd=0.1, lazy_update=True)
    upd = mx.optimizer.get_updater(opt)
    upd(0, g, w)
    out = w.asnumpy()
    untouched = [i for i in range(6) if i not in grad_rows]
    assert_almost_equal(out[untouched], w0[untouched])
    for j, i in enumerate(grad_rows):
        expect = w0[i] - 0.5 * (gvals[j] + 0.1 * w0[i])
        assert_almost_equal(out[i], expect, rtol=1e-5, atol=1e-6)


def test_sparse_leaf_grad_is_sparse():
    """A row_sparse autograd leaf must receive a row_sparse grad sharing
    its indices (ref: rsp weights get rsp grads), under both grad_req
    'write' and 'add' — regression: attach_grad used to allocate a dense
    logical-shape buffer while the tape delivers values-shaped cotangents."""
    from mxtpu import autograd as ag
    from mxtpu.ndarray.sparse import RowSparseNDArray
    vals = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    for req in ("write", "add"):
        r = RowSparseNDArray(vals.copy(), np.array([0, 2], np.int32), (4, 2))
        r.attach_grad(grad_req=req)
        with ag.record():
            y = (r * 3.0).todense()
        y.backward(mx.nd.array(np.ones((4, 2), np.float32)))
        g = r.grad
        assert g.stype == "row_sparse"
        assert g.shape == (4, 2)
        expect = np.zeros((4, 2), np.float32)
        expect[[0, 2]] = 3.0
        assert_almost_equal(g.todense(), expect, rtol=1e-6)
    with pytest.raises(MXNetError):
        r.attach_grad(stype="default")


def test_adam_lazy_update_rows_move():
    from mxtpu.ndarray.sparse import RowSparseNDArray
    w0 = RNG(21).uniform(-1, 1, (5, 3)).astype(np.float32)
    w = mx.nd.array(w0.copy())
    g = RowSparseNDArray(mx.nd.array(RNG(22).uniform(-1, 1, (2, 3))
                                     .astype(np.float32)),
                         mx.nd.array(np.array([0, 3], np.int32)), (5, 3))
    opt = mx.optimizer.Adam(learning_rate=0.1, lazy_update=True)
    upd = mx.optimizer.get_updater(opt)
    upd(0, g, w)
    out = w.asnumpy()
    assert_almost_equal(out[[1, 2, 4]], w0[[1, 2, 4]])
    assert np.abs(out[[0, 3]] - w0[[0, 3]]).max() > 1e-4
