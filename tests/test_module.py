"""Module API tests (ref patterns: tests/python/unittest/test_module.py,
tests/python/train/test_mlp.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import symbol as sym
from mxtpu.io import DataBatch, DataDesc, NDArrayIter
from mxtpu.module import BucketingModule, Module


def _mlp_symbol(num_hidden=32, num_classes=4):
    data = sym.var("data")
    net = sym.FullyConnected(data, sym.var("fc1_weight"), sym.var("fc1_bias"),
                             num_hidden=num_hidden, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, sym.var("fc2_weight"), sym.var("fc2_bias"),
                             num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(net, sym.var("softmax_label"), name="softmax")


def _toy_dataset(n=256, dim=8, classes=4, seed=0):
    """Linearly separable-ish clusters."""
    rng = np.random.RandomState(seed)
    centers = rng.normal(scale=3.0, size=(classes, dim))
    y = rng.randint(0, classes, size=(n,))
    x = centers[y] + rng.normal(scale=0.5, size=(n, dim))
    return x.astype(np.float32), y.astype(np.float32)


def test_module_bind_forward_shapes():
    net = _mlp_symbol()
    mod = Module(net, data_names=("data",), label_names=("softmax_label",))
    mod.bind(data_shapes=[DataDesc("data", (16, 8))],
             label_shapes=[DataDesc("softmax_label", (16,))])
    mod.init_params(initializer=mx.init.Xavier())
    batch = DataBatch(data=[mx.nd.ones((16, 8))],
                      label=[mx.nd.zeros((16,))])
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (16, 4)
    probs = out.asnumpy()
    np.testing.assert_allclose(probs.sum(axis=1), np.ones(16), rtol=1e-5)


def test_module_fit_accuracy():
    """End-to-end fit() must learn the toy problem (train-tier test,
    ref: tests/python/train/test_mlp.py accuracy assert)."""
    x, y = _toy_dataset()
    train = NDArrayIter(x, y, batch_size=32, shuffle=True)
    val = NDArrayIter(x, y, batch_size=32)
    net = _mlp_symbol()
    mod = Module(net)
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            num_epoch=10, initializer=mx.init.Xavier())
    score = mod.score(val, "acc")
    assert score[0][1] > 0.95, score


def test_module_save_load_checkpoint(tmp_path):
    x, y = _toy_dataset(n=64)
    train = NDArrayIter(x, y, batch_size=32)
    net = _mlp_symbol()
    mod = Module(net)
    mod.bind(data_shapes=train.provide_data, label_shapes=train.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer()
    prefix = str(tmp_path / "ckpt")
    mod.save_checkpoint(prefix, 3)

    mod2 = Module.load(prefix, 3)
    mod2.bind(data_shapes=train.provide_data, label_shapes=train.provide_label)
    mod2.init_params()
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        np.testing.assert_allclose(a2[k].asnumpy(), a1[k].asnumpy())
    batch = next(iter(train))
    mod.forward(batch, is_train=False)
    mod2.forward(batch, is_train=False)
    np.testing.assert_allclose(mod2.get_outputs()[0].asnumpy(),
                               mod.get_outputs()[0].asnumpy(), rtol=1e-5)


def test_module_predict_and_input_grads():
    x, y = _toy_dataset(n=64)
    it = NDArrayIter(x, y, batch_size=16)
    mod = Module(_mlp_symbol())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             inputs_need_grad=True)
    mod.init_params(initializer=mx.init.Xavier())
    preds = mod.predict(it)
    assert preds.shape == (64, 4)
    batch = DataBatch(data=[mx.nd.array(x[:16])],
                      label=[mx.nd.array(y[:16])])
    mod.forward(batch, is_train=True)
    mod.backward()
    g = mod.get_input_grads()[0]
    assert g is not None and g.shape == (16, 8)
    assert float(np.abs(g.asnumpy()).sum()) > 0


def test_bucketing_module_shares_params():
    """Variable-length inputs share one set of weights
    (ref: tests/python/train/test_bucketing.py)."""
    def sym_gen(seq_len):
        data = sym.var("data")
        net = sym.FullyConnected(data, sym.var("fc_weight"), sym.var("fc_bias"),
                                 num_hidden=8, flatten=False, name="fc")
        net = sym.mean(net, axis=1)
        net = sym.SoftmaxOutput(net, sym.var("softmax_label"), name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = BucketingModule(sym_gen, default_bucket_key=10)
    mod.bind(data_shapes=[DataDesc("data", (4, 10, 6))],
             label_shapes=[DataDesc("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer()

    for key, t in ((10, 10), (5, 5)):
        batch = DataBatch(
            data=[mx.nd.ones((4, t, 6))], label=[mx.nd.zeros((4,))],
            bucket_key=key,
            provide_data=[DataDesc("data", (4, t, 6))],
            provide_label=[DataDesc("softmax_label", (4,))])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    # both buckets see the same (updated) weight array
    w10 = mod._buckets[10]._exec.arg_dict["fc_weight"].asnumpy()
    w5 = mod._buckets[5]._exec.arg_dict["fc_weight"].asnumpy()
    np.testing.assert_allclose(w10, w5)


def test_ndarray_iter_pad_and_shuffle():
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = NDArrayIter(x, None, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    it2 = NDArrayIter(x, None, batch_size=4, last_batch_handle="discard")
    assert len(list(it2)) == 2


@pytest.mark.multidevice
def test_module_on_mesh_matches_single_device():
    """Module(context=Mesh) runs the classic fit loop data-parallel over the
    mesh (the reference's DataParallelExecutorGroup role) with identical
    numerics to the unsharded run."""
    import jax
    from mxtpu.parallel import make_mesh

    x, y = _toy_dataset(n=64)

    def run(ctx):
        mx.random.seed(0)
        np.random.seed(0)
        net = _mlp_symbol()
        mod = Module(net, context=ctx)
        mod.bind(data_shapes=[DataDesc("data", (32, 8))],
                 label_shapes=[DataDesc("softmax_label", (32,))])
        mod.init_params(initializer=mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        losses = []
        for i in range(4):
            batch = DataBatch(data=[mx.nd.array(x[i * 32:(i + 1) * 32])],
                              label=[mx.nd.array(y[i * 32:(i + 1) * 32])])
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
            losses.append(mod.get_outputs()[0].asnumpy().copy())
        return losses

    plain = run(None)
    mesh = run(make_mesh({"data": 8}, jax.devices()[:8]))
    for a, b in zip(plain, mesh):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_bucketing_module_trains_over_bucket_sentence_iter():
    """End-to-end variable-length training (ref: tests/python/train/
    test_bucketing.py): BucketSentenceIter over real buckets drives a
    per-bucket RNN symbol through BucketingModule.fit."""
    import numpy as np
    from mxtpu.rnn import BucketSentenceIter, encode_sentences

    rng = np.random.RandomState(0)
    # synthetic corpus: sentences of mixed lengths over a small vocab
    sentences = [["w%d" % rng.randint(20) for _ in range(rng.randint(3, 10))]
                 for _ in range(60)]
    data, vocab = encode_sentences(sentences)
    buckets = [5, 10]
    it = BucketSentenceIter(data, batch_size=8, buckets=buckets,
                            data_name="data", label_name="softmax_label")

    vocab_size = len(vocab) + 2
    hidden = 16

    def sym_gen(seq_len):
        data_s = mx.sym.Variable("data")
        emb = mx.sym.Embedding(data_s, input_dim=vocab_size,
                               output_dim=hidden, name="embed")
        tnc = mx.sym.swapaxes(emb, dim1=0, dim2=1)  # NTC -> TNC
        rnn = mx.sym.RNN(tnc, parameters=mx.sym.Variable("rnn_params"),
                         state=mx.sym.Variable("rnn_state"),
                         state_size=hidden, num_layers=1, mode="rnn_tanh",
                         name="rnn")
        ntc = mx.sym.swapaxes(rnn, dim1=0, dim2=1)
        pred = mx.sym.Reshape(ntc, shape=(-1, hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size, name="fc")
        label = mx.sym.Reshape(mx.sym.Variable("softmax_label"),
                               shape=(-1,))
        out = mx.sym.SoftmaxOutput(pred, label, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = BucketingModule(sym_gen, default_bucket_key=max(buckets))
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            eval_metric="perplexity")
    # both buckets were actually exercised and produced finite outputs
    it.reset()
    seen = set()
    for batch in it:
        seen.add(batch.bucket_key)
        mod.forward(batch, is_train=False)
        out = mod.get_outputs()[0].asnumpy()
        assert np.isfinite(out).all()
    assert seen == set(buckets)
