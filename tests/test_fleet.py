"""Elastic multi-host fleet (ISSUE 18): the control-plane failure matrix —

* membership board: per-host heartbeat files, staleness diagnosis
  (never-seen vs stale vs clean ``left``), dead-coordinator check
  raising LOUD with a ``coordinator_loss`` flight artifact;
* board barrier: payload return, deadline miss naming the missing
  hosts, a stale peer failing the wait EARLY with the board diagnosis;
* deadline bring-up: ``_run_with_deadline`` timeout/success/error
  paths, ``fleet.init`` rendezvous deadline (monkeypatched
  ``_rendezvous_required`` drives it on CPU), connect retries counted
  into ``retry.fleet_connect``, board-only bring-up on the forced-CPU
  tier, the ``rejoin_stall`` fault exiting ``EXIT_REJOIN_STALL``;
* fleet collective watchdog: fixed-deadline trip with the membership
  diagnosis in the ``fleet_collective_wedge`` artifact, poisoning,
  ``exit_on_trip`` code;
* step barrier: fingerprint exchange green path, cross-host divergence
  raising with a ``fleet_divergence`` artifact, dead-peer wedge;
* FleetSupervisor: scripted elastic run (host loss -> N-1 -> warm
  rejoin -> clean), victim-vs-lost classification, poison-crash and
  crash-loop refusals dumping ``supervisor_refusal`` with history,
  launch_round exit-code surfacing + hard child timeout;
* ONE bounded multi-process acceptance run: kill a host mid-step,
  survivors exit loud, the reshaped generation resumes from the last
  intact checkpoint and finishes clean.

Everything above the acceptance run is sleep- and subprocess-free on
fake clocks.
"""
import glob
import json
import os
import random
import sys
import threading
import time

import pytest

from mxtpu import fleet, resilience, telemetry
from mxtpu.fleet import (EXIT_FLEET_WEDGE, EXIT_HOST_LOSS,
                         EXIT_REJOIN_STALL, Fleet, FleetBringupError,
                         FleetCollectiveWatchdog, FleetMembership,
                         FleetSupervisor, FleetWedgeError)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_FLEET_DIR", "MXTPU_FLEET_CONNECT_RETRIES",
                "MXTPU_FLEET_CONNECT_BACKOFF_S",
                "MXTPU_FLEET_BRINGUP_TIMEOUT_S", "MXTPU_FLEET_HEARTBEAT_S",
                "MXTPU_FLEET_HEARTBEAT_MISS",
                "MXTPU_FLEET_COLLECTIVE_TIMEOUT_S",
                "MXTPU_FLEET_CHILD_TIMEOUT_S", "MXTPU_FAULT_INJECT",
                "MXTPU_FLIGHT_DIR", "MXTPU_FLIGHT_MAX",
                "MXTPU_COORDINATOR", "MXTPU_NUM_PROCESSES",
                "MXTPU_PROCESS_ID", "MXTPU_SUPERVISOR_RESTARTS",
                "MXTPU_SUPERVISOR_BACKOFF_S", "MXTPU_FLEET_OBS_S",
                "MXTPU_STRAGGLER_X", "MXTPU_PROFILE_ON_TRIP"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    yield
    telemetry.reset()
    resilience.reset_faults()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt

    def sleeper(self, s):
        # fake sleep + a real micro-yield: deadline loops that poll a
        # WORKER THREAD must let it get scheduled, or a busy fake-clock
        # loop can burn the whole fake deadline before the thread runs
        self.t += s
        time.sleep(0.0005)


class _Exit(Exception):
    def __init__(self, code):
        self.code = code


def _counter(name):
    v = telemetry.snapshot()["counters"].get(name, 0)
    return sum(v.values()) if isinstance(v, dict) else v


def _artifacts(tmp_path, reason):
    return sorted(glob.glob(os.path.join(str(tmp_path),
                                         "flight_%s_*" % reason)))


# ------------------------------------------------------- membership board
def test_membership_staleness_matrix(tmp_path):
    """never-seen and stale hosts are dead; fresh and clean-left are not."""
    clk = FakeClock()
    m0 = FleetMembership(tmp_path, 0, 4, clock=clk)
    m1 = FleetMembership(tmp_path, 1, 4, clock=clk)
    m2 = FleetMembership(tmp_path, 2, 4, clock=clk)
    m0.write("up")
    m1.write("up")
    m2.write("up")
    assert m0.dead_hosts() == [3]  # host 3: never seen
    # past the heartbeat bound (2.0s x 3 misses default) host 1 and 2 go
    # stale; host 0 keeps heartbeating; host 2 left CLEANLY first
    clk.advance(4.0)
    m2.write("left")
    clk.advance(100.0)
    m0.write("up")
    assert m0.dead_hosts() == [1, 3]
    assert m0.coordinator_alive()  # host 0 just heartbeat: alive
    desc = m0.describe()
    assert "host 3: NEVER SEEN" in desc and "host 2: left" in desc
    view = m0.view()
    assert sorted(view) == [0, 1, 2] and view[1]["status"] == "up"


def test_dead_coordinator_check_raises_loud(tmp_path, monkeypatch):
    """A survivor (rank != 0) diagnoses the dead coordinator instead of
    hanging: FleetWedgeError with the board, coordinator_loss artifact."""
    art = tmp_path / "flight"
    art.mkdir()
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(art))
    clk = FakeClock()
    board = tmp_path / "board"
    m0 = FleetMembership(board, 0, 2, clock=clk)
    m1 = FleetMembership(board, 1, 2, clock=clk)
    m0.write("up")
    assert m1.check(step=3) == []  # everyone fresh
    clk.advance(50.0)  # coordinator stops heartbeating
    with pytest.raises(FleetWedgeError, match="coordinator"):
        m1.check(step=4)
    arts = _artifacts(art, "coordinator_loss")
    assert len(arts) == 1
    snap = json.load(open(arts[0]))
    assert snap["extra"]["rank"] == 1 and 0 in snap["extra"]["dead"]
    # the coordinator ITSELF reports dead peers but never raises (check
    # above refreshed host 1's heartbeat; let it go stale again)
    clk.advance(50.0)
    assert m0.check(step=4) == [1]


def test_coordinator_loss_fault_injection(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "coordinator_loss@0")
    clk = FakeClock()
    m0 = FleetMembership(tmp_path, 0, 2, clock=clk)
    m1 = FleetMembership(tmp_path, 1, 2, clock=clk)
    m0.write("up")
    with pytest.raises(FleetWedgeError, match="coordinator"):
        m1.check(step=0)


def test_board_barrier_payload_exchange(tmp_path):
    clk = FakeClock()
    m0 = FleetMembership(tmp_path, 0, 2, clock=clk)
    m1 = FleetMembership(tmp_path, 1, 2, clock=clk)
    m1.write("up")
    # peer arrives first (its barrier file is already down)
    os.makedirs(os.path.join(str(tmp_path), "barrier_x"), exist_ok=True)
    fleet._atomic_write(
        os.path.join(str(tmp_path), "barrier_x", "host_1"),
        json.dumps({"rank": 1, "payload": [1.0, 2.0]}))
    got = m0.barrier("x", 10.0, payload=[3.0], clock=clk,
                     sleeper=clk.advance)
    assert got == {0: [3.0], 1: [1.0, 2.0]}


def test_board_barrier_deadline_names_missing_hosts(tmp_path):
    clk = FakeClock()
    m0 = FleetMembership(tmp_path, 0, 3, clock=clk)
    m0.write("up")
    with pytest.raises(FleetWedgeError, match=r"missing \[1, 2\]"):
        m0.barrier("b", 10.0, clock=clk, sleeper=clk.advance,
                   fail_on_dead=False)
    assert clk.t > 10.0  # it really waited out the (fake) deadline


def test_board_barrier_stale_peer_fails_early(tmp_path):
    """A peer whose heartbeat went stale mid-wait fails the barrier as
    soon as it is DIAGNOSED dead — not at the full deadline."""
    clk = FakeClock()
    m0 = FleetMembership(tmp_path, 0, 2, clock=clk)
    m1 = FleetMembership(tmp_path, 1, 2, clock=clk)
    m1.write("up")   # seen once...
    clk.advance(50.0)  # ...then silent far past the heartbeat bound
    m0.write("up")
    with pytest.raises(FleetWedgeError, match="died while the fleet"):
        m0.barrier("b", 1000.0, clock=clk, sleeper=clk.advance)
    assert clk.t < 60.0  # early: nowhere near the 1000s deadline


# ---------------------------------------------------- deadline bring-up
def test_run_with_deadline_paths():
    clk = FakeClock()
    # success
    assert fleet._run_with_deadline(
        lambda: 42, 1000.0, AssertionError,
        clock=clk, sleeper=clk.sleeper) == 42

    # the fn's own error is re-raised, not swallowed into a timeout
    def boom():
        raise ValueError("boom")
    with pytest.raises(ValueError, match="boom"):
        fleet._run_with_deadline(boom, 1000.0, AssertionError,
                                 clock=clk, sleeper=clk.sleeper)
    # a hang trips on_timeout at the (fake) deadline
    gate = threading.Event()
    t0 = clk.t
    try:
        with pytest.raises(FleetBringupError, match="stuck"):
            fleet._run_with_deadline(
                gate.wait, 5.0, lambda: FleetBringupError("stuck"),
                clock=clk, sleeper=clk.sleeper)
    finally:
        gate.set()
    assert clk.t - t0 > 5.0


def test_bringup_deadline_fails_loud_with_board(tmp_path, monkeypatch):
    """ISSUE-18 bring-up acceptance: a missing host fails the deadline
    LOUD with per-host status, instead of hanging the healthy host inside
    the rendezvous. Driven on CPU by forcing the rendezvous path."""
    art = tmp_path / "flight"
    art.mkdir()
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(art))
    monkeypatch.setattr(fleet, "_rendezvous_required", lambda: True)
    gate = threading.Event()
    from mxtpu import distributed
    monkeypatch.setattr(distributed, "init",
                        lambda **kw: (gate.wait(), (0, 2))[1])
    clk = FakeClock()
    board = tmp_path / "board"
    try:
        with pytest.raises(FleetBringupError, match="never joined"):
            fleet.init(fleet_dir=str(board), num_processes=2, process_id=0,
                       timeout_s=5.0, clock=clk, sleeper=clk.sleeper,
                       heartbeat=False)
    finally:
        gate.set()
    err = _artifacts(art, "fleet_bringup_timeout")
    assert len(err) == 1
    snap = json.load(open(err[0]))
    assert snap["extra"]["rank"] == 0 and snap["extra"]["world"] == 2
    # this host published "connecting" before blocking — the board shows
    # who to blame
    view = FleetMembership(board, 0, 2, clock=clk).view()
    assert view[0]["status"] == "connecting" and 1 not in view


def test_bringup_connect_retries_counted(tmp_path, monkeypatch):
    """Transient rendezvous failures retry with backoff under the ONE
    bring-up deadline, counted into retry.fleet_connect."""
    monkeypatch.setattr(fleet, "_rendezvous_required", lambda: True)
    calls = {"n": 0}

    def flaky_init(**kw):
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("coordinator not up yet")
        return (0, 1)
    from mxtpu import distributed
    monkeypatch.setattr(distributed, "init", flaky_init)
    clk = FakeClock()
    f = fleet.init(fleet_dir=str(tmp_path / "b"), num_processes=1,
                   process_id=0, timeout_s=300.0, clock=clk,
                   sleeper=clk.sleeper, rng=random.Random(0),
                   heartbeat=False)
    assert (f.rank, f.num_hosts) == (0, 1) and calls["n"] == 3
    assert _counter("retry.fleet_connect") == 2
    assert f.membership.view()[0]["status"] == "up"


def test_board_only_bringup_two_hosts_in_process(tmp_path, monkeypatch):
    """Forced-CPU tier: bring-up never touches jax.distributed (the
    board IS the rendezvous — global device ids would poison the warm
    compile cache), and both hosts meet at the bring-up barrier."""
    from mxtpu import distributed

    def banned(**kw):
        raise AssertionError("rendezvous must not run on the CPU tier")
    monkeypatch.setattr(distributed, "init", banned)
    board = str(tmp_path / "b")
    out = {}

    def bring(rankid):
        out[rankid] = fleet.init(fleet_dir=board, num_processes=2,
                                 process_id=rankid, timeout_s=60.0,
                                 heartbeat=False)
    ts = [threading.Thread(target=bring, args=(r,)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30.0)
    assert sorted(out) == [0, 1]
    f0, f1 = out[0], out[1]
    assert (f0.rank, f0.num_hosts) == (0, 2)
    # the per-host mesh covers this process's own devices only
    assert f0.mesh().devices.size >= 1
    # PR 9 sharding: per-host shards are a disjoint union of the keys
    keys = list(range(10))
    s0 = f0.data_shard(keys, shuffle=False)
    s1 = f1.data_shard(keys, shuffle=False)
    assert sorted(s0 + s1) == keys and not set(s0) & set(s1)
    f1.leave()
    assert f0.membership.view()[1]["status"] == "left"
    f0.leave()


def test_rejoin_stall_fault_exits_dedicated_code(tmp_path, monkeypatch):
    """Fault kind rejoin_stall@rank: the host publishes "stalled" on the
    board (its peers' deadline names it) and dies EXIT_REJOIN_STALL."""
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "rejoin_stall@1")

    def fake_exit(code):
        raise _Exit(code)
    monkeypatch.setattr(os, "_exit", fake_exit)
    with pytest.raises(_Exit) as ei:
        fleet.init(fleet_dir=str(tmp_path), num_processes=2, process_id=1,
                   timeout_s=1.0, _stall=lambda: None, heartbeat=False)
    assert ei.value.code == EXIT_REJOIN_STALL
    view = FleetMembership(tmp_path, 1, 2).view()
    assert view[1]["status"] == "stalled"


def test_maybe_host_loss_exits_41(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "host_loss@2")

    def fake_exit(code):
        raise _Exit(code)
    monkeypatch.setattr(os, "_exit", fake_exit)
    fleet.maybe_host_loss(0)
    fleet.maybe_host_loss(1)
    with pytest.raises(_Exit) as ei:
        fleet.maybe_host_loss(2)
    assert ei.value.code == EXIT_HOST_LOSS


# ------------------------------------------------- collective watchdog
def test_fleet_watchdog_trip_diagnoses_and_poisons(tmp_path, monkeypatch):
    art = tmp_path / "flight"
    art.mkdir()
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(art))
    clk = FakeClock()
    m0 = FleetMembership(tmp_path / "b", 0, 2, clock=clk)
    m1 = FleetMembership(tmp_path / "b", 1, 2, clock=clk)
    m1.write("up")
    exits = []
    wd = FleetCollectiveWatchdog(membership=m0, timeout_s=10.0, clock=clk,
                                 exit_on_trip=True, exit_fn=exits.append)
    e = wd.arm(7, what="step barrier")
    clk.advance(5.0)
    wd.disarm(e)  # in-bound: no trip
    wd.arm(8, what="step barrier")
    clk.advance(60.0)  # past the fixed deadline; peer 1 is stale too
    m0.write("up")
    with pytest.raises(FleetWedgeError, match="step 8 wedged"):
        wd.poll()
    assert exits == [EXIT_FLEET_WEDGE]
    assert _counter("fleet.wedges") == 1
    arts = _artifacts(art, "fleet_collective_wedge")
    assert len(arts) == 1
    snap = json.load(open(arts[0]))
    assert snap["extra"]["step"] == 8
    assert snap["extra"]["diagnosis"]["dead"] == [1]  # the diagnosis rode
    # the watchdog is poisoned: the next arm on this (dead) fleet refuses
    with pytest.raises(FleetWedgeError):
        wd.arm(9)


def test_fleet_watchdog_disabled_at_zero_timeout():
    wd = FleetCollectiveWatchdog(timeout_s=0)
    assert wd.arm(0) is None
    wd.disarm(None)
    wd.poll()  # never trips
    assert wd.start_monitor() is wd  # no thread either
    assert wd._monitor is None


def test_fleet_watchdog_monitor_lifecycle():
    wd = FleetCollectiveWatchdog(timeout_s=100.0)
    assert wd.start_monitor(0.01) is wd
    assert wd.start_monitor(0.01) is wd  # idempotent
    assert wd._monitor is not None and wd._monitor.is_alive()
    wd.stop_monitor()
    assert wd._monitor is None


# ----------------------------------------------------------- step barrier
def _peer_barrier_file(board, name, rank, payload):
    bdir = os.path.join(str(board), "barrier_%s" % name)
    os.makedirs(bdir, exist_ok=True)
    fleet._atomic_write(os.path.join(bdir, "host_%d" % rank),
                        json.dumps({"rank": rank, "payload": payload}))


def test_step_barrier_fingerprint_green_and_divergent(tmp_path,
                                                      monkeypatch):
    art = tmp_path / "flight"
    art.mkdir()
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(art))
    clk = FakeClock()
    board = tmp_path / "b"
    m0 = FleetMembership(board, 0, 2, clock=clk)
    FleetMembership(board, 1, 2, clock=clk).write("up")
    f = Fleet(0, 2, membership=m0, fleet_dir=str(board))
    # green: identical fingerprints on both hosts
    _peer_barrier_file(board, "step_3", 1, [1.5, 2.0])
    fps = f.step_barrier(3, fingerprint=[1.5, 2.0])
    assert fps == {0: [1.5, 2.0], 1: [1.5, 2.0]}
    assert _counter("resilience.divergence_checks") == 1
    # divergent: a forked replica fails the consistency gate LOUD
    _peer_barrier_file(board, "step_4", 1, [1.5, 999.0])
    with pytest.raises(resilience.DivergenceError, match="step 4"):
        f.step_barrier(4, fingerprint=[1.5, 2.0])
    arts = _artifacts(art, "fleet_divergence")
    assert len(arts) == 1
    snap = json.load(open(arts[0]))
    assert snap["extra"]["fingerprints"]["1"] == [1.5, 999.0]


def test_step_barrier_dead_peer_wedges_loud(tmp_path, monkeypatch):
    art = tmp_path / "flight"
    art.mkdir()
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(art))
    clk = FakeClock()
    board = tmp_path / "b"
    m0 = FleetMembership(board, 0, 2, clock=clk)
    FleetMembership(board, 1, 2, clock=clk).write("up")
    clk.advance(50.0)  # peer dies before reaching the step barrier
    m0.write("up")
    f = Fleet(0, 2, membership=m0, fleet_dir=str(board))
    with pytest.raises(FleetWedgeError, match="died while the fleet"):
        f.step_barrier(5, fingerprint=[1.0])
    assert _counter("fleet.wedges") == 1
    assert len(_artifacts(art, "fleet_collective_wedge")) == 1


# ------------------------------------------------------- fleet supervisor
def _supervisor(script, worlds, latest, **kw):
    """A FleetSupervisor wired subprocess- and sleep-free: ``script`` maps
    generation -> {rank: (rc, tail)}, ``worlds`` pins the expected world
    size per generation, ``latest`` is the checkpoint-step sequence."""
    seen = []
    latest_it = iter(latest)

    def launch(world, generation, extra_env):
        assert world == worlds[generation], (world, generation)
        seen.append(generation)
        return dict(script[generation])
    sup = FleetSupervisor(
        command_for=lambda r, w, g: ["unused"], launch=launch,
        clock=FakeClock(), sleeper=lambda s: None, rng=random.Random(0),
        latest_fn=lambda: next(latest_it), **kw)
    sup._seen = seen
    return sup


def test_supervisor_elastic_loss_then_warm_rejoin():
    """The scripted ISSUE-18 arc: gen0 loses host 1 (exit 41) and host 0
    wedges as its victim (exit 42) -> relaunch on world 1 -> gen1 crashes
    WITH progress -> grow back to full size -> gen2 exits clean."""
    sup = _supervisor(
        {0: {0: (EXIT_FLEET_WEDGE, ""), 1: (EXIT_HOST_LOSS, "")},
         1: {0: (EXIT_HOST_LOSS, "")},
         2: {0: (0, "ok"), 1: (0, "ok")}},
        worlds={0: 2, 1: 1, 2: 2},
        # _latest() is read at each launch AND after each crash:
        # gen0 launch None, gen0 crash 5, gen1 launch 5, gen1 crash 7
        # (progress!), gen2 launch 7
        latest=[None, 5, 5, 7, 7],
        num_hosts=2, min_hosts=1)
    results = sup.run()
    assert results == {0: (0, "ok"), 1: (0, "ok")}
    events = [h["event"] for h in sup.history]
    assert events == ["launch", "crash", "host_loss", "launch", "crash",
                      "rejoin_attempt", "launch", "clean_exit"]
    loss = next(h for h in sup.history if h["event"] == "host_loss")
    assert loss["ranks"] == [1] and loss["world"] == 1
    rejoin = next(h for h in sup.history if h["event"] == "rejoin_attempt")
    assert rejoin["world"] == 2 and rejoin["ckpt_step"] == 7
    assert sup.restarts == 2
    assert _counter("supervisor.restarts") == 2


def test_supervisor_all_victims_still_shrinks():
    """Every failure a wedge with nobody owning the death: the highest
    victim is treated as lost so the fleet cannot flap at a size that
    can never work."""
    sup = _supervisor(
        {0: {0: (EXIT_FLEET_WEDGE, ""), 1: ("timeout", "")},
         1: {0: (0, "")}},
        worlds={0: 2, 1: 1}, latest=[None, 3, 3],
        num_hosts=2, min_hosts=1)
    sup.run()
    loss = next(h for h in sup.history if h["event"] == "host_loss")
    assert loss["ranks"] == [1]  # the highest-ranked victim
    crash = next(h for h in sup.history if h["event"] == "crash")
    assert crash["victims"] == [0] and crash["lost"] == [1]


def test_supervisor_poison_crash_refuses_with_artifact(tmp_path,
                                                       monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    sup = _supervisor(
        {0: {0: (1, "")}, 1: {0: (1, "")}},
        worlds={0: 1, 1: 1}, latest=[3, 3, 3, 3],
        num_hosts=1)
    with pytest.raises(resilience.SupervisorRefusal, match="poison-crash"):
        sup.run()
    arts = _artifacts(tmp_path, "supervisor_refusal")
    assert len(arts) == 1
    snap = json.load(open(arts[0]))
    # the artifact carries the full membership-event history
    events = [h["event"] for h in snap["extra"]["history"]]
    assert events == ["launch", "crash", "launch", "crash"]
    assert "poison-crash" in snap["extra"]["diagnosis"]


def test_supervisor_crash_loop_budget_refuses(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    sup = _supervisor(
        {g: {0: (1, "")} for g in range(4)},
        worlds={g: 1 for g in range(4)},
        latest=[1, 2, 2, 3, 3, 4, 4],  # progress every time: never poison
        num_hosts=1, max_restarts=2)
    with pytest.raises(resilience.SupervisorRefusal, match="crash-loop"):
        sup.run()
    assert sup.restarts == 2
    assert len(_artifacts(tmp_path, "supervisor_refusal")) == 1


def test_launch_round_surfaces_exit_codes_and_timeouts():
    """Real children, hard-bounded: a quick exit surfaces its code and
    tail; a hang is killed and surfaced as "timeout" — never waited on
    unboundedly (the tier-1 budget depends on this)."""
    sup = FleetSupervisor(
        command_for=lambda r, w, g: [
            sys.executable, "-c",
            "import sys; print('tail-marker'); sys.exit(7)"],
        num_hosts=1, timeout_s=30.0)
    out = sup.launch_round(1, 0)
    assert out[0][0] == 7 and "tail-marker" in out[0][1]
    sup2 = FleetSupervisor(
        command_for=lambda r, w, g: [
            sys.executable, "-c", "import time; time.sleep(60)"],
        num_hosts=1, timeout_s=1.5)
    out2 = sup2.launch_round(1, 0)
    assert out2[0][0] == "timeout"


def test_launch_round_exports_env_bootstrap(tmp_path):
    """Children get the standard bootstrap: rank/world/coordinator plus a
    FRESH per-generation fleet board dir."""
    prog = ("import json, os; print('ENV ' + json.dumps("
            "{k: os.environ.get(k) for k in ('MXTPU_PROCESS_ID',"
            "'MXTPU_NUM_PROCESSES', 'MXTPU_COORDINATOR',"
            "'MXTPU_FLEET_DIR', 'EXTRA_MARK')}))")
    sup = FleetSupervisor(
        command_for=lambda r, w, g: [sys.executable, "-c", prog],
        num_hosts=2, fleet_dir=str(tmp_path / "board"), timeout_s=30.0,
        env_for=lambda r, w, g: {"EXTRA_MARK": "r%d" % r})
    out = sup.launch_round(2, 3)
    envs = {}
    for rank, (rc, tail) in out.items():
        assert rc == 0, tail
        envs[rank] = json.loads(
            [ln for ln in tail.splitlines()
             if ln.startswith("ENV ")][0][4:])
    assert envs[0]["MXTPU_PROCESS_ID"] == "0"
    assert envs[1]["MXTPU_PROCESS_ID"] == "1"
    assert envs[0]["MXTPU_NUM_PROCESSES"] == "2"
    assert envs[0]["MXTPU_COORDINATOR"] == envs[1]["MXTPU_COORDINATOR"]
    assert envs[0]["MXTPU_FLEET_DIR"].endswith("gen_3")
    assert envs[1]["EXTRA_MARK"] == "r1"


# --------------------------------------- bounded multi-process acceptance
@pytest.mark.multidevice
def test_fleet_kill_one_host_restore_acceptance(tmp_path):
    """ISSUE-18 acceptance, the bounded tier-1 spelling: a 2-host fleet
    loses host 1 mid-run (injected host_loss@1, exit 41), the survivor
    exits LOUD (42, diagnosed off the board), and the reshaped 1-host
    generation restores the last intact checkpoint and finishes clean —
    resuming at the kill step, never from scratch. Children carry hard
    timeouts; the full run is bounded by them."""
    worker = os.path.join(REPO, "tools", "fleet_worker.py")
    ckpt = str(tmp_path / "ckpt")
    steps = 3

    def command_for(rank, world, generation):
        return [sys.executable, worker, "--ckpt-dir", ckpt,
                "--steps", str(steps), "--devices", "1"]

    def env_for(rank, world, generation):
        env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=1",
               "MXTPU_FLEET_COLLECTIVE_TIMEOUT_S": "30"}
        if generation == 0 and rank == 1:
            env["MXTPU_FAULT_INJECT"] = "host_loss@1"
        return env

    sup = FleetSupervisor(
        command_for=command_for, num_hosts=2, min_hosts=1,
        ckpt_dir=ckpt, fleet_dir=str(tmp_path / "board"),
        timeout_s=240.0, env_for=env_for,
        sleeper=lambda s: None, rng=random.Random(0))
    results = sup.run()
    events = [h["event"] for h in sup.history]
    assert events[:3] == ["launch", "crash", "host_loss"], sup.history
    assert events[-1] == "clean_exit"
    crash = next(h for h in sup.history if h["event"] == "crash")
    assert crash["lost"] == [1], crash  # the injected death, exit 41
    assert crash["exit_codes"]["0"] in (EXIT_FLEET_WEDGE, "timeout"), crash
    # the surviving generation ran on the reshaped world and RESUMED
    assert sorted(results) == [0]
    rc, tail = results[0]
    assert rc == 0, tail
    rec = json.loads([ln for ln in tail.splitlines()
                      if ln.startswith("RESULT ")][0][len("RESULT "):])
    assert rec["world"] == 1
    assert rec["start"] >= 1, rec  # restored, not from scratch
    assert len(rec["losses"]) == steps - rec["start"]
    assert rec["divergence_checks"] >= 1  # the sentinel stayed armed
