"""Unified runtime telemetry (mxtpu/telemetry.py) — ISSUE 4:

* registry semantics: counters (tagged), gauges, histograms with
  quantiles, per-metric reset, MXTPU_TELEMETRY=0 span gating;
* step-phase timeline: spans present after a Trainer step, merged into
  profiler.dump()'s chrome trace with the op events;
* retrace watchdog: fires on an induced policy-flip recompile of the
  fused-update jit, stays silent across a schedule-only lr change;
* transfer watchdog: counts a forced d2h, reads ZERO for the guarded
  hot loop, warns once on a steady-state hot-span sync;
* adoption: pallas DISPATCH_STATS is a view over the registry, health
  monitor verdicts / retries / checkpoint latencies report through it;
* JSONL sink round-trips through tools/telemetry_report.py.
"""
import importlib.util
import json
import logging
import os
import sys

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import optimizer_fused as of
from mxtpu import profiler, resilience, telemetry
from mxtpu.gluon.parameter import Parameter
from mxtpu.gluon.trainer import Trainer


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_TELEMETRY", "MXTPU_TELEMETRY_FLUSH_S",
                "MXTPU_RETRACE_BUDGET", "MXTPU_NUMERICS_GUARD",
                "MXTPU_FAULT_INJECT", "MXTPU_FUSED_OPTIMIZER"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    of.reset()
    yield
    telemetry.reset()
    resilience.reset_faults()
    of.reset()


def _make_trainer(n_params=3, shape=(5,), optimizer="sgd", opt_params=None,
                  scaler=None, seed=0):
    rng = np.random.RandomState(seed)
    params = []
    for j in range(n_params):
        p = Parameter("tp%d" % j, shape=shape, dtype="float32")
        p.initialize()
        p.data()._set_data(mx.nd.array(
            rng.uniform(-1, 1, shape).astype(np.float32))._data)
        params.append(p)
    opt_params = opt_params or {"learning_rate": 0.05, "momentum": 0.9}
    tr = Trainer(params, optimizer, opt_params, kvstore=None,
                 loss_scaler=scaler)
    return tr, params, rng


def _set_grads(params, rng, scale=1.0):
    for p in params:
        p.grad()[:] = mx.nd.array(
            (rng.randn(*p.shape) * scale).astype(np.float32))


# ------------------------------------------------------- registry semantics
def test_counters_gauges_histograms():
    telemetry.inc("c.plain")
    telemetry.inc("c.plain", 4)
    telemetry.inc("c.tagged", tag="a")
    telemetry.inc("c.tagged", 2, tag="b")
    telemetry.gauge("g.one", 3.5)
    for v in range(1, 101):
        telemetry.observe("h.vals", float(v))
    assert telemetry.value("c.plain") == 5
    assert telemetry.value("c.tagged", tag="a") == 1
    assert telemetry.value("c.tagged") == 3  # sums tags when untagged absent
    assert telemetry.tagged("c.tagged") == {"a": 1, "b": 2}
    snap = telemetry.snapshot()
    assert snap["gauges"]["g.one"] == 3.5
    h = snap["histograms"]["h.vals"]
    assert h["count"] == 100 and h["min"] == 1.0 and h["max"] == 100.0
    assert abs(h["mean"] - 50.5) < 1e-9
    assert 49 <= h["p50"] <= 52
    assert 97 <= h["p99"] <= 100
    rep = telemetry.report()
    assert "c.tagged{a}" in rep and "h.vals" in rep
    telemetry.reset_metric("c.tagged")
    assert telemetry.tagged("c.tagged") == {}
    assert telemetry.value("c.plain") == 5  # untouched by per-metric reset


def test_span_disabled_by_env(monkeypatch):
    monkeypatch.setenv("MXTPU_TELEMETRY", "0")
    with telemetry.span("off.region"):
        pass
    assert "off.region" not in telemetry.snapshot()["histograms"]
    assert telemetry.events() == []
    # bare counters stay always-on (the DISPATCH_STATS-style views
    # must keep working under the lever)
    telemetry.inc("always.on")
    assert telemetry.value("always.on") == 1


# ----------------------------------------------------- step-phase timeline
def test_trainer_step_phases_recorded():
    tr, params, rng = _make_trainer()
    for _ in range(2):
        _set_grads(params, rng)
        tr.step(1)
    hists = telemetry.snapshot()["histograms"]
    for name in ("trainer.step", "trainer.step.allreduce",
                 "trainer.step.update"):
        assert hists[name]["count"] == 2, name
    names = {e[0] for e in telemetry.events()}
    assert "trainer.step" in names and "trainer.step.update" in names


def test_profiler_dump_merges_phase_events(tmp_path):
    fname = str(tmp_path / "trace.json")
    profiler.set_config(filename=fname)
    profiler.start()
    tr, params, rng = _make_trainer()
    _set_grads(params, rng)
    tr.step(1)
    profiler.stop()
    profiler.dump()
    with open(fname) as f:
        trace = json.load(f)
    phase = [e for e in trace["traceEvents"] if e["cat"] == "phase"]
    names = {e["name"] for e in phase}
    assert "trainer.step" in names and "trainer.step.update" in names
    for e in phase:  # same shape/conventions as the op events
        assert e["ph"] == "X" and e["pid"] == 0 and "tid" in e
    # the telemetry ring is always-on; the merge is scoped to the
    # profiled window — spans from before start() must not stretch the
    # trace's time axis across the whole process lifetime
    with telemetry.span("outside.window"):
        pass
    profiler.dump()
    with open(fname) as f:
        names2 = {e["name"] for e in json.load(f)["traceEvents"]}
    assert "outside.window" not in names2 and "trainer.step" in names2


def test_data_wait_span_recorded():
    from mxtpu.gluon import data as gdata
    ds = gdata.ArrayDataset(mx.nd.array(
        np.arange(20, dtype=np.float32).reshape(10, 2)))
    loader = gdata.DataLoader(ds, batch_size=5)
    n = sum(1 for _ in loader)
    assert n == 2
    hists = telemetry.snapshot()["histograms"]
    assert hists["data.wait"]["count"] >= 2


# ------------------------------------------------------- retrace watchdog
def test_retrace_watchdog_fires_on_policy_flip(monkeypatch, caplog):
    """A guard-policy flip recompiles the fused-update jit exactly once —
    with MXTPU_RETRACE_BUDGET below that second compile, the watchdog
    must fire and carry the cache-key provenance."""
    monkeypatch.setenv("MXTPU_RETRACE_BUDGET", "1")
    tr, params, rng = _make_trainer(optimizer="adam",
                                    opt_params={"learning_rate": 0.01})
    _set_grads(params, rng)
    tr.step(1)
    assert telemetry.value("retrace.watchdog_trips") == 0  # warmup compile
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")  # induced policy flip
    with caplog.at_level(logging.WARNING, logger="mxtpu.telemetry"):
        _set_grads(params, rng)
        tr.step(1)
    assert of.FUSED_STATS["compiles"] == 2
    assert telemetry.value("retrace.watchdog_trips") == 1
    st = telemetry.retrace_stats("fused_optimizer")
    assert st["compiles"] == 2 and st["trips"] == 1
    assert st["last"]["optimizer"] == "Adam" and st["last"]["guard"] is True
    assert "policy_key" in st["last"]
    assert any("retrace watchdog" in r.message for r in caplog.records)


def test_retrace_watchdog_silent_on_lr_schedule(monkeypatch, caplog):
    """Schedule-only hyper movement is traced, never recompiles, never
    trips the watchdog — even with the tightest budget."""
    monkeypatch.setenv("MXTPU_RETRACE_BUDGET", "1")
    tr, params, rng = _make_trainer(optimizer="adam",
                                    opt_params={"learning_rate": 0.01})
    with caplog.at_level(logging.WARNING, logger="mxtpu.telemetry"):
        for i in range(4):
            tr.set_learning_rate(0.01 / (i + 1))  # schedule-only change
            _set_grads(params, rng)
            tr.step(1)
    assert of.FUSED_STATS["compiles"] == 1
    assert telemetry.value("retrace.watchdog_trips") == 0
    assert not any("retrace watchdog" in r.message for r in caplog.records)


def test_cached_op_retrace_provenance(monkeypatch):
    """CachedOp compiles report through the same watchdog with policy
    provenance; a steady-state re-call adds nothing."""
    from mxtpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(2, 6).astype(np.float32))
    net(x)
    net.hybridize()
    net(x)
    compiles = telemetry.value("retrace.cached_op")
    assert compiles >= 1
    net(x)  # steady state: cache hit
    assert telemetry.value("retrace.cached_op") == compiles
    st = telemetry.retrace_stats("cached_op")
    assert "policy_key" in st["last"]


# ------------------------------------------------------ transfer watchdog
def test_transfer_watchdog_counts_forced_d2h():
    arr = mx.nd.ones((4,))
    c0 = telemetry.d2h_count()
    arr.asnumpy()
    assert telemetry.d2h_count() == c0 + 1
    float(arr.sum())  # asscalar routes through asnumpy too
    assert telemetry.d2h_count() == c0 + 2


def test_guarded_hot_loop_step_d2h_is_zero():
    """The acceptance contract read off the registry instead of a
    transfer guard: steady-state guarded Trainer.steps attribute ZERO
    d2h syncs to the step span."""
    scaler = resilience.DynamicLossScaler(init_scale=4.0)
    tr, params, rng = _make_trainer(optimizer="adam",
                                    opt_params={"learning_rate": 0.01},
                                    scaler=scaler)
    for _ in range(4):
        _set_grads(params, rng)
        ok = tr.step(1)
        assert ok is not None  # verdict handed back, NOT fetched
    assert telemetry.snapshot()["histograms"]["trainer.step"]["count"] == 4
    assert telemetry.value("trainer.step.d2h") == 0


def test_transfer_watchdog_warns_on_steady_state_sync(caplog):
    arr = mx.nd.ones((4,))
    with caplog.at_level(logging.WARNING, logger="mxtpu.telemetry"):
        for _ in range(4):
            with telemetry.span("hot.region", d2h=True):
                arr.asnumpy()
    assert telemetry.value("hot.region.d2h") == 4
    warns = [r for r in caplog.records
             if "transfer watchdog" in r.message]
    assert len(warns) == 1  # warns ONCE, past the warmup occurrences


# ------------------------------------------------------- adopted stats
def test_dispatch_stats_is_view_over_registry():
    import jax.numpy as jnp
    from mxtpu.ops.pallas import conv as pc
    pc.reset_dispatch_stats()
    w = jnp.zeros((3, 3, 4, 8), jnp.float32)
    out = pc.fused_conv(jnp.ones((1, 5, 5, 4)), w, (1, 1), ((1, 1), (1, 1)))
    assert out.shape == (1, 5, 5, 8)
    # off-TPU without the interpreter: counted XLA fallback
    assert telemetry.value("pallas_conv.xla") == 1
    assert any("platform" in r
               for r in telemetry.tagged("pallas_conv.fallback"))
    # the module-level dict is a THIN VIEW over the same registry entries
    assert pc.DISPATCH_STATS["xla"] == 1
    assert pc.DISPATCH_STATS["pallas"] == 0
    assert pc.DISPATCH_STATS["fallback_reasons"] == \
        telemetry.tagged("pallas_conv.fallback")
    assert set(pc.DISPATCH_STATS.keys()) == \
        {"pallas", "xla", "fallback_reasons"}
    pc.reset_dispatch_stats()
    assert pc.DISPATCH_STATS["xla"] == 0
    assert pc.DISPATCH_STATS["fallback_reasons"] == {}


def test_health_monitor_emits_through_telemetry(monkeypatch):
    from mxtpu.monitor import TrainingHealthMonitor
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@1")
    scaler = resilience.DynamicLossScaler(init_scale=8.0)
    tr, params, rng = _make_trainer(scaler=scaler)
    mon = TrainingHealthMonitor(interval=3).install(tr)
    for _ in range(3):
        _set_grads(params, rng)
        tr.step(1)
        mon.after_step()
    assert telemetry.value("resilience.steps_ok") == 2
    assert telemetry.value("resilience.steps_skipped") == 1
    gauges = telemetry.snapshot()["gauges"]
    assert "resilience.grad_norm" in gauges
    assert gauges["resilience.loss_scale"] == 4.0  # backed off once
    # the report shows guard activity without a log scrape
    assert "resilience.steps_skipped" in telemetry.report()


def test_retry_counters():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient")
        return "ok"

    out = resilience.with_retries(flaky, "test op", retries=2,
                                  backoff=0.001, metric="retry.test_site")
    assert out == "ok"
    assert telemetry.value("retry.total") == 1
    assert telemetry.value("retry.test_site") == 1


def test_checkpoint_save_latency_recorded(tmp_path):
    from mxtpu.contrib import async_checkpoint as ackpt
    tr, params, rng = _make_trainer()
    _set_grads(params, rng)
    tr.step(1)
    ackpt.save_trainer(tr, str(tmp_path), step=0)
    snap = telemetry.snapshot()
    assert snap["histograms"]["checkpoint.save_s"]["count"] == 1
    assert telemetry.value("checkpoint.saves") == 1


def test_fault_injection_counted(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@0")
    tr, params, rng = _make_trainer(
        optimizer="adam", opt_params={"learning_rate": 0.01})
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    _set_grads(params, rng)
    tr.step(1)
    assert telemetry.tagged("faults.injected") == {"nan_grad": 1}


# ------------------------------------------------------------ JSONL sink
def _report_mod():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                        "telemetry_report.py")
    spec = importlib.util.spec_from_file_location("telemetry_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_jsonl_sink_roundtrips_through_report(tmp_path, monkeypatch):
    sink = str(tmp_path / "tel.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY", sink)
    for v in range(1, 101):
        telemetry.observe("span.x", float(v))
    telemetry.inc("count.y", 7)
    telemetry.gauge("gauge.z", 2.25)
    telemetry.flush()
    telemetry.flush()  # counters repeat per flush; report must not double
    rep = _report_mod()
    summary = rep.aggregate(rep.load(sink))
    assert summary["span.x"]["count"] == 100
    assert abs(summary["span.x"]["mean"] - 50.5) < 1e-9
    assert 49 <= summary["span.x"]["p50"] <= 52
    assert 97 <= summary["span.x"]["p99"] <= 100
    assert summary["count.y"]["value"] == 7
    assert summary["gauge.z"]["value"] == 2.25
    table = rep.format_table(summary)
    assert "span.x" in table and "count.y" in table
    assert rep.main([sink]) == 0  # the CLI path runs clean too


def test_report_counters_fold_across_process_restarts(tmp_path):
    """perf_battery shares ONE sink file across several sessions, each
    restarting its cumulative counters at 0 — the report must bank each
    session (Prometheus reset semantics), not take the max."""
    sink = str(tmp_path / "multi.jsonl")
    with open(sink, "w") as f:
        for v in (2, 5):      # session A flushes twice, ends at 5
            f.write(json.dumps({"t": 1, "kind": "counter",
                                "metric": "retry.total", "value": v}) + "\n")
        for v in (1, 3):      # session B restarts at 0, ends at 3
            f.write(json.dumps({"t": 2, "kind": "counter",
                                "metric": "retry.total", "value": v}) + "\n")
    rep = _report_mod()
    summary = rep.aggregate(rep.load(sink))
    assert summary["retry.total"]["value"] == 8  # 5 + 3, not max(5, 3)
    assert rep.main(["--json"]) == 1  # flags-only invocation: usage, rc 1


def test_mixed_tag_and_untagged_counter_survives_snapshot():
    telemetry.inc("mix.c", 2)
    telemetry.inc("mix.c", 3, tag="a")
    snap = telemetry.snapshot()["counters"]["mix.c"]
    assert snap == {"_untagged": 2, "a": 3}  # neither form dropped


def test_jsonl_sink_tolerates_torn_line(tmp_path, monkeypatch):
    sink = str(tmp_path / "torn.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY", sink)
    telemetry.observe("m.a", 1.0)
    telemetry.flush()
    with open(sink, "a") as f:
        f.write('{"t": 1, "kind": "obs", "metric": "m.a", "va')  # torn
    rep = _report_mod()
    summary = rep.aggregate(rep.load(sink))
    assert summary["m.a"]["count"] == 1
