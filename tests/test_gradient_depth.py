"""Numeric-gradient DEPTH tier: finite-difference checks across the op
families the reference grinds through check_numeric_gradient in
tests/python/unittest/test_operator.py. tests/test_operator.py spot-checks
a handful; this module sweeps the ops whose vjp rules are hand-written or
structurally risky (norm layers, indexing, orderings, contractions,
losses), each at small shapes so central differences stay cheap.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.test_utils import check_numeric_gradient

RNG = np.random.RandomState


def _u(shape, lo=-1.0, hi=1.0, seed=0):
    return RNG(seed).uniform(lo, hi, shape).astype(np.float32)


# ------------------------------------------------------------ norm layers
def test_layernorm_grad_data_gamma_beta():
    x = _u((3, 8), seed=1)
    g = _u((8,), 0.5, 1.5, seed=2)
    b = _u((8,), seed=3)
    check_numeric_gradient(
        lambda x_, g_, b_: mx.nd.LayerNorm(x_, g_, b_, axis=-1, eps=1e-4),
        [x, g, b], rtol=2e-2, atol=2e-3)


def test_batchnorm_train_grad_wrt_data():
    from mxtpu import autograd as ag
    x = _u((4, 3, 5), seed=4)
    gamma = _u((3,), 0.5, 1.5, seed=5)
    beta = _u((3,), seed=6)
    mm, mv = np.zeros(3, np.float32), np.ones(3, np.float32)

    def f(x_):
        with ag.record(train_mode=True):
            return mx.nd.BatchNorm(x_, mx.nd.array(gamma), mx.nd.array(beta),
                                   mx.nd.array(mm), mx.nd.array(mv),
                                   eps=1e-4, fix_gamma=False)
    check_numeric_gradient(f, [x], rtol=3e-2, atol=3e-3)


def test_instancenorm_and_lrn_grad():
    x = _u((2, 3, 4, 4), 0.1, 1.0, seed=7)
    g = _u((3,), 0.5, 1.5, seed=8)
    b = _u((3,), seed=9)
    # random head grad: with an all-ones head the normalization's
    # mean-invariance makes the true gradient degenerately ~0, and the
    # check compares rounding noise against rounding noise
    hg = _u((2, 3, 4, 4), 0.2, 1.0, seed=40)
    check_numeric_gradient(
        lambda x_: mx.nd.InstanceNorm(x_, mx.nd.array(g), mx.nd.array(b),
                                      eps=1e-4),
        [x], rtol=3e-2, atol=3e-3, head_grad=hg)
    check_numeric_gradient(lambda x_: mx.nd.LRN(x_, nsize=3), [x],
                           rtol=2e-2, atol=2e-3, head_grad=hg)


def test_l2_normalization_grad():
    x = _u((3, 6), 0.2, 1.0, seed=10)
    check_numeric_gradient(
        lambda x_: mx.nd.L2Normalization(x_, mode="instance"), [x],
        rtol=2e-2, atol=2e-3)


# --------------------------------------------------------------- softmax
@pytest.mark.parametrize("axis", [0, -1])
def test_softmax_logsoftmax_grad(axis):
    x = _u((4, 5), -2, 2, seed=11)
    check_numeric_gradient(lambda x_: mx.nd.softmax(x_, axis=axis), [x],
                           rtol=2e-2, atol=2e-3)
    check_numeric_gradient(lambda x_: mx.nd.log_softmax(x_, axis=axis), [x],
                           rtol=2e-2, atol=2e-3)


def test_softmax_temperature_grad():
    x = _u((3, 6), -2, 2, seed=12)
    check_numeric_gradient(
        lambda x_: mx.nd.softmax(x_, temperature=3.0), [x],
        rtol=2e-2, atol=2e-3)


# ------------------------------------------------------------ contraction
def test_dot_transpose_grads():
    a = _u((3, 4), seed=13)
    b = _u((3, 5), seed=14)
    check_numeric_gradient(
        lambda a_, b_: mx.nd.dot(a_, b_, transpose_a=True), [a, b],
        rtol=2e-2, atol=2e-3)


def test_batch_dot_grad():
    a = _u((2, 3, 4), seed=15)
    b = _u((2, 4, 2), seed=16)
    check_numeric_gradient(lambda a_, b_: mx.nd.batch_dot(a_, b_), [a, b],
                           rtol=2e-2, atol=2e-3)


def test_fully_connected_grad_all_inputs():
    x = _u((4, 6), seed=17)
    w = _u((3, 6), seed=18)
    b = _u((3,), seed=19)
    check_numeric_gradient(
        lambda x_, w_, b_: mx.nd.FullyConnected(x_, w_, b_, num_hidden=3),
        [x, w, b], rtol=2e-2, atol=2e-3)


# -------------------------------------------------------------- indexing
def test_take_and_embedding_grad():
    w = _u((7, 4), seed=20)
    idx = np.array([1, 3, 1, 6], np.float32)
    check_numeric_gradient(
        lambda w_: mx.nd.take(w_, mx.nd.array(idx), axis=0), [w],
        rtol=2e-2, atol=2e-3)
    check_numeric_gradient(
        lambda w_: mx.nd.Embedding(mx.nd.array(idx), w_, input_dim=7,
                                   output_dim=4), [w],
        rtol=2e-2, atol=2e-3)


def test_gather_nd_grad():
    x = _u((4, 5), seed=21)
    ind = mx.nd.array(np.array([[0, 2, 3], [1, 4, 0]], np.float32))
    check_numeric_gradient(lambda x_: mx.nd.gather_nd(x_, ind), [x],
                           rtol=2e-2, atol=2e-3)


def test_slice_pad_reverse_grads():
    x = _u((3, 6), seed=22)
    check_numeric_gradient(
        lambda x_: mx.nd.slice(x_, begin=(1, 0), end=(3, 6), step=(1, 2)),
        [x], rtol=2e-2, atol=2e-3)
    check_numeric_gradient(
        lambda x_: mx.nd.reverse(x_, axis=1), [x], rtol=2e-2, atol=2e-3)
    x4 = _u((1, 2, 3, 3), seed=23)
    check_numeric_gradient(
        lambda x_: mx.nd.pad(x_, mode="edge",
                             pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
        [x4], rtol=2e-2, atol=2e-3)


# ------------------------------------------------------- pick / orderings
def test_pick_grad():
    x = _u((4, 5), seed=24)
    idx = mx.nd.array(np.array([0, 2, 4, 1], np.float32))
    check_numeric_gradient(lambda x_: mx.nd.pick(x_, idx, axis=1), [x],
                           rtol=2e-2, atol=2e-3)


def test_topk_value_and_sort_grads():
    # unique, well-separated entries so finite differences don't cross
    # the permutation's decision boundary
    x = (np.arange(12, dtype=np.float32).reshape(3, 4) * 0.37 + 0.1)
    x = RNG(25).permutation(x.ravel()).reshape(3, 4)
    check_numeric_gradient(
        lambda x_: mx.nd.topk(x_, k=2, ret_typ="value"), [x],
        rtol=2e-2, atol=2e-3, eps=1e-2)
    check_numeric_gradient(
        lambda x_: mx.nd.sort(x_, axis=1), [x],
        rtol=2e-2, atol=2e-3, eps=1e-2)


# ----------------------------------------------------------------- losses
def test_softmax_cross_entropy_grad():
    x = _u((4, 5), -2, 2, seed=26)
    lbl = mx.nd.array(np.array([0, 2, 4, 1], np.float32))
    check_numeric_gradient(
        lambda x_: mx.nd.softmax_cross_entropy(x_, lbl), [x],
        rtol=2e-2, atol=2e-3)


def test_smooth_l1_and_huber_region_grads():
    # straddle the |x|=1 kink on purpose (away from the kink pointwise)
    x = np.array([[-2.3, -0.4, 0.6, 1.9]], np.float32)
    check_numeric_gradient(lambda x_: mx.nd.smooth_l1(x_, scalar=1.0), [x],
                           rtol=2e-2, atol=2e-3)


def test_gluon_loss_grads():
    from mxtpu import gluon
    pred = _u((4, 3), -2, 2, seed=27)
    lbl_cls = mx.nd.array(np.array([0, 2, 1, 2], np.float32))
    lbl_reg = mx.nd.array(_u((4, 3), seed=28))
    for loss_blk, lbl in [
            (gluon.loss.SoftmaxCrossEntropyLoss(), lbl_cls),
            (gluon.loss.L2Loss(), lbl_reg),
            (gluon.loss.HuberLoss(rho=0.7), lbl_reg),
            (gluon.loss.LogisticLoss(), mx.nd.array(
                np.sign(_u((4, 3), seed=29)))),
    ]:
        check_numeric_gradient(lambda p_: loss_blk(p_, lbl), [pred],
                               rtol=2e-2, atol=2e-3)


# ------------------------------------------------------------ activations
@pytest.mark.parametrize("act", ["relu", "sigmoid", "tanh", "softrelu",
                                 "softsign"])
def test_activation_grads(act):
    # keep away from relu's kink at 0
    x = _u((3, 4), 0.2, 1.5, seed=30) * np.sign(_u((3, 4), seed=31) + 0.2)
    x[np.abs(x) < 0.05] = 0.5
    check_numeric_gradient(lambda x_: mx.nd.Activation(x_, act_type=act),
                           [x], rtol=2e-2, atol=2e-3)


def test_leaky_variants_grad():
    x = _u((3, 4), 0.2, 1.5, seed=32) * np.sign(_u((3, 4), seed=33) + 0.3)
    x[np.abs(x) < 0.05] = -0.5
    for act, kw in [("leaky", {"slope": 0.1}), ("elu", {"slope": 0.3}),
                    ("selu", {})]:
        check_numeric_gradient(
            lambda x_, act=act, kw=kw: mx.nd.LeakyReLU(x_, act_type=act,
                                                       **kw),
            [x], rtol=2e-2, atol=2e-3)


# --------------------------------------------------------- linalg / misc
def test_linalg_grads():
    a = _u((3, 3), seed=34)
    spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
    check_numeric_gradient(lambda x_: mx.nd.linalg_potrf(x_), [spd],
                           rtol=3e-2, atol=3e-3)
    x = _u((3, 4), seed=35)
    check_numeric_gradient(
        lambda x_: mx.nd.linalg_syrk(x_, transpose=False, alpha=0.5), [x],
        rtol=2e-2, atol=2e-3)


def test_where_and_clip_grads():
    c = mx.nd.array((RNG(36).uniform(size=(3, 4)) > 0.5)
                    .astype(np.float32))
    a = _u((3, 4), seed=37)
    b = _u((3, 4), seed=38)
    check_numeric_gradient(lambda a_, b_: mx.nd.where(c, a_, b_), [a, b],
                           rtol=2e-2, atol=2e-3)
    x = _u((3, 4), -2, 2, seed=39)
    x[np.abs(np.abs(x) - 1.0) < 0.1] = 0.0  # keep away from clip edges
    check_numeric_gradient(
        lambda x_: mx.nd.clip(x_, a_min=-1.0, a_max=1.0), [x],
        rtol=2e-2, atol=2e-3)
