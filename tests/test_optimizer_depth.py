"""Optimizer-zoo DEPTH tier: every optimizer's multi-step trajectory vs an
independent NumPy reimplementation of the published update rule — the
reference's tests/python/unittest/test_optimizer.py pattern (each Py*
NumPy optimizer mirrors the C++ kernel and trajectories must match).

Each oracle below is written from the algorithm (paper/reference
semantics: clip(rescale*grad) then +wd*w unless the rule handles wd
specially), NOT from mxtpu's jnp kernels — matching trajectories over 5
steps therefore checks the kernels AND the class wiring (update counts,
bias-correction schedules, state creation, Updater plumbing).
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import optimizer as opt

RNG = np.random.RandomState
STEPS = 5
SHAPE = (4, 7)


def run_traj(optimizer, seed=0, steps=STEPS, dtype=np.float32):
    """Drive the real Updater with a fixed grad sequence; return weights."""
    rng = RNG(seed)
    w0 = rng.uniform(-1, 1, SHAPE).astype(dtype)
    grads = [rng.uniform(-1, 1, SHAPE).astype(dtype) for _ in range(steps)]
    w = mx.nd.array(w0.copy())
    upd = opt.get_updater(optimizer)
    for g in grads:
        upd(0, mx.nd.array(g), w)
    return w0, grads, w.asnumpy()


def _prep(g, w, rescale=1.0, clip=None, wd=0.0):
    g = g * rescale
    if clip:
        g = np.clip(g, -clip, clip)
    return g + wd * w


def test_sgd_momentum_wd_oracle():
    lr, mom, wd = 0.1, 0.9, 0.01
    w0, grads, got = run_traj(opt.SGD(learning_rate=lr, momentum=mom, wd=wd))
    w = w0.copy().astype(np.float64)
    m = np.zeros_like(w)
    for g in grads:
        m = mom * m - lr * _prep(g.astype(np.float64), w, wd=wd)
        w = w + m
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_sgd_rescale_and_clip_oracle():
    lr = 0.2
    o = opt.SGD(learning_rate=lr, rescale_grad=0.5, clip_gradient=0.3)
    w0, grads, got = run_traj(o)
    w = w0.copy().astype(np.float64)
    for g in grads:
        w = w - lr * _prep(g.astype(np.float64), w, rescale=0.5, clip=0.3)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_nag_oracle():
    lr, mom = 0.05, 0.9
    w0, grads, got = run_traj(opt.NAG(learning_rate=lr, momentum=mom))
    w = w0.copy().astype(np.float64)
    m = np.zeros_like(w)
    for g in grads:
        g = g.astype(np.float64)
        m = mom * m + g
        w = w - lr * (g + mom * m)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_signum_oracle():
    lr, mom = 0.01, 0.9
    w0, grads, got = run_traj(opt.Signum(learning_rate=lr, momentum=mom))
    w = w0.copy().astype(np.float64)
    m = np.zeros_like(w)
    for g in grads:
        m = mom * m - (1 - mom) * g.astype(np.float64)
        w = w + lr * np.sign(m)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_adam_bias_correction_oracle():
    lr, b1, b2, eps, wd = 0.01, 0.9, 0.999, 1e-8, 0.02
    w0, grads, got = run_traj(opt.Adam(learning_rate=lr, wd=wd))
    w = w0.copy().astype(np.float64)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t, g in enumerate(grads, 1):
        g = _prep(g.astype(np.float64), w, wd=wd)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        w = w - lr_t * m / (np.sqrt(v) + eps)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_adagrad_oracle():
    lr, eps = 0.1, 1e-7
    w0, grads, got = run_traj(opt.AdaGrad(learning_rate=lr))
    w = w0.copy().astype(np.float64)
    h = np.zeros_like(w)
    for g in grads:
        g = g.astype(np.float64)
        h = h + g * g
        w = w - lr * g / np.sqrt(h + eps)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_rmsprop_plain_and_centered_oracles():
    lr, g1, g2, eps = 0.01, 0.9, 0.9, 1e-8
    w0, grads, got = run_traj(opt.RMSProp(learning_rate=lr, gamma1=g1))
    w = w0.copy().astype(np.float64)
    n = np.zeros_like(w)
    for g in grads:
        g = g.astype(np.float64)
        n = (1 - g1) * g * g + g1 * n
        w = w - lr * g / np.sqrt(n + eps)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)

    w0, grads, got = run_traj(opt.RMSProp(learning_rate=lr, gamma1=g1,
                                          gamma2=g2, centered=True))
    w = w0.copy().astype(np.float64)
    n = np.zeros_like(w)
    ga = np.zeros_like(w)
    d = np.zeros_like(w)
    for g in grads:
        g = g.astype(np.float64)
        n = (1 - g1) * g * g + g1 * n
        ga = (1 - g1) * g + g1 * ga
        d = g2 * d - lr * g / np.sqrt(n - ga * ga + eps)
        w = w + d
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_adadelta_oracle():
    rho, eps = 0.9, 1e-5
    w0, grads, got = run_traj(opt.AdaDelta(rho=rho, epsilon=eps))
    w = w0.copy().astype(np.float64)
    ag = np.zeros_like(w)
    ad = np.zeros_like(w)
    for g in grads:
        g = g.astype(np.float64)
        ag = rho * ag + (1 - rho) * g * g
        delta = np.sqrt(ad + eps) / np.sqrt(ag + eps) * g
        ad = rho * ad + (1 - rho) * delta * delta
        w = w - delta
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_ftrl_oracle():
    lr, l1, beta, wd = 0.1, 0.01, 1.0, 0.05
    w0, grads, got = run_traj(opt.Ftrl(learning_rate=lr, lamda1=l1,
                                       beta=beta, wd=wd))
    w = w0.copy().astype(np.float64)
    z = np.zeros_like(w)
    n = np.zeros_like(w)
    for g in grads:
        g = g.astype(np.float64)
        n_new = n + g * g
        sigma = (np.sqrt(n_new) - np.sqrt(n)) / lr
        z = z + g - sigma * w
        n = n_new
        w = np.where(np.abs(z) > l1,
                     -(z - np.sign(z) * l1)
                     / ((beta + np.sqrt(n)) / lr + wd), 0.0)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_adamax_oracle():
    lr, b1, b2 = 0.002, 0.9, 0.999
    w0, grads, got = run_traj(opt.Adamax(learning_rate=lr))
    w = w0.copy().astype(np.float64)
    m = np.zeros_like(w)
    u = np.zeros_like(w)
    for t, g in enumerate(grads, 1):
        g = g.astype(np.float64)
        m = b1 * m + (1 - b1) * g
        u = np.maximum(b2 * u, np.abs(g))
        w = w - (lr / (1 - b1 ** t)) * m / (u + 1e-8)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_nadam_oracle():
    lr, b1, b2, eps, sd = 0.001, 0.9, 0.999, 1e-8, 0.004
    w0, grads, got = run_traj(opt.Nadam(learning_rate=lr))
    w = w0.copy().astype(np.float64)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    m_sched = 1.0
    for t, g in enumerate(grads, 1):
        g = g.astype(np.float64)
        mom_t = b1 * (1 - 0.5 * 0.96 ** (t * sd))
        mom_t1 = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * sd))
        m_sched *= mom_t
        m_sched_next = m_sched * mom_t1
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        g_p = g / (1 - m_sched)
        m_p = m / (1 - m_sched_next)
        v_p = v / (1 - b2 ** t)
        m_bar = (1 - mom_t) * g_p + mom_t1 * m_p
        w = w - lr * m_bar / (np.sqrt(v_p) + eps)
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_ftml_oracle():
    lr, b1, b2, eps = 0.01, 0.6, 0.999, 1e-8
    w0, grads, got = run_traj(opt.FTML(learning_rate=lr))
    w = w0.copy().astype(np.float64)
    d = np.zeros_like(w)
    v = np.zeros_like(w)
    z = np.zeros_like(w)
    for t, g in enumerate(grads, 1):
        g = g.astype(np.float64)
        v = b2 * v + (1 - b2) * g * g
        d_new = (1 - b1 ** t) / lr * (np.sqrt(v / (1 - b2 ** t)) + eps)
        sigma = d_new - b1 * d
        z = b1 * z + (1 - b1) * g - sigma * w
        d = d_new
        w = -z / d
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


def test_dcasgd_oracle():
    lr, lam = 0.05, 0.04
    w0, grads, got = run_traj(opt.DCASGD(learning_rate=lr, lamda=lam))
    w = w0.copy().astype(np.float64)
    prev = w.copy()
    for g in grads:
        g = g.astype(np.float64)
        comp = g + lam * g * g * (w - prev)
        prev = w.copy()
        w = w - lr * comp
    np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)


# ----------------------------------------------------------- class wiring
def test_lr_wd_mult_per_param():
    """set_lr_mult/set_wd_mult by name must scale ONLY the matching index
    (ref: optimizer.py lr_mult machinery driven by param_idx2name)."""
    lr = 0.1
    o = opt.SGD(learning_rate=lr, param_idx2name={0: "a", 1: "b"})
    o.set_lr_mult({"a": 0.0})
    upd = opt.get_updater(o)
    wa = mx.nd.array(np.ones((2, 2), np.float32))
    wb = mx.nd.array(np.ones((2, 2), np.float32))
    g = mx.nd.array(np.ones((2, 2), np.float32))
    upd(0, g, wa)
    upd(1, g, wb)
    np.testing.assert_allclose(wa.asnumpy(), 1.0)          # frozen via mult
    np.testing.assert_allclose(wb.asnumpy(), 1.0 - lr)


def test_multi_precision_bf16_matches_f32_master():
    """multi_precision: bf16 weights update through an f32 master copy,
    so 5 steps stay close to the pure-f32 trajectory (plain bf16 updates
    drift much further)."""
    lr, mom = 0.1, 0.9
    w0, grads, w_f32 = run_traj(opt.SGD(learning_rate=lr, momentum=mom))

    o = opt.SGD(learning_rate=lr, momentum=mom, multi_precision=True)
    w = mx.nd.array(w0.copy()).astype("bfloat16")
    state = o.create_state_multi_precision(0, w)
    for g in grads:
        o.update_multi_precision(0, w, mx.nd.array(g).astype("bfloat16"),
                                 state)
    got = w.asnumpy().astype(np.float32)
    # bf16 has ~3 decimal digits; master-copy keeps the trajectory tight
    np.testing.assert_allclose(got, w_f32, rtol=2e-2, atol=2e-2)


def test_updater_serialization_roundtrip():
    """dump_optimizer=True round-trips the optimizer too (update counts
    drive Adam's bias correction), so the resumed trajectory is exact —
    the reference's Trainer.save_states behavior. Without it only the
    state tensors travel and a FRESH optimizer restarts t at 1."""
    o = opt.Adam(learning_rate=0.01)
    upd = opt.get_updater(o)
    w = mx.nd.array(RNG(1).uniform(-1, 1, SHAPE).astype(np.float32))
    for i in range(3):
        upd(0, mx.nd.array(RNG(i + 2).uniform(-1, 1, SHAPE)
                           .astype(np.float32)), w)
    blob = upd.get_states(dump_optimizer=True)
    upd2 = opt.get_updater(opt.Adam(learning_rate=0.01))
    upd2.set_states(blob)
    w2 = mx.nd.array(w.asnumpy())
    g = mx.nd.array(RNG(9).uniform(-1, 1, SHAPE).astype(np.float32))
    upd(0, g, w)
    upd2(0, g, w2)
    np.testing.assert_allclose(w.asnumpy(), w2.asnumpy(), rtol=1e-6)


def test_create_by_name_covers_zoo():
    for name in ("sgd", "nag", "signum", "adam", "adagrad", "rmsprop",
                 "adadelta", "ftrl", "adamax", "nadam", "ftml", "dcasgd",
                 "sgld", "lbsgd"):
        o = opt.create(name)
        assert isinstance(o, opt.Optimizer), name
