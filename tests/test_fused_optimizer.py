"""Fused whole-model optimizer step (mxtpu/optimizer_fused.py):

ONE donated jit per Trainer.step instead of 3-10 eager dispatches per
parameter. Pinned here: fused-vs-eager numerical parity for EVERY
registered optimizer (f32 and bf16), the jit-cache contract (an lr change
or batch-size change must NOT retrace), exactly one compiled update call
per step on a >=50-parameter model, and the eager fallbacks (sparse grads,
MXTPU_FUSED_OPTIMIZER=0, unfusable optimizers).
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import optimizer as opt
from mxtpu import optimizer_fused as of
from mxtpu.gluon.parameter import Parameter
from mxtpu.gluon.trainer import Trainer

STEPS = 4
SHAPES = [(4, 3), (7,), (2, 5)]
ALL_OPTIMIZERS = sorted(opt.Optimizer.opt_registry)


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    monkeypatch.delenv("MXTPU_FUSED_OPTIMIZER", raising=False)
    of.reset()
    yield
    of.reset()


def _make_params(rng, shapes=SHAPES, dtype="float32"):
    ws = []
    for s in shapes:
        w = mx.nd.array(rng.uniform(-1, 1, s).astype(np.float32))
        ws.append(w.astype(dtype) if dtype != "float32" else w)
    return ws


def _run_traj(name, fused, monkeypatch, dtype="float32", **opt_kw):
    """Drive update_batch for STEPS steps; return final weights."""
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1" if fused else "0")
    mx.random.seed(7)  # SGLD draws noise: both paths must see one stream
    o = opt.create(name, learning_rate=0.05, wd=0.01, **opt_kw)
    upd = opt.get_updater(o)
    rng = np.random.RandomState(3)
    ws = _make_params(rng, dtype=dtype)
    for _ in range(STEPS):
        gs = _make_params(rng, dtype=dtype)
        upd.update_batch(list(range(len(ws))), gs, ws)
    return [w.asnumpy() for w in ws]


@pytest.mark.parametrize("name", ALL_OPTIMIZERS)
def test_fused_eager_parity_f32(name, monkeypatch):
    got = _run_traj(name, True, monkeypatch)
    want = _run_traj(name, False, monkeypatch)
    for x, y in zip(got, want):
        np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("name", ALL_OPTIMIZERS)
def test_fused_eager_parity_bf16(name, monkeypatch):
    got = _run_traj(name, True, monkeypatch, dtype="bfloat16")
    want = _run_traj(name, False, monkeypatch, dtype="bfloat16")
    for x, y in zip(got, want):
        np.testing.assert_allclose(x, y, rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("name", ["sgd", "adam"])
def test_fused_multi_precision_parity(name, monkeypatch):
    """bf16 weights + f32 master copy: the fused step must reproduce the
    eager update_multi_precision path (master updated in f32, storage
    recast to bf16)."""
    kw = {"momentum": 0.9} if name == "sgd" else {}
    got = _run_traj(name, True, monkeypatch, dtype="bfloat16",
                    multi_precision=True, **kw)
    fused_steps = of.FUSED_STATS["fused_steps"]
    assert fused_steps == STEPS  # the mp path really fused
    want = _run_traj(name, False, monkeypatch, dtype="bfloat16",
                     multi_precision=True, **kw)
    assert of.FUSED_STATS["fused_steps"] == fused_steps  # env=0 was eager
    for x, y in zip(got, want):
        np.testing.assert_allclose(x, y, rtol=2e-2, atol=2e-2)


def _trainer_with(n_params, optimizer="sgd", opt_params=None, shape=(11,)):
    rng = np.random.RandomState(0)
    params = []
    for j in range(n_params):
        p = Parameter("fp%d" % j, shape=shape, dtype="float32")
        p.initialize()
        p.grad()[:] = mx.nd.array(rng.randn(*shape).astype(np.float32))
        params.append(p)
    opt_params = opt_params or {"learning_rate": 0.1, "momentum": 0.9}
    return Trainer(params, optimizer, opt_params, kvstore=None), params


def test_one_compiled_call_per_step_on_50_plus_params(monkeypatch):
    """The acceptance criterion: Trainer.step on a >=50-parameter model is
    exactly ONE compiled update invocation per step — no per-param
    dispatches, no per-step retraces."""
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1")
    tr, params = _trainer_with(60)
    of.reset()
    for _ in range(3):
        tr.step(1)
    assert of.FUSED_STATS["fused_steps"] == 3
    assert of.FUSED_STATS["traces"] == 1  # compiled once, reused per step
    assert of.FUSED_STATS["eager_updates"] == 0
    assert of.cache_size() == 1


def test_lr_and_batch_change_do_not_recompile(monkeypatch):
    """lr (schedules!) and rescale_grad=1/batch are traced scalars: moving
    them must reuse the ONE cached executable."""
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1")
    tr, params = _trainer_with(5)
    of.reset()
    tr.step(1)
    assert of.FUSED_STATS["traces"] == 1 and of.cache_size() == 1
    tr.set_learning_rate(0.001)
    tr.step(1)
    tr.step(8)  # batch-size change -> different rescale_grad
    assert of.FUSED_STATS["traces"] == 1
    assert of.FUSED_STATS["compiles"] == 1
    assert of.cache_size() == 1
    assert of.FUSED_STATS["fused_steps"] == 3


def test_sparse_grads_fall_back_to_eager(monkeypatch):
    """row_sparse grads take the lazy eager update; dense params in the
    same batch still fuse."""
    from mxtpu.ndarray.sparse import row_sparse_array
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1")
    o = opt.SGD(learning_rate=0.1)
    upd = opt.get_updater(o)
    rng = np.random.RandomState(2)
    w_dense = mx.nd.array(rng.randn(4, 3).astype(np.float32))
    w_sparse = mx.nd.array(rng.randn(6, 3).astype(np.float32))
    w_sparse_ref = w_sparse.asnumpy().copy()
    g_dense = mx.nd.array(rng.randn(4, 3).astype(np.float32))
    rows_data = rng.randn(2, 3).astype(np.float32)
    g_sparse = row_sparse_array((rows_data, [1, 4]), shape=(6, 3))
    of.reset()
    upd.update_batch([0, 1], [g_dense, g_sparse], [w_dense, w_sparse])
    assert of.FUSED_STATS["eager_updates"] == 1  # the sparse one
    assert of.FUSED_STATS["fused_steps"] == 1    # the dense one still fused
    # lazy semantics preserved: untouched rows did not move
    got = w_sparse.asnumpy()
    np.testing.assert_allclose(got[[0, 2, 3, 5]],
                               w_sparse_ref[[0, 2, 3, 5]])
    assert not np.allclose(got[[1, 4]], w_sparse_ref[[1, 4]])


def test_env_escape_hatch_forces_eager(monkeypatch):
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "0")
    tr, params = _trainer_with(4)
    of.reset()
    tr.step(1)
    assert of.FUSED_STATS["fused_steps"] == 0
    assert of.FUSED_STATS["eager_updates"] == 4


def test_tied_parameters_fall_back_per_item(monkeypatch):
    """Two Parameters sharing one buffer would donate it twice — those
    items route to the eager loop; the rest of the batch fuses."""
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1")
    o = opt.SGD(learning_rate=0.1)
    upd = opt.get_updater(o)
    rng = np.random.RandomState(5)
    w0 = mx.nd.array(rng.randn(3).astype(np.float32))
    w_tied = mx.nd.NDArray(w0._data)  # same jax buffer
    w1 = mx.nd.array(rng.randn(3).astype(np.float32))
    gs = [mx.nd.array(rng.randn(3).astype(np.float32)) for _ in range(3)]
    of.reset()
    upd.update_batch([0, 1, 2], gs, [w0, w_tied, w1])
    assert of.FUSED_STATS["fused_steps"] == 1   # w1 alone still fuses
    assert of.FUSED_STATS["eager_updates"] == 2  # the whole alias group
    w0.asnumpy()  # both halves of the tie stay readable (nothing donated)
    w_tied.asnumpy()


def test_kvstore_grouped_push_fuses(monkeypatch):
    """The local kvstore's store-side update (set_optimizer + grouped push)
    rides the same ONE-jit path."""
    from mxtpu import kvstore as kv_mod
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1")
    kv = kv_mod.create("local")
    kv.set_optimizer(opt.SGD(learning_rate=0.1, momentum=0.9))
    rng = np.random.RandomState(4)
    keys = list(range(6))
    ws = [mx.nd.array(rng.randn(5).astype(np.float32)) for _ in keys]
    for k, w in zip(keys, ws):
        kv.init(k, w)
    gs = [mx.nd.array(rng.randn(5).astype(np.float32)) for _ in keys]
    of.reset()
    kv.push(keys, gs)
    assert of.FUSED_STATS["fused_steps"] == 1
    assert of.FUSED_STATS["eager_updates"] == 0
    outs = [mx.nd.zeros((5,)) for _ in keys]
    kv.pull(keys, outs)
    # sanity: the store moved (one SGD step applied)
    assert not np.allclose(outs[0].asnumpy(), ws[0].asnumpy())


def test_pulled_arrays_survive_store_side_fused_update(monkeypatch):
    """pull() must hand out the caller's OWN buffer: the store-side fused
    update DONATES store weights on the next push, which would delete a
    zero-copy alias (real deletion on TPU; pinned here structurally)."""
    from mxtpu import kvstore as kv_mod
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1")
    kv = kv_mod.create("local")
    kv.set_optimizer(opt.SGD(learning_rate=0.1))
    w = mx.nd.array(np.ones(4, np.float32))
    kv.init(0, w)
    pulled = mx.nd.zeros((4,))
    kv.pull(0, pulled)
    assert pulled._data is not kv._store["0"]._data  # no escaping alias
    kv.push(0, mx.nd.array(np.full(4, 0.5, np.float32)))
    np.testing.assert_allclose(pulled.asnumpy(), 1.0)  # survives donation
    after = mx.nd.zeros((4,))
    kv.pull(0, after)
    np.testing.assert_allclose(after.asnumpy(), 0.95)  # 1 - 0.1*0.5


def test_set_data_source_survives_fused_step(monkeypatch):
    """Parameter.set_data must not alias the caller's array: the next
    fused step donates the parameter buffer, which would delete the
    caller's copy."""
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1")
    p = Parameter("sd", shape=(5,), dtype="float32")
    p.initialize()
    src = mx.nd.array(np.full(5, 2.0, np.float32))
    p.set_data(src)
    assert p.data()._data is not src._data
    p.grad()[:] = mx.nd.array(np.ones(5, np.float32))
    tr = Trainer([p], "sgd", {"learning_rate": 0.1}, kvstore=None)
    tr.step(1)
    np.testing.assert_allclose(src.asnumpy(), 2.0)   # caller's array alive
    np.testing.assert_allclose(p.data().asnumpy(), 1.9)


def test_nadam_mixed_batch_keeps_eager_order(monkeypatch):
    """Nadam's m_schedule is order-dependent host state: a batch mixing
    fused-eligible and eager-bound (here: tied/aliased) items must
    reproduce the pure eager trajectory exactly — the whole batch runs
    eagerly in index order."""

    def run(fused):
        monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1" if fused else "0")
        o = opt.create("nadam", learning_rate=0.05)
        upd = opt.get_updater(o)
        rng = np.random.RandomState(9)
        tied = mx.nd.array(rng.randn(5, 3).astype(np.float32))
        ws = [tied, mx.nd.NDArray(tied._data),  # alias group -> eager
              mx.nd.array(rng.randn(5, 3).astype(np.float32))]
        for _ in range(3):
            gs = [mx.nd.array(rng.randn(5, 3).astype(np.float32))
                  for _ in ws]
            upd.update_batch([0, 1, 2], gs, ws)
        return [w.asnumpy() for w in ws], o.m_schedule

    (got, ms_f), (want, ms_e) = run(True), run(False)
    assert ms_f == ms_e  # identical host-side schedule bookkeeping
    for x, y in zip(got, want):
        np.testing.assert_allclose(x, y, rtol=1e-6, atol=1e-7)


def test_set_optimizer_after_aliasing_push_re_owns_store(monkeypatch):
    """A no-updater push stores the caller's buffer as-is (hot-path cheap);
    installing the fused updater must then RE-OWN stored buffers, or the
    next push would donate — delete — an array the caller still holds."""
    from mxtpu import kvstore as kv_mod
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1")
    kv = kv_mod.create("local")
    w = mx.nd.array(np.ones(4, np.float32))
    kv.init(0, w)
    g = mx.nd.array(np.full(4, 2.0, np.float32))
    kv.push(0, g)  # no updater yet: store takes the merged value as-is
    kv.set_optimizer(opt.SGD(learning_rate=0.1))
    kv.push(0, mx.nd.array(np.full(4, 0.5, np.float32)))  # donates store
    np.testing.assert_allclose(g.asnumpy(), 2.0)  # caller's buffer alive


def test_updater_states_roundtrip_across_fused_steps(monkeypatch):
    """get_states/set_states must serialize fused-updated state identically
    to eager state (same trajectory after a save/load)."""
    monkeypatch.setenv("MXTPU_FUSED_OPTIMIZER", "1")
    o = opt.create("adam", learning_rate=0.01)
    upd = opt.get_updater(o)
    rng = np.random.RandomState(6)
    ws = _make_params(rng)
    upd.update_batch([0, 1, 2], _make_params(rng), ws)
    blob = upd.get_states()
    o2 = opt.create("adam", learning_rate=0.01)
    o2._index_update_count = dict(o._index_update_count)
    o2.num_update = o.num_update
    upd2 = opt.get_updater(o2)
    upd2.set_states(blob)
    gs = _make_params(rng)
    ws_a = [mx.nd.array(w.asnumpy()) for w in ws]
    ws_b = [mx.nd.array(w.asnumpy()) for w in ws]
    upd.update_batch([0, 1, 2], gs, ws_a)
    upd2.update_batch([0, 1, 2], gs, ws_b)
    for a, b in zip(ws_a, ws_b):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                   rtol=1e-6, atol=1e-7)
