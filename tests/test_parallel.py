"""Tests for mxtpu.parallel — run on the 8-device virtual CPU mesh (conftest),
the analog of the reference's multi-process-localhost distributed tests
(SURVEY §4: tests/nightly/dist_sync_kvstore.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import mxtpu as mx
from mxtpu import gluon
from mxtpu.gluon import nn
from mxtpu.parallel import (ShardedTrainStep, data_parallel_mesh, make_mesh,
                            pure_forward, ring_self_attention)
from mxtpu.parallel.ring_attention import _dense_attention

pytestmark = pytest.mark.multidevice


def test_make_mesh():
    mesh = make_mesh({"data": 2, "sp": 2, "model": 2})
    assert mesh.shape == {"data": 2, "sp": 2, "model": 2}
    mesh = make_mesh({"data": -1})
    assert mesh.shape["data"] == 8
    with pytest.raises(ValueError):
        make_mesh({"data": 16})


def _mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"))
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(4))
    net.initialize()
    return net


def test_pure_forward_matches_eager():
    net = _mlp()
    x = mx.nd.random.uniform(shape=(8, 10))
    eager = net(x).asnumpy()
    fn, params = pure_forward(net)
    out = jax.jit(fn)(params, x._data)
    np.testing.assert_allclose(np.asarray(out), eager, rtol=1e-5, atol=1e-5)


def test_sharded_train_step_dp_matches_single_device():
    """DP over 8 devices must match the single-logical-device update exactly
    (the reference's check_consistency cross-device comparison pattern)."""
    np.random.seed(0)
    x = np.random.uniform(size=(16, 10)).astype(np.float32)
    y = np.random.randint(0, 4, size=(16,)).astype(np.float32)

    def build():
        mx.random.seed(0)
        net = _mlp()
        net(mx.nd.array(x))  # settle shapes
        return net

    loss = gluon.loss.SoftmaxCrossEntropyLoss()

    # reference: plain autograd + Trainer on one device
    ref = build()
    trainer = gluon.Trainer(ref.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    for _ in range(3):
        with mx.autograd.record():
            l = loss(ref(mx.nd.array(x)), mx.nd.array(y))
        l.backward()
        # backward() of the (batch,)-shaped loss seeds ones => d sum(l_i);
        # step(batch) rescales to d mean(l_i), matching the sharded step
        trainer.step(16)
        ref_loss = l.mean().asnumpy()

    # sharded: same model, same data, 8-way DP
    net = build()
    mesh = data_parallel_mesh()
    step = ShardedTrainStep(net, loss, mesh, optimizer="sgd",
                            optimizer_params={"learning_rate": 0.1,
                                              "momentum": 0.9})
    for _ in range(3):
        sharded_loss = step(mx.nd.array(x), mx.nd.array(y)).asnumpy()

    np.testing.assert_allclose(sharded_loss, ref_loss, rtol=1e-4, atol=1e-5)
    for p_ref, p_new in zip(ref.collect_params().values(),
                            net.collect_params().values()):
        np.testing.assert_allclose(p_new.data().asnumpy(),
                                   p_ref.data().asnumpy(),
                                   rtol=1e-4, atol=1e-5)


def test_sharded_train_step_tp():
    """Tensor-parallel placement: weights sharded over the model axis still
    produce the same loss trajectory as replicated."""
    np.random.seed(0)
    x = np.random.uniform(size=(8, 16)).astype(np.float32)
    y = np.random.randint(0, 8, size=(8,)).astype(np.float32)
    loss = gluon.loss.SoftmaxCrossEntropyLoss()

    def run(param_specs):
        mx.random.seed(0)
        net = nn.HybridSequential(prefix="tp_")
        with net.name_scope():
            net.add(nn.Dense(64, activation="relu"))
            net.add(nn.Dense(8))
        net.initialize()
        net(mx.nd.array(x))
        mesh = make_mesh({"data": 2, "model": 4})
        step = ShardedTrainStep(net, loss, mesh,
                                optimizer_params={"learning_rate": 0.05},
                                param_specs=param_specs)
        out = [step(mx.nd.array(x), mx.nd.array(y)).asnumpy() for _ in range(3)]
        return out

    replicated = run(())
    # Dense weight is [units, in]: shard the output dim (column parallel)
    sharded = run([(r".*dense0_weight", P("model", None)),
                   (r".*dense0_bias", P("model"))])
    np.testing.assert_allclose(sharded, replicated, rtol=1e-4, atol=1e-6)


def test_batchnorm_aux_updates_in_sharded_step():
    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16))
        net.add(nn.BatchNorm())
        net.add(nn.Dense(4))
    net.initialize()
    x = mx.nd.random.uniform(shape=(16, 8))
    y = mx.nd.zeros((16,))
    net(x)
    bn_mean_before = [p.data().asnumpy().copy()
                      for n, p in net.collect_params().items()
                      if "running_mean" in n][0]
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = ShardedTrainStep(net, loss, data_parallel_mesh())
    step(x, y)
    bn_mean_after = [p.data().asnumpy()
                     for n, p in net.collect_params().items()
                     if "running_mean" in n][0]
    assert not np.allclose(bn_mean_before, bn_mean_after)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    """Ring attention over a 4-way sequence shard == dense attention."""
    np.random.seed(0)
    b, h, t, d = 2, 4, 32, 8
    q = jnp.asarray(np.random.normal(size=(b, h, t, d)).astype(np.float32))
    k = jnp.asarray(np.random.normal(size=(b, h, t, d)).astype(np.float32))
    v = jnp.asarray(np.random.normal(size=(b, h, t, d)).astype(np.float32))
    dense = _dense_attention(q, k, v, causal=causal)
    mesh = make_mesh({"data": 2, "sp": 4})
    ring = ring_self_attention(q, k, v, mesh=mesh, seq_axis="sp",
                               batch_axis="data", causal=causal)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_match_dense():
    np.random.seed(1)
    b, h, t, d = 1, 2, 16, 4
    q = jnp.asarray(np.random.normal(size=(b, h, t, d)).astype(np.float32))
    k = jnp.asarray(np.random.normal(size=(b, h, t, d)).astype(np.float32))
    v = jnp.asarray(np.random.normal(size=(b, h, t, d)).astype(np.float32))
    mesh = make_mesh({"sp": 4})

    def loss_dense(q, k, v):
        return jnp.sum(_dense_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        out = ring_self_attention(q, k, v, mesh=mesh, seq_axis="sp",
                                  causal=True)
        return jnp.sum(out ** 2)

    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for gd, gr in zip(g_dense, g_ring):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=2e-4, atol=2e-5)


def test_sharded_dropout_decorrelated_across_shards():
    """Parallel-PRNG story (ref kParallelRandom, src/resource.cc:87;
    mxtpu/random.py docstring): a dropout mask drawn over a batch-sharded
    tensor must be distinct on every data shard — GSPMD partitions the
    generator over the global shape, so no per-device PRNG resource is
    needed."""
    from jax.sharding import NamedSharding
    from mxtpu.ops.nn import Dropout
    from mxtpu import autograd
    from mxtpu.ndarray import NDArray

    mesh = make_mesh({"data": 8})
    x = jnp.ones((8, 4096), jnp.float32)
    x = jax.device_put(x, NamedSharding(mesh, P("data")))
    prev = autograd.set_training(True)
    try:
        out = Dropout(NDArray(x), p=0.5)
    finally:
        autograd.set_training(prev)
    mask = np.asarray(out.asnumpy() != 0)
    rows = [mask[i] for i in range(8)]
    # each device's row must not equal any other's (same-key-per-shard
    # implementations fail this with probability ~1)
    for i in range(8):
        for j in range(i + 1, 8):
            assert not np.array_equal(rows[i], rows[j])


def test_zero1_sharded_weight_update_matches_replicated():
    """shard_weight_update=True (ZeRO-1, arXiv:2004.13336): optimizer state
    is sharded over the data axis, the loss trajectory is unchanged, and
    the state arrays are REALLY sharded (memory claim is structural)."""
    def build():
        np.random.seed(0)
        mx.random.seed(0)  # parameter init draws from the jax PRNG
        net = nn.HybridSequential()
        net.add(nn.Dense(64, activation="relu"), nn.Dense(16))
        net.initialize()
        x = mx.nd.array(np.random.randn(16, 32).astype(np.float32))
        y = mx.nd.array(np.random.randint(0, 16, (16,)).astype(np.float32))
        net(x)
        return net, x, y

    mesh = make_mesh({"data": 8})
    losses = {}
    for zero1 in (False, True):
        net, x, y = build()
        step = ShardedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                mesh, optimizer="sgd",
                                optimizer_params={"learning_rate": 0.1,
                                                  "momentum": 0.9},
                                shard_weight_update=zero1)
        ls = [float(step(x, y).asnumpy()) for _ in range(5)]
        losses[zero1] = ls
        if zero1:
            # states live in the rule registry's structure (None | array |
            # tuple) since the optimizer adapters merged with optimizer_fused
            momenta = [s for st in step._opt_states
                       for s in jax.tree_util.tree_leaves(st)]
            sharded = [m for m in momenta
                       if any(ax is not None for ax in m.sharding.spec)]
            assert sharded, "no optimizer state was actually sharded"
            for m in sharded:
                assert m.sharding.spec[0] == "data"
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5,
                               atol=1e-6)


def test_pipeline_apply_matches_sequential_fwd_and_grad():
    """GPipe-style pipeline over pipe x data (mxtpu/parallel/pipeline.py —
    beyond-reference feature, SURVEY §2.3 'Parallelism NOT present'):
    forward and grads must equal the sequential layer stack."""
    from jax.sharding import Mesh
    from mxtpu.parallel import pipeline_apply

    rng = np.random.RandomState(0)
    n_layers, d = 8, 16
    params = {"w": jnp.asarray(rng.randn(n_layers, d, d) * 0.2, jnp.float32),
              "b": jnp.asarray(rng.randn(n_layers, d) * 0.1, jnp.float32)}

    def layer(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    x = jnp.asarray(rng.randn(32, d), jnp.float32)
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("pipe", "data"))

    def seq(params, x):
        h, _ = jax.lax.scan(lambda h, p: (layer(p, h), None), x, params)
        return h

    out = pipeline_apply(layer, params, x, mesh, axis="pipe",
                         num_microbatches=8, batch_axis="data")
    np.testing.assert_allclose(np.asarray(out), np.asarray(seq(params, x)),
                               rtol=1e-5, atol=1e-5)

    g_pipe = jax.grad(lambda p: jnp.sum(pipeline_apply(
        layer, p, x, mesh, axis="pipe", num_microbatches=8,
        batch_axis="data") ** 2))(params)
    g_seq = jax.grad(lambda p: jnp.sum(seq(p, x) ** 2))(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]), rtol=1e-4,
                                   atol=1e-5)


def test_pipeline_apply_validations():
    from jax.sharding import Mesh
    from mxtpu.parallel import pipeline_apply

    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("pipe", "data"))
    params = {"w": jnp.zeros((6, 4, 4))}  # 6 layers over 4 stages: invalid
    with pytest.raises(mx.MXNetError, match="must divide"):
        pipeline_apply(lambda p, h: h, params, jnp.zeros((8, 4)), mesh)
    params = {"w": jnp.zeros((4, 4, 4))}
    with pytest.raises(mx.MXNetError, match="microbatches"):
        pipeline_apply(lambda p, h: h, params, jnp.zeros((9, 4)), mesh,
                       num_microbatches=4)


def test_switch_moe_dense_and_expert_parallel_parity():
    """Top-1 switch MoE (mxtpu/parallel/moe.py — beyond-reference):
    einsum-dispatch output must equal a per-token reference, on one device
    AND with experts sharded over an expert mesh axis."""
    from jax.sharding import Mesh, NamedSharding
    from mxtpu.parallel import shard_experts, switch_ffn

    rng = np.random.RandomState(0)
    T, D, H, E = 32, 8, 16, 4
    x = jnp.asarray(rng.randn(T, D), jnp.float32)
    router = jnp.asarray(rng.randn(D, E) * 0.5, jnp.float32)
    w1 = jnp.asarray(rng.randn(E, D, H) * 0.2, jnp.float32)
    b1 = jnp.zeros((E, H), jnp.float32)
    w2 = jnp.asarray(rng.randn(E, H, D) * 0.2, jnp.float32)
    b2 = jnp.zeros((E, D), jnp.float32)

    out, aux = switch_ffn(x, router, w1, b1, w2, b2, capacity_factor=4.0)
    logits = np.asarray(x @ router)
    probs = np.exp(logits - logits.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    ref = np.zeros((T, D), np.float32)
    for t in range(T):
        e_i = int(np.argmax(probs[t]))
        h = np.maximum(np.asarray(x[t]) @ np.asarray(w1[e_i]), 0)
        ref[t] = (h @ np.asarray(w2[e_i])) * probs[t].max()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)
    assert float(aux) >= 1.0  # Switch aux loss lower bound at balance

    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("expert", "data"))
    params = shard_experts({"w1": w1, "b1": b1, "w2": w2, "b2": b2}, mesh,
                           num_experts=E)
    assert params["w1"].sharding.spec == P("expert")

    @jax.jit
    def run(x, router, p):
        return switch_ffn(x, router, p["w1"], p["b1"], p["w2"], p["b2"],
                          4.0)[0]

    x_sh = jax.device_put(x, NamedSharding(mesh, P("data")))
    np.testing.assert_allclose(np.asarray(run(x_sh, router, params)), ref,
                               rtol=1e-4, atol=1e-5)


def test_switch_moe_capacity_drops_tokens():
    from mxtpu.parallel import switch_ffn

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(32, 8), jnp.float32)
    router = jnp.asarray(rng.randn(8, 4) * 0.5, jnp.float32)
    w1 = jnp.asarray(rng.randn(4, 8, 16) * 0.2, jnp.float32)
    w2 = jnp.asarray(rng.randn(4, 16, 8) * 0.2, jnp.float32)
    out, _ = switch_ffn(x, router, w1, jnp.zeros((4, 16)), w2,
                        jnp.zeros((4, 8)), capacity_factor=0.25)
    dropped = int((np.abs(np.asarray(out)).sum(1) == 0).sum())
    assert dropped > 0  # over-capacity tokens are zeroed (Switch semantics)


def test_moe_transformer_lm_trains_expert_parallel():
    """Zoo TransformerLM(num_experts=4) under an expert x data sharded
    train step: the Switch aux loss joins the objective inside the trace
    and the loss decreases."""
    from mxtpu.gluon.model_zoo.transformer import (TransformerLM,
                                                   expert_parallel_rules)

    mx.random.seed(0)
    vocab = 64
    net = TransformerLM(vocab_size=vocab, dim=32, num_heads=4, num_layers=2,
                        max_len=64, num_experts=4)
    net.initialize()
    rng = np.random.RandomState(0)
    tokens = mx.nd.array(rng.randint(0, vocab, (4, 16)), dtype="int32")
    labels = mx.nd.array(rng.randint(0, vocab, (4, 16)), dtype="float32")
    net(tokens)
    assert float(net.aux_loss().asnumpy()) >= 1.0  # eager aux available

    loss_blk = gluon.loss.SoftmaxCrossEntropyLoss()

    def forward(block, tokens, labels):
        ce = loss_blk(block(tokens).reshape((-1, vocab)),
                      labels.reshape((-1,)))
        return ce + 0.01 * block.aux_loss()

    mesh = make_mesh({"data": 2, "expert": 4})
    step = ShardedTrainStep(net, None, mesh, optimizer="adam",
                            optimizer_params={"learning_rate": 1e-3},
                            param_specs=expert_parallel_rules("expert"),
                            batch_specs=[P("data"), P("data")],
                            forward=forward)
    l1 = float(step(tokens, labels).asnumpy())
    for _ in range(3):
        l2 = float(step(tokens, labels).asnumpy())
    assert l2 < l1
    # the expert weights really live on the expert axis
    moe_w1 = [d for p, d in zip(step._params, step._param_datas)
              if p.name.endswith("moe_w1")]
    assert moe_w1 and moe_w1[0].sharding.spec[0] == "expert"


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_matches_dense(causal):
    """The flash-bodied ring (per-step fused blocks merged via lse) must
    reproduce full dense attention over the sharded sequence, forward AND
    gradients (the merge + whole-block visibility selects + g_lse path)."""
    from mxtpu.parallel.ring_attention import (_dense_attention,
                                               ring_flash_attention)

    mesh = make_mesh({"sp": 4})
    B, H, T, D = 2, 2, 32, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    spec = P(None, None, "sp", None)

    def ring(q_, k_, v_):
        from mxtpu.parallel.shmap import shard_map
        body = lambda a, b, c: ring_flash_attention(  # noqa: E731
            a, b, c, axis_name="sp", causal=causal)
        return shard_map(body, mesh=mesh, in_specs=(spec,) * 3,
                         out_specs=spec, check_vma=False)(q_, k_, v_)

    out = ring(q, k, v)
    ref = _dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    g = jnp.asarray(rng.randn(B, H, T, D).astype(np.float32))
    grads = jax.grad(lambda q_, k_, v_: jnp.sum(ring(q_, k_, v_) * g),
                     argnums=(0, 1, 2))(q, k, v)
    ref_grads = jax.grad(
        lambda q_, k_, v_: jnp.sum(
            _dense_attention(q_, k_, v_, causal=causal) * g),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_ring_self_attention_flash_switch(monkeypatch):
    """MXTPU_RING_FLASH=1 routes ring_self_attention through the flash
    body with identical numerics."""
    mesh = make_mesh({"sp": 4})
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    base = ring_self_attention(q, q, q, mesh=mesh, causal=True)
    monkeypatch.setenv("MXTPU_RING_FLASH", "1")
    flash = ring_self_attention(q, q, q, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(base),
                               rtol=2e-4, atol=2e-4)


def test_sharded_train_step_policy_flip_recompiles(monkeypatch):
    """A registry.policy_key lever flip must rebuild the step executable
    (otherwise the trainer silently reuses an executable traced under the
    stale policy — the aliasing hazard at registry.py:90), and every build
    must report to the 'parallel.train_step' retrace site."""
    from mxtpu import telemetry

    np.random.seed(0)
    x = np.random.uniform(size=(8, 10)).astype(np.float32)
    y = np.random.randint(0, 4, size=(8,)).astype(np.float32)
    mx.random.seed(0)
    net = _mlp()
    net(mx.nd.array(x))  # settle shapes
    step = ShardedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            data_parallel_mesh())

    def compiles():
        st = telemetry.retrace_stats("parallel.train_step")
        return st["compiles"] if st else 0

    before = compiles()
    step(mx.nd.array(x), mx.nd.array(y)).asnumpy()
    step(mx.nd.array(x), mx.nd.array(y)).asnumpy()
    assert compiles() == before + 1  # steady state: one build, then cached

    monkeypatch.setenv("MXTPU_BN_ONEPASS", "0")  # flip a policy_key lever
    step(mx.nd.array(x), mx.nd.array(y)).asnumpy()
    assert compiles() == before + 2  # exactly one rebuild per flip

    step(mx.nd.array(x), mx.nd.array(y)).asnumpy()
    assert compiles() == before + 2  # flipped policy is now the cached one
