"""INT8 quantization flow (ref: src/operator/quantization/*,
python/mxnet/contrib/quantization.py; test model
tests/python/quantization/test_quantization.py).

ISSUE 11 grew the op layer its serving callers (Predictor int8 weights,
DecodeEngine int8 KV) — the second half of this file pins the properties
that path depends on: requantize round-trips, exact int8 saturation
edges, the signed-symmetric range rule, and every op compiling under
``jax.jit`` with its ranges as TRACED arguments (scales are executable
*arguments*, so a weight reload requantizes without a recompile)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import gluon
from mxtpu.contrib import quantization as q
from mxtpu.gluon import nn
from mxtpu.ops.registry import get_op


def test_quantize_dequantize_roundtrip():
    x = mx.nd.array(np.linspace(-3, 3, 64).astype("float32"))
    xq, mn, mx_ = mx.nd.quantize(x, -3.0, 3.0)
    assert xq.dtype == np.int8
    back = mx.nd.dequantize(xq, mn, mx_)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), atol=3.0 / 127 + 1e-6)


def test_quantize_saturates():
    x = mx.nd.array([-10.0, 0.0, 10.0])
    xq, _, _ = mx.nd.quantize(x, -1.0, 1.0)
    np.testing.assert_array_equal(xq.asnumpy(), [-127, 0, 127])


def test_quantized_fully_connected_matches_fp32():
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (4, 8)).astype("float32")
    w = rng.uniform(-0.5, 0.5, (3, 8)).astype("float32")
    b = rng.uniform(-0.1, 0.1, (3,)).astype("float32")
    want = x @ w.T + b
    xq, _, _ = mx.nd.quantize(mx.nd.array(x), -1.0, 1.0)
    wq, _, _ = mx.nd.quantize(mx.nd.array(w), -0.5, 0.5)
    got = mx.nd.quantized_fully_connected(
        xq, wq, mx.nd.array(b), min_data=-1.0, max_data=1.0,
        min_weight=-0.5, max_weight=0.5).asnumpy()
    np.testing.assert_allclose(got, want, atol=0.08)


def test_quantized_conv_matches_fp32():
    rng = np.random.RandomState(1)
    x = rng.uniform(-1, 1, (2, 3, 8, 8)).astype("float32")
    w = rng.uniform(-0.5, 0.5, (5, 3, 3, 3)).astype("float32")
    want = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                             pad=(1, 1), num_filter=5, no_bias=True).asnumpy()
    xq, _, _ = mx.nd.quantize(mx.nd.array(x), -1.0, 1.0)
    wq, _, _ = mx.nd.quantize(mx.nd.array(w), -0.5, 0.5)
    got = mx.nd.quantized_conv(
        xq, wq, None, min_data=-1.0, max_data=1.0, min_weight=-0.5,
        max_weight=0.5, kernel=(3, 3), pad=(1, 1), num_filter=5,
        no_bias=True).asnumpy()
    err = np.abs(got - want).max()
    assert err < 0.3, err  # int8 conv over 27-elem receptive field


def _toy_images(n=512, seed=0):
    """4-class problem: bright quadrant of a 12x12 image."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 4, n)
    x = rng.uniform(0, 0.3, (n, 1, 12, 12)).astype("float32")
    for i, c in enumerate(y):
        r, cc = divmod(int(c), 2)
        x[i, 0, r * 6:(r + 1) * 6, cc * 6:(cc + 1) * 6] += 0.7
    return x, y.astype("float32")


def test_quantize_trained_cnn_accuracy_drop_below_1pct():
    """The VERDICT acceptance test: quantize a trained small CNN and show
    <1%% accuracy drop vs fp32."""
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Conv2D(16, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Flatten(),
            nn.Dense(32, activation="relu"),
            nn.Dense(4))
    net.initialize()
    x, y = _toy_images()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    bs = 64
    for epoch in range(4):
        for i in range(0, len(x), bs):
            xb = mx.nd.array(x[i:i + bs])
            yb = mx.nd.array(y[i:i + bs])
            with mx.autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(bs)

    def accuracy(m):
        pred = m(mx.nd.array(x)).asnumpy().argmax(axis=1)
        return (pred == y).mean()

    acc_fp32 = accuracy(net)
    assert acc_fp32 > 0.9, acc_fp32

    calib = [mx.nd.array(x[i:i + bs]) for i in range(0, 256, bs)]
    q.quantize_model_gluon(net, calib)
    acc_int8 = accuracy(net)
    assert acc_fp32 - acc_int8 < 0.01, (acc_fp32, acc_int8)


def test_quantized_net_hybridizes():
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    xs = mx.nd.array(np.random.uniform(-1, 1, (4, 5)).astype("float32"))
    net(xs)
    q.quantize_model_gluon(net, [xs])
    eager = net(xs).asnumpy()
    net.hybridize()
    hybrid = net(xs).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-5)


# --------------------------------------------------- ISSUE 11: op-level pins
def test_quantize_symmetric_range_rule():
    # the reference's signed-symmetric rule (quantize-inl.h): r =
    # max(|min|, |max|) — an asymmetric calibration [-1, 4] quantizes on
    # the [-4, 4] grid and REPORTS that grid back
    xq, mn, mx_ = mx.nd.quantize(mx.nd.array([-1.0, 0.0, 4.0]), -1.0, 4.0)
    assert float(mn.asnumpy()) == -4.0 and float(mx_.asnumpy()) == 4.0
    np.testing.assert_array_equal(xq.asnumpy(), [-32, 0, 127])


def test_quantize_saturation_edges_exact():
    # at-range values land exactly on +-127; past-range clamps there; the
    # epsilon neighborhood of zero stays zero (no off-by-half-step drift)
    r = 2.0
    x = mx.nd.array([-5.0, -2.0, -1e-9, 0.0, 1e-9, 2.0, 5.0])
    qv = mx.nd.quantize(x, -r, r)[0].asnumpy()
    np.testing.assert_array_equal(qv, [-127, -127, 0, 0, 0, 127, 127])


def test_requantize_calibrated_round_trip():
    # int32 accumulator -> int8 against a narrower calibrated window must
    # agree (to one grid step) with quantizing the real values directly
    rng = np.random.RandomState(1)
    real = rng.uniform(-0.9, 0.9, size=(257,)).astype(np.float32)
    R32, R8 = 4.0, 1.0
    acc = mx.nd.array(np.round(real * (2.0 ** 31 - 1) / R32), dtype="int32")
    qv, mn, mx_ = mx.nd.requantize(acc, -R32, R32, min_calib_range=-R8,
                                   max_calib_range=R8)
    assert float(mn.asnumpy()) == -R8 and float(mx_.asnumpy()) == R8
    direct = mx.nd.quantize(mx.nd.array(real), -R8, R8)[0].asnumpy()
    delta = np.abs(qv.asnumpy().astype(np.int32)
                   - direct.astype(np.int32)).max()
    assert delta <= 1, delta
    # and values outside the calibrated window saturate exactly
    edge = mx.nd.array(np.array([2 ** 31 - 1, -(2 ** 31 - 1)]),
                       dtype="int32")
    qe = mx.nd.requantize(edge, -8.0, 8.0, min_calib_range=-1.0,
                          max_calib_range=1.0)[0].asnumpy()
    np.testing.assert_array_equal(qe, [127, -127])


def test_quantized_fully_connected_saturated_operands_exact():
    # saturated int8 operands stay exact: +-127 x +-127 contractions are
    # pure int32 integer math — the only float op is the dequant scale
    qfc = get_op("quantized_fully_connected").fn
    x = np.full((2, 8), 127, np.int8)
    w = np.full((3, 8), -127, np.int8)
    out = np.asarray(qfc(x, w, bias=None, no_bias=True, min_data=-1.0,
                         max_data=1.0, min_weight=-2.0, max_weight=2.0))
    expect = (127 * -127 * 8) * (1.0 / 127.0) * (2.0 / 127.0)
    np.testing.assert_allclose(out, np.full((2, 3), expect, np.float32),
                               rtol=1e-6)


def test_ops_compile_with_traced_ranges():
    """The serving int8 contract: ranges are jit ARGUMENTS. Would fail
    with numpy-scalar-type casts (``jnp.float32(tracer)``
    concretizes)."""
    quantize = get_op("quantize").fn
    dequantize = get_op("dequantize").fn
    requantize = get_op("requantize").fn
    qfc = get_op("quantized_fully_connected").fn
    x = jnp.asarray(np.random.RandomState(3).randn(8, 4), jnp.float32)

    @jax.jit
    def round_trip(data, r):
        qv, lo, hi = quantize(data, -r, r)
        return dequantize(qv, lo, hi)

    out = round_trip(x, jnp.float32(2.5))
    assert np.abs(np.asarray(out) - np.asarray(x)).max() <= 2.5 / 127.0

    @jax.jit
    def fc(qx, qw, rx, rw):
        return qfc(qx, qw, bias=None, no_bias=True, min_data=-rx,
                   max_data=rx, min_weight=-rw, max_weight=rw)

    qx = quantize(x, -2.5, 2.5)[0]
    qw = quantize(x[:3], -2.5, 2.5)[0]   # [3, 4]: contracts x's last dim
    assert np.asarray(fc(qx, qw, jnp.float32(2.5),
                         jnp.float32(2.5))).shape == (8, 3)

    @jax.jit
    def requant(acc, r32, r8):
        return requantize(acc, -r32, r32, min_calib_range=-r8,
                          max_calib_range=r8)[0]

    acc = jnp.asarray(np.array([2 ** 30, -(2 ** 30)], np.int32))
    qv = np.asarray(requant(acc, jnp.float32(4.0), jnp.float32(4.0)))
    assert qv.tolist() == [64, -64]
