"""INT8 quantization flow (ref: src/operator/quantization/*,
python/mxnet/contrib/quantization.py; test model
tests/python/quantization/test_quantization.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import gluon
from mxtpu.contrib import quantization as q
from mxtpu.gluon import nn


def test_quantize_dequantize_roundtrip():
    x = mx.nd.array(np.linspace(-3, 3, 64).astype("float32"))
    xq, mn, mx_ = mx.nd.quantize(x, -3.0, 3.0)
    assert xq.dtype == np.int8
    back = mx.nd.dequantize(xq, mn, mx_)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), atol=3.0 / 127 + 1e-6)


def test_quantize_saturates():
    x = mx.nd.array([-10.0, 0.0, 10.0])
    xq, _, _ = mx.nd.quantize(x, -1.0, 1.0)
    np.testing.assert_array_equal(xq.asnumpy(), [-127, 0, 127])


def test_quantized_fully_connected_matches_fp32():
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (4, 8)).astype("float32")
    w = rng.uniform(-0.5, 0.5, (3, 8)).astype("float32")
    b = rng.uniform(-0.1, 0.1, (3,)).astype("float32")
    want = x @ w.T + b
    xq, _, _ = mx.nd.quantize(mx.nd.array(x), -1.0, 1.0)
    wq, _, _ = mx.nd.quantize(mx.nd.array(w), -0.5, 0.5)
    got = mx.nd.quantized_fully_connected(
        xq, wq, mx.nd.array(b), min_data=-1.0, max_data=1.0,
        min_weight=-0.5, max_weight=0.5).asnumpy()
    np.testing.assert_allclose(got, want, atol=0.08)


def test_quantized_conv_matches_fp32():
    rng = np.random.RandomState(1)
    x = rng.uniform(-1, 1, (2, 3, 8, 8)).astype("float32")
    w = rng.uniform(-0.5, 0.5, (5, 3, 3, 3)).astype("float32")
    want = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                             pad=(1, 1), num_filter=5, no_bias=True).asnumpy()
    xq, _, _ = mx.nd.quantize(mx.nd.array(x), -1.0, 1.0)
    wq, _, _ = mx.nd.quantize(mx.nd.array(w), -0.5, 0.5)
    got = mx.nd.quantized_conv(
        xq, wq, None, min_data=-1.0, max_data=1.0, min_weight=-0.5,
        max_weight=0.5, kernel=(3, 3), pad=(1, 1), num_filter=5,
        no_bias=True).asnumpy()
    err = np.abs(got - want).max()
    assert err < 0.3, err  # int8 conv over 27-elem receptive field


def _toy_images(n=512, seed=0):
    """4-class problem: bright quadrant of a 12x12 image."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 4, n)
    x = rng.uniform(0, 0.3, (n, 1, 12, 12)).astype("float32")
    for i, c in enumerate(y):
        r, cc = divmod(int(c), 2)
        x[i, 0, r * 6:(r + 1) * 6, cc * 6:(cc + 1) * 6] += 0.7
    return x, y.astype("float32")


def test_quantize_trained_cnn_accuracy_drop_below_1pct():
    """The VERDICT acceptance test: quantize a trained small CNN and show
    <1%% accuracy drop vs fp32."""
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Conv2D(16, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2),
            nn.Flatten(),
            nn.Dense(32, activation="relu"),
            nn.Dense(4))
    net.initialize()
    x, y = _toy_images()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    bs = 64
    for epoch in range(4):
        for i in range(0, len(x), bs):
            xb = mx.nd.array(x[i:i + bs])
            yb = mx.nd.array(y[i:i + bs])
            with mx.autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(bs)

    def accuracy(m):
        pred = m(mx.nd.array(x)).asnumpy().argmax(axis=1)
        return (pred == y).mean()

    acc_fp32 = accuracy(net)
    assert acc_fp32 > 0.9, acc_fp32

    calib = [mx.nd.array(x[i:i + bs]) for i in range(0, 256, bs)]
    q.quantize_model_gluon(net, calib)
    acc_int8 = accuracy(net)
    assert acc_fp32 - acc_int8 < 0.01, (acc_fp32, acc_int8)


def test_quantized_net_hybridizes():
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    xs = mx.nd.array(np.random.uniform(-1, 1, (4, 5)).astype("float32"))
    net(xs)
    q.quantize_model_gluon(net, [xs])
    eager = net(xs).asnumpy()
    net.hybridize()
    hybrid = net(xs).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-5)
