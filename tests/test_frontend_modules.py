"""mx.name / mx.attribute / mx.viz / mx.registry / mx.engine / mx.util —
the reference's misc frontend modules (python/mxnet/{name,attribute,
visualization,registry,engine,util}.py)."""
import numpy as np

import mxtpu as mx


def test_name_manager_and_prefix():
    with mx.name.Prefix("stage1_"):
        s = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4)
    args = s.list_arguments()
    assert args[1].startswith("stage1_fullyconnected")
    # nested scopes: inner wins, counters independent
    with mx.name.NameManager():
        a = mx.sym.Activation(mx.sym.Variable("x"), act_type="relu")
        b = mx.sym.Activation(mx.sym.Variable("x"), act_type="relu")
    names = [n for n in (a.attr("__name__") or "",)]  # names live on nodes
    assert a._heads[0][0].name == "activation0"
    assert b._heads[0][0].name == "activation1"


def test_attr_scope_applies_to_ops_and_vars():
    with mx.AttrScope(ctx_group="dev1", lr_mult=2):
        v = mx.sym.Variable("w2")
        s = mx.sym.FullyConnected(mx.sym.Variable("d2"), weight=v,
                                  num_hidden=4, name="fc9")
    assert v.attr("__ctx_group__") == "dev1"
    assert s.attr("__lr_mult__") == "2"
    # nesting: inner overrides, outer restored
    with mx.AttrScope(ctx_group="a"):
        with mx.AttrScope(ctx_group="b"):
            inner = mx.sym.Variable("vi")
        outer = mx.sym.Variable("vo")
    assert inner.attr("__ctx_group__") == "b"
    assert outer.attr("__ctx_group__") == "a"
    # no scope: no attrs leak
    clean = mx.sym.Variable("vc")
    assert clean.attr("__ctx_group__") is None


def test_print_summary_counts_params(capsys):
    net = mx.sym.Convolution(mx.sym.Variable("data"), num_filter=8,
                             kernel=(3, 3), pad=(1, 1), name="c1")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=10,
                                name="fc")
    total = mx.viz.print_summary(net, shape={"data": (1, 3, 8, 8)})
    out = capsys.readouterr().out
    # conv: 8*3*3*3 + 8 = 224; fc: 512*10 + 10 = 5130
    assert total == 224 + 5130
    assert "c1 (Convolution)" in out and "Total params" in out


def test_registry_funcs():
    class Base:
        pass

    class Impl(Base):
        pass

    reg = mx.registry.get_register_func(Base, "thing")
    alias = mx.registry.get_alias_func(Base, "thing")
    create = mx.registry.get_create_func(Base, "thing")
    reg(Impl)
    alias("other")(Impl)
    assert isinstance(create("impl"), Impl)
    assert isinstance(create("other"), Impl)
    inst = Impl()
    assert create(inst) is inst


def test_engine_bulk_and_util():
    assert mx.engine.set_bulk_size(15) == 0
    with mx.engine.bulk(30):
        pass
    assert mx.engine.set_bulk_size(0) == 15
    mx.util.makedirs("/tmp/_mxtpu_util_dir/nested")
    mx.util.makedirs("/tmp/_mxtpu_util_dir/nested")  # idempotent
    assert mx.util.is_np_shape() is True

    @mx.util.use_np_shape
    def f(x):
        return x + 1

    assert f(1) == 2


def test_kvstore_server_refuses_with_migration_note():
    import pytest
    from mxtpu.kvstore_server import KVStoreServer
    with pytest.raises(mx.MXNetError, match="symmetric XLA collectives"):
        KVStoreServer().run()


def test_split_input_slice():
    from mxtpu.executor_manager import _split_input_slice
    sl = _split_input_slice(10, [1, 1])
    assert [s.start for s in sl] == [0, 5] and [s.stop for s in sl] == [5, 10]
    sl = _split_input_slice(9, [2, 1])
    assert sl[0] == slice(0, 6) and sl[1] == slice(6, 9)
    import pytest
    with pytest.raises(mx.MXNetError):
        _split_input_slice(1, [1, 1])


def test_attr_scope_symbol_still_executes():
    """Dunder scope attrs are graph annotations, not op kwargs — a symbol
    built inside an AttrScope must infer and bind normally."""
    with mx.AttrScope(ctx_group="dev1"):
        net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                                    name="fca")
    arg_shapes, out_shapes, _ = net.infer_shape(data=(2, 4))
    assert out_shapes[0] == (2, 3)
    ex = net.simple_bind(mx.cpu(), data=(2, 4))
    out = ex.forward(is_train=False, data=mx.nd.ones((2, 4)))
    assert out[0].shape == (2, 3)


def test_attr_scope_object_reuse_does_not_leak():
    a = mx.AttrScope(lr_mult=1)
    with mx.AttrScope(ctx_group="dev1"):
        with a:
            pass
    with a:
        v = mx.sym.Variable("reuse_v")
    assert v.attr("__ctx_group__") is None
    assert v.attr("__lr_mult__") == "1"


def test_v1_and_sparse_embedding_backward():
    import numpy as np
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(2, 3, 8, 8).astype(np.float32))
    w = mx.nd.array(rng.randn(4, 3, 3, 3).astype(np.float32) * 0.1)
    w.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Convolution_v1(x, w, None, kernel=(3, 3), pad=(1, 1),
                                 num_filter=4, no_bias=True)
        z = mx.nd.Pooling_v1(y, kernel=(2, 2), stride=(2, 2),
                             pool_type="avg")
        loss = (z * z).sum()
    loss.backward()
    assert np.isfinite(w.grad.asnumpy()).all()
    assert np.abs(w.grad.asnumpy()).sum() > 0

    emb = mx.nd.array(np.eye(5, 3, dtype=np.float32))
    emb.attach_grad()
    idx = mx.nd.array(np.array([0, 2], np.float32))
    with mx.autograd.record():
        out = mx.nd.contrib.SparseEmbedding(idx, emb, input_dim=5,
                                            output_dim=3)
        loss = out.sum()
    loss.backward()
    g = emb.grad.asnumpy()
    assert g[0].sum() == 3 and g[2].sum() == 3 and g[1].sum() == 0


def test_server_and_scheduler_roles_fail_fast():
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for role in ("server", "scheduler"):
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms','cpu'); "
             "import mxtpu"],
            env={"PATH": "/usr/bin:/bin", "DMLC_ROLE": role,
                 "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo},
            capture_output=True, text=True, timeout=300)
        assert r.returncode != 0, role
        assert "symmetric XLA collectives" in r.stderr, role


def test_op_parity_audit_has_no_missing():
    """docs/op_parity.md generator: every reference-registered op must be
    implemented, autodiff-derived, or explicitly subsumed — no gaps."""
    import os
    import sys
    if not os.path.isdir("/root/reference/src/operator"):
        import pytest
        pytest.skip("reference tree not mounted")
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import op_parity
    missing = [n for n, c, _ in op_parity.classify(op_parity.reference_ops())
               if c == "missing"]
    assert missing == []


def test_registry_sees_builtin_families():
    """mx.registry must operate on the framework's LIVE registries — the
    'ports unchanged' contract (review finding): create('xavier') etc."""
    import mxtpu.initializer as init
    import mxtpu.metric as metric
    import mxtpu.optimizer as opt
    c_init = mx.registry.get_create_func(init.Initializer, "initializer")
    assert isinstance(c_init("xavier"), init.Xavier)
    c_opt = mx.registry.get_create_func(opt.Optimizer, "optimizer")
    assert isinstance(c_opt("sgd"), opt.SGD)
    c_met = mx.registry.get_create_func(metric.EvalMetric, "metric")
    assert isinstance(c_met("accuracy"), metric.Accuracy)

    # registering through mx.registry lands in the live family registry
    reg = mx.registry.get_register_func(opt.Optimizer, "optimizer")

    @reg
    class MyOpt2(opt.SGD):
        pass

    assert isinstance(opt.create("myopt2"), MyOpt2)


def test_print_summary_no_data_inflation_and_shared_weight(capsys):
    # data variable named 'x' must not count as parameters
    net = mx.sym.FullyConnected(mx.sym.Variable("x"), num_hidden=4,
                                name="fcx")
    total = mx.viz.print_summary(net, shape={"x": (2, 8)})
    assert total == 8 * 4 + 4
    # a weight shared by two layers counts once in the total
    w = mx.sym.Variable("shared_weight")
    a = mx.sym.FullyConnected(mx.sym.Variable("x"), weight=w, num_hidden=8,
                              no_bias=True, name="fa")
    b = mx.sym.FullyConnected(mx.sym.Variable("x"), weight=w, num_hidden=8,
                              no_bias=True, name="fb")
    grp = mx.sym.Group([a, b])
    total2 = mx.viz.print_summary(grp, shape={"x": (2, 8)})
    assert total2 == 8 * 8


def test_mx_executor_namespace_alias():
    """Reference code spells mx.executor.Executor (python/mxnet/
    executor.py); isinstance checks against it must see the real class."""
    import mxtpu as mx
    from mxtpu import symbol as sym
    assert mx.executor.Executor is not None
    s = sym.FullyConnected(sym.var("data"), sym.var("w"), sym.var("b"),
                           num_hidden=3, name="fc")
    ex = s.bind(args={"data": mx.nd.ones((2, 4)),
                      "w": mx.nd.ones((3, 4)), "b": mx.nd.zeros((3,))})
    assert isinstance(ex, mx.executor.Executor)


def test_optimizer_contrib_namespace():
    """mx.optimizer.contrib.GroupAdaGrad — the reference spelling
    (python/mxnet/optimizer/contrib.py)."""
    import mxtpu as mx
    from mxtpu.optimizer import contrib
    assert contrib.GroupAdaGrad is mx.optimizer.GroupAdaGrad
    import importlib
    assert importlib.import_module("mxtpu.optimizer.contrib") is contrib


def test_nd_linalg_and_sym_subnamespaces():
    """Reference sub-namespace spellings: mx.nd.linalg.*, mx.sym.linalg/
    image/random/sparse (python/mxnet/{ndarray,symbol}/linalg.py etc.)."""
    import numpy as np
    import mxtpu as mx

    a = mx.nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    np.testing.assert_allclose(
        mx.nd.linalg.gemm2(a, a).asnumpy(), a.asnumpy() @ a.asnumpy(),
        rtol=1e-5)
    np.testing.assert_allclose(
        mx.nd.linalg.syrk(a).asnumpy(), a.asnumpy() @ a.asnumpy().T,
        rtol=1e-5)

    # symbolic twins compose and execute
    s = mx.sym.linalg.gemm2(mx.sym.var("x"), mx.sym.var("y"))
    ex = s.bind(args={"x": a, "y": a})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(),
                               a.asnumpy() @ a.asnumpy(), rtol=1e-5)
    sd = mx.sym.sparse.dot(mx.sym.var("x"), mx.sym.var("y"))
    ex2 = sd.bind(args={"x": a, "y": a})
    np.testing.assert_allclose(ex2.forward()[0].asnumpy(),
                               a.asnumpy() @ a.asnumpy(), rtol=1e-5)
    assert mx.sym.image.resize is not None
    assert mx.sym.random.uniform is not None


def test_rnn_checkpoint_roundtrip(tmp_path):
    """mx.rnn.save/load_rnn_checkpoint: fused blob unpacks on disk and
    re-packs on load (ref: python/mxnet/rnn/rnn.py:32-96)."""
    import numpy as np
    import mxtpu as mx
    from mxtpu import rnn

    H, I_ = 4, 3
    fused = rnn.FusedRNNCell(H, num_layers=1, mode="lstm", prefix="f_")
    out, _ = fused.unroll(2, inputs=mx.sym.var("data"),
                          begin_state=fused.begin_state(batch_size=2),
                          merge_outputs=True)
    n_params = 4 * H * (I_ + H) + 8 * H
    blob = np.random.RandomState(0).rand(n_params).astype(np.float32)
    args = {"f_parameters": mx.nd.array(blob)}
    prefix = str(tmp_path / "rnnckpt")
    rnn.save_rnn_checkpoint(fused, prefix, 3, out, dict(args), {})
    # on-disk params are UNPACKED per-gate arrays, not the runtime blob
    import mxtpu.model as model
    _sym, disk_args, _aux = model.load_checkpoint(prefix, 3)
    assert "f_parameters" not in disk_args
    assert any(k.endswith("weight") or "i2h" in k for k in disk_args)
    # load re-packs to the fused blob exactly
    _sym2, arg2, _aux2 = rnn.load_rnn_checkpoint(fused, prefix, 3)
    np.testing.assert_allclose(arg2["f_parameters"].asnumpy(), blob,
                               rtol=1e-6)
    # do_rnn_checkpoint callback writes on period boundaries only
    cb = rnn.do_rnn_checkpoint(fused, str(tmp_path / "cbck"), period=2)
    cb(0, out, dict(args), {})   # epoch 1: skipped
    import os
    assert not os.path.exists(str(tmp_path / "cbck-0001.params"))
    cb(1, out, dict(args), {})   # epoch 2: written
    assert os.path.exists(str(tmp_path / "cbck-0002.params"))
