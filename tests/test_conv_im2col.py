"""im2col conv lowering (MXTPU_CONV_IM2COL, mxtpu/ops/conv_acc.py) —
deliberately SEPARATE from test_conv_acc.py: that module skips entirely
when the private jax transpose helpers vanish (HAVE_ACC_VJP), but
conv_im2col has no such dependency and must stay covered regardless."""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
import pytest

from mxtpu.ops.conv_acc import conv_fast, conv_im2col, _im2col_applicable

DN = ("NHWC", "HWIO", "NHWC")


@pytest.mark.parametrize("cin,cout,k,hw", [(64, 64, 3, 14), (3, 8, 7, 16),
                                           (128, 32, 5, 10)])
def test_im2col_path_exact(cin, cout, k, hw):
    """The staged im2col lowering (MXTPU_CONV_IM2COL) must equal the conv
    path exactly, forward and weight-gradient (round-5 lever for the
    slow small-channel conv classes, PERF.md)."""
    import numpy as np
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, hw, hw, cin), jnp.float32)
    w = jnp.asarray(rng.randn(k, k, cin, cout) * 0.1, jnp.float32)
    pad = [(k // 2, k // 2)] * 2
    ref = lax.conv_general_dilated(
        x, w, (1, 1), pad, dimension_numbers=DN)
    got = conv_im2col(x, w, pad)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    g1 = jax.grad(lambda w_: jnp.sum(conv_im2col(x, w_, pad) ** 2))(w)
    g2 = jax.grad(lambda w_: jnp.sum(lax.conv_general_dilated(
        x, w_, (1, 1), pad, dimension_numbers=DN) ** 2))(w)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-3, atol=1e-3)


def test_im2col_dispatch_gating(monkeypatch):
    """Only stride-1 / groups-1 / k>1 / C_in<=128 NHWC convs qualify, and
    the env flag genuinely routes conv_fast through the matmul lowering
    (the staged lever must not be silently dead when the auto-battery
    measures it)."""
    x = jnp.zeros((1, 8, 8, 16), jnp.bfloat16)
    w3 = jnp.zeros((3, 3, 16, 8), jnp.bfloat16)
    ok = ("NHWC", "HWIO", "NHWC")
    assert _im2col_applicable(x, w3, (1, 1), None, (1, 1), (1, 1), ok, 1)
    assert not _im2col_applicable(x, w3, (2, 2), None, (1, 1), (1, 1), ok, 1)
    assert not _im2col_applicable(x, jnp.zeros((1, 1, 16, 8)), (1, 1),
                                  None, (1, 1), (1, 1), ok, 1)
    assert not _im2col_applicable(x, jnp.zeros((3, 3, 256, 8)), (1, 1),
                                  None, (1, 1), (1, 1), ok, 1)
    assert not _im2col_applicable(x, w3, (1, 1), None, (1, 1), (1, 1),
                                  ok, 2)
    assert not _im2col_applicable(x, w3, (1, 1), None, (2, 2), (1, 1),
                                  ok, 1)


    args = ((1, 1), [(1, 1), (1, 1)], (1, 1), (1, 1), ok, 1)
    monkeypatch.delenv("MXTPU_CONV_IM2COL", raising=False)
    hlo_off = jax.jit(lambda a, b: conv_fast(a, b, *args)).lower(
        jnp.zeros((1, 8, 8, 16), jnp.bfloat16), w3).as_text()
    assert "convolution" in hlo_off
    monkeypatch.setenv("MXTPU_CONV_IM2COL", "1")
    hlo_on = jax.jit(lambda a, b: conv_fast(a, b, *args)).lower(
        jnp.zeros((1, 8, 8, 16), jnp.bfloat16), w3).as_text()
    # patches extraction lowers to a conv against an identity kernel on
    # some jax versions; the CONTRACTION itself must be a dot_general
    assert "dot_general" in hlo_on and "dot_general" not in hlo_off


def test_im2col_mixed_dtype_promotes_like_conv_semantics(monkeypatch):
    """bf16 activations x f32 weights: lax.conv REJECTS mixed dtypes, so
    the conv path can only ever run on promoted operands — the im2col
    path must return that same promoted dtype, never downcast to x.dtype
    (review r5: the A/B must compare equal-precision programs)."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1, 8, 8, 16), jnp.bfloat16)
    w = jnp.asarray(rng.randn(3, 3, 16, 8) * 0.1, jnp.float32)
    pad = [(1, 1), (1, 1)]
    with pytest.raises(TypeError):  # documents the conv-path contract
        lax.conv_general_dilated(x, w, (1, 1), pad, dimension_numbers=DN)
    got = conv_im2col(x, w, pad)
    assert got.dtype == jnp.float32  # promoted, not x.dtype
    ref = lax.conv_general_dilated(x.astype(jnp.float32), w, (1, 1), pad,
                                   dimension_numbers=DN)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
