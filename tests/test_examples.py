"""Smoke tests for the runnable examples (ref: example/image-classification,
example/gluon/word_language_model) — each must train end to end on tiny
synthetic shapes through its real __main__ path."""
import os
import runpy
import sys

import numpy as np

import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _run(rel, argv):
    old = sys.argv
    sys.argv = ["x"] + argv
    try:
        runpy.run_path(os.path.join(ROOT, rel), run_name="__main__")
    finally:
        sys.argv = old


def test_image_classification_gluon(capsys):
    _run("examples/image_classification/train_cifar10.py",
         ["--epochs", "1", "--batch-size", "4", "--num-batches", "2",
          "--model", "resnet18_v1", "--dtype", "float32"])
    assert "epoch 0" in capsys.readouterr().out


def test_image_classification_module():
    _run("examples/image_classification/train_cifar10.py",
         ["--epochs", "1", "--batch-size", "4", "--num-batches", "2",
          "--module"])


def test_word_language_model(capsys):
    _run("examples/gluon/word_language_model.py",
         ["--epochs", "1", "--batch-size", "2", "--bptt", "4",
          "--vocab", "50", "--embed", "8", "--hidden", "8",
          "--corpus-len", "200", "--dtype", "float32"])
    assert "ppl" in capsys.readouterr().out


def test_lstm_bucketing_legacy_cells(capsys):
    """The classic mx.rnn + BucketingModule workflow (ref: example/rnn/
    bucketing/lstm_bucketing.py): legacy symbolic cells, one executor per
    bucket, must CONVERGE on the synthetic next-token pattern (uniform
    perplexity over vocab 32 would be 32; require < 10)."""
    _run("examples/rnn/lstm_bucketing.py",
         ["--epochs", "4", "--batch-size", "8", "--num-hidden", "16",
          "--num-embed", "8"])
    out = capsys.readouterr().out
    final = [l for l in out.splitlines() if l.startswith("final ")]
    assert final, out
    ppl = float(final[-1].split()[-1])
    assert ppl < 10.0, out


def test_sparse_linear_classification():
    # existing example (BASELINE config 5) keeps working through main
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "slc", os.path.join(ROOT, "examples/sparse/linear_classification.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    path = "/tmp/_ex_sparse.libsvm"
    m.make_synthetic_libsvm(path, num_rows=64, num_features=100,
                            nnz_per_row=5)
    result = m.train(path, 100, batch_size=16, epochs=2)
    acc = result[0]
    assert acc > 0.5


@pytest.mark.multidevice
def test_distributed_example_two_workers():
    """examples/distributed/train_dist.py through tools/launch.py -n 2:
    the symmetric multi-process path a reference dist_sync user follows
    (also guards the launcher's axon-env scrubbing for CPU workers)."""
    import signal
    import subprocess
    # own session so a timeout can kill the whole process GROUP — otherwise
    # hung grandchild workers outlive the test holding the coordinator port
    proc = subprocess.Popen(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"), "-n", "2",
         sys.executable, os.path.join(ROOT, "examples", "distributed",
                                      "train_dist.py")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True)
    try:
        out, err = proc.communicate(timeout=420)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait()
        raise
    r = subprocess.CompletedProcess(proc.args, proc.returncode, out, err)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "workers=2" in r.stdout
    assert "exported checkpoint" in r.stdout


def test_gluon_mnist_converges(capsys):
    """Canonical gluon MNIST MLP (ref: example/gluon/mnist.py) on the
    synthetic prototype set: must reach high val accuracy in 2 epochs."""
    _run("examples/gluon/mnist.py",
         ["--epochs", "2", "--batch-size", "50", "--hidden", "64",
          "--synthetic-size", "600"])
    out = capsys.readouterr().out
    assert "val-acc" in out
    acc = float(out.strip().splitlines()[-1].split()[-1])
    assert acc > 0.9, out


def test_gluon_dcgan_runs(capsys):
    """Adversarial two-trainer loop (ref: example/gluon/dcgan.py): both
    losses must stay finite through an epoch of alternating updates."""
    _run("examples/gluon/dcgan.py",
         ["--epochs", "1", "--batches-per-epoch", "3", "--batch-size", "4",
          "--ngf", "8", "--ndf", "8", "--nz", "8"])
    out = capsys.readouterr().out
    assert "lossD" in out
    toks = out.strip().splitlines()[-1].split()
    lossD, lossG = float(toks[3]), float(toks[5])
    assert np.isfinite(lossD) and np.isfinite(lossG), out


def test_numpy_ops_custom_softmax(capsys):
    """CustomOp escape hatch (ref: example/numpy-ops/custom_softmax.py):
    host-side NumPy fwd/bwd must match the built-in op and its grad."""
    _run("examples/numpy_ops/custom_softmax.py", [])
    assert "OK" in capsys.readouterr().out


@pytest.mark.multidevice
def test_model_parallel_tp_mlp(capsys):
    """Megatron-style column+row parallel MLP (ref: example/model-parallel,
    re-expressed as GSPMD rules) on the 8-device mesh: loss must fall."""
    _run("examples/model_parallel/tp_mlp.py",
         ["--steps", "8", "--batch-size", "16", "--hidden", "64"])
    out = capsys.readouterr().out
    first, last = out.strip().splitlines()[-1].split()[-3], \
        out.strip().splitlines()[-1].split()[-1]
    assert float(last) < float(first), out


def test_cnn_text_classification_converges(capsys):
    """Kim-2014 text CNN (ref: example/cnn_text_classification): parallel
    Conv1D widths + max-over-time pooling must crack the keyword task."""
    _run("examples/cnn_text_classification/text_cnn.py",
         ["--epochs", "3", "--train-size", "512"])
    out = capsys.readouterr().out
    acc = float(out.strip().splitlines()[-1].split()[-1])
    assert acc > 0.8, out


def test_multi_task_both_heads_learn(capsys):
    """Shared-trunk two-head training (ref: example/multi-task): summed
    losses must teach BOTH heads above chance by a wide margin."""
    _run("examples/multi_task/multitask_mlp.py",
         ["--epochs", "6", "--train-size", "1024"])
    out = capsys.readouterr().out
    toks = out.strip().splitlines()[-1].split()
    acc1, acc2 = float(toks[-3]), float(toks[-1])
    assert acc1 > 0.6 and acc2 > 0.8, out


def test_ssd_detection_trains_and_detects():
    """Tiny SSD over the MultiBox op family (ref example/ssd): loss
    falls, and inference decodes + NMS-es real detections."""
    import importlib.util
    import numpy as np
    spec = importlib.util.spec_from_file_location(
        "train_ssd", os.path.join(ROOT, "examples", "ssd", "train_ssd.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    net, anchors, hist = m.train(num_images=16, batch_size=8, epochs=6)
    assert hist[-1] < hist[0], hist
    imgs, labels = m.make_synthetic(2, seed=123)
    det = m.detect(net, anchors, imgs).asnumpy()
    assert det.ndim == 3 and det.shape[2] == 6
    kept = det[0][det[0][:, 0] >= 0]
    assert len(kept) > 0          # at least one post-NMS detection
    assert np.isfinite(kept).all()
    best = kept[np.argmax(kept[:, 1])]
    assert best[0] == 0           # the single foreground class
    assert 0.0 <= best[1] <= 1.0  # a probability score
    # the decoded box is a plausible region, not a degenerate point —
    # the short training run does not localize tightly, so assert
    # overlap with the image rather than IoU against labels
    gt = labels[0, 0, 1:]
    x0, y0, x1, y1 = best[2:6]
    assert x1 > x0 and y1 > y0
    assert x0 < gt[2] and x1 > gt[0]  # horizontal ranges intersect


def test_bi_lstm_sort_learns():
    """Bidirectional LSTM sorts integer sequences (ref
    example/bi-lstm-sort): per-token accuracy far above the 1/vocab
    chance level after a short hybridized training run."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "sort_lstm", os.path.join(ROOT, "examples", "bi_lstm_sort",
                                  "sort_lstm.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    net, hist = m.train(num=512, epochs=15)
    assert hist[-1] < hist[0] * 0.5, hist
    tok_acc, _ = m.accuracy(net, num=64)
    assert tok_acc > 0.4, tok_acc  # chance = 1/16
