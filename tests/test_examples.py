"""Smoke tests for the runnable examples (ref: example/image-classification,
example/gluon/word_language_model) — each must train end to end on tiny
synthetic shapes through its real __main__ path."""
import os
import runpy
import sys

import pytest

ROOT = os.path.join(os.path.dirname(__file__), "..")


def _run(rel, argv):
    old = sys.argv
    sys.argv = ["x"] + argv
    try:
        runpy.run_path(os.path.join(ROOT, rel), run_name="__main__")
    finally:
        sys.argv = old


def test_image_classification_gluon(capsys):
    _run("examples/image_classification/train_cifar10.py",
         ["--epochs", "1", "--batch-size", "4", "--num-batches", "2",
          "--model", "resnet18_v1", "--dtype", "float32"])
    assert "epoch 0" in capsys.readouterr().out


def test_image_classification_module():
    _run("examples/image_classification/train_cifar10.py",
         ["--epochs", "1", "--batch-size", "4", "--num-batches", "2",
          "--module"])


def test_word_language_model(capsys):
    _run("examples/gluon/word_language_model.py",
         ["--epochs", "1", "--batch-size", "2", "--bptt", "4",
          "--vocab", "50", "--embed", "8", "--hidden", "8",
          "--corpus-len", "200", "--dtype", "float32"])
    assert "ppl" in capsys.readouterr().out


def test_lstm_bucketing_legacy_cells(capsys):
    """The classic mx.rnn + BucketingModule workflow (ref: example/rnn/
    bucketing/lstm_bucketing.py): legacy symbolic cells, one executor per
    bucket, must CONVERGE on the synthetic next-token pattern (uniform
    perplexity over vocab 32 would be 32; require < 10)."""
    _run("examples/rnn/lstm_bucketing.py",
         ["--epochs", "4", "--batch-size", "8", "--num-hidden", "16",
          "--num-embed", "8"])
    out = capsys.readouterr().out
    final = [l for l in out.splitlines() if l.startswith("final ")]
    assert final, out
    ppl = float(final[-1].split()[-1])
    assert ppl < 10.0, out


def test_sparse_linear_classification():
    # existing example (BASELINE config 5) keeps working through main
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "slc", os.path.join(ROOT, "examples/sparse/linear_classification.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    path = "/tmp/_ex_sparse.libsvm"
    m.make_synthetic_libsvm(path, num_rows=64, num_features=100,
                            nnz_per_row=5)
    result = m.train(path, 100, batch_size=16, epochs=2)
    acc = result[0]
    assert acc > 0.5


@pytest.mark.multidevice
def test_distributed_example_two_workers():
    """examples/distributed/train_dist.py through tools/launch.py -n 2:
    the symmetric multi-process path a reference dist_sync user follows
    (also guards the launcher's axon-env scrubbing for CPU workers)."""
    import signal
    import subprocess
    # own session so a timeout can kill the whole process GROUP — otherwise
    # hung grandchild workers outlive the test holding the coordinator port
    proc = subprocess.Popen(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"), "-n", "2",
         sys.executable, os.path.join(ROOT, "examples", "distributed",
                                      "train_dist.py")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True)
    try:
        out, err = proc.communicate(timeout=420)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait()
        raise
    r = subprocess.CompletedProcess(proc.args, proc.returncode, out, err)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "workers=2" in r.stdout
    assert "exported checkpoint" in r.stdout
