"""Operator numerics vs NumPy references + numeric-gradient checks.

Modeled on tests/python/unittest/test_operator.py (7213 LoC in the reference): each
op family checked against a NumPy implementation, gradients via finite differences.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd as ag
from mxtpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_unary_math_matches_numpy():
    x = np.random.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
    nd = mx.nd.array(x)
    for name, ref in [
        ("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt), ("square", np.square),
        ("sin", np.sin), ("cos", np.cos), ("tanh", np.tanh), ("abs", np.abs),
        ("floor", np.floor), ("ceil", np.ceil), ("sign", np.sign),
        ("log1p", np.log1p), ("expm1", np.expm1),
    ]:
        out = mx.ops.invoke(name, nd)
        # rtol 1e-3: XLA CPU uses polynomial approximations for transcendentals
        assert_almost_equal(out, ref(x), rtol=1e-3, atol=1e-5)


def test_binary_broadcast():
    a = np.random.uniform(-2, 2, (2, 3, 1)).astype(np.float32)
    b = np.random.uniform(0.5, 2, (1, 3, 4)).astype(np.float32)
    na, nb = mx.nd.array(a), mx.nd.array(b)
    assert_almost_equal(mx.nd.broadcast_add(na, nb), a + b, rtol=1e-5)
    assert_almost_equal(mx.nd.broadcast_mul(na, nb), a * b, rtol=1e-5)
    assert_almost_equal(mx.nd.broadcast_maximum(na, nb), np.maximum(a, b))
    assert_almost_equal(mx.nd.broadcast_power(na + 3, nb), np.power(a + 3, b), rtol=1e-4)


def test_reduce_ops():
    x = np.random.uniform(-1, 1, (2, 3, 4)).astype(np.float32)
    nd = mx.nd.array(x)
    assert_almost_equal(mx.nd.sum(nd), x.sum(), rtol=1e-5)
    assert_almost_equal(mx.nd.sum(nd, axis=1), x.sum(1), rtol=1e-5)
    assert_almost_equal(mx.nd.sum(nd, axis=(0, 2), keepdims=True),
                        x.sum((0, 2), keepdims=True), rtol=1e-5)
    assert_almost_equal(mx.nd.mean(nd, axis=1, exclude=True),
                        x.mean(axis=(0, 2)), rtol=1e-5)
    assert_almost_equal(mx.nd.max(nd, axis=2), x.max(2))
    assert_almost_equal(mx.nd.norm(nd), np.sqrt((x ** 2).sum()), rtol=1e-5)
    assert_almost_equal(mx.nd.argmax(nd, axis=1), x.argmax(1).astype(np.float32))


def test_dot():
    a = np.random.uniform(-1, 1, (3, 4)).astype(np.float32)
    b = np.random.uniform(-1, 1, (4, 5)).astype(np.float32)
    assert_almost_equal(mx.nd.dot(mx.nd.array(a), mx.nd.array(b)), a @ b, rtol=1e-4)
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a), mx.nd.array(b.T), transpose_b=True), a @ b, rtol=1e-4)
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a.T), mx.nd.array(b), transpose_a=True), a @ b, rtol=1e-4)
    # batch_dot
    x = np.random.uniform(-1, 1, (2, 3, 4)).astype(np.float32)
    y = np.random.uniform(-1, 1, (2, 4, 5)).astype(np.float32)
    assert_almost_equal(mx.nd.batch_dot(mx.nd.array(x), mx.nd.array(y)),
                        np.matmul(x, y), rtol=1e-4)


def test_fully_connected():
    x = np.random.uniform(-1, 1, (2, 5)).astype(np.float32)
    w = np.random.uniform(-1, 1, (3, 5)).astype(np.float32)
    b = np.random.uniform(-1, 1, (3,)).astype(np.float32)
    out = mx.nd.FullyConnected(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b),
                               num_hidden=3)
    assert_almost_equal(out, x @ w.T + b, rtol=1e-4, atol=1e-5)
    check_numeric_gradient(
        lambda a, ww, bb: mx.nd.FullyConnected(a, ww, bb, num_hidden=3).sum(),
        [x, w, b], rtol=2e-2, atol=1e-2)


def test_convolution_vs_reference():
    # compare against explicit im2col NumPy conv
    x = np.random.uniform(-1, 1, (1, 2, 5, 5)).astype(np.float32)
    w = np.random.uniform(-1, 1, (3, 2, 3, 3)).astype(np.float32)
    out = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), no_bias=True,
                            kernel=(3, 3), num_filter=3).asnumpy()
    ref = np.zeros((1, 3, 3, 3), np.float32)
    for o in range(3):
        for i in range(3):
            for j in range(3):
                patch = x[0, :, i:i + 3, j:j + 3]
                ref[0, o, i, j] = (patch * w[o]).sum()
    assert_almost_equal(out, ref, rtol=1e-3, atol=1e-4)


def test_conv_grouped_and_strided():
    x = mx.nd.uniform(shape=(2, 4, 8, 8))
    w = mx.nd.uniform(shape=(4, 1, 3, 3))
    out = mx.nd.Convolution(x, w, no_bias=True, kernel=(3, 3), num_filter=4,
                            num_group=4, stride=(2, 2), pad=(1, 1))
    assert out.shape == (2, 4, 4, 4)


def test_pooling():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert out.asnumpy().reshape(2, 2).tolist() == [[5, 7], [13, 15]]
    avg = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="avg")
    assert avg.asnumpy().reshape(2, 2).tolist() == [[2.5, 4.5], [10.5, 12.5]]
    gl = mx.nd.Pooling(mx.nd.array(x), global_pool=True, pool_type="max")
    assert gl.shape == (1, 1, 1, 1) and float(gl.asscalar()) == 15


def test_softmax_and_grad():
    x = np.random.uniform(-2, 2, (3, 5)).astype(np.float32)
    out = mx.nd.softmax(mx.nd.array(x))
    e = np.exp(x - x.max(1, keepdims=True))
    assert_almost_equal(out, e / e.sum(1, keepdims=True), rtol=1e-5)
    check_numeric_gradient(lambda a: mx.nd.softmax(a).sum(), [x], rtol=2e-2, atol=1e-3)
    ls = mx.nd.log_softmax(mx.nd.array(x))
    assert_almost_equal(ls, np.log(e / e.sum(1, keepdims=True)), rtol=1e-4, atol=1e-5)


def test_batchnorm_modes():
    x = np.random.uniform(-1, 1, (4, 3, 2, 2)).astype(np.float32)
    gamma = np.ones(3, np.float32)
    beta = np.zeros(3, np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    args = [mx.nd.array(v) for v in (x, gamma, beta, mm, mv)]
    # inference: normalize by moving stats
    out = mx.nd.BatchNorm(*args, eps=0.0)
    assert_almost_equal(out, x, rtol=1e-4, atol=1e-5)
    # training: batch stats
    with ag.record():
        out_t = mx.nd.BatchNorm(*args, eps=1e-5)
    o = out_t.asnumpy()
    assert abs(o.mean(axis=(0, 2, 3))).max() < 1e-4
    assert abs(o.std(axis=(0, 2, 3)) - 1).max() < 1e-2


def test_layernorm():
    x = np.random.uniform(-1, 1, (2, 5)).astype(np.float32)
    g = np.random.uniform(0.5, 1.5, (5,)).astype(np.float32)
    b = np.random.uniform(-0.5, 0.5, (5,)).astype(np.float32)
    out = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(g), mx.nd.array(b), eps=1e-5)
    mu = x.mean(-1, keepdims=True)
    sd = np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    assert_almost_equal(out, (x - mu) / sd * g + b, rtol=1e-4, atol=1e-5)
    check_numeric_gradient(
        lambda a, gg, bb: mx.nd.LayerNorm(a, gg, bb).sum(), [x, g, b],
        rtol=2e-2, atol=1e-2)


def test_activations():
    x = np.array([-2., -0.5, 0., 0.5, 2.], np.float32)
    nd = mx.nd.array(x)
    assert_almost_equal(mx.nd.Activation(nd, act_type="relu"), np.maximum(x, 0))
    assert_almost_equal(mx.nd.Activation(nd, act_type="sigmoid"), 1 / (1 + np.exp(-x)),
                        rtol=1e-5)
    assert_almost_equal(mx.nd.LeakyReLU(nd, act_type="leaky", slope=0.1),
                        np.where(x > 0, x, 0.1 * x), rtol=1e-6)
    assert_almost_equal(mx.nd.LeakyReLU(nd, act_type="elu", slope=1.0),
                        np.where(x > 0, x, np.exp(x) - 1), rtol=1e-5)
    g = mx.nd.array(np.array([0.2], np.float32))
    assert_almost_equal(mx.nd.LeakyReLU(nd, g, act_type="prelu"),
                        np.where(x > 0, x, 0.2 * x), rtol=1e-6)


def test_take_embedding_onehot():
    w = np.random.uniform(-1, 1, (10, 4)).astype(np.float32)
    idx = np.array([1, 3, 5], np.float32)
    out = mx.nd.Embedding(mx.nd.array(idx), mx.nd.array(w), input_dim=10, output_dim=4)
    assert_almost_equal(out, w[[1, 3, 5]])
    t = mx.nd.take(mx.nd.array(w), mx.nd.array(idx))
    assert_almost_equal(t, w[[1, 3, 5]])
    oh = mx.nd.one_hot(mx.nd.array([0, 2]), 3)
    assert oh.asnumpy().tolist() == [[1, 0, 0], [0, 0, 1]]
    # embedding gradient is scatter-add
    wnd = mx.nd.array(w)
    wnd.attach_grad()
    with ag.record():
        y = mx.nd.Embedding(mx.nd.array(np.array([1, 1, 2], np.float32)), wnd,
                            input_dim=10, output_dim=4).sum()
    y.backward()
    expect = np.zeros_like(w)
    expect[1] = 2
    expect[2] = 1
    assert_almost_equal(wnd.grad, expect)


def test_concat_split_stack():
    a = np.ones((2, 3), np.float32)
    b = 2 * np.ones((2, 3), np.float32)
    c = mx.nd.Concat(mx.nd.array(a), mx.nd.array(b), dim=0)
    assert c.shape == (4, 3)
    parts = mx.nd.SliceChannel(c, num_outputs=2, axis=0)
    assert_almost_equal(parts[0], a)
    assert_almost_equal(parts[1], b)
    s = mx.nd.stack(mx.nd.array(a), mx.nd.array(b), axis=0)
    assert s.shape == (2, 2, 3)


def test_transpose_slice_pad():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    nd = mx.nd.array(x)
    assert_almost_equal(mx.nd.transpose(nd, axes=(2, 0, 1)), x.transpose(2, 0, 1))
    assert_almost_equal(mx.nd.slice(nd, begin=(0, 1), end=(2, 3)), x[0:2, 1:3])
    assert_almost_equal(mx.nd.slice_axis(nd, axis=2, begin=1, end=3), x[:, :, 1:3])
    assert_almost_equal(mx.nd.reverse(nd, axis=1), x[:, ::-1, :])
    assert_almost_equal(mx.nd.tile(nd, reps=(1, 2, 1)), np.tile(x, (1, 2, 1)))
    x4 = np.ones((1, 1, 2, 2), np.float32)
    padded = mx.nd.pad(mx.nd.array(x4), mode="constant",
                       pad_width=(0, 0, 0, 0, 1, 1, 1, 1), constant_value=9)
    assert padded.shape == (1, 1, 4, 4)
    assert float(padded[0, 0, 0, 0].asscalar()) == 9


def test_ordering():
    x = np.array([[3., 1., 2.], [0., 5., 4.]], np.float32)
    nd = mx.nd.array(x)
    assert_almost_equal(mx.nd.sort(nd), np.sort(x, -1))
    assert_almost_equal(mx.nd.argsort(nd), np.argsort(x, -1).astype(np.float32))
    tk = mx.nd.topk(nd, k=2, ret_typ="value")
    assert tk.asnumpy().tolist() == [[3, 2], [5, 4]]
    ti = mx.nd.topk(nd, k=1)
    assert ti.asnumpy().reshape(-1).tolist() == [0, 1]


def test_where_clip_misc():
    cond = mx.nd.array([1., 0., 1.])
    a = mx.nd.array([1., 2., 3.])
    b = mx.nd.array([10., 20., 30.])
    assert mx.nd.where(cond, a, b).asnumpy().tolist() == [1, 20, 3]
    assert mx.nd.clip(b, 15, 25).asnumpy().tolist() == [15, 20, 25]
    assert_almost_equal(mx.nd.elemwise_sum(a, a, a), 3 * a.asnumpy())


def test_sequence_ops():
    x = np.arange(24, dtype=np.float32).reshape(4, 2, 3)  # (T, N, C)
    length = mx.nd.array([2., 4.])
    masked = mx.nd.SequenceMask(mx.nd.array(x), length, use_sequence_length=True,
                                value=-1.0)
    m = masked.asnumpy()
    assert (m[2:, 0] == -1).all() and (m[:, 1] != -1).all()
    last = mx.nd.SequenceLast(mx.nd.array(x), length, use_sequence_length=True)
    assert_almost_equal(last, np.stack([x[1, 0], x[3, 1]]))
    rev = mx.nd.SequenceReverse(mx.nd.array(x), length, use_sequence_length=True)
    assert_almost_equal(rev.asnumpy()[0, 0], x[1, 0])
    assert_almost_equal(rev.asnumpy()[0, 1], x[3, 1])


def test_softmax_output_grad():
    x = np.random.uniform(-1, 1, (4, 3)).astype(np.float32)
    label = np.array([0, 1, 2, 1], np.float32)
    data = mx.nd.array(x)
    data.attach_grad()
    with ag.record():
        out = mx.nd.SoftmaxOutput(data, mx.nd.array(label))
    out.backward()
    p = np.exp(x - x.max(1, keepdims=True))
    p /= p.sum(1, keepdims=True)
    expect = p.copy()
    expect[np.arange(4), label.astype(int)] -= 1
    assert_almost_equal(data.grad, expect, rtol=1e-4, atol=1e-5)


def test_rnn_lstm_shapes():
    T, N, I, H, L = 5, 2, 4, 8, 2
    from mxtpu.ops.rnn_ops import rnn_param_size
    psz = rnn_param_size("lstm", L, I, H)
    params = mx.nd.uniform(-0.1, 0.1, shape=(psz,))
    x = mx.nd.uniform(shape=(T, N, I))
    h0 = mx.nd.zeros((L, N, H))
    c0 = mx.nd.zeros((L, N, H))
    out = mx.nd.RNN(x, params, h0, c0, state_size=H, num_layers=L, mode="lstm")
    assert out.shape == (T, N, H)
    outs = mx.nd.RNN(x, params, h0, c0, state_size=H, num_layers=L, mode="lstm",
                     state_outputs=True)
    assert outs[1].shape == (L, N, H) and outs[2].shape == (L, N, H)
    # bidirectional GRU
    psz = rnn_param_size("gru", 1, I, H, bidirectional=True)
    params = mx.nd.uniform(-0.1, 0.1, shape=(psz,))
    h0 = mx.nd.zeros((2, N, H))
    out = mx.nd.RNN(x, params, h0, state_size=H, num_layers=1, mode="gru",
                    bidirectional=True)
    assert out.shape == (T, N, 2 * H)


def test_control_flow_foreach():
    def step(x, state):
        new = state + x
        return new, new

    data = mx.nd.array(np.arange(6, dtype=np.float32).reshape(3, 2))
    init = mx.nd.zeros((2,))
    outs, final = mx.ops.invoke("foreach", step, data, init)
    assert_almost_equal(final, data.asnumpy().sum(0))
    assert_almost_equal(outs, np.cumsum(data.asnumpy(), 0))


def test_control_flow_while_cond():
    def cond_fn(i, s):
        return i < 5

    def body_fn(i, s):
        return [i + 1, s + i]

    _, (i_f, s_f) = mx.ops.invoke("while_loop", cond_fn, body_fn,
                                  [mx.nd.array([0.0]), mx.nd.array([0.0])])
    assert float(i_f.asscalar()) == 5
    assert float(s_f.asscalar()) == 10
    r = mx.ops.invoke("cond", mx.nd.array([1.0]),
                      lambda x: x * 2, lambda x: x * 3, mx.nd.array([7.0]))
    assert float(r.asscalar()) == 14


def test_linalg():
    a = np.random.uniform(-1, 1, (3, 3)).astype(np.float32)
    spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
    L = mx.nd.linalg_potrf(mx.nd.array(spd))
    assert_almost_equal(mx.nd.dot(L, L.T), spd, rtol=1e-3, atol=1e-4)
    assert_almost_equal(mx.nd.linalg_sumlogdiag(mx.nd.array(spd)),
                        np.log(np.diag(spd)).sum(), rtol=1e-4)


def test_random_ops():
    u = mx.nd.uniform(0, 1, shape=(1000,))
    a = u.asnumpy()
    assert 0 <= a.min() and a.max() <= 1 and 0.4 < a.mean() < 0.6
    n = mx.nd.normal(0, 1, shape=(2000,)).asnumpy()
    assert abs(n.mean()) < 0.1 and 0.8 < n.std() < 1.2
    mx.random.seed(42)
    x1 = mx.nd.uniform(shape=(5,)).asnumpy()
    mx.random.seed(42)
    x2 = mx.nd.uniform(shape=(5,)).asnumpy()
    assert (x1 == x2).all()
    m = mx.nd.multinomial(mx.nd.array([0., 0., 1., 0.]))
    assert int(m.asscalar()) == 2


def test_optimizer_ops():
    w = mx.nd.array([1.0, 2.0])
    g = mx.nd.array([0.1, 0.1])
    mx.nd.sgd_update(w, g, 0.5)  # lr positional
    assert_almost_equal(w, [0.95, 1.95])
    w = mx.nd.array([1.0])
    mom = mx.nd.zeros((1,))
    mx.nd.sgd_mom_update(w, mx.nd.array([1.0]), mom, 0.1, momentum=0.9)
    assert_almost_equal(w, [0.9])
    assert_almost_equal(mom, [-0.1])


def test_gather_scatter():
    data = mx.nd.array(np.arange(9, dtype=np.float32).reshape(3, 3))
    idx = mx.nd.array([[0, 2], [1, 1]])  # (2, M) indexing dims 0,1
    out = mx.nd.gather_nd(data, idx)
    assert out.asnumpy().tolist() == [1, 7]
    sc = mx.nd.scatter_nd(mx.nd.array([5.0, 6.0]), idx, shape=(3, 3))
    assert float(sc[0, 1].asscalar()) == 5 and float(sc[2, 1].asscalar()) == 6
