"""Profiler tests (ref pattern: tests/python/unittest/test_profiler.py)."""
import json

import numpy as np

import mxtpu as mx
from mxtpu import profiler


def test_profiler_records_ops_and_dumps(tmp_path):
    fname = str(tmp_path / "trace.json")
    profiler.set_config(filename=fname)
    profiler.start()
    a = mx.nd.ones((32, 32))
    b = mx.nd.dot(a, a)
    (b + 1).asnumpy()
    profiler.stop()
    profiler.dump()
    with open(fname) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert any("dot" in n for n in names), names
    assert all(e["ph"] == "X" for e in trace["traceEvents"])
    stats = profiler.dumps()
    assert "Calls" in stats


def test_profiler_scopes(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t.json"))
    profiler.start()
    with profiler.ProfileTask("mytask"):
        mx.nd.ones((4,)).asnumpy()
    profiler.stop()
    stats = profiler.dumps(reset=True)
    assert "mytask" in stats


def test_profiler_off_records_nothing(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t2.json"))
    profiler.dumps(reset=True)
    mx.nd.ones((4,)).asnumpy()
    stats = profiler.dumps()
    assert "ones" not in stats


def test_xla_trace_bounded_and_idempotent(tmp_path):
    """A hung workload cannot leave a device capture running: the bounded
    watchdog stops it, and every later stop path is a no-op (the round-3
    chip wedge came from a capture with no surviving stopper)."""
    import glob
    import time

    d = str(tmp_path / "xla")
    profiler.set_config(filename=str(tmp_path / "t.json"), profile_xla=True,
                        xla_trace_dir=d, xla_trace_max_s=1.0)
    profiler.start()
    mx.nd.ones((8, 8)).asnumpy()
    time.sleep(2.5)  # watchdog fires at 1s while "workload" is stuck
    assert not profiler._PROF._xla_tracing
    profiler.stop()          # second stop: must not raise
    profiler._stop_xla_trace()  # third: still a no-op
    assert glob.glob(d + "/**/*.xplane.pb", recursive=True)
    profiler.set_config(filename=str(tmp_path / "t.json"))  # reset config


def test_orphan_guard_noops_while_parent_alive():
    t = profiler.install_orphan_guard(poll_s=0.05)
    import time
    time.sleep(0.2)
    assert t.is_alive()  # parent (us) still alive -> guard keeps watching
