"""Profiler tests (ref pattern: tests/python/unittest/test_profiler.py)."""
import json

import numpy as np

import mxtpu as mx
from mxtpu import profiler


def test_profiler_records_ops_and_dumps(tmp_path):
    fname = str(tmp_path / "trace.json")
    profiler.set_config(filename=fname)
    profiler.start()
    a = mx.nd.ones((32, 32))
    b = mx.nd.dot(a, a)
    (b + 1).asnumpy()
    profiler.stop()
    profiler.dump()
    with open(fname) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert any("dot" in n for n in names), names
    assert all(e["ph"] == "X" for e in trace["traceEvents"])
    stats = profiler.dumps()
    assert "Calls" in stats


def test_profiler_scopes(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t.json"))
    profiler.start()
    with profiler.ProfileTask("mytask"):
        mx.nd.ones((4,)).asnumpy()
    profiler.stop()
    stats = profiler.dumps(reset=True)
    assert "mytask" in stats


def test_profiler_off_records_nothing(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t2.json"))
    profiler.dumps(reset=True)
    mx.nd.ones((4,)).asnumpy()
    stats = profiler.dumps()
    assert "ones" not in stats
