"""Profiler tests (ref pattern: tests/python/unittest/test_profiler.py)."""
import json

import numpy as np

import mxtpu as mx
from mxtpu import profiler


def test_profiler_records_ops_and_dumps(tmp_path):
    fname = str(tmp_path / "trace.json")
    profiler.set_config(filename=fname)
    profiler.start()
    a = mx.nd.ones((32, 32))
    b = mx.nd.dot(a, a)
    (b + 1).asnumpy()
    profiler.stop()
    profiler.dump()
    with open(fname) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]}
    assert any("dot" in n for n in names), names
    assert all(e["ph"] == "X" for e in trace["traceEvents"])
    stats = profiler.dumps()
    assert "Calls" in stats


def test_profiler_scopes(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t.json"))
    profiler.start()
    with profiler.ProfileTask("mytask"):
        mx.nd.ones((4,)).asnumpy()
    profiler.stop()
    stats = profiler.dumps(reset=True)
    assert "mytask" in stats


def test_profiler_off_records_nothing(tmp_path):
    profiler.set_config(filename=str(tmp_path / "t2.json"))
    profiler.dumps(reset=True)
    mx.nd.ones((4,)).asnumpy()
    stats = profiler.dumps()
    assert "ones" not in stats


def test_xla_trace_bounded_and_idempotent(tmp_path):
    """A hung workload cannot leave a device capture running: the bounded
    watchdog stops it, and every later stop path is a no-op (the round-3
    chip wedge came from a capture with no surviving stopper)."""
    import glob
    import time

    d = str(tmp_path / "xla")
    profiler.set_config(filename=str(tmp_path / "t.json"), profile_xla=True,
                        xla_trace_dir=d, xla_trace_max_s=1.0)
    profiler.start()
    mx.nd.ones((8, 8)).asnumpy()
    # watchdog fires at 1s while the "workload" is stuck; poll rather than
    # fixed-sleep — under an oversubscribed host (parallel suite runs) the
    # timer thread can be scheduled well past its deadline
    deadline = time.time() + 20
    while profiler._PROF._xla_tracing and time.time() < deadline:
        time.sleep(0.25)
    assert not profiler._PROF._xla_tracing
    profiler.stop()          # second stop: must not raise
    profiler._stop_xla_trace()  # third: still a no-op
    assert glob.glob(d + "/**/*.xplane.pb", recursive=True)
    profiler.set_config(filename=str(tmp_path / "t.json"))  # reset config


def test_orphan_guard_noops_while_parent_alive():
    t = profiler.install_orphan_guard(poll_s=0.05)
    import time
    time.sleep(0.2)
    assert t.is_alive()  # parent (us) still alive -> guard keeps watching


def test_profiler_autostart_env(tmp_path):
    """MXTPU_PROFILER_AUTOSTART=1 profiles the whole program with no code
    changes and dumps profile.json at exit (ref env_var.md:152)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS",)}
    env.update({"PYTHONPATH": repo, "JAX_PLATFORMS": "cpu",
                "MXTPU_PROFILER_AUTOSTART": "1"})
    code = ("import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import mxtpu as mx\n"
            "mx.nd.dot(mx.nd.ones((4, 4)), mx.nd.ones((4, 4))).asnumpy()\n")
    out = subprocess.run([sys.executable, "-c", code], cwd=str(tmp_path),
                         env=env, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stderr[-1500:]
    trace = json.loads((tmp_path / "profile.json").read_text())
    assert any("dot" in e["name"] for e in trace["traceEvents"])
