"""MXU precision policy guard (PERF.md root cause).

bf16 contractions must lower with precision DEFAULT (native one-pass MXU);
f32 contractions must keep HIGHEST (the honest-f32 global). A regression
here silently costs 3-6x conv throughput on TPU, which is exactly what
capped rounds 1-2 — so the policy is pinned by inspecting lowered
StableHLO, not by timing.
"""
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import mxtpu as mx


def _conv_precisions(dtype):
    from mxtpu.ops.registry import REGISTRY

    conv_fn = REGISTRY["Convolution"].fn  # raw jnp-level op
    x = jnp.zeros((1, 8, 8, 4), dtype)
    w = jnp.zeros((3, 3, 4, 8), dtype)
    lowered = jax.jit(lambda a, b: conv_fn(
        a, b, kernel=(3, 3), num_filter=8, no_bias=True,
        layout="NHWC")).lower(x, w)
    txt = lowered.as_text()
    return re.findall(r"precision_config = \[([^\]]*)\]", txt)


def test_bf16_conv_uses_default_precision():
    precs = _conv_precisions(jnp.bfloat16)
    assert precs and all("DEFAULT" in p for p in precs), precs


def test_f32_conv_keeps_highest_precision():
    precs = _conv_precisions(jnp.float32)
    assert precs and all("HIGHEST" in p for p in precs), precs


def test_mixed_dtype_falls_back_to_honest_precision():
    """bf16 weights with f32 activations must NOT downgrade to one-pass
    bf16 — the honest global wins when any operand is f32."""
    from mxtpu.ops.precision_util import mxu_precision
    from jax import lax

    assert mxu_precision(jnp.zeros((2,), jnp.bfloat16),
                         jnp.zeros((2,), jnp.float32)) is None
    assert mxu_precision(jnp.zeros((2,), jnp.bfloat16),
                         jnp.zeros((2,), jnp.bfloat16)) \
        == lax.Precision.DEFAULT


def test_whole_resnet_step_precision():
    """The exact bench model: every conv in the full train step must be
    DEFAULT under bf16 (158/158 were HIGHEST before the fix)."""
    from mxtpu import gluon
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.parallel import ShardedTrainStep, data_parallel_mesh

    with mx.layout("NHWC"):
        net = vision.resnet18_v1()
    net.initialize()
    x = mx.nd.array(np.zeros((8, 224, 224, 3), np.float32))
    net(x)
    net.cast("bfloat16")
    x = x.astype("bfloat16")
    y = mx.nd.zeros((8,))
    step = ShardedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            data_parallel_mesh(), optimizer="sgd")
    step(x, y)
    txt = step._jit.lower(*step._last_abstract).as_text()
    convs = re.findall(r"convolution.*", txt)
    assert convs
    bad = [c for c in convs if "HIGHEST" in c]
    assert not bad, "%d/%d convs at HIGHEST precision" % (len(bad),
                                                          len(convs))


def test_bn_onepass_stats_match_twopass(monkeypatch):
    """MXTPU_BN_ONEPASS=1 (single-read E[x^2]-mean^2 stats, the staged
    round-4 HBM lever) must match the two-pass default to f32 tolerance
    in training mode, eager AND hybridized (the policy is part of the
    jit cache key — registry.policy_key — so the hybrid A/B genuinely
    recompiles rather than reusing the first trace)."""
    import numpy as np

    import mxtpu as mx
    from mxtpu import autograd
    from mxtpu.gluon import nn

    x = np.random.RandomState(0).uniform(-2, 2, (8, 6, 5, 5)) \
        .astype(np.float32)

    def run(hybridize):
        mx.random.seed(0)
        np.random.seed(0)
        net = nn.BatchNorm(in_channels=6)
        net.initialize()
        if hybridize:
            net.hybridize()
        with autograd.record():
            out = net(mx.nd.array(x))
        return out.asnumpy()

    for hyb in (False, True):
        monkeypatch.setenv("MXTPU_BN_ONEPASS", "0")  # explicit two-pass
        two = run(hyb)                               # (default is now 1)
        monkeypatch.setenv("MXTPU_BN_ONEPASS", "1")
        one = run(hyb)
        np.testing.assert_allclose(one, two, rtol=1e-4, atol=1e-5)

    # the cache-key guarantee itself: one SHARED hybridized net must
    # recompile when the policy flips (a stale reuse would make A/B
    # measurements vacuous)
    net = nn.BatchNorm(in_channels=6)
    net.initialize()
    net.hybridize()
    monkeypatch.setenv("MXTPU_BN_ONEPASS", "0")
    with autograd.record():
        net(mx.nd.array(x))
    n_jits = len(net._cached_op._jits) if net._cached_op else 0
    monkeypatch.setenv("MXTPU_BN_ONEPASS", "1")
    with autograd.record():
        net(mx.nd.array(x))
    assert len(net._cached_op._jits) > n_jits, \
        "policy flip did not recompile the cached executable"
