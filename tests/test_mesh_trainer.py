"""Mesh-native gluon Trainer (ISSUE 7) — the multi-chip fast path.

``Trainer(mesh=...)`` lays parameters/optimizer state out on a
``jax.sharding.Mesh`` at kvstore-init time, shards the batch on the data
axis, and routes :meth:`Trainer.step` through the SAME donated
FusedUpdater jit — with ZeRO-1 weight-update sharding (arXiv:2004.13336)
composed into it. Pins, on the 8-device virtual CPU mesh:

* numeric transparency: a replicated-batch mesh run is BIT-exact vs the
  plain single-device Trainer (losses AND params) for sgd+adam, ZeRO
  on/off — the mesh machinery itself adds zero numeric drift;
* ZeRO-1 on vs off under a data-sharded batch is bit-identical (the
  arXiv:2004.13336 equivalence), and the sharded-batch run tracks the
  single-device trajectory to reduce-order ULPs;
* structure: per-replica optimizer-state shard bytes = replicated/8;
* trace discipline: steady-state ``trainer.step`` keeps d2h == 0 and the
  ``fused_optimizer`` retrace site flat after warmup; a guard-policy
  flip costs exactly one recompile; the MeshPlan is part of the jit
  cache key (a mesh attach never reuses a single-device executable);
* checkpointing: orbax save/load round-trips the sharded state and
  resumes bit-exact;
* control plane: ``shard_batch`` validation, ``MXTPU_MESH`` auto-mesh,
  mesh/kvstore incompatibility errors, grouped-push tree-sum on an
  attached mesh;
* the ``pure_forward`` RNG fix: ``train=True`` draws a fresh dropout
  mask per call instead of silently replaying ``PRNGKey(0)``.
"""
import numpy as np
import pytest

import jax

import mxtpu as mx
from mxtpu import autograd, gluon, telemetry
from mxtpu import kvstore as kv_mod
from mxtpu import optimizer_fused as of
from mxtpu.base import MXNetError
from mxtpu.gluon import nn
from mxtpu.parallel import make_mesh, pure_forward


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_MESH", "MXTPU_ZERO1", "MXTPU_NUMERICS_GUARD",
                "MXTPU_RETRACE_BUDGET", "MXTPU_FUSED_OPTIMIZER"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    of.reset()
    yield
    telemetry.reset()
    of.reset()


_OPTS = {"sgd": {"learning_rate": 0.1, "momentum": 0.9},
         "adam": {"learning_rate": 0.01}}


def _build(seed=0, hidden=32, out=8):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation="relu"), nn.Dense(out))
    net.initialize()
    return net


def _data(n=16, d=16, classes=8):
    x = mx.nd.array(np.random.RandomState(0).randn(n, d).astype(np.float32))
    y = mx.nd.array(np.random.RandomState(1).randint(0, classes, (n,))
                    .astype(np.float32))
    return x, y


def _run(mesh=None, zero1=False, opt="sgd", steps=6, shard=True, out=8,
         fetch_loss=True):
    """Train the reference MLP; returns (losses, params, trainer)."""
    net = _build(out=out)
    x, y = _data(classes=out)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), opt, dict(_OPTS[opt]),
                       mesh=mesh, zero1=zero1)
    losses = []
    for _ in range(steps):
        xs, ys = tr.shard_batch(x, y) if (mesh is not None and shard) \
            else (x, y)
        with autograd.record():
            l = loss_fn(net(xs), ys).mean()
        l.backward()
        tr.step(1)
        if fetch_loss:
            losses.append(float(l.asnumpy()))
    params = [p.data().asnumpy() for p in net.collect_params().values()]
    return losses, params, tr


def _state_leaves(tr):
    upd = tr._updaters[0]
    return [leaf._data if hasattr(leaf, "_data") else leaf
            for i in sorted(upd.states)
            for leaf in jax.tree_util.tree_leaves(upd.states[i])]


# ------------------------------------------------------------ numeric parity
@pytest.mark.multidevice
@pytest.mark.parametrize("opt", ["sgd", "adam"])
@pytest.mark.parametrize("zero1", [False, True])
def test_mesh_trainer_bit_exact_vs_single_device(opt, zero1):
    """A replicated-batch mesh run must be BIT-exact vs the plain
    single-device Trainer: every collective the mesh step adds (ZeRO
    reduce-scatter/all-gather included) is numerically transparent.
    The data-sharded comparison lives in the next test — cross-device
    gradient summation reorders fp adds, so THAT contract is ULP-tight,
    not bitwise."""
    base_l, base_p, _ = _run(None, opt=opt)
    mesh = make_mesh({"data": 8})
    mesh_l, mesh_p, _ = _run(mesh, zero1=zero1, opt=opt, shard=False)
    assert mesh_l == base_l
    for a, b in zip(mesh_p, base_p):
        assert np.array_equal(a, b)


@pytest.mark.multidevice
@pytest.mark.parametrize("opt", ["sgd", "adam"])
def test_data_sharded_zero1_on_off_bit_exact(opt):
    """Under a data-sharded batch, ZeRO-1 on vs off is bit-identical
    (the arXiv:2004.13336 equivalence: reduce-scatter + shard-local
    update + all-gather == replicated update), and both track the
    single-device trajectory to reduce-order ULPs."""
    mesh = make_mesh({"data": 8})
    l_off, p_off, _ = _run(mesh, zero1=False, opt=opt)
    l_on, p_on, _ = _run(mesh, zero1=True, opt=opt)
    assert l_on == l_off
    for a, b in zip(p_on, p_off):
        assert np.array_equal(a, b)
    base_l, base_p, _ = _run(None, opt=opt)
    np.testing.assert_allclose(l_on, base_l, rtol=0, atol=2e-6)
    for a, b in zip(p_on, base_p):
        np.testing.assert_allclose(a, b, rtol=0, atol=2e-6)


# ----------------------------------------------------------- ZeRO structure
@pytest.mark.multidevice
def test_zero1_state_shard_shapes_are_one_eighth():
    """Per-replica optimizer-state memory divides by the axis size: every
    state leaf of the (all-dim0-divisible) net is laid out
    P('data'), its addressable shard holds 1/8 of the rows, and summed
    per-device state bytes == replicated/8."""
    mesh = make_mesh({"data": 8})
    _, _, tr_on = _run(mesh, zero1=True, opt="adam", steps=2)
    _, _, tr_off = _run(mesh, zero1=False, opt="adam", steps=2)
    on, off = _state_leaves(tr_on), _state_leaves(tr_off)
    assert len(on) == len(off) and on
    per_replica = replicated = 0
    for a, b in zip(on, off):
        assert a.sharding.spec == jax.sharding.PartitionSpec("data")
        assert b.sharding.spec == jax.sharding.PartitionSpec()
        shard = a.addressable_shards[0].data
        assert shard.shape[0] * 8 == a.shape[0]
        assert shard.shape[1:] == a.shape[1:]
        per_replica += shard.nbytes
        replicated += b.addressable_shards[0].data.nbytes
    assert per_replica * 8 == replicated


@pytest.mark.multidevice
def test_zero1_indivisible_param_falls_back_replicated():
    """dim 0 not divisible by the axis (out=10 on 8 devices) keeps that
    param's state replicated — and the run still bit-matches the
    single-device trajectory under a replicated batch."""
    mesh = make_mesh({"data": 8})
    base_l, base_p, _ = _run(None, opt="sgd", out=10)
    mesh_l, mesh_p, tr = _run(mesh, zero1=True, opt="sgd", shard=False,
                              out=10)
    assert mesh_l == base_l
    for a, b in zip(mesh_p, base_p):
        assert np.array_equal(a, b)
    specs = [l.sharding.spec for l in _state_leaves(tr)]
    assert jax.sharding.PartitionSpec("data") in specs   # 32-row layer
    assert jax.sharding.PartitionSpec() in specs         # 10-row layer


# --------------------------------------------------------- trace discipline
@pytest.mark.multidevice
def test_step_d2h_zero_and_retrace_flat(monkeypatch):
    """Steady-state contract on the mesh path: after warmup, more steps
    add ZERO compiles at the fused_optimizer retrace site and ZERO d2h
    syncs inside trainer.step; a guard-policy flip then costs exactly
    one recompile (the policy bit is in the cache key)."""
    mesh = make_mesh({"data": 8})
    net = _build()
    x, y = _data()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam", dict(_OPTS["adam"]),
                       mesh=mesh, zero1=True)

    def one_step():
        xs, ys = tr.shard_batch(x, y)
        with autograd.record():
            l = loss_fn(net(xs), ys).mean()
        l.backward()
        tr.step(1)

    for _ in range(2):   # warmup: the single mesh-step compile
        one_step()
    warm = telemetry.retrace_stats("fused_optimizer")["compiles"]
    telemetry.reset_metric("trainer.step.d2h")
    for _ in range(4):
        one_step()
    assert telemetry.retrace_stats("fused_optimizer")["compiles"] == warm
    assert telemetry.value("trainer.step.d2h") == 0
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")   # induced policy flip
    one_step()
    assert telemetry.retrace_stats("fused_optimizer")["compiles"] == warm + 1


@pytest.mark.multidevice
def test_mesh_plan_is_part_of_jit_cache_key():
    """The same optimizer/shapes stepped single-device, on a mesh, and
    with ZeRO flipped are THREE distinct executables — sharding is part
    of the fused-update cache key (ROADMAP item 5 down payment), never a
    silent reuse across placements."""
    of.reset()
    _run(None, opt="sgd", steps=1)
    assert of.cache_size() == 1
    mesh = make_mesh({"data": 8})
    _run(mesh, zero1=False, opt="sgd", steps=1)
    assert of.cache_size() == 2
    _run(mesh, zero1=True, opt="sgd", steps=1)
    assert of.cache_size() == 3
    # same axis shape over DIFFERENT devices: the ZeRO constraints close
    # over the concrete mesh, so these must not share an executable either
    _run(make_mesh({"data": 4}, jax.devices()[:4]), zero1=True, opt="sgd",
         steps=1)
    assert of.cache_size() == 4
    _run(make_mesh({"data": 4}, jax.devices()[4:]), zero1=True, opt="sgd",
         steps=1)
    assert of.cache_size() == 5


# ------------------------------------------------------------- checkpointing
@pytest.mark.multidevice
def test_trainer_checkpoint_roundtrip_sharded(tmp_path):
    """save_trainer/load_trainer round-trip the ZeRO-sharded state: the
    restored trainer's state goes back onto the MeshPlan layout and the
    continued trajectory is bit-exact vs the uninterrupted run."""
    from mxtpu.contrib import async_checkpoint as ackpt
    mesh = make_mesh({"data": 8})
    net = _build()
    x, y = _data()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam", dict(_OPTS["adam"]),
                       mesh=mesh, zero1=True)

    def steps(trainer, model, n):
        out = []
        for _ in range(n):
            xs, ys = trainer.shard_batch(x, y)
            with autograd.record():
                l = loss_fn(model(xs), ys).mean()
            l.backward()
            trainer.step(1)
            out.append(float(l.asnumpy()))
        return out

    steps(tr, net, 3)
    ackpt.save_trainer(tr, str(tmp_path), step=3)
    ref = steps(tr, net, 2)   # the uninterrupted continuation

    net2 = _build(seed=42)    # different init on purpose
    tr2 = gluon.Trainer(net2.collect_params(), "adam", dict(_OPTS["adam"]),
                        mesh=mesh, zero1=True)
    steps(tr2, net2, 1)       # settle placement + state creation
    ackpt.load_trainer(tr2, str(tmp_path), step=3)
    leaves = _state_leaves(tr2)
    assert any(l.sharding.spec == jax.sharding.PartitionSpec("data")
               for l in leaves)
    assert steps(tr2, net2, 2) == ref


# -------------------------------------------------------------- control plane
@pytest.mark.multidevice
def test_shard_batch_layout_and_validation():
    mesh = make_mesh({"data": 8})
    net = _build()
    tr = gluon.Trainer(net.collect_params(), "sgd", dict(_OPTS["sgd"]),
                       mesh=mesh)
    x, y = _data()
    xs, ys = tr.shard_batch(x, y)
    for a in (xs, ys):
        assert a._data.sharding.spec == jax.sharding.PartitionSpec("data")
    with pytest.raises(MXNetError):
        tr.shard_batch(mx.nd.ones((15, 4)))   # 15 % 8 != 0
    tr_plain = gluon.Trainer(_build().collect_params(), "sgd",
                             dict(_OPTS["sgd"]))
    assert tr_plain.shard_batch(x) is x       # identity without a mesh


@pytest.mark.multidevice
def test_mxtpu_mesh_env_auto(monkeypatch):
    monkeypatch.setenv("MXTPU_MESH", "auto")
    tr = gluon.Trainer(_build().collect_params(), "sgd", dict(_OPTS["sgd"]))
    assert tr._mesh is not None
    assert tr._mesh.shape["data"] == len(jax.devices())
    monkeypatch.setenv("MXTPU_MESH", "bogus")
    with pytest.raises(MXNetError):
        gluon.Trainer(_build().collect_params(), "sgd", dict(_OPTS["sgd"]))


@pytest.mark.multidevice
def test_mesh_rejects_incompatible_modes():
    mesh = make_mesh({"data": 8})
    with pytest.raises(MXNetError):   # store-side update contradicts mesh
        gluon.Trainer(_build().collect_params(), "sgd", dict(_OPTS["sgd"]),
                      mesh=mesh, update_on_kvstore=True)
    with pytest.raises(MXNetError):   # mesh must carry the data axis
        gluon.Trainer(_build().collect_params(), "sgd", dict(_OPTS["sgd"]),
                      mesh=make_mesh({"model": 8}))


@pytest.mark.multidevice
def test_kvstore_grouped_push_tree_sum_on_mesh():
    """The control-plane store on an attached mesh: init lays values out
    replicated, and a grouped push reduces its copies in ONE fused
    stack-and-sum (not O(copies) sequential adds)."""
    mesh = make_mesh({"data": 8})
    kv = kv_mod.create("device")
    kv.attach_mesh(mesh)
    base = mx.nd.array(np.zeros((4, 2), np.float32))
    kv.init("w", base)
    vals = [mx.nd.array(np.full((4, 2), float(i + 1), np.float32))
            for i in range(3)]
    kv.push("w", vals)
    out = mx.nd.zeros((4, 2))
    kv.pull("w", out)
    np.testing.assert_array_equal(out.asnumpy(),
                                  np.full((4, 2), 6.0, np.float32))
    assert kv._store["w"]._data.sharding.spec == jax.sharding.PartitionSpec()


# ------------------------------------------------------------ pure_forward RNG
def test_pure_forward_train_rng_draws_fresh_key():
    """The RNG footgun pin: train=True with rng=None must NOT replay
    PRNGKey(0) — two stochastic calls draw different dropout masks,
    matching eager semantics; an explicit rng reproduces; train=False
    stays deterministic."""
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(64), nn.Dropout(0.5), nn.Dense(8))
    net.initialize()
    x = mx.nd.ones((4, 16))
    net(x)  # settle shapes
    fn, params = pure_forward(net, train=True)
    a = np.asarray(fn(params, x._data))
    b = np.asarray(fn(params, x._data))
    assert not np.array_equal(a, b)
    key = jax.random.PRNGKey(7)
    np.testing.assert_array_equal(np.asarray(fn(params, x._data, rng=key)),
                                  np.asarray(fn(params, x._data, rng=key)))
    fn_eval, params = pure_forward(net, train=False)
    np.testing.assert_array_equal(np.asarray(fn_eval(params, x._data)),
                                  np.asarray(fn_eval(params, x._data)))
