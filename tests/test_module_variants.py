"""SequentialModule / PythonModule / FeedForward — the rest of the Module
generation (VERDICT r4 missing #3/#4; ref: python/mxnet/module/
sequential_module.py, python_module.py, model.py:451 FeedForward).
"""
import logging
import warnings

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import symbol as sym
from mxtpu.io import DataBatch, DataDesc, NDArrayIter
from mxtpu.model import FeedForward
from mxtpu.module import (Module, PythonLossModule, PythonModule,
                          SequentialModule)


def _toy_dataset(n=256, dim=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.normal(scale=3.0, size=(classes, dim))
    y = rng.randint(0, classes, size=(n,))
    x = centers[y] + rng.normal(scale=0.5, size=(n, dim))
    return x.astype(np.float32), y.astype(np.float32)


def _feature_symbol(num_hidden=32):
    data = sym.var("data")
    net = sym.FullyConnected(data, sym.var("fc1_weight"), sym.var("fc1_bias"),
                             num_hidden=num_hidden, name="fc1")
    return sym.Activation(net, act_type="relu", name="relu1")


def _head_symbol(classes=4):
    # second stage consumes the first stage's output by its output name
    data = sym.var("relu1_output")
    net = sym.FullyConnected(data, sym.var("fc2_weight"), sym.var("fc2_bias"),
                             num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(net, sym.var("softmax_label"), name="softmax")


# ------------------------------------------------------- SequentialModule
def _seq_mod():
    seq = SequentialModule()
    seq.add(Module(_feature_symbol(), data_names=("data",), label_names=None))
    seq.add(Module(_head_symbol(), data_names=("relu1_output",),
                   label_names=("softmax_label",)), take_labels=True,
            auto_wiring=True)
    return seq


def test_sequential_module_trains_toy_problem():
    x, y = _toy_dataset()
    train = NDArrayIter(x, y, batch_size=32, shuffle=True)
    val = NDArrayIter(x, y, batch_size=32)
    seq = _seq_mod()
    seq.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            num_epoch=10, initializer=mx.init.Xavier())
    score = seq.score(val, "acc")
    assert score[0][1] > 0.95, score
    # merged params span both layers
    arg, _aux = seq.get_params()
    assert {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"} <= set(arg)


def test_sequential_module_shapes_and_wiring():
    seq = _seq_mod()
    seq.bind(data_shapes=[DataDesc("data", (16, 8))],
             label_shapes=[DataDesc("softmax_label", (16,))])
    assert seq.data_names == ["data"]
    assert [s for _n, s in seq.output_shapes] == [(16, 4)]
    # label_shapes kept because the head takes labels
    assert seq.label_shapes is not None
    seq.init_params(initializer=mx.init.Xavier())
    batch = DataBatch(data=[mx.nd.ones((16, 8))],
                      label=[mx.nd.zeros((16,))])
    seq.forward(batch, is_train=False)
    out = seq.get_outputs()[0]
    np.testing.assert_allclose(out.asnumpy().sum(axis=1), np.ones(16),
                               rtol=1e-5)


def test_sequential_module_duplicate_param_names_rejected():
    seq = SequentialModule()
    seq.add(Module(_feature_symbol(), data_names=("data",), label_names=None))
    # same parameter names again in layer 1; auto_wiring renames the
    # incoming relu1_output shape to this module's own "data" input
    seq.add(Module(_feature_symbol(), data_names=("data",),
                   label_names=None), auto_wiring=True)
    seq.bind(data_shapes=[DataDesc("data", (4, 8))])
    with pytest.raises(AssertionError, match="Duplicated parameter name"):
        seq.init_params(initializer=mx.init.Xavier())


def test_sequential_module_add_resets_binding():
    seq = _seq_mod()
    seq.bind(data_shapes=[DataDesc("data", (4, 8))],
             label_shapes=[DataDesc("softmax_label", (4,))])
    assert seq.binded
    seq.add(Module(_feature_symbol(), data_names=("x",)))
    assert not seq.binded and not seq.params_initialized


# ----------------------------------------------------------- PythonModule
def test_python_loss_module_in_chain_trains():
    """Feature Module + host-side PythonLossModule with an explicit
    softmax-CE grad_func — the reference's canonical PythonModule use
    (python_module.py:243 docstring)."""
    x, y = _toy_dataset()
    classes = 4

    feat = sym.var("data")
    net = sym.FullyConnected(feat, sym.var("fc1_weight"), sym.var("fc1_bias"),
                             num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, sym.var("fc2_weight"), sym.var("fc2_bias"),
                             num_hidden=classes, name="fc2")
    body = Module(net, data_names=("data",), label_names=None)

    def softmax_ce_grad(scores, labels):
        s = scores.asnumpy()
        s = np.exp(s - s.max(axis=1, keepdims=True))
        p = s / s.sum(axis=1, keepdims=True)
        onehot = np.eye(classes, dtype=np.float32)[
            labels.asnumpy().astype(np.int64)]
        return (p - onehot) / p.shape[0]

    loss = PythonLossModule(name="ce", data_names=("fc2_output",),
                            label_names=("softmax_label",),
                            grad_func=softmax_ce_grad)
    seq = SequentialModule()
    seq.add(body).add(loss, take_labels=True, auto_wiring=True)
    train = NDArrayIter(x, y, batch_size=32, shuffle=True)
    seq.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    seq.init_params(initializer=mx.init.Xavier())
    seq.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})

    def accuracy():
        val = NDArrayIter(x, y, batch_size=32)
        correct = total = 0
        for batch in val:
            seq.forward(batch, is_train=False)
            pred = seq.get_outputs()[0].asnumpy().argmax(axis=1)
            lab = batch.label[0].asnumpy()
            n = lab.shape[0] - batch.pad
            correct += (pred[:n] == lab[:n]).sum()
            total += n
        return correct / total

    before = accuracy()
    for _epoch in range(8):
        train.reset()
        for batch in train:
            seq.forward(batch, is_train=True)
            seq.backward()
            seq.update()
    after = accuracy()
    assert after > max(before, 0.9), (before, after)


def test_python_module_bind_contract():
    class Shapeless(PythonModule):
        def _compute_output_shapes(self):
            return [(self._output_names[0], self._data_shapes[0][1])]

    m = Shapeless(["data"], ["softmax_label"], ["out"])
    m.bind(data_shapes=[("data", (4, 3))],
           label_shapes=[("softmax_label", (4,))])
    assert m.output_shapes == [("out", (4, 3))]
    assert m.get_params() == ({}, {})
    with pytest.raises(AssertionError):
        m2 = Shapeless(["data"], None, ["out"])
        m2.bind(data_shapes=[("data", (4, 3))], grad_req="add")


# ------------------------------------------------------------ FeedForward
def _full_mlp():
    data = sym.var("data")
    net = sym.FullyConnected(data, sym.var("fc1_weight"), sym.var("fc1_bias"),
                             num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, sym.var("fc2_weight"), sym.var("fc2_bias"),
                             num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, sym.var("softmax_label"), name="softmax")


def test_feedforward_fit_predict_score(tmp_path):
    x, y = _toy_dataset()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        model = FeedForward(_full_mlp(), num_epoch=10, optimizer="sgd",
                            learning_rate=0.1, momentum=0.9,
                            numpy_batch_size=32,
                            initializer=mx.init.Xavier())
    model.fit(x, y, logger=logging.getLogger("ff"))
    # score takes a labeled iterator (bare numpy X carries no labels —
    # reference model.py:742 same contract)
    acc = model.score(NDArrayIter(x, y, batch_size=32))
    assert acc > 0.95, acc
    preds = model.predict(x)
    assert preds.shape == (x.shape[0], 4)
    assert (preds.argmax(axis=1) == y).mean() > 0.95
    # return_data round-trips the inputs
    p2, d2, l2 = model.predict(x, return_data=True)
    np.testing.assert_allclose(p2, preds, rtol=1e-5)
    assert d2.shape == x.shape

    # checkpoint round trip through the reference file format
    prefix = str(tmp_path / "ff")
    model.save(prefix, epoch=3)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        loaded = FeedForward.load(prefix, 3)
    assert loaded.begin_epoch == 3
    preds2 = loaded.predict(x)
    np.testing.assert_allclose(preds2, preds, rtol=1e-4, atol=1e-5)


def test_feedforward_create_and_iter_input():
    x, y = _toy_dataset(n=128)
    train = NDArrayIter(x, y, batch_size=32, shuffle=True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        model = FeedForward.create(_full_mlp(), train, num_epoch=6,
                                   optimizer="sgd", learning_rate=0.1,
                                   momentum=0.9,
                                   initializer=mx.init.Xavier())
    assert model.arg_params and "fc1_weight" in model.arg_params
    assert model.score(NDArrayIter(x, y, batch_size=32)) > 0.9


def test_sequential_auto_wiring_accepts_datadesc_layer0():
    # provide_data yields 4-field DataDesc namedtuples; auto_wiring on the
    # FIRST module must unpack them (regression: 2-tuple unpack crashed)
    x, y = _toy_dataset(n=64)
    it = NDArrayIter(x, y, batch_size=16)
    seq = SequentialModule()
    seq.add(Module(_feature_symbol(), data_names=("data",),
                   label_names=None), auto_wiring=True)
    seq.bind(data_shapes=it.provide_data)
    assert seq.output_shapes[0][1] == (16, 32)


def test_fit_invokes_eval_end_callback():
    x, y = _toy_dataset(n=64)
    train = NDArrayIter(x, y, batch_size=16)
    val = NDArrayIter(x, y, batch_size=16)
    mod = Module(_full_mlp())
    seen = []
    mod.fit(train, eval_data=val, num_epoch=2,
            initializer=mx.init.Xavier(),
            eval_end_callback=lambda p: seen.append((p.epoch,
                                                     p.eval_metric.get())))
    assert [e for e, _ in seen] == [0, 1]


def test_feedforward_predictor_is_cached():
    x, y = _toy_dataset(n=64)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", DeprecationWarning)
        model = FeedForward(_full_mlp(), num_epoch=2, optimizer="sgd",
                            learning_rate=0.1, numpy_batch_size=16,
                            initializer=mx.init.Xavier())
    model.fit(x, y)
    model.predict(x)
    first = model._pred_module
    model.predict(x)
    assert model._pred_module is first          # same shapes: reused
    model.predict(x[:10])
    assert model._pred_module is not first      # new batch size: rebound


def test_feedforward_deprecation_and_errors():
    with pytest.warns(DeprecationWarning):
        model = FeedForward(_full_mlp())
    x, _y = _toy_dataset(n=32)
    with pytest.raises(mx.MXNetError):
        model.fit(x, None)          # y required for numpy X
    with pytest.raises(mx.MXNetError):
        model.predict(x)            # no params yet
