"""Multi-host distributed training over jax.distributed (2 CPU processes
x 4 local devices each — the actual pod topology: DCN between processes,
an ICI-style device mesh within each).

The repo analog of the reference's tests/nightly/dist_sync_kvstore.py run
under tools/launch.py (reference layered path: local device reduce then
network, src/kvstore/kvstore_dist.h:44-650): spawn 2 workers via
subprocess, each joins the distributed runtime, and we assert (a)
dist_sync KVStore push sums across processes — incl. the FUSED multi-key
push costing ONE DCN round trip, (b) a ShardedTrainStep over the
2x4-device global mesh matches the 8-device single-process run, (d) the
hybrid Trainer + dist_sync path produces weights identical to the
single-process full-batch update.
"""
import json
import os
import subprocess
import sys
import socket
import textwrap

import numpy as np
import pytest

pytestmark = pytest.mark.multidevice

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    sys.path.insert(0, %(repo)r)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import mxtpu as mx
    from mxtpu import distributed, gluon
    from mxtpu.gluon import nn
    from mxtpu.parallel import ShardedTrainStep, make_mesh

    rank, world = distributed.init()
    assert world == 2, world

    # (a) dist_sync kvstore: push sums across workers
    kv = mx.kv.create("dist_sync")
    kv.init("w", mx.nd.zeros((3,)))
    kv.push("w", mx.nd.array([1.0 + rank, 2.0, 3.0]))
    out = mx.nd.zeros((3,))
    kv.pull("w", out=out)
    got = out.asnumpy()
    np.testing.assert_allclose(got, [3.0, 4.0, 6.0])
    kv.barrier()

    # (a2) compressed push: each worker pushes 1.0; threshold 0.6 sends
    # +0.6 from each worker on the first push (residual 0.4 stays local)
    kv2 = mx.kv.create("dist_sync")
    kv2.set_gradient_compression({"type": "2bit", "threshold": 0.6})
    kv2.init("c", mx.nd.zeros((4,)))
    kv2.push("c", mx.nd.array([1.0, 1.0, 0.1, -1.0]))
    outc = mx.nd.zeros((4,))
    kv2.pull("c", out=outc)
    np.testing.assert_allclose(outc.asnumpy(), [1.2, 1.2, 0.0, -1.2],
                               atol=1e-6)
    kv2.barrier()

    # (b) cross-process data-parallel ShardedTrainStep: global mesh over
    # 2 hosts x 4 local devices; each process feeds its local half-batch
    assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.Dense(4, in_units=6)
    net.initialize()
    mesh = make_mesh({"data": 8}, jax.devices())
    x_all = np.arange(48, dtype="float32").reshape(8, 6) / 48.0
    y_all = (np.arange(8) %% 4).astype("float32")
    lo, hi = rank * 4, rank * 4 + 4
    x = mx.nd.array(x_all[lo:hi]); y = mx.nd.array(y_all[lo:hi])
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = ShardedTrainStep(net, loss, mesh, optimizer="sgd",
                            optimizer_params={"learning_rate": 0.1})
    vals = [float(step(x, y).asnumpy()) for _ in range(3)]

    # (c) tensor-parallel param over a process-spanning axis: every process
    # holds the full weight; _place assembles the sharded global array
    from jax.sharding import PartitionSpec as P
    mx.random.seed(0); np.random.seed(0)
    net2 = nn.Dense(4, in_units=6)
    net2.initialize()
    mesh2 = make_mesh({"data": 2, "model": 4}, jax.devices())
    step2 = ShardedTrainStep(net2, loss, mesh2, optimizer="sgd",
                             optimizer_params={"learning_rate": 0.1},
                             param_specs=[(r".*weight", P("model", None))])
    lo2, hi2 = rank * 4, rank * 4 + 4
    tp_vals = [float(step2(mx.nd.array(x_all[lo2:hi2]),
                           mx.nd.array(y_all[lo2:hi2])).asnumpy())
               for _ in range(2)]

    # (d) hybrid Trainer + dist_sync: host autograd grads on the local
    # half-batch, ONE fused DCN allreduce for the whole parameter list
    # (KVStore._dist_reduce), identical updates in every process — the
    # reference's layered local-reduce-then-network path
    # (kvstore_dist.h:44) with the O(keys) round trips batched away
    from mxtpu import autograd
    mx.random.seed(0); np.random.seed(0)
    net3 = nn.Dense(4, in_units=6)
    net3.initialize()
    trainer = gluon.Trainer(net3.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="dist_sync")
    with autograd.record():
        l3 = loss(net3(mx.nd.array(x_all[lo:hi])), mx.nd.array(y_all[lo:hi]))
    l3.backward()
    calls = {"n": 0}
    orig_ar = distributed.allreduce_host
    def counting_ar(v):
        calls["n"] += 1
        return orig_ar(v)
    distributed.allreduce_host = counting_ar
    trainer.step(8)  # global batch size
    distributed.allreduce_host = orig_ar
    assert calls["n"] == 1, calls  # weight+bias fused: ONE DCN round trip
    w3 = {k: p.data().asnumpy().tolist()
          for k, p in net3.collect_params().items()}

    print("RESULT " + json.dumps({"rank": rank, "losses": vals,
                                  "tp_losses": tp_vals,
                                  "hybrid_weights": w3}), flush=True)
    distributed.shutdown()
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _single_process_reference():
    """Same model/batch on one process (the correctness oracle)."""
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon import nn
    from mxtpu.parallel import ShardedTrainStep, make_mesh
    import jax

    mx.random.seed(0)
    np.random.seed(0)
    net = nn.Dense(4, in_units=6)
    net.initialize()
    mesh = make_mesh({"data": 8}, jax.devices()[:8])
    x_all = np.arange(48, dtype="float32").reshape(8, 6) / 48.0
    y_all = (np.arange(8) % 4).astype("float32")
    x = mx.nd.array(x_all)
    y = mx.nd.array(y_all)
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = ShardedTrainStep(net, loss, mesh, optimizer="sgd",
                            optimizer_params={"learning_rate": 0.1})
    losses = [float(step(x, y).asnumpy()) for _ in range(3)]

    # hybrid oracle: one full-batch Trainer step on a single process
    from mxtpu import autograd
    mx.random.seed(0)
    np.random.seed(0)
    net3 = nn.Dense(4, in_units=6)
    net3.initialize()
    trainer = gluon.Trainer(net3.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    with autograd.record():
        l3 = loss(net3(x), y)
    l3.backward()
    trainer.step(8)
    weights = {k: p.data().asnumpy()
               for k, p in net3.collect_params().items()}
    return losses, weights


def test_two_process_dist_sync_and_train_step(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(WORKER % {"repo": REPO})
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "MXTPU_COORDINATOR": "127.0.0.1:%d" % port,
            "MXTPU_NUM_PROCESSES": "2",
            "MXTPU_PROCESS_ID": str(rank),
            "JAX_PLATFORMS": "cpu",
        })
        env.pop("XLA_FLAGS", None)  # worker sets its own device count
        procs.append(subprocess.Popen(
            [sys.executable, str(script)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=540)
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]

    results, tp_results, hybrid = {}, {}, {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                r = json.loads(line[len("RESULT "):])
                results[r["rank"]] = r["losses"]
                tp_results[r["rank"]] = r["tp_losses"]
                hybrid[r["rank"]] = r["hybrid_weights"]
    assert sorted(results) == [0, 1], outs
    # both workers see the same (global) loss
    np.testing.assert_allclose(results[0], results[1], rtol=1e-6)
    # and it matches the single-process run on the full batch
    want, want_weights = _single_process_reference()
    np.testing.assert_allclose(results[0], want, rtol=1e-4, atol=1e-5)
    # tensor-parallel losses agree across workers and match dp step 1
    np.testing.assert_allclose(tp_results[0], tp_results[1], rtol=1e-6)
    np.testing.assert_allclose(tp_results[0][0], want[0], rtol=1e-4,
                               atol=1e-5)
    # hybrid Trainer+dist_sync weights: identical across processes AND
    # equal to the single-process full-batch update (the gradient == the
    # 8-device single-process result, VERDICT r4 item 5)
    def _by_suffix(d):
        # block name counters differ per process (dense1 vs dense2) —
        # compare the weight/bias tensors by suffix
        return {k.rsplit("_", 1)[-1]: np.asarray(v) for k, v in d.items()}

    h0, h1, wref = (_by_suffix(hybrid[0]), _by_suffix(hybrid[1]),
                    _by_suffix(want_weights))
    assert set(h0) == set(wref) == {"weight", "bias"}
    for suffix in ("weight", "bias"):
        np.testing.assert_allclose(h0[suffix], h1[suffix], rtol=1e-6)
        np.testing.assert_allclose(h0[suffix], wref[suffix], rtol=1e-4,
                                   atol=1e-5)


def test_dist_sync_requires_init():
    import mxtpu as mx
    from mxtpu.base import MXNetError
    with pytest.raises(MXNetError, match="multi-process"):
        mx.kv.create("dist_sync")


def test_jax_private_probe_still_exists():
    """mxtpu.distributed.is_initialized consults the private
    jax._src.xla_bridge.backends_are_initialized as a guard (public
    jax.process_count would initialize the backend). Pin its existence so a
    jax upgrade fails HERE instead of silently flipping is_initialized."""
    from jax._src import xla_bridge
    assert callable(xla_bridge.backends_are_initialized)


def test_get_num_dead_node_parity():
    """ref kvstore.h:353 — monitoring loops written against the reference
    must run unmodified; the TPU runtime fails fast instead of counting."""
    import mxtpu as mx
    kv = mx.kv.create("local")
    assert kv.get_num_dead_node() == 0
    assert kv.get_num_dead_node(node_id=3, timeout=1) == 0


def test_send_command_to_servers_raises_with_guidance():
    """Reference-parity shim (kvstore.py:616): no server processes exist
    in the symmetric runtime, so the command endpoint must refuse with
    migration guidance, not silently drop."""
    import mxtpu as mx

    kv = mx.kv.create("local")
    with pytest.raises(mx.MXNetError, match="symmetric workers"):
        kv._send_command_to_servers(4, "profile")


def test_dist_reduce_fuses_keys_single_process(monkeypatch):
    """Unit tier for the fused push: N same-dtype keys pushed together →
    ONE allreduce_host call; mixed dtypes → one per dtype; values correct
    (single-process allreduce is identity, so the store must hold exactly
    the pushed sums)."""
    import mxtpu as mx
    from mxtpu import distributed, kvstore as kv_mod

    kv = kv_mod.KVStore("dist_sync")  # direct ctor: skip the init gate
    shapes = {"a": (3,), "b": (2, 2), "c": (4,)}
    for k, s in shapes.items():
        kv.init(k, mx.nd.zeros(s))
    calls = []
    real = distributed.allreduce_host
    monkeypatch.setattr(distributed, "allreduce_host",
                        lambda x: (calls.append(np.shape(x)), real(x))[1])
    kv.push(list(shapes), [mx.nd.ones(shapes["a"]),
                           mx.nd.full((2, 2), 2.0),
                           mx.nd.full((4,), 3.0)])
    assert len(calls) == 1, calls          # fused into one flat vector
    assert calls[0] == (3 + 4 + 4,)
    out = mx.nd.zeros(shapes["b"])
    kv.pull("b", out=out)
    np.testing.assert_allclose(out.asnumpy(), 2.0)
    # mixed dtypes fuse per dtype
    kv.init("i", mx.nd.zeros((2,), dtype="int32"))
    calls.clear()
    kv.push(["a", "i"], [mx.nd.ones((3,)),
                         mx.nd.ones((2,), dtype="int32")])
    assert len(calls) == 2, calls


def test_env_config_precedence_and_port_default(monkeypatch):
    """MXTPU_* spellings win over the reference DMLC_* names; the DMLC
    coordinator port defaults to 9091 (tools/launch.py never exports it
    for single-scheduler runs)."""
    from mxtpu import distributed
    for var in ("MXTPU_COORDINATOR", "MXTPU_NUM_PROCESSES",
                "MXTPU_PROCESS_ID", "DMLC_PS_ROOT_URI",
                "DMLC_PS_ROOT_PORT", "DMLC_NUM_WORKER", "DMLC_WORKER_ID"):
        monkeypatch.delenv(var, raising=False)
    assert distributed._env_config() == (None, None, None)
    # reference spelling, default port
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "10.0.0.1")
    assert distributed._env_config() == ("10.0.0.1:9091", None, None)
    # reference spelling, explicit everything (worker id 0 stays 0, not
    # None — the coordinator rank is a valid id)
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", "7777")
    monkeypatch.setenv("DMLC_NUM_WORKER", "4")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    assert distributed._env_config() == ("10.0.0.1:7777", 4, 0)
    # MXTPU_* wins over every DMLC_* name
    monkeypatch.setenv("MXTPU_COORDINATOR", "coord:2222")
    monkeypatch.setenv("MXTPU_NUM_PROCESSES", "8")
    monkeypatch.setenv("MXTPU_PROCESS_ID", "3")
    assert distributed._env_config() == ("coord:2222", 8, 3)


@pytest.fixture
def _fake_runtime(monkeypatch):
    """Record-only jax.distributed + a clean module flag, restored after:
    init/shutdown lifecycle tests must not touch the real runtime (or
    leave _initialized poisoned for the rest of the suite)."""
    import jax

    from mxtpu import distributed
    calls = {"init": 0, "shutdown": 0, "already": False}
    monkeypatch.setattr(distributed, "_initialized", False)
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: calls.__setitem__("init", calls["init"] + 1))
    monkeypatch.setattr(
        jax.distributed, "shutdown",
        lambda: calls.__setitem__("shutdown", calls["shutdown"] + 1))
    # absent on some jax versions (mxtpu probes it inside try/except) —
    # create it here so the adoption path is drivable either way
    monkeypatch.setattr(jax.distributed, "is_initialized",
                        lambda: calls["already"], raising=False)
    return calls


def test_reinit_after_shutdown(_fake_runtime):
    """init is idempotent while up, shutdown is idempotent while down,
    and a shut-down process can join a NEW fleet (the warm-rejoin path
    re-enters init in the same interpreter)."""
    from mxtpu import distributed
    calls = _fake_runtime
    distributed.init(coordinator_address="c:1", num_processes=1,
                     process_id=0)
    assert calls["init"] == 1 and distributed.is_initialized()
    distributed.init()  # second init: no second rendezvous
    assert calls["init"] == 1
    distributed.shutdown()
    assert calls["shutdown"] == 1 and not distributed._initialized
    distributed.shutdown()  # idempotent: no double-leave
    assert calls["shutdown"] == 1
    distributed.init(coordinator_address="c:2", num_processes=1,
                     process_id=0)  # re-init after shutdown rejoins
    assert calls["init"] == 2 and distributed._initialized
    distributed.shutdown()


def test_init_adopts_already_initialized_runtime(_fake_runtime):
    """A runtime brought up outside this module (jax.distributed
    autodetection on Cloud TPU pods) is ADOPTED: init never calls
    initialize again (it would raise), but shutdown still works."""
    from mxtpu import distributed
    calls = _fake_runtime
    calls["already"] = True
    distributed.init()
    assert calls["init"] == 0 and distributed._initialized
    assert distributed.is_initialized()
    distributed.shutdown()
    assert calls["shutdown"] == 1


def test_dist_reduce_compressed_fuses_to_one_allgather(monkeypatch):
    import mxtpu as mx
    from mxtpu import distributed, kvstore as kv_mod

    kv = kv_mod.KVStore("dist_sync")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("a", mx.nd.zeros((4,)))
    kv.init("b", mx.nd.zeros((6,)))
    calls = []
    real = distributed.allgather_host
    monkeypatch.setattr(distributed, "allgather_host",
                        lambda x: (calls.append(np.shape(x)), real(x))[1])
    kv.push(["a", "b"], [mx.nd.array([1.0, -1.0, 0.1, 0.9]),
                         mx.nd.full((6,), 0.7)])
    assert len(calls) == 1, calls          # one wire payload for both keys
    outa = mx.nd.zeros((4,))
    kv.pull("a", out=outa)
    np.testing.assert_allclose(outa.asnumpy(), [0.5, -0.5, 0.0, 0.5],
                               atol=1e-6)
