"""Gluon DEPTH tier: parameter-lifecycle and Block-composition behaviors
the reference grinds through tests/python/unittest/test_gluon.py
(2,558 LoC) — sharing, partial save/load, grad_req semantics, hybridize
cache behavior under shape/dtype changes, Constant params, apply/
collect_params filtering, Trainer state round-trips.
"""
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.base import MXNetError
from mxtpu.gluon import nn

RNG = np.random.RandomState


def _x(shape, seed=0):
    return mx.nd.array(RNG(seed).uniform(-1, 1, shape).astype(np.float32))


# ------------------------------------------------------- parameter sharing
def test_shared_params_two_blocks():
    """`params=` sharing (ref: gluon Block(params=...)): two Dense layers
    share ONE weight; training through either moves both."""
    a = nn.Dense(4, prefix="shared_")
    b = nn.Dense(4, prefix="shared_", params=a.collect_params())
    a.initialize()
    x = _x((2, 3))
    ya, yb = a(x), b(x)
    np.testing.assert_allclose(ya.asnumpy(), yb.asnumpy(), rtol=1e-6)
    assert a.weight.data() is b.weight.data() or np.allclose(
        a.weight.data().asnumpy(), b.weight.data().asnumpy())
    # gradient steps through `a` change `b`'s output too
    trainer = gluon.Trainer(a.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    with autograd.record():
        loss = (a(x) ** 2).sum()
    loss.backward()
    trainer.step(1)
    np.testing.assert_allclose(a(x).asnumpy(), b(x).asnumpy(), rtol=1e-6)


def test_constant_parameter_never_trains():
    from mxtpu.gluon.block import HybridBlock

    class WithConst(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.const = self.params.get_constant(
                    "scale", np.array([2.0, 3.0], np.float32))
                self.dense = nn.Dense(2)

        def hybrid_forward(self, F, x, const):
            return self.dense(x) * const

    net = WithConst()
    net.initialize()
    x = _x((4, 3))
    before = net.const.data().asnumpy()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(1)
    np.testing.assert_allclose(net.const.data().asnumpy(), before)


# -------------------------------------------------------- save/load depth
def test_partial_load_allow_missing_ignore_extra(tmp_path):
    big = nn.HybridSequential(prefix="net_")
    with big.name_scope():
        big.add(nn.Dense(8), nn.Dense(4))
    big.initialize()
    big(_x((2, 3)))
    f = str(tmp_path / "p.params")
    big.save_parameters(f)

    # smaller net: the file has EXTRA keys -> must raise unless ignored
    small = nn.HybridSequential(prefix="net_")
    with small.name_scope():
        small.add(nn.Dense(8))
    small.initialize()
    small(_x((2, 3)))
    with pytest.raises(MXNetError):
        small.load_parameters(f)
    small.load_parameters(f, ignore_extra=True)
    np.testing.assert_allclose(
        small[0].weight.data().asnumpy(),
        big[0].weight.data().asnumpy(), rtol=1e-6)

    # bigger net: the file is MISSING keys -> must raise unless allowed
    bigger = nn.HybridSequential(prefix="net_")
    with bigger.name_scope():
        bigger.add(nn.Dense(8), nn.Dense(4), nn.Dense(2))
    bigger.initialize()
    bigger(_x((2, 3)))
    with pytest.raises(MXNetError):
        bigger.load_parameters(f)
    bigger.load_parameters(f, allow_missing=True)
    np.testing.assert_allclose(
        bigger[1].weight.data().asnumpy(),
        big[1].weight.data().asnumpy(), rtol=1e-6)


def test_setattr_broadcasts_to_params():
    net = nn.Dense(4)
    net.initialize()
    net(_x((2, 3)))
    net.collect_params().setattr("grad_req", "null")
    assert all(p.grad_req == "null"
               for p in net.collect_params().values())


# ------------------------------------------------------- grad_req semantics
def test_grad_req_add_accumulates_until_zero_grad():
    net = nn.Dense(2, use_bias=False)
    net.initialize()
    x = _x((3, 4))
    net(x)
    net.weight.grad_req = "add"
    net.collect_params().zero_grad()
    for _ in range(3):
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
    g3 = net.weight.grad().asnumpy()
    net.collect_params().zero_grad()
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    g1 = net.weight.grad().asnumpy()
    np.testing.assert_allclose(g3, 3 * g1, rtol=1e-5)


def test_grad_req_null_param_keeps_no_grad():
    net = nn.Dense(2)
    net.initialize()
    x = _x((2, 3))
    net(x)
    net.bias.grad_req = "null"
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    assert net.weight.grad() is not None
    with pytest.raises(MXNetError):
        net.bias.grad()


# ------------------------------------------------- hybridize cache behavior
def test_hybridize_recompiles_on_shape_and_dtype():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, activation="relu"), nn.Dense(2))
    net.initialize()
    net.hybridize()
    y1 = net(_x((2, 3)))
    y2 = net(_x((5, 3), seed=1))          # new batch size: new cache entry
    assert y1.shape == (2, 2) and y2.shape == (5, 2)
    eager = nn.HybridSequential()
    eager.add(nn.Dense(4, activation="relu"), nn.Dense(2))
    eager.initialize()
    for (k, p_src), (_, p_dst) in zip(net.collect_params().items(),
                                      eager.collect_params().items()):
        p_dst.set_data(p_src.data())
    for shape, seed in [((2, 3), 0), ((5, 3), 1)]:
        np.testing.assert_allclose(net(_x(shape, seed)).asnumpy(),
                                   eager(_x(shape, seed)).asnumpy(),
                                   rtol=1e-5)


def test_hybridize_static_alloc_flags_accepted():
    net = nn.Dense(2)
    net.initialize()
    net.hybridize(static_alloc=True, static_shape=True)
    assert net(_x((2, 3))).shape == (2, 2)


# ------------------------------------------------------- block composition
def test_apply_walks_all_children():
    seen = []
    net = nn.HybridSequential()
    net.add(nn.Dense(2), nn.HybridSequential())
    net[1].add(nn.Dense(3))
    net.apply(lambda b: seen.append(type(b).__name__))
    assert seen.count("Dense") == 2
    assert "HybridSequential" in seen


def test_collect_params_regex_select():
    net = nn.HybridSequential(prefix="net_")
    with net.name_scope():
        net.add(nn.Dense(2), nn.Dense(3))
    net.initialize()
    net(_x((2, 3)))
    sel = net.collect_params(".*weight")
    keys = list(sel.keys())
    assert len(keys) == 2
    assert all(k.endswith("weight") for k in keys)


def test_sequential_len_getitem_iteration():
    net = nn.HybridSequential()
    net.add(nn.Dense(2), nn.Dense(3), nn.Dense(4))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    assert [type(b).__name__ for b in net] == ["Dense"] * 3


def test_name_scope_unique_prefixes():
    a, b = nn.Dense(2), nn.Dense(2)
    assert a.prefix != b.prefix  # auto-numbered
    names = set()
    net = nn.HybridSequential()
    net.add(nn.Dense(2), nn.Dense(2))
    net.initialize()
    net(_x((2, 3)))
    for k in net.collect_params():
        assert k not in names
        names.add(k)


# ------------------------------------------------------------ cast / dtype
def test_cast_changes_forward_dtype():
    net = nn.Dense(4)
    net.initialize()
    net(_x((2, 3)))
    net.cast("bfloat16")
    out = net(_x((2, 3)).astype("bfloat16"))
    assert "bfloat16" in str(out.dtype)
    net.cast("float32")
    out = net(_x((2, 3)))
    assert out.dtype == np.float32


# ---------------------------------------------------------------- trainer
def test_trainer_save_load_states_resumes_momentum(tmp_path):
    def make():
        net = nn.Dense(2, use_bias=False, prefix="t_")
        net.initialize(mx.init.Constant(0.5))
        net(_x((2, 3)))
        return net

    x = _x((4, 3))

    def steps(net, trainer, n):
        for _ in range(n):
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            trainer.step(1)

    # continuous run
    net_a = make()
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    steps(net_a, tr_a, 4)

    # interrupted + resumed run
    net_b = make()
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    steps(net_b, tr_b, 2)
    f = str(tmp_path / "trainer.states")
    tr_b.save_states(f)
    net_b.save_parameters(str(tmp_path / "p.params"))

    net_c = make()
    net_c.load_parameters(str(tmp_path / "p.params"))
    tr_c = gluon.Trainer(net_c.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    tr_c.load_states(f)
    steps(net_c, tr_c, 2)
    np.testing.assert_allclose(net_c.weight.data().asnumpy(),
                               net_a.weight.data().asnumpy(), rtol=1e-5)


def test_trainer_lr_scheduler_applies():
    from mxtpu.lr_scheduler import FactorScheduler
    net = nn.Dense(2)
    net.initialize()
    net(_x((2, 3)))
    sched = FactorScheduler(step=2, factor=0.5, base_lr=1.0)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0, "lr_scheduler": sched})
    x = _x((2, 3))
    lrs = []
    for _ in range(4):
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        trainer.step(1)
        lrs.append(trainer.learning_rate)
    assert lrs[-1] < lrs[0]
