"""graftlint (tools/graftlint): fixture corpus per rule + the tier-1
self-clean gate.

Layout per rule: a known-bad fixture where the rule must fire (with the
expected count), a known-good twin where it must stay silent, plus the
shared suppression fixture. The self-clean gate — ``graftlint mxtpu/`` has
zero unsuppressed findings — is the test every future PR inherits: add a
trace-time lever without a policy_key entry, an unregistered jax.jit, or
an undocumented env var, and this file fails before a chip ever sees the
bug. No jax import needed: the analyzer is pure stdlib ast."""
import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # pytest rootdir variants
    sys.path.insert(0, str(REPO))

from tools.graftlint import LintConfig, run  # noqa: E402
from tools.graftlint.rules import ALL_RULE_IDS  # noqa: E402

FIXTURES = REPO / "tests" / "fixtures" / "graftlint"


def fixture_config(**over):
    base = dict(
        root=FIXTURES,
        policy_key_module="registry_fixture.py",
        trace_scopes=("",),          # fixture tree: everything trace-time
        env_doc="env_doc_fixture.md",
        env_extra_roots=(),
        exclude=(),
        jit_allowlist={},
    )
    base.update(over)
    return LintConfig(**base)


def findings_of(path, rule, **over):
    res = run(fixture_config(**over), [path], [rule])
    return res


# ------------------------------------------------------- policy-key-coverage
def test_policy_key_bad_fires():
    res = findings_of("policy_key_bad.py", "policy-key-coverage")
    msgs = [f.message for f in res.findings]
    assert len(msgs) == 3, msgs
    assert any("MXTPU_BAZ" in m and "absent from" in m for m in msgs)
    assert any("MXTPU_BAR" in m and "'0'" in m and "'1'" in m for m in msgs)
    assert any("MXTPU_FOO" in m and "without a default" in m for m in msgs)


def test_policy_key_good_silent():
    res = findings_of("policy_key_good.py", "policy-key-coverage")
    assert res.findings == []


def test_policy_key_registry_module_not_blanket_exempt():
    # only the policy_key() FUNCTION is exempt; a stray trace-time read
    # elsewhere in the registry module itself must still fire
    res = findings_of("registry_fixture.py", "policy-key-coverage")
    msgs = [f.message for f in res.findings]
    assert len(msgs) == 1, msgs
    assert "MXTPU_STRAY" in msgs[0]
    assert not any("MXTPU_FOO" in m or "MXTPU_BAR" in m for m in msgs)


def test_policy_key_scope_gating():
    # outside the configured trace scopes, a missing lever is NOT flagged
    # (host-side trees may read MXTPU_* freely) but a default mismatch of
    # a key member still is
    res = findings_of("policy_key_bad.py", "policy-key-coverage",
                      trace_scopes=("some/other/tree",))
    msgs = [f.message for f in res.findings]
    assert not any("MXTPU_BAZ" in m for m in msgs)
    assert any("MXTPU_BAR" in m for m in msgs)


# ------------------------------------------- host-sync-in-traced-region
def test_host_sync_bad_fires():
    res = findings_of("host_sync_bad.py", "host-sync-in-traced-region")
    msgs = [f.message for f in res.findings]
    # pure: np.asarray + float + asnumpy + item; nested: asnumpy; bool
    assert len(msgs) == 6, msgs
    assert sum("asnumpy" in m for m in msgs) == 2
    assert any("np.asarray" in m for m in msgs)
    assert any("'float(...)'" in m for m in msgs)
    assert any("'.item()'" in m for m in msgs)
    assert any("'bool(...)'" in m for m in msgs)


def test_host_sync_good_silent():
    # shape arithmetic inside the jit and real syncs outside it are legal
    res = findings_of("host_sync_good.py", "host-sync-in-traced-region")
    assert res.findings == []


# ------------------------------------------------------------ use-after-donate
def test_donation_bad_fires():
    res = findings_of("donation_bad.py", "use-after-donate")
    msgs = [f.message for f in res.findings]
    assert len(msgs) == 4, msgs
    assert sum("'params'" in m for m in msgs) == 2  # incl. multi-line call
    assert any("'b'" in m for m in msgs)
    assert any("'state'" in m for m in msgs)  # via donate_argnames


def test_donation_good_silent():
    res = findings_of("donation_good.py", "use-after-donate")
    assert res.findings == []


# ------------------------------------------------ retrace-site-registration
def test_retrace_bad_fires():
    res = findings_of("retrace_bad.py", "retrace-site-registration")
    assert len(res.findings) == 2
    assert all("record_retrace" in f.message for f in res.findings)
    # the inventory names every site even when unregistered
    assert len(res.jit_inventory) == 2
    assert all(e["retrace_site"] is None for e in res.jit_inventory)


def test_retrace_good_silent_and_inventoried():
    res = findings_of("retrace_good.py", "retrace-site-registration")
    assert res.findings == []
    assert len(res.jit_inventory) == 1
    assert res.jit_inventory[0]["retrace_site"] == "fixture_site"


def test_service_seam_out_of_band_fires():
    """ISSUE 15: inside a service scope, a registered-but-private jit
    cache is a finding — it must resolve through compile_service."""
    res = findings_of("service_bad.py", "retrace-site-registration",
                      service_scopes=("",))
    assert len(res.findings) == 1
    assert "compile_service" in res.findings[0].message
    assert res.jit_inventory[0]["service"] is False
    # still registered: the watchdog sees it, only the service seam is
    # missing
    assert res.jit_inventory[0]["retrace_site"] == "fixture_site"


def test_service_seam_good_silent():
    res = findings_of("service_good.py", "retrace-site-registration",
                      service_scopes=("",))
    assert res.findings == []
    entry = res.jit_inventory[0]
    assert entry["service"] is True
    assert entry["retrace_site"] == "fixture_site"
    # the canonical_key call IS the declared cache-key expression
    assert "canonical_key" in entry["cache_key"]
    assert "policy" in entry["cache_key"]


def test_service_scope_gates_the_finding():
    """Outside the declared service scopes (default: mxtpu/) the plain
    record_retrace discipline stays sufficient — fixture trees and
    user code keep linting as before."""
    res = findings_of("service_bad.py", "retrace-site-registration")
    assert res.findings == []
    assert res.jit_inventory[0]["service"] is False


def test_retrace_allowlist():
    allow = {("retrace_bad.py", "compile_it"):
             {"site": "elsewhere", "reason": "counted by a caller",
              "cache_key": "declared-in-allowlist"}}
    res = findings_of("retrace_bad.py", "retrace-site-registration",
                      jit_allowlist=allow)
    # compile_it is allowlisted, one_off still fires
    assert len(res.findings) == 1
    assert "one_off" in res.findings[0].message
    entry = [e for e in res.jit_inventory if e["function"] == "compile_it"][0]
    assert entry["allowlisted"] and entry["retrace_site"] == "elsewhere"
    assert entry["cache_key"] == "declared-in-allowlist"


# ------------------------------------------------------------ env-var-catalog
def test_env_catalog_bad_fires():
    res = findings_of("env_catalog_bad.py", "env-var-catalog")
    by_path = {(f.path, f.message.split()[0]) for f in res.findings}
    assert ("env_catalog_bad.py", "MXTPU_UNDOCUMENTED") in by_path
    assert ("env_doc_fixture.md", "MXTPU_STALE") in by_path
    assert len(res.findings) == 2


def test_env_catalog_good_silent():
    res = findings_of("env_catalog_good.py", "env-var-catalog")
    assert res.findings == []


# ------------------------------------------------------- metric-name-catalog
def _metric_findings(path):
    return findings_of(path, "metric-name-catalog",
                       metric_doc="metric_doc_fixture.md",
                       metric_scopes=("",))


def test_metric_catalog_bad_fires():
    res = _metric_findings("metric_catalog_bad.py")
    msgs = [(f.path, f.message) for f in res.findings]
    assert len(msgs) == 3, msgs
    assert any(p == "metric_catalog_bad.py" and
               "'metric.undocumented'" in m for p, m in msgs)
    assert any(p == "metric_catalog_bad.py" and
               "'span.undocumented'" in m for p, m in msgs)
    assert any(p == "metric_doc_fixture.md" and "'metric.stale'" in m
               for p, m in msgs)


def test_metric_catalog_good_silent():
    # brace expansion, <i> placeholder vs %d pattern, tag annotation
    # stripping, the span d2h twin, and the retrace.<site> prefix all
    # reconcile — zero findings either direction
    res = _metric_findings("metric_catalog_good.py")
    assert res.findings == []


def test_metric_catalog_out_of_scope_collects_nothing():
    # with the default mxtpu/ scope the fixture file contributes no
    # names — and crucially the rule then issues NO stale-row verdicts
    # (a scoped-out run must not condemn the whole catalog)
    res = findings_of("metric_catalog_bad.py", "metric-name-catalog",
                      metric_doc="metric_doc_fixture.md")
    assert res.findings == []


# ---------------------------------------------------------------- suppressions
@pytest.mark.parametrize("rule,expected_suppressed", [
    ("policy-key-coverage", 1),
    ("host-sync-in-traced-region", 1),
    ("use-after-donate", 1),
    ("retrace-site-registration", 3),  # two inline + one disable=all
])
def test_inline_suppressions(rule, expected_suppressed):
    res = findings_of("suppressed.py", rule)
    assert res.findings == [], [f.format() for f in res.findings]
    assert len(res.suppressed) == expected_suppressed


def test_unknown_rule_rejected():
    with pytest.raises(ValueError, match="unknown rule"):
        run(fixture_config(), ["policy_key_good.py"], ["no-such-rule"])


# ------------------------------------------------------------ the tier-1 gate
def _repo_result():
    return run(LintConfig(root=REPO), ["mxtpu"])


def test_self_clean_gate():
    """`python -m tools.graftlint mxtpu/` has ZERO unsuppressed findings.

    If this fails, fix the violation (or, for a genuinely host-side read /
    externally-counted jit site, add the inline suppression or allowlist
    entry WITH a reason) — do not baseline it."""
    res = _repo_result()
    assert res.findings == [], \
        "graftlint found violations:\n" + \
        "\n".join(f.format() for f in res.findings)


def test_all_rules_ran_over_repo():
    # the gate is only meaningful if every rule is registered and loaded
    assert set(ALL_RULE_IDS) == {
        "policy-key-coverage", "host-sync-in-traced-region",
        "use-after-donate", "retrace-site-registration",
        "env-var-catalog", "metric-name-catalog"}


def test_jit_surface_inventory_lists_all_six_caches():
    """The inventory is ROADMAP item 5's scouting report: all six jit
    caches (FusedUpdater, CachedOp, symbol executor, serving Predictor,
    serving DecodeEngine target family, serving DecodeEngine draft
    family) must appear with their retrace sites, and no
    site may be anonymous. Since ISSUE 7 the fused_optimizer cache is
    ALSO the mesh-native Trainer's cache — its declared key must carry
    the sharding component (MeshPlan fingerprint + per-buffer sharding
    tokens), the down payment on the unified compile-cache engine's key
    = fn + shapes + policy_key + sharding. Since ISSUE 8 the serving
    Predictor's site is per-INSTANCE (ReplicaSet members report at
    serving.predict.r<i>), so its inventory entry resolves through the
    JIT_ALLOWLIST declaration — which must name the per-replica caches
    to keep this report honest. Since ISSUE 11 the decode cache
    (serving.decode — step executables per cohort-capacity bucket +
    insert executables per prefill seq bucket) joins the same way: its
    declaration must spell out the AOT discipline (post-warmup compiles
    zero, donated carry). Since ISSUE 16 the speculative-decoding DRAFT
    cache (serving.draft — k-token proposal executables per cohort
    bucket) is the sixth entry: an out-of-band draft jit fails CI."""
    inv = _repo_result().jit_inventory
    sites = {e["retrace_site"] for e in inv}
    assert {"fused_optimizer", "cached_op", "executor",
            "executor.backward", "subgraph_exec", "parallel.train_step",
            "rtc", "serving.predict", "serving.decode",
            "serving.draft"} <= sites, sites
    assert None not in sites and "<dynamic>" not in sites
    # ISSUE 15: the unified compile service is under EVERY jit surface —
    # an inventory entry without the service seam is an out-of-band
    # cache (no LRU bound, no persistent executable cache, no AOT
    # warmup) and the rule fails CI on it inside mxtpu/
    assert all(e["service"] for e in inv), \
        [e for e in inv if not e["service"]]
    fused = [e for e in inv if e["retrace_site"] == "fused_optimizer"]
    # since ISSUE 18 the donation is a policy FUNCTION, not a literal:
    # (0, 2) everywhere except the XLA:CPU portable single-device class,
    # where serialized executables with input-output aliasing silently
    # corrupt when loaded in a fresh process (measured, jaxlib 0.4.37) —
    # the fleet's warm-rejoin disk cache depends on dropping donation
    # there. The inventory must still show ONE declared discipline.
    assert fused and all(e["donation"] == "donate_argnums=_donation()"
                         for e in fused)
    for e in fused:   # the merged mesh-trainer cache: sharding in the key
        assert "MeshPlan" in e["cache_key"], e["cache_key"]
        assert "sharding" in e["cache_key"], e["cache_key"]
    by_site = {e["retrace_site"]: e for e in inv}
    assert by_site["cached_op"]["file"] == "mxtpu/gluon/block.py"
    assert by_site["serving.predict"]["file"] == "mxtpu/serving/engine.py"
    assert "policy_key" in (by_site["cached_op"]["cache_key"] or "")
    serving = by_site["serving.predict"]
    assert serving["allowlisted"] is True
    # the per-replica jit caches are declared, not anonymous: the entry
    # names the serving.predict.r<i> site family and its bound
    assert "serving.predict.r" in serving["cache_key"], serving
    assert "policy_key" in serving["cache_key"], serving
    decode = by_site["serving.decode"]
    assert decode["file"] == "mxtpu/serving/decode.py", decode
    assert decode["allowlisted"] is True
    # the decode cache's contract rides the declaration: bucketed AOT
    # replay (zero post-warmup compiles) over donated carry state
    assert "policy_key" in decode["cache_key"], decode
    assert "bucket" in decode["cache_key"], decode
    assert "donated" in decode["cache_key"], decode
    # ISSUE 16: the paged step family rides the same front door (page
    # table as a traced argument, never a new executable) and the draft
    # cache is declared at its own site with the same AOT discipline
    assert "page_tokens" in decode["cache_key"], decode
    draft = by_site["serving.draft"]
    assert draft["file"] == "mxtpu/serving/decode.py", draft
    assert draft["allowlisted"] is True
    assert "policy_key" in draft["cache_key"], draft
    assert "spec_k" in draft["cache_key"], draft
    # ISSUE 17: the autotuner's measurement probes are a declared jit
    # surface too — ephemeral by design (the persisted artifact is the
    # PLAN; plan identity reaches the real caches through the policy_key
    # digest component), registered at its own record_retrace site so
    # the xprof ledger covers it like every other inventory entry
    assert "autotune.search" in sites, sites
    tune = by_site["autotune.search"]
    assert tune["file"] == "mxtpu/ops/pallas/autotune.py", tune
    assert tune["service"] is True
    assert tune["function"] == "_time_plan", tune


# ------------------------------------------------------------------------ CLI
def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "tools.graftlint"] + args,
        cwd=str(cwd), capture_output=True, text=True, timeout=120)


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n"
                   "def f(fn):\n"
                   "    return jax.jit(fn)\n")
    good = tmp_path / "good.py"
    good.write_text("import jax\n"
                    "def f(fn):\n"
                    "    telemetry.record_retrace('s', {})\n"
                    "    return jax.jit(fn)\n")
    out = tmp_path / "report.json"
    proc = _run_cli(["bad.py", "--root", str(tmp_path),
                     "--rules", "retrace-site-registration",
                     "--json", str(out)], cwd=REPO)
    assert proc.returncode == 1, proc.stderr
    assert "retrace-site-registration" in proc.stdout
    payload = json.loads(out.read_text())
    assert len(payload["findings"]) == 1
    assert len(payload["jit_inventory"]) == 1

    proc = _run_cli(["good.py", "--root", str(tmp_path),
                     "--rules", "retrace-site-registration"], cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_self_clean_and_inventory(tmp_path):
    """The exact perf_battery.sh pre-flight invocation exits 0, and
    --inventory lands the scouting-report JSON."""
    inv = tmp_path / "jit_surfaces.json"
    proc = _run_cli(["mxtpu/", "--inventory", str(inv)], cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    entries = json.loads(inv.read_text())
    assert {e["retrace_site"] for e in entries} >= {
        "fused_optimizer", "cached_op", "executor", "serving.predict"}
