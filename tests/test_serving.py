"""Inference serving subsystem (mxtpu/serving) — ISSUE 5:

* BucketSpec semantics + the pad/slice helper;
* Predictor: compile count == #buckets after warmup and FLAT across a
  mixed-shape traffic run (zero watchdog trips, zero d2h attributed to
  the predict span), pad/slice round-trip parity vs the direct block
  call, seq-bucket parity, chunking past the max bucket, checkpoint and
  trainer-checkpoint load paths;
* MicroBatcher: coalesce-by-size, coalesce-by-deadline (fake clock —
  no sleeps in tier-1), FIFO within bucket, shedding on a full queue,
  per-request deadline expiry, the serve_timeout / serve_overload fault
  kinds;
* ModelServer: /predict /healthz /metrics round-trips, 503 on shed,
  SIGTERM graceful drain (in-flight work completes, new work rejected);
* BaseModule.predict ragged-batch pad-to-bound (executor retrace site
  stays flat);
* telemetry: thread-local d2h attribution under concurrent asnumpy,
  serving.* metrics fold through tools/telemetry_report.py unchanged;
* the ISSUE-5 acceptance run: 500 mixed-shape closed-loop requests with
  <= #buckets compiles at site serving.predict.
"""
import importlib.util
import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import resilience, telemetry
from mxtpu.base import MXNetError
from mxtpu.gluon import nn
from mxtpu.serving import (BucketSpec, DeadlineExceeded, MicroBatcher,
                           ModelServer, Predictor, QueueFull, pad_nd)


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_TELEMETRY", "MXTPU_RETRACE_BUDGET",
                "MXTPU_FAULT_INJECT", "MXTPU_SERVE_MAX_BATCH",
                "MXTPU_SERVE_MAX_WAIT_MS", "MXTPU_SERVE_QUEUE"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    yield
    telemetry.reset()
    resilience.reset_faults()


IN_DIM, OUT_DIM = 12, 4


def _mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(OUT_DIM))
    net.initialize()
    return net


def _warm_predictor(max_batch=8):
    net = _mlp()
    spec = BucketSpec.pow2(max_batch)
    pred = Predictor(net, spec, example=np.zeros((1, IN_DIM), np.float32),
                     warmup=True)
    return net, spec, pred


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _x(n, seed=0, dim=IN_DIM):
    return np.random.RandomState(seed).randn(n, dim).astype(np.float32)


# ------------------------------------------------------------ BucketSpec/pad
def test_bucketspec_semantics():
    spec = BucketSpec.pow2(8)
    assert spec.batch_sizes == (1, 2, 4, 8)
    assert spec.batch_bucket(3) == 4
    assert spec.batch_bucket(8) == 8
    assert spec.batch_bucket(9) is None  # over max: caller chunks
    assert len(spec) == 4
    s2 = BucketSpec((4, 2), seq_lens=(16, 8))
    assert s2.batch_sizes == (2, 4) and s2.seq_lens == (8, 16)
    assert s2.seq_bucket(5) == 8
    assert len(s2) == 4 and len(s2.buckets()) == 4
    with pytest.raises(MXNetError):
        s2.seq_bucket(17)  # sequences cannot be chunked
    with pytest.raises(MXNetError):
        BucketSpec(())


def test_pad_nd_semantics():
    a = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    p = pad_nd(a, 5)
    assert p.shape == (5, 3)
    np.testing.assert_allclose(p.asnumpy()[2:], 0.0)
    np.testing.assert_allclose(p.asnumpy()[:2], a.asnumpy())
    assert pad_nd(a, 2) is a  # exact fit passes through
    p2 = pad_nd(a, 4, seq_len=7, seq_axis=1)
    assert p2.shape == (4, 7)
    with pytest.raises(MXNetError):
        pad_nd(a, 1)


# ----------------------------------------------------------------- Predictor
def test_warmup_compiles_exactly_one_jit_per_bucket():
    _, spec, _pred = _warm_predictor()
    st = telemetry.retrace_stats("serving.predict")
    assert st["compiles"] == len(spec)
    assert st["trips"] == 0
    assert telemetry.snapshot()["gauges"]["serving.buckets"] == len(spec)


def test_mixed_shapes_reuse_warm_buckets_zero_d2h():
    net, spec, pred = _warm_predictor()
    for n in (1, 2, 3, 5, 8, 7, 4, 2, 1, 6):
        out = pred.predict(_x(n, seed=n))
        assert out.shape == (n, OUT_DIM)
    st = telemetry.retrace_stats("serving.predict")
    assert st["compiles"] == len(spec), "traffic must not add compiles"
    assert st["trips"] == 0
    # zero hot-loop d2h: nothing attributed to the predict span
    snap = telemetry.snapshot()
    assert snap["counters"].get("serving.predict.d2h", 0) == 0
    assert snap["histograms"]["serving.predict"]["count"] >= 10


def test_pad_slice_roundtrip_parity():
    net, _, pred = _warm_predictor()
    x = _x(3, seed=42)
    ref = net(mx.nd.array(x)).asnumpy()
    np.testing.assert_allclose(pred.predict(x).asnumpy(), ref,
                               rtol=1e-5, atol=1e-5)
    # NDArray input, exact bucket fit (the donate-protection path), and
    # the caller's array must stay usable afterwards
    x8 = mx.nd.array(_x(8, seed=43))
    ref8 = net(x8).asnumpy()
    np.testing.assert_allclose(pred.predict(x8).asnumpy(), ref8,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(x8.asnumpy(), _x(8, seed=43), rtol=1e-6)


def test_large_request_chunks_through_max_bucket():
    net, spec, pred = _warm_predictor()
    x = _x(19, seed=7)
    out = pred.predict(x)
    assert out.shape == (19, OUT_DIM)
    np.testing.assert_allclose(out.asnumpy(), net(mx.nd.array(x)).asnumpy(),
                               rtol=1e-5, atol=1e-5)
    assert telemetry.retrace_stats("serving.predict")["compiles"] == len(spec)


def test_seq_bucket_parity():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(6, flatten=False))  # (n, seq, d) -> (n, seq, 6)
    net.initialize()
    spec = BucketSpec((2,), seq_lens=(4, 8))
    pred = Predictor(net, spec, example=np.zeros((1, 4, 5), np.float32),
                     warmup=True)
    assert telemetry.retrace_stats("serving.predict")["compiles"] == 2
    x = np.random.RandomState(3).randn(1, 3, 5).astype(np.float32)
    out = pred.predict(x)          # pads to (2, 4, 5); batch-sliced back
    assert out.shape == (1, 4, 6)  # seq stays at its bucket; valid = [:3]
    ref = net(mx.nd.array(x)).asnumpy()
    np.testing.assert_allclose(out.asnumpy()[:, :3], ref, rtol=1e-5,
                               atol=1e-5)
    x7 = np.random.RandomState(4).randn(2, 7, 5).astype(np.float32)
    out7 = pred.predict(x7)        # seq bucket 8
    np.testing.assert_allclose(out7.asnumpy()[:, :7],
                               net(mx.nd.array(x7)).asnumpy(),
                               rtol=1e-5, atol=1e-5)
    assert telemetry.retrace_stats("serving.predict")["compiles"] == 2


def test_predictor_from_symbol_checkpoint(tmp_path):
    net = _mlp()
    x = _x(2, seed=9)
    ref = net(mx.nd.array(x)).asnumpy()
    path = str(tmp_path / "model")
    net.export(path)
    pred = Predictor.from_checkpoint(
        path, 0, BucketSpec.pow2(4),
        example=np.zeros((1, IN_DIM), np.float32), warmup=True)
    np.testing.assert_allclose(pred.predict(x).asnumpy(), ref,
                               rtol=1e-5, atol=1e-5)
    assert telemetry.retrace_stats("serving.predict")["compiles"] == 3


def test_predictor_from_trainer_checkpoint(tmp_path):
    from mxtpu.contrib import async_checkpoint as ackpt
    from mxtpu.gluon.trainer import Trainer

    net = _mlp()
    x = _x(2, seed=11)
    ref = net(mx.nd.array(x)).asnumpy()
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 kvstore=None)
    ackpt.save_trainer(tr, str(tmp_path), step=5)

    fresh = _mlp()  # same architecture, different random params
    fresh(mx.nd.array(x))
    assert not np.allclose(fresh(mx.nd.array(x)).asnumpy(), ref)
    pred = Predictor.from_trainer_checkpoint(
        fresh, str(tmp_path), BucketSpec.pow2(2),
        example=np.zeros((1, IN_DIM), np.float32), warmup=True)
    np.testing.assert_allclose(pred.predict(x).asnumpy(), ref,
                               rtol=1e-5, atol=1e-5)
    # params restored only: step resolves to the newest finalized dir
    assert ackpt.latest_step(str(tmp_path)) == 5


# --------------------------------------------------------------- MicroBatcher
def test_batcher_coalesces_by_size():
    _, _, pred = _warm_predictor()
    clk = FakeClock()
    bat = MicroBatcher(pred, max_batch_size=8, max_wait_ms=1000,
                       clock=clk, start=False)
    futs = [bat.submit(_x(2, seed=i)) for i in range(4)]
    # 8 items waiting == max_batch: dispatches with NO wait
    assert bat.poll() == 4
    for i, f in enumerate(futs):
        assert f.done()
        assert f.result(0).shape == (2, OUT_DIM)
    fill = telemetry.snapshot()["histograms"]["serving.batch_fill"]
    assert fill["max"] == 1.0
    assert telemetry.value("serving.batches") == 1


def test_batcher_coalesces_by_deadline():
    _, _, pred = _warm_predictor()
    clk = FakeClock()
    bat = MicroBatcher(pred, max_batch_size=8, max_wait_ms=5,
                       clock=clk, start=False)
    f1 = bat.submit(_x(1, seed=0))
    f2 = bat.submit(_x(2, seed=1))
    assert bat.poll() == 0          # 3 < 8 items and head waited 0ms
    clk.advance(0.004)
    assert bat.poll() == 0          # 4ms < 5ms: still coalescing
    clk.advance(0.002)
    assert bat.poll() == 2          # head hit max_wait: partial dispatch
    assert f1.result(0).shape == (1, OUT_DIM)
    assert f2.result(0).shape == (2, OUT_DIM)
    fill = telemetry.snapshot()["histograms"]["serving.batch_fill"]
    assert abs(fill["max"] - 3.0 / 4.0) < 1e-9  # 3 items in the 4-bucket


def test_batcher_fifo_within_bucket():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(3, flatten=False))
    net.initialize()
    spec = BucketSpec((1, 2), seq_lens=(4, 8))
    pred = Predictor(net, spec, example=np.zeros((1, 4, 5), np.float32),
                     warmup=True)
    clk = FakeClock()
    bat = MicroBatcher(pred, max_batch_size=2, max_wait_ms=5,
                       clock=clk, start=False)
    rng = np.random.RandomState(0)
    x1 = rng.randn(1, 3, 5).astype(np.float32)   # seq bucket 4
    x2 = rng.randn(1, 7, 5).astype(np.float32)   # seq bucket 8
    x3 = rng.randn(1, 2, 5).astype(np.float32)   # seq bucket 4
    f1, f2, f3 = bat.submit(x1), bat.submit(x2), bat.submit(x3)
    # head cohort (seq-4) is full at 2 items: r1+r3 dispatch together in
    # arrival order; r2 (seq-8) keeps its place and waits for ITS cohort
    assert bat.poll() == 2
    assert f1.done() and f3.done() and not f2.done()
    np.testing.assert_allclose(
        f1.result(0)[:, :3], net(mx.nd.array(x1)).asnumpy(),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        f3.result(0)[:, :2], net(mx.nd.array(x3)).asnumpy(),
        rtol=1e-5, atol=1e-5)
    clk.advance(0.006)
    assert bat.poll() == 1
    np.testing.assert_allclose(
        f2.result(0)[:, :7], net(mx.nd.array(x2)).asnumpy(),
        rtol=1e-5, atol=1e-5)
    assert telemetry.value("serving.batches") == 2


def test_batcher_rejects_malformed_requests_at_admission():
    """A malformed request must refuse at submit (client-shaped error),
    not poison its coalesced cohort or force a hot-path compile."""
    _, spec, pred = _warm_predictor()
    bat = MicroBatcher(pred, max_batch_size=8, max_wait_ms=5,
                       clock=FakeClock(), start=False)
    good = bat.submit(_x(1, seed=0))
    with pytest.raises(MXNetError):
        bat.submit(np.zeros((1, IN_DIM + 3), np.float32))  # wrong dim
    with pytest.raises(MXNetError):
        bat.submit(np.zeros((1, IN_DIM, 2), np.float32))   # wrong rank
    with pytest.raises(MXNetError):
        bat.submit(np.float32(5.0))                        # no batch axis
    with pytest.raises(MXNetError):
        bat.submit((_x(1), _x(1)))                         # wrong input count
    # the admitted request is untouched and still serves
    bat._clock.advance(0.006)
    assert bat.poll() == 1
    assert good.result(0).shape == (1, OUT_DIM)
    # no off-template compile happened
    assert telemetry.retrace_stats("serving.predict")["compiles"] == len(spec)


def test_batcher_sheds_on_full_queue():
    _, _, pred = _warm_predictor()
    bat = MicroBatcher(pred, max_batch_size=8, max_wait_ms=1000,
                       max_queue=4, clock=FakeClock(), start=False)
    bat.submit(_x(2, seed=0))
    bat.submit(_x(2, seed=1))
    with pytest.raises(QueueFull):
        bat.submit(_x(1, seed=2))
    assert telemetry.value("serving.shed", tag="queue_full") == 1
    assert telemetry.value("serving.requests") == 2  # shed never admitted


def test_batcher_deadline_expires_at_dispatch():
    _, _, pred = _warm_predictor()
    clk = FakeClock()
    bat = MicroBatcher(pred, max_batch_size=8, max_wait_ms=5,
                       clock=clk, start=False)
    f_dead = bat.submit(_x(1, seed=0), deadline_ms=3)
    f_live = bat.submit(_x(1, seed=1), deadline_ms=50)
    clk.advance(0.006)  # past max_wait AND past f_dead's deadline
    assert bat.poll() == 2
    with pytest.raises(DeadlineExceeded):
        f_dead.result(0)
    assert f_live.result(0).shape == (1, OUT_DIM)
    assert telemetry.value("serving.deadline_expired") == 1


def test_fault_serve_timeout(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "serve_timeout@0")
    resilience.reset_faults()
    _, _, pred = _warm_predictor()
    clk = FakeClock()
    bat = MicroBatcher(pred, max_batch_size=4, max_wait_ms=5,
                       clock=clk, start=False)
    f1 = bat.submit(_x(1, seed=0))
    f2 = bat.submit(_x(1, seed=1))
    clk.advance(0.006)
    assert bat.poll() == 2
    for f in (f1, f2):  # batch 0 expired wholesale
        with pytest.raises(DeadlineExceeded):
            f.result(0)
    assert telemetry.value("serving.deadline_expired") == 2
    assert resilience.FAULT_STATS["fired"] == [("serve_timeout", 0)]
    # batch 1 is healthy again (consume-once semantics)
    f3 = bat.submit(_x(1, seed=2))
    clk.advance(0.006)
    assert bat.poll() == 1
    assert f3.result(0).shape == (1, OUT_DIM)


def test_fault_serve_overload(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "serve_overload@1")
    resilience.reset_faults()
    _, _, pred = _warm_predictor()
    bat = MicroBatcher(pred, max_batch_size=4, max_wait_ms=5,
                       clock=FakeClock(), start=False)
    bat.submit(_x(1, seed=0))           # submit 0 admitted
    with pytest.raises(QueueFull):
        bat.submit(_x(1, seed=1))       # submit 1 sheds
    assert telemetry.value("serving.shed", tag="injected_overload") == 1
    bat.submit(_x(1, seed=2))           # consume-once: admitted again


# ----------------------------------------------------------------- HTTP front
def _http(addr, path, payload=None, timeout=10):
    url = "http://%s:%d%s" % (addr[0], addr[1], path)
    if payload is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_server_predict_healthz_metrics_roundtrip():
    net, spec, pred = _warm_predictor()
    srv = ModelServer(MicroBatcher(pred, max_batch_size=8, max_wait_ms=1))
    srv.start()
    try:
        x = _x(2, seed=5)
        code, out = _http(srv.address, "/predict", {"data": x.tolist()})
        assert code == 200 and out["n"] == 2
        np.testing.assert_allclose(np.asarray(out["outputs"][0]),
                                   net(mx.nd.array(x)).asnumpy(),
                                   rtol=1e-4, atol=1e-5)
        code, health = _http(srv.address, "/healthz")
        assert code == 200 and health["status"] == "ok"
        # /metrics is telemetry.snapshot(): serving counters + the
        # serving.predict retrace-watchdog state round-trip as JSON
        code, m = _http(srv.address, "/metrics")
        assert code == 200
        assert m["counters"]["serving.requests"] >= 1
        assert m["counters"]["serving.batches"] >= 1
        assert m["retrace"]["serving.predict"]["compiles"] == len(spec)
        assert "serving.latency_s" in m["histograms"]
        code, _ = _http(srv.address, "/nope")
        assert code == 404
    finally:
        srv.close()


def test_server_sheds_503_on_injected_overload(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "serve_overload@0")
    resilience.reset_faults()
    _, _, pred = _warm_predictor()
    srv = ModelServer(MicroBatcher(pred, max_batch_size=8, max_wait_ms=1))
    srv.start()
    try:
        code, out = _http(srv.address, "/predict",
                          {"data": _x(1, seed=0).tolist()})
        assert code == 503 and "shed" in out["error"]
        assert telemetry.value("serving.shed", tag="injected_overload") == 1
        code, _ = _http(srv.address, "/predict",
                        {"data": _x(1, seed=1).tolist()})
        assert code == 200  # consume-once: service healthy again
    finally:
        srv.close()


def test_server_sigterm_graceful_drain():
    _, _, pred = _warm_predictor()
    srv = ModelServer(MicroBatcher(pred, max_batch_size=8, max_wait_ms=1))
    srv.start()
    srv.install_signal_handlers()
    try:
        # in-flight work before the signal
        code, _ = _http(srv.address, "/predict",
                        {"data": _x(2, seed=0).tolist()})
        assert code == 200
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not srv.draining:
            time.sleep(0.01)
        assert srv.draining
        if srv._drain_thread is not None:
            srv._drain_thread.join(5)
        # queued + in-flight finished; NEW work is rejected with 503
        assert srv.batcher.queue_depth == 0
        code, out = _http(srv.address, "/predict",
                          {"data": _x(1, seed=1).tolist()})
        assert code == 503 and out["error"] == "draining"
        code, health = _http(srv.address, "/healthz")
        assert code == 200 and health["status"] == "draining"
        assert telemetry.value("serving.drains") == 1
    finally:
        srv.close()
    # handler restored: a later SIGTERM must not re-enter the server
    assert signal.getsignal(signal.SIGTERM) not in (srv._on_signal,)


def test_server_bad_requests():
    _, _, pred = _warm_predictor()
    srv = ModelServer(MicroBatcher(pred, max_batch_size=8, max_wait_ms=1))
    srv.start()
    try:
        code, out = _http(srv.address, "/predict", {})
        assert code == 400
        code, out = _http(srv.address, "/predict", {"deadline_ms": 5})
        assert code == 400
        code, out = _http(srv.address, "/predict", {"inputs": []})
        assert code == 400
        # client-shaped refusals are 400s, not 500s (a misbehaving caller
        # must not look like a server fault to monitoring)
        code, out = _http(srv.address, "/predict",
                          {"data": _x(9, seed=0).tolist()})  # > max_batch
        assert code == 400 and "max_batch" in out["error"]
        code, out = _http(srv.address, "/predict",
                          {"data": [[1.0, 2.0], [3.0]]})     # ragged json
        assert code == 400
        code, out = _http(srv.address, "/predict", {"data": 5})  # 0-d
        assert code == 400
        code, out = _http(srv.address, "/predict",
                          {"data": np.ones((1, IN_DIM + 1)).tolist()})
        assert code == 400 and "expects" in out["error"]     # wrong dim
    finally:
        srv.close()


def test_server_timeout_orphans_expire_instead_of_executing():
    """A request whose handler already answered 504 must not dispatch
    later and burn a device slot: the server defaults the batcher
    deadline to its own timeout, so orphans expire at dispatch."""
    _, _, pred = _warm_predictor()
    clk = FakeClock()
    bat = MicroBatcher(pred, max_batch_size=8, max_wait_ms=5, clock=clk,
                       start=False)  # nothing dispatches: forces the 504
    srv = ModelServer(bat, request_timeout_s=0.05)
    srv.start()
    try:
        code, out = _http(srv.address, "/predict",
                          {"data": _x(1, seed=0).tolist()})
        assert code == 504
        clk.advance(1.0)  # past max_wait AND the defaulted deadline
        assert bat.poll() == 1
        assert telemetry.value("serving.deadline_expired") == 1
        assert telemetry.value("serving.batches") == 0  # never executed
    finally:
        srv.close()


# --------------------------------------------------- module ragged pad routing
class _ListIter:
    """Minimal DataIter: a fixed batch list (the ragged-tail scenario)."""

    def __init__(self, batches):
        self._batches = batches

    def reset(self):
        pass

    def __iter__(self):
        return iter(self._batches)


def test_module_ragged_predict_pads_instead_of_recompiling():
    from mxtpu import symbol as sym
    from mxtpu.io import DataBatch, DataDesc
    from mxtpu.module import Module

    data = sym.var("data")
    net = sym.FullyConnected(data, sym.var("w"), sym.var("b"), num_hidden=4,
                             name="fc")
    net = sym.SoftmaxOutput(net, sym.var("softmax_label"), name="softmax")
    mod = Module(net, data_names=("data",), label_names=("softmax_label",))
    mod.bind(data_shapes=[DataDesc("data", (8, 6))],
             label_shapes=[DataDesc("softmax_label", (8,))],
             for_training=False)
    mod.init_params(initializer=mx.init.Xavier())

    rng = np.random.RandomState(0)
    x = rng.randn(11, 6).astype(np.float32)
    batches = [
        DataBatch(data=[mx.nd.array(x[:8])],
                  label=[mx.nd.zeros((8,))]),
        DataBatch(data=[mx.nd.array(x[8:])],       # ragged tail: 3 rows
                  label=[mx.nd.zeros((3,))]),
    ]
    preds = mod.predict(_ListIter(batches))
    assert preds.shape == (11, 4)
    # ONE executor compile total: the ragged tail padded to the bound
    # batch size and reused the full-batch executable
    st = telemetry.retrace_stats("executor")
    assert st is not None and st["compiles"] == 1, st
    # value check: the tail rows equal a manual padded forward
    padded = np.zeros((8, 6), np.float32)
    padded[:3] = x[8:]
    mod.forward(DataBatch(data=[mx.nd.array(padded)],
                          label=[mx.nd.zeros((8,))]), is_train=False)
    ref_tail = mod.get_outputs()[0].asnumpy()[:3]
    np.testing.assert_allclose(preds.asnumpy()[8:], ref_tail, rtol=1e-5)
    assert telemetry.retrace_stats("executor")["compiles"] == 1


# ----------------------------------------------------- telemetry thread-safety
def test_d2h_span_attribution_is_thread_local():
    arr = mx.nd.ones((4,))
    arr.asnumpy()  # settle
    telemetry.reset()
    started, stop = threading.Event(), threading.Event()

    def noisy():
        started.set()
        while not stop.is_set():
            arr.asnumpy()

    t = threading.Thread(target=noisy, daemon=True)
    t.start()
    started.wait(5)
    try:
        for _ in range(5):
            with telemetry.span("quiet.region", d2h=True):
                time.sleep(0.002)  # concurrent asnumpy storms meanwhile
    finally:
        stop.set()
        t.join(5)
    snap = telemetry.snapshot()
    # the OTHER thread's syncs must not be attributed to this region...
    assert snap["counters"].get("quiet.region.d2h", 0) == 0
    # ...but the global watchdog counter still sees them
    assert telemetry.value("transfer.d2h") > 0
    # and a sync on the SPAN's own thread still attributes
    with telemetry.span("loud.region", d2h=True):
        arr.asnumpy()
    assert telemetry.snapshot()["counters"]["loud.region.d2h"] >= 1


def test_serving_metrics_fold_through_telemetry_report(tmp_path, monkeypatch):
    sink = str(tmp_path / "serve.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY", sink)
    _, _, pred = _warm_predictor(max_batch=4)
    clk = FakeClock()
    bat = MicroBatcher(pred, max_batch_size=4, max_wait_ms=5, clock=clk,
                       start=False)
    bat.submit(_x(2, seed=0))
    bat.submit(_x(2, seed=1))
    assert bat.poll() == 2
    telemetry.flush()
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "telemetry_report.py")
    spec = importlib.util.spec_from_file_location("telemetry_report", path)
    rep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(rep)
    summary = rep.aggregate(rep.load(sink))
    # counters, spans, and histograms all fold with the stock CLI
    assert summary["serving.requests"]["value"] == 2
    assert summary["serving.batches"]["value"] == 1
    assert summary["serving.predict"]["kind"] == "obs"
    assert summary["serving.batch_fill"]["kind"] == "obs"
    assert "serving.latency_s" in summary
    table = rep.format_table(summary)
    assert "serving.requests" in table


# ----------------------------------------------------------- acceptance run
def test_acceptance_500_requests_mixed_shapes_compile_budget():
    """ISSUE-5 acceptance: a 500-request mixed-shape closed-loop run
    serves with exactly <= B compiles at site serving.predict (zero
    watchdog trips) and zero d2h outside the declared output fetch."""
    net, spec, pred = _warm_predictor(max_batch=8)
    compiles0 = telemetry.retrace_stats("serving.predict")["compiles"]
    assert compiles0 == len(spec)
    bat = MicroBatcher(pred, max_batch_size=8, max_wait_ms=1,
                       max_queue=2048)
    errors = []

    def client(k, n_req):
        rng = np.random.RandomState(k)
        for i in range(n_req):
            n = int(rng.randint(1, 4))
            x = rng.randn(n, IN_DIM).astype(np.float32)
            try:
                out = bat.submit(x).result(timeout=60)
                assert out.shape == (n, OUT_DIM)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=client, args=(k, 125))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    bat.close()
    assert not errors, errors[:3]
    assert telemetry.value("serving.requests") == 500
    st = telemetry.retrace_stats("serving.predict")
    assert st["compiles"] == len(spec), \
        "mixed traffic added compiles: %s" % st
    assert st["trips"] == 0
    assert telemetry.value("retrace.watchdog_trips") == 0
    snap = telemetry.snapshot()
    # the predict span attributed ZERO syncs; the only serving d2h is the
    # declared output fetch span
    assert snap["counters"].get("serving.predict.d2h", 0) == 0
    assert snap["histograms"]["serving.fetch"]["count"] == \
        telemetry.value("serving.batches")
    assert snap["histograms"]["serving.latency_s"]["count"] == 500


# ------------------------------------------------------------------ load tier
@pytest.mark.slow
def test_open_loop_overload_sheds_and_bounds_latency():
    """Wall-clock load test (slow tier): offered QPS far beyond capacity
    must shed rather than grow the queue without bound, and the admitted
    requests' p99 stays bounded by queue/batch arithmetic."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import serve_bench as sb

    # a model heavy enough that this host CANNOT serve 20k single-item
    # requests/s: the run must shed (bounded queue) or expire (deadlines),
    # never absorb the backlog into unbounded latency
    pred, spec = sb.build_predictor(dim=256, width=1024, depth=3,
                                    max_batch=4)
    recs = sb.run_open(pred, spec, qps_list=(20000.0,), n_requests=400,
                       deadline_ms=50.0, max_wait_ms=1.0,
                       emit=lambda rec: None)
    rec = recs[0]
    assert rec["shed_rate"] + rec["expired_rate"] > 0, rec
    assert rec["p99_ms"] is not None and rec["p99_ms"] < 5000, rec


@pytest.mark.slow
def test_serve_bench_sweep_batching_win():
    """The sweep's load-bearing property on shared-CPU hardware: the max
    bucket must serve items substantially faster than batch 1 (the whole
    reason the batcher exists). The strict per-bucket monotonic gate is
    judged on the quiet chip tier via serve_bench/bench.py — adjacent
    buckets on a contended CPU host differ by less than scheduler noise."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import serve_bench as sb

    pred, spec = sb.build_predictor(dim=256, width=512, depth=3, max_batch=8)
    rates, _monotonic = sb.run_sweep(pred, spec, iters=30,
                                     emit=lambda rec: None)
    assert rates[-1] > rates[0] * 1.5, rates
