"""Continuous-batching autoregressive decode (mxtpu/serving/decode) —
ISSUE 11:

* BucketSpec ``decode_slots=`` spelling: capacity ladders, loud refusal
  of every cross-spelling misuse (decode spec in a Predictor, prefill
  spec as a cohort, mixed axes);
* Predictor int8 weight path: logits parity vs f32, refresh-params
  without recompiles;
* DecodeEngine correctness: generated tokens EXACTLY match an eager
  full-prefix reference greedy loop, continuous == restart-per-batch
  token streams (slot insert / donated carry cannot change a sequence's
  math), eos + max_new + max_len stopping, done-at-insert;
* continuous batching: joining sequences reuse freed slots between
  steps — strictly fewer cohort steps than restart-per-batch on the
  same workload, with ZERO post-warmup compiles at ``serving.decode``
  (AOT bucket replay, watchdog-pinned) and ZERO d2h inside the armed
  decode span;
* KVCacheAccountant: kv_residency shedding at the overcommit bound,
  ledger bookkeeping across admit/occupy/release, the MicroBatcher
  ``admission_gate=`` seam, ReplicaSet attach + dispatcher shed;
* decode-step wedge: injected ``decode_wedge`` under a fake clock trips
  the watchdog — stuck futures fail loud, their trace_ids land in the
  ``flight_record("decode_wedge")`` artifact, the engine keeps serving;
* threaded end-to-end + crash barrier;
* the serve_bench decode smoke (deterministic gates only).
"""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import resilience, telemetry
from mxtpu.base import MXNetError
from mxtpu.gluon import nn
from mxtpu.ndarray import NDArray
from mxtpu.serving import (BucketSpec, DeadlineExceeded, DecodeEngine,
                           KVCacheAccountant, MicroBatcher, Predictor,
                           QueueFull)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import serve_bench as sb  # noqa: E402  (the reference DecodeModel lives there)


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_TELEMETRY", "MXTPU_RETRACE_BUDGET",
                "MXTPU_FAULT_INJECT", "MXTPU_SERVE_INT8",
                "MXTPU_DECODE_SLOTS", "MXTPU_DECODE_QUEUE",
                "MXTPU_DECODE_MAX_NEW", "MXTPU_SERVE_KV_OVERCOMMIT",
                "MXTPU_SERVE_DISPATCH_TIMEOUT_MS", "MXTPU_FLIGHT_DIR"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    yield
    telemetry.reset()
    resilience.reset_faults()


VOCAB, DIM, MAX_LEN = 48, 12, 40


@pytest.fixture(scope="module")
def model():
    return sb.build_decode_model(vocab=VOCAB, dim=DIM, max_len=MAX_LEN,
                                 seed=7)


def _pspec():
    return BucketSpec([1], seq_lens=[6, 12])


def _engine(model, slots=2, eos=None, int8=False, continuous=True,
            accountant=None, clock=time.monotonic, timeout_ms=None,
            max_queue=None, max_len=32):
    return DecodeEngine(model, _pspec(),
                        BucketSpec.pow2(decode_slots=slots),
                        max_len=max_len, eos_id=eos, int8=int8,
                        continuous=continuous, accountant=accountant,
                        clock=clock, dispatch_timeout_ms=timeout_ms,
                        max_queue=max_queue, warmup=True, start=False)


def _run_all(eng, futs, limit=2000):
    n = 0
    while not all(f.done() for f in futs) and n < limit:
        eng.poll()
        n += 1
    return [f.result(timeout=2.0) for f in futs]


def _reference_greedy(model, prompt, max_new, eos=None):
    """Eager full-prefix replay — no KV cache, no buckets, no jit of
    ours: the ground truth the engine must match token for token."""
    import jax.numpy as jnp
    toks, out = list(prompt), []
    for _ in range(max_new):
        logits, _k, _v = model(NDArray(jnp.asarray(
            np.asarray(toks, np.int32)[None, :])))
        nxt = int(jnp.argmax(logits._data[0, len(toks) - 1]))
        out.append(nxt)
        toks.append(nxt)
        if eos is not None and nxt == eos:
            break
        if len(toks) >= MAX_LEN:
            break
    return out


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


# ------------------------------------------------------- BucketSpec spelling
def test_decode_slots_spelling():
    d = BucketSpec(decode_slots=(2, 8, 4))
    assert d.is_decode and d.decode_slots == (2, 4, 8)
    assert d.max_slots == 8 and d.slot_bucket(3) == 4
    assert d.slot_bucket(9) is None
    assert BucketSpec.pow2(decode_slots=8).decode_slots == (1, 2, 4, 8)
    assert "decode_slots" in repr(d)
    p = BucketSpec.pow2(4)
    assert not p.is_decode
    with pytest.raises(MXNetError, match="decode_slots"):
        p.max_slots
    with pytest.raises(MXNetError, match="decode_slots"):
        p.slot_bucket(1)


@pytest.mark.parametrize("bad", [
    lambda: BucketSpec(batch_sizes=[2], decode_slots=[2]),
    lambda: BucketSpec(decode_slots=[2], seq_lens=[8]),
    lambda: BucketSpec(decode_slots=[0]),
    lambda: BucketSpec(),
    lambda: BucketSpec.pow2(8, decode_slots=8),
    lambda: BucketSpec.pow2(decode_slots=8, seq_lens=[16]),
    lambda: BucketSpec.pow2(),
])
def test_decode_slots_validation_is_loud(bad):
    with pytest.raises(MXNetError):
        bad()


def test_predictor_refuses_decode_spec():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize()
    with pytest.raises(MXNetError, match="decode-cohort"):
        Predictor(net, BucketSpec(decode_slots=[2]),
                  example=np.zeros((1, 3), np.float32))


def test_engine_refuses_misdeclared_specs(model):
    with pytest.raises(MXNetError, match="decode_slots= spelling"):
        DecodeEngine(model, _pspec(), BucketSpec.pow2(4), warmup=False)
    with pytest.raises(MXNetError, match="prefill_spec is a decode"):
        DecodeEngine(model, BucketSpec(decode_slots=[2]),
                     BucketSpec(decode_slots=[2]), warmup=False)
    with pytest.raises(MXNetError, match="seq_lens"):
        DecodeEngine(model, BucketSpec([1]),
                     BucketSpec(decode_slots=[2]), warmup=False)
    net = nn.HybridSequential()
    with pytest.raises(MXNetError, match="decode_step"):
        DecodeEngine(net, _pspec(), BucketSpec(decode_slots=[2]),
                     warmup=False)


def test_cold_engine_refuses_submit(model):
    cold = DecodeEngine(model, _pspec(), BucketSpec(decode_slots=[2]),
                        warmup=False)
    with pytest.raises(MXNetError, match="cold DecodeEngine"):
        cold.submit(np.arange(3).astype(np.int32))


# ------------------------------------------------------- Predictor int8 path
def test_predictor_int8_parity_and_refresh():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"))
        net.add(nn.Dense(8))
    net.initialize()
    spec = BucketSpec.pow2(4)
    ex = np.zeros((1, 10), np.float32)
    pf = Predictor(net, spec, example=ex, warmup=True, name="f32")
    pq = Predictor(net, spec, example=ex, warmup=True, name="q", int8=True)
    assert pq.int8 and not pf.int8
    x = np.random.RandomState(0).randn(3, 10).astype(np.float32)
    a, b = pf.predict(x).asnumpy(), pq.predict(x).asnumpy()
    rel = np.abs(a - b).mean() / (np.abs(a).mean() + 1e-9)
    assert rel < 0.05, rel
    st = telemetry.retrace_stats("serving.predict")
    assert st["compiles"] == 2 * len(spec)
    # re-quantization after an in-place reload: zero recompiles
    pq.refresh_params()
    np.testing.assert_allclose(pq.predict(x).asnumpy(), b)
    assert telemetry.retrace_stats("serving.predict")["compiles"] \
        == 2 * len(spec)


def test_serve_int8_env_lever(monkeypatch):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize()
    monkeypatch.setenv("MXTPU_SERVE_INT8", "1")
    pred = Predictor(net, BucketSpec([1]),
                     example=np.zeros((1, 6), np.float32))
    assert pred.int8
    assert any(q is not None for q in pred._param_qdtypes)
    # 1-d bias stays exact storage; 2-d weight quantizes
    kinds = {d.ndim: (qdt is not None) for d, qdt
             in zip([p.data()._data for p in pred._params],
                    pred._param_qdtypes)}
    assert kinds[2] is True and kinds[1] is False


# --------------------------------------------------------- decode correctness
def test_engine_matches_eager_reference(model):
    eng = _engine(model, slots=2)
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, VOCAB, size=rng.randint(3, 11))
               .astype(np.int32) for _ in range(5)]
    maxnews = [4, 7, 3, 6, 5]
    futs = [eng.submit(p, max_new=m) for p, m in zip(prompts, maxnews)]
    outs = _run_all(eng, futs)
    for out, p, m in zip(outs, prompts, maxnews):
        assert out.dtype == np.int32
        assert out.tolist() == _reference_greedy(model, p, m)


def test_continuous_equals_restart_tokens(model):
    """Slot insert + donated carry must be invisible to a sequence's
    math: the same workload through a continuous cohort and through
    restart-per-batch produces IDENTICAL token streams."""
    rng = np.random.RandomState(2)
    reqs = [(rng.randint(0, VOCAB, size=rng.randint(3, 11))
             .astype(np.int32), int(rng.randint(2, 9)))
            for _ in range(6)]
    results = {}
    for continuous in (True, False):
        eng = _engine(model, slots=2, continuous=continuous)
        outs = _run_all(eng, [eng.submit(p, max_new=m) for p, m in reqs])
        results[continuous] = [o.tolist() for o in outs]
    assert results[True] == results[False]


def test_eos_stops_generation(model):
    prompt = np.arange(3, 8).astype(np.int32)
    ref = _reference_greedy(model, prompt, 8)
    eos = ref[2]  # force an eos hit at the third generated token
    eng = _engine(model, slots=1, eos=eos)
    out = _run_all(eng, [eng.submit(prompt, max_new=8)])[0]
    assert out.tolist() == _reference_greedy(model, prompt, 8, eos=eos)
    assert out[-1] == eos and len(out) == 3


def test_max_new_one_completes_at_insert(model):
    eng = _engine(model, slots=1)
    steps0 = telemetry.value("serving.decode.steps")
    fut = eng.submit(np.arange(4).astype(np.int32), max_new=1)
    eng.poll()
    out = fut.result(timeout=2.0)
    assert len(out) == 1
    assert out.tolist() == _reference_greedy(model, np.arange(4), 1)
    # done-at-insert: the first token came from the prefill logits, no
    # cohort step ever ran
    assert telemetry.value("serving.decode.steps") == steps0
    assert fut.ttft_s is not None and fut.ttft_s <= fut.e2e_s


def test_submit_validation_is_loud(model):
    eng = _engine(model, slots=1)
    with pytest.raises(MXNetError, match="1-d"):
        eng.submit(np.zeros((2, 3), np.int32))
    with pytest.raises(MXNetError, match="integer"):
        eng.submit(np.zeros(3, np.float32))
    with pytest.raises(MXNetError, match="exceeds the largest declared"):
        eng.submit(np.zeros(13, np.int32))  # past the max seq bucket
    with pytest.raises(MXNetError, match="max_new"):
        eng.submit(np.zeros(3, np.int32), max_new=0)
    # a cache too short to decode past the largest prompt bucket refuses
    # at CONSTRUCTION (which also makes the per-submit length invariant
    # prompt < max_len hold by construction)
    with pytest.raises(MXNetError, match="no room to decode"):
        DecodeEngine(model, _pspec(), BucketSpec(decode_slots=[1]),
                     max_len=12, warmup=False)


# ------------------------------------------------- continuous-batching + AOT
def test_continuous_batching_fewer_steps_flat_compiles(model):
    """The tentpole acceptance, deterministically: same workload, equal
    capacity — the continuous cohort takes strictly fewer steps than
    restart-per-batch (freed slots refill between steps), post-warmup
    compiles at serving.decode are ZERO for both, no watchdog trips, no
    d2h inside the armed span."""
    rng = np.random.RandomState(3)
    reqs = [(rng.randint(0, VOCAB, size=rng.randint(3, 11))
             .astype(np.int32), int(rng.randint(2, 13)))
            for _ in range(10)]
    steps = {}
    for continuous in (True, False):
        eng = _engine(model, slots=4, continuous=continuous)
        st0 = telemetry.retrace_stats(eng._site)["compiles"]
        s0 = telemetry.value("serving.decode.steps")
        _run_all(eng, [eng.submit(p, max_new=m) for p, m in reqs])
        steps[continuous] = telemetry.value("serving.decode.steps") - s0
        assert telemetry.retrace_stats(eng._site)["compiles"] == st0
        assert telemetry.retrace_stats(eng._site)["trips"] == 0
    assert steps[True] < steps[False], steps
    assert telemetry.value("serving.decode.d2h") == 0


def test_joiner_enters_running_cohort(model):
    """A sequence submitted while the cohort is mid-flight joins between
    steps — no drain, no recompile."""
    eng = _engine(model, slots=2)
    compiles0 = telemetry.retrace_stats(eng._site)["compiles"]
    first = eng.submit(np.arange(3).astype(np.int32), max_new=10)
    for _ in range(3):
        eng.poll()   # cohort is running
    assert eng.live_slots == 1 and not first.done()
    joiner = eng.submit(np.arange(5).astype(np.int32), max_new=5)
    eng.poll()
    assert eng.live_slots == 2   # joined the RUNNING cohort
    outs = _run_all(eng, [first, joiner])
    assert outs[0].tolist() == _reference_greedy(model, np.arange(3), 10)
    assert outs[1].tolist() == _reference_greedy(model, np.arange(5), 5)
    assert telemetry.retrace_stats(eng._site)["compiles"] == compiles0


def test_breakdown_and_ttft(model):
    eng = _engine(model, slots=2)
    fut = eng.submit(np.arange(6).astype(np.int32), max_new=4)
    _run_all(eng, [fut])
    bd = fut.breakdown
    for stage in ("serving.submit", "serving.queue_wait", "serving.prefill",
                  "serving.decode", "serving.fetch", "serving.deliver"):
        assert stage in bd, (stage, sorted(bd))
    assert fut.trace_id is not None
    assert fut.ttft_s is not None and 0 <= fut.ttft_s <= fut.e2e_s
    assert telemetry.value("serving.decode.tokens") >= 4


# ----------------------------------------------------------------- int8 path
def test_engine_int8_parity_and_kv_bytes(model):
    eng_f = _engine(model, slots=2)
    eng_q = _engine(model, slots=2, int8=True)
    prompt = np.arange(2, 9).astype(np.int32)
    lf, lq = eng_f.prefill_logits(prompt), eng_q.prefill_logits(prompt)
    rel = np.abs(lf - lq).mean() / (np.abs(lf).mean() + 1e-9)
    assert rel < 0.05, rel
    sf, sq = eng_f.step_logits_probe(prompt), eng_q.step_logits_probe(prompt)
    rel_s = np.abs(sf - sq).mean() / (np.abs(sf).mean() + 1e-9)
    assert rel_s < 0.05, rel_s
    # the residency dividend: int8 KV (+ per-position scales) costs at
    # most ~half the bytes per slot (≈1/4 vs this f32 model)
    assert eng_q.per_slot_kv_bytes() <= 0.55 * eng_f.per_slot_kv_bytes()
    # and the int8 engine still generates (stream math differs from f32
    # by quantization noise, so token equality is NOT asserted)
    out = _run_all(eng_q, [eng_q.submit(prompt, max_new=5)])[0]
    assert out.shape == (5,) and out.dtype == np.int32
    assert telemetry.value("serving.decode.d2h") == 0


# ------------------------------------------------------------- KV accounting
def test_kv_residency_shed_at_overcommit(model):
    acct = KVCacheAccountant()    # default overcommit 2.0
    eng = _engine(model, slots=1, accountant=acct)
    cap = acct.snapshot()["r0"]
    assert cap["per_slot_bytes"] == eng.per_slot_kv_bytes()
    assert cap["bucket_bytes"] == {1: eng.per_slot_kv_bytes()}
    futs = [eng.submit(np.arange(3).astype(np.int32), max_new=4)
            for _ in range(2)]   # 2 x capacity(1 slot) = the bound
    with pytest.raises(QueueFull, match="kv_residency"):
        eng.submit(np.arange(3).astype(np.int32), max_new=4)
    assert telemetry.value("serving.shed", tag="kv_residency") == 1
    _run_all(eng, futs)
    # completions release residency: admissible again
    fut = eng.submit(np.arange(3).astype(np.int32), max_new=2)
    _run_all(eng, [fut])
    snap = acct.snapshot()["r0"]
    assert snap["live"] == 0 and snap["queued"] == 0
    assert acct.resident_bytes("r0") == 0


def test_accountant_gauges_track_residency(model):
    acct = KVCacheAccountant(overcommit=10.0)
    eng = _engine(model, slots=2, accountant=acct)
    assert telemetry.snapshot()["gauges"]["serving.kv_capacity_bytes"] \
        == 2 * eng.per_slot_kv_bytes()
    fut = eng.submit(np.arange(3).astype(np.int32), max_new=6)
    eng.poll()   # prefill -> slot occupied
    assert telemetry.snapshot()["gauges"]["serving.kv_resident_bytes"] \
        == eng.per_slot_kv_bytes()
    _run_all(eng, [fut])
    assert telemetry.snapshot()["gauges"]["serving.kv_resident_bytes"] == 0


def test_microbatcher_admission_gate():
    """The accountant's gate plugs into the PLAIN batcher: admission
    sheds by the gate's reason without subclassing."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize()
    pred = Predictor(net, BucketSpec([2]),
                     example=np.zeros((1, 6), np.float32), warmup=True)
    acct = KVCacheAccountant(capacity_bytes=100, overcommit=1.0)
    acct.register("r0", per_slot_bytes=100, slots=1)
    bat = MicroBatcher(pred, start=False, admission_gate=acct.gate("r0"))
    bat.submit(np.zeros((1, 6), np.float32))   # pool empty: admits
    assert acct.try_admit("r0")
    acct.occupy("r0")                          # pool now full
    with pytest.raises(QueueFull, match="kv_residency"):
        bat.submit(np.zeros((1, 6), np.float32))
    assert telemetry.value("serving.shed", tag="kv_residency") == 1
    acct.release("r0")
    bat.submit(np.zeros((1, 6), np.float32))   # freed: admits again


def test_replicaset_accountant_surface():
    from mxtpu.serving import ReplicaDispatcher, ReplicaSet
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize()
    rset = ReplicaSet(net, BucketSpec([2]), n=1,
                      example=np.zeros((1, 6), np.float32), warmup=True)
    acct = KVCacheAccountant(capacity_bytes=64, overcommit=1.0)
    rset.attach_accountant(acct)
    acct.register("r0", per_slot_bytes=64, slots=1)
    states = rset.states()
    assert states[0]["kv_resident_bytes"] == 0
    disp = ReplicaDispatcher(rset, start=False, clock=FakeClock())
    disp.submit(np.zeros((1, 6), np.float32))   # admissible while empty
    assert acct.try_admit("r0")
    acct.occupy("r0")
    assert rset.states()[0]["kv_resident_bytes"] == 64
    assert not rset.kv_admissible()
    with pytest.raises(QueueFull, match="kv_residency"):
        disp.submit(np.zeros((1, 6), np.float32))


# ------------------------------------------------------------- wedge + fault
def test_decode_wedge_flight_record(model, monkeypatch, tmp_path):
    """The ISSUE-11 flight-recorder satellite: a decode step stuck past
    the dispatch timeout triggers flight_record with the stuck
    sequences' trace_ids; their futures fail loud; the engine keeps
    serving the queue on a fresh carry."""
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "decode_wedge@1")
    clock = FakeClock()
    eng = _engine(model, slots=2, clock=clock, timeout_ms=100.0)
    stuck = [eng.submit(np.arange(3).astype(np.int32), max_new=6)
             for _ in range(2)]
    eng.poll()          # step 0 runs clean
    eng.poll()          # step 1 "never answers" (injected wedge)
    assert not any(f.done() for f in stuck)
    clock.advance(0.2)  # past the 100 ms dispatch timeout
    eng.poll()          # the scan trips the watchdog
    for f in stuck:
        assert f.done()
        with pytest.raises(DeadlineExceeded, match="wedged"):
            f.result(timeout=0)
    assert telemetry.value("serving.decode.wedges") == 1
    assert telemetry.value("flight.dumps", tag="decode_wedge") == 1
    arts = [p for p in os.listdir(tmp_path) if "decode_wedge" in p]
    assert len(arts) == 1
    payload = json.loads((tmp_path / arts[0]).read_text())
    assert payload["reason"] == "decode_wedge"
    assert set(payload["trace_ids"]) == {f.trace_id for f in stuck}
    assert payload["extra"]["stuck"] == 2
    # the engine survives: slots freed, fresh carry, queue keeps serving
    assert eng.live_slots == 0
    out = _run_all(eng, [eng.submit(np.arange(4).astype(np.int32),
                                    max_new=3)])[0]
    assert out.tolist() == _reference_greedy(model, np.arange(4), 3)


def test_deadline_expires_while_queued(model):
    clock = FakeClock()
    eng = _engine(model, slots=1, clock=clock)
    hog = eng.submit(np.arange(3).astype(np.int32), max_new=10)
    eng.poll()   # hog takes the only slot
    late = eng.submit(np.arange(4).astype(np.int32), max_new=2,
                      deadline_ms=50.0)
    clock.advance(0.1)   # deadline passes while queued behind the hog
    _run_all(eng, [hog])
    eng.poll()   # the freed slot's admission pass pops (and expires) late
    assert late.done()
    with pytest.raises(DeadlineExceeded, match="KV slot"):
        late.result(timeout=0)
    assert telemetry.value("serving.deadline_expired") == 1


def test_queue_bound_sheds(model):
    eng = _engine(model, slots=1, max_queue=2)
    futs = [eng.submit(np.arange(3).astype(np.int32), max_new=3)
            for _ in range(2)]
    with pytest.raises(QueueFull, match="queue_full"):
        eng.submit(np.arange(3).astype(np.int32), max_new=3)
    _run_all(eng, futs)


# ------------------------------------------------------------- threaded mode
def test_threaded_end_to_end(model):
    acct = KVCacheAccountant(overcommit=50.0)
    eng = _engine(model, slots=2, accountant=acct)
    eng.start()
    try:
        rng = np.random.RandomState(5)
        prompts = [rng.randint(0, VOCAB, size=rng.randint(3, 11))
                   .astype(np.int32) for _ in range(8)]
        results = [None] * len(prompts)

        def client(i):
            fut = eng.submit(prompts[i], max_new=3 + i % 4)
            results[i] = fut.result(timeout=30.0)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        for i, (p, out) in enumerate(zip(prompts, results)):
            assert out is not None, "request %d hung" % i
            assert out.tolist() == _reference_greedy(model, p, 3 + i % 4)
        # the ledger balances under the submit/occupy race: admit() runs
        # under the admission lock BEFORE the loop thread can pop the
        # sequence, so no phantom queued count survives the run
        snap = acct.snapshot()["r0"]
        assert snap["live"] == 0 and snap["queued"] == 0, snap
    finally:
        eng.close(timeout=10.0)


def test_crash_barrier_fails_loud(model, monkeypatch):
    eng = _engine(model, slots=1)
    eng.start()
    try:
        monkeypatch.setattr(
            eng, "_harvest",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
        fut = eng.submit(np.arange(3).astype(np.int32), max_new=4)
        with pytest.raises(MXNetError, match="decode loop crashed"):
            fut.result(timeout=30.0)
        assert telemetry.value("serving.worker_crashes") == 1
        with pytest.raises(QueueFull, match="worker_crashed"):
            eng.submit(np.arange(3).astype(np.int32))
    finally:
        eng.close(timeout=5.0)


def test_threaded_injected_wedge_recovers(model, monkeypatch):
    """Threaded mode, injected wedge: the unresolved armed entry BLOCKS
    further steps (no clobbering — the wedge cannot be swallowed), the
    monitor trips it on the real clock, the stuck futures fail loud, and
    — because the loop thread kept cycling — probation clears and the
    engine keeps serving."""
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "decode_wedge@0")
    eng = _engine(model, slots=2, timeout_ms=100.0)
    eng.start()
    try:
        stuck = eng.submit(np.arange(3).astype(np.int32), max_new=6)
        with pytest.raises(DeadlineExceeded, match="wedged"):
            stuck.result(timeout=30.0)
        assert telemetry.value("serving.decode.wedges") == 1
        out = eng.submit(np.arange(4).astype(np.int32),
                         max_new=3).result(timeout=30.0)
        assert out.tolist() == _reference_greedy(model, np.arange(4), 3)
    finally:
        eng.close(timeout=10.0)


def test_wedge_probation_crashes_blocked_loop(model, monkeypatch):
    """A REAL wedge blocks the only loop thread inside the device call:
    after the trip, probation watches for loop progress for one more
    timeout window — none means blocked-forever, and the crash barrier
    fails the pending queue loud instead of stranding it
    (shed-never-hang)."""
    eng = _engine(model, slots=1, timeout_ms=100.0)
    block = threading.Event()
    real = eng._get_step_jit

    def blocked_get(b):
        jitted = real(b)

        def run(*args):
            block.wait(30.0)   # "the device call never returns"
            return jitted(*args)

        return run

    monkeypatch.setattr(eng, "_get_step_jit", blocked_get)
    eng.start()
    try:
        stuck = eng.submit(np.arange(3).astype(np.int32), max_new=6)
        queued = eng.submit(np.arange(4).astype(np.int32), max_new=3)
        with pytest.raises(DeadlineExceeded, match="wedged"):
            stuck.result(timeout=30.0)
        # probation expires with zero loop progress: the pending queue
        # fails loud and new submits shed
        with pytest.raises(MXNetError, match="decode loop crashed"):
            queued.result(timeout=30.0)
        assert telemetry.value("serving.worker_crashes") == 1
        with pytest.raises(QueueFull, match="worker_crashed"):
            eng.submit(np.arange(3).astype(np.int32))
    finally:
        block.set()
        eng.close(timeout=10.0)


def test_prefill_failure_completes_the_popped_future(model, monkeypatch):
    """A sequence popped from the queue whose prefill raises is in
    neither _pending nor _slots: its future must complete (loud) before
    the error propagates, and its accountant queued count must
    release — otherwise the crash barrier strands it forever."""
    acct = KVCacheAccountant(overcommit=10.0)
    eng = _engine(model, slots=1, accountant=acct)
    boom = {"on": True}
    real = eng._pred.predict_flat

    def flaky(*a, **k):
        if boom["on"]:
            raise RuntimeError("device burp")
        return real(*a, **k)

    monkeypatch.setattr(eng._pred, "predict_flat", flaky)
    fut = eng.submit(np.arange(3).astype(np.int32), max_new=3)
    with pytest.raises(RuntimeError, match="device burp"):
        eng.poll()
    assert fut.done()
    with pytest.raises(MXNetError, match="prefill failed"):
        fut.result(timeout=0)
    snap = acct.snapshot()["r0"]
    assert snap["queued"] == 0 and snap["live"] == 0, snap
    # poll mode has no crash barrier: once the device recovers, serving
    # continues
    boom["on"] = False
    out = _run_all(eng, [eng.submit(np.arange(4).astype(np.int32),
                                    max_new=2)])[0]
    assert out.tolist() == _reference_greedy(model, np.arange(4), 2)


def test_blocked_insert_dispatch_does_not_hold_the_lock(model, monkeypatch):
    """The insert jit dispatches OUTSIDE self._cond (same discipline as
    the step path): a dispatch blocked by a wedged tunnel must leave
    submits and the wedge scan runnable instead of deadlocking the whole
    engine on the lock. (Generous timeout: the prefill wedge watchdog
    must NOT trip during this test — that path has its own test below.)"""
    eng = _engine(model, slots=2, timeout_ms=30000.0)
    block = threading.Event()
    real = eng._get_insert_jit

    def blocked_get(s):
        jitted = real(s)

        def run(*args):
            block.wait(30.0)
            return jitted(*args)

        return run

    monkeypatch.setattr(eng, "_get_insert_jit", blocked_get)
    eng.start()
    try:
        first = eng.submit(np.arange(3).astype(np.int32), max_new=2)
        time.sleep(0.1)   # the loop is now blocked inside the insert
        t0 = time.perf_counter()
        second = eng.submit(np.arange(4).astype(np.int32), max_new=2)
        elapsed = time.perf_counter() - t0
        assert elapsed < 1.0, "submit blocked behind the wedged dispatch"
        assert eng._scan_wedges(eng._clock()) is None  # scan runnable too
        # the popped-but-unregistered sequence is VISIBLE to drain: the
        # engine must not report empty while a prompt is mid-prefill
        assert eng.drain(timeout=0.2) is False
        block.set()
        for f in (first, second):
            assert len(f.result(timeout=30.0)) == 2
    finally:
        block.set()
        eng.close(timeout=10.0)


def test_prefill_wedge_trips_and_sheds(model, monkeypatch, tmp_path):
    """A wedge during the PREFILL/insert dispatch (not a step) is
    detected too: the prefill watchdog entry trips, the stuck prompt's
    future fails loud with a flight artifact, and — the loop thread
    being genuinely blocked — probation escalates to the crash barrier
    so the queue sheds instead of stranding."""
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    eng = _engine(model, slots=1, timeout_ms=100.0)
    block = threading.Event()
    real = eng._get_insert_jit

    def blocked_get(s):
        jitted = real(s)

        def run(*args):
            block.wait(30.0)   # "the device never answers"
            return jitted(*args)

        return run

    monkeypatch.setattr(eng, "_get_insert_jit", blocked_get)
    eng.start()
    try:
        stuck = eng.submit(np.arange(3).astype(np.int32), max_new=3)
        queued = eng.submit(np.arange(4).astype(np.int32), max_new=3)
        with pytest.raises(DeadlineExceeded, match="prefill dispatch"):
            stuck.result(timeout=30.0)
        # the future fails ATOMICALLY with the abandonment; the flight
        # dump (tmp+rename) follows on the monitor thread — wait for the
        # finalized artifact, not the in-progress .tmp
        arts = []
        for _ in range(200):
            arts = [p for p in os.listdir(tmp_path)
                    if "decode_wedge" in p and p.endswith(".json")]
            if arts:
                break
            time.sleep(0.02)
        assert telemetry.value("serving.decode.wedges") == 1
        assert len(arts) == 1
        payload = json.loads((tmp_path / arts[0]).read_text())
        assert payload["extra"]["kind"] == "prefill"
        assert stuck.trace_id in payload["trace_ids"]
        # probation: the blocked loop makes no progress -> crash barrier
        with pytest.raises(MXNetError, match="decode loop crashed"):
            queued.result(timeout=30.0)
        with pytest.raises(QueueFull, match="worker_crashed"):
            eng.submit(np.arange(3).astype(np.int32))
    finally:
        block.set()
        eng.close(timeout=10.0)


def test_int8_refresh_sticky_on_degenerate_reload():
    """A reload that zeroes a quantized weight keeps its int8 slot (unit
    grid — zeros stay exact): the executables' argument structure never
    changes, so refresh stays recompile-free even through degenerate
    weights."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8))
    net.initialize()
    pred = Predictor(net, BucketSpec([2]),
                     example=np.zeros((1, 6), np.float32), warmup=True,
                     int8=True)
    qdts0 = list(pred._param_qdtypes)
    weight = [p for p in pred._params if p.data()._data.ndim == 2][0]
    weight.set_data(mx.nd.zeros(weight.data().shape))
    pred.refresh_params()
    assert list(pred._param_qdtypes) == qdts0   # structure pinned
    out = pred.predict(np.ones((2, 6), np.float32)).asnumpy()
    # zero weight -> output is exactly the (untouched) bias
    bias = [p for p in pred._params if p.data()._data.ndim == 1][0]
    np.testing.assert_allclose(out, np.tile(bias.data().asnumpy(), (2, 1)),
                               atol=1e-6)
    assert telemetry.retrace_stats("serving.predict")["compiles"] \
        == len(BucketSpec([2]))


# ------------------------------------------------------------ bench smoke
def test_serve_bench_decode_smoke():
    """tools/serve_bench.py --mode decode, small: the DETERMINISTIC
    gates (token parity continuous vs restart, zero post-warmup
    compiles, zero in-loop d2h, int8 parity + KV bytes). The tokens/s
    speedup gate is wall-clock and belongs to the bench artifact, not
    tier-1."""
    rec = sb.run_decode(n_requests=12, slots=2, max_new=8, vocab=64,
                        dim=16, max_prompt=12, emit=lambda r: None)
    assert rec["continuous"]["compiles_post_warmup"] == 0
    assert rec["restart"]["tokens"] == rec["continuous"]["tokens"]
    assert rec["continuous"]["steps"] < rec["restart"]["steps"]
    assert rec["prefill_logits_rel_err"] < 0.05
    assert rec["step_logits_rel_err"] < 0.05
    assert rec["kv_bytes_ratio"] <= 0.55
    assert telemetry.value("serving.decode.d2h") == 0
