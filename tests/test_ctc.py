"""CTC loss vs an independent numpy reference (ref: tests/python/unittest/
test_operator.py:test_ctc_loss; kernel src/operator/nn/ctc_loss.cc).

The numpy oracle enumerates ALL alignment paths for tiny T (exact, no shared
code with the lax.scan implementation), so blank/repeat topology bugs can't
cancel out.
"""
import itertools

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import gluon


def _softmax(x, axis):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _collapse(path, blank):
    out = []
    prev = None
    for p in path:
        if p != prev and p != blank:
            out.append(p)
        prev = p
    return out


def _brute_ctc(acts, label, blank):
    """-log P(label | acts) by summing over every alignment path."""
    T, C = acts.shape
    probs = _softmax(acts, 1)
    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        if _collapse(path, blank) == list(label):
            p = 1.0
            for t, c in enumerate(path):
                p *= probs[t, c]
            total += p
    return -np.log(total)


@pytest.mark.parametrize("blank_label", ["first", "last"])
def test_ctc_loss_vs_bruteforce(blank_label):
    rng = np.random.RandomState(42)
    T, N, C, L = 5, 4, 4, 2
    acts = rng.uniform(-2, 2, (T, N, C)).astype("float32")
    blank = 0 if blank_label == "first" else C - 1
    pad = 0 if blank_label == "first" else -1
    tokens = [c for c in range(C) if c != blank]
    labels = np.full((N, L), pad, "int32")
    # row 0: two distinct tokens; row 1: repeat (needs blank between);
    # row 2: single token; row 3: empty label
    labels[0, :2] = [tokens[0], tokens[1]]
    labels[1, :2] = [tokens[0], tokens[0]]
    labels[2, 0] = tokens[2]
    if blank_label == "first":
        # pad value 0 terminates the label at first 0 -> rows already ok
        pass
    out = mx.nd.CTCLoss(mx.nd.array(acts), mx.nd.array(labels),
                        blank_label=blank_label).asnumpy()
    for n in range(N):
        lab = [int(v) for v in labels[n] if v != pad]
        want = _brute_ctc(acts[:, n], lab, blank)
        np.testing.assert_allclose(out[n], want, rtol=1e-4, atol=1e-4)


def test_ctc_loss_lengths():
    """Explicit data/label lengths mask trailing junk."""
    rng = np.random.RandomState(0)
    T, N, C = 6, 2, 5
    acts = rng.uniform(-1, 1, (T, N, C)).astype("float32")
    labels = np.array([[1, 2, 3], [2, 4, 4]], "int32")  # junk beyond lengths
    dlen = np.array([4, 6], "float32")
    llen = np.array([2, 1], "float32")
    out = mx.nd.CTCLoss(mx.nd.array(acts), mx.nd.array(labels),
                        mx.nd.array(dlen), mx.nd.array(llen),
                        use_data_lengths=True, use_label_lengths=True,
                        blank_label="first").asnumpy()
    for n, (tn, ln) in enumerate([(4, 2), (6, 1)]):
        want = _brute_ctc(acts[:tn, n], list(labels[n, :ln]), 0)
        np.testing.assert_allclose(out[n], want, rtol=1e-4, atol=1e-4)


def test_ctc_loss_gradient():
    """Gradient matches numeric differentiation through softmax+alpha."""
    rng = np.random.RandomState(1)
    T, N, C = 4, 2, 3
    acts = rng.uniform(-1, 1, (T, N, C)).astype("float64").astype("float32")
    labels = np.array([[1, 2], [2, 0]], "int32")
    x = mx.nd.array(acts)
    x.attach_grad()
    with mx.autograd.record():
        loss = mx.nd.CTCLoss(x, mx.nd.array(labels), blank_label="first")
        total = loss.sum()
    total.backward()
    g = x.grad.asnumpy()
    eps = 1e-3
    for (t, n, c) in [(0, 0, 1), (2, 1, 2), (3, 0, 0)]:
        ap = acts.copy(); ap[t, n, c] += eps
        am = acts.copy(); am[t, n, c] -= eps
        lp = mx.nd.CTCLoss(mx.nd.array(ap), mx.nd.array(labels),
                           blank_label="first").asnumpy().sum()
        lm = mx.nd.CTCLoss(mx.nd.array(am), mx.nd.array(labels),
                           blank_label="first").asnumpy().sum()
        np.testing.assert_allclose(g[t, n, c], (lp - lm) / (2 * eps),
                                   rtol=2e-2, atol=2e-3)


def test_gluon_ctc_loss_eager_and_hybrid():
    """gluon.loss.CTCLoss works (VERDICT weak #2: it used to crash) in both
    eager and hybridized mode, NTC layout, blank_label='last' semantics."""
    rng = np.random.RandomState(2)
    N, T, C = 2, 5, 4
    pred = rng.uniform(-1, 1, (N, T, C)).astype("float32")
    label = np.array([[0, 1], [2, -1]], "float32")  # -1 padding ('last')
    blk = gluon.loss.CTCLoss()
    out_eager = blk(mx.nd.array(pred), mx.nd.array(label)).asnumpy()
    blk.hybridize()
    out_hybrid = blk(mx.nd.array(pred), mx.nd.array(label)).asnumpy()
    np.testing.assert_allclose(out_eager, out_hybrid, rtol=1e-5, atol=1e-5)
    for n in range(N):
        lab = [int(v) for v in label[n] if v != -1]
        want = _brute_ctc(pred[n], lab, C - 1)
        np.testing.assert_allclose(out_eager[n], want, rtol=1e-4, atol=1e-4)


def test_gluon_ctc_loss_trains():
    """A tiny model under autograd+Trainer decreases CTC loss."""
    rng = np.random.RandomState(3)
    from mxtpu.gluon import nn
    net = nn.Dense(5, flatten=False)
    net.initialize()
    x = mx.nd.array(rng.uniform(-1, 1, (2, 6, 3)))
    label = mx.nd.array(np.array([[1, 2], [3, -1]], "float32"))
    loss_fn = gluon.loss.CTCLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    first = None
    for i in range(12):
        with mx.autograd.record():
            loss = loss_fn(net(x), label)
        loss.backward()
        trainer.step(2)
        v = float(loss.mean().asnumpy())
        if first is None:
            first = v
    assert v < first
