"""Known-good fixture for the compile-service seam: the cache miss
resolves through compile_service.get_or_build with a canonical key — the
declared site name rides the canonical_key(site=...) literal."""
import jax

compile_service = None  # stand-in; the analyzer matches the call shape


def compile_it(fn, shapes, pol):
    key = compile_service.canonical_key(
        site="fixture_site", fn_id="fixture", signature=shapes, policy=pol)

    def build():
        return jax.jit(fn)

    return compile_service.get_or_build(key, build).fn
