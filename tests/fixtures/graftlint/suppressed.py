"""Suppression fixture: the same violations as the known-bad files, each
carrying an inline `# graftlint: disable=<rule>` — every finding must land
in the suppressed bucket, none in the active one."""
import jax
import os


def host_side_lever():
    return os.environ.get("MXTPU_BAZ", "0")  # graftlint: disable=policy-key-coverage


def build(x):
    def pure(a):
        return a.asnumpy()  # graftlint: disable=host-sync-in-traced-region

    return jax.jit(pure)(x)  # graftlint: disable=retrace-site-registration


def donate_then_read(params, batch):
    step = jax.jit(lambda w, b: w + b, donate_argnums=(0,))  # graftlint: disable=retrace-site-registration
    out = step(params, batch)
    return params.sum() + out  # graftlint: disable=use-after-donate


def compile_it(fn, x):
    return jax.jit(fn)(x)  # graftlint: disable=all
