"""Known-bad fixture for host-sync-in-traced-region: every flagged
construct class, inside jit bodies reached three ways (argument, nested
closure, decorator). Never imported — parsed by the analyzer only."""
import jax
import numpy as np


def build(x):
    def pure(a):
        host = np.asarray(a)          # np.asarray on a traced value
        scalar = float(a.sum())       # scalar coercion syncs
        raw = a.asnumpy()             # the d2h sync spelled directly
        one = a.item()                # item() syncs
        return host, scalar, raw, one

    return jax.jit(pure)(x)


def build_nested(x):
    def outer(a):
        def inner(b):
            return b.asnumpy()        # nested def inside a traced fn

        return inner(a)

    return jax.jit(outer)(x)


@jax.jit
def decorated(a):
    if bool(a.sum() > 0):             # bool() on a traced predicate
        return a
    return -a
