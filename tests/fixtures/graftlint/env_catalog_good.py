"""Known-good fixture for env-var-catalog: every read has a row and the
MXTPU_STALE row has a read here, so the fixture doc is fully reconciled."""
import os


def documented():
    return os.environ.get("MXTPU_DOCUMENTED", "0") == "1"


def stale_is_actually_read_here():
    return os.environ.get("MXTPU_STALE", "0") == "1"
