"""Known-bad fixture for env-var-catalog (vs env_doc_fixture.md): reads a
lever with no catalog row; MXTPU_STALE is documented but never read."""
import os


def undocumented():
    return os.environ.get("MXTPU_UNDOCUMENTED", "0") == "1"


def documented():
    return os.environ.get("MXTPU_DOCUMENTED", "0") == "1"
