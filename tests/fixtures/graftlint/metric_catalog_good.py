"""Known-good fixture for metric-name-catalog: every recorded name has a
row and the `metric.stale` row has a record site here, so the fixture doc
is fully reconciled."""
from mxtpu import telemetry


def documented(i):
    telemetry.inc("good.counter")
    with telemetry.span("good.span", d2h=True):
        pass
    telemetry.gauge("family.a", 1)
    telemetry.observe("family.b", 0.5)
    telemetry.inc("dyn.r%d" % i)
    telemetry.inc("tagged.thing", tag="why")
    telemetry.record_retrace("fixture_site")


def stale_is_actually_recorded_here():
    telemetry.observe("metric.stale", 1.0)
