"""Known-bad fixture for policy-key-coverage (vs registry_fixture.py):
one lever missing from the key, one default mismatch, one missing default.
Never imported — parsed by the analyzer only."""
import os


def baz_enabled():
    # MXTPU_BAZ is not in the fixture policy key at all
    return os.environ.get("MXTPU_BAZ", "0") == "1"


def bar_enabled():
    # key says default "1"; this read site says "0"
    return os.environ.get("MXTPU_BAR", "0") == "1"


def foo_enabled():
    # key says default "0"; this read has NO default (unset -> None)
    return os.environ.get("MXTPU_FOO") == "1"
