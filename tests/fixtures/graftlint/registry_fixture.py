"""Fixture policy-key module: the shape of mxtpu/ops/registry.py:policy_key
reduced to two levers, for graftlint rule tests."""
import os


def policy_key():
    return (os.environ.get("MXTPU_FOO", "0"),
            os.environ.get("MXTPU_BAR", "1"))


def stray_gate():
    # OUTSIDE policy_key: the rule must still convict reads elsewhere in
    # the registry module itself — only the key function's reads are
    # exempt (they ARE the key)
    return os.environ.get("MXTPU_STRAY", "0") == "1"
