"""Known-bad fixture for metric-name-catalog (vs metric_doc_fixture.md):
records two names with no catalog row; `metric.stale` is documented but
never recorded."""
from mxtpu import telemetry


def documented(i):
    telemetry.inc("good.counter")
    with telemetry.span("good.span", d2h=True):
        pass
    telemetry.gauge("family.a", 1)
    telemetry.observe("family.b", 0.5)
    telemetry.inc("dyn.r%d" % i)
    telemetry.inc("tagged.thing", tag="why")
    telemetry.record_retrace("fixture_site")


def undocumented():
    telemetry.inc("metric.undocumented")
    with telemetry.span("span.undocumented"):
        pass
