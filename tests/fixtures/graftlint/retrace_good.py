"""Known-good fixture for retrace-site-registration: the cache-miss path
reports every compile with provenance before building the executable."""
import jax

telemetry = None  # stand-in; the analyzer matches the call shape only
_CACHE = {}


def compile_it(fn, key):
    if key not in _CACHE:
        telemetry.record_retrace("fixture_site", {"key": key})
        _CACHE[key] = jax.jit(fn)
    return _CACHE[key]
