"""Known-bad fixture for the compile-service seam: the site reports its
compiles (record_retrace) but keeps an out-of-band private cache — inside
a service scope every jit surface must resolve through
compile_service.get_or_build so it shares the LRU bound, the persistent
executable cache, and AOT warmup."""
import jax

telemetry = None  # stand-in; the analyzer matches the call shape only
_CACHE = {}


def compile_it(fn, key):
    if key not in _CACHE:
        telemetry.record_retrace("fixture_site", {"key": key})
        _CACHE[key] = jax.jit(fn)
    return _CACHE[key]
