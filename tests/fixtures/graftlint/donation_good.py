"""Known-good fixture for use-after-donate: the donated name is rebound to
the call's result (the fused-updater idiom), or only the result is used."""
import jax


def rebind_form(params, batch):
    step = jax.jit(lambda w, b: w + b, donate_argnums=(0,))
    params = step(params, batch)      # rebinding clears the donation
    return params.sum()


def result_only(a, b):
    out = jax.jit(lambda x, y: x * y, donate_argnums=(0,))(a, b)
    return out + b                    # b was never donated


def multiline_rebind(params, batch):
    step = jax.jit(lambda w, b: w + b, donate_argnums=(0,))
    params = step(                    # call spans lines: the arg load and
        params, batch)                # rebind still order correctly
    return params.sum()
