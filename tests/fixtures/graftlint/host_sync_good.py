"""Known-good fixture for host-sync-in-traced-region: jit bodies stay on
device; syncs and coercions happen only in host code outside the trace;
shape arithmetic inside the trace is static and legal."""
import jax
import jax.numpy as jnp
import numpy as np


def build(x):
    def pure(a):
        n = int(a.shape[0])           # shape arithmetic is static
        m = float(len(a.shape))       # len() is static too
        return jnp.sum(a) / (n * m)

    return jax.jit(pure)(x)


def host_side(x):
    out = build(x)
    host = np.asarray(out)            # legal: outside any traced region
    return float(host.sum()), out.item()
