"""Known-bad fixture for retrace-site-registration: jit caches that never
report compiles to the retrace watchdog. Never imported — parsed only."""
import jax

_CACHE = {}


def compile_it(fn, key):
    if key not in _CACHE:
        _CACHE[key] = jax.jit(fn)     # unreported compile site
    return _CACHE[key]


def one_off(fn, x):
    return jax.jit(fn)(x)             # unreported, not even cached
