"""Known-good fixture for policy-key-coverage: both levers read with
defaults that mirror the fixture key exactly."""
import os


def foo_enabled():
    return os.environ.get("MXTPU_FOO", "0") == "1"


def bar_enabled():
    return os.environ.get("MXTPU_BAR", "1") == "1"
