"""Known-bad fixture for use-after-donate: donated buffers read after the
call, through a bound jit, a direct call, and donate_argnames. Never
imported — parsed by the analyzer only."""
import jax


def bound_form(params, batch):
    step = jax.jit(lambda w, b: w + b, donate_argnums=(0,))
    out = step(params, batch)
    return params.sum() + out         # params was donated on the call above


def direct_form(a, b):
    out = jax.jit(lambda x, y: x * y, donate_argnums=(0, 1))(a, b)
    return out, b                     # b was donated too


def argnames_form(state, grads):
    upd = jax.jit(lambda state, g: state - g, donate_argnames=("state",))
    new = upd(state, grads)
    state.block_until_ready()         # donated by name
    return new


def multiline_form(params, batch):
    step = jax.jit(lambda w, b: w + b, donate_argnums=(0,))
    out = step(
        params, batch)                # donation on a wrapped call
    return params + out               # ...still a use-after-donate
