"""C ABI tests (ref: include/mxnet/c_api.h, src/c_api/c_predict_api.cc).

Two tiers, mirroring how the reference exercises its C surface:
* in-process: drive _libmxtpu.so through ctypes from this interpreter,
* out-of-process: compile a real C program against include/mxtpu/c_api.h,
  link _libmxtpu.so, and have it classify a tensor with an exported model —
  the reference's example/image-classification/predict-cpp scenario.
"""
import ctypes
import os
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import gluon
from mxtpu._native import get_lib, build_error

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def lib():
    lib = get_lib()
    if lib is None:
        pytest.fail("native build failed: %s" % build_error())
    return lib


def _nd_from_blob(lib, arr):
    arr = np.ascontiguousarray(arr, np.float32)
    shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
    h = ctypes.c_void_p()
    rc = lib.MXTPUNDArrayCreateFromBlob(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), shape, arr.ndim,
        ctypes.byref(h))
    assert rc == 0, lib.MXTPUGetLastError()
    return h


def _nd_to_numpy(lib, h):
    ndim = ctypes.c_int()
    shape = (ctypes.c_int64 * 8)()
    rc = lib.MXTPUNDArrayShape(h, ctypes.byref(ndim), shape)
    assert rc == 0, lib.MXTPUGetLastError()
    dims = tuple(shape[i] for i in range(ndim.value))
    out = np.empty(dims, np.float32)
    rc = lib.MXTPUNDArraySyncCopyToCPU(
        h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(np.prod(dims)) if dims else 1)
    assert rc == 0, lib.MXTPUGetLastError()
    return out


def test_ndarray_roundtrip(lib):
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    h = _nd_from_blob(lib, x)
    back = _nd_to_numpy(lib, h)
    np.testing.assert_array_equal(back, x)
    lib.MXTPUNDArrayFree(h)


def test_imperative_invoke_by_name(lib):
    a = np.random.RandomState(0).uniform(-1, 1, (2, 3)).astype(np.float32)
    b = np.random.RandomState(1).uniform(-1, 1, (2, 3)).astype(np.float32)
    ha, hb = _nd_from_blob(lib, a), _nd_from_blob(lib, b)
    ins = (ctypes.c_void_p * 2)(ha, hb)
    outs = (ctypes.c_void_p * 4)()
    nout = ctypes.c_int(4)
    rc = lib.MXTPUImperativeInvoke(b"broadcast_add", ins, 2, None, None, 0,
                                   outs, ctypes.byref(nout))
    assert rc == 0, lib.MXTPUGetLastError()
    assert nout.value == 1
    np.testing.assert_allclose(_nd_to_numpy(lib, outs[0]), a + b, rtol=1e-6)
    for h in (ha, hb, outs[0]):
        lib.MXTPUNDArrayFree(h)


def test_invoke_with_attrs(lib):
    x = np.random.RandomState(0).uniform(-1, 1, (2, 6)).astype(np.float32)
    h = _nd_from_blob(lib, x)
    ins = (ctypes.c_void_p * 1)(h)
    outs = (ctypes.c_void_p * 1)()
    nout = ctypes.c_int(1)
    keys = (ctypes.c_char_p * 1)(b"shape")
    vals = (ctypes.c_char_p * 1)(b"(3, 4)")
    rc = lib.MXTPUImperativeInvoke(b"Reshape", ins, 1, keys, vals, 1, outs,
                                   ctypes.byref(nout))
    assert rc == 0, lib.MXTPUGetLastError()
    np.testing.assert_array_equal(_nd_to_numpy(lib, outs[0]),
                                  x.reshape(3, 4))
    lib.MXTPUNDArrayFree(h)
    lib.MXTPUNDArrayFree(outs[0])


def test_error_surface(lib):
    x = _nd_from_blob(lib, np.ones((2, 2), np.float32))
    ins = (ctypes.c_void_p * 1)(x)
    outs = (ctypes.c_void_p * 1)()
    nout = ctypes.c_int(1)
    rc = lib.MXTPUImperativeInvoke(b"no_such_op_exists", ins, 1, None, None,
                                   0, outs, ctypes.byref(nout))
    assert rc == -1
    assert b"no_such_op_exists" in lib.MXTPUGetLastError()
    lib.MXTPUNDArrayFree(x)


@pytest.fixture(scope="module")
def exported_model(tmp_path_factory):
    """Export a small trained-ish MLP classifier to symbol+params."""
    tmp = tmp_path_factory.mktemp("export")
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu"))
        net.add(gluon.nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).uniform(-1, 1, (2, 8)))
    net(x)
    net.hybridize()
    net(x)
    prefix = str(tmp / "mlp")
    net.export(prefix, epoch=0)
    expect = net(x).asnumpy()
    return prefix, x.asnumpy(), expect


def test_predict_api_inprocess(lib, exported_model):
    prefix, x, expect = exported_model
    shape = (ctypes.c_int64 * 2)(*x.shape)
    pred = ctypes.c_void_p()
    rc = lib.MXTPUPredCreate(prefix.encode(), 0, b"data", shape, 2,
                             ctypes.byref(pred))
    assert rc == 0, lib.MXTPUGetLastError()
    xf = np.ascontiguousarray(x, np.float32)
    rc = lib.MXTPUPredSetInput(
        pred, xf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), xf.size)
    assert rc == 0, lib.MXTPUGetLastError()
    rc = lib.MXTPUPredForward(pred)
    assert rc == 0, lib.MXTPUGetLastError()
    ndim = ctypes.c_int()
    oshape = (ctypes.c_int64 * 8)()
    rc = lib.MXTPUPredGetOutputShape(pred, 0, ctypes.byref(ndim), oshape)
    assert rc == 0, lib.MXTPUGetLastError()
    dims = tuple(oshape[i] for i in range(ndim.value))
    assert dims == expect.shape
    out = np.empty(dims, np.float32)
    rc = lib.MXTPUPredGetOutput(
        pred, 0, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size)
    assert rc == 0, lib.MXTPUGetLastError()
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)
    lib.MXTPUPredFree(pred)


C_SMOKE = r"""
#include <stdio.h>
#include <stdlib.h>
#include "mxtpu/c_api.h"

int main(int argc, char **argv) {
  const char *prefix = argv[1];
  int64_t shape[2] = {2, 8};
  float x[16];
  for (int i = 0; i < 16; ++i) x[i] = (float)(i % 5) * 0.25f - 0.5f;

  if (MXTPURuntimeInit("cpu") != 0) {
    fprintf(stderr, "init: %s\n", MXTPUGetLastError());
    return 1;
  }
  PredictorHandle pred;
  if (MXTPUPredCreate(prefix, 0, "data", shape, 2, &pred) != 0) {
    fprintf(stderr, "create: %s\n", MXTPUGetLastError());
    return 1;
  }
  if (MXTPUPredSetInput(pred, x, 16) != 0 || MXTPUPredForward(pred) != 0) {
    fprintf(stderr, "fwd: %s\n", MXTPUGetLastError());
    return 1;
  }
  int ndim;
  int64_t oshape[8];
  if (MXTPUPredGetOutputShape(pred, 0, &ndim, oshape) != 0) return 1;
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= oshape[i];
  float *out = (float *)malloc(n * sizeof(float));
  if (MXTPUPredGetOutput(pred, 0, out, n) != 0) return 1;
  /* print argmax per row: the "classification" */
  for (int64_t r = 0; r < oshape[0]; ++r) {
    int best = 0;
    for (int c = 1; c < oshape[1]; ++c)
      if (out[r * oshape[1] + c] > out[r * oshape[1] + best]) best = c;
    printf("row%lld:class%d\n", (long long)r, best);
  }
  for (int64_t i = 0; i < n; ++i) printf("%.6f ", out[i]);
  printf("\n");
  MXTPUPredFree(pred);
  return 0;
}
"""


def _compile_against_abi(src_path, exe_path, compiler="gcc", extra=()):
    """ONE copy of the build recipe for out-of-process ABI smoke programs
    (shared by the C and C++ frontend tests)."""
    so_dir = os.path.join(REPO, "mxtpu", "_native")
    ver = sysconfig.get_config_var("LDVERSION")
    libdir = sysconfig.get_config_var("LIBDIR")
    cmd = ([compiler] + list(extra) + [str(src_path), "-o", str(exe_path),
           "-I", os.path.join(REPO, "include"),
           "-L", so_dir, "-Wl,-rpath," + so_dir, "-l:_libmxtpu.so",
           "-L", libdir, "-Wl,-rpath," + libdir, "-lpython" + ver])
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def _run_smoke(exe_path, prefix=None):
    env = dict(os.environ)
    site = sysconfig.get_paths()["purelib"]
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO, site] + env.get("PYTHONPATH", "").split(os.pathsep))
    env["MXTPU_JAX_PLATFORMS"] = "cpu"  # hermetic: no TPU tunnel from CI
    cmd = [str(exe_path)] + ([] if prefix is None else [prefix])
    proc = subprocess.run(cmd, capture_output=True,
                          text=True, env=env, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc.stdout.strip().splitlines()


def _reference_forward(prefix):
    """Python-side forward of the exported checkpoint on the smoke
    programs' fixed input — the expectation both smoke tests check."""
    x = (np.arange(16, dtype=np.float32) % 5) * 0.25 - 0.5
    x = x.reshape(2, 8)
    from mxtpu import model as mxmodel
    sym, arg, aux = mxmodel.load_checkpoint(prefix, 0)
    exe_ = sym.bind(args={**arg, "data": mx.nd.array(x)}, aux_states=aux,
                    grad_req="null")
    return exe_.forward(is_train=False)[0].asnumpy()


def test_predict_api_from_c_program(lib, exported_model, tmp_path):
    """Compile + run a real C program against the ABI (no Python host)."""
    prefix, _x, expect = exported_model
    csrc = tmp_path / "smoke.c"
    csrc.write_text(C_SMOKE)
    exe = tmp_path / "smoke"
    _compile_against_abi(csrc, exe, "gcc")
    lines = _run_smoke(exe, prefix)
    # the C program's per-row argmax must match the python forward's
    got_classes = [int(l.split("class")[1]) for l in lines[:-1]]
    ref = _reference_forward(prefix)
    np.testing.assert_array_equal(got_classes, ref.argmax(1))
    vals = np.fromstring(lines[-1], dtype=np.float32, sep=" ") \
        if hasattr(np, "fromstring") else None
    if vals is not None and vals.size == ref.size:
        np.testing.assert_allclose(vals.reshape(ref.shape), ref, rtol=1e-4,
                                   atol=1e-5)


CPP_SMOKE = r"""
#include <cstdio>
#include <vector>
#include "mxtpu/mxtpu-cpp.hpp"

int main(int argc, char **argv) {
  if (MXTPURuntimeInit(nullptr) != 0) {
    fprintf(stderr, "init: %s\n", MXTPUGetLastError());
    return 1;
  }
  try {
    float da[6] = {1, 2, 3, 4, 5, 6};
    float db[6] = {10, 20, 30, 40, 50, 60};
    mxtpu::cpp::NDArray a({2, 3}, da), b({2, 3}, db);
    auto c = mxtpu::cpp::Operator("broadcast_add")(a, b);
    auto host = c.CopyToHost();
    for (float v : host) printf("%.1f ", v);
    printf("\n");
    auto s = mxtpu::cpp::Operator("sum").SetAttr("axis", "1")(a);
    for (float v : s.CopyToHost()) printf("%.1f ", v);
    printf("\n");
    // predictor over the exported checkpoint
    mxtpu::cpp::Predictor pred(argv[1], 0, "data", {2, 8});
    std::vector<float> x(16);
    for (int i = 0; i < 16; ++i) x[i] = (i % 5) * 0.25f - 0.5f;
    pred.SetInput(x);
    pred.Forward();
    auto shape = pred.OutputShape();
    auto out = pred.Output();
    for (int64_t r = 0; r < shape[0]; ++r) {
      int best = 0;
      for (int cix = 1; cix < shape[1]; ++cix)
        if (out[r * shape[1] + cix] > out[r * shape[1] + best]) best = cix;
      printf("row%lld:class%d\n", (long long)r, best);
    }
  } catch (const std::exception &e) {
    fprintf(stderr, "exception: %s\n", e.what());
    return 1;
  }
  return 0;
}
"""


def test_cpp_frontend(lib, exported_model, tmp_path):
    """Header-only C++ frontend (include/mxtpu/mxtpu-cpp.hpp, ref
    cpp-package/include/mxnet-cpp): compile + run a real C++ program."""
    prefix, _x, _expect = exported_model
    src = tmp_path / "smoke.cc"
    src.write_text(CPP_SMOKE)
    exe = tmp_path / "smoke_cpp"
    _compile_against_abi(src, exe, "g++", extra=("-std=c++14",))
    lines = _run_smoke(exe, prefix)
    assert lines[0].split() == ["11.0", "22.0", "33.0", "44.0", "55.0",
                                "66.0"]
    assert lines[1].split() == ["6.0", "15.0"]
    # classification rows match the python forward
    ref = _reference_forward(prefix)
    got = [int(l.split("class")[1]) for l in lines[2:]]
    np.testing.assert_array_equal(got, ref.argmax(1))


def test_symbolblock_importable():
    """API-surface check (ref: gluon.SymbolBlock wraps exported symbols)."""
    from mxtpu.gluon import SymbolBlock  # noqa: F401


def test_cpp_training_via_abi(lib, tmp_path):
    """A C++ program TRAINS an MLP to convergence through the ABI (ref:
    cpp-package/example/mlp.cpp): Symbol compose -> Executor bind ->
    forward/backward -> KVStore sgd push/pull. The round-4 widening of the
    C surface from predict-only to training."""
    src = os.path.join(REPO, "examples", "cpp", "train_mlp.cpp")
    exe = tmp_path / "train_mlp"
    _compile_against_abi(src, exe, "g++", extra=("-std=c++14",))
    lines = _run_smoke(exe)
    assert "TRAINED_OK" in lines, lines


def test_autograd_and_kvstore_from_ctypes(lib):
    """In-process tier for the new training surface: record an imperative
    graph, backward, read the gradient, and run one kvstore sgd step."""
    w = _nd_from_blob(lib, np.ones((2, 2), np.float32))
    assert lib.MXTPUNDArrayAttachGrad(w) == 0, lib.MXTPUGetLastError()
    prev = ctypes.c_int()
    assert lib.MXTPUAutogradSetRecording(1, ctypes.byref(prev)) == 0
    out = (ctypes.c_void_p * 4)()
    nout = ctypes.c_int(4)
    assert lib.MXTPUImperativeInvoke(
        b"square", (ctypes.c_void_p * 1)(ctypes.c_void_p(w.value)), 1,
        None, None, 0, out, ctypes.byref(nout)) == 0, \
        lib.MXTPUGetLastError()
    sq = ctypes.c_void_p(out[0])
    nout = ctypes.c_int(4)
    assert lib.MXTPUImperativeInvoke(
        b"sum", (ctypes.c_void_p * 1)(sq), 1, None, None, 0, out,
        ctypes.byref(nout)) == 0, lib.MXTPUGetLastError()
    s = ctypes.c_void_p(out[0])
    assert lib.MXTPUAutogradSetRecording(prev.value, None) == 0
    assert lib.MXTPUNDArrayBackward(s, 0) == 0, lib.MXTPUGetLastError()
    g = ctypes.c_void_p()
    assert lib.MXTPUNDArrayGetGrad(w, ctypes.byref(g)) == 0, \
        lib.MXTPUGetLastError()
    np.testing.assert_allclose(_nd_to_numpy(lib, g),
                               2 * np.ones((2, 2), np.float32))

    kv = ctypes.c_void_p()
    assert lib.MXTPUKVStoreCreate(b"local", ctypes.byref(kv)) == 0
    keys = (ctypes.c_char_p * 1)(b"w0")
    vals = (ctypes.c_void_p * 1)(ctypes.c_void_p(w.value))
    assert lib.MXTPUKVStoreInit(kv, 1, keys, vals) == 0, \
        lib.MXTPUGetLastError()
    ok = (ctypes.c_char_p * 1)(b"learning_rate")
    ov = (ctypes.c_char_p * 1)(b"0.5")
    assert lib.MXTPUKVStoreSetOptimizer(kv, b"sgd", ok, ov, 1) == 0, \
        lib.MXTPUGetLastError()
    gv = (ctypes.c_void_p * 1)(ctypes.c_void_p(g.value))
    assert lib.MXTPUKVStorePush(kv, 1, keys, gv, 0) == 0, \
        lib.MXTPUGetLastError()
    assert lib.MXTPUKVStorePull(kv, 1, keys, vals, 0) == 0, \
        lib.MXTPUGetLastError()
    # w <- w - 0.5 * grad(=2) = 1 - 1 = 0
    np.testing.assert_allclose(_nd_to_numpy(lib, w),
                               np.zeros((2, 2), np.float32), atol=1e-6)
    lib.MXTPUKVStoreFree(kv)
    for h in (w, sq, s, g):
        lib.MXTPUNDArrayFree(h)


def test_ndarray_save_load_dtype_from_c(lib, tmp_path):
    """C-side save writes a REAL reference-format .params the python side
    reads (and vice versa), with dtype-aware creation (ref:
    MXNDArraySave/Load/CreateEx)."""
    # dtype-aware create: int32
    a = np.array([[1, -2], [3, 4]], np.int32)
    shape = (ctypes.c_int64 * 2)(2, 2)
    h = ctypes.c_void_p()
    assert lib.MXTPUNDArrayCreateFromBlobEx(
        a.ctypes.data_as(ctypes.c_void_p), 4, shape, 2,
        ctypes.byref(h)) == 0, lib.MXTPUGetLastError()
    flag = ctypes.c_int()
    assert lib.MXTPUNDArrayGetDType(h, ctypes.byref(flag)) == 0
    assert flag.value == 4

    f = str(tmp_path / "cside.params").encode()
    keys = (ctypes.c_char_p * 1)(b"arg:w")
    handles = (ctypes.c_void_p * 1)(ctypes.c_void_p(h.value))
    assert lib.MXTPUNDArraySave(f, 1, handles, keys) == 0, \
        lib.MXTPUGetLastError()
    # python loads the C-written file; bytes are the 0x112 layout
    import struct as _struct
    raw = open(f, "rb").read(8)
    assert _struct.unpack("<Q", raw)[0] == 0x112
    out = mx.nd.load(f.decode())
    np.testing.assert_array_equal(out["arg:w"].asnumpy(), a)

    # C loads a python-written file
    f2 = str(tmp_path / "pyside.params")
    mx.nd.save(f2, {"x": mx.nd.array(np.arange(3, dtype=np.float32))})
    n = ctypes.c_int()
    hs = ctypes.POINTER(ctypes.c_void_p)()
    nn = ctypes.c_int()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUNDArrayLoad(f2.encode(), ctypes.byref(n),
                                ctypes.byref(hs), ctypes.byref(nn),
                                ctypes.byref(names)) == 0, \
        lib.MXTPUGetLastError()
    assert n.value == 1 and nn.value == 1
    assert names[0] == b"x"
    got = _nd_to_numpy(lib, ctypes.c_void_p(hs[0]))
    np.testing.assert_array_equal(got, np.arange(3, dtype=np.float32))
    lib.MXTPUNDArrayFree(ctypes.c_void_p(hs[0]))
    lib.MXTPUNDArrayFree(h)


def test_version_opnames_waitall(lib):
    """Introspection + sync surface (ref MXGetVersion / MXListAllOpNames /
    MXNDArrayWaitAll)."""
    v = ctypes.c_int()
    assert lib.MXTPUGetVersion(ctypes.byref(v)) == 0
    from mxtpu.libinfo import __version__
    parts = (__version__.split(".") + ["0", "0"])[:3]
    assert v.value == (int(parts[0]) * 10000 + int(parts[1]) * 100
                       + int(parts[2]))
    n = ctypes.c_int()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUListAllOpNames(ctypes.byref(n),
                                   ctypes.byref(names)) == 0
    got = {names[i].decode() for i in range(n.value)}
    assert {"FullyConnected", "Convolution", "dot"} <= got
    assert n.value > 200
    assert lib.MXTPUNDArrayWaitAll() == 0


def test_cpp_recordio_training_via_abi(lib, tmp_path):
    """C++ writes a RecordIO dataset, reads it back, and trains through
    the ABI (VERDICT r4 item 7: the frontend-completeness example)."""
    src = os.path.join(REPO, "examples", "cpp", "train_recordio.cpp")
    exe = tmp_path / "train_recordio"
    _compile_against_abi(src, exe, "g++", extra=("-std=c++14",))
    out = _run_smoke(exe, prefix=str(tmp_path / "data.rec"))
    assert any("TRAIN_RECORDIO_OK" in line for line in out), out


def test_data_iter_abi(lib):
    """MXTPUDataIter*: create an NDArrayIter over host arrays? The C
    surface creates by name with string attrs, so drive CSVIter instead
    (file-based, C-friendly)."""
    import tempfile
    csv = tempfile.NamedTemporaryFile("w", suffix=".csv", delete=False)
    for i in range(8):
        csv.write("%d,%d,%d\n" % (i, i + 1, i + 2))
    csv.close()
    n = ctypes.c_int()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUListDataIters(ctypes.byref(n), ctypes.byref(names)) == 0
    have = {names[i].decode() for i in range(n.value)}
    assert {"CSVIter", "NDArrayIter", "ImageRecordIter"} <= have

    keys = (ctypes.c_char_p * 3)(b"data_csv", b"data_shape", b"batch_size")
    vals = (ctypes.c_char_p * 3)(csv.name.encode(), b"(3,)", b"4")
    h = ctypes.c_void_p()
    rc = lib.MXTPUDataIterCreate(b"CSVIter", 3, keys, vals, ctypes.byref(h))
    assert rc == 0, lib.MXTPUGetLastError()
    batches = []
    more = ctypes.c_int()
    while True:
        assert lib.MXTPUDataIterNext(h, ctypes.byref(more)) == 0
        if not more.value:
            break
        d = ctypes.c_void_p()
        assert lib.MXTPUDataIterGetData(h, ctypes.byref(d)) == 0
        batches.append(_nd_to_numpy(lib, d))
        lib.MXTPUNDArrayFree(d)
        pad = ctypes.c_int()
        assert lib.MXTPUDataIterGetPadNum(h, ctypes.byref(pad)) == 0
        assert pad.value == 0
    assert len(batches) == 2
    np.testing.assert_allclose(batches[0][0], [0.0, 1.0, 2.0])
    # reset replays the epoch
    assert lib.MXTPUDataIterBeforeFirst(h) == 0
    assert lib.MXTPUDataIterNext(h, ctypes.byref(more)) == 0
    assert more.value == 1
    lib.MXTPUDataIterFree(h)
    os.unlink(csv.name)


def test_recordio_abi_roundtrip(lib, tmp_path):
    path = str(tmp_path / "abi.rec").encode()
    w = ctypes.c_void_p()
    assert lib.MXTPURecordIOWriterCreate(path, ctypes.byref(w)) == 0
    payloads = [b"hello", b"", b"x" * 100, b"\x00\x01\x02"]
    for p in payloads:
        assert lib.MXTPURecordIOWriterWriteRecord(w, p, len(p)) == 0
    pos = ctypes.c_size_t()
    assert lib.MXTPURecordIOWriterTell(w, ctypes.byref(pos)) == 0
    assert pos.value > 0
    assert lib.MXTPURecordIOWriterFree(w) == 0

    r = ctypes.c_void_p()
    assert lib.MXTPURecordIOReaderCreate(path, ctypes.byref(r)) == 0
    got = []
    buf = ctypes.c_void_p()
    size = ctypes.c_size_t()
    while True:
        assert lib.MXTPURecordIOReaderReadRecord(
            r, ctypes.byref(buf), ctypes.byref(size)) == 0
        if not buf.value:
            break  # NULL buf = EOF; an empty RECORD has non-NULL buf
        got.append(ctypes.string_at(buf, size.value) if size.value else b"")
    assert got == payloads
    assert lib.MXTPURecordIOReaderFree(r) == 0
    # python reader agrees (wire-format interop)
    from mxtpu import recordio
    rr = recordio.MXRecordIO(path.decode(), "r")
    assert rr.read() == payloads[0]
    rr.close()


def test_symbol_attr_abi(lib):
    h = ctypes.c_void_p()
    assert lib.MXTPUSymbolCreateVariable(b"x", ctypes.byref(h)) == 0
    assert lib.MXTPUSymbolSetAttr(h, b"__lr_mult__", b"2.0") == 0
    out = ctypes.c_char_p()
    assert lib.MXTPUSymbolGetAttr(h, b"__lr_mult__", ctypes.byref(out)) == 0
    assert out.value == b"2.0"
    n = ctypes.c_int()
    kv = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUSymbolListAttr(h, ctypes.byref(n), ctypes.byref(kv)) == 0
    flat = [kv[i].decode() for i in range(n.value)]
    assert "__lr_mult__" in flat and "2.0" in flat
    # missing attr is an error, not a crash
    assert lib.MXTPUSymbolGetAttr(h, b"nope", ctypes.byref(out)) == -1
    lib.MXTPUSymbolFree(h)


def test_symbol_infer_shape_abi(lib):
    data = ctypes.c_void_p()
    w = ctypes.c_void_p()
    assert lib.MXTPUSymbolCreateVariable(b"data", ctypes.byref(data)) == 0
    assert lib.MXTPUSymbolCreateVariable(b"w", ctypes.byref(w)) == 0
    keys = (ctypes.c_char_p * 2)(b"num_hidden", b"no_bias")
    vals = (ctypes.c_char_p * 2)(b"7", b"True")
    inputs = (ctypes.c_void_p * 2)(data, w)
    fc = ctypes.c_void_p()
    assert lib.MXTPUSymbolCompose(b"FullyConnected", b"fc", inputs, 2,
                                  keys, vals, 2, ctypes.byref(fc)) == 0
    names = (ctypes.c_char_p * 1)(b"data")
    shape_data = (ctypes.c_int64 * 2)(5, 3)
    ndims = (ctypes.c_int * 1)(2)
    out_n = ctypes.c_int()
    flat = ctypes.POINTER(ctypes.c_int64)()
    assert lib.MXTPUSymbolInferOutputShape(
        fc, 1, names, shape_data, ndims, ctypes.byref(out_n),
        ctypes.byref(flat)) == 0
    assert out_n.value == 1
    assert flat[0] == 2 and flat[1] == 5 and flat[2] == 7
    # list outputs / aux via the new surfaces
    ln = ctypes.c_int()
    lnames = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUSymbolListOutputs(fc, ctypes.byref(ln),
                                      ctypes.byref(lnames)) == 0
    assert ln.value == 1 and lnames[0] == b"fc_output"
    for hh in (data, w, fc):
        lib.MXTPUSymbolFree(hh)


def test_executor_monitor_callback_abi(lib, tmp_path):
    """MXTPUExecutorSetMonitorCallback fires per node output with a
    borrowed NDArray handle the C side can inspect."""
    import mxtpu as mx
    from mxtpu import symbol as sym

    data = ctypes.c_void_p()
    w = ctypes.c_void_p()
    assert lib.MXTPUSymbolCreateVariable(b"data", ctypes.byref(data)) == 0
    assert lib.MXTPUSymbolCreateVariable(b"w", ctypes.byref(w)) == 0
    inputs = (ctypes.c_void_p * 2)(data, w)
    keys = (ctypes.c_char_p * 2)(b"num_hidden", b"no_bias")
    vals = (ctypes.c_char_p * 2)(b"4", b"True")
    fc = ctypes.c_void_p()
    assert lib.MXTPUSymbolCompose(b"FullyConnected", b"fc", inputs, 2,
                                  keys, vals, 2, ctypes.byref(fc)) == 0
    relu = ctypes.c_void_p()
    rin = (ctypes.c_void_p * 1)(fc)
    rkeys = (ctypes.c_char_p * 1)(b"act_type")
    rvals = (ctypes.c_char_p * 1)(b"relu")
    assert lib.MXTPUSymbolCompose(b"Activation", b"relu1", rin, 1,
                                  rkeys, rvals, 1, ctypes.byref(relu)) == 0

    a_data = _nd_from_blob(lib, np.ones((2, 3), np.float32))
    a_w = _nd_from_blob(lib, np.full((4, 3), 0.5, np.float32))
    arg_names = (ctypes.c_char_p * 2)(b"data", b"w")
    arg_vals = (ctypes.c_void_p * 2)(a_data, a_w)
    ex = ctypes.c_void_p()
    assert lib.MXTPUExecutorBind(relu, 2, arg_names, arg_vals, b"write",
                                 ctypes.byref(ex)) == 0, \
        lib.MXTPUGetLastError()

    seen = []
    CB = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                          ctypes.c_void_p)

    @CB
    def monitor(name, nd_handle, _ctx):
        shape = (ctypes.c_int64 * 8)()
        ndim = ctypes.c_int()
        lib.MXTPUNDArrayShape(nd_handle, ctypes.byref(ndim), shape)
        seen.append((name.decode(), tuple(shape[:ndim.value])))

    assert lib.MXTPUExecutorSetMonitorCallback(ex, monitor, None) == 0
    assert lib.MXTPUExecutorForward(ex, 0) == 0, lib.MXTPUGetLastError()
    names_seen = [n for n, _s in seen]
    assert "fc_output" in names_seen and "relu1_output" in names_seen
    assert dict(seen)["fc_output"] == (2, 4)
    for hh in (data, w, fc, relu):
        lib.MXTPUSymbolFree(hh)
    lib.MXTPUExecutorFree(ex)
    lib.MXTPUNDArrayFree(a_data)
    lib.MXTPUNDArrayFree(a_w)


def test_misc_breadth_abi(lib):
    assert lib.MXTPURandomSeed(42) == 0
    a = _nd_from_blob(lib, np.arange(12, dtype=np.float32).reshape(4, 3))
    s = ctypes.c_void_p()
    assert lib.MXTPUNDArraySlice(a, 1, 3, ctypes.byref(s)) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, s),
                               np.arange(12, dtype=np.float32)
                               .reshape(4, 3)[1:3])
    r = ctypes.c_void_p()
    shape = (ctypes.c_int64 * 2)(3, 4)
    assert lib.MXTPUNDArrayReshape(a, shape, 2, ctypes.byref(r)) == 0
    assert _nd_to_numpy(lib, r).shape == (3, 4)
    # sync copy from cpu overwrites in place
    new = np.full(12, 7.0, np.float32)
    assert lib.MXTPUNDArraySyncCopyFromCPU(
        a, new.ctypes.data_as(ctypes.c_void_p), new.nbytes) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, a), 7.0)
    ctx = ctypes.c_char_p()
    assert lib.MXTPUNDArrayGetContext(a, ctypes.byref(ctx)) == 0
    assert ctx.value
    for hh in (a, s, r):
        lib.MXTPUNDArrayFree(hh)


def test_kvstore_breadth_abi(lib):
    kv = ctypes.c_void_p()
    assert lib.MXTPUKVStoreCreate(b"local", ctypes.byref(kv)) == 0
    rank = ctypes.c_int()
    size = ctypes.c_int()
    assert lib.MXTPUKVStoreGetRank(kv, ctypes.byref(rank)) == 0
    assert lib.MXTPUKVStoreGetGroupSize(kv, ctypes.byref(size)) == 0
    assert rank.value == 0 and size.value == 1
    assert lib.MXTPUKVStoreBarrier(kv) == 0
    # pushpull round trip
    a = _nd_from_blob(lib, np.ones(3, np.float32))
    out = _nd_from_blob(lib, np.zeros(3, np.float32))
    keys = (ctypes.c_char_p * 1)(b"k")
    vals = (ctypes.c_void_p * 1)(a)
    outs = (ctypes.c_void_p * 1)(out)
    assert lib.MXTPUKVStoreInit(kv, 1, keys, vals) == 0
    two = _nd_from_blob(lib, np.full(3, 2.0, np.float32))
    vals2 = (ctypes.c_void_p * 1)(two)
    assert lib.MXTPUKVStorePushPull(kv, 1, keys, vals2, outs, 0) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, out), 2.0)
    for hh in (a, out, two):
        lib.MXTPUNDArrayFree(hh)
    lib.MXTPUKVStoreFree(kv)


def test_abi_function_count_target():
    """VERDICT r4 item 7: ABI >= 70 functions."""
    import re
    hdr = open(os.path.join(REPO, "include", "mxtpu", "c_api.h")).read()
    fns = set(re.findall(r"int (MXTPU\w+)\(", hdr))
    fns |= set(re.findall(r"const char \*(MXTPU\w+)\(", hdr))
    assert len(fns) >= 70, len(fns)


# ---- round-5 ABI breadth: autograd / CachedOp / NDArray / Symbol /
# Executor / KVStore II / profiler / misc (ref: include/mxnet/c_api.h
# MXAutogradIsRecording, MXCreateCachedOpEx, MXNDArrayAt/Detach/...,
# MXSymbolCreateAtomicSymbol/GetInternals/..., MXExecutorSimpleBind,
# MXKVStoreSetUpdater, MXSetProfilerConfig, MXGetGPUCount) ----


def test_autograd_breadth_abi(lib):
    x = _nd_from_blob(lib, np.ones((2, 2), np.float32))
    reqs = (ctypes.c_int * 1)(1)  # write
    assert lib.MXTPUAutogradMarkVariables(1, ctypes.byref(x), reqs) == 0
    rec = ctypes.c_int()
    assert lib.MXTPUAutogradIsRecording(ctypes.byref(rec)) == 0
    assert rec.value == 0
    prev = ctypes.c_int()
    assert lib.MXTPUAutogradSetRecording(1, ctypes.byref(prev)) == 0
    outs = (ctypes.c_void_p * 1)()
    nout = ctypes.c_int(1)
    assert lib.MXTPUImperativeInvoke(b"square", ctypes.byref(x), 1, None,
                                     None, 0, outs, ctypes.byref(nout)) == 0
    assert lib.MXTPUAutogradIsRecording(ctypes.byref(rec)) == 0
    assert rec.value == 1
    tr = ctypes.c_int()
    assert lib.MXTPUAutogradIsTraining(ctypes.byref(tr)) == 0
    # backward over the recorded head with a NULL ograd (ones seed)
    assert lib.MXTPUAutogradBackward(1, outs, None, 0) == 0
    assert lib.MXTPUAutogradSetRecording(0, ctypes.byref(prev)) == 0
    g = ctypes.c_void_p()
    assert lib.MXTPUNDArrayGetGrad(x, ctypes.byref(g)) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, g), 2.0)
    for h in (x, ctypes.c_void_p(outs[0]), g):
        lib.MXTPUNDArrayFree(h)


def test_cached_op_abi(lib):
    a = ctypes.c_void_p()
    b = ctypes.c_void_p()
    assert lib.MXTPUSymbolCreateVariable(b"a", ctypes.byref(a)) == 0
    assert lib.MXTPUSymbolCreateVariable(b"b", ctypes.byref(b)) == 0
    comp = ctypes.c_void_p()
    assert lib.MXTPUSymbolCompose(b"elemwise_add", b"add0",
                                  (ctypes.c_void_p * 2)(a, b), 2, None,
                                  None, 0, ctypes.byref(comp)) == 0
    co = ctypes.c_void_p()
    assert lib.MXTPUCreateCachedOp(comp, 0, None, None,
                                   ctypes.byref(co)) == 0
    x = _nd_from_blob(lib, np.ones(3, np.float32))
    y = _nd_from_blob(lib, np.full(3, 2.0, np.float32))
    nout = ctypes.c_int(4)
    outs = (ctypes.c_void_p * 4)()
    assert lib.MXTPUInvokeCachedOp(co, 2, (ctypes.c_void_p * 2)(x, y),
                                   ctypes.byref(nout), outs) == 0
    assert nout.value == 1
    np.testing.assert_allclose(
        _nd_to_numpy(lib, ctypes.c_void_p(outs[0])), 3.0)
    # second invoke with the same signature reuses the cached executor
    assert lib.MXTPUInvokeCachedOp(co, 2, (ctypes.c_void_p * 2)(x, y),
                                   ctypes.byref(nout), outs) == 0
    assert lib.MXTPUFreeCachedOp(co) == 0


def test_ndarray_breadth_abi(lib):
    h = _nd_from_blob(lib, np.arange(6, dtype=np.float32).reshape(2, 3))
    st = ctypes.c_int()
    assert lib.MXTPUNDArrayGetStorageType(h, ctypes.byref(st)) == 0
    assert st.value == 0  # kDefaultStorage (ref ndarray.h:61)
    at = ctypes.c_void_p()
    assert lib.MXTPUNDArrayAt(h, 1, ctypes.byref(at)) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, at), [3, 4, 5])
    det = ctypes.c_void_p()
    assert lib.MXTPUNDArrayDetach(h, ctypes.byref(det)) == 0
    assert lib.MXTPUNDArrayWaitToRead(h) == 0
    assert lib.MXTPUNDArrayWaitToWrite(h) == 0
    assert lib.MXTPUNDArraySyncCheckFormat(h, 1) == 0
    none = ctypes.c_void_p()
    assert lib.MXTPUNDArrayCreateNone(ctypes.byref(none)) == 0
    # raw-bytes single-record roundtrip (ref MXNDArraySaveRawBytes)
    size = ctypes.c_size_t()
    buf = ctypes.c_char_p()
    assert lib.MXTPUNDArraySaveRawBytes(h, ctypes.byref(size),
                                        ctypes.byref(buf)) == 0
    raw = ctypes.string_at(buf, size.value)
    h2 = ctypes.c_void_p()
    assert lib.MXTPUNDArrayLoadFromRawBytes(raw, len(raw),
                                            ctypes.byref(h2)) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, h2),
                               np.arange(6).reshape(2, 3))
    # device-to-device copy
    z = _nd_from_blob(lib, np.zeros((2, 3), np.float32))
    assert lib.MXTPUNDArraySyncCopyFromNDArray(z, h) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, z),
                               np.arange(6).reshape(2, 3))
    # shape mismatch surfaces as an error, not silence
    bad = _nd_from_blob(lib, np.zeros(5, np.float32))
    assert lib.MXTPUNDArraySyncCopyFromNDArray(bad, h) == -1
    for hh in (h, at, det, none, h2, z, bad):
        lib.MXTPUNDArrayFree(hh)


def test_ndarray_load_from_buffer_abi(lib, tmp_path):
    import mxtpu.ndarray.utils as ndu
    path = str(tmp_path / "buf.params")
    ndu.save(path, {"w": mx.nd.ones((2, 2))}, format="mxnet")
    blob = open(path, "rb").read()
    num = ctypes.c_int()
    handles = ctypes.POINTER(ctypes.c_void_p)()
    nn = ctypes.c_int()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUNDArrayLoadFromBuffer(
        blob, len(blob), ctypes.byref(num), ctypes.byref(handles),
        ctypes.byref(nn), ctypes.byref(names)) == 0
    assert num.value == 1 and names[0] == b"w"
    np.testing.assert_allclose(
        _nd_to_numpy(lib, ctypes.c_void_p(handles[0])), 1.0)


def test_sparse_abi(lib):
    data = _nd_from_blob(lib, np.ones((2, 3), np.float32))
    idx = _nd_from_blob(lib, np.array([0.0, 2.0], np.float32))
    shape = (ctypes.c_int64 * 2)(4, 3)
    rs = ctypes.c_void_p()
    assert lib.MXTPUNDArrayCreateSparseEx(1, data, 1, ctypes.byref(idx),
                                          shape, 2, ctypes.byref(rs)) == 0
    st = ctypes.c_int()
    assert lib.MXTPUNDArrayGetStorageType(rs, ctypes.byref(st)) == 0
    assert st.value == 1  # kRowSparseStorage
    dnd = ctypes.c_void_p()
    assert lib.MXTPUNDArrayGetDataNDArray(rs, ctypes.byref(dnd)) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, dnd), 1.0)
    aux = ctypes.c_void_p()
    assert lib.MXTPUNDArrayGetAuxNDArray(rs, 0, ctypes.byref(aux)) == 0
    af = ctypes.c_int()
    assert lib.MXTPUNDArrayGetAuxType(rs, 0, ctypes.byref(af)) == 0
    assert af.value in (4, 6)  # int32/int64
    # dense arrays refuse the sparse-only accessors
    assert lib.MXTPUNDArrayGetDataNDArray(data, ctypes.byref(dnd)) == -1


def test_symbol_breadth2_abi(lib):
    s = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 1)(b"num_hidden")
    vals = (ctypes.c_char_p * 1)(b"4")
    assert lib.MXTPUSymbolCreateAtomicSymbol(b"FullyConnected", 1, keys,
                                             vals, ctypes.byref(s)) == 0
    n = ctypes.c_int()
    assert lib.MXTPUSymbolGetNumOutputs(s, ctypes.byref(n)) == 0
    assert n.value == 1
    a = ctypes.c_void_p()
    b = ctypes.c_void_p()
    lib.MXTPUSymbolCreateVariable(b"a", ctypes.byref(a))
    lib.MXTPUSymbolCreateVariable(b"b", ctypes.byref(b))
    grp = ctypes.c_void_p()
    assert lib.MXTPUSymbolCreateGroup(2, (ctypes.c_void_p * 2)(a, b),
                                      ctypes.byref(grp)) == 0
    assert lib.MXTPUSymbolGetNumOutputs(grp, ctypes.byref(n)) == 0
    assert n.value == 2
    comp = ctypes.c_void_p()
    assert lib.MXTPUSymbolCompose(b"elemwise_add", b"add0",
                                  (ctypes.c_void_p * 2)(a, b), 2, None,
                                  None, 0, ctypes.byref(comp)) == 0
    name = ctypes.c_char_p()
    ok = ctypes.c_int()
    assert lib.MXTPUSymbolGetName(comp, ctypes.byref(name),
                                  ctypes.byref(ok)) == 0
    assert ok.value == 1 and name.value == b"add0"
    # a group has no single name
    assert lib.MXTPUSymbolGetName(grp, ctypes.byref(name),
                                  ctypes.byref(ok)) == 0
    assert ok.value == 0
    kids = ctypes.c_void_p()
    assert lib.MXTPUSymbolGetChildren(comp, ctypes.byref(kids)) == 0
    nk = ctypes.c_int()
    assert lib.MXTPUSymbolGetNumOutputs(kids, ctypes.byref(nk)) == 0
    assert nk.value == 2
    out0 = ctypes.c_void_p()
    assert lib.MXTPUSymbolGetOutput(comp, 0, ctypes.byref(out0)) == 0
    internals = ctypes.c_void_p()
    assert lib.MXTPUSymbolGetInternals(comp, ctypes.byref(internals)) == 0
    pr = ctypes.c_char_p()
    assert lib.MXTPUSymbolPrint(comp, ctypes.byref(pr)) == 0
    assert b"Symbol" in pr.value
    js = ctypes.c_char_p()
    assert lib.MXTPUSymbolSaveToJSON(comp, ctypes.byref(js)) == 0
    assert js.value.startswith(b"{")
    ncr = ctypes.c_int()
    creators = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXTPUSymbolListAtomicSymbolCreators(
        ctypes.byref(ncr), ctypes.byref(creators)) == 0
    assert ncr.value > 200  # the full op registry


def test_symbol_infer_type_abi(lib):
    a = ctypes.c_void_p()
    b = ctypes.c_void_p()
    lib.MXTPUSymbolCreateVariable(b"a", ctypes.byref(a))
    lib.MXTPUSymbolCreateVariable(b"b", ctypes.byref(b))
    comp = ctypes.c_void_p()
    assert lib.MXTPUSymbolCompose(b"elemwise_add", b"add0",
                                  (ctypes.c_void_p * 2)(a, b), 2, None,
                                  None, 0, ctypes.byref(comp)) == 0
    flags = (ctypes.c_int * 2)(0, 0)
    an = ctypes.c_int(); af = ctypes.POINTER(ctypes.c_int)()
    on = ctypes.c_int(); of = ctypes.POINTER(ctypes.c_int)()
    xn = ctypes.c_int(); xf = ctypes.POINTER(ctypes.c_int)()
    assert lib.MXTPUSymbolInferType(
        comp, 2, (ctypes.c_char_p * 2)(b"a", b"b"), flags,
        ctypes.byref(an), ctypes.byref(af), ctypes.byref(on),
        ctypes.byref(of), ctypes.byref(xn), ctypes.byref(xf)) == 0
    assert an.value == 2 and af[0] == 0 and af[1] == 0
    # partial shape inference with only one input known
    sd = (ctypes.c_int64 * 1)(2)
    sn = (ctypes.c_int * 1)(1)
    num = ctypes.c_int()
    flat = ctypes.POINTER(ctypes.c_int64)()
    assert lib.MXTPUSymbolInferShapePartial(
        comp, 1, (ctypes.c_char_p * 1)(b"a"), sd, sn,
        ctypes.byref(num), ctypes.byref(flat)) == 0


def test_executor_breadth_abi(lib):
    a = ctypes.c_void_p()
    b = ctypes.c_void_p()
    lib.MXTPUSymbolCreateVariable(b"a", ctypes.byref(a))
    lib.MXTPUSymbolCreateVariable(b"b", ctypes.byref(b))
    comp = ctypes.c_void_p()
    assert lib.MXTPUSymbolCompose(b"elemwise_add", b"add0",
                                  (ctypes.c_void_p * 2)(a, b), 2, None,
                                  None, 0, ctypes.byref(comp)) == 0
    names = (ctypes.c_char_p * 2)(b"a", b"b")
    shape_data = (ctypes.c_int64 * 2)(2, 2)
    shape_ndim = (ctypes.c_int * 2)(1, 1)
    ex = ctypes.c_void_p()
    assert lib.MXTPUExecutorSimpleBind(comp, 2, names, shape_data,
                                       shape_ndim, b"write",
                                       ctypes.byref(ex)) == 0
    assert lib.MXTPUExecutorForward(ex, 0) == 0
    cnt = ctypes.c_int(4)
    outs = (ctypes.c_void_p * 4)()
    assert lib.MXTPUExecutorOutputs(ex, ctypes.byref(cnt), outs) == 0
    assert cnt.value == 1
    pr = ctypes.c_char_p()
    assert lib.MXTPUExecutorPrint(ex, ctypes.byref(pr)) == 0
    assert b"Executor" in pr.value
    # reshape returns a NEW executor at the new shapes
    shape3 = (ctypes.c_int64 * 2)(3, 3)
    ex2 = ctypes.c_void_p()
    assert lib.MXTPUExecutorReshape(ex, 2, names, shape3, shape_ndim,
                                    ctypes.byref(ex2)) == 0
    assert lib.MXTPUExecutorForward(ex2, 0) == 0
    lib.MXTPUExecutorFree(ex)
    lib.MXTPUExecutorFree(ex2)


def test_kvstore_breadth2_abi(lib):
    kv = ctypes.c_void_p()
    assert lib.MXTPUKVStoreCreate(b"local", ctypes.byref(kv)) == 0
    t = ctypes.c_char_p()
    assert lib.MXTPUKVStoreGetType(kv, ctypes.byref(t)) == 0
    assert t.value == b"local"
    # C updater callback fires on push-merge with the int key
    seen = []
    UPD = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                           ctypes.c_void_p, ctypes.c_void_p)

    @UPD
    def updater(key, recv, local, ctx):
        seen.append(key)

    assert lib.MXTPUKVStoreSetUpdater(kv, updater, None) == 0
    w = _nd_from_blob(lib, np.zeros(4, np.float32))
    g = _nd_from_blob(lib, np.ones(4, np.float32))
    keys = (ctypes.c_char_p * 1)(b"3")
    assert lib.MXTPUKVStoreInit(kv, 1, keys, ctypes.byref(w)) == 0
    assert lib.MXTPUKVStorePush(kv, 1, keys, ctypes.byref(g), 0) == 0
    assert seen == [3]
    role = ctypes.c_int()
    assert lib.MXTPUKVStoreIsWorkerNode(ctypes.byref(role)) == 0
    assert role.value == 1
    assert lib.MXTPUKVStoreIsServerNode(ctypes.byref(role)) == 0
    assert role.value == 0
    assert lib.MXTPUKVStoreIsSchedulerNode(ctypes.byref(role)) == 0
    assert role.value == 0
    dead = ctypes.c_int()
    assert lib.MXTPUKVStoreGetNumDeadNode(kv, 0, ctypes.byref(dead)) == 0
    assert dead.value == 0
    gk = (ctypes.c_char_p * 1)(b"type")
    gv = (ctypes.c_char_p * 1)(b"2bit")
    assert lib.MXTPUKVStoreSetGradientCompression(kv, 1, gk, gv) == 0
    lib.MXTPUKVStoreFree(kv)


def test_profiler_and_misc_abi(lib, tmp_path):
    pk = (ctypes.c_char_p * 1)(b"filename")
    pv = (ctypes.c_char_p * 1)(str(tmp_path / "prof.json").encode())
    assert lib.MXTPUSetProfilerConfig(1, pk, pv) == 0
    assert lib.MXTPUSetProfilerState(1) == 0
    assert lib.MXTPUProfilePause(1) == 0
    assert lib.MXTPUProfilePause(0) == 0
    assert lib.MXTPUSetProfilerState(0) == 0
    assert lib.MXTPUDumpProfile(1) == 0
    cnt = ctypes.c_int()
    assert lib.MXTPUGetDeviceCount(ctypes.byref(cnt)) == 0
    assert cnt.value >= 1
    # CPU backend exposes no HBM stats: the call must FAIL, not guess
    free = ctypes.c_uint64()
    total = ctypes.c_uint64()
    rc = lib.MXTPUGetMemoryInformation(0, ctypes.byref(free),
                                       ctypes.byref(total))
    assert rc in (0, -1)
    assert lib.MXTPUNotifyShutdown() == 0
    prev = ctypes.c_int()
    assert lib.MXTPUEngineSetBulkSize(8, ctypes.byref(prev)) == 0
    # the embedded impl shares THIS interpreter: restore the bulk size or
    # later engine tests see the mutated global
    restored = ctypes.c_int()
    assert lib.MXTPUEngineSetBulkSize(prev.value, ctypes.byref(restored)) == 0
    assert restored.value == 8
    assert lib.MXTPUSetNumOMPThreads(4) == 0
    assert lib.MXTPURandomSeedContext(42, 1, 0) == 0
    nm = ctypes.c_char_p()
    ds = ctypes.c_char_p()
    assert lib.MXTPUDataIterGetIterInfo(b"NDArrayIter", ctypes.byref(nm),
                                        ctypes.byref(ds)) == 0
    assert nm.value == b"NDArrayIter"


def test_data_iter_get_index_abi(lib):
    attrs_k = (ctypes.c_char_p * 2)(b"data", b"batch_size")
    attrs_v = (ctypes.c_char_p * 2)(
        repr(np.arange(12, dtype=np.float32).reshape(6, 2).tolist()).encode(),
        b"2")
    it = ctypes.c_void_p()
    assert lib.MXTPUDataIterCreate(b"NDArrayIter", 2, attrs_k, attrs_v,
                                   ctypes.byref(it)) == 0
    has = ctypes.c_int()
    assert lib.MXTPUDataIterNext(it, ctypes.byref(has)) == 0 and has.value
    idx = ctypes.POINTER(ctypes.c_uint64)()
    sz = ctypes.c_uint64()
    assert lib.MXTPUDataIterGetIndex(it, ctypes.byref(idx),
                                     ctypes.byref(sz)) == 0
    # NDArrayIter tracks per-batch sample indices
    assert sz.value in (0, 2)
    lib.MXTPUDataIterFree(it)


def test_abi_function_count_140(lib):
    """Round-5 C-ABI breadth: >=135 of the reference's 194 functions
    (VERDICT r4 missing #5; the remainder is CUDA-specific Rtc/TensorRT
    and the deprecated MXFunc legacy-function family)."""
    import re
    hdr = open(os.path.join(REPO, "include", "mxtpu", "c_api.h")).read()
    fns = set(re.findall(r"int (MXTPU\w+)\(", hdr))
    fns |= set(re.findall(r"const char \*(MXTPU\w+)\(", hdr))
    assert len(fns) >= 135, len(fns)


# ---- review-fix regressions: CachedOp aux/recording, str-key updater,
# partial-inference output contract ----


def test_cached_op_aux_states_abi(lib):
    """CachedOp over a BatchNorm symbol: aux states (moving mean/var) must
    bind as aux, not args (review finding r5)."""
    import mxtpu.c_api_impl as impl
    import mxtpu.symbol as sym
    x = sym.var("x")
    bn = sym.BatchNorm(x, name="bn")
    co = impl.cached_op_create(bn, (), ())
    names = bn.list_inputs()
    feed = {"x": mx.nd.array(np.random.randn(4, 3).astype(np.float32)),
            "bn_gamma": mx.nd.ones((3,)), "bn_beta": mx.nd.zeros((3,)),
            "bn_moving_mean": mx.nd.zeros((3,)),
            "bn_moving_var": mx.nd.ones((3,))}
    outs = impl.cached_op_invoke(co, tuple(feed[n] for n in names))
    assert outs[0].shape == (4, 3)
    # cache-hit path refreshes aux values in place
    impl.cached_op_invoke(co, tuple(feed[n] for n in names))


def test_cached_op_records_on_tape(lib):
    """CachedOp invoked under autograd.record() must land on the tape so
    backward works (ref MXInvokeCachedOpEx records when recording)."""
    import mxtpu.c_api_impl as impl
    import mxtpu.symbol as sym
    from mxtpu import autograd
    a = sym.var("a")
    b = sym.var("b")
    co = impl.cached_op_create(a * b, (), ())
    xa = mx.nd.ones((3,))
    xb = mx.nd.array(np.full(3, 2.0, np.float32))
    xa.attach_grad()
    with autograd.record():
        (out,) = impl.cached_op_invoke(co, (xa, xb))
        out.backward()
    np.testing.assert_allclose(xa.grad.asnumpy(), 2.0)


def test_kvstore_str_updater_abi(lib):
    """Named keys need the string-key updater; the int-key updater must
    fail LOUDLY on them, not crash or silently drop (review finding r5)."""
    kv = ctypes.c_void_p()
    assert lib.MXTPUKVStoreCreate(b"local", ctypes.byref(kv)) == 0
    seen = []
    SUPD = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                            ctypes.c_void_p, ctypes.c_void_p)

    @SUPD
    def supd(key, recv, local, ctx):
        seen.append(key)

    assert lib.MXTPUKVStoreSetUpdaterEx(kv, supd, None) == 0
    w = _nd_from_blob(lib, np.zeros(4, np.float32))
    g = _nd_from_blob(lib, np.ones(4, np.float32))
    keys = (ctypes.c_char_p * 1)(b"fc1_weight")
    assert lib.MXTPUKVStoreInit(kv, 1, keys, ctypes.byref(w)) == 0
    assert lib.MXTPUKVStorePush(kv, 1, keys, ctypes.byref(g), 0) == 0
    assert seen == [b"fc1_weight"]
    # int-key updater + named key -> loud error pointing at SetUpdaterEx
    kv2 = ctypes.c_void_p()
    assert lib.MXTPUKVStoreCreate(b"local", ctypes.byref(kv2)) == 0
    UPD = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                           ctypes.c_void_p, ctypes.c_void_p)

    @UPD
    def iupd(key, recv, local, ctx):
        pass

    assert lib.MXTPUKVStoreSetUpdater(kv2, iupd, None) == 0
    assert lib.MXTPUKVStoreInit(kv2, 1, keys, ctypes.byref(w)) == 0
    assert lib.MXTPUKVStorePush(kv2, 1, keys, ctypes.byref(g), 0) == -1
    lib.MXTPUGetLastError.restype = ctypes.c_char_p
    assert b"SetUpdaterEx" in lib.MXTPUGetLastError()


def test_infer_shape_partial_output_contract(lib):
    """On unresolvable hints the fallback still reports one entry per
    symbol output (ndim 0), never an empty list (review finding r5)."""
    import mxtpu.c_api_impl as impl
    import mxtpu.symbol as sym
    a = sym.var("a")
    b = sym.var("b")
    c = a + b
    args, outs, auxs = impl.symbol_infer_shape_partial(
        c, ("a", "b"), ((2,), (3,)))  # conflicting shapes
    assert len(outs) == len(c.list_outputs())
    assert outs[0] == ()


def test_cached_op_train_mode_and_bn_aux(lib):
    """Train-mode CachedOp updates the caller's BN moving stats on BOTH
    paths (recording: eager tape; not recording: cached executor), and
    honors train_mode for the executor path (review r5)."""
    import mxtpu.c_api_impl as impl
    import mxtpu.symbol as msym
    from mxtpu import autograd
    x = msym.var("x")
    bn = msym.BatchNorm(x, name="bn")
    co = impl.cached_op_create(bn, (), ())
    names = bn.list_inputs()

    def fresh_feed():
        return {"x": mx.nd.array(
                    np.random.RandomState(0).randn(64, 3).astype(np.float32)
                    * 5 + 2),
                "bn_gamma": mx.nd.ones((3,)),
                "bn_beta": mx.nd.zeros((3,)),
                "bn_moving_mean": mx.nd.zeros((3,)),
                "bn_moving_var": mx.nd.ones((3,))}

    feed = fresh_feed()
    with autograd.record(train_mode=True):
        impl.cached_op_invoke(co, tuple(feed[n] for n in names))
    assert np.abs(feed["bn_moving_mean"].asnumpy()).sum() > 0

    feed2 = fresh_feed()
    prev = autograd.set_training(True)
    try:
        impl.cached_op_invoke(co, tuple(feed2[n] for n in names))
    finally:
        autograd.set_training(prev)
    assert np.abs(feed2["bn_moving_mean"].asnumpy()).sum() > 0


def test_autograd_backward_null_entry_ograds(lib):
    """Per-entry NULL ograds = ones-like seed for that head (ref
    MXAutogradBackwardEx); must not crash the process (review r5)."""
    x = _nd_from_blob(lib, np.ones((3,), np.float32))
    reqs = (ctypes.c_int * 1)(1)
    assert lib.MXTPUAutogradMarkVariables(1, ctypes.byref(x), reqs) == 0
    prev = ctypes.c_int()
    assert lib.MXTPUAutogradSetRecording(1, ctypes.byref(prev)) == 0
    outs1 = (ctypes.c_void_p * 1)()
    n1 = ctypes.c_int(1)
    assert lib.MXTPUImperativeInvoke(b"square", ctypes.byref(x), 1, None,
                                     None, 0, outs1, ctypes.byref(n1)) == 0
    outs2 = (ctypes.c_void_p * 1)()
    n2 = ctypes.c_int(1)
    assert lib.MXTPUImperativeInvoke(b"square", ctypes.byref(x), 1, None,
                                     None, 0, outs2, ctypes.byref(n2)) == 0
    assert lib.MXTPUAutogradSetRecording(0, ctypes.byref(prev)) == 0
    two = _nd_from_blob(lib, np.full(3, 2.0, np.float32))
    heads = (ctypes.c_void_p * 2)(outs1[0], outs2[0])
    ograds = (ctypes.c_void_p * 2)(None, two)  # first entry NULL
    assert lib.MXTPUAutogradBackward(2, heads, ograds, 0) == 0
    g = ctypes.c_void_p()
    assert lib.MXTPUNDArrayGetGrad(x, ctypes.byref(g)) == 0
    # d/dx (x^2 * 1) + d/dx (x^2 * 2) at x=1 -> 2 + 4
    np.testing.assert_allclose(_nd_to_numpy(lib, g), 6.0)


def test_symbol_get_children_keeps_output_index(lib):
    import mxtpu.c_api_impl as impl
    import mxtpu.symbol as msym
    s = msym.var("s")
    parts = msym.SliceChannel(s, num_outputs=2, name="split")
    h = parts[1] * 2
    kids = impl.symbol_get_children(h)
    assert "split_output1" in kids.list_outputs()


def test_cached_op_bn_scrambled_keyword_compose(lib):
    """Keyword BN compose in arbitrary order: stat updates must land on
    moving_mean/var by NAME, never on gamma/beta (review r5 — value and
    destination derived from the same kw slot)."""
    import mxtpu.c_api_impl as impl
    import mxtpu.symbol as msym
    from mxtpu import autograd
    x = msym.var("x")
    g = msym.var("g")
    b = msym.var("b")
    mm = msym.var("mm")
    mv = msym.var("mv")
    bn = msym.BatchNorm(x, moving_var=mv, moving_mean=mm, gamma=g, beta=b,
                        name="bn")
    co = impl.cached_op_create(bn, (), ())
    names = bn.list_inputs()
    feed = {"x": mx.nd.array(
                np.random.RandomState(0).randn(64, 3).astype(np.float32)
                * 5 + 2),
            "g": mx.nd.ones((3,)), "b": mx.nd.zeros((3,)),
            "mm": mx.nd.zeros((3,)), "mv": mx.nd.ones((3,))}
    with autograd.record(train_mode=True):
        impl.cached_op_invoke(co, tuple(feed[n] for n in names))
    np.testing.assert_allclose(feed["g"].asnumpy(), 1.0)
    np.testing.assert_allclose(feed["b"].asnumpy(), 0.0)
    assert np.abs(feed["mm"].asnumpy()).sum() > 0


def test_cached_op_bn_mixed_positional_keyword_compose(lib):
    """4 positional + 1 keyword BN compose must update stats, not raise
    IndexError from the positional fallback (review r5)."""
    import mxtpu.c_api_impl as impl
    import mxtpu.symbol as msym
    from mxtpu import autograd
    x = msym.var("x")
    g = msym.var("g")
    b = msym.var("b")
    mm = msym.var("mm")
    mv = msym.var("mv")
    bn = msym.BatchNorm(x, g, b, mm, moving_var=mv, name="bn")
    co = impl.cached_op_create(bn, (), ())
    names = bn.list_inputs()
    feed = {"x": mx.nd.array(
                np.random.RandomState(0).randn(64, 3).astype(np.float32)
                * 5 + 2),
            "g": mx.nd.ones((3,)), "b": mx.nd.zeros((3,)),
            "mm": mx.nd.zeros((3,)), "mv": mx.nd.ones((3,))}
    with autograd.record(train_mode=True):
        impl.cached_op_invoke(co, tuple(feed[n] for n in names))
    np.testing.assert_allclose(feed["g"].asnumpy(), 1.0)
    assert np.abs(feed["mm"].asnumpy()).sum() > 0


def test_dlpack_abi(lib):
    """C-level DLPack: export a DLManagedTensor*, re-import it, release
    an unconsumed one via the deleter (ref MXNDArrayToDLPack family)."""
    x = _nd_from_blob(lib, np.arange(6, dtype=np.float32).reshape(2, 3))
    dlm = ctypes.c_void_p()
    assert lib.MXTPUNDArrayToDLPack(x, ctypes.byref(dlm)) == 0
    assert dlm.value
    h2 = ctypes.c_void_p()
    assert lib.MXTPUNDArrayFromDLPack(dlm, ctypes.byref(h2)) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, h2),
                               np.arange(6).reshape(2, 3))
    dlm2 = ctypes.c_void_p()
    assert lib.MXTPUNDArrayToDLPack(x, ctypes.byref(dlm2)) == 0
    assert lib.MXTPUNDArrayCallDLPackDeleter(dlm2) == 0


def test_shared_mem_abi(lib):
    """Name-addressed shared-memory transfer (ref
    MXNDArrayCreateFromSharedMem with POSIX-name semantics)."""
    x = _nd_from_blob(lib, np.arange(6, dtype=np.float32).reshape(2, 3))
    nm = ctypes.c_char_p()
    assert lib.MXTPUNDArrayGetSharedMemHandle(x, ctypes.byref(nm)) == 0
    shp = (ctypes.c_int64 * 2)(2, 3)
    h = ctypes.c_void_p()
    assert lib.MXTPUNDArrayCreateFromSharedMem(nm.value, 0, shp, 2,
                                               ctypes.byref(h)) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, h),
                               np.arange(6).reshape(2, 3))


def test_cpp_interop_via_abi(lib, tmp_path):
    """C++ drives CachedOp (hybridize), DLPack exchange, and shared-memory
    transfer through the header-only frontend (round-5 interop trio)."""
    src = os.path.join(REPO, "examples", "cpp", "interop.cpp")
    exe = tmp_path / "interop"
    _compile_against_abi(src, exe, "g++", extra=("-std=c++14",))
    out = _run_smoke(exe)
    for marker in ("CACHEDOP OK", "DLPACK OK", "SHAREDMEM OK"):
        assert any(marker in line for line in out), (marker, out)


def test_profile_object_family_abi(lib, tmp_path):
    """Scoped profiler objects from C (ref MXProfileCreate* family):
    task/frame/event durations, counters, markers, and the aggregate
    stats table."""
    import time
    pk = (ctypes.c_char_p * 1)(b"filename")
    pv = (ctypes.c_char_p * 1)(str(tmp_path / "pobj.json").encode())
    assert lib.MXTPUSetProfilerConfig(1, pk, pv) == 0
    assert lib.MXTPUSetProfilerState(1) == 0
    try:
        dom = ctypes.c_void_p()
        assert lib.MXTPUProfileCreateDomain(b"dom", ctypes.byref(dom)) == 0
        task = ctypes.c_void_p()
        assert lib.MXTPUProfileCreateTask(dom, b"abi_task",
                                          ctypes.byref(task)) == 0
        assert lib.MXTPUProfileDurationStart(task) == 0
        time.sleep(0.005)
        assert lib.MXTPUProfileDurationStop(task) == 0
        ctr = ctypes.c_void_p()
        assert lib.MXTPUProfileCreateCounter(dom, b"abi_ctr",
                                             ctypes.byref(ctr)) == 0
        assert lib.MXTPUProfileSetCounter(ctr, 41) == 0
        assert lib.MXTPUProfileAdjustCounter(ctr, 1) == 0
        assert lib.MXTPUProfileSetMarker(dom, b"abi_mark", b"process") == 0
        stats = ctypes.c_char_p()
        assert lib.MXTPUAggregateProfileStatsPrint(ctypes.byref(stats),
                                                   1) == 0
        s = stats.value.decode()
        assert "abi_task" in s and "abi_ctr=42" in s and "abi_mark" in s
        for h in (task, ctr, dom):
            assert lib.MXTPUProfileDestroyHandle(h) == 0
    finally:
        lib.MXTPUSetProfilerState(0)


def test_rtc_abi(lib):
    """Runtime Pallas-kernel compilation from C (ref MXRtcCudaModule* /
    MXRtcCudaKernel* — source here is Python defining Pallas kernels)."""
    src = (b"def saxpy(x_ref, y_ref, o_ref):\n"
           b"    o_ref[...] = 2.0 * x_ref[...] + y_ref[...]\n")
    mod = ctypes.c_void_p()
    assert lib.MXTPURtcModuleCreate(src, 0, None, ctypes.byref(mod)) == 0
    k = ctypes.c_void_p()
    assert lib.MXTPURtcKernelCreate(mod, b"saxpy", 1, ctypes.byref(k)) == 0
    x = _nd_from_blob(lib, np.arange(8, dtype=np.float32))
    y = _nd_from_blob(lib, np.ones(8, np.float32))
    ins = (ctypes.c_void_p * 2)(x, y)
    shp = (ctypes.c_int64 * 1)(8)
    nd1 = (ctypes.c_int * 1)(1)
    dt = (ctypes.c_int * 1)(0)
    outs = (ctypes.c_void_p * 1)()
    assert lib.MXTPURtcKernelCall(k, 2, ins, 1, shp, nd1, dt, outs) == 0
    np.testing.assert_allclose(
        _nd_to_numpy(lib, ctypes.c_void_p(outs[0])),
        2 * np.arange(8) + 1)
    # unknown kernel name errors loudly
    k2 = ctypes.c_void_p()
    assert lib.MXTPURtcKernelCreate(mod, b"nope", 1, ctypes.byref(k2)) == -1
    lib.MXTPUGetLastError.restype = ctypes.c_char_p
    assert b"nope" in lib.MXTPUGetLastError()
    assert lib.MXTPURtcKernelFree(k) == 0
    assert lib.MXTPURtcModuleFree(mod) == 0


def test_reshape64_alias_abi(lib):
    h = _nd_from_blob(lib, np.arange(6, dtype=np.float32))
    shp = (ctypes.c_int64 * 2)(2, 3)
    out = ctypes.c_void_p()
    assert lib.MXTPUNDArrayReshape64(h, shp, 2, ctypes.byref(out)) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, out),
                               np.arange(6).reshape(2, 3))


def test_executor_backward_ex_none_seed_keeps_head_dtype():
    """A None ograd entry seeds with ones in the HEAD's dtype (ones_like
    semantics, ref MXExecutorBackwardEx NULL entries): a float32 seed on a
    bf16 head would promote every gradient downstream (ADVICE r5)."""
    import mxtpu as mx
    from mxtpu import c_api_impl
    from mxtpu import symbol as sym

    x = sym.var("x")
    y = x * 2.0
    w = mx.nd.ones((3,)).astype("bfloat16")
    exe = y.bind(args={"x": w}, grad_req={"x": "write"})
    exe.forward(is_train=True)
    assert str(exe.outputs[0].dtype) == "bfloat16"
    c_api_impl.executor_backward_ex(exe, (None,))
    assert str(exe.grad_dict["x"].dtype) == "bfloat16"
    np.testing.assert_allclose(
        exe.grad_dict["x"].asnumpy().astype(np.float32), 2.0)


def test_executor_backward_ex_and_grad_state_abi(lib):
    """Explicit-ograd backward + the fresh-grad bookkeeping bit
    (ref MXExecutorBackwardEx / MXNDArraySetGradState)."""
    a = ctypes.c_void_p()
    b = ctypes.c_void_p()
    lib.MXTPUSymbolCreateVariable(b"a", ctypes.byref(a))
    lib.MXTPUSymbolCreateVariable(b"b", ctypes.byref(b))
    comp = ctypes.c_void_p()
    assert lib.MXTPUSymbolCompose(b"elemwise_mul", b"m0",
                                  (ctypes.c_void_p * 2)(a, b), 2, None,
                                  None, 0, ctypes.byref(comp)) == 0
    av = _nd_from_blob(lib, np.full(3, 2.0, np.float32))
    bv = _nd_from_blob(lib, np.full(3, 5.0, np.float32))
    names = (ctypes.c_char_p * 2)(b"a", b"b")
    vals = (ctypes.c_void_p * 2)(av, bv)
    ex = ctypes.c_void_p()
    assert lib.MXTPUExecutorBind(comp, 2, names, vals, b"write",
                                 ctypes.byref(ex)) == 0
    assert lib.MXTPUExecutorForward(ex, 1) == 0
    og = _nd_from_blob(lib, np.full(3, 3.0, np.float32))
    assert lib.MXTPUExecutorBackwardEx(ex, 1,
                                       (ctypes.c_void_p * 1)(og)) == 0
    g = ctypes.c_void_p()
    assert lib.MXTPUExecutorArgGrad(ex, b"a", ctypes.byref(g)) == 0
    np.testing.assert_allclose(_nd_to_numpy(lib, g), 15.0)  # b * ograd
    st = ctypes.c_int()
    assert lib.MXTPUNDArrayGetGradState(av, ctypes.byref(st)) == 0
    assert st.value == 0
    assert lib.MXTPUNDArraySetGradState(av, 1) == 0
    assert lib.MXTPUNDArrayGetGradState(av, ctypes.byref(st)) == 0
    assert st.value == 1


def test_process_profiler_aliases_abi(lib, tmp_path):
    pk = (ctypes.c_char_p * 1)(b"filename")
    pv = (ctypes.c_char_p * 1)(str(tmp_path / "pp.json").encode())
    assert lib.MXTPUSetProcessProfilerConfig(1, pk, pv, 0) == 0
    assert lib.MXTPUSetProcessProfilerState(1, 0) == 0
    assert lib.MXTPUProcessProfilePause(1, 0) == 0
    assert lib.MXTPUProcessProfilePause(0, 0) == 0
    assert lib.MXTPUSetProcessProfilerState(0, 0) == 0
    assert lib.MXTPUDumpProcessProfile(1, 0) == 0
