"""Smoke test for the inference scoring benchmark (tools/benchmark_score.py,
analog of the reference's example/image-classification/benchmark_score.py):
it must import, resolve zoo models by the reference's dotted names, and
produce a finite img/s on CPU."""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def test_score_model_smoke():
    from benchmark_score import score_model
    rate = score_model("squeezenet1.0", 2, steps=2, image_size=64)
    assert np.isfinite(rate) and rate > 0


def test_get_model_accepts_dotted_names():
    from mxtpu.gluon.model_zoo import vision
    net = vision.get_model("mobilenet1.0", classes=10)
    assert net is not None


def test_parse_log_table(tmp_path):
    """tools/parse_log.py parses this framework's (reference-format)
    training logs into a table (ref: tools/parse_log.py)."""
    import subprocess
    import sys

    log = tmp_path / "t.log"
    log.write_text(
        "INFO:root:Epoch[0] Batch [20]\tSpeed: 1000.00 samples/sec\t"
        "accuracy=0.1\n"
        "INFO:root:Epoch[0] Train-accuracy=0.25\n"
        "INFO:root:Epoch[0] Time cost=12.3\n"
        "INFO:root:Epoch[0] Validation-accuracy=0.31\n"
        "INFO:root:Epoch[1] Train-accuracy=0.5\n")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "parse_log.py"),
         str(log), "--format", "csv"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    lines = out.stdout.strip().splitlines()
    assert lines[0] == "epoch,Train-accuracy,Validation-accuracy,speed,time"
    assert lines[1] == "0,0.25,0.31,1000,12.3"
    assert lines[2].startswith("1,0.5")


def test_diagnose_cpu_verdict():
    """tools/diagnose.py must reach a CPU-ONLY/HEALTHY verdict promptly
    on the hermetic CPU backend (the wedge path is exercised for real
    whenever the tunnel is down; ref: tools/diagnose.py)."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PYTHONPATH", None)
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "diagnose.py"),
         "--timeout", "120"],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-500:]
    assert "VERDICT: CPU-ONLY" in out.stdout or \
        "VERDICT: HEALTHY" in out.stdout, out.stdout[-2000:]
