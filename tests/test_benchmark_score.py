"""Smoke test for the inference scoring benchmark (tools/benchmark_score.py,
analog of the reference's example/image-classification/benchmark_score.py):
it must import, resolve zoo models by the reference's dotted names, and
produce a finite img/s on CPU."""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def test_score_model_smoke():
    from benchmark_score import score_model
    rate = score_model("squeezenet1.0", 2, steps=2, image_size=64)
    assert np.isfinite(rate) and rate > 0


def test_get_model_accepts_dotted_names():
    from mxtpu.gluon.model_zoo import vision
    net = vision.get_model("mobilenet1.0", classes=10)
    assert net is not None
