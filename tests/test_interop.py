"""mx.log + torch interop (ref: python/mxnet/log.py, plugin/torch)."""
import numpy as np

import mxtpu as mx


def test_log_getLogger(tmp_path, capsys):
    logger = mx.log.get_logger("t1", level=mx.log.INFO)
    logger.info("hello %d", 7)
    assert mx.log.get_logger("t1") is logger  # idempotent
    f = tmp_path / "x.log"
    flog = mx.log.get_logger("t2", filename=str(f), level=mx.log.DEBUG)
    flog.warning("to file")
    for h in flog.handlers:
        h.flush()
    assert "to file" in f.read_text()


def test_torch_roundtrip():
    import torch
    from mxtpu.torch_interop import from_torch, to_torch

    a = mx.nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    t = to_torch(a)
    assert isinstance(t, torch.Tensor)
    np.testing.assert_array_equal(t.numpy(), a.asnumpy())

    src = torch.arange(6, dtype=torch.float32).reshape(2, 3) * 0.5
    b = from_torch(src)
    np.testing.assert_array_equal(b.asnumpy(), src.numpy())
    # ops compose on the converted array
    np.testing.assert_allclose((b + b).asnumpy(), src.numpy() * 2)
    # non-contiguous tensors still convert (copy path)
    nc = src.t()
    c = from_torch(nc)
    np.testing.assert_array_equal(c.asnumpy(), nc.numpy())


def test_from_torch_copies_and_handles_bf16():
    import torch
    from mxtpu.torch_interop import from_torch, to_torch

    # COPY semantics: in-place torch mutation must NOT leak into the array
    src = torch.zeros(3)
    b = from_torch(src)
    src.fill_(7)
    np.testing.assert_array_equal(b.asnumpy(), [0, 0, 0])

    # bf16 both ways, incl. the non-contiguous path that numpy can't carry
    tb = torch.arange(6, dtype=torch.bfloat16).reshape(2, 3).t()
    c = from_torch(tb)
    assert str(c.dtype) == "bfloat16"
    np.testing.assert_array_equal(c.asnumpy(),
                                  tb.to(torch.float32).numpy())
    a = mx.nd.array(np.ones((2, 2), np.float32)).astype("bfloat16")
    t = to_torch(a)
    assert t.dtype == torch.bfloat16
    # and to_torch results are owned: mutating them leaves the array alone
    t.fill_(5)
    np.testing.assert_array_equal(a.asnumpy(), np.ones((2, 2)))


def test_log_root_untouched():
    import logging
    n_before = len(logging.getLogger().handlers)
    mx.log.get_logger()  # name=None: must not install a root handler
    assert len(logging.getLogger().handlers) == n_before
