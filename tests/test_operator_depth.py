"""Operator DEPTH sweeps: many parameterizations per heavy op, each against
a from-scratch NumPy oracle, in the style of the reference's exhaustive
tests/python/unittest/test_operator.py (7,213 LoC — e.g. its convolution
tests sweep kernel/stride/dilate/pad/group combinations; its pooling tests
sweep conventions). tests/test_operator.py covers one-or-two configs per op;
this module is the combinatorial tier.

Oracles here are textbook implementations written for this file (naive
loops), not ports: correctness is anchored to the math, not to either
framework.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.test_utils import assert_almost_equal, check_numeric_gradient

RNG = np.random.RandomState


# ----------------------------------------------------------------- oracles
def np_conv2d(x, w, stride=(1, 1), dilate=(1, 1), pad=(0, 0), groups=1):
    """Naive NCHW conv: loops over every output element."""
    n, cin, h, wd = x.shape
    cout, cin_g, kh, kw = w.shape
    assert cin_g * groups == cin
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    xp = np.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (wd + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    out = np.zeros((n, cout, oh, ow), np.float64)
    cpg = cout // groups  # output channels per group
    for b in range(n):
        for co in range(cout):
            g = co // cpg
            for i in range(oh):
                for j in range(ow):
                    acc = 0.0
                    for ci in range(cin_g):
                        for u in range(kh):
                            for v in range(kw):
                                acc += (xp[b, g * cin_g + ci,
                                           i * sh + u * dh, j * sw + v * dw]
                                        * w[co, ci, u, v])
                    out[b, co, i, j] = acc
    return out.astype(np.float32)


def np_deconv2d(x, w, stride=(1, 1), pad=(0, 0), adj=(0, 0)):
    """Transposed conv oracle: insert (s-1) zeros between input pixels,
    pad by (k-1-p, k-1-p+adj), then correlate with the spatially-flipped,
    io-swapped kernel (the standard construction)."""
    n, cin, h, wd = x.shape
    cin_w, cout, kh, kw = w.shape  # reference weight layout (in, out, kh, kw)
    assert cin_w == cin
    sh, sw = stride
    up = np.zeros((n, cin, (h - 1) * sh + 1, (wd - 1) * sw + 1), x.dtype)
    up[:, :, ::sh, ::sw] = x
    ph, pw = pad
    ah, aw = adj
    xp = np.pad(up, ((0, 0), (0, 0),
                     (kh - 1 - ph, kh - 1 - ph + ah),
                     (kw - 1 - pw, kw - 1 - pw + aw)))
    wf = w[:, :, ::-1, ::-1].transpose(1, 0, 2, 3)  # (out,in,kh,kw) flipped
    return np_conv2d(xp, wf)


def np_pool2d(x, kernel, pool_type="max", stride=(1, 1), pad=(0, 0),
              convention="valid", count_include_pad=True):
    n, c, h, wd = x.shape
    kh, kw = kernel
    sh, sw = stride
    ph, pw = pad
    if convention == "full":
        oh = int(np.ceil((h + 2 * ph - kh) / sh)) + 1
        ow = int(np.ceil((wd + 2 * pw - kw) / sw)) + 1
    else:
        oh = (h + 2 * ph - kh) // sh + 1
        ow = (wd + 2 * pw - kw) // sw + 1
    out = np.zeros((n, c, oh, ow), np.float64)
    for b in range(n):
        for ch in range(c):
            for i in range(oh):
                for j in range(ow):
                    vals, n_real = [], 0
                    for u in range(kh):
                        for v in range(kw):
                            y, z = i * sh + u - ph, j * sw + v - pw
                            if 0 <= y < h and 0 <= z < wd:
                                vals.append(x[b, ch, y, z])
                                n_real += 1
                    if pool_type == "max":
                        out[b, ch, i, j] = max(vals)
                    elif pool_type == "sum":
                        out[b, ch, i, j] = sum(vals)
                    else:  # avg: padded zeros count iff count_include_pad
                        denom = kh * kw if count_include_pad else n_real
                        out[b, ch, i, j] = sum(vals) / denom
    return out.astype(np.float32)


# ------------------------------------------------------------- convolution
CONV_CFGS = [
    # kernel, stride, dilate, pad, groups  (ref conv tests sweep these axes)
    ((3, 3), (1, 1), (1, 1), (1, 1), 1),
    ((3, 3), (2, 2), (1, 1), (1, 1), 1),
    ((3, 3), (1, 1), (2, 2), (2, 2), 1),   # dilated
    ((1, 1), (1, 1), (1, 1), (0, 0), 1),   # pointwise
    ((5, 3), (2, 1), (1, 1), (2, 1), 1),   # asymmetric kernel/stride/pad
    ((3, 3), (1, 1), (1, 1), (1, 1), 2),   # grouped
    ((3, 3), (1, 1), (1, 1), (1, 1), 4),   # depthwise (g == C_in)
]


@pytest.mark.parametrize("kernel,stride,dilate,pad,groups", CONV_CFGS)
def test_convolution_sweep(kernel, stride, dilate, pad, groups):
    rng = RNG(7)
    cin, cout = 4, 8
    x = rng.uniform(-1, 1, (2, cin, 9, 9)).astype(np.float32)
    w = rng.uniform(-0.5, 0.5,
                    (cout, cin // groups) + kernel).astype(np.float32)
    b = rng.uniform(-0.5, 0.5, (cout,)).astype(np.float32)
    out = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b),
                            kernel=kernel, stride=stride, dilate=dilate,
                            pad=pad, num_filter=cout, num_group=groups)
    ref = np_conv2d(x, w, stride, dilate, pad, groups) + b[None, :, None, None]
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)


def test_convolution_1d_and_3d():
    rng = RNG(3)
    x1 = rng.uniform(-1, 1, (2, 3, 12)).astype(np.float32)
    w1 = rng.uniform(-1, 1, (5, 3, 4)).astype(np.float32)
    out = mx.nd.Convolution(mx.nd.array(x1), mx.nd.array(w1), no_bias=True,
                            kernel=(4,), stride=(2,), pad=(1,), num_filter=5)
    # 1D == 2D conv with unit height
    ref = np_conv2d(x1[:, :, None, :], w1[:, :, None, :],
                    (1, 2), (1, 1), (0, 1))[:, :, 0, :]
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)

    x3 = rng.uniform(-1, 1, (1, 2, 5, 5, 5)).astype(np.float32)
    w3 = rng.uniform(-1, 1, (4, 2, 2, 2, 2)).astype(np.float32)
    out3 = mx.nd.Convolution(mx.nd.array(x3), mx.nd.array(w3), no_bias=True,
                             kernel=(2, 2, 2), num_filter=4)
    # 3D oracle: sum of 2D convs over the depth taps
    ref3 = np.zeros((1, 4, 4, 4, 4), np.float32)
    for dz in range(2):
        for z in range(4):
            ref3[:, :, z] += np_conv2d(x3[:, :, z + dz], w3[:, :, dz])
    assert_almost_equal(out3, ref3, rtol=1e-4, atol=1e-4)


def test_convolution_numeric_grad():
    rng = RNG(11)
    x = rng.uniform(-1, 1, (1, 2, 6, 6)).astype(np.float32)
    w = rng.uniform(-0.5, 0.5, (3, 2, 3, 3)).astype(np.float32)

    def f(x_, w_):
        return mx.nd.Convolution(x_, w_, no_bias=True, kernel=(3, 3),
                                 stride=(2, 2), pad=(1, 1), num_filter=3)
    check_numeric_gradient(f, [mx.nd.array(x), mx.nd.array(w)])


# ----------------------------------------------------------- deconvolution
DECONV_CFGS = [
    # kernel, stride, pad, adj
    ((3, 3), (1, 1), (1, 1), (0, 0)),
    ((2, 2), (2, 2), (0, 0), (0, 0)),
    ((3, 3), (2, 2), (1, 1), (1, 1)),  # adj recovers odd sizes
    ((4, 4), (2, 2), (1, 1), (0, 0)),
]


@pytest.mark.parametrize("kernel,stride,pad,adj", DECONV_CFGS)
def test_deconvolution_sweep(kernel, stride, pad, adj):
    rng = RNG(5)
    cin, cout = 3, 5
    x = rng.uniform(-1, 1, (2, cin, 5, 5)).astype(np.float32)
    w = rng.uniform(-0.5, 0.5, (cin, cout) + kernel).astype(np.float32)
    out = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w),
                              kernel=kernel, stride=stride, pad=pad,
                              adj=adj, num_filter=cout)
    ref = np_deconv2d(x, w, stride, pad, adj)
    # output size formula (ref deconvolution doc): (i-1)*s - 2p + k + adj
    expect = tuple((5 - 1) * s - 2 * p + k + a
                   for s, p, k, a in zip(stride, pad, kernel, adj))
    assert out.shape[2:] == expect
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)


def test_deconv_is_conv_data_grad():
    """Deconvolution must equal the gradient of Convolution wrt its input
    (the defining property; ref implements it exactly that way)."""
    rng = RNG(9)
    x = rng.uniform(-1, 1, (1, 4, 4, 4)).astype(np.float32)  # conv OUTPUT side
    w = rng.uniform(-1, 1, (4, 2, 3, 3)).astype(np.float32)  # (cout,cin,k,k)
    from mxtpu import autograd as ag
    inp = mx.nd.array(rng.uniform(-1, 1, (1, 2, 8, 8)).astype(np.float32))
    inp.attach_grad()
    with ag.record():
        y = mx.nd.Convolution(inp, mx.nd.array(w), no_bias=True,
                              kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                              num_filter=4)
        y.backward(mx.nd.array(x))
    # deconv weight layout is (cin_of_deconv==cout_of_conv, cout, k, k) = w as-is
    dec = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w),
                              kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                              adj=(1, 1), num_filter=2)
    assert_almost_equal(inp.grad, dec.asnumpy(), rtol=1e-4, atol=1e-4)


# ----------------------------------------------------------------- pooling
POOL_CFGS = [
    # kernel, pool_type, stride, pad, convention, count_include_pad
    ((2, 2), "max", (2, 2), (0, 0), "valid", True),
    ((3, 3), "max", (2, 2), (1, 1), "valid", True),
    ((3, 3), "max", (2, 2), (1, 1), "full", True),
    ((2, 2), "avg", (2, 2), (0, 0), "valid", True),
    ((3, 3), "avg", (2, 2), (1, 1), "valid", False),
    ((3, 3), "avg", (2, 2), (1, 1), "full", True),
    ((2, 3), "sum", (1, 2), (0, 1), "valid", True),
    ((3, 3), "max", (3, 3), (0, 0), "full", True),
]


@pytest.mark.parametrize(
    "kernel,pool_type,stride,pad,convention,cip", POOL_CFGS)
def test_pooling_sweep(kernel, pool_type, stride, pad, convention, cip):
    rng = RNG(13)
    x = rng.uniform(-1, 1, (2, 3, 7, 7)).astype(np.float32)
    out = mx.nd.Pooling(mx.nd.array(x), kernel=kernel, pool_type=pool_type,
                        stride=stride, pad=pad,
                        pooling_convention=convention,
                        count_include_pad=cip)
    ref = np_pool2d(x, kernel, pool_type, stride, pad, convention, cip)
    assert out.shape == ref.shape, (out.shape, ref.shape)
    assert_almost_equal(out, ref, rtol=1e-5, atol=1e-5)


def test_pooling_global_and_lp():
    rng = RNG(17)
    x = rng.uniform(0.1, 1, (2, 3, 5, 6)).astype(np.float32)
    g = mx.nd.Pooling(mx.nd.array(x), global_pool=True, pool_type="max")
    assert_almost_equal(g, x.max((2, 3), keepdims=True))
    g = mx.nd.Pooling(mx.nd.array(x), global_pool=True, pool_type="avg")
    assert_almost_equal(g, x.mean((2, 3), keepdims=True), rtol=1e-5)
    lp = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2),
                       pool_type="lp", p_value=3)
    # lp oracle: (sum |x|^p)^(1/p) over each window
    p3 = np_pool2d(np.abs(x) ** 3, (2, 2), "sum", (2, 2)) ** (1 / 3)
    assert_almost_equal(lp, p3, rtol=1e-4, atol=1e-5)


def test_avg_pool_numeric_grad():
    rng = RNG(19)
    x = mx.nd.array(rng.uniform(-1, 1, (1, 2, 6, 6)).astype(np.float32))

    def f(x_):
        return mx.nd.Pooling(x_, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                             pool_type="avg", count_include_pad=False)
    check_numeric_gradient(f, [x])


# ----------------------------------------------------------------- softmax
@pytest.mark.parametrize("axis", [0, 1, 2, -1])
def test_softmax_axes(axis):
    rng = RNG(23)
    x = rng.uniform(-3, 3, (3, 4, 5)).astype(np.float32)

    def np_softmax(x, axis):
        e = np.exp(x - x.max(axis=axis, keepdims=True))
        return e / e.sum(axis=axis, keepdims=True)

    assert_almost_equal(mx.nd.softmax(mx.nd.array(x), axis=axis),
                        np_softmax(x, axis), rtol=1e-5, atol=1e-6)
    assert_almost_equal(mx.nd.log_softmax(mx.nd.array(x), axis=axis),
                        np.log(np_softmax(x, axis)), rtol=1e-4, atol=1e-5)


def test_softmax_temperature_and_softmin():
    rng = RNG(29)
    x = rng.uniform(-3, 3, (4, 6)).astype(np.float32)
    for t in (0.5, 2.0, 10.0):
        e = np.exp((x - x.max(1, keepdims=True)) / t)
        assert_almost_equal(
            mx.nd.softmax(mx.nd.array(x), temperature=t),
            e / e.sum(1, keepdims=True), rtol=1e-5, atol=1e-6)
    e = np.exp(-x - (-x).max(1, keepdims=True))
    assert_almost_equal(mx.nd.softmin(mx.nd.array(x)),
                        e / e.sum(1, keepdims=True), rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------ norm / stats
@pytest.mark.parametrize("ord_", [1, 2])
@pytest.mark.parametrize("axis", [None, 0, 1, (0, 1)])
@pytest.mark.parametrize("keepdims", [False, True])
def test_norm_sweep(ord_, axis, keepdims):
    rng = RNG(31)
    x = rng.uniform(-2, 2, (3, 4, 5)).astype(np.float32)
    if ord_ == 1:
        ref = np.abs(x).sum(axis=axis, keepdims=keepdims)
    else:
        ref = np.sqrt((x ** 2).sum(axis=axis, keepdims=keepdims))
    out = mx.nd.norm(mx.nd.array(x), ord=ord_, axis=axis, keepdims=keepdims)
    assert_almost_equal(out, np.asarray(ref, np.float32), rtol=1e-4,
                        atol=1e-5)


# -------------------------------------------------------------------- topk
@pytest.mark.parametrize("ret_typ", ["indices", "value", "mask", "both"])
@pytest.mark.parametrize("is_ascend", [False, True])
def test_topk_sweep(ret_typ, is_ascend):
    rng = RNG(37)
    x = rng.permutation(24).reshape(4, 6).astype(np.float32)  # unique values
    k = 3
    order = np.argsort(x, axis=1)
    idx = order[:, :k] if is_ascend else order[:, ::-1][:, :k]
    out = mx.nd.topk(mx.nd.array(x), axis=1, k=k, ret_typ=ret_typ,
                     is_ascend=is_ascend)
    if ret_typ == "indices":
        assert_almost_equal(out, idx.astype(np.float32))
    elif ret_typ == "value":
        assert_almost_equal(out, np.take_along_axis(x, idx, 1))
    elif ret_typ == "mask":
        mask = np.zeros_like(x)
        np.put_along_axis(mask, idx, 1.0, 1)
        assert_almost_equal(out, mask)
    else:  # both -> (values, indices)
        assert_almost_equal(out[0], np.take_along_axis(x, idx, 1))
        assert_almost_equal(out[1], idx.astype(np.float32))


def test_topk_axis0_and_k1():
    rng = RNG(41)
    x = rng.permutation(12).reshape(3, 4).astype(np.float32)
    out = mx.nd.topk(mx.nd.array(x), axis=0, k=2, ret_typ="value")
    ref = np.sort(x, axis=0)[::-1][:2]
    assert_almost_equal(out, ref)
    out = mx.nd.topk(mx.nd.array(x), k=1)  # default axis=-1, indices
    assert_almost_equal(out, x.argmax(1, keepdims=True).astype(np.float32))


# ----------------------------------------------------------- take / gather
@pytest.mark.parametrize("axis", [0, 1, -1])
@pytest.mark.parametrize("mode", ["clip", "wrap"])
def test_take_sweep(axis, mode):
    rng = RNG(43)
    x = rng.uniform(-1, 1, (4, 5, 6)).astype(np.float32)
    idx = np.array([[0, 2], [7, -3]], np.float32)  # out-of-range on purpose
    n = x.shape[axis]
    ii = idx.astype(np.int64)
    ii = np.clip(ii, 0, n - 1) if mode == "clip" else ii % n
    out = mx.nd.take(mx.nd.array(x), mx.nd.array(idx), axis=axis, mode=mode)
    assert_almost_equal(out, np.take(x, ii, axis=axis), rtol=1e-6)


def test_embedding_grad_accumulates_repeats():
    """Repeated indices must SUM their output grads into the same row
    (the correctness trap for one-hot/scatter implementations)."""
    from mxtpu import autograd as ag
    w = mx.nd.array(np.zeros((5, 3), np.float32))
    w.attach_grad()
    idx = mx.nd.array(np.array([1, 1, 1, 4], np.float32))
    with ag.record():
        out = mx.nd.Embedding(idx, w, input_dim=5, output_dim=3)
        out.backward(mx.nd.array(np.ones((4, 3), np.float32)))
    expect = np.zeros((5, 3), np.float32)
    expect[1] = 3.0
    expect[4] = 1.0
    assert_almost_equal(w.grad, expect)


# ------------------------------------------------------------------ slicing
def test_slice_step_variants():
    rng = RNG(47)
    x = rng.uniform(-1, 1, (6, 8)).astype(np.float32)
    nd = mx.nd.array(x)
    out = mx.nd.slice(nd, begin=(1, 0), end=(5, 8), step=(2, 3))
    assert_almost_equal(out, x[1:5:2, 0:8:3])
    out = mx.nd.slice(nd, begin=(4, None), end=(0, None), step=(-2, 1))
    assert_almost_equal(out, x[4:0:-2, :])
    out = mx.nd.slice_axis(nd, axis=1, begin=-3, end=None)
    assert_almost_equal(out, x[:, -3:])
    like = mx.nd.array(np.zeros((3, 4), np.float32))
    assert_almost_equal(mx.nd.slice_like(nd, like), x[:3, :4])
    assert_almost_equal(mx.nd.slice_like(nd, like, axes=(1,)), x[:, :4])


# ---------------------------------------------------------------- batch_dot
@pytest.mark.parametrize("ta", [False, True])
@pytest.mark.parametrize("tb", [False, True])
def test_batch_dot_sweep(ta, tb):
    rng = RNG(53)
    a = rng.uniform(-1, 1, (4, 3, 5)).astype(np.float32)
    b = rng.uniform(-1, 1, (4, 5, 2)).astype(np.float32)
    an = a.transpose(0, 2, 1) if ta else a
    bn = b.transpose(0, 2, 1) if tb else b
    out = mx.nd.batch_dot(mx.nd.array(an), mx.nd.array(bn),
                          transpose_a=ta, transpose_b=tb)
    assert_almost_equal(out, np.matmul(a, b), rtol=1e-4, atol=1e-5)


# --------------------------------------------------------------- batchnorm
def test_batchnorm_channels_last_and_fix_gamma():
    rng = RNG(59)
    x = rng.uniform(-2, 2, (4, 5, 3)).astype(np.float32)  # (N, W, C), axis=-1
    gamma = rng.uniform(0.5, 1.5, (3,)).astype(np.float32)
    beta = rng.uniform(-0.5, 0.5, (3,)).astype(np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    from mxtpu import autograd as ag
    with ag.record(train_mode=True):
        out = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.array(gamma),
                              mx.nd.array(beta), mx.nd.array(mm),
                              mx.nd.array(mv), axis=-1, eps=1e-5,
                              fix_gamma=False)
    mean = x.mean((0, 1))
    var = x.var((0, 1))
    ref = (x - mean) / np.sqrt(var + 1e-5) * gamma + beta
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)
    # fix_gamma=True (the reference's default): scale pinned to 1
    with ag.record(train_mode=True):
        out = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.array(gamma),
                              mx.nd.array(beta), mx.nd.array(mm),
                              mx.nd.array(mv), axis=-1, eps=1e-5,
                              fix_gamma=True)
    ref = (x - mean) / np.sqrt(var + 1e-5) + beta
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)


def test_batchnorm_use_global_stats():
    rng = RNG(61)
    x = rng.uniform(-2, 2, (2, 3, 4, 4)).astype(np.float32)
    gamma = np.ones(3, np.float32)
    beta = np.zeros(3, np.float32)
    mm = rng.uniform(-0.5, 0.5, 3).astype(np.float32)
    mv = rng.uniform(0.5, 1.5, 3).astype(np.float32)
    from mxtpu import autograd as ag
    with ag.record(train_mode=True):  # use_global_stats overrides train mode
        out = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.array(gamma),
                              mx.nd.array(beta), mx.nd.array(mm),
                              mx.nd.array(mv), eps=1e-5,
                              use_global_stats=True)
    ref = ((x - mm[None, :, None, None])
           / np.sqrt(mv[None, :, None, None] + 1e-5))
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)


# ------------------------------------------------------------------- misc
def test_pick_modes_and_keepdims():
    rng = RNG(67)
    x = rng.uniform(-1, 1, (3, 4)).astype(np.float32)
    idx = np.array([0, 5, 2], np.float32)  # 5 out of range -> clip to 3
    out = mx.nd.pick(mx.nd.array(x), mx.nd.array(idx), axis=1)
    ii = np.clip(idx.astype(np.int64), 0, 3)
    ref = x[np.arange(3), ii]
    assert_almost_equal(out, ref)
    out = mx.nd.pick(mx.nd.array(x), mx.nd.array(idx), axis=1, keepdims=True)
    assert_almost_equal(out, ref[:, None])
    out = mx.nd.pick(mx.nd.array(x), mx.nd.array(idx), axis=1, mode="wrap")
    assert_almost_equal(out, x[np.arange(3), idx.astype(np.int64) % 4])


def test_one_hot_values_and_dtype():
    idx = mx.nd.array(np.array([0, 2, 1], np.float32))
    out = mx.nd.one_hot(idx, 4, on_value=2.5, off_value=-1.0)
    ref = np.full((3, 4), -1.0, np.float32)
    for i, j in enumerate([0, 2, 1]):
        ref[i, j] = 2.5
    assert_almost_equal(out, ref)
    assert mx.nd.one_hot(idx, 4, dtype="int32").dtype == np.int32
