"""Oracle tests for the round-5 straggler ops — the 14 reference operators
registered via MXNET_OPERATOR_REGISTER_* wrapper macros that the original
parity audit never saw (VERDICT r4 missing #2): hard_sigmoid, _hypot(_scalar),
_square_sum, _logical_{and,or,xor}_scalar, _rmod_scalar, _mod, _grad_add,
_scatter_{plus,minus}_scalar, _scatter_elemwise_div, _sample_unique_zipfian.

Reference semantics: src/operator/tensor/elemwise_unary_op_basic.cc:109,
elemwise_binary_broadcast_op_extended.cc, square_sum.cc,
elemwise_scatter_op.cc, random/unique_sample_op.h.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.ndarray.sparse import RowSparseNDArray


def _nd(a):
    return mx.nd.array(np.asarray(a, np.float32))


def test_hard_sigmoid_oracle_and_grad():
    x = np.array([-10.0, -2.5, -1.0, 0.0, 1.0, 2.5, 10.0], np.float32)
    out = mx.nd.hard_sigmoid(_nd(x))
    np.testing.assert_allclose(out.asnumpy(),
                               np.clip(0.2 * x + 0.5, 0.0, 1.0), rtol=1e-6)
    # non-default alpha/beta
    out2 = mx.nd.hard_sigmoid(_nd(x), alpha=0.5, beta=0.25)
    np.testing.assert_allclose(out2.asnumpy(),
                               np.clip(0.5 * x + 0.25, 0.0, 1.0), rtol=1e-6)
    # grad = alpha inside the linear band, 0 where saturated
    xv = _nd(x)
    xv.attach_grad()
    with mx.autograd.record():
        y = mx.nd.hard_sigmoid(xv)
    y.backward(mx.nd.ones_like(y))
    expect = np.where((0.2 * x + 0.5 > 0) & (0.2 * x + 0.5 < 1), 0.2, 0.0)
    np.testing.assert_allclose(xv.grad.asnumpy(), expect, rtol=1e-6)


def test_hypot_tensor_and_scalar():
    a = np.array([[3.0, 5.0], [8.0, 7.0]], np.float32)
    b = np.array([[4.0, 12.0], [15.0, 24.0]], np.float32)
    np.testing.assert_allclose(
        mx.nd._internal._hypot(_nd(a), _nd(b)).asnumpy(),
        np.hypot(a, b), rtol=1e-6)
    np.testing.assert_allclose(
        mx.nd._internal._hypot_scalar(_nd(a), 4.0).asnumpy(),
        np.hypot(a, 4.0), rtol=1e-6)


def test_mod_family():
    a = np.array([5.0, -7.0, 9.5], np.float32)
    np.testing.assert_allclose(
        mx.nd._internal._mod(_nd(a), _nd(np.array([3.0, 4.0, 2.0]))).asnumpy(),
        np.mod(a, [3.0, 4.0, 2.0]), rtol=1e-6)
    # _rmod_scalar computes scalar mod x
    np.testing.assert_allclose(
        mx.nd._internal._rmod_scalar(_nd(a), 3.0).asnumpy(),
        np.mod(3.0, a), rtol=1e-6)


def test_logical_scalar_variants():
    a = np.array([0.0, 1.0, -2.0, 0.0], np.float32)
    for name, onp in (("_logical_and_scalar", np.logical_and),
                      ("_logical_or_scalar", np.logical_or),
                      ("_logical_xor_scalar", np.logical_xor)):
        fn = getattr(mx.nd._internal, name)
        np.testing.assert_allclose(fn(_nd(a), 1.0).asnumpy(),
                                   onp(a != 0, True).astype(np.float32))
        np.testing.assert_allclose(fn(_nd(a), 0.0).asnumpy(),
                                   onp(a != 0, False).astype(np.float32))


def test_grad_add_is_elemwise_add():
    a, b = np.ones((2, 3), np.float32), np.full((2, 3), 2.0, np.float32)
    np.testing.assert_allclose(
        mx.nd._internal._grad_add(_nd(a), _nd(b)).asnumpy(), a + b)


def test_square_sum_dense_axes():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    for axis, keepdims in [(None, False), (0, False), (1, False), (1, True)]:
        got = mx.nd._internal._square_sum(_nd(x), axis=axis,
                                          keepdims=keepdims).asnumpy()
        np.testing.assert_allclose(
            got, np.sum(np.square(x), axis=axis, keepdims=keepdims),
            rtol=1e-6)


def test_square_sum_row_sparse():
    # rsp with stored rows {0, 2} of a (4, 3) logical array
    vals = np.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]], np.float32)
    rsp = RowSparseNDArray(vals, [0, 2], (4, 3))
    dense = rsp.todense().asnumpy()
    # axis=1 keepdims → row_sparse output sharing row ids (square_sum.cc:61)
    out = mx.nd._internal._square_sum(rsp, axis=1, keepdims=True)
    assert out.stype == "row_sparse"
    assert out.shape == (4, 1)
    np.testing.assert_allclose(out.todense().asnumpy(),
                               np.sum(np.square(dense), 1, keepdims=True),
                               rtol=1e-6)
    # axis=1 without keepdims and axis=0 → dense
    np.testing.assert_allclose(
        mx.nd._internal._square_sum(rsp, axis=1).asnumpy(),
        np.sum(np.square(dense), axis=1), rtol=1e-6)
    np.testing.assert_allclose(
        mx.nd._internal._square_sum(rsp, axis=0).asnumpy(),
        np.sum(np.square(dense), axis=0), rtol=1e-6)
    np.testing.assert_allclose(
        mx.nd._internal._square_sum(rsp).asnumpy(),
        np.sum(np.square(dense)), rtol=1e-6)


def test_square_sum_csr_densifies():
    from mxtpu.ndarray.sparse import CSRNDArray
    # [[1,0,2],[0,0,0],[3,4,0]]
    csr = CSRNDArray([1.0, 2.0, 3.0, 4.0], [0, 2, 2, 4], [0, 2, 0, 1], (3, 3))
    dense = csr.todense().asnumpy()
    for axis in (0, 1, None):
        np.testing.assert_allclose(
            mx.nd._internal._square_sum(csr, axis=axis).asnumpy(),
            np.sum(np.square(dense), axis=axis), rtol=1e-6)


def test_square_sum_grad():
    x = _nd(np.array([[1.0, -2.0], [3.0, 0.5]], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd._internal._square_sum(x, axis=1)
    y.backward(mx.nd.ones_like(y))
    np.testing.assert_allclose(x.grad.asnumpy(), 2.0 * x.asnumpy(), rtol=1e-6)


def test_scatter_scalar_dense_matches_plain_op():
    a = np.array([[1.0, 0.0], [0.0, 4.0]], np.float32)
    np.testing.assert_allclose(
        mx.nd._internal._scatter_plus_scalar(_nd(a), 2.0).asnumpy(), a + 2.0)
    np.testing.assert_allclose(
        mx.nd._internal._scatter_minus_scalar(_nd(a), 2.0).asnumpy(), a - 2.0)


def test_scatter_scalar_keeps_row_sparse_storage():
    vals = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    rsp = RowSparseNDArray(vals, [1, 3], (5, 2))
    out = mx.nd._internal._scatter_plus_scalar(rsp, 10.0)
    # storage and sparsity pattern preserved; op applied ONLY at stored rows
    # (elemwise_scatter_op.cc:94: unstored rows stay zero, NOT 10)
    assert out.stype == "row_sparse"
    np.testing.assert_array_equal(out.indices.asnumpy(), [1, 3])
    np.testing.assert_allclose(out.data.asnumpy(), vals + 10.0)
    dense = out.todense().asnumpy()
    np.testing.assert_allclose(dense[0], 0.0)


def test_scatter_elemwise_div_row_sparse_lhs():
    vals = np.array([[2.0, 4.0], [6.0, 8.0]], np.float32)
    rsp = RowSparseNDArray(vals, [0, 2], (3, 2))
    rhs = np.array([[2.0, 2.0], [7.0, 7.0], [4.0, 2.0]], np.float32)
    out = mx.nd._internal._scatter_elemwise_div(rsp, _nd(rhs))
    assert out.stype == "row_sparse"
    np.testing.assert_allclose(out.data.asnumpy(),
                               vals / rhs[[0, 2]], rtol=1e-6)
    # dense lhs degenerates to plain division
    a = np.array([[8.0, 6.0]], np.float32)
    np.testing.assert_allclose(
        mx.nd._internal._scatter_elemwise_div(_nd(a), _nd([[2.0, 3.0]])).asnumpy(),
        a / np.array([[2.0, 3.0]], np.float32), rtol=1e-6)


def test_scatter_elemwise_div_csr_falls_back_dense():
    """CSR operands take the reference's dense storage fallback — the 1-D
    values buffer must never be divided as if it were the logical array."""
    from mxtpu.ndarray.sparse import CSRNDArray
    # csr([[1, 0], [0, 3]]) with nnz == ncols == 2 (the shape-coincidence
    # case where a values-buffer division would silently broadcast)
    csr = CSRNDArray([1.0, 3.0], [0, 1, 2], [0, 1], (2, 2))
    rhs = np.array([[2.0, 5.0], [5.0, 2.0]], np.float32)
    out = mx.nd._internal._scatter_elemwise_div(csr, _nd(rhs))
    np.testing.assert_allclose(out.asnumpy(),
                               csr.todense().asnumpy() / rhs, rtol=1e-6)
    # csr rhs under a row_sparse lhs is read densely
    vals = np.array([[4.0, 9.0]], np.float32)
    rsp = RowSparseNDArray(vals, [1], (2, 2))
    out2 = mx.nd._internal._scatter_elemwise_div(rsp, csr)
    # dense(csr)[row 1] == [0, 3]; division by the 0 entry yields inf
    got = out2.data.asnumpy()
    assert np.isinf(got[0, 0]) and np.isclose(got[0, 1], 3.0)


def test_scatter_out_param_moves_sparse_aux():
    vals = np.array([[1.0, 2.0]], np.float32)
    rsp = RowSparseNDArray(vals, [2], (4, 2))
    dst = RowSparseNDArray(np.zeros((1, 2), np.float32), [0], (4, 2))
    out = mx.nd._internal._scatter_plus_scalar(rsp, 1.0, out=dst)
    # copyto must carry the row ids, not just the values (stale indices
    # would attribute the rows to row 0)
    np.testing.assert_array_equal(out.indices.asnumpy(), [2])
    np.testing.assert_allclose(out.data.asnumpy(), vals + 1.0)


def test_sample_unique_zipfian_contract():
    mx.random.seed(7)
    out = mx.nd._internal._sample_unique_zipfian(range_max=1000,
                                                 shape=(4, 50))
    samples, tries = out[0].asnumpy(), out[1].asnumpy()
    assert samples.shape == (4, 50) and tries.shape == (4,)
    # reference emits int64; under jax's default x64-off config the device
    # array is int32 — either satisfies the contract for range_max < 2^31
    assert samples.dtype in (np.int32, np.int64)
    for row, t in zip(samples, tries):
        assert len(set(row.tolist())) == 50        # unique within row
        assert row.min() >= 0 and row.max() < 1000  # in range
        assert t >= 50                              # ≥1 try per sample
    # log-uniform shape: small ids must dominate (P(v) ∝ log((v+2)/(v+1)))
    all_s = samples.ravel()
    assert (all_s < 100).sum() > (all_s >= 500).sum()
    # seeding reproduces
    mx.random.seed(7)
    out2 = mx.nd._internal._sample_unique_zipfian(range_max=1000,
                                                  shape=(4, 50))
    np.testing.assert_array_equal(samples, out2[0].asnumpy())


def test_audit_reports_zero_missing():
    """The fixed audit (scanning MXNET_OPERATOR_REGISTER_* call sites too)
    must see every reference op accounted for — an audit that cannot fail
    is worse than none (VERDICT r4 weak #3), so this pins the fixed scan's
    verdict."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "op_parity", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "op_parity.py"))
    opp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(opp)
    if not os.path.isdir(opp.REF):
        pytest.skip("reference tree not present")
    names = opp.reference_ops()
    # the widened scan must see the wrapper-macro registrations
    assert "hard_sigmoid" in names and "_square_sum" in names
    assert len(names) > 400
    missing = [n for n, cat, _ in opp.classify(names) if cat == "missing"]
    assert missing == []
