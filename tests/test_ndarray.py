"""NDArray semantics tests (modeled on tests/python/unittest/test_ndarray.py in the
reference: creation, arithmetic, indexing, copy, serialization, sync semantics)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.test_utils import assert_almost_equal


def test_creation():
    a = mx.nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    b = mx.nd.ones((2, 3), dtype="int32")
    assert b.asnumpy().sum() == 6
    c = mx.nd.full((2, 2), 7.0)
    assert c.asnumpy().tolist() == [[7, 7], [7, 7]]
    d = mx.nd.array(np.arange(6).reshape(2, 3))
    assert d.shape == (2, 3)
    e = mx.nd.arange(0, 10, 2)
    assert e.asnumpy().tolist() == [0, 2, 4, 6, 8]


def test_python_float_defaults_to_f32():
    a = mx.nd.array([1.5, 2.5])
    assert a.dtype == np.float32


def test_arithmetic():
    a = mx.nd.array([[1., 2.], [3., 4.]])
    b = mx.nd.array([[10., 20.], [30., 40.]])
    assert_almost_equal(a + b, [[11, 22], [33, 44]])
    assert_almost_equal(b - a, [[9, 18], [27, 36]])
    assert_almost_equal(a * 2, [[2, 4], [6, 8]])
    assert_almost_equal(2 * a, [[2, 4], [6, 8]])
    assert_almost_equal(1 / a, 1 / a.asnumpy())
    assert_almost_equal(b / a, b.asnumpy() / a.asnumpy())
    assert_almost_equal(a ** 2, a.asnumpy() ** 2)
    assert_almost_equal(-a, -a.asnumpy())
    assert_almost_equal(abs(-a), a.asnumpy())
    assert_almost_equal(a % 2, a.asnumpy() % 2)


def test_comparison_returns_float():
    a = mx.nd.array([1., 2., 3.])
    b = mx.nd.array([2., 2., 2.])
    assert (a == b).asnumpy().tolist() == [0, 1, 0]
    assert (a > b).asnumpy().tolist() == [0, 0, 1]
    assert (a >= b).asnumpy().tolist() == [0, 1, 1]
    assert (a < b).asnumpy().tolist() == [1, 0, 0]
    assert (a != b).dtype == np.float32


def test_inplace():
    a = mx.nd.ones((2, 2))
    a += 1
    assert_almost_equal(a, np.full((2, 2), 2.0))
    a *= 3
    assert_almost_equal(a, np.full((2, 2), 6.0))
    a /= 2
    assert_almost_equal(a, np.full((2, 2), 3.0))
    a -= 1
    assert_almost_equal(a, np.full((2, 2), 2.0))


def test_indexing():
    a = mx.nd.array(np.arange(24).reshape(2, 3, 4))
    assert a[1].shape == (3, 4)
    assert a[1, 2].shape == (4,)
    assert float(a[1, 2, 3].asscalar()) == 23
    assert a[:, 1:3].shape == (2, 2, 4)
    assert a[0, :, ::2].shape == (3, 2)
    # advanced indexing with NDArray
    idx = mx.nd.array([0, 1], dtype="int32")
    assert a[idx].shape == (2, 3, 4)


def test_setitem():
    a = mx.nd.zeros((3, 3))
    a[1] = 5.0
    assert a.asnumpy()[1].tolist() == [5, 5, 5]
    a[0, 0] = 2.0
    assert float(a[0, 0].asscalar()) == 2.0
    a[:] = np.ones((3, 3))
    assert a.asnumpy().sum() == 9
    b = mx.nd.zeros((2, 2))
    b[:] = mx.nd.ones((2, 2)) * 4
    assert b.asnumpy().sum() == 16


def test_reshape_codes():
    a = mx.nd.zeros((2, 3, 4))
    assert a.reshape((6, 4)).shape == (6, 4)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert mx.nd.Reshape(a, shape=(-3, 4)).shape == (6, 4)
    assert mx.nd.Reshape(a, shape=(-2,)).shape == (2, 3, 4)
    assert mx.nd.Reshape(a, shape=(-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)


def test_copy_and_context():
    a = mx.nd.ones((2, 2))
    b = a.copy()
    b += 1
    assert a.asnumpy().sum() == 4  # copy is deep
    c = mx.nd.zeros((2, 2))
    a.copyto(c)
    assert c.asnumpy().sum() == 4
    d = a.as_in_context(mx.cpu())
    assert d.context.device_type == "cpu"


def test_dtype_cast():
    a = mx.nd.ones((2, 2))
    b = a.astype("float16")
    assert b.dtype == np.float16
    c = a.astype("bfloat16")
    assert str(c.dtype) == "bfloat16"
    assert_almost_equal(c.astype("float32"), np.ones((2, 2)))


def test_wait_and_scalar():
    a = mx.nd.ones((2,))
    a.wait_to_read()
    mx.nd.waitall()
    s = mx.nd.array([3.5])
    assert float(s.asscalar()) == 3.5
    with pytest.raises(mx.MXNetError):
        mx.nd.ones((2,)).asscalar()


def test_save_load(tmp_path):
    fname = str(tmp_path / "nd.params")
    a = mx.nd.uniform(shape=(3, 4))
    b = mx.nd.arange(0, 5)
    mx.nd.save(fname, {"a": a, "b": b})
    loaded = mx.nd.load(fname)
    assert set(loaded) == {"a", "b"}
    assert_almost_equal(loaded["a"], a)
    assert_almost_equal(loaded["b"], b)
    mx.nd.save(fname, [a, b])
    lst = mx.nd.load(fname)
    assert len(lst) == 2
    assert_almost_equal(lst[0], a)


def test_iteration_len():
    a = mx.nd.array(np.arange(6).reshape(3, 2))
    assert len(a) == 3
    rows = [r.asnumpy().tolist() for r in a]
    assert rows == [[0, 1], [2, 3], [4, 5]]


def test_attached_methods():
    a = mx.nd.array([[1., 2.], [3., 4.]])
    assert float(a.sum().asscalar()) == 10
    assert float(a.mean().asscalar()) == 2.5
    assert float(a.max().asscalar()) == 4
    assert a.sum(axis=1).asnumpy().tolist() == [3, 7]
    assert a.clip(2, 3).asnumpy().tolist() == [[2, 2], [3, 3]]
    assert a.sqrt().shape == (2, 2)
    assert a.T.shape == (2, 2)
    assert a.expand_dims(0).shape == (1, 2, 2)
    assert a.flatten().shape == (2, 2)


def test_sparse_roundtrip():
    dense = np.zeros((4, 3), np.float32)
    dense[1] = [1, 2, 3]
    dense[3] = [4, 5, 6]
    rsp = mx.nd.array(dense).tostype("row_sparse")
    assert rsp.stype == "row_sparse"
    assert rsp.indices.asnumpy().tolist() == [1, 3]
    assert_almost_equal(rsp.todense(), dense)
    csr = mx.nd.array(dense).tostype("csr")
    assert csr.stype == "csr"
    assert_almost_equal(csr.todense(), dense)


def test_sparse_save_load(tmp_path):
    fname = str(tmp_path / "sp.params")
    dense = np.zeros((4, 3), np.float32)
    dense[2] = [7, 8, 9]
    rsp = mx.nd.array(dense).tostype("row_sparse")
    mx.nd.save(fname, {"w": rsp})
    loaded = mx.nd.load(fname)
    assert loaded["w"].stype == "row_sparse"
    assert_almost_equal(loaded["w"].todense(), dense)
