"""Metric-zoo DEPTH tier: every EvalMetric checked against hand-computed
or sklearn-free closed-form values (ref: tests/python/unittest/
test_metric.py — each metric pinned on small literal cases).
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import metric

ND = mx.nd.array


def test_accuracy_from_logits_and_labels():
    m = metric.Accuracy()
    preds = ND(np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]],
                        np.float32))
    labels = ND(np.array([1, 1, 1], np.float32))
    m.update([labels], [preds])
    assert m.get()[1] == pytest.approx(2 / 3)
    m.reset()
    assert np.isnan(m.get()[1])


def test_topk_accuracy():
    m = metric.TopKAccuracy(top_k=2)
    preds = ND(np.array([[0.6, 0.3, 0.1],       # top2 = {0,1}
                         [0.1, 0.2, 0.7],       # top2 = {1,2}
                         [0.2, 0.5, 0.3]],      # top2 = {1,2}
                        np.float32))
    labels = ND(np.array([1, 0, 2], np.float32))
    m.update([labels], [preds])
    assert m.get()[1] == pytest.approx(2 / 3)


def test_f1_binary_closed_form():
    m = metric.F1()
    # preds prob of class1; threshold 0.5
    preds = ND(np.array([[0.7, 0.3], [0.2, 0.8], [0.4, 0.6], [0.9, 0.1]],
                        np.float32))
    labels = ND(np.array([0, 1, 0, 1], np.float32))
    m.update([labels], [preds])
    # predictions: [0, 1, 1, 0]; tp=1 fp=1 fn=1 -> P=R=0.5 -> F1=0.5
    assert m.get()[1] == pytest.approx(0.5)


def test_mcc_matches_formula():
    m = metric.MCC()
    preds = ND(np.array([[0.2, 0.8], [0.7, 0.3], [0.3, 0.7], [0.6, 0.4],
                         [0.1, 0.9]], np.float32))
    labels = ND(np.array([1, 0, 0, 0, 1], np.float32))
    m.update([labels], [preds])
    tp, tn, fp, fn = 2, 2, 1, 0
    want = (tp * tn - fp * fn) / np.sqrt(
        (tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
    assert m.get()[1] == pytest.approx(want, rel=1e-6)


def test_perplexity_uniform_is_vocab_size():
    vocab = 8
    m = metric.Perplexity(ignore_label=None)
    preds = ND(np.full((5, vocab), 1.0 / vocab, np.float32))
    labels = ND(np.arange(5, dtype=np.float32))
    m.update([labels], [preds])
    assert m.get()[1] == pytest.approx(vocab, rel=1e-5)


def test_perplexity_ignore_label():
    m = metric.Perplexity(ignore_label=0)
    preds = ND(np.array([[0.5, 0.5], [1e-9, 1.0 - 1e-9]], np.float32))
    labels = ND(np.array([0, 1], np.float32))  # first row ignored
    m.update([labels], [preds])
    assert m.get()[1] == pytest.approx(1.0, rel=1e-4)


def test_regression_metrics_closed_form():
    p = np.array([[1.0, 2.0], [3.0, 5.0]], np.float32)
    t = np.array([[2.0, 2.0], [3.0, 1.0]], np.float32)
    mae = metric.MAE()
    mae.update([ND(t)], [ND(p)])
    assert mae.get()[1] == pytest.approx(np.abs(p - t).mean())
    mse = metric.MSE()
    mse.update([ND(t)], [ND(p)])
    assert mse.get()[1] == pytest.approx(((p - t) ** 2).mean())
    rmse = metric.RMSE()
    rmse.update([ND(t)], [ND(p)])
    assert rmse.get()[1] == pytest.approx(
        np.sqrt(((p - t) ** 2).mean()), rel=1e-6)


def test_cross_entropy_and_nll():
    preds = np.array([[0.25, 0.75], [0.9, 0.1]], np.float32)
    labels = np.array([1, 0], np.float32)
    ce = metric.CrossEntropy()
    ce.update([ND(labels)], [ND(preds)])
    want = -(np.log(0.75) + np.log(0.9)) / 2
    assert ce.get()[1] == pytest.approx(want, rel=1e-5)
    nll = metric.NegativeLogLikelihood()
    nll.update([ND(labels)], [ND(preds)])
    assert nll.get()[1] == pytest.approx(want, rel=1e-5)


def test_pearson_correlation_exact():
    x = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    y = 2 * x + 1  # perfectly correlated
    m = metric.PearsonCorrelation()
    m.update([ND(y)], [ND(x)])
    assert m.get()[1] == pytest.approx(1.0, rel=1e-5)
    m2 = metric.PearsonCorrelation()
    m2.update([ND(-y)], [ND(x)])
    assert m2.get()[1] == pytest.approx(-1.0, rel=1e-5)


def test_loss_metric_averages_batches():
    m = metric.Loss()
    m.update(None, [ND(np.array([2.0, 4.0], np.float32))])
    m.update(None, [ND(np.array([6.0], np.float32))])
    assert m.get()[1] == pytest.approx(4.0)


def test_custom_metric_and_composite():
    def double_mae(label, pred):
        return 2 * np.abs(label - pred).mean()

    cm = metric.CustomMetric(double_mae, name="dmae")
    lbl = np.array([1.0, 3.0], np.float32)
    prd = np.array([2.0, 5.0], np.float32)
    cm.update([ND(lbl)], [ND(prd)])
    assert cm.get()[1] == pytest.approx(3.0)

    comp = metric.CompositeEvalMetric()
    comp.add(metric.MAE())
    comp.add(metric.MSE())
    comp.update([ND(lbl)], [ND(prd)])
    names, vals = comp.get()
    assert "mae" in names[0] and vals[0] == pytest.approx(1.5)
    assert "mse" in names[1] and vals[1] == pytest.approx(2.5)


def test_metric_create_by_name_registry():
    for name, cls in [("acc", metric.Accuracy), ("mae", metric.MAE),
                      ("mse", metric.MSE), ("rmse", metric.RMSE)]:
        m = metric.create(name)
        assert isinstance(m, cls), name


def test_accuracy_with_flat_class_preds():
    """Reference behavior: 1-D predictions are taken as class ids."""
    m = metric.Accuracy()
    m.update([ND(np.array([1, 0, 2], np.float32))],
             [ND(np.array([1, 1, 2], np.float32))])
    assert m.get()[1] == pytest.approx(2 / 3)
