"""ONNX export/import tests (ref: tests/python-pytest/onnx in the
reference; VERDICT r2 item 6).

Round-trip validation: export zoo models to ModelProto bytes, re-import
through the generic wire-format decoder into a fresh Symbol, and compare
forward outputs against the original network. When the real ``onnx``
package is installed, additionally run onnx.checker + onnxruntime parity.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.contrib.onnx import export_model, import_model_bytes


def _roundtrip(model_name, in_shape=(1, 3, 64, 64), tol=1e-4):
    from mxtpu.gluon.model_zoo import vision

    net = vision.get_model(model_name, classes=10)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0)
                    .uniform(-1, 1, in_shape).astype(np.float32))
    ref = net(x).asnumpy()

    blob = export_model(net)
    assert isinstance(blob, bytes) and len(blob) > 1000

    sym, arg_params, aux_params = import_model_bytes(blob)
    args = dict(arg_params)
    args["data"] = x
    exe = sym.bind(args=args, aux_states=aux_params, grad_req="null")
    got = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=tol, atol=tol)
    return blob


def test_mlp_roundtrip(tmp_path):
    from mxtpu import gluon

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu"))
        net.add(gluon.nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0)
                    .uniform(-1, 1, (2, 8)).astype(np.float32))
    ref = net(x).asnumpy()
    path = str(tmp_path / "mlp.onnx")
    from mxtpu.contrib.onnx import export_model as em, import_model
    em(net, path=path)
    sym, arg_params, aux_params = import_model(path)
    args = dict(arg_params)
    args["data"] = x
    got = sym.bind(args=args, aux_states=aux_params, grad_req="null") \
        .forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_resnet50_roundtrip():
    # 53 conv/BN layers of f32 accumulate ~5e-3 fusion-order drift between
    # the two (differently-structured, hence differently-fused) graphs
    _roundtrip("resnet50_v1", tol=2e-2)


def test_mobilenet_roundtrip():
    _roundtrip("mobilenet1_0")


def test_mobilenet_v2_roundtrip():
    """Exercises Clip (relu6) with initializer-borne min/max."""
    _roundtrip("mobilenet_v2_1_0")


def test_exported_bytes_are_wellformed_protobuf():
    """Structural check of the wire format: every length-delimited field
    parses, the graph has nodes/initializers/inputs/outputs, and tensor
    raw_data sizes match their dims."""
    from mxtpu import gluon
    from mxtpu.contrib.onnx import proto

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((1, 6)))
    blob = export_model(net)
    m = proto.decode(blob)
    assert m[1] == [8]  # ir_version
    opset = proto.decode(m[8][0])
    assert int(opset[2][0]) == 13
    g = proto.decode(m[7][0])
    assert g.get(1) and g.get(5) and g.get(11) and g.get(12)
    for tb in g[5]:
        t = proto.decode(tb)
        dims = [int(d) for d in t.get(1, [])]
        assert len(t[9][0]) == int(np.prod(dims or [1])) * 4


def test_onnx_checker_if_available():
    onnx = pytest.importorskip("onnx")
    from mxtpu import gluon

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((1, 6)))
    blob = export_model(net)
    model = onnx.load_model_from_string(blob)
    onnx.checker.check_model(model)
