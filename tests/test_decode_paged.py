"""Paged KV cache, prefix reuse, speculative decoding
(mxtpu/serving/decode, ISSUE 16):

* paged-vs-rowed token parity: greedy streams identical under the
  block-pool layout (eos / max_new stopping, joiners entering a running
  cohort) and identical to the eager full-prefix reference;
* page lifecycle: pages allocate as sequences grow, return to the free
  list on completion, and the next admission reuses them — page gauges
  (`serving.kv_page_free/resident/shared`, `serving.kv_resident_tokens`)
  track the pool;
* pool exhaustion: admission AND mid-decode growth shed loud
  (`QueueFull` / `serving.shed{kv_residency}`) with the survivor's
  stream untouched and the ledger balanced after;
* prefix cache: refcounted read-only pages under shared-then-diverging
  prompts — hit/miss counters, shared-page gauge, cache-only pages
  evict under pressure instead of shedding, token parity throughout;
* speculative decoding: draft==target and divergent-draft streams both
  bit-identical to plain greedy, strictly fewer cohort steps, accept
  counters; int8 spec == int8 paged; k+1 committed in one macro;
* replay discipline: ZERO post-warmup compiles at `serving.decode` AND
  `serving.draft`, zero d2h inside the armed span;
* teardown ledger balance from every path: wedge watchdog (fake
  clock), crash barrier, close() — no page leaks, free list whole.
"""
import os
import time

import numpy as np
import pytest

from mxtpu import resilience, telemetry
from mxtpu.base import MXNetError
from mxtpu.serving import (BucketSpec, DeadlineExceeded, DecodeEngine,
                           KVCacheAccountant, QueueFull)

from test_decode import (VOCAB, DIM, MAX_LEN, FakeClock,  # noqa: F401
                         _pspec, _reference_greedy, _run_all, model)

PT = 4


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_TELEMETRY", "MXTPU_RETRACE_BUDGET",
                "MXTPU_FAULT_INJECT", "MXTPU_SERVE_INT8",
                "MXTPU_KV_PAGE_TOKENS", "MXTPU_PREFIX_CACHE",
                "MXTPU_SPEC_DECODE_K", "MXTPU_SERVE_KV_OVERCOMMIT",
                "MXTPU_SERVE_DISPATCH_TIMEOUT_MS", "MXTPU_FLIGHT_DIR"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    yield
    telemetry.reset()
    resilience.reset_faults()


def _pengine(model, slots=2, eos=None, int8=False, accountant=None,
             clock=time.monotonic, timeout_ms=None, max_len=32,
             page_tokens=PT, pool_pages=None, prefix=False,
             draft_model=None, spec_k=None):
    return DecodeEngine(model, _pspec(),
                        BucketSpec.pow2(decode_slots=slots),
                        max_len=max_len, eos_id=eos, int8=int8,
                        continuous=True, accountant=accountant,
                        clock=clock, dispatch_timeout_ms=timeout_ms,
                        page_tokens=page_tokens, pool_pages=pool_pages,
                        prefix_cache=prefix or None,
                        draft_model=draft_model, spec_k=spec_k,
                        warmup=True, start=False)


def _poll_all(eng, futs, limit=4000):
    """Drive to completion WITHOUT harvesting results — for workloads
    where some futures hold a shed exception."""
    n = 0
    while not all(f.done() for f in futs) and n < limit:
        eng.poll()
        n += 1
    assert all(f.done() for f in futs)


def _pool_balanced(eng):
    """Every page home, no dangling refs: the teardown-ledger invariant
    all paths must restore."""
    return (len(eng._free_pages) == eng._pool_pages
            and int(eng._page_ref[1:].sum()) == 0)


# ----------------------------------------------------- parity with rowed
def test_paged_matches_eager_reference(model):
    eng = _pengine(model)
    prompt = np.array([3, 1, 4, 1, 5], np.int32)
    out = _run_all(eng, [eng.submit(prompt, max_new=9)])[0]
    assert out.tolist() == _reference_greedy(model, prompt, 9)
    assert _pool_balanced(eng)


def test_paged_equals_rowed_with_joiners(model):
    """More requests than slots: joiners land in freed slots mid-run —
    the paged gather/scatter step must reproduce the rowed streams
    token for token (stopping included: eos on one, budget on rest)."""
    rng = np.random.RandomState(3)
    reqs = [(rng.randint(0, VOCAB, size=rng.randint(2, 9))
             .astype(np.int32), int(rng.randint(2, 9)))
            for _ in range(6)]

    def run(page_tokens):
        eng = DecodeEngine(model, _pspec(),
                           BucketSpec.pow2(decode_slots=2),
                           max_len=32, eos_id=7, continuous=True,
                           page_tokens=page_tokens, warmup=True,
                           start=False)
        outs = _run_all(eng, [eng.submit(p, max_new=m) for p, m in reqs])
        return eng, outs

    peng, paged = run(PT)
    _, rowed = run(0)
    for a, b in zip(paged, rowed):
        assert a.tolist() == b.tolist()
    assert _pool_balanced(peng)


def test_paged_eos_and_budget_stopping(model):
    eng = _pengine(model, eos=5)
    prompt = np.arange(4).astype(np.int32)
    out = _run_all(eng, [eng.submit(prompt, max_new=12)])[0]
    assert out.tolist() == _reference_greedy(model, prompt, 12, eos=5)
    if 5 in out.tolist():
        assert out.tolist().index(5) == len(out) - 1


# --------------------------------------------------------- page lifecycle
def test_page_free_and_reuse(model):
    eng = _pengine(model, slots=2)
    p0 = len(eng._free_pages)
    fut = eng.submit(np.arange(6).astype(np.int32), max_new=6)
    eng.poll()   # prefill -> slot, prompt pages mapped
    held = p0 - len(eng._free_pages)
    assert held >= -(-6 // PT)
    first_pages = list(eng._slots[0].pages)
    _run_all(eng, [fut])
    # completion returned every page
    assert len(eng._free_pages) == p0 and _pool_balanced(eng)
    # the next admission draws from the same pool — pages recycle
    fut2 = eng.submit(np.arange(6).astype(np.int32), max_new=6)
    eng.poll()
    assert set(eng._slots[0].pages) & set(first_pages)
    _run_all(eng, [fut2])
    assert _pool_balanced(eng)


def test_page_gauges_track_pool(model):
    eng = _pengine(model, slots=2)
    fut = eng.submit(np.arange(5).astype(np.int32), max_new=6)
    eng.poll()
    free = telemetry.gauge_value("serving.kv_page_free")
    resident = telemetry.gauge_value("serving.kv_page_resident")
    assert resident >= 2 and free + resident == eng._pool_pages
    assert telemetry.gauge_value("serving.kv_resident_tokens") >= 5
    _run_all(eng, [fut])
    assert telemetry.gauge_value("serving.kv_page_resident") == 0
    assert telemetry.gauge_value("serving.kv_page_free") == eng._pool_pages
    assert telemetry.gauge_value("serving.kv_resident_tokens") == 0


# -------------------------------------------------------- pool exhaustion
def test_pool_exhaustion_sheds_at_admission(model):
    # pool = exactly one max_len sequence's pages: the second admission
    # finds the free list dry mid-prefill and sheds loud
    eng = _pengine(model, slots=2, pool_pages=32 // PT)
    hog = eng.submit(np.arange(12).astype(np.int32), max_new=18)
    eng.poll()
    # grow the hog until fewer than a prompt's worth of pages remain,
    # so the late arrival's slot insert finds the free list dry
    n = 0
    while len(eng._free_pages) > 2 and n < 2000:
        eng.poll()
        n += 1
    shed = eng.submit(np.arange(12).astype(np.int32), max_new=4)
    _poll_all(eng, [hog, shed])
    with pytest.raises(QueueFull, match="kv_residency"):
        shed.result(timeout=0)
    assert telemetry.value("serving.shed", tag="kv_residency") >= 1
    assert hog.result(timeout=0).tolist() == \
        _reference_greedy(model, np.arange(12), 18)
    assert _pool_balanced(eng)


def test_pool_exhaustion_mid_decode_sheds_survivor_exact(model):
    # two growing sequences against a pool that cannot hold both at
    # full length: one sheds MID-DECODE when its next page allocation
    # fails; the survivor's stream is untouched and the ledger balances
    eng = _pengine(model, slots=2, pool_pages=8)
    pa = np.arange(7).astype(np.int32)
    pb = (np.arange(7) + 9).astype(np.int32)
    fa = eng.submit(pa, max_new=12)
    fb = eng.submit(pb, max_new=12)
    _poll_all(eng, [fa, fb])
    results = {}
    for name, fut, prompt in (("a", fa, pa), ("b", fb, pb)):
        try:
            results[name] = fut.result(timeout=0)
        except QueueFull:
            results[name] = None
    shed = [k for k, v in results.items() if v is None]
    assert len(shed) == 1
    assert telemetry.value("serving.shed", tag="kv_residency") == 1
    survivor = "b" if shed == ["a"] else "a"
    prompt = pb if survivor == "b" else pa
    assert results[survivor].tolist() == \
        _reference_greedy(model, prompt, 12)
    assert _pool_balanced(eng)


# ----------------------------------------------------------- prefix cache
def test_prefix_hit_skips_and_matches(model):
    tmpl = np.array([2, 9, 4, 11, 6, 1, 8, 3], np.int32)   # 2 full chunks
    eng = _pengine(model, slots=2, prefix=True)
    out1 = _run_all(eng, [eng.submit(tmpl, max_new=5)])[0]
    assert telemetry.value("serving.prefix.misses") >= 1
    hits0 = telemetry.value("serving.prefix.hits")
    out2 = _run_all(eng, [eng.submit(tmpl, max_new=5)])[0]
    assert telemetry.value("serving.prefix.hits") > hits0
    # the hit path skipped prefill work but NOT correctness
    ref = _reference_greedy(model, tmpl, 5)
    assert out1.tolist() == ref and out2.tolist() == ref
    # cache pins survive completion: pinned pages stay off the free list
    assert len(eng._free_pages) < eng._pool_pages
    assert int(eng._page_ref[1:].sum()) == len(eng._prefix)


def test_prefix_refcount_shared_then_diverging(model):
    tmpl = np.array([2, 9, 4, 11, 6, 1, 8, 3], np.int32)
    sfx_a = np.array([40, 41], np.int32)
    sfx_b = np.array([42, 43, 44], np.int32)
    eng = _pengine(model, slots=2, prefix=True)
    # publish the template's chunks
    _run_all(eng, [eng.submit(tmpl, max_new=3)])
    fa = eng.submit(np.concatenate([tmpl, sfx_a]), max_new=4)
    fb = eng.submit(np.concatenate([tmpl, sfx_b]), max_new=4)
    eng.poll()
    eng.poll()
    # both live: the template pages are cache-pinned AND doubly shared
    assert (telemetry.gauge_value("serving.kv_page_shared") or 0) >= 2
    assert int(np.sum(eng._page_ref[1:] >= 3)) >= 1
    outs = _run_all(eng, [fa, fb])
    assert outs[0].tolist() == _reference_greedy(
        model, np.concatenate([tmpl, sfx_a]), 4)
    assert outs[1].tolist() == _reference_greedy(
        model, np.concatenate([tmpl, sfx_b]), 4)
    # divergent suffixes never wrote a shared page: refs fall back to
    # the cache's own pins only
    assert int(eng._page_ref[1:].sum()) == len(eng._prefix)


def test_prefix_cache_evicts_under_pressure_not_shed(model):
    # fill the cache, then admit a stranger that needs the pinned pages:
    # cache-only pages evict (LRU) instead of shedding the stranger
    eng = _pengine(model, slots=1, prefix=True, pool_pages=8)
    tmpl = np.array([2, 9, 4, 11, 6, 1, 8, 3], np.int32)
    _run_all(eng, [eng.submit(tmpl, max_new=3)])
    cached = len(eng._prefix)
    assert cached >= 1
    stranger = (np.arange(12) + 20).astype(np.int32)
    # the stranger grows to 30 tokens = 8 pages — the WHOLE pool — so it
    # can only complete if the cache's 2 pinned pages evict on demand:
    # finishing with zero sheds IS the eviction proof
    out = _run_all(eng, [eng.submit(stranger, max_new=18)], limit=4000)[0]
    assert out.tolist() == _reference_greedy(model, stranger, 18)
    assert telemetry.value("serving.shed", tag="kv_residency") == 0
    # pins stayed consistent: every surviving entry still holds exactly
    # its one cache reference
    assert int(eng._page_ref[1:].sum()) == len(eng._prefix)


# ---------------------------------------------------- speculative decoding
def test_spec_matches_greedy_and_takes_fewer_steps(model):
    rng = np.random.RandomState(5)
    reqs = [(rng.randint(0, VOCAB, size=rng.randint(2, 9))
             .astype(np.int32), 12) for _ in range(3)]

    def run(spec):
        telemetry.reset()
        eng = _pengine(model, slots=2,
                       draft_model=model if spec else None,
                       spec_k=3 if spec else None)
        outs = _run_all(eng, [eng.submit(p, max_new=m) for p, m in reqs])
        return eng, outs, telemetry.value("serving.decode.steps")

    peng, plain, steps_plain = run(False)
    seng, spec, steps_spec = run(True)
    for a, b in zip(plain, spec):
        assert a.tolist() == b.tolist()
    assert steps_spec < steps_plain
    assert _pool_balanced(peng) and _pool_balanced(seng)


def test_spec_accept_counters_near_perfect_selfdraft(model):
    # draft == target: with the d_k row backfilled every macro, the only
    # non-accepts are final-macro budget truncation
    eng = _pengine(model, draft_model=model, spec_k=3)
    prompt = np.array([1, 2, 3], np.int32)
    out = _run_all(eng, [eng.submit(prompt, max_new=17)])[0]
    assert out.tolist() == _reference_greedy(model, prompt, 17)
    proposed = telemetry.value("serving.decode.spec_proposed")
    accepted = telemetry.value("serving.decode.spec_accepted")
    assert proposed > 0
    assert accepted / proposed >= 0.75
    assert _pool_balanced(eng)


def test_spec_divergent_draft_still_exact(model):
    # a draft that disagrees (different seed) costs acceptance, NEVER
    # tokens: the commit rule truncates at the first mismatch
    import serve_bench as sb
    other = sb.build_decode_model(vocab=VOCAB, dim=DIM, max_len=MAX_LEN,
                                  seed=99)
    eng = _pengine(model, draft_model=other, spec_k=3)
    prompt = np.array([4, 4, 2, 7], np.int32)
    out = _run_all(eng, [eng.submit(prompt, max_new=10)])[0]
    assert out.tolist() == _reference_greedy(model, prompt, 10)
    proposed = telemetry.value("serving.decode.spec_proposed")
    accepted = telemetry.value("serving.decode.spec_accepted")
    assert 0 <= accepted < proposed
    assert _pool_balanced(eng)


def test_spec_int8_matches_int8_paged(model):
    # int8 engines chain the verify through the SAME per-row quantize
    # grids the step path writes, so int8+spec == int8 paged bit for bit
    prompt = np.array([6, 3, 9, 1], np.int32)

    def run(spec):
        eng = _pengine(model, int8=True,
                       draft_model=model if spec else None,
                       spec_k=3 if spec else None)
        return _run_all(eng, [eng.submit(prompt, max_new=10)])[0]

    assert run(False).tolist() == run(True).tolist()


def test_spec_requires_paged_and_draft(model):
    with pytest.raises(MXNetError, match="needs paged"):
        _pengine(model, page_tokens=0, draft_model=model, spec_k=3)
    with pytest.raises(MXNetError, match="draft_model"):
        _pengine(model, spec_k=3)
    with pytest.raises(MXNetError, match="power of two"):
        _pengine(model, page_tokens=3)
    with pytest.raises(MXNetError, match="one "):
        _pengine(model, prefix=True, draft_model=model, spec_k=2)


# ------------------------------------------------------- replay discipline
def test_zero_postwarmup_compiles_and_no_d2h_both_sites(model):
    eng = _pengine(model, slots=2, draft_model=model, spec_k=3)
    c0 = (telemetry.retrace_stats(eng._site) or {}).get("compiles", 0)
    d0 = (telemetry.retrace_stats(eng._draft_site) or {}).get(
        "compiles", 0)
    rng = np.random.RandomState(11)
    futs = [eng.submit(rng.randint(0, VOCAB, size=rng.randint(2, 12))
                       .astype(np.int32), max_new=int(rng.randint(2, 11)))
            for _ in range(5)]
    _run_all(eng, futs)
    assert (telemetry.retrace_stats(eng._site) or {}).get(
        "compiles", 0) == c0
    assert (telemetry.retrace_stats(eng._draft_site) or {}).get(
        "compiles", 0) == d0
    assert telemetry.value("serving.decode.d2h") == 0


# ------------------------------------------------- teardown ledger balance
def test_wedge_teardown_releases_pages(model, monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "decode_wedge@1")
    clock = FakeClock()
    acct = KVCacheAccountant(overcommit=50.0)
    eng = _pengine(model, slots=2, clock=clock, timeout_ms=100.0,
                   accountant=acct)
    stuck = [eng.submit(np.arange(3).astype(np.int32), max_new=6)
             for _ in range(2)]
    eng.poll()          # step 0 clean
    eng.poll()          # step 1 wedges
    clock.advance(0.2)
    eng.poll()          # watchdog trips: casualties torn down
    for f in stuck:
        assert f.done()
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=0)
    # every page came home through the one teardown ledger
    assert _pool_balanced(eng)
    snap = acct.snapshot()["r0"]
    assert snap["live"] == 0 and snap["queued"] == 0
    assert acct.resident_bytes("r0") == 0
    # and the engine still serves correctly on recycled pages
    out = _run_all(eng, [eng.submit(np.arange(4).astype(np.int32),
                                    max_new=3)])[0]
    assert out.tolist() == _reference_greedy(model, np.arange(4), 3)
    assert _pool_balanced(eng)


def test_crash_barrier_releases_pages(model, monkeypatch):
    acct = KVCacheAccountant(overcommit=50.0)
    eng = _pengine(model, slots=1, accountant=acct)
    eng.start()
    try:
        monkeypatch.setattr(
            eng, "_harvest",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
        fut = eng.submit(np.arange(3).astype(np.int32), max_new=4)
        with pytest.raises(MXNetError, match="decode loop crashed"):
            fut.result(timeout=30.0)
    finally:
        eng.close(timeout=5.0)
    assert _pool_balanced(eng)
    snap = acct.snapshot()["r0"]
    assert snap["live"] == 0 and snap["queued"] == 0
    assert acct.resident_bytes("r0") == 0


def test_close_releases_pages_and_prefix_pins(model):
    acct = KVCacheAccountant(overcommit=50.0)
    eng = _pengine(model, slots=2, prefix=True, accountant=acct)
    tmpl = np.array([2, 9, 4, 11, 6, 1, 8, 3], np.int32)
    _run_all(eng, [eng.submit(tmpl, max_new=3)])
    assert len(eng._prefix) >= 1          # cache holds pins
    eng.submit(np.arange(5).astype(np.int32), max_new=6)
    eng.poll()                            # one live slot holding pages
    eng.close(timeout=5.0)
    assert len(eng._prefix) == 0
    assert _pool_balanced(eng)
    assert acct.resident_bytes("r0") == 0


def test_env_lever_page_tokens(model, monkeypatch):
    monkeypatch.setenv("MXTPU_KV_PAGE_TOKENS", "8")
    eng = DecodeEngine(model, _pspec(),
                       BucketSpec.pow2(decode_slots=2),
                       max_len=32, warmup=True, start=False)
    assert eng._pt == 8
    prompt = np.arange(5).astype(np.int32)
    out = _run_all(eng, [eng.submit(prompt, max_new=6)])[0]
    assert out.tolist() == _reference_greedy(model, prompt, 6)
    assert _pool_balanced(eng)
