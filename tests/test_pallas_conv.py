"""Pallas fused implicit-GEMM conv (mxtpu/ops/pallas/conv.py).

Tier-1 runs the ACTUAL kernel through the Pallas interpreter
(MXTPU_PALLAS_CONV_INTERPRET=1) on CPU — fwd, input-grad and weight-grad
are pinned against ``lax.conv_general_dilated`` + jax autodiff, f32 at
exact tolerance and bf16 at ulp tolerance, across odd spatial sizes and
stride 2. The shape gate (route MXU-underfilled convs, leave filled ones
on XLA) is asserted through ``pallas_applicable`` reasons and the
``DISPATCH_STATS`` counters; the 0/1 lever A/B is pinned through
``registry.policy_key`` and a hybridized CachedOp recompile."""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
import pytest

from mxtpu.ops.conv_acc import conv_fast
from mxtpu.ops.pallas import conv as pc

DN = ("NHWC", "HWIO", "NHWC")


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in ("MXTPU_PALLAS_CONV", "MXTPU_PALLAS_CONV_INTERPRET",
                "MXTPU_CONV_ACC", "MXTPU_CONV_IM2COL"):
        monkeypatch.delenv(var, raising=False)
    pc.reset_dispatch_stats()


@pytest.fixture
def interp(monkeypatch):
    """Run the real kernel via the Pallas interpreter on CPU."""
    monkeypatch.setenv("MXTPU_PALLAS_CONV_INTERPRET", "1")


def _ref(x, w, s, pad):
    return lax.conv_general_dilated(x, w, (s, s), pad,
                                    dimension_numbers=DN)


# shapes: stem-like 7x7s2 odd-H, 3x3s1 odd, 1x1 (pure GEMM), strided 1x1
# (downsample shortcut), strided 3x3 — every class the gate routes
SHAPES = [
    (15, 3, 8, 7, 2, 3),
    (9, 4, 8, 3, 1, 1),
    (8, 16, 8, 1, 1, 0),
    (9, 8, 8, 1, 2, 0),
    (11, 4, 8, 3, 2, 1),
]


@pytest.mark.parametrize("h,cin,cout,k,s,p", SHAPES)
def test_kernel_fwd_and_grads_match_xla_f32(h, cin, cout, k, s, p, interp):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, h, h, cin), jnp.float32)
    w = jnp.asarray(rng.randn(k, k, cin, cout) * 0.1, jnp.float32)
    pad = ((p, p), (p, p))
    out = pc.fused_conv(x, w, (s, s), pad)
    assert pc.DISPATCH_STATS["pallas"] >= 1  # the kernel, not the fallback
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, w, s, pad)),
                               rtol=1e-5, atol=1e-5)
    gf = jax.grad(lambda x_, w_: jnp.sum(
        pc.fused_conv(x_, w_, (s, s), pad) ** 2), argnums=(0, 1))(x, w)
    gp = jax.grad(lambda x_, w_: jnp.sum(
        _ref(x_, w_, s, pad) ** 2), argnums=(0, 1))(x, w)
    for a, b in zip(gf, gp):  # input grad, then weight grad
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("h,cin,cout,k,s,p", [SHAPES[0], SHAPES[1],
                                              SHAPES[3]])
def test_kernel_fwd_and_grads_match_xla_bf16(h, cin, cout, k, s, p, interp):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, h, h, cin), jnp.bfloat16)
    w = jnp.asarray(rng.randn(k, k, cin, cout) * 0.1, jnp.bfloat16)
    pad = ((p, p), (p, p))
    out = pc.fused_conv(x, w, (s, s), pad)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(_ref(x, w, s, pad), np.float32),
                               rtol=2e-2, atol=2e-2)
    gf = jax.grad(lambda x_, w_: jnp.sum(pc.fused_conv(
        x_, w_, (s, s), pad).astype(jnp.float32) ** 2), argnums=(0, 1))(x, w)
    gp = jax.grad(lambda x_, w_: jnp.sum(
        _ref(x_, w_, s, pad).astype(jnp.float32) ** 2), argnums=(0, 1))(x, w)
    for a, b in zip(gf, gp):
        assert a.dtype == b.dtype
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-2, atol=5e-2)


def test_fused_epilogue_matches_composition(interp):
    """conv + scale + bias + residual + relu in ONE kernel vs the op-by-op
    composition, including gradients for every differentiable input."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 9, 9, 4), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 4, 8) * 0.1, jnp.float32)
    sc = jnp.asarray(rng.randn(8), jnp.float32)
    bi = jnp.asarray(rng.randn(8), jnp.float32)
    res = jnp.asarray(rng.randn(2, 9, 9, 8), jnp.float32)
    pad = ((1, 1), (1, 1))

    def fused(x, w, sc, bi, res):
        return pc.fused_conv(x, w, (1, 1), pad, scale=sc, bias=bi,
                             residual=res, relu=True)

    def ref(x, w, sc, bi, res):
        return jnp.maximum(_ref(x, w, 1, pad) * sc + bi + res, 0.0)

    np.testing.assert_allclose(np.asarray(fused(x, w, sc, bi, res)),
                               np.asarray(ref(x, w, sc, bi, res)),
                               rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda *a: jnp.sum(fused(*a) ** 2),
                  argnums=(0, 1, 2, 3, 4))(x, w, sc, bi, res)
    g2 = jax.grad(lambda *a: jnp.sum(ref(*a) ** 2),
                  argnums=(0, 1, 2, 3, 4))(x, w, sc, bi, res)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_shape_gate_routes_underfilled_and_declines_filled():
    """The PERF.md gate, executable: stem (C_out=64), 1x1 pointwise
    (K=64 or C_out=64) and stage-2 small-C spatials route; a conv with
    BOTH im2col K and C_out at/above the 128 lanes stays on XLA."""
    def ok(shape_x, shape_w, strides=(1, 1)):
        x = jnp.zeros(shape_x, jnp.bfloat16)
        w = jnp.zeros(shape_w, jnp.bfloat16)
        return pc.pallas_applicable(x, w, strides, ((0, 0), (0, 0)),
                                    (1, 1), (1, 1), DN, 1)

    assert ok((1, 224, 224, 3), (7, 7, 3, 64), (2, 2))[0]     # stem
    assert ok((1, 56, 56, 256), (1, 1, 256, 64))[0]           # 1x1 down
    assert ok((1, 56, 56, 64), (1, 1, 64, 256))[0]            # 1x1 up, K=64
    assert ok((1, 56, 56, 64), (3, 3, 64, 64))[0]             # stage-2 3x3
    routed, reason = ok((1, 14, 14, 1024), (1, 1, 1024, 256))
    assert not routed and "MXU-filled" in reason              # stays on XLA
    routed, reason = ok((1, 7, 7, 512), (3, 3, 512, 512))
    assert not routed and "MXU-filled" in reason


def test_gate_rejects_out_of_domain_convs():
    x = jnp.zeros((1, 8, 8, 4), jnp.float32)
    w = jnp.zeros((3, 3, 4, 8), jnp.float32)
    z = ((0, 0), (0, 0))
    assert not pc.pallas_applicable(x, w, (1, 1), z, (1, 1), (1, 1),
                                    ("NCHW", "OIHW", "NCHW"), 1)[0]
    assert not pc.pallas_applicable(x, jnp.zeros((3, 3, 2, 8)), (1, 1), z,
                                    (1, 1), (1, 1), DN, 2)[0]   # grouped
    assert not pc.pallas_applicable(x, w, (1, 1), z, (2, 2), (1, 1),
                                    DN, 1)[0]                   # deconv
    assert not pc.pallas_applicable(x, w, (1, 1), z, (1, 1), (2, 2),
                                    DN, 1)[0]                   # dilated
    assert not pc.pallas_applicable(x.astype(jnp.float64) if False else
                                    jnp.zeros((1, 8, 8, 4), jnp.int32),
                                    w, (1, 1), z, (1, 1), (1, 1), DN, 1)[0]


def test_dispatch_counters_from_conv_fast(monkeypatch, interp):
    """conv_fast must actually hand the gated shapes to the kernel (and
    leave MXU-filled shapes on XLA) when the lever is on — counted, not
    assumed."""
    monkeypatch.setenv("MXTPU_PALLAS_CONV", "1")
    rng = np.random.RandomState(3)
    pad1 = [(1, 1), (1, 1)]
    x = jnp.asarray(rng.randn(1, 9, 9, 4), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 4, 8) * 0.1, jnp.float32)
    ref = lax.conv_general_dilated(x, w, (1, 1), pad1,
                                   dimension_numbers=DN)
    got = conv_fast(x, w, (1, 1), pad1, (1, 1), (1, 1), DN, 1)
    assert pc.DISPATCH_STATS["pallas"] == 1
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # MXU-filled control: K = 9*128 and C_out = 128 both fill the lanes
    pc.reset_dispatch_stats()
    xb = jnp.zeros((1, 6, 6, 128), jnp.float32)
    wb = jnp.zeros((3, 3, 128, 128), jnp.float32)
    conv_fast(xb, wb, (1, 1), pad1, (1, 1), (1, 1), DN, 1)
    assert pc.DISPATCH_STATS["pallas"] == 0  # gate declined before launch


def test_resolve_fallback_reasons(monkeypatch):
    """Inside the gate, _resolve still declines: off-TPU without the
    interpreter (quiet XLA fallback, counted), and a per-block VMEM plan
    over budget even on 'tpu'."""
    cfg = pc._Cfg((1, 1), ((1, 1), (1, 1)), False, False, False, False)
    x = jnp.zeros((1, 9, 9, 4), jnp.float32)
    w = jnp.zeros((3, 3, 4, 8), jnp.float32)
    geom, reason = pc._resolve(x, w, cfg)
    assert geom is None and "platform" in reason
    # the fallback forward still computes (and counts) off-platform
    pc.reset_dispatch_stats()
    out = pc.fused_conv(jnp.ones((1, 5, 5, 4)), w, (1, 1), ((1, 1), (1, 1)))
    assert out.shape == (1, 5, 5, 8)
    assert pc.DISPATCH_STATS["xla"] == 1
    assert any("platform" in r
               for r in pc.DISPATCH_STATS["fallback_reasons"])
    # VMEM budget: a single 1x1 conv row block of width 128k lanes
    monkeypatch.setattr(pc, "_platform", lambda: "tpu")
    xh = jnp.zeros((1, 1, 200000, 64), jnp.bfloat16)
    wh = jnp.zeros((1, 1, 64, 64), jnp.bfloat16)
    cfg1 = pc._Cfg((1, 1), ((0, 0), (0, 0)), False, False, False, False)
    geom, reason = pc._resolve(xh, wh, cfg1)
    assert geom is None and "VMEM" in reason
    # a sane shape resolves on 'tpu' without the interpreter env
    geom, reason = pc._resolve(x, w, cfg)
    assert geom is not None and geom["bo"] >= 1


def test_conv_fast_bias_fusion_matches_external_add(monkeypatch, interp):
    """conv_fast(bias=...) must equal conv + bias on every dispatch path
    (the Convolution op now hands its bias to conv_fast)."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(2, 9, 9, 4), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 4, 8) * 0.1, jnp.float32)
    b = jnp.asarray(rng.randn(8), jnp.float32)
    pad1 = [(1, 1), (1, 1)]
    ref = lax.conv_general_dilated(x, w, (1, 1), pad1,
                                   dimension_numbers=DN) + b
    plain = conv_fast(x, w, (1, 1), pad1, (1, 1), (1, 1), DN, 1, bias=b)
    np.testing.assert_allclose(np.asarray(plain), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    monkeypatch.setenv("MXTPU_PALLAS_CONV", "1")
    fused = conv_fast(x, w, (1, 1), pad1, (1, 1), (1, 1), DN, 1, bias=b)
    assert pc.DISPATCH_STATS["pallas"] == 1   # bias rode the kernel epilogue
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_policy_key_ab_recompiles(monkeypatch):
    """MXTPU_PALLAS_CONV=0/1 must produce distinct policy keys (so every
    jit cache keyed on it recompiles), and a hybridized conv block must
    trace one executable per flag value — the A/B genuinely compares two
    programs."""
    from mxtpu.ops.registry import policy_key
    monkeypatch.delenv("MXTPU_PALLAS_CONV", raising=False)
    k0 = policy_key()
    monkeypatch.setenv("MXTPU_PALLAS_CONV", "1")
    k1 = policy_key()
    assert k0 != k1

    import mxtpu as mx
    from mxtpu.gluon import nn

    monkeypatch.setenv("MXTPU_PALLAS_CONV_INTERPRET", "1")
    with mx.layout("NHWC"):
        net = nn.Conv2D(8, 3, padding=1, in_channels=4)
    net.initialize()
    x = mx.nd.array(np.random.RandomState(5).randn(1, 7, 7, 4)
                    .astype(np.float32))
    net.hybridize()
    monkeypatch.setenv("MXTPU_PALLAS_CONV", "0")
    y0 = net(x).asnumpy()
    n_traces = len(net._cached_op._jits)
    pc.reset_dispatch_stats()
    monkeypatch.setenv("MXTPU_PALLAS_CONV", "1")
    y1 = net(x).asnumpy()
    assert len(net._cached_op._jits) == n_traces + 1  # recompiled, not reused
    assert pc.DISPATCH_STATS["pallas"] >= 1           # and took the kernel
    np.testing.assert_allclose(y0, y1, rtol=1e-5, atol=1e-5)


def test_backward_multi_block_batch_order(monkeypatch, interp):
    """Regression: when the per-block patches budget splits the batch
    into MULTIPLE scan blocks, dX must land on the right batch elements
    (the scan stacks [n_blocks, bn, ...] where block i IS batch
    [i*bn, (i+1)*bn) — an axis swap there scrambled dx across the batch
    while every single-block test still passed)."""
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(6, 9, 9, 4), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 4, 8) * 0.1, jnp.float32)
    pad = ((1, 1), (1, 1))
    # budget for EXACTLY bn=2 -> 3 scan blocks: a bn of 1 would make any
    # block/batch axis swap a no-op reshape and hide the scramble
    per_item = 9 * 9 * (3 * 3 * 4) * x.dtype.itemsize
    monkeypatch.setattr(pc, "_BWD_COLS_BUDGET", 2 * per_item)
    # per-batch-element weighting makes any batch permutation visible
    wt = jnp.asarray(np.arange(1, 7, dtype=np.float32)[:, None, None, None])
    gf = jax.grad(lambda x_, w_: jnp.sum(
        wt * pc.fused_conv(x_, w_, (1, 1), pad) ** 2), argnums=(0, 1))(x, w)
    gp = jax.grad(lambda x_, w_: jnp.sum(
        wt * _ref(x_, w_, 1, pad) ** 2), argnums=(0, 1))(x, w)
    for a, b in zip(gf, gp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_pallas_bias_fusion_keeps_external_add_dtype(monkeypatch, interp):
    """An f32 bias on bf16 operands promotes the output to f32 on the XLA
    path (`out + bias`); the lever must not change that — conv_fast keeps
    a dtype-promoting bias OUTSIDE the fused epilogue."""
    rng = np.random.RandomState(8)
    x = jnp.asarray(rng.randn(1, 7, 7, 4), jnp.bfloat16)
    w = jnp.asarray(rng.randn(1, 1, 4, 8) * 0.1, jnp.bfloat16)
    b = jnp.asarray(rng.randn(8), jnp.float32)
    args = ((1, 1), [(0, 0), (0, 0)], (1, 1), (1, 1), DN, 1)
    off = conv_fast(x, w, *args, bias=b)
    monkeypatch.setenv("MXTPU_PALLAS_CONV", "1")
    on = conv_fast(x, w, *args, bias=b)
    assert on.dtype == off.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(on, np.float32),
                               np.asarray(off, np.float32),
                               rtol=2e-2, atol=2e-2)
    # same-dtype bias still rides the fused epilogue
    pc.reset_dispatch_stats()
    on16 = conv_fast(x, w, *args, bias=b.astype(jnp.bfloat16))
    assert on16.dtype == jnp.bfloat16
    assert pc.DISPATCH_STATS["pallas"] >= 1


@pytest.mark.slow
def test_interpret_kernel_on_real_stem_shape(interp):
    """The actual ImageNet stem geometry (224^2, 7x7s2 pad3, 3->64) at
    batch 1 through the interpreter — the full-size block/halo plumbing,
    beyond the tier-1-sized shapes above."""
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(1, 224, 224, 3), jnp.bfloat16)
    w = jnp.asarray(rng.randn(7, 7, 3, 64) * 0.1, jnp.bfloat16)
    pad = ((3, 3), (3, 3))
    out = pc.fused_conv(x, w, (2, 2), pad)
    assert out.shape == (1, 112, 112, 64)
    assert pc.DISPATCH_STATS["pallas"] == 1
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(_ref(x, w, 2, pad), np.float32),
                               rtol=3e-2, atol=3e-2)
