"""Deeper tests for the legacy-vision / SSD straggler ops
(ref: src/operator/crop.cc, svm_output.cc, correlation.cc,
tensor/histogram.cc, contrib/multibox_*.cc)."""
import numpy as np

import mxtpu as mx
from mxtpu import autograd as ag


def test_crop_like():
    x = mx.nd.array(np.arange(64, np.float32).reshape(1, 1, 8, 8)
                    if False else
                    np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8))
    like = mx.nd.zeros((1, 1, 4, 4))
    out = mx.nd.Crop(x, like, offset=(2, 2)).asnumpy()
    np.testing.assert_array_equal(out[0, 0], x.asnumpy()[0, 0, 2:6, 2:6])
    out = mx.nd.Crop(x, like, center_crop=True).asnumpy()
    np.testing.assert_array_equal(out[0, 0], x.asnumpy()[0, 0, 2:6, 2:6])


def test_svm_output_gradient():
    """Hinge gradient: violating classes get positive grad, the true class
    the negative sum (ref: svm_output.cc L1-SVM backward)."""
    d = mx.nd.array(np.array([[2.0, 1.5, -1.0]], np.float32))
    lab = mx.nd.array(np.array([0.0], np.float32))
    d.attach_grad()
    with ag.record():
        out = mx.nd.SVMOutput(d, lab, margin=1.0, use_linear=True)
    out.backward()
    g = d.grad.asnumpy()[0]
    # class1: margin violated (2.0 - 1.5 = 0.5 < 1) -> +1; class2: ok -> 0
    np.testing.assert_allclose(g, [-1.0, 1.0, 0.0], atol=1e-6)


def test_histogram_matches_numpy():
    x = np.random.RandomState(0).uniform(0, 10, (100,)).astype(np.float32)
    counts, edges = mx.nd.histogram(mx.nd.array(x), bin_cnt=5,
                                    range=(0.0, 10.0))
    ref_c, ref_e = np.histogram(x, bins=5, range=(0, 10))
    np.testing.assert_array_equal(counts.asnumpy(), ref_c)
    np.testing.assert_allclose(edges.asnumpy(), ref_e, rtol=1e-6)
    # explicit bin edges
    counts, edges = mx.nd.histogram(mx.nd.array(x),
                                    bins=mx.nd.array([0.0, 2.5, 7.5, 10.0]))
    ref_c, _ = np.histogram(x, bins=[0.0, 2.5, 7.5, 10.0])
    np.testing.assert_array_equal(counts.asnumpy(), ref_c)


def test_correlation_identity_displacement():
    """Zero displacement of identical inputs = mean of squares over
    channels x kernel window."""
    x = np.random.RandomState(0).uniform(-1, 1, (1, 3, 5, 5)) \
        .astype(np.float32)
    out = mx.nd.Correlation(mx.nd.array(x), mx.nd.array(x), kernel_size=1,
                            max_displacement=0).asnumpy()
    ref = (x * x).mean(axis=1)
    np.testing.assert_allclose(out[:, 0], ref, rtol=1e-5, atol=1e-6)


def test_multibox_prior_reference_layout():
    """Anchor math matches multibox_prior-inl.h: num_sizes-1+num_ratios
    anchors per cell, centers at (i+offset)*step."""
    data = mx.nd.zeros((1, 3, 2, 2))
    out = mx.nd.multibox_prior(data, sizes=(0.5,), ratios=(1.0,)).asnumpy()
    assert out.shape == (1, 4, 4)
    # first cell center (0.25, 0.25), half-w = 0.5*2/2/2=0.25, half-h 0.25
    np.testing.assert_allclose(out[0, 0], [0.0, 0.0, 0.5, 0.5], atol=1e-6)
    # two sizes + two ratios -> 3 anchors/cell
    out = mx.nd.multibox_prior(data, sizes=(0.5, 0.25),
                               ratios=(1.0, 2.0)).asnumpy()
    assert out.shape == (1, 2 * 2 * 3, 4)


def test_multibox_target_matches_and_encodes():
    anchors = mx.nd.array(np.array(
        [[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]]], np.float32))
    # one gt box of class 0 overlapping anchor 0 exactly
    label = mx.nd.array(np.array([[[0.0, 0.1, 0.1, 0.4, 0.4]]], np.float32))
    cls_pred = mx.nd.array(np.random.RandomState(0)
                           .uniform(0, 1, (1, 3, 2)).astype(np.float32))
    lt, lm, ct = mx.nd.multibox_target(anchors, label, cls_pred)
    ct = ct.asnumpy()
    assert ct[0, 0] == 1.0  # class 0 + 1
    assert ct[0, 1] == 0.0  # background
    lm = lm.asnumpy()
    assert lm[0, :4].sum() == 4 and lm[0, 4:].sum() == 0
    np.testing.assert_allclose(lt.asnumpy()[0, :4], 0.0, atol=1e-5)


def test_multibox_detection_decodes_and_nms():
    # two anchors; anchor0 strongly class-1, anchor1 background
    cls_prob = mx.nd.array(np.array(
        [[[0.1, 0.8], [0.85, 0.15], [0.05, 0.05]]],
        np.float32).transpose(0, 2, 1))
    # ^ shape [1, C=3, A=2]: anchor0 -> class1 (p=.85... wait transposed)
    cls_prob = mx.nd.array(np.array(
        [[[0.1, 0.85, 0.05], [0.8, 0.15, 0.05]]], np.float32)
        .transpose(0, 2, 1))  # [1, 3, 2]: anchor0 class1 .85, anchor1 bg .8
    loc_pred = mx.nd.zeros((1, 8))
    anchors = mx.nd.array(np.array(
        [[[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9]]], np.float32))
    out = mx.nd.multibox_detection(cls_prob, loc_pred, anchors).asnumpy()
    assert out.shape == (1, 2, 6)
    cid, score = out[0, 0, 0], out[0, 0, 1]
    assert cid == 0.0 and abs(score - 0.85) < 1e-6  # class1 -> id 0
    np.testing.assert_allclose(out[0, 0, 2:], [0.1, 0.1, 0.4, 0.4],
                               atol=1e-5)
    assert out[0, 1, 0] == -1.0  # background anchor suppressed


def test_quantize_net_warns_on_skipped_layers(caplog):
    """VERDICT r2 weak #9: non-Dense/Conv2D parameterized layers must be
    reported, not silently left fp32."""
    import logging
    from mxtpu import gluon
    from mxtpu.contrib.quantization import quantize_net

    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(8))
        net.add(gluon.nn.BatchNorm())
    net.initialize()
    net(mx.nd.zeros((2, 8)))
    with caplog.at_level(logging.WARNING):
        quantize_net(net)
    assert any("BatchNorm" in r.message for r in caplog.records)
