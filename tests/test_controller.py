"""SLO-aware serving control plane (mxtpu/serving/controller) — ISSUE 13:

* predictive admission: the per-bucket latency model sheds
  ``predicted_miss`` before the depth bound fills in a slow-bucket
  regime, falls back to the depth bound while cold, and is fed from the
  delivered requests' stage breakdowns;
* priority classes: batch yields its coalescing slot to interactive up
  to the aging floor, and is first evicted under queue pressure;
* the submit-time expired-deadline sweep (a dead entry must not crowd
  fresh work into a ``queue_full`` shed);
* elastic ReplicaSet: scale-up joins only after AOT warmup (compiles
  pinned at #buckets at the new ``serving.predict.r<i>`` site),
  scale-down drains without failing in-flight futures, dead-replica
  replacement end-to-end on a fresh device, cooldown hysteresis
  suppressing flapping, KV-residency as a scale signal;
* the HTTP surfaces: 503 ``Retry-After`` from the predicted drain time,
  ``/healthz`` controller view;
* the serve_bench ``--mode slo`` gates (wall-clock, marked slow).

Every controller/autoscaler test runs sleep-free on an injected clock —
the PR-8 discipline.
"""
import os

import numpy as np
import pytest

from mxtpu import resilience, telemetry
from mxtpu.base import MXNetError
from mxtpu.gluon import nn
from mxtpu.serving import (BucketSpec, DeadlineExceeded, KVCacheAccountant,
                           MicroBatcher, ModelServer, Predictor, QueueFull,
                           ReplicaDispatcher, ReplicaSet, ServingController)

import jax

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 3,
    reason="controller tests need >= 3 (virtual) devices for replacement")


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_TELEMETRY", "MXTPU_RETRACE_BUDGET",
                "MXTPU_FAULT_INJECT", "MXTPU_SERVE_MAX_BATCH",
                "MXTPU_SERVE_MAX_WAIT_MS", "MXTPU_SERVE_QUEUE",
                "MXTPU_SERVE_REPLICAS", "MXTPU_SERVE_DISPATCH_TIMEOUT_MS",
                "MXTPU_SERVE_BREAKER_THRESHOLD",
                "MXTPU_SERVE_BREAKER_BACKOFF_MS",
                "MXTPU_SERVE_BREAKER_BACKOFF_MAX_MS",
                "MXTPU_SERVE_BATCH_AGING_MS", "MXTPU_SERVE_MIN_REPLICAS",
                "MXTPU_SERVE_MAX_REPLICAS", "MXTPU_SERVE_SCALE_COOLDOWN_MS",
                "MXTPU_SERVE_REPLACE_AFTER_MS"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    yield
    telemetry.reset()
    resilience.reset_faults()


IN_DIM, OUT_DIM = 12, 4

# the slow-bucket regime every predictive test trains on: the shape of a
# real PR-10 stage breakdown (what MicroBatcher._deliver feeds through
# controller.observe), with a service time far above the deadlines used
SLOW_BREAKDOWN = {"serving.queue_wait": 0.05, "serving.pad": 0.01,
                  "serving.predict": 0.19}


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(OUT_DIM))
    net.initialize()
    return net


def _x(n, seed=0, dim=IN_DIM):
    return np.random.RandomState(seed).randn(n, dim).astype(np.float32)


def _rset(n=1, max_batch=4, **kw):
    net = _mlp()
    spec = BucketSpec.pow2(max_batch)
    kw.setdefault("breaker_backoff_ms", 1000)
    rs = ReplicaSet(net, spec, n=n,
                    example=np.zeros((1, IN_DIM), np.float32),
                    warmup=True, **kw)
    return net, spec, rs


def _disp(rs, clk, **kw):
    kw.setdefault("max_batch_size", rs.spec.max_batch)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("dispatch_timeout_ms", 2000)
    return ReplicaDispatcher(rs, clock=clk, start=False, **kw)


def _ctrl(bat, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 2)
    kw.setdefault("scale_cooldown_ms", 1000)
    kw.setdefault("min_samples", 4)
    return ServingController(bat, **kw)


def _decisions(tag):
    return telemetry.value("serving.controller.decisions", tag=tag)


# ------------------------------------------------------- predictive admission
def test_predictive_shed_fires_before_depth_shed():
    """Slow-bucket regime: the model (trained from breakdown-shaped
    observations) predicts a miss, so the submit sheds predicted_miss
    while the queue depth is nowhere near MXTPU_SERVE_QUEUE."""
    _, _, rs = _rset(n=1)
    clk = FakeClock()
    bat = _disp(rs, clk)          # default depth bound: 256 items
    ctrl = _ctrl(bat, max_replicas=1)
    for _ in range(6):
        ctrl.observe(None, SLOW_BREAKDOWN, hit=True, now=clk())
    assert ctrl.predicted_s(None) == pytest.approx(0.25, abs=0.06)
    assert bat.queue_depth == 0 and bat.max_queue == 256
    with pytest.raises(QueueFull, match="predicted_miss"):
        bat.submit(_x(1), deadline_ms=50)
    assert telemetry.value("serving.shed", tag="predicted_miss") == 1
    assert telemetry.value("serving.shed", tag="queue_full") == 0
    assert _decisions("predicted_shed") == 1
    # a feasible deadline (and a deadline-less submit) still admit
    f1 = bat.submit(_x(1), deadline_ms=2000)
    f2 = bat.submit(_x(1, seed=1))
    clk.advance(0.006)
    assert bat.poll() == 2
    assert f1.done() and f2.done()


def test_predictive_model_fed_from_delivery_breakdowns():
    """Integration of the observe half: real deliveries train the model
    through MicroBatcher._deliver (queue-wait measured on the injected
    clock), and the attainment counters see their deadline verdicts."""
    _, _, rs = _rset(n=1)
    clk = FakeClock()
    bat = _disp(rs, clk)
    ctrl = _ctrl(bat, max_replicas=1)
    for i in range(5):
        f = bat.submit(_x(1, seed=i), deadline_ms=10000)
        clk.advance(0.2)          # 200 ms of fake-clock queue wait
        assert bat.poll() == 1
        assert f.done()
    now = clk()
    m = ctrl._models[None]
    assert m["total"].count(now) == 5
    # the model's totals are dominated by the injected-clock queue wait
    assert m["total"].quantile(0.9, now) >= 0.2
    assert ctrl.view()["slo_attainment"] == 1.0


def test_cold_model_falls_back_to_depth_bound():
    """While the model is cold, even an absurd deadline admits — and the
    plain depth bound still governs."""
    _, _, rs = _rset(n=1)
    clk = FakeClock()
    bat = _disp(rs, clk, max_queue=4)
    _ctrl(bat, max_replicas=1, min_samples=8)
    f = bat.submit(_x(1), deadline_ms=1)   # cold: admitted, not predicted
    assert telemetry.value("serving.shed", tag="predicted_miss") == 0
    for i in range(3):
        bat.submit(_x(1, seed=i), deadline_ms=10000)
    with pytest.raises(QueueFull, match="queue_full"):
        bat.submit(_x(1, seed=9), deadline_ms=10000)
    clk.advance(0.006)
    bat.poll()
    with pytest.raises(DeadlineExceeded):
        f.result(0)               # its 1 ms deadline expired at dispatch


def test_retry_after_tracks_estimated_drain():
    _, _, rs = _rset(n=1)
    clk = FakeClock()
    bat = _disp(rs, clk)
    ctrl = _ctrl(bat, max_replicas=1)
    assert ctrl.retry_after_s() >= 1   # empty queue: the floor
    for _ in range(6):
        ctrl.observe(None, SLOW_BREAKDOWN, hit=True, now=clk())
    for i in range(8):                 # two full batches queued
        bat.submit(_x(1, seed=i))
    # drain estimate: depth over the observed drain rate — seconds scale
    assert ctrl.estimate_drain_s() > 0
    assert ctrl.retry_after_s() >= 1
    while bat.poll():
        clk.advance(0.006)


# ------------------------------------------------------------ priority classes
def _seq_batcher(clk, max_queue=None, batch_aging_ms=1000):
    """A seq-bucketed predictor so interactive and batch work can live
    in DIFFERENT cohorts (same-bucket traffic simply co-batches)."""
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(3, flatten=False))
    net.initialize()
    spec = BucketSpec((2,), seq_lens=(4, 8))
    pred = Predictor(net, spec, example=np.zeros((1, 4, 5), np.float32),
                     warmup=True)
    return MicroBatcher(pred, max_batch_size=2, max_wait_ms=5, clock=clk,
                        start=False, max_queue=max_queue,
                        batch_aging_ms=batch_aging_ms)


def test_batch_yields_then_aging_floor_wins():
    """Strict-priority dequeue: a batch-class head yields its coalescing
    slot to a fresher interactive cohort (counted as a yield decision on
    the batch request's own trace) — until the aging floor passes, after
    which the batch head dispatches ahead of fresh interactive work."""
    clk = FakeClock()
    bat = _seq_batcher(clk, batch_aging_ms=1000)
    rng = np.random.RandomState(0)
    xb = rng.randn(1, 7, 5).astype(np.float32)   # seq bucket 8 (batch)
    xi = rng.randn(1, 3, 5).astype(np.float32)   # seq bucket 4
    fb = bat.submit(xb, priority="batch")
    fi = bat.submit(xi)
    clk.advance(0.006)   # both past max_wait; far below the aging floor
    assert bat.poll() == 1
    assert fi.done() and not fb.done()           # interactive jumped
    assert _decisions("yield") == 1
    # past the aging floor the batch head beats fresh interactive work
    clk.advance(1.05)
    fi2 = bat.submit(rng.randn(1, 2, 5).astype(np.float32))
    clk.advance(0.006)
    assert bat.poll() == 1
    assert fb.done() and not fi2.done()
    assert bat.poll() == 1
    assert fi2.done()
    assert _decisions("yield") == 1              # aging win is not a yield


def test_batch_evicted_first_under_queue_pressure():
    """Queue full + an interactive arrival: the NEWEST batch-class
    entries are evicted (shed priority_evict) to admit it; a batch
    arrival never evicts."""
    clk = FakeClock()
    bat = _seq_batcher(clk, max_queue=2)
    rng = np.random.RandomState(1)
    xb = rng.randn(1, 7, 5).astype(np.float32)
    fb1 = bat.submit(xb, priority="batch")
    fb2 = bat.submit(xb, priority="batch")
    fi = bat.submit(rng.randn(1, 3, 5).astype(np.float32))
    with pytest.raises(QueueFull, match="priority_evict"):
        fb2.result(0)
    assert not fb1.done() and not fi.done()      # oldest batch survives
    assert telemetry.value("serving.shed", tag="priority_evict") == 1
    with pytest.raises(QueueFull, match="queue_full"):
        bat.submit(xb, priority="batch")         # batch never evicts
    assert telemetry.value("serving.shed", tag="priority_evict") == 1


def test_eviction_refused_when_it_cannot_make_room():
    """An interactive submit that would STILL shed after evicting every
    batch entry must not drop batch work for nothing — and no evicted
    future may ever strand (review finding: the old path raised
    queue_full before failing the victims)."""
    clk = FakeClock()
    bat = _seq_batcher(clk, max_queue=2)
    rng = np.random.RandomState(2)
    fb = bat.submit(rng.randn(1, 7, 5).astype(np.float32),
                    priority="batch")
    bat.submit(rng.randn(1, 3, 5).astype(np.float32))
    # needs 2 items of room; evicting the single batch item cannot make
    # it fit -> shed the arrival, keep the batch work queued
    with pytest.raises(QueueFull, match="queue_full"):
        bat.submit(rng.randn(2, 3, 5).astype(np.float32))
    assert not fb.done()
    assert telemetry.value("serving.shed", tag="priority_evict") == 0
    bat.drain(timeout=5)
    assert fb.done()


def test_warmup_failure_is_recorded_not_lost(monkeypatch):
    """A replica bring-up that dies in warmup is RECORDED as a
    warmup_failed decision (and the half-built replica leaves the set)
    instead of dying silently."""
    from mxtpu.serving.engine import Predictor as _P
    _, _, rs = _rset(n=1)
    clk = FakeClock()
    bat = _disp(rs, clk, max_queue=8)
    _ctrl(bat, min_samples=999, scale_cooldown_ms=0)
    monkeypatch.setattr(_P, "warmup",
                        lambda self: (_ for _ in ()).throw(
                            RuntimeError("device dead at bring-up")))
    for i in range(4):
        bat.submit(_x(1, seed=i))
    clk.advance(0.006)
    bat.poll()                                 # tick -> scale_up -> boom
    assert _decisions("scale_up") == 1
    assert _decisions("warmup_failed") == 1
    assert [r.index for r in rs.replicas] == [0]  # never joined
    while bat.poll():
        pass


def test_predictive_model_trains_with_tracing_off(monkeypatch):
    """MXTPU_TRACE=0 leaves no stage breakdowns — deliveries then train
    the model on the enqueue->deliver interval, so predictive admission
    degrades gracefully instead of going silently inert."""
    monkeypatch.setenv("MXTPU_TRACE", "0")
    telemetry.reset()
    _, _, rs = _rset(n=1)
    clk = FakeClock()
    bat = _disp(rs, clk)
    ctrl = _ctrl(bat, max_replicas=1, min_samples=4)
    for i in range(5):
        f = bat.submit(_x(1, seed=i), deadline_ms=10000)
        clk.advance(0.2)
        assert bat.poll() == 1
        assert f.done() and f.breakdown is None   # tracing really off
    now = clk()
    m = ctrl._models[None]
    assert m["total"].count(now) == 5
    assert m["total"].quantile(0.9, now) >= 0.2   # the fake-clock wait
    # at depth 0 the live bound (no service info without breakdowns)
    # admits; once a backlog builds, the e2e-trained history predicts
    # the miss and admission sheds
    bat.submit(_x(1, seed=7), deadline_ms=50)
    for i in range(3):
        bat.submit(_x(1, seed=i))
    with pytest.raises(QueueFull, match="predicted_miss"):
        bat.submit(_x(1, seed=9), deadline_ms=50)
    while bat.poll():
        clk.advance(0.006)


def test_unknown_priority_refused():
    clk = FakeClock()
    _, _, rs = _rset(n=1)
    bat = _disp(rs, clk)
    with pytest.raises(MXNetError, match="priority"):
        bat.submit(_x(1), priority="best_effort")


# -------------------------------------------------------- expired-entry sweep
def test_expired_sweep_admits_fresh_work_before_depth_shed():
    """ISSUE-13 satellite: an entry whose deadline passed while queued
    is swept at submit-time pressure, so fresh work is admitted instead
    of shed queue_full."""
    net = _mlp()
    pred = Predictor(net, BucketSpec.pow2(4),
                     example=np.zeros((1, IN_DIM), np.float32), warmup=True)
    clk = FakeClock()
    bat = MicroBatcher(pred, max_batch_size=4, max_wait_ms=1000,
                       max_queue=2, clock=clk, start=False)
    f1 = bat.submit(_x(1), deadline_ms=10)
    clk.advance(0.05)                        # f1's deadline passed queued
    f2 = bat.submit(_x(2, seed=1))           # 1+2 > 2: sweep, then admit
    with pytest.raises(DeadlineExceeded):
        f1.result(0)
    assert bat.queue_depth == 2
    assert telemetry.value("serving.deadline_expired") == 1
    # no expired entries left: the depth bound sheds as before
    with pytest.raises(QueueFull, match="queue_full"):
        bat.submit(_x(1, seed=2))
    bat.drain(timeout=5)
    assert f2.done()


# ---------------------------------------------------------- elastic ReplicaSet
def test_scale_up_joins_only_after_warmup_compiles_pinned():
    """A warming replica is visible but NEVER routed; it joins the pool
    only once every bucket compiled at its own fresh retrace site —
    compiles == #buckets, watchdog-pinned."""
    _, spec, rs = _rset(n=1)
    rep = rs.add_replica(warm=False)
    assert rep.state == "warming" and rep.index == 1
    assert len(rs.replicas) == 2
    assert rs.healthy_count() == 1
    assert rs.pick().index == 0              # warming: never picked
    assert telemetry.retrace_stats("serving.predict.r1") is None
    rs.warm_replica(rep)
    assert rep.state == "healthy" and rs.healthy_count() == 2
    st = telemetry.retrace_stats("serving.predict.r1")
    assert st["compiles"] == len(spec) and st["trips"] == 0
    assert telemetry.value("serving.replica.joins", tag="r1") == 1
    # parity: the elastic member serves the same math
    x = _x(2, seed=3)
    np.testing.assert_allclose(rep.predictor.predict(x).asnumpy(),
                               rs.replicas[0].predictor.predict(x).asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_controller_scales_up_on_queue_pressure():
    _, spec, rs = _rset(n=1)
    clk = FakeClock()
    bat = _disp(rs, clk, max_queue=8)
    _ctrl(bat, min_samples=999, scale_cooldown_ms=0)
    for i in range(4):                        # pressure 0.5 == high bar
        bat.submit(_x(1, seed=i))
    clk.advance(0.006)
    bat.poll()                                # maintain -> tick -> grow
    assert len(rs.replicas) == 2
    assert [r.state for r in rs.replicas] == ["healthy", "healthy"]
    assert _decisions("scale_up") == 1
    st = telemetry.retrace_stats("serving.predict.r1")
    assert st["compiles"] == len(spec) and st["trips"] == 0
    snap = telemetry.snapshot()["gauges"]
    assert snap["serving.replicas"] == 2
    while bat.poll():
        pass


def test_scale_down_drains_without_failing_inflight_futures():
    _, _, rs = _rset(n=2)
    clk = FakeClock()
    bat = _disp(rs, clk)
    _ctrl(bat, min_replicas=1, max_replicas=2, scale_cooldown_ms=1000,
          min_samples=999)
    f1 = bat.submit(_x(2, seed=0))
    clk.advance(0.006)
    bat.poll()
    assert f1.done()
    clk.advance(1.2)                          # idle past the cooldown
    bat.poll()                                # tick -> scale_down
    assert _decisions("scale_down") == 1
    assert [r.state for r in rs.replicas] == ["healthy", "retiring"]
    # new work keeps serving on the survivor while the victim drains
    f2 = bat.submit(_x(1, seed=1))
    clk.advance(0.006)
    bat.poll()                                # finalize + dispatch
    assert f2.result(0).shape == (1, OUT_DIM)
    assert [r.index for r in rs.replicas] == [0]
    assert telemetry.value("serving.replica.retirements", tag="r1") == 1
    assert telemetry.snapshot()["gauges"]["serving.replicas"] == 1


def test_dead_replica_replacement_end_to_end():
    """The self-healing path: a replica whose breaker stays open past
    MXTPU_SERVE_REPLACE_AFTER_MS is replaced by a fresh AOT-warmed
    replica on a FRESH device; the dead one retires. Sleep-free."""
    _, spec, rs = _rset(n=2)
    clk = FakeClock()
    bat = _disp(rs, clk)
    _ctrl(bat, min_replicas=2, max_replicas=2, replace_after_ms=500,
          scale_cooldown_ms=100000, min_samples=999)
    dead_dev = rs.replicas[0].device
    bat.quarantine_replica(0, backoff_s=3600)  # a dead chip
    assert rs.healthy_count() == 1
    clk.advance(0.3)
    bat.poll()                                 # before the bound: no-op
    assert _decisions("replace") == 0
    clk.advance(0.3)                           # 0.6 s down >= 0.5 s bound
    bat.poll()                                 # tick -> replace
    assert _decisions("replace") == 1
    bat.poll()                                 # finalize the retired dead
    assert [r.index for r in rs.replicas] == [1, 2]
    assert [r.state for r in rs.replicas] == ["healthy", "healthy"]
    assert rs.replicas[-1].device is not dead_dev  # a FRESH device
    st = telemetry.retrace_stats("serving.predict.r2")
    assert st["compiles"] == len(spec) and st["trips"] == 0
    # capacity restored: traffic round-trips on the replacement pool
    f = bat.submit(_x(2, seed=5))
    clk.advance(0.006)
    assert bat.poll() == 1
    assert f.result(0).shape == (2, OUT_DIM)
    assert telemetry.value("serving.replica.retirements", tag="r0") == 1


def test_cooldown_hysteresis_suppresses_flapping():
    """One pressure spike scales up exactly once; the idle scale-down
    waits out BOTH the action cooldown and a full cooldown of idleness;
    nothing flaps in between."""
    _, _, rs = _rset(n=1)
    clk = FakeClock()
    bat = _disp(rs, clk, max_queue=8)
    _ctrl(bat, scale_cooldown_ms=1000, min_samples=999)
    for i in range(4):
        bat.submit(_x(1, seed=i))
    clk.advance(0.006)
    bat.poll()                                 # spike -> scale_up
    assert _decisions("scale_up") == 1
    while bat.poll():
        pass                                   # drain; now fully idle
    clk.advance(0.5)
    bat.poll()                                 # inside cooldown: nothing
    assert _decisions("scale_up") == 1 and _decisions("scale_down") == 0
    assert len(rs.replicas) == 2
    clk.advance(1.1)                           # past cooldown AND idle
    bat.poll()
    assert _decisions("scale_down") == 1
    bat.poll()                                 # finalize
    assert len(rs.replicas) == 1
    clk.advance(0.5)
    bat.poll()                                 # floor reached: stable
    assert _decisions("scale_down") == 1 and _decisions("scale_up") == 1


def test_kv_residency_is_a_scale_signal():
    """ISSUE-13 tentpole: the decode KV accountant's residency pressure
    (live+queued vs the overcommit bound) triggers scale-up BEFORE the
    kv_residency sheds start."""
    _, _, rs = _rset(n=1)
    acct = KVCacheAccountant(overcommit=2.0)
    acct.register("r0", per_slot_bytes=64, slots=2)
    rs.attach_accountant(acct)
    clk = FakeClock()
    bat = _disp(rs, clk)
    _ctrl(bat, min_samples=999, scale_cooldown_ms=0)
    for _ in range(4):                         # fill to the admission bound
        assert acct.try_admit("r0")
    assert acct.pressure() == pytest.approx(1.0)
    clk.advance(0.01)
    bat.poll()                                 # tick -> kv-pressure grow
    assert _decisions("scale_up") == 1
    assert len(rs.replicas) == 2


# ----------------------------------------------------------------- HTTP front
def _http(addr, path, payload=None, timeout=10):
    import json
    import urllib.error
    import urllib.request
    url = "http://%s:%d%s" % (addr[0], addr[1], path)
    if payload is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_retry_after_header_and_healthz_controller_view():
    _, _, rs = _rset(n=1)
    bat = ReplicaDispatcher(rs, max_batch_size=4, max_wait_ms=1)
    ServingController(bat, min_replicas=1, max_replicas=1, min_samples=4)
    srv = ModelServer(bat).start()
    try:
        x = _x(2, seed=5)
        code, out, _h = _http(srv.address, "/predict", {"data": x.tolist()})
        assert code == 200 and out["n"] == 2
        # unknown priority is the CLIENT's fault
        code, out, _h = _http(srv.address, "/predict",
                              {"data": x.tolist(), "priority": "bogus"})
        assert code == 400 and "priority" in out["error"]
        # a named priority class round-trips
        code, out, _h = _http(srv.address, "/predict",
                              {"data": x.tolist(), "priority": "batch"})
        assert code == 200
        # the controller block on /healthz
        code, health, _h = _http(srv.address, "/healthz")
        assert code == 200
        view = health["controller"]
        assert view["replica_target"] == 1 and view["replica_actual"] == 1
        assert view["min_replicas"] == 1 and view["max_replicas"] == 1
        assert view["queue_depths"] == {"interactive": 0, "batch": 0}
        assert "last_decision" in view and "estimated_drain_s" in view
        # a shed answers 503 WITH a Retry-After derived from the model
        srv.draining = True
        code, out, headers = _http(srv.address, "/predict",
                                   {"data": x.tolist()})
        assert code == 503
        assert int(headers["Retry-After"]) >= 1
    finally:
        srv.draining = False
        srv.close()


# ------------------------------------------------------------- bench (slow)
@pytest.mark.slow
def test_serve_bench_slo_gates():
    """tools/serve_bench.py --mode slo: the controller strictly beats
    the static depth-shed router on goodput-at-SLO on >= 1 overload
    point, and the kill/restore sweep replaces the dead replica with
    p99 recovering in-window and zero hung futures (wall-clock)."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import serve_bench as sb

    rec = sb.run_slo(dim=64, width=64, depth=2, replicas=2,
                     n_requests=200, qps_factors=(3.0, 8.0),
                     recover_window_s=12.0, emit=lambda r: None)
    assert rec["hangs"] == 0
    assert rec["curve_ok"], rec["gains"]
    assert rec["killrestore"]["ok"], rec["killrestore"]
    assert rec["ok"]
