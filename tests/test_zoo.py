"""Multi-tenant model zoo (mxtpu/serving/zoo) — ISSUE 20:

* cold-model policy matrix: shed vs the bounded page-in queue (waiters
  complete after the page-in, overflow sheds ``zoo_cold``), plus the
  deterministic ``zoo_cold`` fault hook;
* HBM-currency placement: count caps and byte budgets evict the
  coldest resident — whose queued + in-flight futures complete FIRST
  (eviction never strands a request) — and the co-residency-aware
  warmup preflight warns ``memory.overcommit`` before a page-in OOMs;
* versioned canary rollout: deterministic hash routing, promote via the
  no-recompile sticky-int8 ``refresh_params`` swap, SLO/injected/parity
  auto-rollback mid-cohort with ZERO dropped or hung futures;
* page-in as a disk-warm no-compile event (subprocess: the second
  process's page-in is all disk hits, ``retrace.serving.predict.zoo.*``
  stays 0);
* per-tenant SLO classes: priority isolation under overload and the
  per-tenant goodput-attainment counters;
* the multi-model HTTP front: ``model``/``version`` routing, 404s with
  the known-name lists, the /healthz zoo block.

Everything except the HTTP/threaded tests runs sleep-free on an
injected clock — the PR-8 discipline.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import mxtpu as mx
from mxtpu import resilience, telemetry, xprof
from mxtpu import compile_service as csvc
from mxtpu.base import MXNetError
from mxtpu.gluon import nn
from mxtpu.serving import (BucketSpec, ModelServer, ModelZoo, QueueFull,
                           ZooScheduler)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

IN_DIM, OUT_DIM = 6, 4
ZOO_SITE = "serving.predict.zoo"


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_TELEMETRY", "MXTPU_RETRACE_BUDGET",
                "MXTPU_FAULT_INJECT", "MXTPU_SERVE_MAX_BATCH",
                "MXTPU_SERVE_MAX_WAIT_MS", "MXTPU_SERVE_QUEUE",
                "MXTPU_SERVE_BATCH_AGING_MS", "MXTPU_SERVE_INT8",
                "MXTPU_ZOO_MAX_RESIDENT", "MXTPU_ZOO_HBM_BUDGET",
                "MXTPU_ZOO_COLD_POLICY", "MXTPU_ZOO_PAGEIN_QUEUE",
                "MXTPU_ZOO_DEMAND_HORIZON_S", "MXTPU_ZOO_CANARY_FLOOR",
                "MXTPU_ZOO_CANARY_WINDOW", "MXTPU_ZOO_PARITY_TOL",
                "MXTPU_COMPILE_CACHE_DIR"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    csvc.reset()
    yield
    telemetry.reset()
    resilience.reset_faults()
    csvc.reset()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _mlp(seed=0):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"))
        net.add(nn.Dense(OUT_DIM))
    net.initialize()
    net(mx.nd.array(np.full((1, IN_DIM), 1.0 + seed, np.float32)))
    return net


def _x(n, seed=0):
    return np.random.RandomState(seed).randn(n, IN_DIM).astype(np.float32)


def _zoo(models=("alpha",), manifest_dir=None):
    zoo = ModelZoo(manifest_dir=manifest_dir)
    spec = BucketSpec([1, 4])
    ex = np.zeros((1, IN_DIM), np.float32)
    for i, name in enumerate(models):
        zoo.register(name, _mlp(seed=i), spec, example=ex)
    return zoo


def _sched(zoo, clk, **kw):
    kw.setdefault("start", False)
    kw.setdefault("devices", [jax.devices()[0]])
    return ZooScheduler(zoo, clock=clk, **kw)


def _drive(clk, sched, rounds=3, dt=0.006):
    for _ in range(rounds):
        clk.advance(dt)
        sched.poll()


# ------------------------------------------------------------- cold policy
def test_cold_policy_shed():
    clk = FakeClock()
    sched = _sched(_zoo(), clk, cold_policy="shed")
    with pytest.raises(QueueFull, match="zoo_cold"):
        sched.submit("alpha", _x(1))
    assert telemetry.value("serving.shed", tag="zoo_cold") == 1
    assert "alpha" not in sched._residents


def test_cold_queue_bounded_pagein_wait():
    """The queue policy: cold submits wait behind ONE bounded page-in —
    waiters complete once the model is resident, overflow sheds
    ``zoo_cold`` instead of building unserviceable backlog."""
    clk = FakeClock()
    sched = _sched(_zoo(), clk, pagein_queue=2)
    f1 = sched.submit("alpha", _x(1, seed=1))
    f2 = sched.submit("alpha", _x(2, seed=2))
    assert not f1.done() and not f2.done()
    with pytest.raises(QueueFull, match="zoo_cold"):
        sched.submit("alpha", _x(1, seed=3))
    assert telemetry.value("serving.shed", tag="zoo_cold") == 1
    _drive(clk, sched)
    assert np.asarray(f1.result(timeout=5)).shape == (1, OUT_DIM)
    assert np.asarray(f2.result(timeout=5)).shape == (2, OUT_DIM)
    assert telemetry.value("zoo.pageins", tag="alpha") == 1
    # warm now: a follow-up request routes straight to the live batcher
    f3 = sched.submit("alpha", _x(1, seed=4))
    _drive(clk, sched)
    assert f3.result(timeout=5) is not None
    assert telemetry.value("zoo.pageins", tag="alpha") == 1


def test_zoo_cold_fault_injection(monkeypatch):
    """``MXTPU_FAULT_INJECT=zoo_cold``: the next submit sheds as if its
    model were cold and unpageable — exactly once."""
    clk = FakeClock()
    sched = _sched(_zoo(), clk)
    sched.ensure_resident("alpha")
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "zoo_cold@0")
    with pytest.raises(QueueFull, match="zoo_cold"):
        sched.submit("alpha", _x(1))
    f = sched.submit("alpha", _x(1))
    _drive(clk, sched)
    assert f.result(timeout=5) is not None


def test_unknown_model_refused():
    sched = _sched(_zoo(), FakeClock())
    with pytest.raises(MXNetError, match="alpha"):
        sched.submit("nope", _x(1))


# --------------------------------------------------------------- placement
def test_count_cap_evicts_coldest_and_never_strands():
    """One device, max_resident=1: paging beta in evicts alpha — and
    alpha's still-queued request completes BEFORE its executables are
    released (eviction never strands a future)."""
    clk = FakeClock()
    sched = _sched(_zoo(("alpha", "beta")), clk, max_resident=1)
    sched.ensure_resident("alpha")
    # drive demand so beta is the hot model when placement ranks
    fa = sched.submit("alpha", _x(1, seed=1))
    assert not fa.done()  # queued in alpha's batcher, not yet dispatched
    fb = sched.submit("beta", _x(2, seed=2))
    clk.advance(0.2)  # alpha's demand decays below beta's
    for _ in range(5):
        sched.submit("beta", _x(1, seed=3)).__class__  # heat beta up
        break
    _drive(clk, sched)
    # the eviction drained alpha first: its future delivered a result
    assert np.asarray(fa.result(timeout=5)).shape == (1, OUT_DIM)
    assert np.asarray(fb.result(timeout=5)).shape == (2, OUT_DIM)
    assert "beta" in sched._residents and "alpha" not in sched._residents
    assert telemetry.value("zoo.evictions", tag="alpha:capacity") == 1
    assert telemetry.gauge_value("zoo.hbm_resident_bytes", tag="alpha") == 0
    assert telemetry.gauge_value("zoo.resident_models") == 1


def test_hbm_budget_currency_eviction():
    """Byte-currency placement: a budget smaller than two resident
    footprints forces the coldest model out (the ledger-derived
    footprint is the shared currency, not a replica count)."""
    clk = FakeClock()
    sched = _sched(_zoo(("alpha", "beta")), clk)
    ra = sched.ensure_resident("alpha")
    assert ra.footprint > 0  # the ledger actually priced the model
    sched.hbm_budget = int(ra.footprint * 1.5)  # room for ~one model
    f = sched.submit("beta", _x(1))
    _drive(clk, sched)
    assert f.result(timeout=5) is not None
    assert telemetry.value("zoo.evictions", tag="alpha:capacity") == 1
    assert "alpha" not in sched._residents


def test_manual_evict_completes_queued_future():
    clk = FakeClock()
    sched = _sched(_zoo(), clk)
    sched.ensure_resident("alpha")
    f = sched.submit("alpha", _x(2))
    assert not f.done()
    sched.evict("alpha", "manual")
    assert np.asarray(f.result(timeout=5)).shape == (2, OUT_DIM)
    assert telemetry.value("zoo.evictions", tag="alpha:manual") == 1
    # the next submit takes the cold path again
    f2 = sched.submit("alpha", _x(1))
    _drive(clk, sched)
    assert f2.result(timeout=5) is not None
    assert telemetry.value("zoo.pageins", tag="alpha") == 2


def test_co_residency_preflight_overcommit():
    """Satellite: the warmup preflight sums co-resident footprints —
    a limit that fits one model alone but not the neighbourhood warns
    ``memory.overcommit{site}`` at page-in, before the OOM."""
    clk = FakeClock()
    sched = _sched(_zoo(("alpha", "beta")), clk)
    ra = sched.ensure_resident("alpha")
    site_b = ZOO_SITE + ".beta"
    assert telemetry.value("memory.overcommit", tag=site_b) == 0
    sched.ensure_resident("beta")
    fp_b = xprof.site_footprint(site_b, family=True)
    assert fp_b > 0
    # replay the preflight with a limit between beta-alone and
    # beta+alpha: alone fits, co-residency overcommits
    limit = fp_b + ra.footprint // 2
    assert xprof.preflight(site_b, limit=limit) == (fp_b, limit)
    assert telemetry.value("memory.overcommit", tag=site_b) == 0
    need, _ = xprof.preflight(site_b, limit=limit,
                              extra_bytes=ra.footprint)
    assert need == fp_b + ra.footprint > limit
    assert telemetry.value("memory.overcommit", tag=site_b) == 1


# ----------------------------------------------------------------- rollout
def test_canary_hash_routing_and_promote_zero_drops():
    clk = FakeClock()
    zoo = _zoo()
    sched = _sched(zoo, clk)
    sched.ensure_resident("alpha")
    zoo.add_version("alpha", "v2")
    out = zoo.deploy("alpha", "v2", canary_frac=0.5)
    assert out["mode"] == "canary"
    res = sched._residents["alpha"]
    futs = [sched.submit("alpha", _x(1, seed=i), request_id=i)
            for i in range(24)]
    # the deterministic hash split sent traffic to BOTH arms
    assert res.stable.batcher.queue_depth > 0
    assert res.canary.batcher.queue_depth > 0
    # same request id -> same arm, always (stable across retries)
    depth = res.canary.batcher.queue_depth
    promoted = sched.promote("alpha")
    assert promoted["mode"] == "promoted"
    # promote drained the canary arm mid-cohort; the stable queue
    # dispatches on the next polls — every future completes, no drops
    _drive(clk, sched)
    for f in futs:
        assert np.asarray(f.result(timeout=5)).shape == (1, OUT_DIM)
    assert res.canary is None
    assert zoo.active_version("alpha") == "v2"
    assert res.stable.predictor.param_version == "v2"
    assert telemetry.value("zoo.promotes", tag="alpha") == 1
    assert telemetry.value("serving.param_refreshes",
                           tag=ZOO_SITE + ".alpha") == 1
    assert depth > 0


def test_canary_injected_rollback_mid_cohort_zero_drops(monkeypatch):
    """``MXTPU_FAULT_INJECT=canary_rollback`` rules regression at the
    next gate tick: queued canary-cohort futures complete on the canary
    weights (zero drops), the stable version keeps serving."""
    clk = FakeClock()
    zoo = _zoo()
    sched = _sched(zoo, clk)
    sched.ensure_resident("alpha")
    zoo.add_version("alpha", "v2")
    zoo.deploy("alpha", "v2", canary_frac=0.5)
    res = sched._residents["alpha"]
    futs = [sched.submit("alpha", _x(1, seed=i), request_id=i)
            for i in range(24)]
    assert res.canary.batcher.queue_depth > 0  # mid-cohort
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "canary_rollback@0")
    sched.tick(clk())
    assert res.canary is None
    _drive(clk, sched)
    for f in futs:
        assert np.asarray(f.result(timeout=5)).shape == (1, OUT_DIM)
    assert zoo.active_version("alpha") == "v1"
    assert telemetry.value("zoo.rollbacks", tag="injected") == 1
    # post-rollback traffic all routes stable
    f = sched.submit("alpha", _x(1), request_id=999)
    _drive(clk, sched)
    assert f.result(timeout=5) is not None


def test_canary_slo_auto_rollback(monkeypatch):
    """The attainment gate: a canary whose requests keep missing their
    deadlines is rolled back automatically once the verdict window
    fills."""
    monkeypatch.setenv("MXTPU_ZOO_CANARY_WINDOW", "4")
    monkeypatch.setenv("MXTPU_ZOO_CANARY_FLOOR", "0.8")
    clk = FakeClock()
    zoo = _zoo()
    sched = _sched(zoo, clk)
    sched.ensure_resident("alpha")
    zoo.add_version("alpha", "v2")
    zoo.deploy("alpha", "v2", canary_frac=0.5)
    res = sched._residents["alpha"]
    arm = res.canary
    # drive misses straight into the canary arm's controller (the same
    # verdict path an expiring queued request takes)
    for _ in range(6):
        arm.ctrl.note_expired(clk(), meta={"tenant": "gold"})
    sched.tick(clk())
    assert res.canary is None
    assert telemetry.value("zoo.rollbacks", tag="slo") == 1
    assert zoo.active_version("alpha") == "v1"


def test_deploy_parity_probe_rolls_back():
    """An output-parity regression past the tolerance refuses the deploy
    at probe time — immediate rollback, stable untouched."""
    clk = FakeClock()
    zoo = _zoo()
    sched = _sched(zoo, clk)
    sched.ensure_resident("alpha")
    # v2 = wildly different weights: parity probe must flag it
    m = zoo._get("alpha")
    bad = {name: np.asarray(p.data().asnumpy()) * 100.0 + 7.0
           for name, p in m.block.collect_params().items()}
    zoo.add_version("alpha", "v2", params=bad)
    out = zoo.deploy("alpha", "v2", canary_frac=0.5,
                     parity_example=_x(2, seed=9), parity_tol=1e-3)
    assert out["mode"] == "rolled_back" and out["reason"] == "parity"
    assert sched._residents["alpha"].canary is None
    assert telemetry.value("zoo.rollbacks", tag="parity") == 1
    assert zoo.active_version("alpha") == "v1"
    # identical weights pass the same probe
    zoo.add_version("alpha", "v3")
    out = zoo.deploy("alpha", "v3", canary_frac=0.5,
                     parity_example=_x(2, seed=9), parity_tol=1e-3)
    assert out["mode"] == "canary"


def test_version_pinning_and_unknown_version():
    clk = FakeClock()
    zoo = _zoo()
    sched = _sched(zoo, clk)
    sched.ensure_resident("alpha")
    zoo.add_version("alpha", "v2")
    zoo.deploy("alpha", "v2", canary_frac=0.3)
    res = sched._residents["alpha"]
    f = sched.submit("alpha", _x(1), version="v2", request_id=1)
    assert res.canary.batcher.queue_depth == 1  # pinned past the hash
    with pytest.raises(MXNetError, match="not live"):
        sched.submit("alpha", _x(1), version="v9")
    _drive(clk, sched)
    assert f.result(timeout=5) is not None


def test_int8_stickiness_across_versioned_swap(monkeypatch):
    """Satellite: a canary promote on an int8 Predictor re-asserts the
    PR-11 quantization-eligibility pin — a degenerate (all-zero) weight
    in the new version keeps its int8 slot, the executables' argument
    structure never changes, and zero recompiles happen."""
    monkeypatch.setenv("MXTPU_SERVE_INT8", "1")
    clk = FakeClock()
    zoo = _zoo()
    sched = _sched(zoo, clk)
    sched.ensure_resident("alpha")
    res = sched._residents["alpha"]
    pred = res.stable.predictor
    assert pred.int8
    qd0 = list(pred._param_qdtypes)
    assert any(q is not None for q in qd0)
    compiles0 = telemetry.value("retrace." + ZOO_SITE + ".alpha")
    m = zoo._get("alpha")
    v2 = {name: np.zeros_like(p.data().asnumpy())
          for name, p in m.block.collect_params().items()}
    zoo.add_version("alpha", "v2", params=v2)
    zoo.deploy("alpha", "v2")  # direct promote through refresh_params
    assert pred.param_version == "v2"
    assert list(pred._param_qdtypes) == qd0  # the sticky pin held
    f = sched.submit("alpha", _x(2))
    _drive(clk, sched)
    np.testing.assert_allclose(np.asarray(f.result(timeout=5)), 0.0,
                               atol=1e-6)
    assert telemetry.value("retrace." + ZOO_SITE + ".alpha") == compiles0
    assert telemetry.gauge_value("zoo.active_version", tag="alpha") == 1


# ------------------------------------------------------------ tenancy/SLO
def test_tenant_classes_and_priority_isolation():
    """Per-tenant SLO classes under overload: the gold (interactive)
    tenant's request evicts free (batch) work instead of shedding, and
    every delivery/expiry verdict lands in that tenant's attainment
    counters."""
    clk = FakeClock()
    sched = _sched(_zoo(), clk,
                   batcher_kw={"max_queue": 4, "max_wait_ms": 5},
                   tenants={"gold": {"priority": "interactive",
                                     "deadline_ms": 500},
                            "free": {"priority": "batch",
                                     "deadline_ms": 500}})
    sched.ensure_resident("alpha")
    free_futs = [sched.submit("alpha", _x(1, seed=i), tenant="free")
                 for i in range(4)]
    # queue full of batch work: the gold submit evicts, never sheds
    gold = sched.submit("alpha", _x(2, seed=9), tenant="gold")
    assert telemetry.value("serving.shed", tag="priority_evict") >= 1
    evicted = [f for f in free_futs if f.done()]
    assert evicted  # newest batch entries failed with the evict verdict
    with pytest.raises(QueueFull):
        evicted[-1].result(timeout=0)
    _drive(clk, sched)
    assert np.asarray(gold.result(timeout=5)).shape == (2, OUT_DIM)
    survivors = [f for f in free_futs if f not in evicted]
    for f in survivors:
        assert f.result(timeout=5) is not None
    assert telemetry.gauge_value("serving.tenant_attainment",
                                 tag="gold") == 1.0
    gold2 = sched.submit("alpha", _x(1), tenant="gold")
    _drive(clk, sched)
    assert gold2.result(timeout=5) is not None
    ctrl = sched._residents["alpha"].stable.ctrl
    ta = ctrl.tenant_attainment(clk())
    assert ta["gold"] == 1.0 and "free" in ta
    assert "tenant_attainment" in ctrl.view()


def test_pagein_deadline_expiry_feeds_tenant_attainment():
    """A deadline that passes DURING the page-in fails the waiter with
    the same verdict a queued expiry gets — and the tenant's attainment
    sees the miss."""
    clk = FakeClock()
    sched = _sched(_zoo(), clk,
                   tenants={"gold": {"priority": "interactive",
                                     "deadline_ms": 50}})
    f = sched.submit("alpha", _x(1), tenant="gold")
    clk.advance(0.2)  # the page-in "takes" 200 ms on the request clock
    sched.poll()
    with pytest.raises(Exception, match="page-in"):
        f.result(timeout=1)
    assert telemetry.value("serving.deadline_expired") == 1
    ctrl = sched._residents["alpha"].stable.ctrl
    assert ctrl.tenant_attainment(clk()).get("gold", 1.0) == 0.0


# ---------------------------------------------------------------- registry
def test_registry_manifest_persisted(tmp_path):
    zoo = _zoo(manifest_dir=str(tmp_path))
    zoo.add_version("alpha", "v2")
    man = zoo.manifest()
    assert man["format"] == 1
    row = man["models"]["alpha"]
    assert row["active"] == "v1"
    assert set(row["versions"]) == {"v1", "v2"}
    assert row["versions"]["v2"]["ordinal"] == 1
    zoo.set_active("alpha", "v2")
    assert zoo.manifest()["models"]["alpha"]["active"] == "v2"


def test_registry_refusals():
    zoo = _zoo()
    with pytest.raises(MXNetError, match="already registered"):
        zoo.register("alpha", _mlp(), BucketSpec([1]))
    with pytest.raises(MXNetError, match="immutable"):
        zoo.add_version("alpha", "v1")
    with pytest.raises(MXNetError, match="unknown version"):
        zoo.version("alpha", "v9")
    with pytest.raises(MXNetError, match="A-Za-z0-9"):
        ModelZoo().register("bad name!", _mlp(), BucketSpec([1]))


def test_drain_fails_pending_and_sheds_new():
    clk = FakeClock()
    sched = _sched(_zoo(), clk)
    f = sched.submit("alpha", _x(1))  # pending behind the page-in
    assert sched.drain(timeout=1)
    with pytest.raises(QueueFull, match="draining"):
        f.result(timeout=1)
    with pytest.raises(QueueFull, match="draining"):
        sched.submit("alpha", _x(1))


# ------------------------------------------------- disk-warm no-compile
_PAGEIN_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MXTPU_COMPILE_CACHE_DIR"] = sys.argv[1]
import numpy as np
import mxtpu as mx
from mxtpu import telemetry
from mxtpu.gluon import nn
from mxtpu.serving import BucketSpec, ModelZoo, ZooScheduler

mx.random.seed(0)
net = nn.HybridSequential()
with net.name_scope():
    net.add(nn.Dense(8, activation="relu"))
    net.add(nn.Dense(4))
net.initialize()
net(mx.nd.array(np.ones((1, 6), np.float32)))

class Clock:
    t = 0.0
    def __call__(self):
        return self.t

zoo = ModelZoo()
zoo.register("m", net, BucketSpec([1, 4]),
             example=np.zeros((1, 6), np.float32))
sched = ZooScheduler(zoo, clock=Clock(), start=False)
res = sched.ensure_resident("m")
print("PAGEIN", res.warm_summary.get("disk", 0),
      res.warm_summary.get("built", 0),
      telemetry.value("retrace.serving.predict.zoo.m"))
"""


def test_pagein_zero_compiles_off_warm_disk_cache(tmp_path):
    """Acceptance gate: a page-in off a warm compile cache is a pure
    disk event — every bucket a disk hit, zero compiles reported at the
    model's retrace site."""
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)

    def run():
        p = subprocess.run([sys.executable, "-c", _PAGEIN_CHILD,
                            str(tmp_path)],
                           env=env, cwd=REPO, capture_output=True,
                           text=True, timeout=300)
        assert p.returncode == 0, p.stderr[-2000:]
        line = [ln for ln in p.stdout.splitlines()
                if ln.startswith("PAGEIN ")][0]
        return [int(v) for v in line.split()[1:]]

    disk1, built1, compiles1 = run()
    assert built1 == 2 and compiles1 == 2  # cold: one per bucket
    disk2, built2, compiles2 = run()
    assert disk2 == 2      # every bucket disk-served
    assert built2 == 0     # zero page-in compiles
    assert compiles2 == 0  # retrace.serving.predict.zoo.* stayed 0


# --------------------------------------------------------------- HTTP front
def test_server_zoo_routing_and_views():
    """The multi-model front: /predict routes by model name, 404s
    unknown models/versions with the known lists, /healthz carries the
    zoo block."""
    from tests.test_replica_serving import _http
    zoo = _zoo(("alpha", "beta"))
    sched = ZooScheduler(zoo, devices=[jax.devices()[0]], start=True)
    sched.set_tenant("gold", priority="interactive", deadline_ms=2000)
    srv = ModelServer(sched).start()
    try:
        x = _x(2, seed=5)
        code, out = _http(srv.address, "/predict",
                          {"model": "alpha", "data": x.tolist(),
                           "tenant": "gold"})
        assert code == 200 and out["n"] == 2
        code, out = _http(srv.address, "/predict",
                          {"model": "gamma", "data": x.tolist()})
        assert code == 404
        assert sorted(out["known_models"]) == ["alpha", "beta"]
        code, out = _http(srv.address, "/predict",
                          {"model": "alpha", "version": "v9",
                           "data": x.tolist()})
        assert code == 404 and out["known_versions"] == ["v1"]
        code, out = _http(srv.address, "/predict", {"data": x.tolist()})
        assert code == 400 and "model" in out["error"]
        code, health = _http(srv.address, "/healthz")
        assert code == 200
        z = health["zoo"]
        assert z["resident_models"] == 1
        assert z["models"]["alpha"]["resident"]
        assert z["models"]["alpha"]["stable_version"] == "v1"
        assert not z["models"]["beta"]["resident"]
        code, met = _http(srv.address, "/metrics")
        assert code == 200
        assert met["gauges"]["zoo.resident_models"] == 1
        assert met["gauges"]["zoo.hbm_resident_bytes"]["alpha"] >= 0
    finally:
        srv.close(timeout=10)
        sched.close(timeout=10)
