"""Initializer-zoo DEPTH tier (ref: tests/python/unittest/test_init.py +
the init checks inside test_gluon.py): deterministic initializers pinned
exactly, stochastic ones by distribution statistics, and the
pattern-dispatch machinery (Mixed, attrs) by behavior.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import init
from mxtpu.gluon import nn

RNG = np.random.RandomState


def _init_param(shape, initializer, name="weight"):
    net = nn.Dense(shape[0], in_units=shape[1], use_bias=False)
    net.initialize(initializer)
    return net.weight.data().asnumpy()


def test_zero_one_constant():
    assert (_init_param((4, 3), init.Zero()) == 0).all()
    assert (_init_param((4, 3), init.One()) == 1).all()
    assert (_init_param((4, 3), init.Constant(2.5)) == 2.5).all()


def test_uniform_and_normal_ranges():
    mx.random.seed(0)
    w = _init_param((64, 128), init.Uniform(0.2))
    assert np.abs(w).max() <= 0.2 + 1e-6
    assert np.abs(w).mean() > 0.05          # actually spread out
    mx.random.seed(0)
    w = _init_param((64, 128), init.Normal(0.05))
    assert abs(w.std() - 0.05) < 0.005
    assert abs(w.mean()) < 0.005


@pytest.mark.parametrize("factor,expected_fan", [
    ("in", "fan_in"), ("out", "fan_out"), ("avg", "avg")])
def test_xavier_scale_matches_fan(factor, expected_fan):
    mx.random.seed(0)
    nin, nout, mag = 300, 150, 3.0
    w = _init_param((nout, nin), init.Xavier(rnd_type="uniform",
                                             factor_type=factor,
                                             magnitude=mag))
    fans = {"fan_in": nin, "fan_out": nout, "avg": (nin + nout) / 2}
    scale = np.sqrt(mag / fans[expected_fan])
    assert np.abs(w).max() <= scale + 1e-6
    # a U(-s, s) sample of this size has std ~ s/sqrt(3)
    assert abs(w.std() - scale / np.sqrt(3)) < 0.1 * scale


def test_xavier_gaussian_and_msra():
    mx.random.seed(0)
    nin, nout = 400, 200
    w = _init_param((nout, nin), init.Xavier(rnd_type="gaussian",
                                             factor_type="in", magnitude=2))
    assert abs(w.std() - np.sqrt(2.0 / nin)) < 0.1 * np.sqrt(2.0 / nin)
    mx.random.seed(0)
    slope = 0.25
    w = _init_param((nout, nin), init.MSRAPrelu(factor_type="in",
                                                slope=slope))
    want = np.sqrt(2.0 / (1 + slope ** 2) / nin)
    assert abs(w.std() - want) < 0.1 * want


def test_orthogonal_rows_are_orthonormal():
    mx.random.seed(0)
    scale = 1.414
    w = _init_param((16, 64), init.Orthogonal(scale=scale))
    gram = (w / scale) @ (w / scale).T
    np.testing.assert_allclose(gram, np.eye(16), atol=1e-4)


def test_bilinear_kernel_is_separable_triangle():
    from mxtpu.ndarray.ndarray import NDArray
    import jax.numpy as jnp
    arr = mx.nd.array(np.zeros((2, 1, 4, 4), np.float32))
    init.Bilinear()("weight", arr)
    w = arr.asnumpy()
    f = np.ceil(4 / 2.0)
    c = (2 * f - 1 - f % 2) / (2.0 * f)
    tri = np.array([1 - abs(x / f - c) for x in range(4)])
    np.testing.assert_allclose(w[0, 0], np.outer(tri, tri), rtol=1e-6)
    np.testing.assert_allclose(w[1, 0], w[0, 0], rtol=1e-6)  # same per filter


def test_lstm_bias_forget_gate_only():
    """Per-param LSTMBias must survive the *bias -> zeros name dispatch:
    the chosen initializer rides InitDesc attrs (reference mechanism),
    regression for bias_initializer being silently zeroed."""
    net = nn.Dense(8, in_units=2,
                   bias_initializer=init.LSTMBias(forget_bias=1.0))
    net.initialize()
    b = net.bias.data().asnumpy()      # 4 gates x 2 hidden
    np.testing.assert_allclose(b[2:4], 1.0)   # forget gate block
    np.testing.assert_allclose(b[:2], 0.0)
    np.testing.assert_allclose(b[4:], 0.0)


def test_mixed_initializer_pattern_dispatch():
    """Mixed maps name patterns to initializers (ref: Module init_params
    usage; like the reference, Mixed is not itself an Initializer and is
    called per-(name, array))."""
    m = init.Mixed([".*special.*", ".*"],
                   [init.Constant(7.0), init.One()])
    a = mx.nd.array(np.zeros((3,), np.float32))
    b = mx.nd.array(np.zeros((3,), np.float32))
    m("special_weight", a)
    m("plain_weight", b)
    np.testing.assert_allclose(a.asnumpy(), 7.0)
    np.testing.assert_allclose(b.asnumpy(), 1.0)
    with pytest.raises(Exception):
        init.Mixed(["nomatch"], [init.One()])("other_weight", a)


def test_initializer_create_registry_and_repr():
    for name, cls in [("zero", init.Zero), ("uniform", init.Uniform),
                      ("xavier", init.Xavier), ("normal", init.Normal)]:
        o = init.create(name) if hasattr(init, "create") else cls()
        assert isinstance(o, cls)


def test_parameter_init_override_beats_global():
    """Per-parameter init= overrides the initialize(default) argument
    (ref: Parameter(init=...) precedence)."""
    net = nn.Dense(4, in_units=3, weight_initializer=init.One(),
                   bias_initializer=init.Constant(3.0))
    net.initialize(init.Zero())
    np.testing.assert_allclose(net.weight.data().asnumpy(), 1.0)
    np.testing.assert_allclose(net.bias.data().asnumpy(), 3.0)


def test_force_reinit_changes_values():
    net = nn.Dense(4, in_units=3)
    net.initialize(init.One())
    np.testing.assert_allclose(net.weight.data().asnumpy(), 1.0)
    net.initialize(init.Zero())              # no-op without force_reinit
    np.testing.assert_allclose(net.weight.data().asnumpy(), 1.0)
    net.initialize(init.Zero(), force_reinit=True)
    np.testing.assert_allclose(net.weight.data().asnumpy(), 0.0)
