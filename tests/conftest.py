"""Test environment: force an 8-device virtual CPU mesh before jax initializes,
mirroring SURVEY §4's implication — multi-chip collective tests must run on a single
host the way the reference runs multi-process localhost PS tests.

Cross-device tier (the reference's tests/python/gpu/test_operator_gpu.py
pattern — the WHOLE op suite re-run against the accelerator): set
``MXTPU_TEST_PLATFORM=tpu`` to leave the real backend active instead of
the hermetic CPU mesh. Tests requiring >1 device are skipped there (one
chip); everything else exercises the identical code paths on real
hardware. Usage: ``MXTPU_TEST_PLATFORM=tpu python -m pytest
tests/test_operator.py tests/test_operator_sweep.py ...``.
"""
import os

_PLATFORM = os.environ.get("MXTPU_TEST_PLATFORM", "cpu")

if _PLATFORM == "cpu":
    # the environment presets JAX_PLATFORMS=axon (the TPU tunnel); tests
    # force CPU so the suite is hermetic and the 8-device virtual mesh is
    # available. The axon sitecustomize calls jax config programmatically
    # (jax_platforms='axon,cpu'), which overrides the env var — so the
    # config must be updated via jax.config, not os.environ.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if _PLATFORM == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "multidevice: needs the 8-device virtual CPU mesh or spawns a "
        "multi-process world; skipped on the single-chip TPU tier")
    config.addinivalue_line(
        "markers",
        "slow: exceeds the tier-1 wall-clock budget (interpret-mode "
        "Pallas kernels at real shapes etc.); tier-1 runs -m 'not slow'")


def pytest_collection_modifyitems(config, items):
    if _PLATFORM == "cpu":
        return
    # accelerator tier: a single real chip — skip tests explicitly marked
    # as needing the multi-device mesh (a name-substring heuristic used
    # here previously wrongly matched e.g. test_orde[ring])
    multi = pytest.mark.skip(
        reason="needs the 8-device virtual CPU mesh (MXTPU_TEST_PLATFORM)")
    for item in items:
        if item.get_closest_marker("multidevice") is not None:
            item.add_marker(multi)


@pytest.fixture(autouse=True)
def _seed():
    """@with_seed equivalent (ref: tests/python/unittest/common.py)."""
    np.random.seed(0)
    import mxtpu as mx
    mx.random.seed(0)
    yield
