"""Test environment: force an 8-device virtual CPU mesh before jax initializes,
mirroring SURVEY §4's implication — multi-chip collective tests must run on a single
host the way the reference runs multi-process localhost PS tests."""
import os

# the environment presets JAX_PLATFORMS=axon (the TPU tunnel); tests force CPU so
# the suite is hermetic and the 8-device virtual mesh is available. The axon
# sitecustomize calls jax config programmatically (jax_platforms='axon,cpu'),
# which overrides the env var — so the config must be updated via jax.config,
# not os.environ.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    """@with_seed equivalent (ref: tests/python/unittest/common.py)."""
    np.random.seed(0)
    import mxtpu as mx
    mx.random.seed(0)
    yield
