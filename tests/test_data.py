"""Tests for recordio (native C++ + python fallback), gluon.data, image
(ref patterns: tests/python/unittest/test_recordio.py, test_gluon_data.py,
test_image.py)."""
import os
import struct

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import recordio
from mxtpu.gluon.data import (ArrayDataset, BatchSampler, DataLoader,
                              RandomSampler, SequentialSampler, SimpleDataset)
from mxtpu.gluon.data.vision import transforms


# ----------------------------------------------------------------- recordio
def _roundtrip(tmp_path, force_python):
    path = str(tmp_path / ("py.rec" if force_python else "cc.rec"))
    records = [b"hello", b"x" * 1000, b"",
               # payloads containing the magic word at aligned offsets
               struct.pack("<I", 0xced7230a) * 3,
               b"abcd" + struct.pack("<I", 0xced7230a) + b"efgh"]
    if force_python:
        w = recordio._PyWriter(path, "wb")
        for r in records:
            w.write(r)
        w.close()
        r = recordio._PyReader(path)
        got = []
        while True:
            rec = r.read()
            if rec is None:
                break
            got.append(rec)
        r.close()
    else:
        w = recordio.MXRecordIO(path, "w")
        for rec in records:
            w.write(rec)
        w.close()
        r = recordio.MXRecordIO(path, "r")
        got = []
        while True:
            rec = r.read()
            if rec is None:
                break
            got.append(rec)
        r.close()
    assert got == records


def test_recordio_roundtrip_native(tmp_path):
    from mxtpu._native import get_lib, build_error
    lib = get_lib()
    assert lib is not None, "native build failed: %s" % build_error()
    _roundtrip(tmp_path, force_python=False)


def test_recordio_roundtrip_python(tmp_path):
    _roundtrip(tmp_path, force_python=True)


def test_recordio_native_python_interop(tmp_path):
    """Files written by the C++ writer must read back via the python reader
    and vice versa (same wire format)."""
    path = str(tmp_path / "interop.rec")
    records = [b"one", struct.pack("<I", 0xced7230a) + b"tail", b"x" * 37]
    w = recordio.MXRecordIO(path, "w")  # native if available
    for r in records:
        w.write(r)
    w.close()
    r = recordio._PyReader(path)
    got = []
    while True:
        rec = r.read()
        if rec is None:
            break
        got.append(rec)
    r.close()
    assert got == records


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "indexed.rec")
    idx_path = str(tmp_path / "indexed.idx")
    w = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(20):
        w.write_idx(i, b"record_%d" % i)
    w.close()
    r = recordio.MXIndexedRecordIO(idx_path, path, "r")
    assert r.keys == list(range(20))
    assert r.read_idx(13) == b"record_13"
    assert r.read_idx(4) == b"record_4"
    r.close()


def test_pack_unpack_with_label():
    header = recordio.IRHeader(0, np.array([1.0, 2.0], np.float32), 7, 0)
    packed = recordio.pack(header, b"payload")
    h, payload = recordio.unpack(packed)
    assert payload == b"payload"
    np.testing.assert_allclose(h.label, [1.0, 2.0])
    assert h.id == 7


def test_pack_img_unpack_img():
    img = np.random.RandomState(0).randint(
        0, 255, size=(32, 32, 3)).astype(np.uint8)
    header = recordio.IRHeader(0, 3.0, 0, 0)
    s = recordio.pack_img(header, img, quality=100, img_fmt=".png")
    h, decoded = recordio.unpack_img(s)
    assert h.label == 3.0
    np.testing.assert_array_equal(decoded, img)  # png is lossless


# -------------------------------------------------------------- gluon.data
def test_array_dataset_and_samplers():
    x = np.arange(20).reshape(10, 2).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    ds = ArrayDataset(x, y)
    assert len(ds) == 10
    xi, yi = ds[3]
    np.testing.assert_allclose(xi, x[3])
    assert list(SequentialSampler(5)) == [0, 1, 2, 3, 4]
    assert sorted(RandomSampler(5)) == [0, 1, 2, 3, 4]
    bs = BatchSampler(SequentialSampler(10), 4, "keep")
    assert [len(b) for b in bs] == [4, 4, 2]
    bs = BatchSampler(SequentialSampler(10), 4, "discard")
    assert [len(b) for b in bs] == [4, 4]


def test_dataloader_batches():
    x = np.random.uniform(size=(17, 3)).astype(np.float32)
    y = np.arange(17).astype(np.float32)
    loader = DataLoader(ArrayDataset(x, y), batch_size=5, last_batch="keep")
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (5, 3)
    assert batches[-1][0].shape == (2, 3)
    np.testing.assert_allclose(batches[0][1].asnumpy(), y[:5])


def test_dataloader_workers_match_serial():
    x = np.random.uniform(size=(23, 4)).astype(np.float32)
    ds = ArrayDataset(x, np.arange(23).astype(np.float32))
    serial = [b[1].asnumpy() for b in DataLoader(ds, batch_size=4)]
    threaded = [b[1].asnumpy()
                for b in DataLoader(ds, batch_size=4, num_workers=3)]
    assert len(serial) == len(threaded)
    for a, b in zip(serial, threaded):
        np.testing.assert_allclose(a, b)


def test_dataset_transform_first():
    x = np.ones((6, 2), np.float32)
    ds = ArrayDataset(x, np.arange(6).astype(np.float32))
    t = ds.transform_first(lambda d: d * 2)
    xd, yd = t[1]
    np.testing.assert_allclose(xd, [2, 2])
    assert yd == 1.0


# -------------------------------------------------------------- transforms
def test_transforms_pipeline():
    img = mx.nd.array(np.random.RandomState(0).randint(
        0, 255, size=(40, 30, 3)).astype(np.uint8))
    t = transforms.Compose([
        transforms.Resize((24, 24)),
        transforms.ToTensor(),
        transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(0.25, 0.25, 0.25)),
    ])
    out = t(img)
    assert out.shape == (3, 24, 24)
    assert out.dtype == np.float32


def test_to_tensor_and_normalize_values():
    img = mx.nd.array(np.full((4, 4, 3), 255, np.uint8))
    t = transforms.ToTensor()(img)
    np.testing.assert_allclose(t.asnumpy(), np.ones((3, 4, 4)), rtol=1e-6)
    n = transforms.Normalize(mean=1.0, std=0.5)(t)
    np.testing.assert_allclose(n.asnumpy(), np.zeros((3, 4, 4)), atol=1e-6)


def test_random_resized_crop_shape():
    img = mx.nd.array(np.random.randint(
        0, 255, size=(50, 60, 3)).astype(np.uint8))
    out = transforms.RandomResizedCrop(32)(img)
    assert out.shape == (32, 32, 3)


def test_record_dataset_threaded_loader_no_race(tmp_path):
    """Concurrent workers reading one RecordIO handle must not interleave
    seek+read (regression: corrupted/None records under num_workers>1)."""
    path = str(tmp_path / "race.rec")
    idx_path = str(tmp_path / "race.idx")
    w = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(64):
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0),
            b"payload_%03d" % i + b"x" * (i * 7 % 100)))
    w.close()
    from mxtpu.gluon.data import RecordFileDataset
    ds = RecordFileDataset(path)
    for trial in range(3):
        loader = DataLoader(ds, batch_size=4, num_workers=4,
                            batchify_fn=lambda recs: recs)
        seen = []
        for batch in loader:
            for rec in batch:
                h, payload = recordio.unpack(rec)
                assert payload.startswith(b"payload_%03d" % int(h.label))
                seen.append(int(h.label))
        assert sorted(seen) == list(range(64))


# ------------------------------------------------------- image record e2e
def test_image_record_dataset_and_iter(tmp_path):
    """Pack images into RecordIO, read back via ImageRecordDataset and
    ImageIter (the reference's full decode path, SURVEY §3.5)."""
    rng = np.random.RandomState(0)
    path = str(tmp_path / "imgs.rec")
    idx_path = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx_path, path, "w")
    originals = []
    for i in range(8):
        img = rng.randint(0, 255, size=(36, 36, 3)).astype(np.uint8)
        originals.append(img)
        header = recordio.IRHeader(0, float(i % 3), i, 0)
        w.write_idx(i, recordio.pack_img(header, img, img_fmt=".png"))
    w.close()

    from mxtpu.gluon.data.vision import ImageRecordDataset
    ds = ImageRecordDataset(path)
    assert len(ds) == 8
    img, label = ds[2]
    assert img.shape == (36, 36, 3)
    assert label == 2.0

    from mxtpu.image import ImageIter
    it = ImageIter(batch_size=4, data_shape=(3, 32, 32), path_imgrec=path,
                   rand_crop=False, rand_mirror=False)
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3, 32, 32)
    assert batch.label[0].shape == (4,)


def test_image_det_iter():
    """Detection iterator: padded object labels, box-aware flip
    (ref: python/mxnet/image/detection.py ImageDetIter; feeds the SSD
    multibox ops)."""
    import numpy as np
    import mxtpu as mx
    from mxtpu.image import ImageDetIter
    from mxtpu.image.detection import DetHorizontalFlipAug

    # two in-memory "images" via imglist: label = [A=4, B=5, pad, pad,
    # objects...]
    import cv2
    import tempfile, os
    tmp = tempfile.mkdtemp()
    paths = []
    for i in range(3):
        p = os.path.join(tmp, "img%d.png" % i)
        cv2.imwrite(p, np.random.randint(0, 255, (40, 60, 3), np.uint8))
        paths.append(p)
    # one object for img0, two for img1, one for img2
    lab0 = [4, 5, 0, 0, 1.0, 0.1, 0.2, 0.5, 0.6]
    lab1 = [4, 5, 0, 0, 0.0, 0.0, 0.0, 0.3, 0.3,
            2.0, 0.5, 0.5, 0.9, 0.9]
    lab2 = [4, 5, 0, 0, 1.0, 0.2, 0.2, 0.4, 0.4]
    imglist = [lab0 + [paths[0]], lab1 + [paths[1]], lab2 + [paths[2]]]
    it = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                      imglist=imglist, path_root="")
    batch = next(iter(it))
    assert batch.data[0].shape == (2, 3, 32, 32)
    lab = batch.label[0].asnumpy()
    assert lab.shape == (2, 2, 5)  # padded to max 2 objects
    np.testing.assert_allclose(lab[0, 0], [1.0, 0.1, 0.2, 0.5, 0.6],
                               atol=1e-6)
    assert lab[0, 1, 0] == -1.0  # padding row

    # flip adjusts boxes: x -> 1 - x (always flip)
    flip = DetHorizontalFlipAug(p=1.1)
    img = np.zeros((10, 10, 3), np.float32)
    objs = np.array([[1.0, 0.1, 0.2, 0.5, 0.6]], np.float32)
    _, flipped = flip(img, objs)
    np.testing.assert_allclose(flipped[0], [1.0, 0.5, 0.2, 0.9, 0.6],
                               atol=1e-6)


def test_rec2idx_rebuilds_index(tmp_path):
    """tools/rec2idx.py (ref tools/rec2idx.py): a rebuilt .idx must make
    the pack readable by key through MXIndexedRecordIO."""
    import os
    import sys
    from mxtpu.recordio import MXIndexedRecordIO, MXRecordIO

    rec = str(tmp_path / "pack.rec")
    w = MXRecordIO(rec, "w")
    payloads = [("rec%03d" % i).encode() * (i + 1) for i in range(7)]
    for p in payloads:
        w.write(p)
    w.close()

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import rec2idx
    idx_path, n = rec2idx.build_index(rec)
    assert n == 7
    r = MXIndexedRecordIO(idx_path, rec, "r")
    for i in (0, 3, 6):
        assert r.read_idx(i) == payloads[i]
    r.close()


def test_rec2idx_refuses_truncated_pack(tmp_path):
    import os
    import sys
    import pytest
    from mxtpu.recordio import MXRecordIO

    rec = str(tmp_path / "trunc.rec")
    w = MXRecordIO(rec, "w")
    for i in range(5):
        w.write(b"x" * 100)
    w.close()
    with open(rec, "r+b") as f:  # chop mid-record
        f.truncate(os.path.getsize(rec) - 37)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import rec2idx
    with pytest.raises(RuntimeError, match="corrupt/truncated"):
        rec2idx.build_index(rec)


def test_imageiter_preprocess_threads_match_serial(tmp_path):
    """ImageIter(preprocess_threads=N) — the v2 iterator's parallel
    decode stage (ref: src/io/iter_image_recordio_2.cc:672) — must
    produce exactly the serial batches for deterministic augmenters."""
    import numpy as np
    import mxtpu as mx
    from mxtpu import recordio

    cv2 = pytest.importorskip("cv2")
    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    for i in range(10):
        img = rng.randint(0, 255, (40, 40, 3), np.uint8)
        ok, buf = cv2.imencode(".png", img)  # png: lossless roundtrip
        assert ok
        hdr = recordio.IRHeader(0, float(i), i, 0)
        w.write_idx(i, recordio.pack(hdr, buf.tobytes()))
    w.close()

    def run(threads):
        it = mx.image.ImageIter(batch_size=4, data_shape=(3, 32, 32),
                                path_imgrec=rec, path_imgidx=idx,
                                resize=32, preprocess_threads=threads)
        out = []
        for b in it:
            out.append((b.data[0].asnumpy(), b.label[0].asnumpy(), b.pad))
        return out

    serial, threaded = run(0), run(4)
    assert len(serial) == len(threaded) == 3
    for (sd, sl, sp), (td, tl, tp) in zip(serial, threaded):
        np.testing.assert_array_equal(sd, td)
        np.testing.assert_array_equal(sl, tl)
        assert sp == tp
    assert serial[-1][2] == 2  # 10 samples, batch 4 -> last pad 2

    # threaded iter under the PrefetchingIter double buffer still agrees
    from mxtpu.io import PrefetchingIter
    it = mx.image.ImageIter(batch_size=4, data_shape=(3, 32, 32),
                            path_imgrec=rec, path_imgidx=idx,
                            resize=32, preprocess_threads=4)
    pre = PrefetchingIter(it)
    got = [b.data[0].asnumpy() for b in pre]
    assert len(got) == len(serial)
    for s, g in zip(serial, got):
        np.testing.assert_array_equal(s[0], g)


def test_mnist_iter_idx_format(tmp_path):
    """mx.io.MNISTIter over the standard idx-ubyte files
    (ref: src/io/iter_mnist.cc — 1/256 normalization, flat option,
    full-batch-only epochs, deterministic seeded shuffle)."""
    import gzip
    import struct

    import numpy as np
    import mxtpu as mx

    n = 10
    rng = np.random.RandomState(3)
    imgs = rng.randint(0, 255, (n, 28, 28), np.uint8)
    lbls = (np.arange(n) % 10).astype(np.uint8)
    img_path = str(tmp_path / "imgs-idx3-ubyte.gz")
    lbl_path = str(tmp_path / "lbls-idx1-ubyte.gz")
    with gzip.open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(lbls.tobytes())

    it = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=4,
                         shuffle=False, silent=True)
    batches = list(it)
    assert len(batches) == 2              # tail of 2 dropped (full-batch only)
    d = batches[0].data[0].asnumpy()
    assert d.shape == (4, 1, 28, 28)
    np.testing.assert_allclose(d, imgs[:4, None] / 256.0, rtol=1e-6)
    np.testing.assert_array_equal(batches[0].label[0].asnumpy(), lbls[:4])

    flat = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=4,
                           shuffle=False, flat=True, silent=True)
    assert next(iter(flat)).data[0].shape == (4, 784)

    # seeded shuffle reproduces
    a = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=4,
                        shuffle=True, seed=7, silent=True)
    b = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=4,
                        shuffle=True, seed=7, silent=True)
    np.testing.assert_array_equal(next(iter(a)).label[0].asnumpy(),
                                  next(iter(b)).label[0].asnumpy())


def test_image_record_iter_reference_spelling(tmp_path):
    """mx.io.ImageRecordIter — the reference's registered name with its
    flat mean_r/g/b params (src/io/iter_image_recordio_2.cc:736)."""
    import numpy as np
    import mxtpu as mx
    from mxtpu import recordio

    cv2 = pytest.importorskip("cv2")
    rec, idx = str(tmp_path / "r.rec"), str(tmp_path / "r.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(6):
        img = np.full((36, 36, 3), 30 * i, np.uint8)
        ok, buf = cv2.imencode(".png", img)
        w.write_idx(i, recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                                     buf.tobytes()))
    w.close()
    it = mx.io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                               data_shape=(3, 32, 32), batch_size=3,
                               resize=32, mean_r=10.0, mean_g=10.0,
                               mean_b=10.0, preprocess_threads=2)
    b = next(iter(it))
    assert b.data[0].shape == (3, 3, 32, 32)
    # mean was subtracted: first image is all zeros -> -10 after mean
    np.testing.assert_allclose(b.data[0].asnumpy()[0], -10.0, atol=1e-5)
    np.testing.assert_array_equal(b.label[0].asnumpy(), [0.0, 1.0, 2.0])


def test_mnist_iter_rejects_unknown_options(tmp_path):
    import mxtpu as mx
    from mxtpu.base import MXNetError
    with pytest.raises(MXNetError, match="unknown options"):
        mx.io.MNISTIter(image="x", label="y", shufle=False)


def test_image_record_iter_std_without_mean_not_dropped(tmp_path):
    import numpy as np
    import mxtpu as mx
    from mxtpu import recordio
    cv2 = pytest.importorskip("cv2")
    rec, idx = str(tmp_path / "s.rec"), str(tmp_path / "s.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    img = np.full((32, 32, 3), 100, np.uint8)
    ok, buf = cv2.imencode(".png", img)
    w.write_idx(0, recordio.pack(recordio.IRHeader(0, 0.0, 0, 0),
                                 buf.tobytes()))
    w.close()
    it = mx.io.ImageRecordIter(path_imgrec=rec, path_imgidx=idx,
                               data_shape=(3, 32, 32), batch_size=1,
                               std_r=2.0, std_g=2.0, std_b=2.0)
    b = next(iter(it))
    np.testing.assert_allclose(b.data[0].asnumpy(), 50.0, atol=1e-4)
