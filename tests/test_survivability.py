"""Survivable training (ISSUE 14): the training half's failure matrix —

* step-wedge watchdog: a wedged Trainer.step trips a rolling-baseline
  deadline, dumps flight_record("train_wedge") with the step's trace_id
  + per-thread stacks + ledger/memory view, and fails LOUD — all on a
  fake clock, sleep-free (fault kind ``train_wedge``);
* checkpoint integrity + tiered restore: save_trainer writes a per-blob
  crc manifest, restore verifies BEFORE committing, a corrupt newest
  step is tombstoned and resume falls back bit-exact to the older
  intact step (fault kind ``ckpt_corrupt``; real on-disk byte flips too);
  retention GC never deletes the newest intact step;
* cross-replica divergence sentinel: the fused update jit emits a
  fingerprint compiled into the SAME executable (compiles stay flat,
  d2h stays 0); an injected divergent shard view dumps
  flight_record("divergence") and raises;
* poison-batch quarantine: MXTPU_POISON_STREAK consecutive skips ring
  the offending steps + trace ids, flight-record, and raise/continue;
* crash-resume supervisor: jittered respawns under a budget, poison
  (same-step-twice) refusal diagnosis — subprocess- and sleep-free.
"""
import glob
import json
import os
import random

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import optimizer_fused as of
from mxtpu import resilience, telemetry
from mxtpu.contrib import async_checkpoint as ackpt
from mxtpu.gluon.parameter import Parameter
from mxtpu.gluon.trainer import Trainer
from mxtpu.monitor import TrainingHealthMonitor


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_NUMERICS_GUARD", "MXTPU_FAULT_INJECT",
                "MXTPU_DIVERGENCE_EVERY", "MXTPU_TRAIN_STEP_TIMEOUT_X",
                "MXTPU_POISON_STREAK", "MXTPU_CKPT_KEEP",
                "MXTPU_FLIGHT_DIR", "MXTPU_FLIGHT_MAX",
                "MXTPU_SUPERVISOR_RESTARTS", "MXTPU_SUPERVISOR_BACKOFF_S"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    of.reset()
    yield
    telemetry.reset()
    resilience.reset_faults()
    of.reset()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _make_trainer(n_params=3, shape=(5,), optimizer="sgd", opt_params=None,
                  seed=0):
    rng = np.random.RandomState(seed)
    params = []
    for j in range(n_params):
        p = Parameter("sv%d" % j, shape=shape, dtype="float32")
        p.initialize()
        p.data()._set_data(mx.nd.array(
            rng.uniform(-1, 1, shape).astype(np.float32))._data)
        params.append(p)
    opt_params = opt_params or {"learning_rate": 0.05, "momentum": 0.9}
    tr = Trainer(params, optimizer, opt_params, kvstore=None)
    return tr, params, rng


def _set_grads(params, rng, scale=1.0):
    for p in params:
        p.grad()[:] = mx.nd.array(
            (rng.randn(*p.shape) * scale).astype(np.float32))


def _counter(name):
    v = telemetry.snapshot()["counters"].get(name, 0)
    return sum(v.values()) if isinstance(v, dict) else v


def _artifacts(tmp_path, reason):
    return sorted(glob.glob(os.path.join(str(tmp_path),
                                         "flight_%s_*" % reason)))


# ------------------------------------------------------ step-wedge watchdog
def test_watchdog_baseline_and_deadline():
    clk = FakeClock()
    wd = resilience.TrainStepWatchdog(timeout_x=5.0, min_timeout_s=0.0,
                                      min_samples=3, clock=clk)
    assert wd.deadline_s() is None  # warmup: nothing to derive from
    for i in range(4):
        e = wd.arm(i)
        assert e["deadline"] is None or i >= 3
        clk.advance(0.1)
        wd.disarm(e)
    # rolling median of 0.1s durations x 5.0
    assert wd.baseline() == pytest.approx(0.1)
    assert wd.deadline_s() == pytest.approx(0.5)
    # the floor guards against a too-tight baseline
    wd2 = resilience.TrainStepWatchdog(timeout_x=5.0, min_timeout_s=2.0,
                                       min_samples=1, clock=clk)
    e = wd2.arm(0)
    clk.advance(0.01)
    wd2.disarm(e)
    assert wd2.deadline_s() == 2.0


def test_wedged_step_trips_dumps_and_fails_loud(tmp_path, monkeypatch):
    """ISSUE-14 acceptance (a): a fake-clock run wedges a step — the trip
    dumps a flight artifact carrying the step's trace_id and per-thread
    stacks, bumps train.wedges, poll() raises, and the NEXT step on the
    (poisoned) watchdog refuses too. Sleep-free."""
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "train_wedge@3")
    clk = FakeClock()
    wd = resilience.TrainStepWatchdog(timeout_x=5.0, min_timeout_s=1.0,
                                      min_samples=1, clock=clk)
    tr, params, rng = _make_trainer()
    tr.attach_step_watchdog(wd)
    for _ in range(2):  # healthy steps build the baseline
        _set_grads(params, rng)
        tr.step(1)
    _set_grads(params, rng)
    tr.step(1)  # seq 3: the injected wedge — its entry stays armed
    upd = tr._updaters[0]
    wedged_trace = upd._step_traces[upd._step_count - 1]
    clk.advance(100.0)
    with pytest.raises(resilience.TrainWedgeError, match="step 3 wedged"):
        wd.poll()
    assert _counter("train.wedges") == 1
    arts = _artifacts(tmp_path, "train_wedge")
    assert len(arts) == 1
    snap = json.load(open(arts[0]))
    assert snap["trace_ids"] == [wedged_trace]
    assert snap["threads"] and any(s["stack"] for s in snap["threads"])
    assert "ledger" in snap["extra"] and "memory" in snap["extra"]
    assert snap["extra"]["step"] == 3
    # the watchdog is poisoned: the training thread's next step fails loud
    _set_grads(params, rng)
    with pytest.raises(resilience.TrainWedgeError):
        tr.step(1)


def test_watchdog_monitor_lifecycle():
    wd = resilience.TrainStepWatchdog(timeout_x=5.0)
    assert wd.start_monitor(0.01) is wd
    assert wd.start_monitor(0.01) is wd  # idempotent
    assert wd._monitor is not None and wd._monitor.is_alive()
    wd.stop_monitor()
    assert wd._monitor is None


def test_trainer_env_wires_watchdog(monkeypatch):
    monkeypatch.setenv("MXTPU_TRAIN_STEP_TIMEOUT_X", "10")
    tr, _, _ = _make_trainer()
    assert tr._step_watchdog is not None
    assert tr._step_watchdog.timeout_x == 10.0
    tr._step_watchdog.stop_monitor()
    monkeypatch.delenv("MXTPU_TRAIN_STEP_TIMEOUT_X")
    tr2, _, _ = _make_trainer()
    assert tr2._step_watchdog is None


# ------------------------------------------------------ divergence sentinel
def test_divergence_fingerprint_emitted_and_deterministic(monkeypatch):
    monkeypatch.setenv("MXTPU_DIVERGENCE_EVERY", "1")

    def run():
        tr, params, rng = _make_trainer(seed=4)
        for _ in range(2):
            _set_grads(params, rng)
            tr.step(1)
        fp = tr._updaters[0].last_fingerprint
        assert fp is not None
        return (float(fp[0]), int(fp[1]))
    a, b = run(), run()
    assert a == b  # pure function of the (identical) training state
    # and it moves when the state moves
    tr, params, rng = _make_trainer(seed=4)
    for _ in range(3):
        _set_grads(params, rng)
        tr.step(1)
    fp3 = tr._updaters[0].last_fingerprint
    assert (float(fp3[0]), int(fp3[1])) != a


def test_divergence_check_cadence_via_monitor(monkeypatch):
    monkeypatch.setenv("MXTPU_DIVERGENCE_EVERY", "2")
    tr, params, rng = _make_trainer()
    mon = TrainingHealthMonitor(interval=100).install(tr)
    assert mon.divergence_every == 2  # env default picked up
    for _ in range(5):
        _set_grads(params, rng)
        tr.step(1)
        mon.after_step()
    assert mon._sentinel.checks == 2  # after steps 2 and 4
    assert _counter("resilience.divergence_checks") == 2


def test_injected_divergence_dumps_and_raises(tmp_path, monkeypatch):
    """ISSUE-14 acceptance (c): a divergent shard fingerprint view dumps
    flight_record("divergence") and raises."""
    monkeypatch.setenv("MXTPU_DIVERGENCE_EVERY", "1")
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    tr, params, rng = _make_trainer()
    mon = TrainingHealthMonitor(interval=100).install(tr)
    _set_grads(params, rng)
    tr.step(1)
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "divergence@0")
    with pytest.raises(resilience.DivergenceError, match="divergence"):
        mon.after_step()
    arts = _artifacts(tmp_path, "divergence")
    assert len(arts) == 1
    snap = json.load(open(arts[0]))
    assert snap["extra"]["fingerprints"]  # every replica's view rides along


def test_divergence_skipped_step_fingerprint_unchanged(monkeypatch):
    """A sentinel-skipped step is a no-op on params AND state — its
    fingerprint must be bit-identical to the previous step's (replicas
    agree on skips too)."""
    monkeypatch.setenv("MXTPU_DIVERGENCE_EVERY", "1")
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@1")
    tr, params, rng = _make_trainer()
    _set_grads(params, rng)
    tr.step(1)
    fp0 = tr._updaters[0].last_fingerprint
    fp0 = (float(fp0[0]), int(fp0[1]))
    _set_grads(params, rng)
    ok = tr.step(1)  # poisoned -> skip
    assert bool(ok.asnumpy()) is False
    fp1 = tr._updaters[0].last_fingerprint
    assert (float(fp1[0]), int(fp1[1])) == fp0


def test_divergence_flip_is_one_recompile_and_flat(monkeypatch):
    """ISSUE-14 acceptance: flipping MXTPU_DIVERGENCE_EVERY on is at most
    one recompile (cache key + policy key), steady-state compiles flat
    with the sentinel ON, and guard+divergence compose."""
    tr, params, rng = _make_trainer()
    _set_grads(params, rng)
    tr.step(1)
    assert of.FUSED_STATS["compiles"] == 1
    monkeypatch.setenv("MXTPU_DIVERGENCE_EVERY", "4")
    _set_grads(params, rng)
    tr.step(1)
    assert of.FUSED_STATS["compiles"] == 2  # exactly one more
    traces = of.FUSED_STATS["traces"]
    for _ in range(3):
        _set_grads(params, rng)
        tr.step(1)
    assert of.FUSED_STATS["traces"] == traces  # flat with the sentinel on
    assert of.FUSED_STATS["compiles"] == 2
    # guard on top: one more (guard bit + div bit in one key)
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    _set_grads(params, rng)
    tr.step(1)
    assert of.FUSED_STATS["compiles"] == 3


def test_survivability_stack_keeps_zero_host_sync(monkeypatch):
    """ISSUE-14 acceptance: trainer.step d2h == 0 with the watchdog AND
    the divergence sentinel enabled — the bracket is host bookkeeping,
    the fingerprint is an async output nobody fetches in the loop."""
    import jax
    monkeypatch.setenv("MXTPU_DIVERGENCE_EVERY", "1")
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    clk = FakeClock()
    wd = resilience.TrainStepWatchdog(timeout_x=50.0, min_timeout_s=10.0,
                                      min_samples=1, clock=clk)
    tr, params, rng = _make_trainer(optimizer="adam",
                                    opt_params={"learning_rate": 0.01})
    tr.attach_step_watchdog(wd)
    _set_grads(params, rng)
    tr.step(1)  # warmup + compile
    with jax.transfer_guard_device_to_host("disallow"):
        for _ in range(3):
            _set_grads(params, rng)
            ok = tr.step(1)
            assert ok is not None
            clk.advance(0.01)
    # the verdicts and fingerprint are still there, fetchable off-path
    assert tr._updaters[0].health.ok_history()[-3:] == [True] * 3
    assert tr._updaters[0].last_fingerprint is not None


# -------------------------------------------- checkpoint integrity + tiers
def _ckpt_trainer(seed=3):
    tr, params, _ = _make_trainer(optimizer="adam",
                                  opt_params={"learning_rate": 0.05},
                                  seed=seed)
    return tr, params


def _train_and_save(tr, params, d, steps_saves):
    rng = np.random.RandomState(17)
    snaps = {}
    step = 0
    for save_at in steps_saves:
        while step < save_at:
            _set_grads(params, rng)
            tr.step(1)
            step += 1
        ackpt.save_trainer(tr, d, step=save_at)
        snaps[save_at] = [p.data().asnumpy().copy() for p in params]
    return snaps


def test_save_trainer_writes_crc_manifest(tmp_path):
    tr, params = _ckpt_trainer()
    _train_and_save(tr, params, str(tmp_path), [1])
    meta = ackpt._read_meta(ackpt._step_dir(str(tmp_path), 1))
    crc = meta["crc"]
    assert set(crc) == {"p%d" % j for j in range(len(params))} \
        | {"updater", "rng"}
    assert all(isinstance(v, int) for v in crc.values())


def test_corrupt_newest_falls_back_one_tier_bit_exact(tmp_path):
    """ISSUE-14 acceptance (b): corrupt the newest checkpoint on disk —
    restore falls back one tier and resumes BIT-EXACT from the older
    step; the fallback is counted."""
    d = str(tmp_path)
    tr, params = _ckpt_trainer()
    snaps = _train_and_save(tr, params, d, [1, 3])
    # flip bytes through every file of the newest step
    for f in glob.glob(os.path.join(d, "step_3", "**"), recursive=True):
        if os.path.isfile(f):
            with open(f, "r+b") as fh:
                data = bytearray(fh.read())
                for i in range(0, len(data), 7):
                    data[i] ^= 0xFF
                fh.seek(0)
                fh.write(data)
    tr2, params2 = _ckpt_trainer(seed=9)  # fresh process stand-in
    step = ackpt.load_trainer_fallback(tr2, d)
    assert step == 1
    for a, b in zip(snaps[1], [p.data().asnumpy() for p in params2]):
        np.testing.assert_array_equal(a, b)
    assert _counter("checkpoint.restore_fallbacks") >= 1


def test_ckpt_corrupt_fault_exercises_checksum_tier(tmp_path, monkeypatch):
    """Fault kind ckpt_corrupt: the saved blob's bytes flip after the
    manifest — verification fails (checksum reason), the step is
    tombstoned, latest_step skips it, resume lands on the older step."""
    d = str(tmp_path)
    tr, params = _ckpt_trainer()
    snaps = _train_and_save(tr, params, d, [1])
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "ckpt_corrupt@0")
    _train_and_save(tr, params, d, [3])
    assert resilience.FAULT_STATS["fired"] == [("ckpt_corrupt", 0)]
    assert ackpt.latest_step(d) == 3  # not yet known-corrupt
    tr2, params2 = _ckpt_trainer(seed=9)
    assert ackpt.load_trainer_fallback(tr2, d) == 1
    for a, b in zip(snaps[1], [p.data().asnumpy() for p in params2]):
        np.testing.assert_array_equal(a, b)
    snap = telemetry.snapshot()["counters"]["checkpoint.restore_fallbacks"]
    assert snap == {"checksum": 1}
    # tombstoned: every later scan skips without re-reading the bytes
    assert os.path.exists(os.path.join(d, "step_3.corrupt.json"))
    assert ackpt.latest_step(d) == 1


def test_verify_happens_before_commit(tmp_path, monkeypatch):
    """A corrupt restore must never half-overwrite the live trainer:
    params are bit-identical to pre-restore after the refusal."""
    d = str(tmp_path)
    tr, params = _ckpt_trainer()
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "ckpt_corrupt@0")
    _train_and_save(tr, params, d, [2])
    tr2, params2 = _ckpt_trainer(seed=9)
    before = [p.data().asnumpy().copy() for p in params2]
    with pytest.raises(ackpt.CheckpointCorrupt):
        ackpt.load_trainer(tr2, d, step=2)
    for a, b in zip(before, [p.data().asnumpy() for p in params2]):
        np.testing.assert_array_equal(a, b)
    assert ackpt.load_trainer_fallback(tr2, d) is None  # nothing intact


def test_resilient_loop_resume_uses_tiers(tmp_path, monkeypatch):
    d = str(tmp_path)
    tr, params = _ckpt_trainer()
    loop = resilience.ResilientLoop(
        tr, resilience.CheckpointPolicy(d, every_steps=100))
    rng = np.random.RandomState(0)
    _set_grads(params, rng)
    tr.step(1)
    assert loop.save(1) is True
    loop.wait_for_pending()
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "ckpt_corrupt@0")
    _set_grads(params, rng)
    tr.step(1)
    assert loop.save(4) is True
    loop.wait_for_pending()
    monkeypatch.delenv("MXTPU_FAULT_INJECT")
    resilience.reset_faults()
    tr2, params2 = _ckpt_trainer(seed=9)
    loop2 = resilience.ResilientLoop(
        tr2, resilience.CheckpointPolicy(d, every_steps=100))
    assert loop2.resume() == 2  # fell back from corrupt step 4 to step 1


# ------------------------------------------------------------- retention GC
def test_gc_retains_keep_newest_intact(tmp_path, monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv("MXTPU_CKPT_KEEP", "2")
    tr, params = _ckpt_trainer()
    _train_and_save(tr, params, d, [1, 3, 5, 7])
    assert ackpt._finalized_steps(d) == [5, 7]
    assert ackpt.latest_step(d) == 7
    # sidecars of deleted steps are gone too
    assert not glob.glob(os.path.join(d, "step_1.*"))


def test_gc_keep1_never_deletes_newest_intact(tmp_path, monkeypatch):
    """Satellite: KEEP=1 with the latest save mid-write or known-corrupt
    must keep the newest INTACT step."""
    d = str(tmp_path)
    tr, params = _ckpt_trainer()
    _train_and_save(tr, params, d, [1])
    # (a) latest is known-corrupt (tombstoned): step 3 saved corrupt,
    # restore tombstones it, then a KEEP=1 GC pass runs on the NEXT save
    with pytest.MonkeyPatch.context() as mp:
        mp.setenv("MXTPU_FAULT_INJECT", "ckpt_corrupt@0")
        _train_and_save(tr, params, d, [3])
    resilience.reset_faults()
    tr2, _ = _ckpt_trainer(seed=9)
    assert ackpt.load_trainer_fallback(tr2, d) == 1  # tombstones step 3
    monkeypatch.setenv("MXTPU_CKPT_KEEP", "1")
    deleted = ackpt._gc_steps(d, 1)
    assert deleted == []  # step 1 IS the newest intact: survives
    assert ackpt.latest_step(d) == 1
    # (b) latest save mid-write: a sidecar with no finalized dir — the
    # newest finalized step stays the keeper
    ackpt._write_meta(ackpt._step_dir(d, 9), {"kind": "trainer"})
    assert ackpt._gc_steps(d, 1) == []
    assert ackpt.latest_step(d) == 1


def test_force_resave_clears_tombstone_and_manifest(tmp_path, monkeypatch):
    """Satellite: overwrite + force=True writes a FRESH manifest and
    clears the step's tombstone — the re-saved bytes verify again."""
    d = str(tmp_path)
    tr, params = _ckpt_trainer()
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "ckpt_corrupt@0")
    _train_and_save(tr, params, d, [2])
    resilience.reset_faults()
    monkeypatch.delenv("MXTPU_FAULT_INJECT")
    tr2, _ = _ckpt_trainer(seed=9)
    assert ackpt.load_trainer_fallback(tr2, d) is None  # tombstoned
    # overwrite without force refuses (manifest or not)
    with pytest.raises(mx.MXNetError, match="force=True"):
        ackpt.save_trainer(tr, d, step=2)
    ackpt.save_trainer(tr, d, step=2, force=True)
    assert not os.path.exists(os.path.join(d, "step_2.corrupt.json"))
    assert ackpt.latest_step(d) == 2
    tr3, params3 = _ckpt_trainer(seed=11)
    assert ackpt.load_trainer_fallback(tr3, d) == 2  # fresh crc verifies
    for a, b in zip([p.data().asnumpy() for p in params],
                    [p.data().asnumpy() for p in params3]):
        np.testing.assert_array_equal(a, b)


# --------------------------------------------------- poison-batch quarantine
def _guarded_trainer(monkeypatch, streak, on_poison="raise"):
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    tr, params, rng = _make_trainer()
    mon = TrainingHealthMonitor(interval=1, poison_streak=streak,
                                on_poison=on_poison).install(tr)
    return tr, params, rng, mon


def test_poison_streak_quarantines_and_raises(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@2,3")
    tr, params, rng, mon = _guarded_trainer(monkeypatch, streak=2)
    with pytest.raises(resilience.PoisonBatchError, match="2 CONSECUTIVE"):
        for _ in range(5):
            _set_grads(params, rng)
            tr.step(1)
            mon.after_step()
    assert len(mon.quarantined) == 1
    entry = mon.quarantined[0]
    assert entry["steps"] == [2, 3]
    # trace attribution: the steps' owning trace ids ride the ring + dump
    traces = tr._updaters[0]._step_traces
    assert entry["trace_ids"] == [traces[2], traces[3]]
    arts = _artifacts(tmp_path, "poison_batch")
    assert len(arts) == 1
    assert json.load(open(arts[0]))["trace_ids"] == entry["trace_ids"]
    assert _counter("resilience.poison_quarantines") == 1


def test_poison_continue_policy_keeps_training(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@1,2")
    tr, params, rng, mon = _guarded_trainer(monkeypatch, streak=2,
                                            on_poison="continue")
    for _ in range(5):
        _set_grads(params, rng)
        tr.step(1)
        mon.after_step()
    assert len(mon.quarantined) == 1  # quarantined, run continued
    assert _counter("resilience.poison_quarantines") == 1


def test_poison_streak_resets_on_good_step(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@1,3")  # broken run
    tr, params, rng, mon = _guarded_trainer(monkeypatch, streak=2)
    for _ in range(5):
        _set_grads(params, rng)
        tr.step(1)
        mon.after_step()  # never raises: the streak broke at step 2
    assert len(mon.quarantined) == 0


# ------------------------------------------------------ crash-resume driver
def test_supervisor_respawns_with_jittered_backoff():
    delays = []
    exits = iter([1, 1, 0])
    ck_steps = iter([None, 0, 3, 3, 5])  # progresses between crashes
    sup = resilience.TrainSupervisor(
        ["train"], spawn=lambda argv: next(exits),
        sleeper=delays.append, rng=random.Random(0),
        backoff_s=1.0, max_restarts=5)
    sup._latest = lambda: next(ck_steps)
    assert sup.run() == 0
    assert sup.restarts == 2
    assert _counter("supervisor.restarts") == 2
    assert delays[0] == 1.0
    assert 1.0 <= delays[1] <= 3.0  # decorrelated jitter bounds
    # seeded rng => deterministic schedule
    delays2 = []
    exits2 = iter([1, 1, 0])
    ck2 = iter([None, 0, 3, 3, 5])
    sup2 = resilience.TrainSupervisor(
        ["train"], spawn=lambda argv: next(exits2),
        sleeper=delays2.append, rng=random.Random(0),
        backoff_s=1.0, max_restarts=5)
    sup2._latest = lambda: next(ck2)
    sup2.run()
    assert delays2 == delays


def test_supervisor_same_step_twice_refuses_with_poison_diagnosis():
    """ISSUE-14 acceptance (d): the child crashes twice on the same
    checkpoint step — the supervisor refuses with the poison-crash
    diagnosis instead of flapping. Sleep-free (injected sleeper)."""
    sup = resilience.TrainSupervisor(
        ["train"], spawn=lambda argv: 1, sleeper=lambda s: None,
        rng=random.Random(0), backoff_s=0.1, max_restarts=10)
    sup._latest = lambda: 7
    with pytest.raises(resilience.SupervisorRefusal,
                       match="poison-crash") as e:
        sup.run()
    assert "step 7" in str(e.value)
    assert sup.restarts == 1  # one respawn, then the diagnosis


def test_supervisor_budget_refusal_and_injected_crash(monkeypatch):
    steps = iter(range(100))  # always progressing: transient crashes
    sup = resilience.TrainSupervisor(
        ["train"], spawn=lambda argv: 1, sleeper=lambda s: None,
        rng=random.Random(0), backoff_s=0.1, max_restarts=3)
    sup._latest = lambda: next(steps)
    with pytest.raises(resilience.SupervisorRefusal, match="crash-loop"):
        sup.run()
    assert sup.restarts == 3
    # fault kind supervisor_crash: a clean exit treated as a crash
    resilience.reset_faults()
    telemetry.reset()
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "supervisor_crash@0")
    steps2 = iter(range(100))
    sup2 = resilience.TrainSupervisor(
        ["train"], spawn=lambda argv: 0, sleeper=lambda s: None,
        rng=random.Random(0), backoff_s=0.1, max_restarts=5)
    sup2._latest = lambda: next(steps2)
    assert sup2.run() == 0  # second attempt's clean exit sticks
    assert sup2.restarts == 1
    snap = telemetry.snapshot()["counters"]["supervisor.restarts"]
    assert snap == {"injected": 1}


def test_supervisor_reads_intact_checkpoint_view(tmp_path, monkeypatch):
    """The supervisor's progress signal is the INTACT latest step — a
    tombstoned newest checkpoint reads as the older step."""
    d = str(tmp_path)
    tr, params = _ckpt_trainer()
    _train_and_save(tr, params, d, [1])
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "ckpt_corrupt@0")
    _train_and_save(tr, params, d, [3])
    resilience.reset_faults()
    tr2, _ = _ckpt_trainer(seed=9)
    ackpt.load_trainer_fallback(tr2, d)  # tombstones step 3
    sup = resilience.TrainSupervisor(["train"], ckpt_dir=d)
    assert sup._latest() == 1


def test_supervisor_no_checkpoint_signal_is_transient_not_poison():
    """Review regression: without a progress signal (no ckpt_dir, or the
    child dies before the first checkpoint lands) crash_step is None on
    every attempt — that must run the budget+backoff path, NOT
    misdiagnose a deterministic poison-crash after one restart."""
    delays = []
    sup = resilience.TrainSupervisor(
        ["train"], ckpt_dir=None, spawn=lambda argv: 1,
        sleeper=delays.append, rng=random.Random(0), backoff_s=0.1,
        max_restarts=4)
    with pytest.raises(resilience.SupervisorRefusal, match="crash-loop"):
        sup.run()
    assert sup.restarts == 4 and len(delays) == 4  # budget consumed


def test_divergence_cadence_value_not_in_policy_key(monkeypatch):
    """Review regression: only the ON BIT of MXTPU_DIVERGENCE_EVERY is
    trace-time — retuning the compare cadence must not invalidate every
    policy_key-keyed forward/serving executable."""
    from mxtpu.ops.registry import policy_key
    monkeypatch.setenv("MXTPU_DIVERGENCE_EVERY", "8")
    k8 = policy_key()
    monkeypatch.setenv("MXTPU_DIVERGENCE_EVERY", "16")
    assert policy_key() == k8  # cadence retune: same executables
    monkeypatch.delenv("MXTPU_DIVERGENCE_EVERY")
    assert policy_key() != k8  # the on/off flip IS a policy change


def test_attach_step_watchdog_stops_replaced_monitor(monkeypatch):
    """Review regression: replacing the env-built watchdog must stop its
    monitor thread (and a dropped watchdog's monitor must not pin it)."""
    monkeypatch.setenv("MXTPU_TRAIN_STEP_TIMEOUT_X", "10")
    tr, _, _ = _make_trainer()
    old = tr._step_watchdog
    assert old._monitor is not None and old._monitor.is_alive()
    clk = FakeClock()
    wd = resilience.TrainStepWatchdog(timeout_x=5.0, clock=clk)
    tr.attach_step_watchdog(wd)
    assert old._monitor is None  # replaced => monitor stopped
    tr.attach_step_watchdog(None)


def test_process_rng_reseeds_per_pid(monkeypatch):
    """Review regression: the fleet jitter rng is resolved per PID at use
    time, so a fork-started worker draws its OWN schedule instead of a
    copy of the parent's import-time state."""
    a = resilience._process_rng()
    assert resilience._process_rng() is a  # stable within a process
    real_pid = os.getpid()
    monkeypatch.setattr(os, "getpid", lambda: real_pid + 12345)
    b = resilience._process_rng()
    assert b is not a
    monkeypatch.setattr(os, "getpid", lambda: real_pid)
    seq_parent = [resilience._process_rng().uniform(0, 1)
                  for _ in range(3)]
    monkeypatch.setattr(os, "getpid", lambda: real_pid + 12345)
    seq_child = [resilience._process_rng().uniform(0, 1)
                 for _ in range(3)]
    assert seq_parent != seq_child  # de-correlated schedules


def test_supervisor_cli_clean_child(tmp_path):
    """The CLI front door: a clean child is one spawn, exit 0."""
    import sys

    from tools import train_supervisor
    rc = train_supervisor.main(
        ["--ckpt-dir", str(tmp_path), "--backoff-s", "0.01", "--",
         sys.executable, "-c", "import sys; sys.exit(0)"])
    assert rc == 0


# --------------------------------------------------------------- bench gate
def test_integrity_overhead_bench_schema(monkeypatch):
    """bench.py's integrity_overhead config emits per-(config, mode) JSON
    lines plus a serve_bench-style gate summary — the artifact the <2%
    survivability budget is read from."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench
    assert "integrity_overhead" in bench.CONFIGS
    monkeypatch.setenv("BENCH_GUARD_PARAMS", "4")
    monkeypatch.setenv("BENCH_GUARD_PARAM_SIZE", "32")
    monkeypatch.setenv("BENCH_GUARD_STEPS", "10")
    monkeypatch.setenv("BENCH_INTEGRITY_ROUNDS", "2")
    monkeypatch.setenv("BENCH_INTEGRITY_CONFIGS", "optimizer_step")
    lines = []
    rec = bench.bench_integrity_overhead(
        emit=lambda r: lines.append(bench._stamp(r)))
    assert {"metric", "value", "unit", "vs_baseline", "mfu", "hfu",
            "gates", "ok"} <= set(rec)
    assert set(rec["gates"]) == {"overhead_budget", "retrace_flat",
                                 "divergence_checks", "no_wedges"}
    # the stack really ran: sentinel checked, compiles flat, no wedges
    assert rec["gates"]["retrace_flat"] is True
    assert rec["gates"]["divergence_checks"] is True
    assert rec["gates"]["no_wedges"] is True
    assert rec["ok"] is True  # host tier: budget reported, not gating
    modes = {(l.get("metric"), l.get("integrity")) for l in lines}
    assert ("integrity_overhead_optimizer_step", "off") in modes
    assert ("integrity_overhead_optimizer_step", "on") in modes
