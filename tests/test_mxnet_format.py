"""Reference (.params, 0x112) serialization parity.

The load-path fixtures here are constructed byte-by-byte from the C++
serializer's documented layout (ref: src/ndarray/ndarray.cc:1574-1806,
include/mxnet/base.h:188 Context::Save, nnvm Tuple::Save) — NOT via this
repo's writer — so the reader is checked against the wire format itself,
exactly what a file written by real MXNet contains.
"""
import struct

import numpy as np
import pytest

import mxtpu as mx
from mxtpu.base import MXNetError
from mxtpu.ndarray import mxnet_format

V2 = 0xF993FAC9
V1 = 0xF993FAC8


def _tshape(*dims):
    return struct.pack("<I", len(dims)) + \
        np.asarray(dims, "<i8").tobytes()


def _dense_v2(a, dev_type=1):
    # NDARRAY_V2_MAGIC, stype 0, shape, context, dtype flag, raw data
    flag = {np.dtype(np.float32): 0, np.dtype(np.float64): 1,
            np.dtype(np.uint8): 3, np.dtype(np.int32): 4,
            np.dtype(np.int64): 6}[a.dtype]
    return (struct.pack("<I", V2) + struct.pack("<i", 0)
            + _tshape(*a.shape) + struct.pack("<ii", dev_type, 0)
            + struct.pack("<i", flag) + a.tobytes())


def _file(records, names):
    blob = struct.pack("<QQ", 0x112, 0)
    blob += struct.pack("<Q", len(records)) + b"".join(records)
    blob += struct.pack("<Q", len(names))
    for n in names:
        blob += struct.pack("<Q", len(n)) + n.encode()
    return blob


def test_load_handwritten_v2_dense_dict(tmp_path):
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    b = np.array([7, 8, 9], dtype=np.int64)
    p = tmp_path / "ref.params"
    # dev_type 2 (GPU in the writer's context) must still load to host
    p.write_bytes(_file([_dense_v2(a, dev_type=2), _dense_v2(b)],
                        ["arg:w", "aux:s"]))
    out = mx.nd.load(str(p))
    assert set(out) == {"arg:w", "aux:s"}
    np.testing.assert_array_equal(out["arg:w"].asnumpy(), a)
    np.testing.assert_array_equal(out["aux:s"].asnumpy(), b)
    # int64 payload survives; the NDArray layer may narrow to int32 (jax
    # x64-disabled default) but values are exact
    assert out["aux:s"].asnumpy().dtype in (np.int32, np.int64)


def test_load_handwritten_v2_list(tmp_path):
    a = np.random.RandomState(0).rand(4).astype(np.float32)
    p = tmp_path / "ref_list.params"
    p.write_bytes(_file([_dense_v2(a)], []))
    out = mx.nd.load(str(p))
    assert isinstance(out, list) and len(out) == 1
    np.testing.assert_array_equal(out[0].asnumpy(), a)


def test_load_handwritten_legacy_records(tmp_path):
    a = np.arange(4, dtype=np.float32).reshape(2, 2)
    # V1: magic, i64 shape, context, dtype, data (no storage type field)
    v1 = (struct.pack("<I", V1) + _tshape(2, 2)
          + struct.pack("<ii", 1, 0) + struct.pack("<i", 0) + a.tobytes())
    # pre-V1: leading u32 IS ndim, dims are u32 (ref LegacyTShapeLoad)
    pre = (struct.pack("<I", 2) + np.asarray([2, 2], "<u4").tobytes()
           + struct.pack("<ii", 1, 0) + struct.pack("<i", 0) + a.tobytes())
    p = tmp_path / "legacy.params"
    p.write_bytes(_file([v1, pre], ["v1", "pre"]))
    out = mx.nd.load(str(p))
    np.testing.assert_array_equal(out["v1"].asnumpy(), a)
    np.testing.assert_array_equal(out["pre"].asnumpy(), a)


def test_load_handwritten_csr(tmp_path):
    # 2x4 csr: values [1, 2, 3], indptr [0, 2, 3], indices [0, 3, 1]
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    indptr = np.array([0, 2, 3], np.int64)
    idx = np.array([0, 3, 1], np.int64)
    rec = (struct.pack("<I", V2) + struct.pack("<i", 2)   # stype csr
           + _tshape(3)                                   # storage shape
           + _tshape(2, 4)                                # shape
           + struct.pack("<ii", 1, 0) + struct.pack("<i", 0)
           + struct.pack("<i", 6) + _tshape(3)            # aux0: indptr
           + struct.pack("<i", 6) + _tshape(3)            # aux1: indices
           + vals.tobytes() + indptr.tobytes() + idx.tobytes())
    p = tmp_path / "csr.params"
    p.write_bytes(_file([rec], ["w"]))
    out = mx.nd.load(str(p))["w"]
    assert out.stype == "csr"
    dense = np.array([[1, 0, 0, 2], [0, 3, 0, 0]], np.float32)
    np.testing.assert_array_equal(out.todense().asnumpy(), dense)


def test_roundtrip_writes_reference_bytes(tmp_path):
    d = {"arg:fc_w": mx.nd.array(np.random.RandomState(1).rand(3, 2)
                                 .astype(np.float32)),
         "aux:bn_mean": mx.nd.array(np.zeros(2, np.float32))}
    p = tmp_path / "rt.params"
    mx.nd.save(str(p), d)
    raw = p.read_bytes()
    assert struct.unpack("<Q", raw[:8])[0] == 0x112  # reference magic
    out = mx.nd.load(str(p))
    for k in d:
        np.testing.assert_array_equal(out[k].asnumpy(), d[k].asnumpy())


def test_roundtrip_sparse(tmp_path):
    dense = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0]], np.float32)
    csr = mx.nd.array(dense).tostype("csr")
    rs = mx.nd.array(dense).tostype("row_sparse")
    p = tmp_path / "sp.params"
    mx.nd.save(str(p), {"csr": csr, "rs": rs})
    assert struct.unpack("<Q", p.read_bytes()[:8])[0] == 0x112
    out = mx.nd.load(str(p))
    np.testing.assert_array_equal(out["csr"].todense().asnumpy(), dense)
    np.testing.assert_array_equal(out["rs"].todense().asnumpy(), dense)
    assert out["csr"].stype == "csr" and out["rs"].stype == "row_sparse"


def test_bf16_falls_back_to_native(tmp_path):
    d = {"w": mx.nd.ones((2, 2)).astype("bfloat16")}
    p = tmp_path / "bf16.params"
    mx.nd.save(str(p), d)
    assert p.read_bytes()[:8] == b"MXTPU001"  # no bf16 in the ref format
    out = mx.nd.load(str(p))
    assert str(out["w"].dtype) == "bfloat16"
    # explicit reference format upcasts (documented loss to f32)
    p2 = tmp_path / "bf16_ref.params"
    mx.nd.save(str(p2), d, format="mxnet")
    assert struct.unpack("<Q", p2.read_bytes()[:8])[0] == 0x112
    np.testing.assert_array_equal(mx.nd.load(str(p2))["w"].asnumpy(),
                                  np.ones((2, 2), np.float32))


def test_gluon_parameters_use_reference_format(tmp_path):
    from mxtpu.gluon import nn
    net = nn.Dense(3, in_units=4)
    net.initialize()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    assert struct.unpack("<Q", open(f, "rb").read(8))[0] == 0x112
    net2 = nn.Dense(3, in_units=4)
    net2.load_parameters(f)
    np.testing.assert_array_equal(net2.weight.data().asnumpy(),
                                  net.weight.data().asnumpy())


def test_truncated_file_raises(tmp_path):
    a = np.zeros((2, 2), np.float32)
    blob = _file([_dense_v2(a)], ["w"])
    p = tmp_path / "trunc.params"
    p.write_bytes(blob[:len(blob) // 2])
    with pytest.raises(MXNetError, match="truncated"):
        mx.nd.load(str(p))


def test_dumps_loads_symmetry():
    items = [("default", np.arange(5, dtype=np.float32))]
    blob = mxnet_format.dumps(items, ["x"])
    back, names = mxnet_format.loads(blob)
    assert names == ["x"]
    np.testing.assert_array_equal(back[0][1], items[0][1])


def test_nonencodable_dtypes_fall_back_to_native(tmp_path):
    """bool/int16 have no mshadow flag: default save must pick the native
    format and round-trip the dtype exactly."""
    d = {"b": mx.nd.array(np.array([1, 0, 1], np.bool_).astype(np.float32)
                          > 0.5)}
    # NDArray bool support varies; exercise via int16 which numpy carries
    a16 = np.array([1, -2, 3], np.int16)
    p = tmp_path / "i16.params"
    mx.nd.save(str(p), {"w": mx.nd.array(a16.astype(np.float32))})
    # f32 is encodable -> reference format
    assert struct.unpack("<Q", p.read_bytes()[:8])[0] == 0x112


def test_scalar_arrays_preserved_via_native_fallback(tmp_path):
    """Rank-0 has NO reference encoding (ndim-0 TShape means 'none' to
    the reference reader): forced mxnet format refuses, auto save picks
    the native format and preserves the rank."""
    with pytest.raises(MXNetError, match="rank-0"):
        mxnet_format.dumps([("default", np.float32(3.0).reshape(()))],
                           ["s"])
    p = tmp_path / "scalar.params"
    mx.nd.save(str(p), {"s": mx.nd.array(3.0)})
    assert p.read_bytes()[:8] == b"MXTPU001"
    out = mx.nd.load(str(p))
    assert out["s"].shape == () and float(out["s"].asnumpy()) == 3.0


def test_committed_reference_fixture():
    """A COMMITTED reference-format artifact must keep loading forever
    (the reference's tests/python/legacy_ndarray.v0 pattern): V2 dense
    (incl. a GPU-context record and an f64), V1 and pre-V1 legacy
    records, and a CSR record, with the expected values pinned here."""
    import os
    path = os.path.join(os.path.dirname(__file__), "fixtures",
                        "reference_format.params")
    out = mx.nd.load(path)
    a = np.arange(24, dtype=np.float32).reshape(2, 3, 4) * 0.5 - 3
    np.testing.assert_array_equal(out["arg:conv0_weight"].asnumpy(), a)
    np.testing.assert_array_equal(out["aux:stat_f64"].asnumpy(),
                                  np.array([[1, -2], [3, -4]], np.float64))
    np.testing.assert_array_equal(out["legacy:v1_u8"].asnumpy(),
                                  np.array([250, 7, 13]))
    np.testing.assert_array_equal(out["legacy:pre_v1_i32"].asnumpy(),
                                  np.array([[9, 8], [7, 6]]))
    csr = out["sparse:csr"]
    np.testing.assert_array_equal(
        csr.todense().asnumpy(),
        np.array([[0, 0, 1.5], [-2.5, 0, 0]], np.float32))
