"""Model zoo tests (ref: tests/python/unittest/test_gluon_model_zoo.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.gluon.model_zoo import vision


@pytest.mark.parametrize("name", [
    "resnet18_v1", "resnet18_v2", "mobilenet0_25", "mobilenet_v2_0_25",
    "squeezenet1_0", "squeezenet1_1", "alexnet",
])
def test_model_forward(name):
    net = vision.get_model(name, classes=10)
    net.initialize()
    x = mx.nd.random.uniform(shape=(1, 3, 224, 224))
    y = net(x)
    assert y.shape == (1, 10)
    assert np.isfinite(y.asnumpy()).all()


def test_model_zoo_registry():
    # every reference model name resolves (model_zoo/vision/__init__.py)
    for name in ["resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
                 "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
                 "resnet101_v2", "resnet152_v2", "vgg11", "vgg13", "vgg16",
                 "vgg19", "vgg11_bn", "vgg13_bn", "vgg16_bn", "vgg19_bn",
                 "alexnet", "densenet121", "densenet161", "densenet169",
                 "densenet201", "squeezenet1_0", "squeezenet1_1",
                 "inception_v3", "mobilenet1_0", "mobilenet0_75",
                 "mobilenet0_5", "mobilenet0_25", "mobilenet_v2_1_0",
                 "mobilenet_v2_0_75", "mobilenet_v2_0_5", "mobilenet_v2_0_25"]:
        assert name in vision._models, name
    with pytest.raises(mx.MXNetError):
        vision.get_model("resnet19_v9")


def test_thumbnail_resnet_train_step():
    """ResNet-20-ish thumbnail on CIFAR shapes trains one step end to end."""
    net = vision.get_resnet(1, 18, thumbnail=True, classes=10)
    net.initialize(mx.init.Xavier())
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1})
    x = mx.nd.random.uniform(shape=(4, 3, 32, 32))
    y = mx.nd.array(np.array([0, 1, 2, 3]))
    with mx.autograd.record():
        out = net(x)
        loss = loss_fn(out, y)
    loss.backward()
    trainer.step(4)
    assert np.isfinite(loss.asnumpy()).all()


def test_hybridize_model():
    net = vision.get_model("resnet18_v1", classes=10)
    net.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 224, 224))
    y0 = net(x).asnumpy()
    net.hybridize()
    y1 = net(x).asnumpy()
    np.testing.assert_allclose(y0, y1, rtol=1e-4, atol=1e-4)
