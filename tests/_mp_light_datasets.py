"""Pure-numpy datasets for the multiprocess DataLoader tests. No mxtpu
import: spawned workers unpickle these by importing THIS module only, so
the tests measure worker behavior, not jax import time."""
import os
import time

import numpy as np


class SlowIOdataset:
    """50 ms 'IO wait' per item — overlaps across worker processes even on
    a 1-core host, which is what proves the workers are real processes."""

    def __len__(self):
        return 12

    def __getitem__(self, i):
        time.sleep(0.05)
        return np.float32(i)


class PidDataset:
    def __len__(self):
        return 8

    def __getitem__(self, i):
        return np.int64(os.getpid())


class CrashingDataset:
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.float32(i)


class FakeDeviceArray:
    """Duck-types a device array (asnumpy attr) without importing mxtpu —
    the worker-side batchify must reject it just like a real NDArray."""

    def asnumpy(self):  # pragma: no cover - never called
        return np.zeros(2)


class DeviceArrayDataset:
    def __len__(self):
        return 4

    def __getitem__(self, i):
        return FakeDeviceArray()


class PlainArrayPairDataset:
    """(x, y) pairs from deterministic numpy — the correctness workhorse."""

    def __init__(self, n=30, dim=4):
        self.x = np.arange(n * dim, dtype=np.float32).reshape(n, dim)
        self.y = np.arange(n, dtype=np.float32)

    def __len__(self):
        return len(self.y)

    def __getitem__(self, i):
        return self.x[i], self.y[i]
