"""Autograd tests (modeled on tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import autograd as ag
from mxtpu.test_utils import assert_almost_equal


def test_simple_grad():
    x = mx.nd.array([1., 2., 3.])
    x.attach_grad()
    with ag.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain_and_reuse():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x
        z = y * x  # x^3
    z.backward()
    assert_almost_equal(x.grad, [12.0])  # 3x^2


def test_multi_input():
    a = mx.nd.array([1., 2.])
    b = mx.nd.array([3., 4.])
    a.attach_grad()
    b.attach_grad()
    with ag.record():
        c = (a * b).sum()
    c.backward()
    assert_almost_equal(a.grad, b.asnumpy())
    assert_almost_equal(b.grad, a.asnumpy())


def test_grad_add_accumulate():
    x = mx.nd.array([1., 2.])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad, 3 * 2 * x.asnumpy())


def test_head_grad():
    x = mx.nd.array([1., 2., 3.])
    x.attach_grad()
    with ag.record():
        y = x * 2
    y.backward(mx.nd.array([1., 10., 100.]))
    assert_almost_equal(x.grad, [2., 20., 200.])


def test_detach_blocks():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with ag.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, [9.0])  # only d(y_const * x)/dx = y


def test_blockgrad_op():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with ag.record():
        y = mx.nd.BlockGrad(x * x) * x
    y.backward()
    assert_almost_equal(x.grad, [9.0])


def test_training_modes():
    assert not ag.is_training()
    with ag.record():
        assert ag.is_training()
        assert ag.is_recording()
        with ag.pause():
            assert not ag.is_recording()
    with ag.predict_mode():
        assert not ag.is_training()


def test_dropout_respects_mode():
    x = mx.nd.ones((100,))
    out = mx.nd.Dropout(x, p=0.5)  # not training: identity
    assert_almost_equal(out, np.ones(100))
    with ag.record():
        out = mx.nd.Dropout(x, p=0.5)
    a = out.asnumpy()
    assert (a == 0).any() and (a > 1).any()  # inverted dropout scales kept values


def test_dropout_backward_consistent_mask():
    # backward must re-use the forward's mask (key captured at call time)
    x = mx.nd.ones((1000,))
    x.attach_grad()
    with ag.record():
        y = mx.nd.Dropout(x, p=0.5)
        s = y.sum()
    s.backward()
    fwd = y.asnumpy()
    g = x.grad.asnumpy()
    assert_almost_equal(g, fwd)  # grad of sum(dropout(x)) is exactly the mask/keep


def test_inplace_while_recording():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * 3
        y += x  # taped as functional add
        z = y * x
    z.backward()
    # z = (3x + x) * x = 4x^2, dz/dx = 8x = 16
    assert_almost_equal(x.grad, [16.0])


def test_grad_function():
    x = mx.nd.array([1., 2., 3.])
    x.attach_grad()
    with ag.record():
        y = (x * x).sum()
    g = ag.grad(y, x, retain_graph=True)
    assert_almost_equal(g, 2 * x.asnumpy())


def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = 1.0 / (1.0 + mx.nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    f = Sigmoid()
    x = mx.nd.array([0.0, 1.0])
    x.attach_grad()
    with ag.record():
        y = f(x)
    y.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, sig * (1 - sig), rtol=1e-5)


def test_error_on_unrecorded_head():
    x = mx.nd.array([1.0])
    with pytest.raises(mx.MXNetError):
        x.backward()


def test_deferred_style_exception():
    # errors inside async dispatch surface at sync points (wait_to_read/asnumpy)
    a = mx.nd.array([1.0, 2.0])
    with pytest.raises(Exception):
        b = a.reshape((3,))  # impossible reshape raises at call or sync
        b.wait_to_read()
