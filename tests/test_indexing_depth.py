"""NDArray indexing DEPTH tier vs NumPy semantics — the reference's
tests/python/unittest/test_ndarray.py indexing battery (basic/advanced
indexing, setitem variants, degenerate shapes). Oracle is NumPy itself:
every get must equal the same expression on the backing numpy array, and
every set must leave the array equal to numpy's result.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.base import MXNetError

RNG = np.random.RandomState


def _pair(shape=(4, 5, 6), seed=0):
    a = RNG(seed).uniform(-2, 2, shape).astype(np.float32)
    return mx.nd.array(a), a


GET_KEYS = [
    1,
    -1,
    (2, 3),
    slice(1, 3),
    slice(None, None, 2),
    slice(3, None, -1),
    (slice(None), 2),
    (slice(1, 3), slice(None), slice(None, None, 3)),
    (Ellipsis, 1),
    (1, Ellipsis),
    (slice(None), None),          # new axis
    None,
    (0, slice(1, 4), -2),
]


@pytest.mark.parametrize("key", GET_KEYS, ids=[repr(k) for k in GET_KEYS])
def test_getitem_matches_numpy(key):
    nd, a = _pair()
    out = nd[key]
    ref = a[key]
    assert out.shape == ref.shape, (out.shape, ref.shape)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)


def test_getitem_integer_array_and_boolean():
    nd, a = _pair()
    idx = np.array([0, 2, 3])
    np.testing.assert_allclose(nd[mx.nd.array(idx.astype(np.float32))]
                               .asnumpy(), a[idx], rtol=1e-6)
    np.testing.assert_allclose(nd[idx].asnumpy(), a[idx], rtol=1e-6)
    # fancy on two axes
    i = np.array([0, 1]), np.array([2, 3])
    np.testing.assert_allclose(nd[i].asnumpy(), a[i], rtol=1e-6)
    # boolean masks: 1-D on an axis, full-shape, and mixed-in-tuple —
    # converted host-side to nonzero indices (static-shape gathers)
    m1 = np.array([True, False, True, False])
    np.testing.assert_allclose(nd[m1].asnumpy(), a[m1], rtol=1e-6)
    np.testing.assert_allclose(nd[(m1, 2)].asnumpy(), a[m1, 2], rtol=1e-6)
    mfull = RNG(20).uniform(size=a.shape) > 0.5
    np.testing.assert_allclose(nd[mfull].asnumpy(), a[mfull], rtol=1e-6)
    m2 = RNG(21).uniform(size=a.shape[:2]) > 0.5
    np.testing.assert_allclose(nd[m2].asnumpy(), a[m2], rtol=1e-6)


def test_getitem_degenerate_and_scalar():
    nd, a = _pair((3,), seed=1)
    s = nd[1]
    assert s.shape == ()
    assert float(s.asnumpy()) == pytest.approx(float(a[1]))
    z = nd[1:1]
    assert z.shape == (0,)


SET_CASES = [
    (1, 7.5),
    ((slice(None), 2), -1.0),
    (slice(1, 3), "row"),             # broadcast a row
    ((slice(None), slice(None), 0), "col"),
    ((Ellipsis, -1), 0.0),
    ((0, 1), 3.25),
]


@pytest.mark.parametrize("key,val", SET_CASES,
                         ids=[repr(k) for k, _ in SET_CASES])
def test_setitem_matches_numpy(key, val):
    nd, a = _pair(seed=2)
    a = a.copy()
    if val == "row":
        v = RNG(3).uniform(-1, 1, a[key].shape[-2:]).astype(np.float32)
    elif val == "col":
        v = RNG(4).uniform(-1, 1, a[key].shape).astype(np.float32)
    else:
        v = val
    nd[key] = v
    a[key] = v
    np.testing.assert_allclose(nd.asnumpy(), a, rtol=1e-6)


def test_setitem_with_ndarray_value_and_full_slice():
    nd, a = _pair(seed=5)
    v = RNG(6).uniform(-1, 1, a.shape).astype(np.float32)
    nd[:] = mx.nd.array(v)
    np.testing.assert_allclose(nd.asnumpy(), v, rtol=1e-6)
    nd[1:3] = mx.nd.array(v[0:2])
    v2 = v.copy()
    v2[1:3] = v[0:2]
    np.testing.assert_allclose(nd.asnumpy(), v2, rtol=1e-6)


def test_setitem_integer_array_rows():
    nd, a = _pair(seed=7)
    a = a.copy()
    rows = np.array([0, 3])
    v = RNG(8).uniform(-1, 1, (2,) + a.shape[1:]).astype(np.float32)
    nd[rows] = mx.nd.array(v)
    a[rows] = v
    np.testing.assert_allclose(nd.asnumpy(), a, rtol=1e-6)


def test_setitem_under_recording_raises():
    from mxtpu import autograd
    nd, _ = _pair()
    with pytest.raises(MXNetError):
        with autograd.record():
            nd[0] = 1.0


def test_getitem_grad_flows_through_slice():
    from mxtpu import autograd
    nd, a = _pair(seed=9)
    nd.attach_grad()
    with autograd.record():
        y = nd[1:3, ::2].sum()
    y.backward()
    g = np.zeros_like(a)
    g[1:3, ::2] = 1.0
    np.testing.assert_allclose(nd.grad.asnumpy(), g, rtol=1e-6)


def test_views_do_not_alias_source():
    """Deliberate divergence from the reference: MXNet's basic indexing
    (_at/_slice) returns memory-SHARING views where ``s[:] = x`` writes
    back; here slice results are functional copies (jax arrays are
    immutable — write-back aliasing cannot be expressed), so mutating a
    slice result must never touch the source. Pinned so the divergence
    is documented behavior, not an accident."""
    nd, a = _pair(seed=10)
    s = nd[0]
    s[:] = 99.0
    np.testing.assert_allclose(nd.asnumpy(), a, rtol=1e-6)


def test_zero_size_and_newaxis_combos():
    nd, a = _pair((2, 0, 3), seed=11)
    assert nd.shape == (2, 0, 3)
    assert nd[1].shape == (0, 3)
    out = nd[:, :, None, 1]
    assert out.shape == a[:, :, None, 1].shape


def test_take_along_negative_and_step_mix():
    nd, a = _pair((6, 7), seed=12)
    for key in [(slice(-4, -1), slice(None)),
                (slice(None, None, -2), slice(1, None, 3)),
                (-2, slice(-3, None))]:
        np.testing.assert_allclose(nd[key].asnumpy(), a[key], rtol=1e-6)
