"""Channels-last (NHWC) layout scope vs reference NCHW numerics.

The reference is NCHW-only (src/operator/nn/convolution.cc layout check);
mxtpu adds a channels-last path because that is the TPU-native layout
(mxtpu/layout.py). These tests pin NHWC == NCHW numerics so the fast path
can't drift from the reference-parity path.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import gluon
from mxtpu.gluon import nn


def _to_nhwc(x):
    return np.transpose(x, (0, 2, 3, 1))


def test_conv2d_layout_match():
    x = np.random.uniform(-1, 1, (2, 3, 8, 8)).astype("float32")
    a = nn.Conv2D(5, 3, strides=2, padding=1, in_channels=3)
    a.initialize()
    with mx.layout("NHWC"):
        b = nn.Conv2D(5, 3, strides=2, padding=1, in_channels=3)
    b.initialize()
    # share weights: OIHW -> HWIO
    w = a.weight.data().asnumpy()
    b.weight.set_data(mx.nd.array(np.transpose(w, (2, 3, 1, 0))))
    b.bias.set_data(a.bias.data())
    ya = a(mx.nd.array(x)).asnumpy()
    yb = b(mx.nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(_to_nhwc(ya), yb, rtol=1e-5, atol=1e-5)


def test_conv2d_transpose_layout_match():
    x = np.random.uniform(-1, 1, (2, 3, 8, 8)).astype("float32")
    a = nn.Conv2DTranspose(5, 3, strides=2, padding=1, in_channels=3)
    a.initialize()
    with mx.layout("NHWC"):
        b = nn.Conv2DTranspose(5, 3, strides=2, padding=1, in_channels=3)
    b.initialize()
    # IOHW -> HWOI (channels-last deconv stores (*k, out/g, in))
    w = a.weight.data().asnumpy()
    b.weight.set_data(mx.nd.array(np.transpose(w, (2, 3, 1, 0))))
    b.bias.set_data(a.bias.data())
    ya = a(mx.nd.array(x)).asnumpy()
    yb = b(mx.nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(_to_nhwc(ya), yb, rtol=1e-5, atol=1e-5)


def test_pooling_layout_match():
    x = np.random.uniform(-1, 1, (2, 3, 9, 9)).astype("float32")
    for cls, kw in [(nn.MaxPool2D, dict(pool_size=3, strides=2, padding=1)),
                    (nn.AvgPool2D, dict(pool_size=2, strides=2)),
                    (nn.GlobalAvgPool2D, {}), (nn.GlobalMaxPool2D, {})]:
        a = cls(**kw)
        with mx.layout("NHWC"):
            b = cls(**kw)
        ya = a(mx.nd.array(x)).asnumpy()
        yb = b(mx.nd.array(_to_nhwc(x))).asnumpy()
        np.testing.assert_allclose(_to_nhwc(ya), yb, rtol=1e-6, atol=1e-6)


def test_batchnorm_layout_match():
    x = np.random.uniform(-1, 1, (2, 3, 4, 4)).astype("float32")
    a = nn.BatchNorm(in_channels=3)
    a.initialize()
    with mx.layout("NHWC"):
        b = nn.BatchNorm(in_channels=3)
    b.initialize()
    with mx.autograd.record():
        ya = a(mx.nd.array(x))
        yb = b(mx.nd.array(_to_nhwc(x)))
    np.testing.assert_allclose(_to_nhwc(ya.asnumpy()), yb.asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_explicit_layout_overrides_scope():
    with mx.layout("NHWC"):
        c = nn.Conv2D(4, 3, layout="NCHW", in_channels=3)
    assert c._layout == "NCHW"
    assert c.weight.shape == (4, 3, 3, 3)


def test_resnet18_layout_scope_end_to_end():
    """The whole untouched model zoo flips to NHWC with one scope line."""
    np.random.seed(0)
    x = np.random.uniform(-1, 1, (2, 3, 32, 32)).astype("float32")
    from mxtpu.gluon.model_zoo import vision
    mx.random.seed(0)
    a = vision.resnet18_v1(classes=10, thumbnail=True)
    a.initialize()
    mx.random.seed(0)
    with mx.layout("NHWC"):
        b = vision.resnet18_v1(classes=10, thumbnail=True)
    b.initialize()
    ya = a(mx.nd.array(x))
    yb = b(mx.nd.array(_to_nhwc(x)))
    # same seed -> same init draw order; conv weights differ only by
    # transpose, which the fan-in/fan-out Xavier computation is blind to,
    # so outputs agree when we copy weights across
    for (na, pa), (nb, pb) in zip(sorted(a.collect_params().items()),
                                  sorted(b.collect_params().items())):
        wa = pa.data().asnumpy()
        if wa.ndim == 4:  # OIHW -> HWIO
            pb.set_data(mx.nd.array(np.transpose(wa, (2, 3, 1, 0))))
        else:
            pb.set_data(pa.data())
    ya = a(mx.nd.array(x)).asnumpy()
    yb = b(mx.nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(ya, yb, rtol=1e-4, atol=1e-4)


def test_concat_models_nhwc_forward():
    """Channel-concat zoo families (densenet/squeezenet) resolve their
    concat axis from the layout scope."""
    from mxtpu.gluon.model_zoo import vision
    x = np.random.uniform(-1, 1, (1, 64, 64, 3)).astype("float32")
    with mx.layout("NHWC"):
        for name in ("densenet121", "squeezenet1_1"):
            net = vision.get_model(name, classes=7)
            net.initialize()
            out = net(mx.nd.array(x))
            assert out.shape == (1, 7), (name, out.shape)


def test_layout_global_set_and_restore():
    """Bare call sets globally; context restores."""
    mx.layout("NHWC")
    from mxtpu.layout import is_channels_last
    assert is_channels_last()
    mx.layout("NCHW")
    assert not is_channels_last()
    with mx.layout("NHWC"):
        assert is_channels_last()
    assert not is_channels_last()


def test_nhwc_train_step():
    """NHWC net trains under ShardedTrainStep (loss decreases)."""
    from mxtpu.parallel import ShardedTrainStep, data_parallel_mesh
    np.random.seed(0)
    with mx.layout("NHWC"):
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
                nn.BatchNorm(), nn.MaxPool2D(2), nn.Flatten(), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.uniform(-1, 1, (8, 8, 8, 3)))
    y = mx.nd.array(np.random.randint(0, 4, (8,)).astype("float32"))
    net(x)
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    step = ShardedTrainStep(net, loss, data_parallel_mesh(),
                            optimizer="sgd",
                            optimizer_params={"learning_rate": 0.1})
    first = float(step(x, y).asnumpy())
    for _ in range(10):
        last = float(step(x, y).asnumpy())
    assert last < first
