"""Tests for gluon.contrib + mx.rnn (ref patterns:
tests/python/unittest/test_gluon_contrib.py, test_rnn.py)."""
import numpy as np

import mxtpu as mx
from mxtpu import gluon
from mxtpu.gluon import nn
from mxtpu.gluon.contrib import nn as cnn
from mxtpu.gluon.contrib import rnn as crnn
from mxtpu.rnn import BucketSentenceIter, encode_sentences


def test_concurrent_and_identity():
    net = cnn.HybridConcurrent(axis=1)
    with net.name_scope():
        net.add(nn.Dense(4))
        net.add(nn.Dense(6))
        net.add(cnn.Identity())
    net.initialize()
    x = mx.nd.random.uniform(shape=(2, 3))
    out = net(x)
    assert out.shape == (2, 4 + 6 + 3)


def test_sparse_embedding():
    emb = cnn.SparseEmbedding(10, 4)
    emb.initialize()
    out = emb(mx.nd.array([1, 2, 1]))
    assert out.shape == (3, 4)
    np.testing.assert_allclose(out[0].asnumpy(), out[2].asnumpy())


def test_sync_batch_norm_runs():
    bn = cnn.SyncBatchNorm(num_devices=4)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8), bn)
    net.initialize()
    with mx.autograd.record():
        out = net(mx.nd.random.uniform(shape=(4, 3)))
    assert out.shape == (4, 8)


def test_conv_lstm_cell():
    cell = crnn.Conv2DLSTMCell(input_shape=(3, 8, 8), hidden_channels=5,
                               i2h_kernel=3, i2h_pad=1, h2h_kernel=3)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 8, 8))
    states = cell.begin_state(batch_size=2)
    out, new_states = cell(x, states)
    assert out.shape == (2, 5, 8, 8)
    assert len(new_states) == 2
    assert new_states[1].shape == (2, 5, 8, 8)


def test_conv_gru_unroll():
    cell = crnn.Conv1DGRUCell(input_shape=(2, 10), hidden_channels=4,
                              i2h_kernel=3, i2h_pad=1, h2h_kernel=3)
    cell.initialize()
    inputs = [mx.nd.random.uniform(shape=(3, 2, 10)) for _ in range(4)]
    outputs, states = cell.unroll(4, inputs, layout="TNC", merge_outputs=False)
    assert len(outputs) == 4
    assert outputs[0].shape == (3, 4, 10)


def test_variational_dropout_cell_mask_reuse():
    base = gluon.rnn.LSTMCell(8)
    cell = crnn.VariationalDropoutCell(base, drop_inputs=0.5)
    cell.initialize()
    x = mx.nd.ones((2, 4))
    states = cell.begin_state(batch_size=2)
    with mx.autograd.record():  # training mode so dropout is live
        out1, states = cell(x, states)
        mask1 = cell.drop_inputs_mask.asnumpy()
        out2, states = cell(x, states)
        mask2 = cell.drop_inputs_mask.asnumpy()
    np.testing.assert_allclose(mask1, mask2)  # same mask across steps


def test_lstmp_cell():
    cell = crnn.LSTMPCell(hidden_size=16, projection_size=6)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 4))
    states = cell.begin_state(batch_size=2)
    out, new_states = cell(x, states)
    assert out.shape == (2, 6)       # projected
    assert new_states[1].shape == (2, 16)  # cell state unprojected


def test_encode_sentences_and_bucket_iter():
    sentences = [["the", "cat", "sat"], ["a", "dog", "ran", "far"],
                 ["hi"], ["the", "dog", "sat"]] * 4
    coded, vocab = encode_sentences(sentences, start_label=1)
    assert vocab["the"] != vocab["cat"]
    it = BucketSentenceIter(coded, batch_size=2, buckets=[3, 5],
                            invalid_label=0)
    batches = list(it)
    assert batches, "no batches produced"
    for b in batches:
        assert b.bucket_key in (3, 5)
        assert b.data[0].shape == (2, b.bucket_key)
        d = b.data[0].asnumpy()
        l = b.label[0].asnumpy()
        np.testing.assert_allclose(l[:, :-1], d[:, 1:])


def test_switch_moe_layer_trains_and_reports_aux():
    """gluon.contrib.nn.SwitchMoE (expert-parallel MoE layer, no reference
    counterpart): (out, aux) two-output convention, eager AND hybridized,
    plus a training step through both outputs."""
    import numpy as np
    from mxtpu.gluon.contrib import nn as cnn

    mx.random.seed(0)
    moe = cnn.SwitchMoE(dim=8, hidden=16, num_experts=4,
                        capacity_factor=2.0)
    moe.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(2, 6, 8)
                    .astype(np.float32))
    y, aux = moe(x)
    assert y.shape == (2, 6, 8)
    assert float(aux.asnumpy()) >= 1.0 - 1e-3
    # hybridized: the aux output survives the jit cache (it is a REAL
    # output, not a side-channel attribute)
    moe.hybridize()
    y_h, aux_h = moe(x)
    np.testing.assert_allclose(y_h.asnumpy(), y.asnumpy(), rtol=1e-5)
    np.testing.assert_allclose(float(aux_h.asnumpy()),
                               float(aux.asnumpy()), rtol=1e-5)
    y_h2, aux_h2 = moe(x)  # second call hits the compiled path
    np.testing.assert_allclose(y_h2.asnumpy(), y.asnumpy(), rtol=1e-5)
    # training through task + aux loss updates the router
    before = moe.router.data().asnumpy().copy()
    tr = mx.gluon.Trainer(moe.collect_params(), "adam",
                          {"learning_rate": 1e-2})
    with mx.autograd.record():
        out, aux_t = moe(x)
        loss = (out ** 2).mean() + 0.01 * aux_t
    loss.backward()
    tr.step(2)
    assert np.abs(moe.router.data().asnumpy() - before).sum() > 0
    # wrong input dim is refused, not silently reshaped
    import pytest
    with pytest.raises(ValueError, match="last axis"):
        moe(mx.nd.zeros((2, 6, 4)))
