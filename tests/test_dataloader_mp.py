"""Multiprocess DataLoader tier (VERDICT r4 missing #1 / next-round #3):
real spawned worker processes, shared-memory batch handoff, wall-clock
overlap proof, crash containment, and no leaked segments.

Reference design being matched: python/mxnet/gluon/data/dataloader.py:26-120
(multiprocess workers + cpu_shared NDArray handoff via ForkingPickler).
Worker-side internals under test live in mxtpu/gluon/data/_mp_worker.py
(numpy-only so spawned workers never pay the jax import).
"""
import glob
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))  # _mp_light_datasets

from _mp_light_datasets import (CrashingDataset, DeviceArrayDataset,
                                PidDataset, PlainArrayPairDataset,
                                SlowIOdataset)
from mxtpu.gluon.data import DataLoader
from mxtpu.gluon.data.dataset import ArrayDataset


def _shm_segments():
    return set(glob.glob("/dev/shm/psm_*"))


def test_mp_loader_matches_serial_and_reuses_pool():
    ds = PlainArrayPairDataset()
    before = _shm_segments()
    serial = [tuple(b) for b in DataLoader(ds, batch_size=8)]
    dl = DataLoader(ds, batch_size=8, num_workers=2)
    for _epoch in range(2):  # second epoch must reuse the spawned pool
        got = list(dl)
        assert len(got) == len(serial)
        for (sd, sl), mb in zip(serial, got):
            np.testing.assert_array_equal(sd.asnumpy(), mb[0].asnumpy())
            np.testing.assert_array_equal(sl.asnumpy(), mb[1].asnumpy())
    dl.close()
    assert _shm_segments() <= before  # no leaked shared memory


def test_mp_loader_works_with_mxtpu_dataset():
    """ArrayDataset pickles through spawn (workers then import mxtpu —
    slower, but must work)."""
    x = np.arange(48, dtype=np.float32).reshape(12, 4)
    y = np.arange(12, dtype=np.float32)
    ds = ArrayDataset(x, y)
    serial = [tuple(b) for b in DataLoader(ds, batch_size=4)]
    dl = DataLoader(ds, batch_size=4, num_workers=1)
    got = list(dl)
    dl.close()
    for (sd, _sl), mb in zip(serial, got):
        np.testing.assert_array_equal(sd.asnumpy(), mb[0].asnumpy())


def test_mp_loader_workers_are_separate_processes():
    dl = DataLoader(PidDataset(), batch_size=1, num_workers=2)
    pids = {int(b.asnumpy()[0]) for b in dl}
    dl.close()
    assert os.getpid() not in pids
    assert len(pids) >= 1  # at least one distinct worker process


def test_mp_loader_overlaps_io_bound_work():
    """Wall-clock proof the workers parallelize: 12 x 50ms sleeps must
    overlap across 4 processes (sleeps don't need cores)."""
    dl = DataLoader(SlowIOdataset(), batch_size=1, num_workers=4)
    list(dl)  # warm: spawn cost excluded from the timing
    t0 = time.perf_counter()
    list(dl)
    mp_t = time.perf_counter() - t0
    dl.close()
    t0 = time.perf_counter()
    list(DataLoader(SlowIOdataset(), batch_size=1))
    ser_t = time.perf_counter() - t0
    assert mp_t < ser_t / 2, (ser_t, mp_t)


def test_mp_loader_propagates_worker_exception():
    dl = DataLoader(CrashingDataset(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at 5"):
        list(dl)
    dl.close()


def test_mp_loader_rejects_device_arrays_loudly():
    dl = DataLoader(DeviceArrayDataset(), batch_size=2, num_workers=1)
    with pytest.raises(RuntimeError, match="numpy samples"):
        list(dl)
    dl.close()


def test_mp_loader_early_exit_cleans_up():
    ds = PlainArrayPairDataset(n=100)
    before = _shm_segments()
    dl = DataLoader(ds, batch_size=4, num_workers=2, prefetch=8)
    it = iter(dl)
    next(it)
    next(it)
    del it          # abandon mid-epoch with batches in flight
    # next epoch must not be satisfied by stale batches
    got = list(dl)
    assert len(got) == 25
    np.testing.assert_array_equal(got[0][0].asnumpy(), ds.x[:4])
    dl.close()
    time.sleep(0.3)
    assert _shm_segments() <= before


def test_thread_pool_mode_still_available():
    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    ds = ArrayDataset(x, np.arange(10, dtype=np.float32))
    serial = [b[0].asnumpy() for b in DataLoader(ds, batch_size=2)]
    threaded = [b[0].asnumpy() for b in
                DataLoader(ds, batch_size=2, num_workers=2,
                           thread_pool=True)]
    for s, t in zip(serial, threaded):
        np.testing.assert_array_equal(s, t)


def test_shm_roundtrip_unit():
    """_mp_worker's descriptor protocol, exercised in-process."""
    from mxtpu.gluon.data import _mp_worker as w
    payload = [np.arange(6).reshape(2, 3).astype(np.float32),
               (np.zeros(0, np.int32), np.float64(3.5)),
               "label"]
    segs = []
    desc = w.to_shm(payload, segs)
    for s in segs:
        s.close()
    out = w.from_shm(desc, lambda a: a)
    np.testing.assert_array_equal(out[0], payload[0])
    assert out[1][0].shape == (0,)
    assert out[1][1] == 3.5 and out[2] == "label"
