"""Resilient training runtime (mxtpu/resilience.py) — the fault-injection
matrix of ISSUE 3:

* injected-NaN steps SKIP (params + optimizer state + t bit-identical to
  pre-step) and the dynamic loss scaler backs off then regrows;
* step_ok history matches the injection schedule, fetched asynchronously
  (a guarded hot loop runs under a device->host transfer-guard);
* SIGTERM mid-train writes a final checkpoint and a fresh trainer resumes
  bit-exact (params, optimizer state, loss scaler, RNG);
* checkpoint IO failures retry with backoff then degrade gracefully;
* a killed dataloader worker restarts and the epoch completes;
* jit cache stability: guard on/off is ONE extra compile, flag flips are
  ZERO (fused-update cache and CachedOp alike).
"""
import json
import os
import signal
import sys

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import optimizer_fused as of
from mxtpu import resilience
from mxtpu.gluon.parameter import Parameter
from mxtpu.gluon.trainer import Trainer

sys.path.insert(0, os.path.dirname(__file__))  # _mp_light_datasets


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_NUMERICS_GUARD", "MXTPU_FAULT_INJECT",
                "MXTPU_LOSS_SCALE", "MXTPU_CKPT_RETRIES",
                "MXTPU_FUSED_OPTIMIZER", "MXTPU_DL_WORKER_RESTARTS"):
        monkeypatch.delenv(var, raising=False)
    resilience.reset_faults()
    of.reset()
    yield
    resilience.reset_faults()
    of.reset()


def _make_trainer(n_params=3, shape=(5,), optimizer="sgd", opt_params=None,
                  scaler=None, seed=0):
    rng = np.random.RandomState(seed)
    params = []
    for j in range(n_params):
        p = Parameter("rp%d" % j, shape=shape, dtype="float32")
        p.initialize()
        p.data()._set_data(mx.nd.array(
            rng.uniform(-1, 1, shape).astype(np.float32))._data)
        params.append(p)
    opt_params = opt_params or {"learning_rate": 0.05, "momentum": 0.9}
    tr = Trainer(params, optimizer, opt_params, kvstore=None,
                 loss_scaler=scaler)
    return tr, params, rng


def _set_grads(params, rng, scale=1.0):
    for p in params:
        p.grad()[:] = mx.nd.array(
            (rng.randn(*p.shape) * scale).astype(np.float32))


def _snapshot(tr, params):
    upd = tr._updaters[0]
    weights = [p.data().asnumpy().copy() for p in params]
    states = []
    for i in sorted(upd.states):
        s = upd.states[i]
        states.append(of._tree_data(s))
    flat = []

    def leaves(x):
        if x is None:
            return
        if isinstance(x, tuple):
            for c in x:
                leaves(c)
        else:
            flat.append(np.asarray(x).copy())
    for s in states:
        leaves(s)
    return weights, flat


# ------------------------------------------------------------ skip stepping
def test_nan_step_skips_params_state_and_t(monkeypatch):
    """An injected-NaN step is a NO-OP: params, momentum, and the device
    bias-correction count t_good are bit-identical to pre-step."""
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@1")
    tr, params, rng = _make_trainer(optimizer="adam",
                                    opt_params={"learning_rate": 0.05})
    _set_grads(params, rng)
    tr.step(1)
    w_before, s_before = _snapshot(tr, params)
    t_before = int(tr._updaters[0]._t_good)
    _set_grads(params, rng)
    ok = tr.step(1)  # the poisoned step
    assert bool(ok.asnumpy()) is False
    w_after, s_after = _snapshot(tr, params)
    for a, b in zip(w_before, w_after):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(s_before, s_after):
        np.testing.assert_array_equal(a, b)
    assert int(tr._updaters[0]._t_good) == t_before
    _set_grads(params, rng)
    ok = tr.step(1)  # clean step moves again
    assert bool(ok.asnumpy()) is True
    w_next, _ = _snapshot(tr, params)
    assert not np.array_equal(w_after[0], w_next[0])
    assert int(tr._updaters[0]._t_good) == t_before + 1


def test_step_ok_history_matches_injection_schedule(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@1,4")
    scaler = resilience.DynamicLossScaler(init_scale=8.0, growth_interval=50)
    tr, params, rng = _make_trainer(scaler=scaler)
    verdicts = []
    for _ in range(6):
        _set_grads(params, rng)
        verdicts.append(bool(tr.step(1).asnumpy()))
    want = [True, False, True, True, False, True]
    assert verdicts == want
    # the async health buffer saw the same schedule
    assert tr._updaters[0].health.ok_history() == want
    assert resilience.FAULT_STATS["fired"] == [("nan_grad", 1),
                                               ("nan_grad", 4)]


def test_scaler_backs_off_then_regrows(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@2")
    scaler = resilience.DynamicLossScaler(init_scale=16.0, growth_interval=3)
    tr, params, rng = _make_trainer(scaler=scaler)
    scales = []
    for _ in range(9):
        _set_grads(params, rng)
        tr.step(1)
        scales.append(scaler.scale_value())
    # back off at the skip, regrow x2 after each 3-good-step streak
    assert scales[:3] == [16.0, 16.0, 8.0]
    assert scales[-1] >= 16.0  # regrown past the backoff
    assert 8.0 in scales[3:]   # and it stayed down right after the skip


def test_scaled_grads_unscale_exactly(monkeypatch):
    """Power-of-two loss scaling is EXACT: a run with scale S applied to
    the gradients must reproduce the unscaled run bit-for-bit."""
    def run(scale):
        scaler = resilience.DynamicLossScaler(
            init_scale=scale, growth_interval=10 ** 6) if scale else None
        if scale is None:
            os.environ["MXTPU_NUMERICS_GUARD"] = "1"
        tr, params, rng = _make_trainer(optimizer="adam",
                                        opt_params={"learning_rate": 0.05},
                                        scaler=scaler)
        for _ in range(4):
            _set_grads(params, rng, scale=1.0)
            if scale:
                for p in params:
                    p.grad()[:] = p.grad() * scale
            tr.step(1)
        out = [p.data().asnumpy() for p in params]
        os.environ.pop("MXTPU_NUMERICS_GUARD", None)
        return out
    base = run(None)
    scaled = run(256.0)
    for a, b in zip(base, scaled):
        np.testing.assert_array_equal(a, b)


def test_guard_cache_stability_fused_and_cachedop(monkeypatch):
    """Guard on/off = ONE extra compile of the update jit (and one CachedOp
    retrace via policy_key); flag flips (finite vs non-finite grads) = ZERO
    retraces anywhere."""
    from mxtpu import gluon
    from mxtpu.gluon import nn

    tr, params, rng = _make_trainer()
    _set_grads(params, rng)
    tr.step(1)
    assert of.FUSED_STATS["compiles"] == 1
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    _set_grads(params, rng)
    tr.step(1)
    assert of.FUSED_STATS["compiles"] == 2  # exactly one more
    traces = of.FUSED_STATS["traces"]
    # flag flips: poison then clean — same executable both ways
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@2")
    for _ in range(3):
        _set_grads(params, rng)
        tr.step(1)
    assert of.FUSED_STATS["traces"] == traces
    assert of.FUSED_STATS["compiles"] == 2

    # CachedOp side: a guard flip is one new cache entry, steps are zero
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(2, 6).astype(np.float32))
    net(x)
    net.hybridize()
    net(x)
    n0 = len(net._cached_op._jits)
    net(x)
    assert len(net._cached_op._jits) == n0  # steady state
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "0")
    net(x)
    assert len(net._cached_op._jits) == n0 + 1  # policy flip: ONE retrace


@pytest.mark.parametrize("telemetry_on,trace_on,xprof_on",
                         [("0", "0", "0"), ("1", "0", "0"),
                          ("1", "1", "0"), ("1", "1", "1")])
def test_guarded_hot_loop_has_no_host_sync(monkeypatch, telemetry_on,
                                           trace_on, xprof_on):
    """The acceptance contract: sentinel+scaler add no per-step host sync.
    After warmup, guarded Trainer.steps run under a device->host transfer
    guard that hard-fails on any fetch. Runs with the telemetry layer ON
    too (ISSUE 4), with causal tracing ON on top (ISSUE 10), and with the
    executable observatory ON on top of that (ISSUE 12): spans, trace
    contexts, the flight-recorder ring, and the ledger's wrapped-jit call
    counting are pure host bookkeeping and must not introduce a single
    device fetch."""
    import jax
    monkeypatch.setenv("MXTPU_TELEMETRY", telemetry_on)
    monkeypatch.setenv("MXTPU_TRACE", trace_on)
    monkeypatch.setenv("MXTPU_XPROF", xprof_on)
    scaler = resilience.DynamicLossScaler(init_scale=4.0)
    tr, params, rng = _make_trainer(optimizer="adam",
                                    opt_params={"learning_rate": 0.01},
                                    scaler=scaler)
    _set_grads(params, rng)
    tr.step(1)  # warmup + compile
    with jax.transfer_guard_device_to_host("disallow"):
        for _ in range(3):
            _set_grads(params, rng)
            ok = tr.step(1)
            assert ok is not None  # verdict handed back, NOT fetched
    assert tr._updaters[0].health.ok_history()[-3:] == [True] * 3


def test_guard_enabled_on_warm_optimizer_continues_t(monkeypatch):
    """Flipping the guard on after N unguarded steps must seed the device
    bias-correction count from the host clock — Adam's effective lr would
    otherwise transiently jump ~3x as if training restarted at t=1."""
    tr, params, rng = _make_trainer(optimizer="adam",
                                    opt_params={"learning_rate": 0.05})
    for _ in range(3):
        _set_grads(params, rng)
        tr.step(1)
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    _set_grads(params, rng)
    tr.step(1)
    assert int(tr._updaters[0]._t_good) == 4  # N+1, not 1


def test_mixed_batch_grad_norm_is_global(monkeypatch):
    """Eager-bound items (tied buffers here) must contribute to the
    reported global grad norm, not just to the finite flag."""
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    from mxtpu import optimizer as opt
    upd = opt.get_updater(opt.SGD(learning_rate=0.1))
    rng = np.random.RandomState(0)
    tied = mx.nd.array(rng.randn(4).astype(np.float32))
    ws = [tied, mx.nd.NDArray(tied._data),  # alias group -> eager
          mx.nd.array(rng.randn(4).astype(np.float32))]  # fused
    gs = [mx.nd.array(np.full(4, 100.0, np.float32)),  # huge eager grads
          mx.nd.array(np.full(4, 100.0, np.float32)),
          mx.nd.array(np.full(4, 0.01, np.float32))]   # tiny fused grad
    upd.update_batch([0, 1, 2], gs, ws)
    assert of.FUSED_STATS["fused_steps"] == 1  # really a mixed batch
    got = float(upd.last_grad_norm)
    want = float(np.sqrt(sum(float((g.asnumpy() ** 2).sum()) for g in gs)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_guarded_multi_precision_skip_is_exact(monkeypatch):
    """bf16 weights + f32 master copy under the guard: a skipped step
    leaves BOTH the master and the bf16 storage bit-identical."""
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@1")
    from mxtpu import optimizer as opt
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9,
                   multi_precision=True)
    upd = opt.get_updater(o)
    rng = np.random.RandomState(0)
    ws = [mx.nd.array(rng.randn(6).astype(np.float32)).astype("bfloat16")
          for _ in range(2)]

    def step():
        gs = [mx.nd.array(rng.randn(6).astype(np.float32))
              .astype("bfloat16") for _ in range(2)]
        upd.update_batch([0, 1], gs, ws)
    step()
    assert of.FUSED_STATS["fused_steps"] == 1  # the mp path really fused
    w_before = [w.asnumpy().copy() for w in ws]
    masters_before = [np.asarray(of._tree_data(upd.states[i])[0]).copy()
                      for i in (0, 1)]
    step()  # poisoned
    assert bool(upd.last_step_ok) is False
    for w, b in zip(ws, w_before):
        np.testing.assert_array_equal(w.asnumpy(), b)
    for i, m in zip((0, 1), masters_before):
        np.testing.assert_array_equal(
            np.asarray(of._tree_data(upd.states[i])[0]), m)


def test_guarded_eager_optimizers_still_skip(monkeypatch):
    """Optimizers without an in-graph t rule (Nadam) take the guarded-eager
    path: one sync per step, but the skip/backoff semantics hold."""
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@1")
    scaler = resilience.DynamicLossScaler(init_scale=8.0)
    tr, params, rng = _make_trainer(optimizer="nadam",
                                    opt_params={"learning_rate": 0.01},
                                    scaler=scaler)
    _set_grads(params, rng)
    tr.step(1)
    w_before = [p.data().asnumpy().copy() for p in params]
    _set_grads(params, rng)
    ok = tr.step(1)
    assert bool(ok.asnumpy()) is False
    for p, w in zip(params, w_before):
        np.testing.assert_array_equal(p.data().asnumpy(), w)
    assert scaler.scale_value() == 4.0
    assert of.FUSED_STATS["fused_steps"] == 0  # really took the eager path


def test_guarded_empty_update_batch_is_noop(monkeypatch):
    """An empty batch no-ops under the guard exactly like the base
    Updater — no crash, no recorded step."""
    from mxtpu import optimizer as opt
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    upd = opt.get_updater(opt.SGD(learning_rate=0.1))
    upd.update_batch([], [], [])
    assert upd.last_step_ok is None and len(upd.health) == 0


def test_module_update_rides_the_sentinel(monkeypatch):
    """module.Module.update drives the same guarded updater: a NaN step is
    skipped, params untouched, and the async verdict lands on
    module.last_step_ok."""
    from mxtpu import symbol as sym
    from mxtpu.io import DataBatch, DataDesc
    from mxtpu.module import Module
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@1")
    data = sym.var("data")
    net = sym.FullyConnected(data, sym.var("fc_weight"), sym.var("fc_bias"),
                             num_hidden=4, name="fc")
    net = sym.SoftmaxOutput(net, sym.var("softmax_label"), name="softmax")
    mod = Module(net)
    mod.bind(data_shapes=[DataDesc("data", (8, 6))],
             label_shapes=[DataDesc("softmax_label", (8,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    rng = np.random.RandomState(0)
    batch = DataBatch(data=[mx.nd.array(rng.randn(8, 6)
                                        .astype(np.float32))],
                      label=[mx.nd.array(rng.randint(0, 4, (8,))
                                         .astype(np.float32))])

    def one_step():
        mod.forward(batch)
        mod.backward()
        mod.update()
    one_step()
    assert bool(mod.last_step_ok) is True
    w_before = {n: mod._exec.arg_dict[n].asnumpy().copy()
                for n in mod._param_names}
    one_step()  # the poisoned step
    assert bool(mod.last_step_ok) is False
    for n in mod._param_names:
        np.testing.assert_array_equal(mod._exec.arg_dict[n].asnumpy(),
                                      w_before[n])
    one_step()
    assert bool(mod.last_step_ok) is True


# --------------------------------------------------------------- monitoring
def test_training_health_monitor_logs_skips(monkeypatch, caplog):
    import logging

    from mxtpu.monitor import TrainingHealthMonitor
    monkeypatch.setenv("MXTPU_NUMERICS_GUARD", "1")
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@1")
    tr, params, rng = _make_trainer()
    mon = TrainingHealthMonitor(interval=3).install(tr)
    with caplog.at_level(logging.WARNING, logger="mxtpu.resilience"):
        for _ in range(3):
            _set_grads(params, rng)
            tr.step(1)
            mon.after_step()
    assert [s for s, _ in mon.skipped] == [1]
    assert any("skipped" in r.message for r in caplog.records)


# ------------------------------------------------------------- checkpointing
def _loop_trainer(tmp_path, every_steps=100):
    scaler = resilience.DynamicLossScaler(init_scale=16.0, growth_interval=4)
    tr, params, _ = _make_trainer(optimizer="adam",
                                  opt_params={"learning_rate": 0.05},
                                  scaler=scaler, seed=3)
    loop = resilience.ResilientLoop(
        tr, resilience.CheckpointPolicy(str(tmp_path),
                                        every_steps=every_steps))
    return loop, tr, params, scaler


def _deterministic_step(tr, params):
    def step_fn(step):
        rng = np.random.RandomState(1000 + step)
        for p in params:
            base = mx.nd.array(rng.randn(*p.shape).astype(np.float32))
            noise = mx.nd.random_normal(shape=p.shape) * 0.1
            p.grad()[:] = base + noise  # trajectory depends on GLOBAL RNG
        tr.step(1)
    return step_fn


def test_sigterm_checkpoints_and_resumes_bitexact(tmp_path, monkeypatch):
    """SIGTERM mid-train -> final checkpoint; a FRESH trainer resumes and
    finishes with params/optimizer/scaler/RNG bit-identical to an
    uninterrupted run."""
    # uninterrupted reference
    mx.random.seed(7)
    loop_c, tr_c, params_c, scaler_c = _loop_trainer(tmp_path / "ref")
    loop_c.run(_deterministic_step(tr_c, params_c), 8)

    # interrupted run: SIGTERM injected after step 4
    mx.random.seed(7)
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "sigterm@4")
    loop_a, tr_a, params_a, _ = _loop_trainer(tmp_path / "run")
    last = loop_a.run(_deterministic_step(tr_a, params_a), 8)
    assert loop_a.preempted and last == 4
    assert loop_a.latest_step() == 4
    monkeypatch.delenv("MXTPU_FAULT_INJECT")
    resilience.reset_faults()

    # fresh process stand-in: new objects, scrambled RNG — resume fixes all
    mx.random.seed(999)
    loop_b, tr_b, params_b, scaler_b = _loop_trainer(tmp_path / "run")
    start = loop_b.resume()
    assert start == 5
    loop_b.run(_deterministic_step(tr_b, params_b), 8, start_step=start)

    for a, b in zip(params_c, params_b):
        np.testing.assert_array_equal(a.data().asnumpy(),
                                      b.data().asnumpy())
    _, s_ref = _snapshot(tr_c, params_c)
    _, s_res = _snapshot(tr_b, params_b)
    for a, b in zip(s_ref, s_res):
        np.testing.assert_array_equal(a, b)
    assert scaler_b.scale_value() == scaler_c.scale_value()
    assert int(tr_b._updaters[0]._t_good) == int(tr_c._updaters[0]._t_good)


def test_ckpt_io_failure_retries_then_succeeds(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "ckpt_io@0")
    loop, tr, params, _ = _loop_trainer(tmp_path)
    rng = np.random.RandomState(0)
    _set_grads(params, rng)
    tr.step(1)
    assert loop.save(0) is True  # first attempt failed, retry landed
    assert resilience.FAULT_STATS["fired"] == [("ckpt_io", 0)]
    loop.wait_for_pending()  # interval saves are async: drain before reading
    assert loop.latest_step() == 0
    with open(os.path.join(str(tmp_path), "latest.json")) as f:
        assert json.load(f)["step"] == 0


def test_ckpt_io_failure_degrades_gracefully(tmp_path, monkeypatch):
    """Every retry failing must NOT kill training for an interval save —
    only the final preemption save raises."""
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "ckpt_io@0,1,2,3,4,5")
    monkeypatch.setenv("MXTPU_CKPT_RETRIES", "1")
    loop, tr, params, _ = _loop_trainer(tmp_path)
    rng = np.random.RandomState(0)
    _set_grads(params, rng)
    tr.step(1)
    assert loop.save(0) is False  # logged, swallowed
    assert loop.latest_step() is None
    with pytest.raises(OSError):
        loop.save(1, final=True)  # the preemption save stays loud


def test_resume_ignores_unfinalized_latest(tmp_path):
    """latest.json pointing at a step dir that never materialized (async
    save died) falls back to the newest FINALIZED step."""
    loop, tr, params, _ = _loop_trainer(tmp_path)
    rng = np.random.RandomState(0)
    _set_grads(params, rng)
    tr.step(1)
    assert loop.save(3) is True
    loop.wait_for_pending()
    loop._write_latest(9)  # simulate a crash after pointing at step 9
    assert loop.latest_step() == 3


def test_restore_without_scaler_warns_instead_of_resurrecting(caplog):
    """Loading scaler-carrying states into a scaler-less trainer must NOT
    silently activate the guard's unscale (nothing would scale the loss —
    training would stall 32768x); it warns and continues unscaled."""
    import logging
    scaler = resilience.DynamicLossScaler(init_scale=128.0)
    tr_a, params_a, rng = _make_trainer(scaler=scaler)
    _set_grads(params_a, rng)
    tr_a.step(1)
    blob = tr_a._updaters[0].get_states(dump_optimizer=True)
    tr_b, params_b, rng_b = _make_trainer()  # no scaler
    with caplog.at_level(logging.WARNING, logger="mxtpu.resilience"):
        tr_b._updaters[0].set_states(blob)
    assert tr_b._updaters[0].scaler is None
    assert any("no loss scaler is attached" in r.message
               for r in caplog.records)
    _set_grads(params_b, rng_b)
    assert tr_b.step(1) is None  # really unguarded: no verdict


def test_kvstore_dist_reduce_retries_transient_failure(monkeypatch):
    from mxtpu import kvstore as kv_mod
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "kv_fail@0")
    kv = kv_mod.KVStore("dist_sync")
    out = kv._dist_reduce(["0"], [np.ones(3, np.float32)])
    np.testing.assert_allclose(np.asarray(out[0]), 1.0)
    assert resilience.FAULT_STATS["fired"] == [("kv_fail", 0)]

    calls = {"n": 0}
    from mxtpu import distributed

    def flaky(x):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient DCN hiccup")
        return x
    monkeypatch.setattr(distributed, "allreduce_host", flaky)
    out = kv._dist_reduce(["0"], [np.full(2, 2.0, np.float32)])
    np.testing.assert_allclose(np.asarray(out[0]), 2.0)
    assert calls["n"] == 2


# ----------------------------------------------------- async checkpoint sat.
def test_checkpoint_overwrite_requires_force(tmp_path):
    from mxtpu.contrib import async_checkpoint as ackpt
    from mxtpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(4))
    net.initialize()
    net(mx.nd.ones((2, 3)))
    ackpt.save_block(net, str(tmp_path), step=0)
    with pytest.raises(mx.MXNetError, match="force=True"):
        ackpt.save_block(net, str(tmp_path), step=0)
    ackpt.save_block(net, str(tmp_path), step=0, force=True)  # explicit wins


def test_async_background_error_surfaces_on_next_save(tmp_path, monkeypatch):
    """An exception captured in the async checkpointer's background thread
    must fail the NEXT save loudly instead of rotting silently."""
    from mxtpu.contrib import async_checkpoint as ackpt
    from mxtpu.gluon import nn
    net = nn.HybridSequential()
    net.add(nn.Dense(4))
    net.initialize()
    net(mx.nd.ones((2, 3)))
    ck = ackpt.save_block(net, str(tmp_path), step=0, async_save=True)
    ck.wait_until_finished()

    def boom():
        raise RuntimeError("background write died")
    monkeypatch.setattr(ackpt._ASYNC_CKPTR, "check_for_errors", boom,
                        raising=False)
    with pytest.raises(RuntimeError, match="background write died"):
        ackpt.save_block(net, str(tmp_path), step=1, async_save=True)


# ------------------------------------------------------------ dataloader
def test_killed_dataloader_worker_restarts_and_epoch_completes():
    from _mp_light_datasets import PlainArrayPairDataset

    from mxtpu.gluon.data import DataLoader
    ds = PlainArrayPairDataset(n=64)
    serial = [b[0].asnumpy() for b in DataLoader(ds, batch_size=4)]
    # ONE worker: killing it guarantees the parent stalls and takes the
    # restart path (with >1, a surviving worker can finish the epoch
    # before the death is ever observed — correct, but unasserted)
    dl = DataLoader(ds, batch_size=4, num_workers=1)
    with pytest.warns(UserWarning, match="restarting"):
        got = []
        for i, b in enumerate(dl):
            got.append(b[0].asnumpy())
            if i == 0:  # kill the worker mid-epoch
                workers = dl._pool[2]
                os.kill(workers[0].pid, signal.SIGKILL)
        # second epoch reuses the healed pool
        got2 = [b[0].asnumpy() for b in dl]
    dl.close()
    assert len(got) == len(serial) and len(got2) == len(serial)
    for s, g in zip(serial, got):
        np.testing.assert_array_equal(s, g)
    for s, g in zip(serial, got2):
        np.testing.assert_array_equal(s, g)


def test_dataloader_gives_up_with_exit_codes_and_batch_index(monkeypatch):
    from _mp_light_datasets import PlainArrayPairDataset

    from mxtpu.gluon.data import DataLoader
    monkeypatch.setenv("MXTPU_DL_WORKER_RESTARTS", "0")
    dl = DataLoader(PlainArrayPairDataset(n=64), batch_size=4, num_workers=2)
    with pytest.raises(RuntimeError,
                       match=r"exit codes \[-9.*batch \d+/16"):
        for i, _b in enumerate(dl):
            if i == 0:
                for w in dl._pool[2]:
                    os.kill(w.pid, signal.SIGKILL)
    dl.close()


def test_worker_death_injection_hook(monkeypatch):
    """MXTPU_FAULT_INJECT=worker_death@N kills a live worker at batch N —
    the same restart path, driven by the deterministic injection hook."""
    from _mp_light_datasets import PlainArrayPairDataset

    from mxtpu.gluon.data import DataLoader
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "worker_death@2")
    ds = PlainArrayPairDataset(n=48)
    serial = [b[0].asnumpy() for b in DataLoader(ds, batch_size=4)]
    dl = DataLoader(ds, batch_size=4, num_workers=1)  # deterministic stall
    with pytest.warns(UserWarning, match="restarting"):
        got = [b[0].asnumpy() for b in dl]
    dl.close()
    assert resilience.FAULT_STATS["fired"] == [("worker_death", 2)]
    for s, g in zip(serial, got):
        np.testing.assert_array_equal(s, g)


# ---------------------------------------------------------------- injection
def test_fault_spec_parsing_and_consume_once(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@3,5;ckpt_io@0")
    assert resilience.inject("nan_grad", 2) is False
    assert resilience.inject("nan_grad", 3) is True
    assert resilience.inject("nan_grad", 3) is False  # consumed
    assert resilience.inject("nan_grad", 5) is True
    assert resilience.inject("ckpt_io") is True       # counter-indexed
    assert resilience.inject("ckpt_io") is False
    assert resilience.inject("unknown") is False
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "broken")
    with pytest.raises(mx.MXNetError, match="kind@idx"):
        resilience.inject("nan_grad", 0)


def test_with_retries_backs_off_and_reraises():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"
    assert resilience.with_retries(flaky, "t", retries=3,
                                   backoff=0.001) == "ok"
    assert calls["n"] == 3

    def hard():
        raise OSError("hard failure")
    with pytest.raises(OSError, match="hard failure"):
        resilience.with_retries(hard, "t", retries=1, backoff=0.001)


def test_with_retries_decorrelated_jitter_deterministic():
    """ISSUE-14 satellite: the backoff is decorrelated-jittered (first
    wait exactly ``backoff``, then uniform in [base, 3*prev], capped) off
    an injectable sleeper+rng — a seeded run is bit-deterministic and
    sleep-free, different seeds de-synchronize (the anti-thundering-herd
    point), and the retry/reraise contract above is unchanged."""
    import random

    def run(seed, n_fail=5):
        delays = []
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] <= n_fail:
                raise OSError("transient")
            return "ok"
        out = resilience.with_retries(flaky, "t", retries=n_fail,
                                      backoff=0.25,
                                      sleeper=delays.append,
                                      rng=random.Random(seed))
        assert out == "ok"
        return delays

    a = run(7)
    assert a[0] == 0.25  # deterministic floor for the first retry
    for prev, d in zip(a, a[1:]):
        assert 0.25 <= d <= max(0.25, prev * 3.0)  # decorrelated bounds
    assert all(d <= 0.25 * 64 for d in a)          # cap
    assert run(7) == a        # seeded => bit-deterministic
    assert run(8) != a        # fleet members draw different schedules


def test_new_fault_kinds_consume_once(monkeypatch):
    """ISSUE-14 satellite: the survivability fault kinds ride inject()'s
    consume-once-per-(kind,index) semantics like every other kind."""
    monkeypatch.setenv(
        "MXTPU_FAULT_INJECT",
        "train_wedge@2;ckpt_corrupt@0;divergence@1;supervisor_crash@0")
    assert resilience.inject("train_wedge", 1) is False
    assert resilience.inject("train_wedge", 2) is True
    assert resilience.inject("train_wedge", 2) is False   # consumed
    assert resilience.inject("ckpt_corrupt") is True      # counter-indexed
    assert resilience.inject("ckpt_corrupt") is False
    assert resilience.inject("divergence", 0) is False
    assert resilience.inject("divergence", 1) is True
    assert resilience.inject("divergence", 1) is False
    assert resilience.inject("supervisor_crash", 0) is True
    assert resilience.inject("supervisor_crash", 0) is False
    assert resilience.FAULT_STATS["fired"] == [
        ("train_wedge", 2), ("ckpt_corrupt", 0), ("divergence", 1),
        ("supervisor_crash", 0)]
