"""Legacy mx.rnn symbolic cells (ref: tests/python/unittest/test_rnn.py —
unroll each cell kind, bind, check shapes; LSTM additionally against a
NumPy oracle)."""
import numpy as np

import mxtpu as mx
from mxtpu import rnn

B, T, I, H = 4, 3, 5, 6


def _bind_forward(outputs, states, feed):
    """Group outputs+states, bind with feed dict, return forward values."""
    from mxtpu.symbol import Group
    heads = (list(outputs) if isinstance(outputs, (list, tuple))
             else [outputs]) + list(states)
    g = Group(heads)
    args = {n: mx.nd.array(feed[n]) for n in g.list_arguments()
            if n in feed}
    missing = [n for n in g.list_arguments() if n not in feed]
    assert not missing, "unbound args: %s" % missing
    exe = g.bind(args=args, grad_req="null")
    return [o.asnumpy() for o in exe.forward()]


def _feed(names, rng):
    return {n: rng.uniform(-0.5, 0.5, s).astype(np.float32)
            for n, s in names.items()}


def test_rnn_cell_unroll():
    cell = rnn.RNNCell(H, prefix="rnn_")
    x = mx.sym.var("data")
    outputs, states = cell.unroll(
        T, inputs=x, begin_state=cell.begin_state(batch_size=B),
        layout="NTC", merge_outputs=False)
    rng = np.random.RandomState(0)
    feed = _feed({"data": (B, T, I), "rnn_i2h_weight": (H, I),
                  "rnn_i2h_bias": (H,), "rnn_h2h_weight": (H, H),
                  "rnn_h2h_bias": (H,)}, rng)
    vals = _bind_forward(outputs, states, feed)
    assert all(v.shape == (B, H) for v in vals[:T])
    # oracle: h_t = tanh(x W_i^T + b_i + h W_h^T + b_h)
    h = np.zeros((B, H), np.float32)
    for t in range(T):
        h = np.tanh(feed["data"][:, t] @ feed["rnn_i2h_weight"].T
                    + feed["rnn_i2h_bias"]
                    + h @ feed["rnn_h2h_weight"].T + feed["rnn_h2h_bias"])
        np.testing.assert_allclose(vals[t], h, rtol=1e-5, atol=1e-6)


def test_lstm_cell_oracle_and_merge():
    cell = rnn.LSTMCell(H, prefix="lstm_", forget_bias=1.0)
    outputs, states = cell.unroll(
        T, inputs=mx.sym.var("data"),
        begin_state=cell.begin_state(batch_size=B), layout="NTC",
        merge_outputs=True)
    rng = np.random.RandomState(1)
    feed = _feed({"data": (B, T, I), "lstm_i2h_weight": (4 * H, I),
                  "lstm_i2h_bias": (4 * H,), "lstm_h2h_weight": (4 * H, H),
                  "lstm_h2h_bias": (4 * H,)}, rng)
    merged, h_out, c_out = _bind_forward(outputs, states, feed)
    assert merged.shape == (B, T, H)

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    for t in range(T):
        gates = (feed["data"][:, t] @ feed["lstm_i2h_weight"].T
                 + feed["lstm_i2h_bias"]
                 + h @ feed["lstm_h2h_weight"].T + feed["lstm_h2h_bias"])
        i, f, g, o = np.split(gates, 4, axis=1)
        c = sig(f + 1.0) * c + sig(i) * np.tanh(g)  # forget_bias 1.0
        h = sig(o) * np.tanh(c)
        np.testing.assert_allclose(merged[:, t], h, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h_out, h, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(c_out, c, rtol=1e-4, atol=1e-5)


def test_gru_cell_unroll():
    cell = rnn.GRUCell(H, prefix="gru_")
    outputs, states = cell.unroll(
        T, inputs=mx.sym.var("data"),
        begin_state=cell.begin_state(batch_size=B), merge_outputs=True)
    rng = np.random.RandomState(2)
    feed = _feed({"data": (B, T, I), "gru_i2h_weight": (3 * H, I),
                  "gru_i2h_bias": (3 * H,), "gru_h2h_weight": (3 * H, H),
                  "gru_h2h_bias": (3 * H,)}, rng)
    merged = _bind_forward(outputs, states, feed)[0]
    assert merged.shape == (B, T, H)
    assert np.isfinite(merged).all()


def test_stacked_residual_dropout_cells():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(H, prefix="l0_"))
    stack.add(rnn.ResidualCell(rnn.LSTMCell(H, prefix="l1_")))
    stack.add(rnn.DropoutCell(0.5, prefix="do_"))
    outputs, states = stack.unroll(
        T, inputs=mx.sym.var("data"),
        begin_state=stack.begin_state(batch_size=B), merge_outputs=True)
    assert len(states) == 4  # 2 LSTM cells x (h, c); dropout stateless
    rng = np.random.RandomState(3)
    shapes = {"data": (B, T, H)}
    for p in ("l0_", "l1_"):
        shapes.update({p + "i2h_weight": (4 * H, H),
                       p + "i2h_bias": (4 * H,),
                       p + "h2h_weight": (4 * H, H),
                       p + "h2h_bias": (4 * H,)})
    vals = _bind_forward(outputs, states, _feed(shapes, rng))
    assert vals[0].shape == (B, T, H)


def test_bidirectional_cell():
    bi = rnn.BidirectionalCell(rnn.LSTMCell(H, prefix="l_"),
                               rnn.LSTMCell(H, prefix="r_"))
    outputs, states = bi.unroll(
        T, inputs=mx.sym.var("data"),
        begin_state=bi.begin_state(batch_size=B), merge_outputs=True)
    rng = np.random.RandomState(4)
    shapes = {"data": (B, T, I)}
    for p in ("l_", "r_"):
        shapes.update({p + "i2h_weight": (4 * H, I),
                       p + "i2h_bias": (4 * H,),
                       p + "h2h_weight": (4 * H, H),
                       p + "h2h_bias": (4 * H,)})
    vals = _bind_forward(outputs, states, _feed(shapes, rng))
    assert vals[0].shape == (B, T, 2 * H)
    # the reverse half must actually see the reversed sequence: the last
    # H columns at t=0 depend on the whole sequence, so they differ from
    # a fwd-only unroll's t=0 (weak but real asymmetry check)
    assert not np.allclose(vals[0][:, 0, H:], vals[0][:, -1, H:])


def test_fused_rnn_cell_and_unfuse():
    fused = rnn.FusedRNNCell(H, num_layers=2, mode="lstm", prefix="f_",
                             get_next_state=True)
    outputs, states = fused.unroll(
        T, inputs=mx.sym.var("data"),
        begin_state=fused.begin_state(batch_size=B), layout="NTC",
        merge_outputs=True)
    # packed parameter size: layer0 4H(I+H) + 8H bias, layer1 4H(H+H) + 8H
    n_params = (4 * H * (I + H) + 8 * H) + (4 * H * (H + H) + 8 * H)
    rng = np.random.RandomState(5)
    feed = _feed({"data": (B, T, I), "f_parameters": (n_params,)}, rng)
    vals = _bind_forward(outputs, states, feed)
    assert vals[0].shape == (B, T, H)
    assert vals[1].shape == (2, B, H) and vals[2].shape == (2, B, H)

    stack = fused.unfuse()
    outputs2, _ = stack.unroll(
        T, inputs=mx.sym.var("data"),
        begin_state=stack.begin_state(batch_size=B), merge_outputs=True)
    names = set()
    from mxtpu.symbol import Group
    g = Group([outputs2])
    names = set(g.list_arguments())
    assert "f_l0_i2h_weight" in names and "f_l1_h2h_weight" in names


def test_zoneout_cell_runs():
    z = rnn.ZoneoutCell(rnn.RNNCell(H, prefix="z_"), zoneout_outputs=0.3,
                        zoneout_states=0.3)
    outputs, states = z.unroll(
        T, inputs=mx.sym.var("data"),
        begin_state=z.begin_state(batch_size=B), merge_outputs=True)
    rng = np.random.RandomState(6)
    feed = _feed({"data": (B, T, I), "z_i2h_weight": (H, I),
                  "z_i2h_bias": (H,), "z_h2h_weight": (H, H),
                  "z_h2h_bias": (H,)}, rng)
    vals = _bind_forward(outputs, states, feed)
    assert np.isfinite(vals[0]).all()


def test_fused_unpack_matches_unfused_stack():
    """fused.unroll(blob) == unfuse().unroll(unpack_weights(blob)) — the
    reference's documented fused<->unfused workflow, checked numerically,
    plus pack_weights round-trip."""
    fused = rnn.FusedRNNCell(H, num_layers=2, mode="lstm", prefix="f_")
    outputs, _ = fused.unroll(
        T, inputs=mx.sym.var("data"),
        begin_state=fused.begin_state(batch_size=B), merge_outputs=True)
    n_params = (4 * H * (I + H) + 8 * H) + (4 * H * (H + H) + 8 * H)
    rng = np.random.RandomState(7)
    feed = _feed({"data": (B, T, I), "f_parameters": (n_params,)}, rng)
    fused_out = _bind_forward(outputs, [], feed)[0]

    stack = fused.unfuse()
    s_out, _ = stack.unroll(
        T, inputs=mx.sym.var("data"),
        begin_state=stack.begin_state(batch_size=B), merge_outputs=True)
    unpacked = fused.unpack_weights({"f_parameters":
                                     mx.nd.array(feed["f_parameters"])})
    feed2 = {k: (v.asnumpy() if hasattr(v, "asnumpy") else v)
             for k, v in unpacked.items()}
    feed2["data"] = feed["data"]
    stack_out = _bind_forward(s_out, [], feed2)[0]
    np.testing.assert_allclose(stack_out, fused_out, rtol=1e-4, atol=1e-5)

    packed = fused.pack_weights(unpacked)
    np.testing.assert_allclose(packed["f_parameters"].asnumpy(),
                               feed["f_parameters"], rtol=1e-6)


def test_cell_weight_sharing_via_params():
    """Weight sharing through an explicit RNNParams container (ref:
    RNNParams docstring): two cells with the same prefix+params reuse the
    SAME variables; the stack's merged container sees them once."""
    c1 = rnn.LSTMCell(H, prefix="s0_")
    c2 = rnn.LSTMCell(H, prefix="s0_", params=c1.params)
    assert c2._iW is c1._iW and c2._hB is c1._hB
    stack = rnn.SequentialRNNCell()
    stack.add(c1)
    stack.add(c2)
    assert "s0_i2h_weight" in stack.params._params


def test_rnn_hoist_ab_legs_identical(monkeypatch):
    """MXTPU_RNN_HOIST=0 (input GEMM inside the scan, the pre-round-5
    lowering) must equal the hoisted default bit-for-bit in f32 — the
    perf A/B compares identical math."""
    import numpy as np
    import mxtpu as mx
    from mxtpu.ops import invoke as op_invoke
    from mxtpu.ops.rnn_ops import rnn_param_size
    rng = np.random.RandomState(0)
    for mode in ("lstm", "gru", "rnn_tanh"):
        size = rnn_param_size(mode, 2, 6, 5, bidirectional=True)
        params = mx.nd.array(rng.randn(size).astype(np.float32) * 0.1)
        data = mx.nd.array(rng.randn(7, 3, 6).astype(np.float32))
        state = mx.nd.zeros((4, 3, 5))
        kw = dict(state_size=5, num_layers=2, mode=mode,
                  bidirectional=True)
        if mode == "lstm":
            kw["state_cell"] = mx.nd.zeros((4, 3, 5))
        monkeypatch.setenv("MXTPU_RNN_HOIST", "1")
        hoisted = op_invoke("RNN", data, params, state, **kw).asnumpy()
        monkeypatch.setenv("MXTPU_RNN_HOIST", "0")
        inscan = op_invoke("RNN", data, params, state, **kw).asnumpy()
        np.testing.assert_allclose(hoisted, inscan, rtol=1e-5, atol=1e-6)
