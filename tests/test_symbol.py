"""Symbol/Executor tests (ref pattern: tests/python/unittest/test_symbol.py,
test_executor.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import gluon
from mxtpu import symbol as sym
from mxtpu.base import MXNetError
from mxtpu.gluon import nn


def _mlp_sym():
    data = sym.var("data")
    w1, b1 = sym.var("fc1_weight"), sym.var("fc1_bias")
    net = sym.FullyConnected(data, w1, b1, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    w2, b2 = sym.var("fc2_weight"), sym.var("fc2_bias")
    return sym.FullyConnected(net, w2, b2, num_hidden=4, name="fc2")


def test_compose_and_listing():
    net = _mlp_sym()
    assert net.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                    "fc2_weight", "fc2_bias"]
    assert net.list_outputs() == ["fc2_output"]
    assert net.name == "fc2"


def test_infer_shape():
    net = _mlp_sym()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(
        data=(8, 10), fc1_weight=(16, 10), fc1_bias=(16,),
        fc2_weight=(4, 16), fc2_bias=(4,))
    assert out_shapes == [(8, 4)]
    assert arg_shapes[0] == (8, 10)


def test_eval_matches_ndarray():
    np.random.seed(0)
    x = mx.nd.array(np.random.normal(size=(3, 5)).astype(np.float32))
    w = mx.nd.array(np.random.normal(size=(7, 5)).astype(np.float32))
    b = mx.nd.array(np.random.normal(size=(7,)).astype(np.float32))
    s = sym.FullyConnected(sym.var("x"), sym.var("w"), sym.var("b"),
                           num_hidden=7)
    out = s.eval(x=x, w=w, b=b)[0]
    ref = mx.nd.FullyConnected(x, w, b, num_hidden=7)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-5)


def test_arithmetic_and_scalar_ops():
    a, b = sym.var("a"), sym.var("b")
    c = (a + b) * 2.0 - a / b
    x = mx.nd.array([[2.0, 4.0]])
    y = mx.nd.array([[1.0, 2.0]])
    out = c.eval(a=x, b=y)[0].asnumpy()
    np.testing.assert_allclose(out, [[(2 + 1) * 2 - 2, (4 + 2) * 2 - 2]])


def test_json_roundtrip():
    net = _mlp_sym()
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    x = mx.nd.ones((2, 10))
    feed = {"data": x,
            "fc1_weight": mx.nd.ones((16, 10)), "fc1_bias": mx.nd.zeros((16,)),
            "fc2_weight": mx.nd.ones((4, 16)), "fc2_bias": mx.nd.zeros((4,))}
    np.testing.assert_allclose(net2.eval(**feed)[0].asnumpy(),
                               net.eval(**feed)[0].asnumpy())


def test_simple_bind_forward_backward():
    net = _mlp_sym()
    exe = net.simple_bind(grad_req="write", data=(8, 10))
    rng = np.random.RandomState(0)
    for name, arr in exe.arg_dict.items():
        arr._set_data(mx.nd.array(
            rng.normal(scale=0.1, size=arr.shape).astype(np.float32))._data)
    out = exe.forward(is_train=True, data=mx.nd.ones((8, 10)))[0]
    assert out.shape == (8, 4)
    exe.backward(out_grads=mx.nd.ones((8, 4)))
    # numeric check of one weight gradient against finite differences
    w = exe.arg_dict["fc2_weight"]
    g = exe.grad_dict["fc2_weight"].asnumpy()
    eps = 1e-3
    wd = w.asnumpy().copy()
    wd[0, 0] += eps
    w._set_data(mx.nd.array(wd)._data)
    out_p = exe.forward(is_train=True)[0].asnumpy().sum()
    wd[0, 0] -= 2 * eps
    w._set_data(mx.nd.array(wd)._data)
    out_m = exe.forward(is_train=True)[0].asnumpy().sum()
    np.testing.assert_allclose(g[0, 0], (out_p - out_m) / (2 * eps),
                               rtol=1e-2, atol=1e-3)


def test_batchnorm_executor_updates_aux():
    data = sym.var("data")
    out = sym.BatchNorm(data, sym.var("bn_gamma"), sym.var("bn_beta"),
                        sym.var("bn_moving_mean"), sym.var("bn_moving_var"),
                        fix_gamma=False, name="bn")
    exe = out.simple_bind(data=(16, 4))
    exe.arg_dict["bn_gamma"]._set_data(mx.nd.ones((4,))._data)
    x = mx.nd.array(np.random.RandomState(0).normal(
        loc=3.0, size=(16, 4)).astype(np.float32))
    before = exe.aux_dict["bn_moving_mean"].asnumpy().copy()
    exe.forward(is_train=True, data=x)
    after = exe.aux_dict["bn_moving_mean"].asnumpy()
    assert not np.allclose(before, after)
    # eval mode must not touch aux
    snap = after.copy()
    exe.forward(is_train=False, data=x)
    np.testing.assert_allclose(exe.aux_dict["bn_moving_mean"].asnumpy(), snap)


def test_grad_req_add_and_null():
    x = sym.var("x")
    y = (x * 2.0)
    exe = y.bind(args={"x": mx.nd.ones((3,))},
                 grad_req={"x": "add"})
    exe.forward(is_train=True)
    exe.backward(out_grads=mx.nd.ones((3,)))
    exe.backward(out_grads=mx.nd.ones((3,)))
    np.testing.assert_allclose(exe.grad_dict["x"].asnumpy(), [4.0, 4.0, 4.0])


def test_trace_block_export_symbolblock():
    np.random.seed(0)
    net = nn.HybridSequential(prefix="mlp_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.normal(size=(2, 8)).astype(np.float32))
    ref = net(x).asnumpy()

    s, arg_names = sym.trace_block(net)
    assert "data" in s.list_inputs()
    # evaluate the traced graph with the block's own params
    feed = {"data": x}
    for name, p in net.collect_params().items():
        feed[name] = p.data()
    np.testing.assert_allclose(s.eval(**feed)[0].asnumpy(), ref, rtol=1e-5)


def test_export_and_symbolblock_imports(tmp_path):
    np.random.seed(0)
    net = nn.HybridSequential(prefix="exp_")
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"))
        net.add(nn.Dense(2))
    net.initialize()
    x = mx.nd.array(np.random.normal(size=(4, 6)).astype(np.float32))
    ref = net(x).asnumpy()
    path = str(tmp_path / "model")
    net.export(path)

    loaded = gluon.SymbolBlock.imports(path + "-symbol.json", ["data"],
                                       path + "-0000.params")
    out = loaded(x).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_group_and_slicing():
    a, b = sym.var("a"), sym.var("b")
    g = sym.Group([a * 2.0, b + 1.0])
    assert len(g.list_outputs()) == 2
    outs = g.eval(a=mx.nd.ones((2,)), b=mx.nd.zeros((2,)))
    np.testing.assert_allclose(outs[0].asnumpy(), [2, 2])
    np.testing.assert_allclose(outs[1].asnumpy(), [1, 1])
    first = g[0]
    np.testing.assert_allclose(first.eval(a=mx.nd.ones((2,)))[0].asnumpy(),
                               [2, 2])


def test_infer_shape_backward_fill_conv():
    """Unknown conv/FC parameter shapes are filled from the data shape by
    the registry's per-op FInferShape rules (ref:
    src/executor/infer_graph_attr_pass.cc backward fill)."""
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, weight=mx.sym.Variable("cw"),
                             bias=mx.sym.Variable("cb"), kernel=(3, 3),
                             num_filter=8, pad=(1, 1), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, weight=mx.sym.Variable("fw"),
                                bias=mx.sym.Variable("fb"), num_hidden=10)
    arg_shapes, out_shapes, _ = net.infer_shape(data=(2, 3, 8, 8))
    shapes = dict(zip(net.list_arguments(), arg_shapes))
    assert shapes["cw"] == (8, 3, 3, 3)
    assert shapes["cb"] == (8,)
    assert shapes["fw"] == (10, 8 * 8 * 8)
    assert shapes["fb"] == (10,)
    assert out_shapes == [(2, 10)]


def test_infer_shape_backward_fill_rnn():
    """RNN packed parameter vector + state shapes from the TNC data shape
    (ref: rnn-inl.h GetParamSize)."""
    from mxtpu.ops.rnn_ops import rnn_param_size
    data = mx.sym.Variable("data")
    out = mx.sym.RNN(data, parameters=mx.sym.Variable("p"),
                     state=mx.sym.Variable("h0"),
                     state_cell=mx.sym.Variable("c0"),
                     state_size=16, num_layers=2, mode="lstm")
    arg_shapes, out_shapes, _ = out.infer_shape(data=(5, 3, 8))
    shapes = dict(zip(out.list_arguments(), arg_shapes))
    assert shapes["p"] == (rnn_param_size("lstm", 2, 8, 16),)
    assert shapes["h0"] == (2, 3, 16)
    assert shapes["c0"] == (2, 3, 16)
    assert out_shapes == [(5, 3, 16)]


def test_bucketing_module_unseen_bucket():
    """BucketingModule switches to a bucket never bound before: shape
    inference must complete from the data shape alone
    (ref: python/mxnet/module/bucketing_module.py)."""
    import numpy as np
    from mxtpu.module import BucketingModule

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        out = mx.sym.RNN(data, parameters=mx.sym.Variable("rnn_p"),
                         state=mx.sym.Variable("rnn_h"),
                         state_size=8, num_layers=1, mode="rnn_tanh",
                         name="rnn")
        out = mx.sym.SequenceLast(out)
        out = mx.sym.FullyConnected(out, weight=mx.sym.Variable("fcw"),
                                    bias=mx.sym.Variable("fcb"),
                                    num_hidden=4, name="fc")
        out = mx.sym.SoftmaxOutput(out, label, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = BucketingModule(sym_gen, default_bucket_key=10)
    from mxtpu.io import DataDesc
    mod.bind(data_shapes=[DataDesc("data", (10, 2, 6))],
             label_shapes=[DataDesc("softmax_label", (2,))])
    mod.init_params()
    # switch to a bucket that was never bound: backward fill must kick in
    mod.switch_bucket(4, [DataDesc("data", (4, 2, 6))],
                      [DataDesc("softmax_label", (2,))])
    batch = np.random.uniform(-1, 1, (4, 2, 6)).astype(np.float32)
    from mxtpu.io import DataBatch
    mod.forward(DataBatch(data=[mx.nd.array(batch)],
                          label=[mx.nd.zeros((2,))],
                          bucket_key=4), is_train=False)
    out = mod.get_outputs()[0].asnumpy()
    assert out.shape == (2, 4)


def test_infer_shape_backward_fill_conv_nhwc():
    """Channels-last layout fills an HWIO weight (mirrors _conv_dims)."""
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, weight=mx.sym.Variable("w"),
                             kernel=(3, 3), num_filter=8, layout="NHWC",
                             no_bias=True)
    arg_shapes, out_shapes, _ = net.infer_shape(data=(2, 8, 8, 4))
    shapes = dict(zip(net.list_arguments(), arg_shapes))
    assert shapes["w"] == (3, 3, 4, 8)
    assert out_shapes == [(2, 6, 6, 8)]


def test_auto_created_param_variables():
    """Reference parity (symbol/register.py codegen + nnvm ListInputNames):
    sym ops auto-create their parameter Variables when not supplied —
    Convolution makes <name>_weight/_bias, BatchNorm adds gamma/beta args
    and moving_mean/var aux, output ops make <name>_label."""
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=4, kernel=(3, 3), pad=(1, 1),
                             name="c1")
    net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=3, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    assert net.list_arguments() == [
        "data", "c1_weight", "c1_bias", "bn1_gamma", "bn1_beta",
        "fc_weight", "fc_bias", "softmax_label"]
    assert net.list_auxiliary_states() == ["bn1_moving_mean",
                                           "bn1_moving_var"]
    # no_bias suppresses the bias variable
    nb = mx.sym.Convolution(data, num_filter=4, kernel=(1, 1), no_bias=True,
                            name="c2")
    assert nb.list_arguments() == ["data", "c2_weight"]
    # explicitly supplied params are NOT duplicated
    w = mx.sym.Variable("myw")
    ex = mx.sym.FullyConnected(data, weight=w, num_hidden=3, name="fc2")
    args = ex.list_arguments()
    assert "myw" in args and "fc2_weight" not in args
