"""Measured Pallas block-shape autotuner (mxtpu/ops/pallas/autotune.py,
ISSUE 17): declared plan spaces with pre-compile feasibility pruning,
measured search with warmup-discarded median timing, persistent plan
artifacts under MXTPU_COMPILE_CACHE_DIR with the full degradation
matrix (every bad blob lands on the hand-picked default with a counted
``autotune.drops{reason}``), zero warm-start searches in a fresh
process (subprocess-pinned), plan identity riding registry.policy_key,
and interpret-mode numerical parity of EVERY candidate plan the search
may emit for both registered kernels."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from mxtpu import telemetry
from mxtpu.ops import registry
from mxtpu.ops.pallas import autotune
from mxtpu.ops.pallas import conv as pc
# the package __init__ re-exports the flash_attention FUNCTION, which
# shadows the submodule name — import the module explicitly
import importlib
fa = importlib.import_module("mxtpu.ops.pallas.flash_attention")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DN = ("NHWC", "HWIO", "NHWC")


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in ("MXTPU_AUTOTUNE", "MXTPU_AUTOTUNE_ROUNDS",
                "MXTPU_AUTOTUNE_BUDGET_S", "MXTPU_COMPILE_CACHE_DIR",
                "MXTPU_PALLAS_CONV", "MXTPU_PALLAS_CONV_INTERPRET",
                "MXTPU_FLASH_INTERPRET"):
        monkeypatch.delenv(var, raising=False)
    autotune.reset()
    yield
    autotune.reset()


def _ctr(name, tag=None):
    return telemetry.value(name, tag=tag)


def _conv_sc(n=1, h=8, cin=4, cout=8, k=3, s=1, p=1, dtype="float32",
             scale=0, res=0):
    return {"n": n, "h": h, "w": h, "cin": cin, "kh": k, "kw": k,
            "cout": cout, "sh": s, "sw": s, "p0": p, "p1": p,
            "q0": p, "q1": p, "dtype": dtype, "scale": scale, "res": res}


# ------------------------------------------------------- registry & spaces
def test_both_kernels_registered_with_full_descriptors():
    ks = autotune.kernels()
    assert {"pallas_conv", "pallas_flash"} <= set(ks)
    for tk in ks.values():
        sc = tk.classes(True)[0]
        default = tk.default(sc)
        ok, reason = tk.feasible(default, sc)
        assert ok, (tk.kernel_id, reason)   # the default is always feasible
        assert any(tk.space(sc)), tk.kernel_id


def test_conv_feasibility_prunes_nondivisor_and_vmem_overflow():
    sc = _conv_sc(h=8)                       # oh = 8
    ok, reason = pc._tune_feasible({"bo": 3}, sc)
    assert not ok and "divisor" in reason
    big = _conv_sc(h=256, cin=128, cout=256, k=3, s=1, p=1)
    ok, reason = pc._tune_feasible({"bo": 256}, big)
    assert not ok and "VMEM" in reason


def test_flash_feasibility_enforces_granules_and_vmem():
    sc = {"b": 1, "h": 2, "t": 256, "tk": 256, "d": 64,
          "dtype": "float32"}
    ok, reason = fa._tune_feasible({"block_q": 100, "block_k": 128}, sc)
    assert not ok and "block_q" in reason
    ok, reason = fa._tune_feasible({"block_q": 128, "block_k": 100}, sc)
    assert not ok and "block_k" in reason
    wide = {"b": 1, "h": 2, "t": 2048, "tk": 2048, "d": 1024,
            "dtype": "float32"}
    ok, reason = fa._tune_feasible({"block_q": 2048, "block_k": 2048},
                                   wide)
    assert not ok and "VMEM" in reason


def test_space_candidates_are_always_feasible_for_declared_classes():
    """The space is declared REALIZED (granule-snapped divisors), so a
    candidate the search would time can never be one feasibility (or
    worse, Mosaic) rejects."""
    for tk in autotune.kernels().values():
        for sc in tk.classes(True):
            for plan in tk.space(sc):
                ok, reason = tk.feasible(plan, sc)
                assert ok, (tk.kernel_id, plan, reason)


# -------------------------------------------------------------- key material
def test_class_token_is_order_independent_and_plan_id_stable():
    sc = _conv_sc()
    assert autotune.class_token(sc) == autotune.class_token(
        dict(reversed(list(sc.items()))))
    assert autotune.plan_id_of({"block_q": 256, "block_k": 128}) == \
        "block_k=128,block_q=256"


def test_forced_stack_wins_and_unwinds():
    with autotune.forced("pallas_conv", {"bo": 4}):
        assert autotune.lookup("pallas_conv", _conv_sc()) == {"bo": 4}
        with autotune.forced("pallas_conv", {"bo": 2}):
            assert autotune.lookup("pallas_conv",
                                   _conv_sc()) == {"bo": 2}
        assert autotune.lookup("pallas_conv", _conv_sc()) == {"bo": 4}
    assert autotune.lookup("pallas_conv", _conv_sc()) is None


def test_disabled_is_inert(monkeypatch, tmp_path):
    """MXTPU_AUTOTUNE unset: installs are invisible to lookup, the
    policy token is the constant "0", and ensure_loaded never scans."""
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    autotune.save_plan("pallas_conv", _conv_sc(), {"bo": 2})
    autotune.install_plan("pallas_conv", _conv_sc(), {"bo": 2})
    assert autotune.lookup("pallas_conv", _conv_sc()) is None
    assert autotune.policy_token() == "0"


# ---------------------------------------------------- search + persistence
def test_search_prunes_times_and_persists_only_improvements(
        monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    s0 = _ctr("autotune.searches")
    sc = _conv_sc(n=1, h=8, cin=4, cout=8)
    res = autotune.search("pallas_conv", sc, rounds=1, budget_s=60)
    assert _ctr("autotune.searches") == s0 + 1
    assert res["timed"] >= 1
    assert res["default_plan_id"] == autotune.plan_id_of(
        pc._tune_default(sc))
    assert res["timings"][0]["plan_id"] == res["default_plan_id"]
    ids = [t["plan_id"] for t in res["timings"]]
    assert len(ids) == len(set(ids))         # dedup by plan identity
    if res["improved"]:
        assert res["best_s"] < res["default_s"]
        assert res["persisted"] and os.path.exists(res["persisted"])
        assert autotune.installed()
    else:
        assert res["persisted"] is None
        assert not autotune.installed()      # ties keep the default


def test_search_budget_stops_with_best_so_far(monkeypatch):
    res = autotune.search("pallas_conv", _conv_sc(n=1, h=8),
                          rounds=1, budget_s=0.0, install=False,
                          persist=False)
    # deadline already passed: the default still timed, sweep cut short
    assert res["timed"] >= 1
    assert res["budget_exhausted"] or res["candidates"] == res["timed"]


def test_persisted_plan_roundtrip_serves_from_disk(monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    sc = _conv_sc(h=8)
    path = autotune.save_plan("pallas_conv", sc, {"bo": 2},
                              meta={"speedup": 1.5})
    assert path and os.path.basename(path).startswith("plan_")
    rec = json.load(open(path, encoding="utf-8"))
    assert rec["magic"] == "MXTPU-AT"
    assert rec["env"]["format"] == autotune.FORMAT_VERSION
    assert rec["key"].startswith("pallas_conv|")
    autotune.reset()                          # "fresh process"
    monkeypatch.setenv("MXTPU_AUTOTUNE", "1")
    h0 = _ctr("autotune.plan_hits", tag="disk")
    assert autotune.lookup("pallas_conv", sc) == {"bo": 2}
    assert _ctr("autotune.plan_hits", tag="disk") == h0 + 1
    pid, prov = autotune.active_plan("pallas_conv", sc)
    assert (pid, prov) == ("bo=2", "tuned")
    # an unknown class misses and the gauge resets to default
    m0 = _ctr("autotune.plan_misses")
    assert autotune.lookup("pallas_conv", _conv_sc(h=16)) is None
    assert _ctr("autotune.plan_misses") == m0 + 1


def test_active_plan_reports_default_provenance_for_default_plan(
        monkeypatch):
    monkeypatch.setenv("MXTPU_AUTOTUNE", "1")
    sc = _conv_sc(h=8)
    autotune.install_plan("pallas_conv", sc, pc._tune_default(sc))
    pid, prov = autotune.active_plan("pallas_conv", sc)
    assert prov == "default" and pid is not None


# --------------------------------------------------------- degradation matrix
def _plant(tmp_path, sc, plan, kernel="pallas_conv"):
    path = autotune.save_plan(kernel, sc, plan, root=str(tmp_path))
    assert path
    return path


def _serve(monkeypatch, tmp_path):
    autotune.reset()
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_AUTOTUNE", "1")


def test_truncated_blob_drops_corrupt(monkeypatch, tmp_path):
    sc = _conv_sc(h=8)
    path = _plant(tmp_path, sc, {"bo": 2})
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) // 3])
    _serve(monkeypatch, tmp_path)
    d0 = _ctr("autotune.drops", tag="corrupt")
    assert autotune.lookup("pallas_conv", sc) is None   # default, no crash
    assert _ctr("autotune.drops", tag="corrupt") == d0 + 1


def test_garbage_blob_drops_corrupt(monkeypatch, tmp_path):
    sc = _conv_sc(h=8)
    path = _plant(tmp_path, sc, {"bo": 2})
    with open(path, "wb") as f:
        f.write(b"not json at all \x00\xff")
    _serve(monkeypatch, tmp_path)
    d0 = _ctr("autotune.drops", tag="corrupt")
    assert autotune.lookup("pallas_conv", sc) is None
    assert _ctr("autotune.drops", tag="corrupt") == d0 + 1


def test_ill_typed_plan_drops_corrupt(monkeypatch, tmp_path):
    sc = _conv_sc(h=8)
    path = _plant(tmp_path, sc, {"bo": 2})
    rec = json.load(open(path, encoding="utf-8"))
    rec["plan"] = [2]                         # not a dict
    json.dump(rec, open(path, "w", encoding="utf-8"))
    _serve(monkeypatch, tmp_path)
    d0 = _ctr("autotune.drops", tag="corrupt")
    assert autotune.lookup("pallas_conv", sc) is None
    assert _ctr("autotune.drops", tag="corrupt") == d0 + 1


def test_format_or_device_skew_drops_version_mismatch(monkeypatch,
                                                      tmp_path):
    sc = _conv_sc(h=8)
    path = _plant(tmp_path, sc, {"bo": 2})
    rec = json.load(open(path, encoding="utf-8"))
    rec["env"] = {"format": autotune.FORMAT_VERSION + 1,
                  "device": rec["env"]["device"]}
    json.dump(rec, open(path, "w", encoding="utf-8"))
    _serve(monkeypatch, tmp_path)
    d0 = _ctr("autotune.drops", tag="version_mismatch")
    assert autotune.lookup("pallas_conv", sc) is None
    assert _ctr("autotune.drops", tag="version_mismatch") == d0 + 1


def test_foreign_device_blob_drops_version_mismatch(monkeypatch,
                                                    tmp_path):
    sc = _conv_sc(h=8)
    path = _plant(tmp_path, sc, {"bo": 2})
    rec = json.load(open(path, encoding="utf-8"))
    rec["env"] = {"format": autotune.FORMAT_VERSION,
                  "device": "tpu/TPU v9"}
    json.dump(rec, open(path, "w", encoding="utf-8"))
    _serve(monkeypatch, tmp_path)
    d0 = _ctr("autotune.drops", tag="version_mismatch")
    assert autotune.lookup("pallas_conv", sc) is None
    assert _ctr("autotune.drops", tag="version_mismatch") == d0 + 1


def test_forged_rename_drops_key_mismatch(monkeypatch, tmp_path):
    """A blob renamed onto ANOTHER class's digest is refused by the
    in-blob key check — geometry tuned for one shape class can never be
    served to a different one."""
    sc_a, sc_b = _conv_sc(h=8), _conv_sc(h=16)
    path_a = _plant(tmp_path, sc_a, {"bo": 2})
    path_b = autotune.plan_path("pallas_conv", sc_b, root=str(tmp_path))
    os.replace(path_a, path_b)
    _serve(monkeypatch, tmp_path)
    d0 = _ctr("autotune.drops", tag="key_mismatch")
    assert autotune.lookup("pallas_conv", sc_b) is None
    assert _ctr("autotune.drops", tag="key_mismatch") == d0 + 1


def test_io_failure_counts_and_returns_none(monkeypatch, tmp_path):
    target = tmp_path / "not_a_dir"
    target.write_text("file blocks the mkdir")
    d0 = _ctr("autotune.drops", tag="io")
    assert autotune.save_plan("pallas_conv", _conv_sc(h=8), {"bo": 2},
                              root=str(target / "x")) is None
    assert _ctr("autotune.drops", tag="io") == d0 + 1


def test_infeasible_served_plan_degrades_at_consult(monkeypatch,
                                                    tmp_path, ):
    """A plan that passes the blob checks but fails the kernel's OWN
    revalidation (bo no longer divides oh) degrades to the default at
    _resolve with autotune.drops{infeasible} — never a Mosaic error."""
    monkeypatch.setenv("MXTPU_PALLAS_CONV_INTERPRET", "1")
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1, 8, 8, 4), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 4, 8) * 0.1, jnp.float32)
    with autotune.forced("pallas_conv", {"bo": 3}):   # oh=8, 8 % 3 != 0
        d0 = _ctr("autotune.drops", tag="infeasible")
        out = pc.fused_conv(x, w, (1, 1), ((1, 1), (1, 1)))
        assert _ctr("autotune.drops", tag="infeasible") >= d0 + 1
    ref = lax.conv_general_dilated(x, w, (1, 1), ((1, 1), (1, 1)),
                                   dimension_numbers=DN)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


# ------------------------------------------------------ policy-key identity
def test_policy_token_flips_on_install_and_registry_carries_it(
        monkeypatch):
    monkeypatch.setenv("MXTPU_AUTOTUNE", "1")
    key_off = registry.policy_key()
    t0 = autotune.policy_token()
    assert t0 == "0"                          # empty table
    autotune.install_plan("pallas_conv", _conv_sc(h=8), {"bo": 2})
    t1 = autotune.policy_token()
    assert t1 not in ("0", t0)
    key_on = registry.policy_key()
    assert key_off != key_on                  # the digest rides the key
    assert t1 in key_on
    autotune.install_plan("pallas_conv", _conv_sc(h=8), {"bo": 4})
    assert autotune.policy_token() != t1      # plan flip -> new digest
    # stability: same installed set, same token
    assert autotune.policy_token() == autotune.policy_token()


def test_warmup_preloads_plan_table(monkeypatch, tmp_path):
    from mxtpu import compile_service as csvc
    monkeypatch.setenv("MXTPU_COMPILE_CACHE_DIR", str(tmp_path))
    autotune.save_plan("pallas_conv", _conv_sc(h=8), {"bo": 2})
    autotune.reset()
    monkeypatch.setenv("MXTPU_AUTOTUNE", "1")
    csvc.warmup([])                           # fleet warmup path
    assert autotune.installed(), "warmup must preload plan artifacts"


# --------------------------------------------- zero warm-start (subprocess)
_CHILD = r"""
import json, os, sys
import numpy as np
import jax.numpy as jnp
from mxtpu import telemetry
from mxtpu.ops.pallas import autotune
from mxtpu.ops.pallas import conv as pc

sc = json.loads(os.environ["AT_TEST_CLASS"])
rng = np.random.RandomState(0)
x = jnp.asarray(rng.randn(sc["n"], sc["h"], sc["w"], sc["cin"]),
                jnp.float32)
w = jnp.asarray(rng.randn(sc["kh"], sc["kw"], sc["cin"], sc["cout"]) * 0.1,
                jnp.float32)
out = pc.fused_conv(x, w, (sc["sh"], sc["sw"]),
                    ((sc["p0"], sc["p1"]), (sc["q0"], sc["q1"])))
print("AT_CHILD " + json.dumps({
    "searches": telemetry.value("autotune.searches"),
    "hits_disk": telemetry.value("autotune.plan_hits", tag="disk"),
    "drops": telemetry.tagged("autotune.drops"),
    "plan": autotune.lookup("pallas_conv", sc),
    "pallas_dispatches": pc.DISPATCH_STATS["pallas"],
    "checksum": float(np.asarray(out).sum()),
}))
"""


def test_fresh_process_serves_tuned_plans_zero_searches(tmp_path):
    """ISSUE-17 acceptance: a fresh process against a warm plan dir
    serves the tuned plan with ZERO measured searches (and zero search
    probes compiled — the searches counter is the probe account), zero
    drops, and the identical kernel output."""
    sc = _conv_sc(n=1, h=8, cin=4, cout=8)
    autotune.save_plan("pallas_conv", sc, {"bo": 2}, root=str(tmp_path))

    def run():
        env = dict(os.environ, PYTHONPATH=REPO,
                   MXTPU_COMPILE_CACHE_DIR=str(tmp_path),
                   MXTPU_AUTOTUNE="1",
                   MXTPU_PALLAS_CONV_INTERPRET="1",
                   AT_TEST_CLASS=json.dumps(sc))
        proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                              cwd=REPO, capture_output=True, text=True,
                              timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = [ln for ln in proc.stdout.splitlines()
                if ln.startswith("AT_CHILD ")][0]
        return json.loads(line[len("AT_CHILD "):])

    a, b = run(), run()
    for r in (a, b):
        assert r["searches"] == 0, r          # zero warm-start searches
        assert r["hits_disk"] >= 1, r
        assert r["drops"] in ({}, None), r
        assert r["plan"] == {"bo": 2}, r
        assert r["pallas_dispatches"] >= 1, r  # tuned geometry really ran
    assert a["checksum"] == b["checksum"]      # deterministic serving


# ------------------------------------------- candidate-plan interpret parity
def _conv_candidates(sc):
    tk = autotune.kernels()["pallas_conv"]
    plans, seen = [], set()
    for plan in [tk.default(sc)] + list(tk.space(sc)):
        pid = autotune.plan_id_of(plan)
        if pid in seen or not tk.feasible(plan, sc)[0]:
            continue
        seen.add(pid)
        plans.append(plan)
    return plans


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize("h,k,s,p", [(25, 3, 1, 1),   # odd spatial
                                     (47, 3, 2, 1)])  # odd + stride 2
def test_every_conv_candidate_plan_matches_xla(monkeypatch, dtype,
                                               h, k, s, p):
    """Every plan the search may emit for odd/stride-2 classes runs the
    REAL kernel (interpreter) to the XLA reference — a winning plan is
    a fast plan, never a differently-answering one."""
    monkeypatch.setenv("MXTPU_PALLAS_CONV_INTERPRET", "1")
    dt = jnp.dtype(dtype)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1, h, h, 4), dt)
    w = jnp.asarray(rng.randn(k, k, 4, 8) * 0.1, dt)
    pad = ((p, p), (p, p))
    ref = lax.conv_general_dilated(
        x.astype(jnp.float32), w.astype(jnp.float32), (s, s), pad,
        dimension_numbers=DN)
    sc = _conv_sc(n=1, h=h, cin=4, cout=8, k=k, s=s, p=p, dtype=dtype)
    plans = _conv_candidates(sc)
    assert len(plans) >= 2                    # a real space, not a point
    tol = dict(rtol=1e-5, atol=1e-5) if dtype == "float32" \
        else dict(rtol=2e-2, atol=2e-2)
    for plan in plans:
        with autotune.forced("pallas_conv", plan):
            out = pc.fused_conv(x, w, (s, s), pad)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), **tol)


def _flash_candidates(sc):
    tk = autotune.kernels()["pallas_flash"]
    plans, seen = [], set()
    for plan in [tk.default(sc)] + list(tk.space(sc)):
        pid = autotune.plan_id_of(plan)
        if pid in seen or not tk.feasible(plan, sc)[0]:
            continue
        seen.add(pid)
        plans.append(plan)
    return plans


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_every_flash_candidate_plan_matches_xla(monkeypatch, dtype):
    monkeypatch.setenv("MXTPU_FLASH_INTERPRET", "1")
    dt = jnp.dtype(dtype)
    rng = np.random.RandomState(0)
    b, h, t, d = 1, 2, 256, 64
    q = jnp.asarray(rng.randn(b, h, t, d), dt)
    k = jnp.asarray(rng.randn(b, h, t, d), dt)
    v = jnp.asarray(rng.randn(b, h, t, d), dt)
    scale = 1.0 / (d ** 0.5)
    ref = fa._xla_attention(q.astype(jnp.float32),
                            k.astype(jnp.float32),
                            v.astype(jnp.float32), False, scale)
    sc = fa.shape_class_of(q, k)
    plans = _flash_candidates(sc)
    assert len(plans) >= 2
    tol = dict(rtol=2e-5, atol=2e-5) if dtype == "float32" \
        else dict(rtol=3e-2, atol=3e-2)
    p0 = telemetry.value("pallas_flash.pallas")
    for plan in plans:
        with autotune.forced("pallas_flash", plan):
            out = fa.flash_attention(q, k, v, causal=False)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), **tol)
    assert telemetry.value("pallas_flash.pallas") >= p0 + len(plans)


# ----------------------------------------------- flash dispatch observability
def test_flash_dispatch_counters_mirror_conv(monkeypatch):
    fa.reset_dispatch_stats()
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 1, 16, 8), jnp.float32)
    # off-TPU without the interpreter: counted reason-tagged fallback
    out = fa.flash_attention(q, q, q)
    assert fa.DISPATCH_STATS["xla"] >= 1
    assert fa.DISPATCH_STATS["fallback_reasons"].get(
        "platform is not tpu", 0) >= 1
    assert fa.DISPATCH_STATS["pallas"] == 0
    # the interpreter path counts as a pallas dispatch
    monkeypatch.setenv("MXTPU_FLASH_INTERPRET", "1")
    q2 = jnp.asarray(rng.randn(1, 1, 128, 64), jnp.float32)
    fa.flash_attention(q2, q2, q2)
    assert fa.DISPATCH_STATS["pallas"] >= 1
    assert out.shape == q.shape


# ------------------------------------------------------------ bench A/B
def test_bench_autotune_ab_record_schema(monkeypatch):
    """bench._autotune_ab (the conv_class/flash_class tuned-vs-default
    lines) must emit the x_vs_default schema with the not-worse gate and
    must NOT install into the serving table. A single-candidate class
    (oh*ow <= 256 collapses the target-M ladder) keeps it cheap: the
    search times exactly the default."""
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    monkeypatch.setenv("MXTPU_PALLAS_CONV_INTERPRET", "1")
    monkeypatch.setenv("MXTPU_AUTOTUNE_ROUNDS", "1")
    lines = []
    rec = bench._autotune_ab(lines.append, autotune, "pallas_conv",
                             "conv_tiny", _conv_sc(h=8), host_tier=True)
    assert lines == [rec] and "error" not in rec
    assert rec["unit"] == "x_vs_default"
    assert rec["impl"] == "autotune_ab"
    assert rec["default_plan"] == rec["best_plan"]   # only candidate
    assert rec["not_worse"] and not rec["improved"]
    assert rec["timed"] == 1 and rec["candidates"] == 1
    assert rec["value"] == pytest.approx(1.0)
    assert autotune.installed() == {}                # install=False held


# --------------------------------------------------- ledger -> tuning queue
def test_tuning_queue_emitter_ranks_by_executed_flops(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import telemetry_report as tr
    finally:
        sys.path.pop(0)
    cands = [
        {"site": "trainer.step", "seq": 1, "shapes": ["f32[8,224,224,3]"],
         "intensity": 4.0, "verdict": "memory", "calls": 100,
         "flops": 2e9},
        {"site": "serving.predict", "seq": 2, "shapes": None,
         "intensity": 9.0, "verdict": "memory", "calls": 10,
         "flops": 1e9},
    ]
    q = tr.tuning_queue([], cands)
    assert q["format"] == 1
    assert [e["site"] for e in q["queue"]] == ["trainer.step",
                                               "serving.predict"]
    assert q["queue"][0]["executed_gflops"] == pytest.approx(200.0)
    assert q["queue"][0]["shapes"] == ["f32[8,224,224,3]"]
    # the CLI consumes it: queue-ranked kernel ordering
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import autotune_session as ats
    finally:
        sys.path.pop(0)
    order = ats._kernel_order(
        [{"site": "transformer.attention"}, {"site": "resnet.conv"}],
        {"pallas_conv": None, "pallas_flash": None})
    assert order == ["pallas_flash", "pallas_conv"]
