"""DLPack interchange (ref: python/mxnet/ndarray/ndarray.py:3925
to_dlpack_for_read/to_dlpack_for_write/from_dlpack; dlpack tests in
tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxtpu as mx


def test_capsule_round_trip():
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = mx.nd.from_dlpack(mx.nd.to_dlpack_for_read(x))
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy())
    y2 = mx.nd.from_dlpack(x.to_dlpack_for_write())
    np.testing.assert_allclose(y2.asnumpy(), x.asnumpy())


def test_for_write_is_a_loud_host_copy(monkeypatch):
    """XLA buffers are immutable: the write variant delivers a host copy
    and warns on EVERY call (ADVICE r5: the warn-once behavior silently
    lost writes after filters ate the first warning).
    MXTPU_DLPACK_WRITE_COPY=1 is the explicit opt-in that silences it."""
    import warnings
    monkeypatch.delenv("MXTPU_DLPACK_WRITE_COPY", raising=False)
    x = mx.nd.array(np.zeros(3, np.float32))
    with pytest.warns(UserWarning, match="do not propagate"):
        cap = x.to_dlpack_for_write()
    with pytest.warns(UserWarning, match="do not propagate"):
        x.to_dlpack_for_write()  # ...and again on the next call
    monkeypatch.setenv("MXTPU_DLPACK_WRITE_COPY", "1")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        x.to_dlpack_for_write()  # acknowledged: silent
    monkeypatch.delenv("MXTPU_DLPACK_WRITE_COPY")
    torch = pytest.importorskip("torch")
    t = torch.utils.dlpack.from_dlpack(cap)
    t.add_(5.0)  # writes land in the copy...
    np.testing.assert_allclose(x.asnumpy(), 0.0)  # ...never in x


def test_versioned_capsule_is_a_named_error():
    """A DLPack-1.0 'dltensor_versioned' capsule must raise a clear
    MXNetError naming the versioned-capsule case, not an obscure jax
    failure (ADVICE r5)."""
    import ctypes
    from mxtpu.base import MXNetError
    new = ctypes.pythonapi.PyCapsule_New
    new.restype = ctypes.py_object
    new.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p]
    cap = new(ctypes.c_void_p(1), b"dltensor_versioned", None)
    with pytest.raises(MXNetError, match="dltensor_versioned"):
        mx.nd.from_dlpack(cap)


def test_torch_both_directions():
    torch = pytest.importorskip("torch")
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    t = torch.utils.dlpack.from_dlpack(mx.nd.to_dlpack_for_read(x))
    assert tuple(t.shape) == (2, 3) and float(t.sum()) == 15.0
    z = mx.nd.from_dlpack(torch.utils.dlpack.to_dlpack(torch.arange(4.0)))
    np.testing.assert_allclose(z.asnumpy(), [0, 1, 2, 3])
    # modern object protocol too (no capsule in user code)
    z2 = mx.nd.from_dlpack(torch.full((2,), 7.0))
    np.testing.assert_allclose(z2.asnumpy(), 7.0)


def test_from_numpy():
    w = mx.nd.from_numpy(np.ones((2, 2), np.float32))
    assert w.shape == (2, 2)
    with pytest.raises(mx.base.MXNetError):
        mx.nd.from_numpy(np.ones((4, 4), np.float32).T)  # non-contiguous


def test_int_dtype_round_trip():
    x = mx.nd.array(np.arange(4), dtype="int32")
    y = mx.nd.from_dlpack(mx.nd.to_dlpack_for_read(x))
    assert str(y.dtype) == "int32"
    np.testing.assert_array_equal(y.asnumpy(), [0, 1, 2, 3])
