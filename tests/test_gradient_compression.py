"""2-bit gradient compression (ref: src/kvstore/gradient_compression.h;
test model tests/python/unittest/test_kvstore.py compressed paths)."""
import numpy as np
import pytest

from mxtpu.base import MXNetError
from mxtpu.gradient_compression import GradientCompression


def test_quantize_semantics():
    gc = GradientCompression(threshold=0.5)
    g = np.array([0.6, -0.7, 0.2, -0.2, 0.0], "float32")
    packed, n = gc.quantize("k", g)
    out = gc.dequantize(packed, n, g.shape)
    np.testing.assert_allclose(out, [0.5, -0.5, 0.0, 0.0, 0.0])
    # residual keeps the quantization error
    np.testing.assert_allclose(gc._residuals["k"],
                               [0.1, -0.2, 0.2, -0.2, 0.0], atol=1e-6)


def test_error_feedback_accumulates():
    """Sub-threshold gradients eventually fire thanks to the residual —
    over many steps the sent total tracks the true total."""
    gc = GradientCompression(threshold=0.5)
    g = np.full((3,), 0.2, "float32")
    sent = np.zeros(3)
    for _ in range(10):
        packed, n = gc.quantize("k", g)
        sent += gc.dequantize(packed, n, g.shape)
    np.testing.assert_allclose(sent, 2.0, atol=0.5)  # true total = 10*0.2


def test_packing_roundtrip_shapes():
    gc = GradientCompression(threshold=1.0)
    rng = np.random.RandomState(0)
    for shape in [(1,), (4,), (5,), (3, 7), (2, 3, 5)]:
        g = rng.uniform(-3, 3, shape).astype("float32")
        packed, n = gc.quantize(str(shape), g)
        assert packed.dtype == np.uint8 and packed.size == -(-n // 4)
        out = gc.dequantize(packed, n, shape)
        assert out.shape == shape
        assert set(np.unique(out)).issubset({-1.0, 0.0, 1.0})


def test_rejects_bad_params():
    with pytest.raises(MXNetError):
        GradientCompression(type="1bit")
    with pytest.raises(MXNetError):
        GradientCompression(threshold=-1)


def test_kvstore_accepts_compression_params():
    import mxtpu as mx
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    assert kv._compression.threshold == 0.5
