"""The round-5 measured-best defaults, pinned (PERF.md lever table):
BN one-pass ON (+7.8% end-to-end), conv_acc custom-vjp OFF (-2.8%),
flash head-dim padding ON (+8.9% BERT), RNN hoist ON, staged levers
(im2col, ring-flash) OFF until their on-chip A/B. A default drifting
here silently changes every user's performance — this test makes that
a visible decision, not an accident."""
import os

import pytest


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in ("MXTPU_CONV_ACC", "MXTPU_BN_ONEPASS", "MXTPU_RING_FLASH",
                "MXTPU_FLASH_PAD_D", "MXTPU_CONV_IM2COL",
                "MXTPU_RNN_HOIST", "BENCH_S2D_STEM", "BENCH_LAYOUT"):
        monkeypatch.delenv(var, raising=False)


def test_policy_key_defaults_are_the_measured_best():
    from mxtpu.ops.registry import policy_key
    # (conv_acc, bn_onepass, ring_flash, flash_pad_d, im2col, rnn_hoist)
    assert policy_key() == ("0", "1", "0", "1", "0", "1")


def test_read_sites_mirror_policy_key():
    from mxtpu.ops.conv_acc import _enabled, _im2col_enabled
    from mxtpu.ops.nn import _bn_onepass
    from mxtpu.ops.rnn_ops import _hoist_enabled
    assert _enabled() is False          # conv_acc: measured regression
    assert _bn_onepass() is True        # measured +7.8%
    assert _im2col_enabled() is False   # staged, awaiting on-chip A/B
    assert _hoist_enabled() is True


def test_bench_defaults_measure_the_best_config(monkeypatch):
    """A plain `python bench.py` resnet run must measure the best-known
    config: the s2d stem defaults ON for NHWC (and off elsewhere —
    the transform requires NHWC), overridable by BENCH_S2D_STEM."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench
    assert bench._default_s2d("NHWC") == "1"
    assert bench._default_s2d("NCHW") == "0"
    monkeypatch.setenv("BENCH_S2D_STEM", "0")
    assert bench._default_s2d("NHWC") == "0"
