"""The round-5 measured-best defaults, pinned (PERF.md lever table):
BN one-pass ON (+7.8% end-to-end), conv_acc custom-vjp OFF (-2.8%),
flash head-dim padding ON (+8.9% BERT), RNN hoist ON, staged levers
(im2col, ring-flash) OFF until their on-chip A/B. A default drifting
here silently changes every user's performance — this test makes that
a visible decision, not an accident."""
import os

import pytest


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in ("MXTPU_CONV_ACC", "MXTPU_BN_ONEPASS", "MXTPU_RING_FLASH",
                "MXTPU_FLASH_PAD_D", "MXTPU_CONV_IM2COL",
                "MXTPU_RNN_HOIST", "BENCH_S2D_STEM", "BENCH_LAYOUT",
                "MXTPU_FUSED_OPTIMIZER", "MXTPU_PALLAS_CONV",
                "MXTPU_PALLAS_CONV_INTERPRET", "MXTPU_S2D_STEM",
                "MXTPU_NUMERICS_GUARD", "MXTPU_LOSS_SCALE",
                "MXTPU_FAULT_INJECT", "MXTPU_CKPT_RETRIES",
                "MXTPU_DIVERGENCE_EVERY", "MXTPU_TRAIN_STEP_TIMEOUT_X",
                "MXTPU_POISON_STREAK", "MXTPU_CKPT_KEEP",
                "MXTPU_AUTOTUNE", "MXTPU_FLASH_INTERPRET"):
        monkeypatch.delenv(var, raising=False)


def test_policy_key_defaults_are_the_measured_best():
    from mxtpu.ops.pallas import autotune
    from mxtpu.ops.registry import policy_key
    autotune.reset()
    # (conv_acc, bn_onepass, ring_flash, flash_pad_d, im2col, rnn_hoist,
    #  pallas_conv, pallas_conv_interpret, s2d_stem, numerics_guard,
    #  divergence_every, autotune, flash_interpret, autotune_plans)
    assert policy_key() == ("0", "1", "0", "1", "0", "1", "0", "0", "0",
                            "0", "0", "0", "0", "0")


def test_read_sites_mirror_policy_key():
    from mxtpu.contrib.s2d_stem import stem_mode
    from mxtpu.ops.conv_acc import (_enabled, _im2col_enabled,
                                    _pallas_enabled)
    from mxtpu.ops.nn import _bn_onepass
    from mxtpu.ops.pallas.conv import _interpret
    from mxtpu.ops.rnn_ops import _hoist_enabled
    from mxtpu.resilience import guard_enabled
    assert _enabled() is False          # conv_acc: measured regression
    assert _bn_onepass() is True        # measured +7.8%
    assert _im2col_enabled() is False   # staged, awaiting on-chip A/B
    assert _hoist_enabled() is True
    assert _pallas_enabled() is False   # staged: resnet_pallas battery
    assert _interpret() is False        # test-only interpreter path
    assert stem_mode() == 0             # plain stem until measured
    # numerics sentinel OFF by default without a loss scaler: the guarded
    # jit is a different executable, so the default must be a decision
    # (guard_overhead bench tracks its <2% cost), not an accident
    assert guard_enabled() is False


def test_numerics_guard_and_loss_scale_defaults():
    """The resilience levers' env defaults, pinned like every other lever:
    guard off, initial loss scale 2**15, 3 checkpoint retries, no faults,
    and the ISSUE-14 survivability levers all opt-in (0 = off)."""
    import mxtpu.resilience as res
    assert res.guard_enabled() is False
    assert res.default_loss_scale() == 2.0 ** 15
    assert res.ckpt_retries() == 3
    assert res.DynamicLossScaler().config() == (2.0, 0.5, 2000, 2.0 ** 24,
                                                1.0)
    # survivability layer (ISSUE 14): every piece is opt-in — a default
    # flipping here changes the hot path (divergence bakes into the
    # update jit) or deletes checkpoints (keep), so it must be a decision
    assert res.divergence_every() == 0
    assert res.train_step_timeout_x() == 0.0
    assert res.poison_streak() == 0
    assert res.ckpt_keep() == 0


def test_guard_overhead_bench_emits_the_benchline_schema(monkeypatch):
    """bench.py's guard_overhead config must emit per-(config, guard) JSON
    lines plus a summary in the standard schema — the artifact the <2%
    sentinel-cost acceptance bound is read from."""
    import json
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench
    assert "guard_overhead" in bench.CONFIGS
    monkeypatch.setenv("BENCH_GUARD_PARAMS", "4")
    monkeypatch.setenv("BENCH_GUARD_PARAM_SIZE", "32")
    monkeypatch.setenv("BENCH_GUARD_STEPS", "2")
    monkeypatch.setenv("BENCH_GUARD_CONFIGS", "optimizer_step")
    lines = []
    rec = bench.bench_guard_overhead(
        emit=lambda r: lines.append(bench._stamp(r)))
    assert {"metric", "value", "unit", "vs_baseline", "mfu",
            "hfu"} <= set(rec)
    assert rec["metric"] == "guard_overhead"
    assert rec["unit"] == "overhead_frac"
    assert len(lines) == 2  # guard on + guard off for optimizer_step
    for l in lines:
        json.dumps(l)
        assert l["guard"] in ("on", "off")
        assert "platform" in l and "policy_key" in l
        assert l["value"] > 0 and l["unit"] == "steps/sec"
    # the A/B must restore the ambient defaults
    assert os.environ.get("MXTPU_NUMERICS_GUARD") is None


def test_fused_optimizer_is_the_measured_default():
    """The fused whole-model optimizer step (one donated jit per
    Trainer.step, mxtpu/optimizer_fused.py) is the measured default; the
    eager per-param loop is reachable only via MXTPU_FUSED_OPTIMIZER=0."""
    from mxtpu.optimizer_fused import FusedUpdater, fused_enabled
    from mxtpu import optimizer as opt
    assert fused_enabled() is True
    assert isinstance(opt.get_updater(opt.SGD()), FusedUpdater)


def test_optimizer_step_bench_emits_the_benchline_schema(monkeypatch):
    """bench.py's optimizer_step config must emit the same JSON-line schema
    the BENCH_r*.json harness parses ({metric, value, unit, vs_baseline,
    mfu, hfu}), with the fused/eager comparison riding as extra keys."""
    import json
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench
    assert "optimizer_step" in bench.CONFIGS
    monkeypatch.setenv("BENCH_OPT_PARAMS", "6")
    monkeypatch.setenv("BENCH_OPT_PARAM_SIZE", "32")
    monkeypatch.setenv("BENCH_OPT_STEPS", "2")
    rec = bench.bench_optimizer_step()
    assert {"metric", "value", "unit", "vs_baseline", "mfu",
            "hfu"} <= set(rec)
    assert rec["metric"].startswith("optimizer_step")
    assert rec["unit"] == "params_updated/sec"
    assert rec["fused_params_per_s"] == rec["value"]
    assert rec["eager_params_per_s"] > 0
    json.dumps(rec)  # one parseable JSON line
    # the measurement must restore the ambient default (fused on)
    assert os.environ.get("MXTPU_FUSED_OPTIMIZER") is None


def test_conv_class_bench_emits_per_class_lines(monkeypatch):
    """bench.py's conv_class config must emit one stamped JSON line per
    (conv class, impl) — at least 3 classes, XLA vs Pallas — plus a
    summary record in the standard schema. On the CPU tier the 'pallas'
    impl lines must SAY they fell back (impl_used), which is exactly the
    artifact-readability property the platform/policy stamp exists for."""
    import json
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench
    assert "conv_class" in bench.CONFIGS
    monkeypatch.setenv("BENCH_CONV_BATCH", "1")
    monkeypatch.setenv("BENCH_CONV_STEPS", "2")
    # autotune A/B off: the measured-search sweep (ISSUE 17) emits its own
    # x_vs_default lines and costs real search time — it has its own test
    # (test_autotune.py); this pin covers the per-class timing schema
    monkeypatch.setenv("BENCH_AUTOTUNE", "0")
    lines = []
    rec = bench.bench_conv_class(emit=lambda r: lines.append(bench._stamp(r)))
    assert {"metric", "value", "unit", "vs_baseline", "mfu", "hfu"} <= set(rec)
    assert rec["unit"] == "json_lines"
    classes = {l["metric"] for l in lines}
    assert len(classes) >= 3
    for l in lines:
        json.dumps(l)                      # parseable artifact lines
        # assert on ms, not the TFLOP/s rounding — a loaded CPU host can
        # legitimately land below the value's printable resolution
        assert l["unit"] == "TFLOP/s" and l["ms"] > 0 and l["value"] >= 0
        assert l["impl"] in ("xla", "pallas")
        assert "platform" in l and "policy_key" in l   # the round-7 stamp
        if l["impl"] == "pallas" and l["platform"] != "tpu":
            assert l["impl_used"].startswith("xla")    # honest fallback tag
    # the A/B must restore the ambient default (lever off)
    assert os.environ.get("MXTPU_PALLAS_CONV") is None


def test_bench_lines_are_stamped_with_platform_and_policy(monkeypatch):
    """Every bench.py JSON line carries the resolved platform + active
    lever set — wedge-skips and CPU fallbacks must be distinguishable
    from real TPU measurements in BENCH_r*.json after the fact."""
    import bench
    from mxtpu.ops.registry import policy_key
    rec = bench._stamp({"metric": "x"})
    assert rec["platform"] in ("cpu", "tpu", "unknown")
    assert rec["policy_key"] == list(policy_key())
    # pre-stamped records (the preflight probe knows its platform) win
    assert bench._stamp({"platform": "tpu"})["platform"] == "tpu"


def test_bench_defaults_measure_the_best_config(monkeypatch):
    """A plain `python bench.py` resnet run must measure the best-known
    config: the s2d stem defaults ON for NHWC (and off elsewhere —
    the transform requires NHWC), overridable by BENCH_S2D_STEM."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench
    assert bench._default_s2d("NHWC") == "1"
    assert bench._default_s2d("NCHW") == "0"
    monkeypatch.setenv("BENCH_S2D_STEM", "0")
    assert bench._default_s2d("NHWC") == "0"
