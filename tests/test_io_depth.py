"""IO iterator DEPTH tier (ref: tests/python/unittest/test_io.py):
NDArrayIter's three last-batch policies, shuffle correctness, the
DataBatch pad contract, dict/multi-input data, CSVIter parsing, and
PrefetchingIter equivalence.
"""
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu.base import MXNetError

RNG = np.random.RandomState


def _collect(it):
    batches = []
    for b in it:
        batches.append((b.data[0].asnumpy().copy(),
                        b.label[0].asnumpy().copy()
                        if b.label else None, b.pad))
    return batches


def test_ndarrayiter_exact_division():
    x = np.arange(12, dtype=np.float32).reshape(12, 1)
    y = np.arange(12, dtype=np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=4)
    bs = _collect(it)
    assert len(bs) == 3
    np.testing.assert_allclose(np.concatenate([b[0] for b in bs]), x)
    assert all(b[2] == 0 for b in bs)


def test_ndarrayiter_pad_policy():
    """pad: the tail batch is filled up to batch_size by wrapping, and
    DataBatch.pad reports how many samples are padding (ref: io.py
    NDArrayIter pad semantics)."""
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = mx.io.NDArrayIter(x, None, batch_size=4, last_batch_handle="pad")
    bs = _collect(it)
    assert len(bs) == 3
    assert [b[2] for b in bs] == [0, 0, 2]
    assert bs[2][0].shape == (4, 1)
    np.testing.assert_allclose(bs[2][0][:2], x[8:10])  # real tail samples
    np.testing.assert_allclose(bs[2][0][2:], x[0:2])   # wrap-pad, not zeros


def test_ndarrayiter_discard_policy():
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = mx.io.NDArrayIter(x, None, batch_size=4,
                           last_batch_handle="discard")
    bs = _collect(it)
    assert len(bs) == 2
    np.testing.assert_allclose(np.concatenate([b[0] for b in bs]), x[:8])


def test_ndarrayiter_roll_over_policy():
    """roll_over: the incomplete tail is NOT emitted this epoch; it
    leads the next epoch's stream (ref: io.py roll_over semantics)."""
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = mx.io.NDArrayIter(x, None, batch_size=4,
                           last_batch_handle="roll_over")
    e1 = _collect(it)
    assert len(e1) == 2                       # floor(10/4) full batches
    np.testing.assert_allclose(
        np.concatenate([b[0] for b in e1]), x[:8])
    it.reset()
    e2 = _collect(it)
    # epoch 2 = [8, 9] rolled over + the fresh epoch: 12 samples, 3 full
    assert len(e2) == 3
    np.testing.assert_allclose(e2[0][0][:2], x[8:10])
    np.testing.assert_allclose(e2[0][0][2:], x[0:2])
    # across both epochs nothing is lost or duplicated beyond the policy
    total = sum(b[0].shape[0] for b in e1 + e2)
    assert total == 20


def test_ndarrayiter_shuffle_is_permutation_and_aligned():
    mx.random.seed(0)
    x = np.arange(20, dtype=np.float32).reshape(20, 1)
    y = np.arange(20, dtype=np.float32) * 10
    it = mx.io.NDArrayIter(x, y, batch_size=5, shuffle=True)
    bs = _collect(it)
    xs = np.concatenate([b[0] for b in bs]).ravel()
    ys = np.concatenate([b[1] for b in bs]).ravel()
    assert sorted(xs.tolist()) == x.ravel().tolist()   # a permutation
    np.testing.assert_allclose(ys, xs * 10)            # labels track data
    it.reset()
    xs2 = np.concatenate([b[0] for b in _collect(it)]).ravel()
    assert sorted(xs2.tolist()) == x.ravel().tolist()


def test_ndarrayiter_dict_inputs_and_provide_data():
    x1 = np.zeros((8, 2), np.float32)
    x2 = np.ones((8, 3), np.float32)
    it = mx.io.NDArrayIter({"a": x1, "b": x2}, None, batch_size=4)
    descs = {d.name: d.shape for d in it.provide_data}
    assert descs == {"a": (4, 2), "b": (4, 3)}
    b = next(iter(it))
    assert len(b.data) == 2


def test_ndarrayiter_length_mismatch_raises():
    with pytest.raises(MXNetError):
        mx.io.NDArrayIter(np.zeros((8, 2), np.float32),
                          np.zeros((7,), np.float32), batch_size=4)


def test_csviter_values_and_shapes(tmp_path):
    data = RNG(0).uniform(-1, 1, (9, 4)).astype(np.float32)
    lbl = RNG(1).randint(0, 3, (9, 1)).astype(np.float32)
    dcsv = str(tmp_path / "d.csv")
    lcsv = str(tmp_path / "l.csv")
    np.savetxt(dcsv, data, delimiter=",", fmt="%.6f")
    np.savetxt(lcsv, lbl, delimiter=",", fmt="%.0f")
    it = mx.io.CSVIter(data_csv=dcsv, data_shape=(4,), label_csv=lcsv,
                       label_shape=(1,), batch_size=3)
    got_x, got_y = [], []
    for b in it:
        got_x.append(b.data[0].asnumpy())
        got_y.append(b.label[0].asnumpy())
    np.testing.assert_allclose(np.concatenate(got_x), data, rtol=1e-5)
    np.testing.assert_allclose(np.concatenate(got_y).ravel(), lbl.ravel())


def test_prefetching_iter_equivalence():
    x = np.arange(48, dtype=np.float32).reshape(24, 2)
    base = mx.io.NDArrayIter(x, None, batch_size=6)
    plain = [b.data[0].asnumpy().copy() for b in base]
    base.reset()
    pre = mx.io.PrefetchingIter(base)
    fetched = [b.data[0].asnumpy().copy() for b in pre]
    assert len(plain) == len(fetched)
    for p, f in zip(plain, fetched):
        np.testing.assert_allclose(p, f)


def test_iter_reset_mid_epoch():
    x = np.arange(16, dtype=np.float32).reshape(16, 1)
    it = mx.io.NDArrayIter(x, None, batch_size=4)
    next(iter(it))
    it.reset()
    bs = _collect(it)
    assert len(bs) == 4  # full epoch after reset


def test_roll_over_mid_epoch_reset_drops_planned_tail():
    """ADVICE r4: resetting before the epoch is consumed must not roll the
    previously PLANNED tail into the next epoch."""
    import numpy as np
    from mxtpu.io import NDArrayIter
    x = np.arange(10, dtype=np.float32)
    it = NDArrayIter(x, np.zeros(10, np.float32), batch_size=4,
                     last_batch_handle="roll_over")
    # epoch 1 fully consumed: 2 full batches, tail {8, 9} carries
    n = sum(1 for _ in it)
    assert n == 2
    it.reset()
    assert it.num_batches == 3  # 2 carried + 10 = 12 -> 3 full batches
    # abandon epoch 2 after ONE batch, reset: planned tail must be dropped
    next(iter(it))
    it.reset()
    assert it.num_batches == 2  # fresh 10 samples -> 2 full batches only


def test_roll_over_getpad_always_zero_documented():
    import numpy as np
    from mxtpu.io import NDArrayIter
    x = np.arange(10, dtype=np.float32)
    it = NDArrayIter(x, np.zeros(10, np.float32), batch_size=4,
                     last_batch_handle="roll_over")
    for _ in range(2):
        for batch in it:
            assert batch.pad == 0  # every roll_over batch is real samples
        it.reset()


def test_scalar_float_index_truncates():
    import numpy as np
    import mxtpu as mx
    a = mx.nd.array(np.arange(6, dtype=np.float32).reshape(3, 2))
    np.testing.assert_array_equal(a[1.0].asnumpy(), a[1].asnumpy())
    np.testing.assert_array_equal(a[2.7].asnumpy(), a[2].asnumpy())
    b = mx.nd.array(np.arange(4, dtype=np.float32))
    b[1.2] = 9.0
    assert b.asnumpy()[1] == 9.0


def test_roll_over_tail_carries_without_extra_failing_next():
    """Consumers that read exactly num_batches batches (no StopIteration
    probe) still count as a fully consumed epoch — the tail must carry."""
    import numpy as np
    from mxtpu.io import NDArrayIter
    x = np.arange(10, dtype=np.float32)
    it = NDArrayIter(x, np.zeros(10, np.float32), batch_size=4,
                     last_batch_handle="roll_over")
    for _ in range(it.num_batches):
        it.next()
    it.reset()
    assert it.num_batches == 3  # tail {8,9} carried + 10 fresh = 3 batches
