"""End-to-end causal tracing (mxtpu/telemetry.py) — ISSUE 10:

* TraceContext semantics: span nesting builds parent/child trees, the
  contextvar restores, MXTPU_TRACE=0 disables cleanly;
* explicit thread handoff: a worker adopting a context via
  trace_handoff keeps the trace id + parent linkage — the batcher
  dispatch worker, the replica re-dispatch after an injected wedge
  (SAME trace across both dispatches), and the prefetch producer are
  each covered, sleep-free under the injected clock where one exists;
* per-request latency breakdown: stages (submit, queue-wait, pad,
  predict, fetch, deliver) ride the future and sum to ~e2e; the HTTP
  front returns them with the trace_id;
* flight recorder: an injected replica_wedge dumps a JSON artifact whose
  trace_ids contain the wedged request's trace and whose thread stacks
  are present (the ISSUE-10 acceptance), injected faults and worker
  crashes dump too, bounded by MXTPU_FLIGHT_MAX;
* Prometheus exposition: every registry metric appears in valid text
  format; /metrics content-negotiates it next to the JSON snapshot;
* profiler.dump() merges the trace tree as chrome flow events;
* tools/telemetry_report.py --traces: the per-trace critical path view
  round-trips from the JSONL sink.
"""
import json
import os
import threading
import urllib.request

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import profiler, resilience, telemetry
from mxtpu.gluon import nn
from mxtpu.serving import (BucketSpec, MicroBatcher, ModelServer, Predictor,
                           ReplicaDispatcher, ReplicaSet)

import jax

IN_DIM, OUT_DIM = 12, 4


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_TELEMETRY", "MXTPU_TRACE", "MXTPU_TRACE_RING",
                "MXTPU_FLIGHT_DIR", "MXTPU_FLIGHT_MAX",
                "MXTPU_FAULT_INJECT", "MXTPU_RETRACE_BUDGET",
                "MXTPU_SERVE_DISPATCH_TIMEOUT_MS"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    yield
    telemetry.reset()
    resilience.reset_faults()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(OUT_DIM))
    net.initialize()
    return net


def _warm_predictor(max_batch=8):
    net = _mlp()
    spec = BucketSpec.pow2(max_batch)
    pred = Predictor(net, spec, example=np.zeros((1, IN_DIM), np.float32),
                     warmup=True)
    return net, spec, pred


def _x(n, seed=0):
    return np.random.RandomState(seed).randn(n, IN_DIM).astype(np.float32)


# ------------------------------------------------------------- context model
def test_span_nesting_builds_trace_tree():
    ctx = telemetry.new_trace()
    assert ctx is not None and ctx.span_id == 0
    with telemetry.trace_handoff(ctx):
        with telemetry.span("outer") as outer:
            with telemetry.span("inner") as inner:
                assert telemetry.current_trace() is inner.ctx
        assert telemetry.current_trace() is ctx
    assert telemetry.current_trace() is None
    evs = {e["name"]: e for e in telemetry.trace_events(ctx.trace_id)}
    assert evs["outer"]["parent"] == 0
    assert evs["inner"]["parent"] == evs["outer"]["span"]
    assert evs["inner"]["trace"] == evs["outer"]["trace"] == ctx.trace_id


def test_trace_disabled_is_clean(monkeypatch):
    monkeypatch.setenv("MXTPU_TRACE", "0")
    assert telemetry.new_trace() is None
    with telemetry.span("x", new_trace=True) as sp:
        pass
    assert sp.ctx is None
    assert telemetry.trace_events() == []
    # spans still time into the histogram with tracing off
    assert telemetry.snapshot()["histograms"]["x"]["count"] == 1
    # and the None-safe helpers are no-ops, not errors
    with telemetry.trace_handoff(None):
        telemetry.add_stage(None, "s", 1.0)
        telemetry.trace_mark(None, "m")
    assert telemetry.trace_breakdown(None) == {}


def test_handoff_carries_trace_across_thread():
    ctx = telemetry.new_trace()
    with telemetry.trace_handoff(ctx), telemetry.span("parent") as par:
        carried = par.ctx

        def worker():
            # a bare thread has NO context (no implicit inheritance)...
            assert telemetry.current_trace() is None
            # ...until it explicitly adopts the handed-off one
            with telemetry.trace_handoff(carried):
                with telemetry.span("child.on.thread"):
                    pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    evs = {e["name"]: e for e in telemetry.trace_events(ctx.trace_id)}
    child = evs["child.on.thread"]
    assert child["trace"] == ctx.trace_id
    assert child["parent"] == carried.span_id


def test_pend_link_drains_into_next_step_trace():
    src = telemetry.new_trace()
    telemetry.pend_link("data.h2d", src)
    with telemetry.span("trainer.step", new_trace=True) as st:
        assert telemetry.link_pending() == 1
        step_trace = st.ctx.trace_id
    links = [e for e in telemetry.trace_events() if e["kind"] == "link"]
    assert len(links) == 1
    assert links[0]["trace"] == step_trace
    assert links[0]["parent"]["trace"] == src.trace_id
    # drained: a second step adopts nothing
    with telemetry.span("trainer.step", new_trace=True):
        assert telemetry.link_pending() == 0


# -------------------------------------------------------------- serving path
def test_batcher_breakdown_across_dispatch_thread():
    """Two cohort requests submitted on this thread, dispatched by
    ANOTHER thread (the worker handoff), under the fake clock: each
    future carries its own trace_id and a breakdown whose queue_wait is
    the exact fake-clock wait."""
    _, spec, pred = _warm_predictor(max_batch=4)
    clk = FakeClock()
    bat = MicroBatcher(pred, max_batch_size=4, max_wait_ms=5,
                       clock=clk, start=False)
    f1 = bat.submit(_x(2, seed=1))
    f2 = bat.submit(_x(1, seed=2))
    clk.advance(0.006)  # head past max_wait: one cohort of both requests

    t = threading.Thread(target=bat.poll)
    t.start()
    t.join()
    assert f1.done() and f2.done()
    assert f1.trace_id is not None and f2.trace_id is not None
    assert f1.trace_id != f2.trace_id
    for f in (f1, f2):
        bd = f.breakdown
        assert set(bd) >= {"serving.submit", "serving.queue_wait",
                           "serving.pad", "serving.predict",
                           "serving.fetch", "serving.deliver"}, bd
        # queue wait measured on the INJECTED clock: exactly the advance
        assert bd["serving.queue_wait"] == pytest.approx(0.006)
    # the cohort lead's trace carries the batch-level span tree
    lead = {e["name"] for e in telemetry.trace_events(f1.trace_id)}
    assert {"serving.submit", "serving.pad", "serving.predict",
            "serving.fetch", "serving.deliver"} <= lead
    # and the member links into it
    links = [e for e in telemetry.trace_events(f1.trace_id)
             if e["kind"] == "link" and e["name"] == "serving.cohort"]
    assert links and links[0]["parent"]["trace"] == f2.trace_id


def test_breakdown_sums_to_e2e_realtime():
    """Real clock, threaded worker: stages sum to ~the measured e2e (the
    serve_bench gate is 5% median; a single CI request gets a loose
    absolute bound — the point is no stage interval is unaccounted)."""
    _, spec, pred = _warm_predictor(max_batch=4)
    bat = MicroBatcher(pred, max_batch_size=4, max_wait_ms=1)
    try:
        futs = [bat.submit(_x(2, seed=i)) for i in range(3)]
        for f in futs:
            f.result(timeout=30)
        for f in futs:
            assert f.e2e_s is not None
            gap = abs(sum(f.breakdown.values()) - f.e2e_s)
            assert gap <= max(0.05 * f.e2e_s, 0.005), \
                (f.breakdown, f.e2e_s)
    finally:
        bat.close()


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="needs >= 2 (virtual) devices")
def test_wedge_redispatch_joins_original_trace_and_flight_dump(
        monkeypatch, tmp_path):
    """The ISSUE-10 acceptance: an injected replica_wedge produces a
    flight-recorder dump whose trace_ids contain the wedged request's
    trace (the one its future reports) and whose per-thread stacks are
    present; the re-dispatch on the healthy replica delivers under the
    SAME trace, annotated with wedged/redispatch marks."""
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "replica_wedge@0")
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    resilience.reset_faults()
    net = _mlp()
    spec = BucketSpec.pow2(4)
    rs = ReplicaSet(net, spec, n=2,
                    example=np.zeros((1, IN_DIM), np.float32), warmup=True)
    clk = FakeClock()
    bat = ReplicaDispatcher(rs, max_batch_size=4, max_wait_ms=5,
                            dispatch_timeout_ms=2000, clock=clk,
                            start=False)
    x = _x(2, seed=7)
    fut = bat.submit(x)
    clk.advance(0.006)
    assert bat.poll() == 1          # dispatch 0 wedges (no answer)
    assert not fut.done()
    clk.advance(2.5)                # past the dispatch timeout
    assert bat.poll() == 1          # watchdog trips -> re-dispatch
    np.testing.assert_allclose(fut.result(0), net(mx.nd.array(x)).asnumpy(),
                               rtol=1e-5, atol=1e-5)
    # one trace end to end
    names = [e["name"] for e in telemetry.trace_events(fut.trace_id)]
    assert "serving.wedged" in names and "serving.redispatch" in names
    assert names.count("serving.predict") >= 1
    # two dispatches' worth of queue_wait/predict accumulated into ONE
    # breakdown (the re-dispatch joined, it did not restart)
    assert fut.breakdown["serving.queue_wait"] > 0
    # the artifact
    dumps = sorted(tmp_path.glob("flight_replica_wedge_*.json"))
    assert dumps, list(tmp_path.iterdir())
    art = json.loads(dumps[0].read_text())
    assert fut.trace_id in art["trace_ids"]
    assert art["threads"] and all("stack" in t for t in art["threads"])
    assert art["extra"]["replica"] == 0
    assert any(e["trace"] == fut.trace_id for e in art["events"])
    assert telemetry.value("flight.dumps") >= 1


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="needs >= 2 (virtual) devices")
def test_breaker_open_flight_dump(monkeypatch, tmp_path):
    """The failure that OPENS a replica's circuit breaker dumps a flight
    artifact tagged with the failing batch's traces."""
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "replica_fail@0")
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    resilience.reset_faults()
    net = _mlp()
    spec = BucketSpec.pow2(4)
    rs = ReplicaSet(net, spec, n=2, breaker_threshold=1,
                    example=np.zeros((1, IN_DIM), np.float32), warmup=True)
    clk = FakeClock()
    bat = ReplicaDispatcher(rs, max_batch_size=4, max_wait_ms=5,
                            dispatch_timeout_ms=2000, clock=clk,
                            start=False)
    fut = bat.submit(_x(1, seed=9))
    clk.advance(0.006)
    assert bat.poll() == 1          # dispatch 0 fails -> breaker opens
    with pytest.raises(Exception):
        fut.result(0)
    # note: the 'fault' dump from inject() fires too; the breaker dump
    # is the one tagged with the request's trace and replica extra
    dumps = sorted(tmp_path.glob("flight_breaker_open_*.json"))
    assert dumps
    art = json.loads(dumps[0].read_text())
    assert art["extra"]["replica"] in (0, 1)
    assert art["trace_ids"], art
    assert telemetry.tagged("flight.dumps").get("breaker_open") == 1


def test_flight_dump_on_injected_fault_and_cap(monkeypatch, tmp_path):
    monkeypatch.setenv("MXTPU_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_FLIGHT_MAX", "1")
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "nan_grad@0,1")
    resilience.reset_faults()
    assert resilience.inject("nan_grad", 0)
    assert resilience.inject("nan_grad", 1)
    dumps = list(tmp_path.glob("flight_fault_*.json"))
    assert len(dumps) == 1  # capped at MXTPU_FLIGHT_MAX
    art = json.loads(dumps[0].read_text())
    assert art["extra"]["kind"] == "nan_grad"
    assert art["threads"]


def test_flight_disabled_without_dir():
    assert telemetry.flight_record("whatever") is None
    assert telemetry.value("flight.dumps") == 0


# --------------------------------------------------------------- prefetcher
def test_prefetch_producer_trace_pends_and_links():
    from mxtpu.io.stream import DevicePrefetcher
    src = [np.full((4, 2), i, np.float32) for i in range(3)]
    pf = DevicePrefetcher(iter(src), depth=2)
    try:
        batches = [next(pf), next(pf)]
    finally:
        pf.close()
    assert [float(b.asnumpy()[0, 0]) for b in batches] == [0.0, 1.0]
    # the producer thread recorded data.h2d under its OWN traces
    h2d = [e for e in telemetry.trace_events() if e["name"] == "data.h2d"]
    assert len(h2d) >= 2
    # consuming pended the handoffs; the next step trace adopts them
    with telemetry.span("trainer.step", new_trace=True) as st:
        n = telemetry.link_pending()
    assert n >= 2  # data.h2d + data.wait per consumed batch
    links = [e for e in telemetry.trace_events(st.ctx.trace_id)
             if e["kind"] == "link"]
    link_srcs = {e["parent"]["trace"] for e in links}
    assert {e["trace"] for e in h2d[:2]} <= link_srcs


# ------------------------------------------------------------- trainer step
def test_trainer_step_is_trace_root_with_children():
    from mxtpu.gluon.parameter import Parameter
    from mxtpu.gluon.trainer import Trainer
    p = Parameter("w", shape=(4, 4))
    p.initialize()
    tr = Trainer([p], "sgd", {"learning_rate": 0.1})
    p.grad()[:] = 1.0
    tr.step(1)
    steps = [e for e in telemetry.trace_events()
             if e["name"] == "trainer.step"]
    assert steps and steps[-1]["parent"] == 0
    tid = steps[-1]["trace"]
    names = {e["name"]: e for e in telemetry.trace_events(tid)}
    assert names["trainer.step.allreduce"]["parent"] == \
        steps[-1]["span"]
    assert names["trainer.step.update"]["parent"] == steps[-1]["span"]
    # a second step is a NEW trace (per-step roots)
    p.grad()[:] = 1.0
    tr.step(1)
    steps2 = [e for e in telemetry.trace_events()
              if e["name"] == "trainer.step"]
    assert len(steps2) == 2 and steps2[-1]["trace"] != tid


# -------------------------------------------------------------- exposition
_PROM_LINE = None


def _valid_prom(text):
    import re
    label = r'[a-zA-Z0-9_]+="(\\.|[^"\\])*"'
    sample = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{%s(,%s)*\})? [0-9.eE+-]+(nan|inf)?$'
        % (label, label))
    names = set()
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            parts = line.split()
            assert len(parts) == 4 and parts[3] in \
                ("counter", "gauge", "summary"), line
            continue
        assert sample.match(line), "bad exposition line: %r" % line
        names.add(line.split("{")[0].split(" ")[0])
    return names


def test_prometheus_covers_every_registry_metric():
    telemetry.inc("plain.counter", 3)
    telemetry.inc("tagged.counter", tag='why "quoted"\nnewline')
    telemetry.inc("tagged.counter")  # mixed tagged+untagged
    telemetry.gauge("some.gauge", -1.5)
    telemetry.observe("some.hist", 0.25)
    telemetry.observe("some.hist", 0.75)
    names = _valid_prom(telemetry.prometheus())
    assert {"mxtpu_plain_counter", "mxtpu_tagged_counter",
            "mxtpu_some_gauge", "mxtpu_some_hist",
            "mxtpu_some_hist_sum", "mxtpu_some_hist_count"} <= names
    snap = telemetry.snapshot()
    for metric in list(snap["counters"]) + list(snap["gauges"]):
        assert telemetry._prom_name(metric) in names, metric
    for metric in snap["histograms"]:
        assert telemetry._prom_name(metric) + "_count" in names, metric


def test_server_metrics_content_negotiation_and_breakdown():
    _, spec, pred = _warm_predictor(max_batch=4)
    srv = ModelServer(MicroBatcher(pred, max_batch_size=4, max_wait_ms=1),
                      port=0).start()
    host, port = srv.address
    base = "http://%s:%d" % (host, port)
    try:
        body = json.dumps({"data": _x(2, seed=3).tolist()}).encode()
        req = urllib.request.Request(base + "/predict", data=body,
                                     headers={"Content-Type":
                                              "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert "trace_id" in out and "breakdown_ms" in out
        assert out["e2e_ms"] > 0
        assert sum(out["breakdown_ms"].values()) == pytest.approx(
            out["e2e_ms"], rel=0.05, abs=5.0)
        # default stays JSON
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            assert "application/json" in r.headers["Content-Type"]
            snap = json.loads(r.read())
            assert "counters" in snap
        # Accept: text/plain -> valid Prometheus exposition of the
        # whole registry (the ISSUE-10 acceptance)
        req = urllib.request.Request(base + "/metrics",
                                     headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            names = _valid_prom(r.read().decode())
        for metric in list(snap["counters"]) + list(snap["gauges"]):
            assert telemetry._prom_name(metric) in names, metric
    finally:
        srv.close()


# ------------------------------------------------------------ chrome flows
def test_profiler_dump_emits_flow_events(tmp_path):
    path = str(tmp_path / "trace.json")
    profiler.set_config(filename=path)
    profiler.start()
    with telemetry.span("root.region", new_trace=True) as root:
        with telemetry.span("child.region"):
            pass
        # mirror the prefetch producer: the pended context is a SPAN's
        # (it has a ring event for the flow arrow to start from)
        src_root = telemetry.new_trace()
        with telemetry.trace_handoff(src_root):
            with telemetry.span("data.h2d") as src_sp:
                pass
        telemetry.pend_link("data.h2d", src_sp.ctx)
        telemetry.link_pending()
    profiler.stop()
    profiler.dump()
    trace = json.loads(open(path).read())
    evs = trace["traceEvents"]
    flows = [e for e in evs if e.get("ph") in ("s", "f")]
    assert flows
    tree = [e for e in flows if e["cat"] == "trace"]
    links = [e for e in flows if e["cat"] == "trace.link"]
    assert {e["ph"] for e in tree} == {"s", "f"}
    assert {e["ph"] for e in links} == {"s", "f"}
    # the flow pair shares an id; starts precede finishes
    by_id = {}
    for e in flows:
        by_id.setdefault((e["cat"], e["id"]), []).append(e)
    for pair in by_id.values():
        assert len(pair) == 2
        s = next(e for e in pair if e["ph"] == "s")
        f = next(e for e in pair if e["ph"] == "f")
        assert s["ts"] <= f["ts"]
    # X events still present alongside
    assert any(e.get("ph") == "X" and e["name"] == "child.region"
               for e in evs)


# -------------------------------------------------------------- report tool
def test_telemetry_report_traces_roundtrip(monkeypatch, tmp_path):
    jl = str(tmp_path / "t.jsonl")
    monkeypatch.setenv("MXTPU_TELEMETRY", jl)
    import time as _time
    ctx = telemetry.new_trace()
    with telemetry.trace_handoff(ctx):
        with telemetry.span("serving.predict"):
            _time.sleep(0.01)
        with telemetry.span("serving.fetch"):
            _time.sleep(0.001)
    telemetry.add_stage(ctx, "serving.queue_wait", 0.002, event=True)
    telemetry.flush()
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import telemetry_report
    rows = telemetry_report.trace_summary(telemetry_report.load(jl))
    assert len(rows) == 1
    row = rows[0]
    assert row["trace"] == ctx.trace_id
    assert row["slowest"] == "serving.predict"
    assert row["spans"] == 3
    assert row["total"] == pytest.approx(
        sum(row["stages"].values()), rel=1e-6)
    table = telemetry_report.format_trace_table(rows)
    assert "serving.predict" in table
    # CLI end to end
    import subprocess
    out = subprocess.run(
        [sys.executable, "tools/telemetry_report.py", jl, "--traces", "5"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0
    assert "Slowest stage" in out.stdout
