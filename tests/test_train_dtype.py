"""Low-precision end-to-end training tier (ref: tests/python/train/
test_dtype.py — the fp16 training accuracy asserts, mapped to bf16, the
TPU design point). Exercises the f32-accumulate conv/dot fast paths
(conv_acc.py, precision_util.py) through a REAL training run with an
accuracy bar, not just op-level parity."""
import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.gluon import nn


def _blob_data(n=256, size=12, seed=0):
    """Two classes of images separable by a bright vs dark center blob."""
    rng = np.random.RandomState(seed)
    x = rng.uniform(-0.4, 0.4, (n, size, size, 3)).astype(np.float32)
    y = rng.randint(0, 2, n).astype(np.float32)
    c = size // 2
    for i in range(n):
        sign = 1.0 if y[i] else -1.0
        x[i, c - 2:c + 2, c - 2:c + 2] += sign * 0.8
    return x, y


def test_bf16_conv_training_reaches_accuracy():
    mx.random.seed(0)
    with mx.layout("NHWC"):
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1, layout="NHWC",
                          activation="relu"),
                nn.Conv2D(8, 3, padding=1, layout="NHWC",
                          activation="relu"),
                nn.GlobalAvgPool2D(layout="NHWC"),
                nn.Dense(2))
    net.initialize(mx.init.Xavier())
    xf, y = _blob_data()
    net(mx.nd.array(xf[:8]))  # settle shapes
    net.cast("bfloat16")
    net.hybridize()

    # multi-precision: bf16 weights, f32 master copies (ref optimizer.py
    # mp_sgd_update pattern)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.2, "momentum": 0.9,
                             "multi_precision": True})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    bs = 32
    for epoch in range(4):
        for i in range(0, len(xf), bs):
            xb = mx.nd.array(xf[i:i + bs]).astype("bfloat16")
            yb = mx.nd.array(y[i:i + bs])
            with autograd.record():
                out = net(xb)
                loss = loss_fn(out, yb)
            loss.backward()
            trainer.step(bs)

    logits = net(mx.nd.array(xf).astype("bfloat16")).asnumpy()
    acc = float((logits.argmax(1) == y).mean())
    assert acc >= 0.95, "bf16 training accuracy %.3f < 0.95" % acc
    # weights really are stored bf16 (the fast path was exercised)
    w = list(net.collect_params().values())[0].data()
    assert str(w.dtype) == "bfloat16"
