"""The f32-accumulate conv custom-vjp (mxtpu/ops/conv_acc.py) must be
numerically indistinguishable from jax's own autodiff of the plain conv —
the bwd reuses jax's transpose-rule implementations, so any drift means the
wiring (padding/stride/group plumbing) broke."""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
import pytest

from mxtpu.ops.conv_acc import HAVE_ACC_VJP, conv_fast

pytestmark = pytest.mark.skipif(not HAVE_ACC_VJP,
                                reason="private jax transpose helpers absent")


@pytest.fixture(autouse=True)
def _force_custom_path(monkeypatch):
    """MXTPU_CONV_ACC defaults to 0 as of round 5 (end-to-end regression
    on chip); these tests exist to keep the still-re-enableable custom
    vjp from rotting, so they pin the flag ON."""
    monkeypatch.setenv("MXTPU_CONV_ACC", "1")

DN = ("NHWC", "HWIO", "NHWC")


def _plain(x, w, strides, padding, lhs_dil, rhs_dil, dims, groups):
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        lhs_dilation=lhs_dil, rhs_dilation=rhs_dil,
        dimension_numbers=dims, feature_group_count=groups,
        precision=lax.Precision.DEFAULT)


@pytest.mark.parametrize("strides,pad,rhs_dil,groups,cin,cout,k", [
    ((1, 1), (1, 1), (1, 1), 1, 8, 16, 3),
    ((2, 2), (1, 1), (1, 1), 1, 8, 16, 3),
    ((2, 2), (3, 3), (1, 1), 1, 3, 16, 7),   # resnet stem shape
    ((1, 1), (0, 0), (1, 1), 1, 8, 16, 1),   # 1x1 bottleneck
    ((1, 1), (2, 2), (2, 2), 1, 8, 16, 3),   # dilated
    ((1, 1), (1, 1), (1, 1), 4, 8, 16, 3),   # grouped
    ((1, 1), (1, 1), (1, 1), 8, 8, 8, 3),    # depthwise
])
def test_conv_acc_matches_plain_autodiff(strides, pad, rhs_dil, groups,
                                         cin, cout, k):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 12, 12, cin), jnp.bfloat16)
    w = jnp.asarray(rng.randn(k, k, cin // groups, cout) * 0.1, jnp.bfloat16)
    padding = [(pad[0], pad[0]), (pad[1], pad[1])]
    args = (strides, padding, (1, 1), rhs_dil, DN, groups)

    def f_fast(x, w):
        return jnp.sum(conv_fast(x, w, *args).astype(jnp.float32) ** 2)

    def f_plain(x, w):
        return jnp.sum(_plain(x, w, *args).astype(jnp.float32) ** 2)

    y_fast = conv_fast(x, w, *args)
    y_plain = _plain(x, w, *args)
    assert y_fast.dtype == x.dtype
    # fwd: f32 accumulation is at least as accurate as the plain result
    np.testing.assert_allclose(np.asarray(y_fast, np.float32),
                               np.asarray(y_plain, np.float32),
                               rtol=2e-2, atol=2e-2)
    gf = jax.grad(f_fast, argnums=(0, 1))(x, w)
    gp = jax.grad(f_plain, argnums=(0, 1))(x, w)
    for a, b in zip(gf, gp):
        assert a.dtype == b.dtype
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-2, atol=5e-2)


def test_conv_acc_lhs_dilation_matches_plain_autodiff():
    """The Deconvolution path: lhs_dilation != 1 exercises the transposed-
    conv padding arithmetic inside the reused jax transpose helpers."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 6, 6, 8), jnp.bfloat16)
    w = jnp.asarray(rng.randn(3, 3, 8, 8) * 0.1, jnp.bfloat16)
    # deconv stride 2: lhs_dilation (2,2), padding (k-1-pad) style
    args = ((1, 1), [(2, 2), (2, 2)], (2, 2), (1, 1), DN, 1)

    def f_fast(x, w):
        return jnp.sum(conv_fast(x, w, *args).astype(jnp.float32) ** 2)

    def f_plain(x, w):
        return jnp.sum(_plain_full(x, w, *args).astype(jnp.float32) ** 2)

    def _plain_full(x, w, strides, padding, lhs_dil, rhs_dil, dims, groups):
        return lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            lhs_dilation=lhs_dil, rhs_dilation=rhs_dil,
            dimension_numbers=dims, feature_group_count=groups,
            precision=lax.Precision.DEFAULT)

    y_fast = conv_fast(x, w, *args)
    y_plain = _plain_full(x, w, *args)
    np.testing.assert_allclose(np.asarray(y_fast, np.float32),
                               np.asarray(y_plain, np.float32),
                               rtol=2e-2, atol=2e-2)
    gf = jax.grad(f_fast, argnums=(0, 1))(x, w)
    gp = jax.grad(f_plain, argnums=(0, 1))(x, w)
    for a, b in zip(gf, gp):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-2, atol=5e-2)


def test_conv_acc_under_jit_and_vmap():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(3, 2, 8, 8, 4), jnp.bfloat16)
    w = jnp.asarray(rng.randn(3, 3, 4, 4) * 0.1, jnp.bfloat16)
    args = ((1, 1), [(1, 1), (1, 1)], (1, 1), (1, 1), DN, 1)

    @jax.jit
    def g(x, w):
        per = jax.vmap(lambda xi: conv_fast(xi, w, *args))(x)
        return jnp.sum(per.astype(jnp.float32))

    val, grads = jax.value_and_grad(g, argnums=(0, 1))(x, w)
    assert np.isfinite(float(val))
    assert grads[0].shape == x.shape and grads[1].shape == w.shape


def test_f32_operands_keep_plain_path():
    """f32 convs must NOT take the custom path — they stay on the honest
    HIGHEST-precision global (precision_util docstring)."""
    x = jnp.ones((1, 6, 6, 4), jnp.float32)
    w = jnp.ones((3, 3, 4, 4), jnp.float32)
    args = ((1, 1), [(1, 1), (1, 1)], (1, 1), (1, 1), DN, 1)
    txt = jax.jit(lambda x, w: conv_fast(x, w, *args)).lower(x, w).as_text()
    assert "HIGHEST" in txt


@pytest.mark.parametrize("cin,cout,k,hw", [(64, 64, 3, 14), (3, 8, 7, 16),
                                           (128, 32, 5, 10)])
def test_im2col_path_exact(cin, cout, k, hw):
    """The staged im2col lowering (MXTPU_CONV_IM2COL) must equal the conv
    path exactly, forward and weight-gradient (round-5 lever for the
    slow small-channel conv classes, PERF.md)."""
    import numpy as np
    from mxtpu.ops.conv_acc import conv_im2col
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, hw, hw, cin), jnp.float32)
    w = jnp.asarray(rng.randn(k, k, cin, cout) * 0.1, jnp.float32)
    pad = [(k // 2, k // 2)] * 2
    ref = lax.conv_general_dilated(
        x, w, (1, 1), pad, dimension_numbers=DN)
    got = conv_im2col(x, w, pad)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    g1 = jax.grad(lambda w_: jnp.sum(conv_im2col(x, w_, pad) ** 2))(w)
    g2 = jax.grad(lambda w_: jnp.sum(lax.conv_general_dilated(
        x, w_, (1, 1), pad, dimension_numbers=DN) ** 2))(w)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-3, atol=1e-3)


def test_im2col_dispatch_gating(monkeypatch):
    """Only stride-1 / groups-1 / k>1 / C_in<=128 NHWC convs qualify, and
    the env flag genuinely routes conv_fast through the matmul lowering
    (the staged lever must not be silently dead when the auto-battery
    measures it)."""
    from mxtpu.ops.conv_acc import _im2col_applicable
    x = jnp.zeros((1, 8, 8, 16), jnp.bfloat16)
    w3 = jnp.zeros((3, 3, 16, 8), jnp.bfloat16)
    ok = ("NHWC", "HWIO", "NHWC")
    assert _im2col_applicable(x, w3, (1, 1), None, (1, 1), (1, 1), ok, 1)
    assert not _im2col_applicable(x, w3, (2, 2), None, (1, 1), (1, 1), ok, 1)
    assert not _im2col_applicable(x, jnp.zeros((1, 1, 16, 8)), (1, 1),
                                  None, (1, 1), (1, 1), ok, 1)
    assert not _im2col_applicable(x, jnp.zeros((3, 3, 256, 8)), (1, 1),
                                  None, (1, 1), (1, 1), ok, 1)
    assert not _im2col_applicable(x, w3, (1, 1), None, (1, 1), (1, 1),
                                  ok, 2)
    assert not _im2col_applicable(x, w3, (1, 1), None, (2, 2), (1, 1),
                                  ok, 1)


    args = ((1, 1), [(1, 1), (1, 1)], (1, 1), (1, 1), ok, 1)
    monkeypatch.delenv("MXTPU_CONV_IM2COL", raising=False)
    hlo_off = jax.jit(lambda a, b: conv_fast(a, b, *args)).lower(
        jnp.zeros((1, 8, 8, 16), jnp.bfloat16), w3).as_text()
    assert "convolution" in hlo_off
    monkeypatch.setenv("MXTPU_CONV_IM2COL", "1")
    hlo_on = jax.jit(lambda a, b: conv_fast(a, b, *args)).lower(
        jnp.zeros((1, 8, 8, 16), jnp.bfloat16), w3).as_text()
    # patches extraction lowers to a conv against an identity kernel on
    # some jax versions; the CONTRACTION itself must be a dot_general
    assert "dot_general" in hlo_on and "dot_general" not in hlo_off
