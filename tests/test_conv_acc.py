"""The f32-accumulate conv custom-vjp (mxtpu/ops/conv_acc.py) must be
numerically indistinguishable from jax's own autodiff of the plain conv —
the bwd reuses jax's transpose-rule implementations, so any drift means the
wiring (padding/stride/group plumbing) broke."""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
import pytest

from mxtpu.ops.conv_acc import HAVE_ACC_VJP, conv_fast

pytestmark = pytest.mark.skipif(not HAVE_ACC_VJP,
                                reason="private jax transpose helpers absent")


@pytest.fixture(autouse=True)
def _force_custom_path(monkeypatch):
    """MXTPU_CONV_ACC defaults to 0 as of round 5 (end-to-end regression
    on chip); these tests exist to keep the still-re-enableable custom
    vjp from rotting, so they pin the flag ON."""
    monkeypatch.setenv("MXTPU_CONV_ACC", "1")

DN = ("NHWC", "HWIO", "NHWC")


def _plain(x, w, strides, padding, lhs_dil, rhs_dil, dims, groups):
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        lhs_dilation=lhs_dil, rhs_dilation=rhs_dil,
        dimension_numbers=dims, feature_group_count=groups,
        precision=lax.Precision.DEFAULT)


@pytest.mark.parametrize("strides,pad,rhs_dil,groups,cin,cout,k", [
    ((1, 1), (1, 1), (1, 1), 1, 8, 16, 3),
    ((2, 2), (1, 1), (1, 1), 1, 8, 16, 3),
    ((2, 2), (3, 3), (1, 1), 1, 3, 16, 7),   # resnet stem shape
    ((1, 1), (0, 0), (1, 1), 1, 8, 16, 1),   # 1x1 bottleneck
    ((1, 1), (2, 2), (2, 2), 1, 8, 16, 3),   # dilated
    ((1, 1), (1, 1), (1, 1), 4, 8, 16, 3),   # grouped
    ((1, 1), (1, 1), (1, 1), 8, 8, 8, 3),    # depthwise
])
def test_conv_acc_matches_plain_autodiff(strides, pad, rhs_dil, groups,
                                         cin, cout, k):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 12, 12, cin), jnp.bfloat16)
    w = jnp.asarray(rng.randn(k, k, cin // groups, cout) * 0.1, jnp.bfloat16)
    padding = [(pad[0], pad[0]), (pad[1], pad[1])]
    args = (strides, padding, (1, 1), rhs_dil, DN, groups)

    def f_fast(x, w):
        return jnp.sum(conv_fast(x, w, *args).astype(jnp.float32) ** 2)

    def f_plain(x, w):
        return jnp.sum(_plain(x, w, *args).astype(jnp.float32) ** 2)

    y_fast = conv_fast(x, w, *args)
    y_plain = _plain(x, w, *args)
    assert y_fast.dtype == x.dtype
    # fwd: f32 accumulation is at least as accurate as the plain result
    np.testing.assert_allclose(np.asarray(y_fast, np.float32),
                               np.asarray(y_plain, np.float32),
                               rtol=2e-2, atol=2e-2)
    gf = jax.grad(f_fast, argnums=(0, 1))(x, w)
    gp = jax.grad(f_plain, argnums=(0, 1))(x, w)
    for a, b in zip(gf, gp):
        assert a.dtype == b.dtype
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-2, atol=5e-2)


def test_conv_acc_lhs_dilation_matches_plain_autodiff():
    """The Deconvolution path: lhs_dilation != 1 exercises the transposed-
    conv padding arithmetic inside the reused jax transpose helpers."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 6, 6, 8), jnp.bfloat16)
    w = jnp.asarray(rng.randn(3, 3, 8, 8) * 0.1, jnp.bfloat16)
    # deconv stride 2: lhs_dilation (2,2), padding (k-1-pad) style
    args = ((1, 1), [(2, 2), (2, 2)], (2, 2), (1, 1), DN, 1)

    def f_fast(x, w):
        return jnp.sum(conv_fast(x, w, *args).astype(jnp.float32) ** 2)

    def f_plain(x, w):
        return jnp.sum(_plain_full(x, w, *args).astype(jnp.float32) ** 2)

    def _plain_full(x, w, strides, padding, lhs_dil, rhs_dil, dims, groups):
        return lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            lhs_dilation=lhs_dil, rhs_dilation=rhs_dil,
            dimension_numbers=dims, feature_group_count=groups,
            precision=lax.Precision.DEFAULT)

    y_fast = conv_fast(x, w, *args)
    y_plain = _plain_full(x, w, *args)
    np.testing.assert_allclose(np.asarray(y_fast, np.float32),
                               np.asarray(y_plain, np.float32),
                               rtol=2e-2, atol=2e-2)
    gf = jax.grad(f_fast, argnums=(0, 1))(x, w)
    gp = jax.grad(f_plain, argnums=(0, 1))(x, w)
    for a, b in zip(gf, gp):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-2, atol=5e-2)


def test_conv_acc_under_jit_and_vmap():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(3, 2, 8, 8, 4), jnp.bfloat16)
    w = jnp.asarray(rng.randn(3, 3, 4, 4) * 0.1, jnp.bfloat16)
    args = ((1, 1), [(1, 1), (1, 1)], (1, 1), (1, 1), DN, 1)

    @jax.jit
    def g(x, w):
        per = jax.vmap(lambda xi: conv_fast(xi, w, *args))(x)
        return jnp.sum(per.astype(jnp.float32))

    val, grads = jax.value_and_grad(g, argnums=(0, 1))(x, w)
    assert np.isfinite(float(val))
    assert grads[0].shape == x.shape and grads[1].shape == w.shape


def test_f32_operands_keep_plain_path():
    """f32 convs must NOT take the custom path — they stay on the honest
    HIGHEST-precision global (precision_util docstring)."""
    x = jnp.ones((1, 6, 6, 4), jnp.float32)
    w = jnp.ones((3, 3, 4, 4), jnp.float32)
    args = ((1, 1), [(1, 1), (1, 1)], (1, 1), (1, 1), DN, 1)
    txt = jax.jit(lambda x, w: conv_fast(x, w, *args)).lower(x, w).as_text()
    assert "HIGHEST" in txt
