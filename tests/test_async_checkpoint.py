"""Sharded/async checkpointing (mxtpu/contrib/async_checkpoint.py) — the
TPU-native upgrade over the reference's single-writer files (SURVEY §5)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import gluon
from mxtpu.gluon import nn
from mxtpu.contrib import async_checkpoint as ackpt
from mxtpu.parallel import ShardedTrainStep, make_mesh


def _build(seed=0):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).randn(16, 16).astype(np.float32))
    net(x)
    return net, x


@pytest.mark.multidevice
def test_train_step_roundtrip_with_zero1_state(tmp_path):
    net, x = _build()
    y = mx.nd.array(np.random.RandomState(1).randint(0, 8, (16,))
                    .astype(np.float32))
    mesh = make_mesh({"data": 8})
    step = ShardedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), mesh,
                            optimizer="sgd",
                            optimizer_params={"learning_rate": 0.1,
                                              "momentum": 0.9},
                            shard_weight_update=True)
    for _ in range(3):
        step(x, y)
    ck = ackpt.save_train_step(step, str(tmp_path), step=3, async_save=True)
    ck.wait_until_finished()
    l_next = float(step(x, y).asnumpy())

    net2, _ = _build(seed=42)  # different init on purpose
    step2 = ShardedTrainStep(net2, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mesh, optimizer="sgd",
                             optimizer_params={"learning_rate": 0.1,
                                               "momentum": 0.9},
                             shard_weight_update=True)
    ackpt.load_train_step(step2, str(tmp_path), step=3)
    assert step2._num_update == 3
    # momentum came back SHARDED, and the next step matches exactly
    # (states live in the rule registry's structure — None | array | tuple)
    import jax
    m = [s for st in step2._opt_states
         for s in jax.tree_util.tree_leaves(st)][0]
    assert m.sharding.spec[0] == "data"
    assert abs(float(step2(x, y).asnumpy()) - l_next) < 1e-6


def test_block_roundtrip(tmp_path):
    net, x = _build()
    ackpt.save_block(net, str(tmp_path), step=0)
    net2, _ = _build(seed=7)
    with pytest.raises(Exception):
        np.testing.assert_allclose(net2(x).asnumpy(), net(x).asnumpy())
    ackpt.load_block(net2, str(tmp_path), step=0)
    np.testing.assert_allclose(net2(x).asnumpy(), net(x).asnumpy(),
                               rtol=1e-6)


@pytest.mark.multidevice
def test_optimizer_structure_mismatch_refused(tmp_path):
    """Restoring into a trainer with a different optimizer-state shape must
    raise, not silently drop state (that would fork the trajectory)."""
    net, x = _build()
    y = mx.nd.array(np.zeros(16, np.float32))
    mesh = make_mesh({"data": 8})
    step = ShardedTrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), mesh,
                            optimizer="sgd",
                            optimizer_params={"learning_rate": 0.1,
                                              "momentum": 0.9})
    step(x, y)
    ackpt.save_train_step(step, str(tmp_path), step=1)
    net2, _ = _build(seed=1)
    momless = ShardedTrainStep(net2, gluon.loss.SoftmaxCrossEntropyLoss(),
                               mesh, optimizer="sgd",
                               optimizer_params={"learning_rate": 0.1})
    with pytest.raises(mx.MXNetError, match="state structure mismatch"):
        ackpt.load_train_step(momless, str(tmp_path), step=1)
