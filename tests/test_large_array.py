"""Large-tensor tier: >2^31-element arrays (ref: tests/nightly/
test_large_array.py, gated there by the USE_INT64_TENSOR_SIZE build).

The TPU-native analog of that build flag is jax's x64 mode: with it the
engine indexes in int64 and every path below is exact past 2^31 (verified
here); without it jax truncates indices to int32 (slice raises
OverflowError rather than corrupting — checked too). The checks run in a
SUBPROCESS so JAX_ENABLE_X64 can be set before jax initializes.

Opt-in (like the reference's nightly tier): MXTPU_TEST_LARGE=1, needs
~4 GB RAM and a few minutes.
"""
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("MXTPU_TEST_LARGE") != "1",
    reason="large-tensor tier: set MXTPU_TEST_LARGE=1 (needs ~4GB RAM)")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHECKS = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import mxtpu as mx

N = (1 << 31) + 5  # past int32 element count

x = mx.nd.zeros((N,), dtype="uint8")
assert x.shape == (N,)
x[N - 2] = 7                      # setitem past 2^31
assert int(x[N - 2].asnumpy()) == 7
assert x[N - 4:N - 1].asnumpy().tolist() == [0, 0, 7]
assert int(x._data.sum()) == 7  # fused reduce; no int64 copy

# engine-level int64 indexing is exact (the framework argmax keeps the
# reference's float32 return convention, which rounds past 2^24)
am = x._data.argmax()
assert am.dtype == jnp.int64 and int(am) == N - 2, (am.dtype, int(am))
assert int(jnp.take(x._data, jnp.asarray([N - 2]))[0]) == 7
print("OK1D")
"""

_CHECKS_2D = r"""
import jax
jax.config.update("jax_platforms", "cpu")
import mxtpu as mx

rows, cols = 1 << 16, (1 << 15) + 1           # 2^31 + 2^16 elements
y = mx.nd.zeros((rows, cols), dtype="uint8")
y[rows - 1, cols - 1] = 9
assert int(y[rows - 1, cols - 1].asnumpy()) == 9
t = y[rows - 1]
assert t.shape == (cols,) and int(t.asnumpy()[-1]) == 9
print("OK2D")
"""


def _run(code, x64):
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO
    env["JAX_ENABLE_X64"] = "1" if x64 else "0"
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=1200)


def test_large_1d_int64_indexing():
    out = _run(_CHECKS, x64=True)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK1D" in out.stdout


def test_large_2d_indexing():
    out = _run(_CHECKS_2D, x64=True)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK2D" in out.stdout


def test_without_x64_fails_loudly_not_silently():
    """Outside the large-tensor mode, indexing past 2^31 must ERROR
    (OverflowError from the int32 index path), never silently truncate —
    the failure mode the reference's int64 build gate also guards."""
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import mxtpu as mx\n"
        "N = (1 << 31) + 5\n"
        "x = mx.nd.zeros((N,), dtype='uint8')\n"
        "try:\n"
        "    v = x[N - 2].asnumpy()\n"
        "    print('SILENT', v)\n"
        "except Exception as e:\n"
        "    print('RAISED', type(e).__name__)\n")
    out = _run(code, x64=False)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "RAISED" in out.stdout, out.stdout
