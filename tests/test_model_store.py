"""Pretrained-weight store + torch conversion (ref:
python/mxnet/gluon/model_zoo/model_store.py; tests/python/gpu/test_gluon_model_zoo_gpu.py
pattern of exercising pretrained load paths)."""
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu.base import MXNetError
from mxtpu.gluon.model_zoo import model_store, vision


def _settle(net, size=32):
    x = mx.nd.array(np.random.RandomState(0).uniform(
        -1, 1, (1, 3, size, size)).astype(np.float32))
    net(x)
    return x


def test_get_model_file_plain_dropin(tmp_path):
    net = vision.resnet18_v1()
    net.initialize()
    _settle(net)
    f = str(tmp_path / "resnet18_v1.params")
    net.save_parameters(f)
    path = model_store.get_model_file("resnet18_v1", root=str(tmp_path))
    assert path == f


def test_get_model_file_missing_raises_with_instructions(tmp_path):
    with pytest.raises(MXNetError, match="convert torch weights"):
        model_store.get_model_file("resnet18_v1", root=str(tmp_path))


def test_get_model_file_rejects_bad_hash(tmp_path):
    # a file wearing the hash-verified name but with wrong content must
    # not be returned as verified (ref: check_sha1 gate)
    bad = tmp_path / ("resnet18_v1-%s.params"
                      % model_store.short_hash("resnet18_v1"))
    bad.write_bytes(b"junk")
    with pytest.raises(MXNetError):
        model_store.get_model_file("resnet18_v1", root=str(tmp_path))


def test_pretrained_loads_from_store(tmp_path, monkeypatch):
    src = vision.resnet18_v1()
    src.initialize()
    x = _settle(src)
    src.save_parameters(str(tmp_path / "resnet18_v1.params"))
    monkeypatch.setenv("MXTPU_MODEL_ZOO_PATH", str(tmp_path))
    net = vision.get_model("resnet18_v1", pretrained=True)
    np.testing.assert_allclose(net(x).asnumpy(), src(x).asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_purge(tmp_path):
    (tmp_path / "resnet18_v1.params").write_bytes(b"x")
    (tmp_path / "keep.txt").write_bytes(b"x")
    model_store.purge(root=str(tmp_path))
    assert not (tmp_path / "resnet18_v1.params").exists()
    assert (tmp_path / "keep.txt").exists()


# ------------------------------------------------------- torch conversion
def test_torchvision_resnet_map_covers_all_params():
    """The static name map must cover EXACTLY the zoo net's parameters —
    this pins the map to both naming schemes."""
    from mxtpu.contrib import torch_zoo
    for depth, builder in ((18, vision.resnet18_v1),
                           (50, vision.resnet50_v1)):
        net = builder()
        net.initialize()
        _settle(net)
        ours = set(net._collect_params_with_prefix())
        mapped = set(torch_zoo.torchvision_resnet_map(depth).values())
        assert mapped == ours, (depth, mapped ^ ours)


def test_torch_state_dict_conversion_matches_numerics(tmp_path):
    """conv-bn-dense torch module vs the gluon equivalent: converted
    weights must reproduce torch's eval-mode forward to float tolerance
    (validates OIHW conv layout, BN field renames, Linear transpose)."""
    torch = pytest.importorskip("torch")
    import torch.nn as tnn

    from mxtpu import gluon
    from mxtpu.contrib import torch_zoo
    from mxtpu.gluon import nn

    tmod = tnn.Sequential(
        tnn.Conv2d(3, 4, 3, padding=1),
        tnn.BatchNorm2d(4),
        tnn.ReLU(),
        tnn.Flatten(),
        tnn.Linear(4 * 8 * 8, 5))
    # non-trivial BN running stats
    tmod.train()
    with torch.no_grad():
        for _ in range(3):
            tmod(torch.randn(4, 3, 8, 8))
    tmod.eval()

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, 3, padding=1, in_channels=3))
        net.add(nn.BatchNorm(in_channels=4))
        net.add(nn.Activation("relu"))
        net.add(nn.Flatten())
        net.add(nn.Dense(5, in_units=4 * 8 * 8))
    net.initialize()
    x = np.random.RandomState(1).uniform(-1, 1, (2, 3, 8, 8)) \
        .astype(np.float32)
    net(mx.nd.array(x))

    name_map = {"0.weight": "0.weight", "0.bias": "0.bias",
                "1.weight": "1.gamma", "1.bias": "1.beta",
                "1.running_mean": "1.running_mean",
                "1.running_var": "1.running_var",
                "4.weight": "4.weight", "4.bias": "4.bias"}
    torch_zoo.load_torch_parameters(net, tmod.state_dict(), name_map)

    with torch.no_grad():
        expect = tmod(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(net(mx.nd.array(x)).asnumpy(), expect,
                               rtol=1e-4, atol=1e-5)

    # and the converted net round-trips through the weight store
    f = str(tmp_path / "converted.params")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Conv2D(4, 3, padding=1, in_channels=3))
        net2.add(nn.BatchNorm(in_channels=4))
        net2.add(nn.Activation("relu"))
        net2.add(nn.Flatten())
        net2.add(nn.Dense(5, in_units=4 * 8 * 8))
    net2.load_parameters(f)
    np.testing.assert_allclose(net2(mx.nd.array(x)).asnumpy(), expect,
                               rtol=1e-4, atol=1e-5)


def test_strict_conversion_rejects_gaps():
    from mxtpu.contrib import torch_zoo
    torch = pytest.importorskip("torch")
    import torch.nn as tnn

    from mxtpu.gluon import nn
    net = nn.Dense(3, in_units=4)
    net.initialize()
    tmod = tnn.Linear(4, 3)
    with pytest.raises(MXNetError, match="no mapping"):
        torch_zoo.load_torch_parameters(net, tmod.state_dict(),
                                        {"weight": "weight"})
    with pytest.raises(MXNetError, match="missing"):
        torch_zoo.load_torch_parameters(
            net, {"weight": tmod.weight}, {"weight": "weight"})
