"""Fault-tolerant replica serving (mxtpu/serving/replicas) — ISSUE 8:

* ReplicaSet: one AOT-warmed Predictor per device — per-replica retrace
  sites pinned at #buckets each, params device_put per replica,
  per-replica output parity vs the plain block;
* least-loaded routing (quarantined/busy replicas are never picked);
* the wedge watchdog (fake clock, zero sleeps): an injected
  ``replica_wedge`` strands a dispatch -> the replica is quarantined, the
  batch re-dispatches exactly ONCE on a healthy replica, every future
  completes, and a half-open probe later restores the replica;
* the circuit breaker: ``replica_fail`` x threshold opens it, shed
  reason ``no_healthy_replica`` appears only when ALL replicas are down,
  and a due probe closes it again;
* MicroBatcher satellites: the worker crash barrier (queued futures fail
  instead of hanging on a dead daemon thread) and the condvar drain (no
  bare time.sleep against the real clock);
* ModelServer: /healthz per-replica state + degraded status, /metrics
  replica-tagged counters;
* the threaded end-to-end run: per-replica workers serve a closed-loop
  burst with zero hangs.
"""
import os
import threading
import time

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import resilience, telemetry
from mxtpu.base import MXNetError
from mxtpu.gluon import nn
from mxtpu.serving import (BucketSpec, DeadlineExceeded, MicroBatcher,
                           ModelServer, Predictor, QueueFull,
                           ReplicaDispatcher, ReplicaSet)

import jax

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="replica serving tests need >= 2 (virtual) devices")


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for var in ("MXTPU_TELEMETRY", "MXTPU_RETRACE_BUDGET",
                "MXTPU_FAULT_INJECT", "MXTPU_SERVE_MAX_BATCH",
                "MXTPU_SERVE_MAX_WAIT_MS", "MXTPU_SERVE_QUEUE",
                "MXTPU_SERVE_REPLICAS", "MXTPU_SERVE_DISPATCH_TIMEOUT_MS",
                "MXTPU_SERVE_BREAKER_THRESHOLD",
                "MXTPU_SERVE_BREAKER_BACKOFF_MS",
                "MXTPU_SERVE_BREAKER_BACKOFF_MAX_MS"):
        monkeypatch.delenv(var, raising=False)
    telemetry.reset()
    resilience.reset_faults()
    yield
    telemetry.reset()
    resilience.reset_faults()


IN_DIM, OUT_DIM = 12, 4


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def _mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(OUT_DIM))
    net.initialize()
    return net


def _x(n, seed=0, dim=IN_DIM):
    return np.random.RandomState(seed).randn(n, dim).astype(np.float32)


def _rset(n=2, max_batch=4, **kw):
    net = _mlp()
    spec = BucketSpec.pow2(max_batch)
    kw.setdefault("breaker_backoff_ms", 1000)
    rs = ReplicaSet(net, spec, n=n,
                    example=np.zeros((1, IN_DIM), np.float32),
                    warmup=True, **kw)
    return net, spec, rs


def _disp(rs, clk, **kw):
    kw.setdefault("max_batch_size", rs.spec.max_batch)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("dispatch_timeout_ms", 2000)
    return ReplicaDispatcher(rs, clock=clk, start=False, **kw)


def _states(bat):
    return [s["state"] for s in bat.replica_states()]


# ------------------------------------------------------------------ ReplicaSet
def test_replicaset_warmup_per_replica_sites_and_devices():
    _, spec, rs = _rset(n=2)
    assert len(rs) == 2
    # one warmed executable cache per replica, each pinned at #buckets
    # at its OWN retrace site
    for i, rep in enumerate(rs.replicas):
        st = telemetry.retrace_stats("serving.predict.r%d" % i)
        assert st["compiles"] == len(spec), st
        assert st["trips"] == 0
    # the PR-5 site is untouched: no anonymous serving compiles
    assert telemetry.retrace_stats("serving.predict") is None
    # params committed per replica device
    d0 = {str(d) for d in
          (rs.replicas[0].predictor._param_datas[0].devices()
           if hasattr(rs.replicas[0].predictor._param_datas[0], "devices")
           else [rs.replicas[0].predictor._param_datas[0].device()])}
    d1 = {str(d) for d in
          (rs.replicas[1].predictor._param_datas[0].devices()
           if hasattr(rs.replicas[1].predictor._param_datas[0], "devices")
           else [rs.replicas[1].predictor._param_datas[0].device()])}
    assert d0 != d1
    assert telemetry.snapshot()["gauges"]["serving.replicas"] == 2


def test_replicaset_per_replica_parity():
    net, _, rs = _rset(n=2)
    x = _x(3, seed=42)
    ref = net(mx.nd.array(x)).asnumpy()
    for rep in rs.replicas:
        np.testing.assert_allclose(rep.predictor.predict(x).asnumpy(), ref,
                                   rtol=1e-5, atol=1e-5)


def test_replicaset_refuses_more_replicas_than_devices():
    net = _mlp()
    with pytest.raises(MXNetError):
        ReplicaSet(net, BucketSpec.pow2(2), n=len(jax.devices()) + 1,
                   example=np.zeros((1, IN_DIM), np.float32), warmup=False)


def test_pick_least_loaded_skips_quarantined():
    _, _, rs = _rset(n=2)
    assert rs.pick().index == 0                 # tie -> lowest index
    rs.acquire(rs.replicas[0])
    assert rs.pick().index == 1                 # least loaded
    rs.release(rs.replicas[0])
    rs.force_quarantine(1, now=0.0)
    assert rs.pick().index == 0                 # quarantined never picked
    rs.force_quarantine(0, now=0.0)
    assert rs.pick() is None                    # all down


# -------------------------------------------------------------- wedge watchdog
def test_wedge_recovery_full_cycle(monkeypatch):
    """ISSUE-8 acceptance: with 2 replicas and an injected replica_wedge,
    every submitted future completes (the wedged batch re-dispatches once
    on the healthy replica), the wedged replica is quarantined and later
    restored by a half-open probe — all under a fake clock, zero sleeps."""
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "replica_wedge@0")
    resilience.reset_faults()
    net, _, rs = _rset(n=2)
    clk = FakeClock()
    bat = _disp(rs, clk)
    x = _x(2, seed=7)
    f_wedged = bat.submit(x)
    f_other = bat.submit(_x(1, seed=8))
    clk.advance(0.006)
    assert bat.poll() == 2        # dispatch 0 -> r0: wedges (no answer)
    assert not f_wedged.done() and not f_other.done()
    assert _states(bat) == ["healthy", "healthy"]  # not yet past deadline
    clk.advance(2.5)              # past MXTPU_SERVE_DISPATCH_TIMEOUT_MS
    assert bat.poll() == 2        # scan trips -> re-dispatch on r1
    np.testing.assert_allclose(f_wedged.result(0),
                               net(mx.nd.array(x)).asnumpy(),
                               rtol=1e-5, atol=1e-5)
    assert f_other.done()
    assert _states(bat) == ["quarantined", "healthy"]
    assert telemetry.value("serving.replica.wedges", tag="r0") == 1
    assert telemetry.value("serving.replica.quarantines", tag="r0") == 1
    assert telemetry.value("serving.replica.redispatches", tag="r0") == 1
    assert resilience.FAULT_STATS["fired"] == [("replica_wedge", 0)]
    # half-open probe restores after the backoff (1000 ms)
    clk.advance(1.2)
    bat.poll()
    assert _states(bat) == ["healthy", "healthy"]
    assert telemetry.value("serving.replica.restores", tag="r0") == 1
    # service fully healthy again: traffic round-trips on both replicas
    f2 = bat.submit(_x(2, seed=9))
    clk.advance(0.006)
    assert bat.poll() == 1
    assert f2.result(0).shape == (2, OUT_DIM)
    # nothing ever hung: every future completed
    for f in (f_wedged, f_other, f2):
        assert f.done()


def test_wedge_redispatch_exactly_once(monkeypatch):
    """A re-dispatched batch that wedges AGAIN fails its futures loudly —
    re-dispatch is exactly-once, never a loop."""
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "replica_wedge@0,1")
    resilience.reset_faults()
    _, _, rs = _rset(n=2)
    clk = FakeClock()
    bat = _disp(rs, clk)
    f = bat.submit(_x(1, seed=0))
    clk.advance(0.006)
    assert bat.poll() == 1        # wedge on r0
    clk.advance(2.5)
    assert bat.poll() == 1        # re-dispatch on r1 -> wedges too
    clk.advance(2.5)
    bat.poll()                    # second trip: fail, don't re-dispatch
    with pytest.raises(DeadlineExceeded):
        f.result(0)
    # r1 quarantined by its wedge; r0's earlier quarantine already cycled
    # through a due half-open probe in the same maintenance pass
    assert _states(bat) == ["healthy", "quarantined"]
    assert telemetry.value("serving.replica.wedges") == 2


def test_wedge_single_replica_sheds_instead_of_hanging(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "replica_wedge@0")
    resilience.reset_faults()
    _, _, rs = _rset(n=1)
    clk = FakeClock()
    bat = _disp(rs, clk)
    f = bat.submit(_x(1, seed=0))
    clk.advance(0.006)
    assert bat.poll() == 1
    clk.advance(2.5)
    bat.poll()  # trip: no healthy replica left to re-dispatch on
    with pytest.raises(QueueFull):
        f.result(0)
    assert telemetry.value("serving.shed", tag="no_healthy_replica") == 1


# -------------------------------------------------------------- circuit breaker
def test_breaker_opens_after_threshold(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "replica_fail@0,1,2")
    resilience.reset_faults()
    _, _, rs = _rset(n=2, breaker_threshold=3)
    clk = FakeClock()
    bat = _disp(rs, clk)
    for i in range(3):  # idle set: least-loaded always routes to r0
        f = bat.submit(_x(1, seed=i))
        clk.advance(0.006)
        assert bat.poll() == 1
        with pytest.raises(MXNetError):
            f.result(0)
    assert _states(bat) == ["quarantined", "healthy"]
    assert telemetry.value("serving.replica.failures", tag="r0") == 3
    assert telemetry.value("serving.replica.quarantines", tag="r0") == 1
    # traffic continues on the healthy replica
    f = bat.submit(_x(1, seed=9))
    clk.advance(0.006)
    assert bat.poll() == 1
    assert f.result(0).shape == (1, OUT_DIM)
    assert telemetry.value("serving.replica.dispatches", tag="r1") == 1
    # one isolated failure does NOT open the breaker
    assert telemetry.value("serving.shed", tag="no_healthy_replica") == 0


def test_breaker_success_resets_consecutive_count(monkeypatch):
    """Failures must be CONSECUTIVE: a success in between closes the
    window, so sporadic errors never quarantine a replica."""
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "replica_fail@0,2,4")
    resilience.reset_faults()
    _, _, rs = _rset(n=2, breaker_threshold=3)
    clk = FakeClock()
    bat = _disp(rs, clk)
    for i in range(6):  # fail, ok, fail, ok, fail, ok — all on r0
        f = bat.submit(_x(1, seed=i))
        clk.advance(0.006)
        assert bat.poll() == 1
        if i % 2 == 0:
            with pytest.raises(MXNetError):
                f.result(0)
        else:
            assert f.result(0).shape == (1, OUT_DIM)
    assert _states(bat) == ["healthy", "healthy"]


def test_all_replicas_down_sheds_then_probe_restores_service(monkeypatch):
    """The shed reason no_healthy_replica appears ONLY when all replicas
    are down; a due half-open probe restores service — checked at the
    next submit, no poll needed."""
    monkeypatch.setenv("MXTPU_FAULT_INJECT",
                       "replica_fail@0,1;replica_wedge@2")
    resilience.reset_faults()
    # backoff far past the wedge timeline so no probe restores a replica
    # before the all-down assertion
    _, _, rs = _rset(n=2, breaker_threshold=2, breaker_backoff_ms=10000)
    clk = FakeClock()
    bat = _disp(rs, clk)
    for i in range(2):  # two consecutive failures open r0's breaker
        f = bat.submit(_x(1, seed=i))
        clk.advance(0.006)
        bat.poll()
        with pytest.raises(MXNetError):
            f.result(0)
    assert _states(bat) == ["quarantined", "healthy"]
    assert telemetry.value("serving.shed", tag="no_healthy_replica") == 0
    # k-of-N degraded: submits still admitted while ONE replica lives
    f = bat.submit(_x(1, seed=5))
    clk.advance(0.006)
    bat.poll()                    # dispatch 2 -> r1: wedges
    clk.advance(2.5)
    bat.poll()                    # trip: r1 quarantined, no target -> shed
    with pytest.raises(QueueFull):
        f.result(0)
    assert _states(bat) == ["quarantined", "quarantined"]
    # ALL down: admission sheds with the dedicated reason
    with pytest.raises(QueueFull):
        bat.submit(_x(1, seed=6))
    assert telemetry.value("serving.shed", tag="no_healthy_replica") >= 2
    # past the backoff the NEXT submit triggers the half-open probes
    # (admission runs maintenance before refusing) and service resumes
    clk.advance(11.0)
    f = bat.submit(_x(1, seed=7))
    clk.advance(0.006)
    assert bat.poll() == 1
    assert f.result(0).shape == (1, OUT_DIM)
    assert telemetry.value("serving.replica.restores") == 2


def test_failed_probe_doubles_backoff(monkeypatch):
    _, _, rs = _rset(n=2, breaker_threshold=1, breaker_backoff_ms=1000,
                     breaker_backoff_max_ms=3000)
    clk = FakeClock()
    bat = _disp(rs, clk)
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "replica_fail@0")
    resilience.reset_faults()
    f = bat.submit(_x(1, seed=0))
    clk.advance(0.006)
    bat.poll()
    with pytest.raises(MXNetError):
        f.result(0)
    assert _states(bat) == ["quarantined", "healthy"]
    rep = rs.replicas[0]
    # make the probe itself fail deterministically
    monkeypatch.setattr(rs, "run_probe",
                        lambda r: (_ for _ in ()).throw(RuntimeError("dead")))
    clk.advance(1.2)
    bat.poll()
    assert _states(bat)[0] == "quarantined"
    assert rep.backoff_s == pytest.approx(2.0)   # doubled
    clk.advance(2.2)
    bat.poll()
    assert rep.backoff_s == pytest.approx(3.0)   # capped at the max
    assert telemetry.value("serving.replica.restores") == 0


# -------------------------------------------------------- batcher satellites
def test_worker_crash_barrier_fails_queued_futures(monkeypatch):
    """Satellite: a dispatch worker dying OUTSIDE _dispatch's try used to
    strand every queued future on a dead daemon thread — now they all
    fail, new submits shed, and serving.worker_crashes counts it."""
    net = _mlp()
    pred = Predictor(net, BucketSpec.pow2(4),
                     example=np.zeros((1, IN_DIM), np.float32), warmup=True)
    bat = MicroBatcher(pred, max_batch_size=4, max_wait_ms=1000, start=False)
    f1 = bat.submit(_x(1, seed=0))
    f2 = bat.submit(_x(1, seed=1))

    def boom(now):
        raise RuntimeError("gather bug")

    monkeypatch.setattr(bat, "_gather_locked", boom)
    bat.start()
    with pytest.raises(MXNetError, match="worker crashed"):
        f1.result(timeout=5)
    with pytest.raises(MXNetError, match="worker crashed"):
        f2.result(timeout=5)
    assert telemetry.value("serving.worker_crashes") == 1
    with pytest.raises(QueueFull):
        bat.submit(_x(1, seed=2))
    assert telemetry.value("serving.shed", tag="worker_crashed") == 1
    assert bat.queue_depth == 0


def test_drain_no_bare_sleep_and_fake_clock_timeout(monkeypatch):
    """Satellite: drain waits on the condition variable and measures its
    timeout on the injected clock — never a bare time.sleep poll."""
    from mxtpu.serving import batcher as batcher_mod

    def no_sleep(_s):
        raise AssertionError("drain must not busy-wait on time.sleep")

    monkeypatch.setattr(batcher_mod.time, "sleep", no_sleep)
    net = _mlp()
    pred = Predictor(net, BucketSpec.pow2(4),
                     example=np.zeros((1, IN_DIM), np.float32), warmup=True)
    # threaded drain: the worker's notify wakes drain, no sleep involved
    bat = MicroBatcher(pred, max_batch_size=4, max_wait_ms=1)
    f = bat.submit(_x(2, seed=0))
    assert bat.drain(timeout=10) is True
    assert f.done()
    bat.close()
    # fake-clock, no-worker drain: synchronous poll path, also sleep-free
    clk = FakeClock()
    bat2 = MicroBatcher(pred, max_batch_size=4, max_wait_ms=1000,
                        clock=clk, start=False)
    f2 = bat2.submit(_x(1, seed=1))
    assert bat2.drain(timeout=5) is True  # draining forces the dispatch
    assert f2.done()


def test_dispatcher_drain_waits_for_wedged_entries(monkeypatch):
    """A simulated-wedge batch is neither queued nor inflight — drain
    must still refuse to report empty until the watchdog resolves it."""
    monkeypatch.setenv("MXTPU_FAULT_INJECT", "replica_wedge@0")
    resilience.reset_faults()
    _, _, rs = _rset(n=2)
    clk = FakeClock()
    bat = _disp(rs, clk)
    f = bat.submit(_x(1, seed=0))
    clk.advance(0.006)
    bat.poll()                       # wedged: future pending off-queue
    assert bat.drain(timeout=1) is False
    clk.advance(2.5)                 # now the scan can resolve it
    assert bat.drain(timeout=1) is True
    assert f.done()


# ------------------------------------------------------------------ HTTP front
def _http(addr, path, payload=None, timeout=10):
    import json
    import urllib.error
    import urllib.request
    url = "http://%s:%d%s" % (addr[0], addr[1], path)
    if payload is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_server_healthz_reports_replica_states():
    net, _, rs = _rset(n=2)
    srv = ModelServer(rs)  # a ReplicaSet auto-wraps in a ReplicaDispatcher
    assert isinstance(srv.batcher, ReplicaDispatcher)
    srv.start()
    try:
        x = _x(2, seed=5)
        code, out = _http(srv.address, "/predict", {"data": x.tolist()})
        assert code == 200 and out["n"] == 2
        np.testing.assert_allclose(np.asarray(out["outputs"][0]),
                                   net(mx.nd.array(x)).asnumpy(),
                                   rtol=1e-4, atol=1e-5)
        code, health = _http(srv.address, "/healthz")
        assert code == 200 and health["status"] == "ok"
        assert health["healthy_replicas"] == 2
        assert [r["state"] for r in health["replicas"]] == \
            ["healthy", "healthy"]
        assert {r["device"] for r in health["replicas"]} \
            == {str(d) for d in jax.devices()[:2]}
        # lose one replica: still serving, /healthz says degraded
        srv.batcher.quarantine_replica(0, backoff_s=3600)
        code, health = _http(srv.address, "/healthz")
        assert code == 200 and health["status"] == "degraded"
        assert health["healthy_replicas"] == 1
        code, out = _http(srv.address, "/predict", {"data": x.tolist()})
        assert code == 200
        # /metrics carries the replica-tagged counters + per-replica sites
        code, m = _http(srv.address, "/metrics")
        assert code == 200
        assert "r0" in m["counters"]["serving.replica.quarantines"]
        assert "serving.predict.r0" in m["retrace"]
        assert "serving.predict.r1" in m["retrace"]
    finally:
        srv.close()


# ------------------------------------------------------------- threaded tier
def test_threaded_end_to_end_zero_hangs():
    """Real per-replica workers: a closed-loop burst completes with zero
    hangs and the work spreads across replicas."""
    _, spec, rs = _rset(n=2, max_batch=4)
    bat = ReplicaDispatcher(rs, max_batch_size=4, max_wait_ms=1,
                            max_queue=4096)
    errors = []

    def client(k, n_req):
        rng = np.random.RandomState(k)
        for _ in range(n_req):
            n = int(rng.randint(1, 4))
            try:
                out = bat.submit(
                    rng.randn(n, IN_DIM).astype(np.float32)).result(
                        timeout=60)
                assert out.shape == (n, OUT_DIM)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=client, args=(k, 40))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    bat.close()
    assert not errors, errors[:3]
    assert telemetry.value("serving.requests") == 160
    per = telemetry.tagged("serving.replica.dispatches")
    assert sum(per.values()) == telemetry.value("serving.batches") \
        + telemetry.value("serving.replica.stale_results")
    assert len(per) == 2, "both replicas served: %s" % per
    # post-warmup compile budget holds per replica
    for i in range(2):
        st = telemetry.retrace_stats("serving.predict.r%d" % i)
        assert st["compiles"] <= len(spec) and st["trips"] == 0


def test_serve_bench_replicas_smoke():
    """tools/serve_bench.py --mode replicas: the kill-one-replica-mid-run
    sweep completes with zero hangs and reports per-replica dispatches."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import serve_bench as sb

    rset, spec = sb.build_replica_set(dim=32, width=32, depth=2,
                                      max_batch=4, replicas=2)
    rec = sb.run_replicas(rset, spec, n_requests=60, workers=3,
                          max_wait_ms=1.0, kill_frac=0.5,
                          emit=lambda r: None)
    assert rec["hangs"] == 0
    assert rec["errors"] == 0
    assert rec["killed_replica"] == 0
    assert rec["completed"] + rec["shed"] + rec["expired"] == 60
    assert sum(rec["per_replica_dispatches"].values()) >= 1
    assert rec["final_states"][0] == "quarantined"  # the killed replica
